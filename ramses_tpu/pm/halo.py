"""Halo-analysis chain: clump membership, unbinding, merger trees.

Reference: ``pm/clump_merger.f90`` (clump properties + output tables),
``pm/unbinding.f90:1-2296`` (iterative particle unbinding against the
clump's own potential), ``pm/merger_tree.f90:1-4312`` (progenitor /
descendant links via shared particle IDs across snapshots).

All passes are host-side numpy over particle arrays — halos are few and
the per-clump work is O(members log members); the expensive part
(density deposition + watershed labelling) already runs on device
(:mod:`ramses_tpu.pm.clumps`).  The unbinding potential uses the
monopole (spherical mass-profile) approximation of the reference
(``unbinding.f90`` 'potential from the cumulative mass profile').
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np


# ----------------------------------------------------------------------
# membership
# ----------------------------------------------------------------------

def particle_labels(x: np.ndarray, labels_grid: np.ndarray, dx: float,
                    boxlen: float) -> np.ndarray:
    """Clump label of each particle = label of its NGP cell on the
    dense labelled grid (-1 = unlabelled background)."""
    shape = labels_grid.shape
    nd = x.shape[1]
    idx = tuple(
        np.clip((np.mod(x[:, d], boxlen) / dx).astype(np.int64), 0,
                shape[d] - 1) for d in range(nd))
    return labels_grid[idx]


# ----------------------------------------------------------------------
# unbinding (pm/unbinding.f90)
# ----------------------------------------------------------------------

def _sphere_potential(r: np.ndarray, m: np.ndarray, G: float):
    """Monopole potential at each member's radius from the cumulative
    mass profile: phi(r_i) = -G [ M(<r_i)/r_i + sum_{r_j>r_i} m_j/r_j ]
    (the reference's spherical unbinding potential)."""
    order = np.argsort(r)
    rs = np.maximum(r[order], 1e-12)
    ms = m[order]
    mcum = np.cumsum(ms) - ms            # mass strictly inside r_i
    inv_term = np.cumsum((ms / rs)[::-1])[::-1] - ms / rs  # shells outside
    phi_sorted = -G * ((mcum + ms) / rs + inv_term)
    phi = np.empty_like(phi_sorted)
    phi[order] = phi_sorted
    return phi


def _binned_potential(r: np.ndarray, m: np.ndarray, G: float,
                      nbins: int, logbins: bool = True):
    """Monopole potential from a BINNED cumulative mass profile — the
    reference's ``nmassbins``/``logbins`` option set
    (``unbinding.f90`` compute_phi: the potential is tabulated on a
    radial mass-bin grid and particles interpolate), O(n) instead of
    the exact per-particle sort."""
    rmax = max(float(r.max()), 1e-12)
    rmin = max(float(r.min()), 1e-6 * rmax)
    if logbins:
        edges = np.geomspace(rmin, rmax, nbins + 1)
        edges[0] = 0.0
    else:
        edges = np.linspace(0.0, rmax, nbins + 1)
    ib = np.clip(np.searchsorted(edges, r, side="right") - 1, 0,
                 nbins - 1)
    mbin = np.bincount(ib, weights=m, minlength=nbins)
    rcen = 0.5 * (edges[1:] + np.maximum(edges[:-1], 1e-12 * rmax))
    mcum = np.cumsum(mbin)                       # mass inside bin edge
    # phi at bin centres: interior monopole + exterior shell sum
    shell = mbin / rcen
    outer = np.cumsum(shell[::-1])[::-1] - shell
    phi_bin = -G * (mcum / rcen + outer)
    return phi_bin[ib]


def unbind_clump(x: np.ndarray, v: np.ndarray, m: np.ndarray,
                 center: np.ndarray, boxlen: float, G: float = 1.0,
                 periodic: bool = True, max_iter: int = 10,
                 keep_frac_min: float = 0.0, saddle_pot: bool = False,
                 nmassbins: int = 0, logbins: bool = True):
    """Iterative unbinding of one clump's member particles.

    Returns a bool mask of BOUND members.  Each iteration recomputes
    the bulk velocity and the monopole potential from the currently
    bound set, then strips particles with
    ``0.5|v - vbulk|^2 + phi > phi_ref`` (``unbinding.f90`` iterative
    mode, ``:1400-1600``) until the bound set is stable.

    Reference option set: ``saddle_pot`` references the binding energy
    to the potential at the clump boundary instead of infinity (a
    particle energetic enough to reach the saddle surface counts as
    unbound — stricter); ``nmassbins``/``logbins`` switch the exact
    per-particle monopole to the reference's binned mass-profile
    potential.
    """
    n = len(m)
    bound = np.ones(n, dtype=bool)
    rel = x - center
    if periodic:
        rel = rel - boxlen * np.round(rel / boxlen)
    r = np.sqrt((rel ** 2).sum(axis=1))
    phi_ref = None
    for _ in range(max_iter):
        nb = bound.sum()
        if nb < 2:
            break
        mtot = m[bound].sum()
        vbulk = (v[bound] * m[bound, None]).sum(0) / mtot
        phi = np.zeros(n)
        if nmassbins >= 2:
            phi[bound] = _binned_potential(r[bound], m[bound], G,
                                           nmassbins, logbins)
        else:
            phi[bound] = _sphere_potential(r[bound], m[bound], G)
        if saddle_pot:
            # boundary reference FROZEN at the first iteration (the
            # reference's saddle surface does not shrink with the
            # bound set; a per-iteration max would strip the
            # outermost member forever and never converge)
            if phi_ref is None:
                phi_ref = float(phi[bound].max())
        else:
            phi_ref = 0.0
        ekin = 0.5 * ((v - vbulk) ** 2).sum(axis=1)
        new_bound = bound & (ekin + phi < phi_ref)
        if new_bound.sum() < max(2, int(keep_frac_min * n)):
            break                        # keep the last stable set
        if new_bound.sum() == nb:
            bound = new_bound
            break
        bound = new_bound
    return bound


# ----------------------------------------------------------------------
# clump catalogue with particle membership
# ----------------------------------------------------------------------

@dataclass
class Halo:
    """One halo/clump with particle membership (the clump_merger table
    row + the unbinding particle lists)."""
    index: int
    mass: float                  # bound mass
    npart: int
    pos: np.ndarray              # mass-weighted bound centre
    vel: np.ndarray              # bulk velocity
    ekin: float                  # internal kinetic energy (bulk removed)
    epot: float                  # monopole potential energy estimate
    ids: np.ndarray              # bound particle IDs, MOST BOUND FIRST
                                 # (the reference's nmost_bound tracer
                                 # ordering, merger_tree.f90)


def build_catalogue(x: np.ndarray, v: np.ndarray, m: np.ndarray,
                    ids: np.ndarray, plabels: np.ndarray, boxlen: float,
                    G: float = 1.0, periodic: bool = True,
                    unbind: bool = True,
                    npart_min: int = 10, saddle_pot: bool = False,
                    nmassbins: int = 0, logbins: bool = True) -> List[Halo]:
    """Halo catalogue from labelled particles (one entry per clump with
    >= ``npart_min`` bound members), heaviest first.  ``saddle_pot`` /
    ``nmassbins`` / ``logbins``: unbinding options (see
    :func:`unbind_clump`)."""
    halos: List[Halo] = []
    for lbl in np.unique(plabels[plabels >= 0]):
        sel = np.nonzero(plabels == lbl)[0]
        if len(sel) < npart_min:
            continue
        xs, vs, ms = x[sel], v[sel], m[sel]
        # provisional centre: mass-weighted with periodic unwrap about
        # the first member
        rel = xs - xs[0]
        if periodic:
            rel = rel - boxlen * np.round(rel / boxlen)
        center = xs[0] + (rel * ms[:, None]).sum(0) / ms.sum()
        if unbind:
            bound = unbind_clump(xs, vs, ms, center, boxlen, G, periodic,
                                 saddle_pot=saddle_pot,
                                 nmassbins=nmassbins, logbins=logbins)
        else:
            bound = np.ones(len(sel), dtype=bool)
        if bound.sum() < npart_min:
            continue
        xs, vs, ms = xs[bound], vs[bound], ms[bound]
        sid = ids[sel][bound]
        mtot = ms.sum()
        rel = xs - center
        if periodic:
            rel = rel - boxlen * np.round(rel / boxlen)
        pos = center + (rel * ms[:, None]).sum(0) / mtot
        if periodic:
            pos = np.mod(pos, boxlen)
        vel = (vs * ms[:, None]).sum(0) / mtot
        r = np.sqrt(((rel - (pos - center)) ** 2).sum(axis=1))
        phi = _sphere_potential(np.maximum(r, 1e-12), ms, G)
        ekin = float(0.5 * (ms * ((vs - vel) ** 2).sum(axis=1)).sum())
        epot = float(0.5 * (ms * phi).sum())
        # ids ordered most-bound-first: per-particle energy in the
        # halo frame (the reference picks its nmost_bound tree tracers
        # exactly this way, merger_tree.f90 most-bound lists)
        ebind = 0.5 * ((vs - vel) ** 2).sum(axis=1) + phi
        halos.append(Halo(index=int(lbl), mass=float(mtot),
                          npart=int(bound.sum()), pos=pos, vel=vel,
                          ekin=ekin, epot=epot,
                          ids=sid.astype(np.int64)[np.argsort(ebind)]))
    halos.sort(key=lambda h: -h.mass)
    return halos


def write_halo_table(halos: List[Halo], path: str):
    """``clump_masses.txt``-style ascii catalogue."""
    with open(path, "w") as f:
        f.write("# index npart mass x y z vx vy vz ekin epot 2T/|U|\n")
        for h in halos:
            p3 = list(h.pos) + [0.0] * (3 - len(h.pos))
            v3 = list(h.vel) + [0.0] * (3 - len(h.vel))
            vir = 2.0 * h.ekin / max(abs(h.epot), 1e-300)
            f.write(f"{h.index:8d} {h.npart:8d} {h.mass:14.6e} "
                    f"{p3[0]:12.6f} {p3[1]:12.6f} {p3[2]:12.6f} "
                    f"{v3[0]:12.5e} {v3[1]:12.5e} {v3[2]:12.5e} "
                    f"{h.ekin:12.5e} {h.epot:12.5e} {vir:8.3f}\n")


# ----------------------------------------------------------------------
# merger trees (pm/merger_tree.f90)
# ----------------------------------------------------------------------

@dataclass
class TreeLink:
    """One progenitor→descendant link."""
    desc: int                    # descendant halo index (later snapshot)
    prog: int                    # progenitor halo index (earlier)
    shared: int                  # shared tracer count
    main: bool                   # True: prog is desc's main progenitor
    frac: float = 0.0            # shared / progenitor tracer count
    snap_prog: int = -1          # progenitor snapshot (0-based); a gap
                                 # link has snap_prog < snap_desc - 1


def link_catalogues(progs: List[Halo], descs: List[Halo],
                    nmost_bound: int = 0, snap_prog: int = -1,
                    ) -> List[TreeLink]:
    """Progenitor/descendant links via shared particle IDs.

    The reference tracks the ``nmost_bound`` MOST BOUND particles per
    clump across snapshots and links by who holds them
    (``merger_tree.f90`` make_merger_tree); ``nmost_bound=0`` uses
    every bound particle.  Halo.ids are most-bound-first, so the
    tracer set is a prefix.  ``frac`` records the progenitor-fraction
    merit (shared / progenitor tracers); the main progenitor of a
    descendant is the one contributing the most shared tracers.
    """
    id2prog: Dict[int, int] = {}
    ntr: Dict[int, int] = {}
    for hp in progs:
        tr = hp.ids[:nmost_bound] if nmost_bound else hp.ids
        ntr[hp.index] = len(tr)
        for pid in tr:
            id2prog[int(pid)] = hp.index
    links: List[TreeLink] = []
    for hd in descs:
        counts: Dict[int, int] = {}
        for pid in hd.ids:
            pr = id2prog.get(int(pid))
            if pr is not None:
                counts[pr] = counts.get(pr, 0) + 1
        if not counts:
            continue
        main = max(counts, key=lambda k: counts[k])
        for pr, c in sorted(counts.items(), key=lambda kv: -kv[1]):
            links.append(TreeLink(desc=hd.index, prog=pr, shared=c,
                                  main=(pr == main),
                                  frac=c / max(ntr[pr], 1),
                                  snap_prog=snap_prog))
    return links


class MergerTree:
    """Accumulates catalogues over outputs and writes the tree table
    (``mergertree_txt`` output of ``merger_tree.f90``).

    ``max_gap``: a halo that drops out of the catalogue (below
    threshold, temporarily disrupted) stays a live progenitor
    candidate for up to ``max_gap`` later snapshots — the reference's
    past-merged-progenitor jumps (``merger_tree.f90`` 'jumpers'): a
    descendant with no progenitor in the previous catalogue is linked
    across the gap.  ``nmost_bound``: tracer count per halo (0 = all
    bound particles)."""

    def __init__(self, max_gap: int = 2, nmost_bound: int = 0):
        self.snapshots: List[Tuple[float, List[Halo]]] = []
        self.links: List[Tuple[int, List[TreeLink]]] = []
        self.max_gap = int(max_gap)
        self.nmost_bound = int(nmost_bound)
        # open progenitor pool: (snap_idx, Halo) not yet main-linked
        self._open: List[Tuple[int, Halo]] = []

    def add_snapshot(self, t: float, halos: List[Halo]):
        self.snapshots.append((t, halos))
        snap = len(self.snapshots) - 1
        if snap == 0:
            self._open = [(0, h) for h in halos]
            return
        prev = self.snapshots[-2][1]
        links = link_catalogues(prev, halos, self.nmost_bound,
                                snap_prog=snap - 1)
        # gap links: descendants with no progenitor in snap-1 search
        # the open pool of older snapshots, most recent first
        unmatched = [h for h in halos
                     if not any(l.desc == h.index for l in links)]
        pool = [(s, h) for s, h in self._open
                if s < snap - 1 and snap - s <= self.max_gap]
        pool.sort(key=lambda sh: -sh[0])
        for s in sorted({s for s, _ in pool}, reverse=True):
            if not unmatched:
                break
            cands = [h for ss, h in pool if ss == s]
            glinks = link_catalogues(cands, unmatched,
                                     self.nmost_bound, snap_prog=s)
            links.extend(glinks)
            matched = {l.desc for l in glinks}
            unmatched = [h for h in unmatched
                         if h.index not in matched]
        self.links.append((snap, links))
        # progenitors main-linked into this snapshot leave the pool;
        # everything else ages (and expires past max_gap); the new
        # catalogue joins the pool
        claimed = {(l.snap_prog, l.prog) for l in links if l.main}
        self._open = [(s, h) for s, h in self._open
                      if (s, h.index) not in claimed
                      and snap - s < self.max_gap]
        self._open.extend((snap, h) for h in halos)

    def progenitors(self, snap: int, halo_index: int) -> List[TreeLink]:
        """Links into ``halo_index`` of snapshot ``snap`` (1-based on
        the second snapshot onward)."""
        for s, links in self.links:
            if s == snap:
                return [l for l in links if l.desc == halo_index]
        return []

    def main_branch(self, snap: int, halo_index: int
                    ) -> List[Tuple[int, int]]:
        """Walk the main-progenitor branch back from (snap, halo):
        [(snap, index), (snap_prog, prog), ...] — the quantity merger
        trees exist to answer."""
        out = [(snap, halo_index)]
        s, h = snap, halo_index
        while True:
            ls = [l for l in self.progenitors(s, h) if l.main]
            if not ls:
                return out
            s, h = ls[0].snap_prog, ls[0].prog
            out.append((s, h))

    def write(self, path: str):
        with open(path, "w") as f:
            f.write("# snap desc_index prog_snap prog_index shared "
                    "frac main\n")
            for s, links in self.links:
                for l in links:
                    f.write(f"{s:6d} {l.desc:8d} {l.snap_prog:6d} "
                            f"{l.prog:8d} {l.shared:8d} {l.frac:8.4f} "
                            f"{int(l.main):2d}\n")
