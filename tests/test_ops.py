"""Operational hygiene: signal dumps, stop file, walltime watchdog,
screen block, memory accounting (``amr/ramses.f90:17-48``,
``adaptive_loop.f90:199-226``)."""

import os
import signal

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import load_params
from ramses_tpu.utils.ops import OpsGuard, device_mb, rss_mb

NML = "namelists/sedov3d.nml"



pytestmark = pytest.mark.smoke

def _sim(lmin=4, lmax=5):
    p = load_params(NML, ndim=3)
    p.amr.levelmin, p.amr.levelmax = lmin, lmax
    p.refine.err_grad_d = 0.1
    p.refine.err_grad_p = 0.1
    return AmrSim(p, dtype=jnp.float64)


@pytest.mark.slow
def test_sigusr1_snapshot(tmp_path):
    """SIGUSR1 mid-run produces a valid restartable snapshot."""
    sim = _sim()
    guard = OpsGuard(sim, str(tmp_path))
    sim.evolve(1e9, nstepmax=1, guard=guard)
    os.kill(os.getpid(), signal.SIGUSR1)
    sim.evolve(1e9, nstepmax=sim.nstep + 2, guard=guard)
    outs = [d for d in os.listdir(tmp_path) if d.startswith("output_")]
    assert outs, "no snapshot written after SIGUSR1"
    p2 = load_params(NML, ndim=3)
    p2.amr.levelmin, p2.amr.levelmax = 4, 5
    back = AmrSim.from_snapshot(p2, os.path.join(tmp_path, sorted(outs)[0]),
                                dtype=jnp.float64)
    assert np.isfinite(np.asarray(back.totals())).all()


def test_stop_file_halts(tmp_path):
    sim = _sim()
    guard = OpsGuard(sim, str(tmp_path), install_signals=False)
    (tmp_path / "stop_run").write_text("")
    sim.evolve(1e9, nstepmax=50, guard=guard)
    assert sim.nstep == 0                  # stopped before stepping
    outs = [d for d in os.listdir(tmp_path) if d.startswith("output_")]
    assert outs                            # but dumped a snapshot first


def test_walltime_watchdog(tmp_path):
    sim = _sim()
    guard = OpsGuard(sim, str(tmp_path), walltime_s=1e-6,
                     install_signals=False)
    sim.evolve(1e9, nstepmax=50, guard=guard)
    assert sim.nstep <= 1
    assert any(d.startswith("output_") for d in os.listdir(tmp_path))


def test_screen_block_and_memory():
    sim = _sim()
    guard = OpsGuard(sim, install_signals=False)
    guard.check()
    line = guard.screen_block()
    assert "Main step=" in line and "mem=" in line and "octs=" in line
    assert rss_mb() > 10.0                 # a real python process
    assert device_mb() > 0.0               # live device arrays exist


def test_nan_trap_dumps_and_stops(tmp_path):
    """debug_nan=.true. (SURVEY.md §5.2 NaN-trap sanitizer): the guard
    dumps a crash snapshot and stops the run at the first non-finite
    state instead of marching NaNs to tend."""
    sim = _sim()
    guard = OpsGuard(sim, str(tmp_path), install_signals=False,
                     nan_check=True)
    assert guard.check()                   # healthy state passes
    sim.dt_old = float("nan")              # poisoned step
    assert not guard.check()
    assert any(d.startswith("output_") for d in os.listdir(tmp_path))


def test_nan_trap_from_namelist():
    p = load_params(NML, ndim=3)
    p.amr.levelmin = p.amr.levelmax = 4
    p.run.debug_nan = True
    sim = AmrSim(p, dtype=jnp.float64)
    guard = OpsGuard(sim, install_signals=False)
    assert guard.nan_check                 # picked up from &RUN_PARAMS


def test_nan_trap_jit_raise_path(tmp_path):
    """jax_debug_nans raises FloatingPointError from INSIDE the step;
    run_guarded must still write the crash snapshot, then re-raise."""
    sim = _sim()
    guard = OpsGuard(sim, str(tmp_path), install_signals=False,
                     nan_check=True)

    def boom():
        raise FloatingPointError("nan in jitted step")

    with pytest.raises(FloatingPointError):
        guard.run_guarded(boom)
    assert any(d.startswith("output_") for d in os.listdir(tmp_path))
