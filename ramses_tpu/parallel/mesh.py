"""Device mesh construction for spatial domain decomposition.

The reference decomposes space over MPI ranks via Hilbert-curve cuts
(``amr/load_balance.f90:657-720``, SURVEY.md §2.12 P1).  On TPU the
domain maps onto a ``jax.sharding.Mesh``: spatial axes of the state array
are sharded over mesh axes, and XLA's SPMD partitioner materializes the
halo exchanges (P2) as ICI collective-permutes — the ``make_virtual_fine``
of this design is compiler-generated.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_NAMES = ("x", "y", "z")


def factorize(n: int, ndim: int) -> Tuple[int, ...]:
    """Split n devices into an ndim mesh shape, most-balanced first.

    Prefers cubic-ish decompositions (minimum surface/volume => minimum
    halo bytes over ICI), mirroring how MPI codes pick process grids.
    """
    best: Tuple[int, ...] = (n,) + (1,) * (ndim - 1)
    best_cost = None

    def rec(rem: int, dims: List[int]):
        nonlocal best, best_cost
        if len(dims) == ndim - 1:
            dims = dims + [rem]
            # halo cost ~ sum of cross-sections
            cost = sum(np.prod(dims) / d for d in dims)
            if best_cost is None or cost < best_cost:
                best_cost = cost
                best = tuple(sorted(dims, reverse=True))
            return
        d = 1
        while d <= rem:
            if rem % d == 0:
                rec(rem // d, dims + [d])
            d += 1

    rec(n, [])
    return best


def make_mesh(ndim: int, devices: Optional[Sequence[jax.Device]] = None
              ) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    shape = factorize(len(devices), ndim)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXIS_NAMES[:ndim])


def spatial_sharding(mesh: Mesh, n_leading: int = 1) -> NamedSharding:
    """Sharding for arrays [*leading, nx(,ny(,nz))]: spatial axes on mesh."""
    spec = P(*([None] * n_leading), *mesh.axis_names)
    return NamedSharding(mesh, spec)


REPLICA_AXIS = "rep"


def replica_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over independent ensemble replicas: the two-level
    parallel composition (ensemble/meshplan) shards the leading member
    axis of a packed sub-batch over this axis — members are data-
    parallel (no cross-member collectives), so GSPMD partitions the
    vmapped step chain into per-device replica programs with zero
    communication."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (REPLICA_AXIS,))


def replica_sharding(mesh: Mesh, ndim_total: int) -> NamedSharding:
    """Sharding for a ``[B, ...]`` batched array: member axis on the
    replica mesh, everything else replicated per device."""
    return NamedSharding(
        mesh, P(REPLICA_AXIS, *([None] * (ndim_total - 1))))


OCT_AXIS = "oct"


def oct_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the AMR row ("oct") axis: every level batch is
    row-sharded over this single axis, device ``d`` owning the row block
    ``[d*cap, (d+1)*cap)`` — the cuts the cost-weighted balancer
    (:mod:`ramses_tpu.parallel.balance`) fills with contiguous
    Hilbert-key ranges."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (OCT_AXIS,))
