"""Out-of-core AMR offload (ramses_tpu/amr/offload.py).

Pins the engine's three contracts:

  * bitwise parity — ``offload=on`` equals ``off`` exactly through
    steps, regrids, and a checkpoint written WHILE levels were parked
    (the segmented per-level path runs the same kernels in the same
    order on the same inputs, so there is no tolerance to tune);
  * honest accounting — prefetches that land count as overlapped,
    prefetches that don't (and cold fetches) count as stalls, and the
    per-step device high-water tracks the managed residency;
  * zero overhead when off — the default path adds no device fetches
    and no engine at all (``sim._offload is None``).

Parity runs use ``nremap=1``: the chunked fast path accumulates ``t``
on device while engaged runs accumulate on host, so chunk==1 keeps both
sides on the per-step path where even ``t`` is bitwise equal.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.amr.offload import is_parked
from ramses_tpu.config import params_from_string

pytestmark = pytest.mark.smoke

SEDOV2D = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
nremap=1
/
&AMR_PARAMS
levelmin=4
levelmax={lmax}
boxlen=1.0
offload='{mode}'
offload_hbm_budget_mb={budget}
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&OUTPUT_PARAMS
tend=1.0
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""


def _params(mode="off", budget=0.0, lmax=5, nstep=20):
    return params_from_string(
        SEDOV2D.format(mode=mode, budget=budget, lmax=lmax,
                       nstep=nstep), ndim=2)


def _assert_state_equal(a, b):
    assert list(a.levels()) == list(b.levels())
    for l in a.levels():
        np.testing.assert_array_equal(np.asarray(a.u[l]),
                                      np.asarray(b.u[l]))


# ---------------------------------------------------------------------
# bitwise parity: steps + regrids + checkpoint-while-parked + restore
# ---------------------------------------------------------------------
@pytest.mark.slow          # ~38s; nightly tier on the 1-core box
def test_bitwise_parity_through_steps_regrid_restart(tmp_path):
    s_off = AmrSim(_params("off", lmax=6))
    s_on = AmrSim(_params("on", lmax=6))
    s_off.evolve(1e9, nstepmax=4)
    s_on.evolve(1e9, nstepmax=4)
    eng = s_on._offload
    assert eng is not None and eng.engaged(s_on)
    assert eng.last_step_stats is not None
    assert eng.last_step_stats["fetches"] > 0
    # the engaged run really is out-of-core between steps
    assert any(is_parked(a) for a in s_on.u.values())
    _assert_state_equal(s_off, s_on)
    assert s_off.t == s_on.t

    # elastic checkpoint written while levels are parked: pario stages
    # the host buffer directly (no device round-trip), and the restored
    # sim continues bitwise with the never-offloaded reference
    out = s_on.dump_pario(1, str(tmp_path))
    assert any(is_parked(a) for a in s_on.u.values())   # dump didn't unpark
    s_res = AmrSim.from_checkpoint_dir(_params("off", lmax=6), out)
    assert s_res.t == s_off.t and s_res.nstep == s_off.nstep
    _assert_state_equal(s_off, s_res)

    s_off.evolve(1e9, nstepmax=6)
    s_on.evolve(1e9, nstepmax=6)
    s_res.evolve(1e9, nstepmax=6)
    _assert_state_equal(s_off, s_on)
    _assert_state_equal(s_off, s_res)
    assert s_off.t == s_on.t == s_res.t


# ---------------------------------------------------------------------
# prefetch/stall accounting
# ---------------------------------------------------------------------
def test_prefetch_disabled_counts_stalls():
    sim = AmrSim(_params("on", lmax=6))
    sim._offload.prefetch_depth = 0        # every fetch is cold
    sim.evolve(1e9, nstepmax=2)
    st = sim._offload.last_step_stats
    assert st["prefetches"] == 0
    assert st["fetches"] > 0
    assert st["stalls"] == st["fetches"]
    assert st["overlap_frac"] == 0.0
    assert st["device_hwm_bytes"] > 0


def test_prefetch_overlap_accounted():
    sim = AmrSim(_params("on", lmax=6))
    sim.evolve(1e9, nstepmax=3)
    tot = sim._offload._tot
    assert tot["prefetches"] > 0
    assert tot["overlapped"] + tot["stalls"] == tot["fetches"]
    assert tot["bytes_parked"] > 0 and tot["bytes_fetched"] > 0


# ---------------------------------------------------------------------
# engagement modes
# ---------------------------------------------------------------------
def test_auto_mode_engagement_threshold():
    tiny = AmrSim(_params("auto", budget=1e-4))    # ~100 bytes: exceed
    assert tiny._offload is not None
    assert tiny._offload.engaged(tiny)
    huge = AmrSim(_params("auto", budget=1e6))     # 1 TB: never exceed
    assert huge._offload is not None
    assert not huge._offload.engaged(huge)
    # under the cap the fast path must hold device arrays only
    huge.step_coarse(huge.coarse_dt())
    assert not any(is_parked(a) for a in huge.u.values())


def test_on_mode_warns_and_declines_when_ineligible(recwarn):
    p = _params("on")
    p.run.fault_inject = "nan@999"         # fault injector present
    sim = AmrSim(p)
    assert sim._offload is not None
    assert not sim._offload.engaged(sim)
    assert any("offload=on ignored" in str(w.message) for w in recwarn)


def test_bad_mode_rejected():
    with pytest.raises(ValueError, match="offload"):
        AmrSim(_params("sometimes"))


# ---------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------
def test_zero_overhead_when_off(monkeypatch):
    import jax

    sim = AmrSim(_params("off"))
    assert sim._offload is None            # no engine on the default path
    sim.regrid_interval = 0
    sim.evolve(1e9, nstepmax=4)            # warm the fused chunk
    calls = {"n": 0}
    real = jax.device_get

    def counted(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counted)
    sim.evolve(1e9, nstepmax=sim.nstep + 8)
    assert calls["n"] == 0, \
        "offload=off must not add device fetches to evolve"


# ---------------------------------------------------------------------
# telemetry composition
# ---------------------------------------------------------------------
def test_telemetry_records_offload_stats(tmp_path):
    import json

    p = _params("on", lmax=6)
    p.output.telemetry = str(tmp_path / "run.jsonl")
    p.output.telemetry_interval = 1
    sim = AmrSim(p)
    sim.evolve(1e9, nstepmax=3)
    sim.telemetry.close(sim, print_timers=False)
    with open(tmp_path / "run.jsonl") as f:
        recs = [json.loads(line) for line in f]
    assert recs[0]["run_info"]["offload"] == "on"
    steps = [r for r in recs if r["kind"] == "step"]
    offs = [r["offload"] for r in steps if "offload" in r]
    assert offs, "engaged steps must carry the offload block"
    for o in offs:
        for k in ("stalls", "prefetches", "fetches", "overlap_frac",
                  "bytes_parked", "bytes_fetched", "device_hwm_bytes"):
            assert k in o
    foot = recs[-1]
    assert foot["kind"] == "run_footer"
    assert "offload_stalls" in foot
    assert foot["offload_bytes_parked"] > 0
    assert foot["offload_device_hwm_bytes"] > 0


# ---------------------------------------------------------------------
# schedule planner
# ---------------------------------------------------------------------
def test_plan_working_sets_cover_neighbors():
    from ramses_tpu.amr.offload import plan_schedule

    sim = AmrSim(_params("on", lmax=6))
    ops = plan_schedule(sim._fused_spec())
    lv = list(sim.levels())
    sweeps = [op for op in ops if op.kind == "sweep"]
    # factor-2 subcycling: level i sweeps 2^(i-lmin) times
    assert len(sweeps) == sum(1 << (i) for i in range(len(lv)))
    for op in ops:
        if op.kind == "sweep" and lv[op.i] > sim.lmin:
            assert lv[op.i] in op.ws and lv[op.i] - 1 in op.ws
        if op.kind == "restrict":
            assert set(op.ws) == {lv[op.i], lv[op.i + 1]}
    # every level is courant-scanned exactly once per coarse step
    assert sorted(op.i for op in ops if op.kind == "courant") \
        == list(range(len(lv)))
