"""Batched many-scenario engine + run-service front-end (ROADMAP item
3): ``batch`` vmaps the fused uniform step chains over a leading member
axis with frozen-config sub-batch grouping; ``queue``/``service`` are
the file-backed submit/claim/complete layer that turns the CLI into a
system absorbing many runs (``python -m ramses_tpu --serve <dir>``)."""

from ramses_tpu.ensemble.batch import (EnsembleEngine, EnsembleSpec,
                                       apply_override, build_member)
from ramses_tpu.ensemble import queue
from ramses_tpu.ensemble.meshplan import MeshPlan, plan_for, stamp_cost
from ramses_tpu.ensemble.service import serve, submit_namelist

__all__ = ["EnsembleEngine", "EnsembleSpec", "MeshPlan",
           "apply_override", "build_member", "plan_for", "queue",
           "serve", "stamp_cost", "submit_namelist"]
