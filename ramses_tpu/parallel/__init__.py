from ramses_tpu.parallel.mesh import make_mesh, spatial_sharding  # noqa: F401
