"""Numerical step-guard: finiteness checks + rollback bookkeeping.

The drivers already fetch scalar (t, dt) summaries per coarse step /
chunk; :class:`StepGuard` checks those for finiteness (a NaN from the
fused step poisons t within one iteration because the scan's active
flag ``t < tend`` compares False for NaN, so stepping freezes and the
NaN propagates to the returned time).  On a trip the driver restores
its retained pre-step state and retries with halved dt — the
reference's redo-step — escalating the Riemann solver to diffusive
LLF on the second retry.  This module holds only the policy and the
telemetry plumbing; the state capture/restore lives with each driver
because capture semantics differ (donated fused buffers need device
copies, the uniform path keeps plain refs).
"""

from __future__ import annotations

import math
from typing import Optional


class StepRetryExhausted(RuntimeError):
    """Raised after ``max_step_retries`` rollback attempts all failed;
    the driver emergency-dumps the last clean state before raising."""


class StepGuard:
    """Retry policy + telemetry for in-run numerical fault recovery.

    Stateless between steps apart from counters; ``ok()`` is the hot
    check and touches only already-host scalars — arming the guard
    adds no host<->device fetches.
    """

    def __init__(self, max_retries: int = 2, telemetry=None):
        self.max_retries = int(max_retries)
        self.telemetry = telemetry
        self.rollbacks = 0      # retry attempts taken (all steps)
        self.recovered = 0      # steps saved by the ladder
        self.aborts = 0

    @classmethod
    def from_params(cls, params, telemetry=None) -> Optional["StepGuard"]:
        """A guard when ``&RUN_PARAMS max_step_retries > 0``, else
        None (zero-overhead off switch: drivers skip capture)."""
        n = int(getattr(getattr(params, "run", None),
                        "max_step_retries", 0) or 0)
        if n <= 0:
            return None
        return cls(max_retries=n, telemetry=telemetry)

    @staticmethod
    def ok(*vals) -> bool:
        """All host scalars finite (None entries skipped).  Non-finite
        OR the guard's caller passing an already-NaN dt both trip."""
        for v in vals:
            if v is None:
                continue
            if not math.isfinite(float(v)):
                return False
        return True

    # ---- telemetry / screen ------------------------------------------

    def _emit(self, kind: str, **fields):
        tel = self.telemetry
        if tel is not None:
            try:
                tel.record_event(kind, **fields)
            except Exception:
                pass

    def record_trip(self, sim, reason: str = "nonfinite"):
        self._emit("fault", reason=reason,
                   nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)))
        print(f" step guard: non-finite state at nstep="
              f"{int(getattr(sim, 'nstep', 0))} ({reason}); "
              "rolling back")

    def record_rollback(self, sim, attempt: int, dt: float,
                        escalated: bool):
        self.rollbacks += 1
        self._emit("rollback", attempt=int(attempt), dt=float(dt),
                   escalated=bool(escalated),
                   nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)))
        extra = ", riemann->llf" if escalated else ""
        print(f" step guard: retry {attempt}/{self.max_retries} "
              f"with dt={dt:.6e}{extra}")

    def record_recovered(self, sim, attempt: int):
        self.recovered += 1
        self._emit("rollback_recovered", attempt=int(attempt),
                   nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)))
        print(f" step guard: step recovered on retry {attempt}")

    def record_abort(self, sim, outdir: Optional[str]):
        self.aborts += 1
        self._emit("rollback_abort", nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)),
                   emergency_dump=outdir or "")
        print(" step guard: retry ladder exhausted"
              + (f"; emergency dump -> {outdir}" if outdir else ""))
