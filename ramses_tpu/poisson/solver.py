"""Uniform-grid Poisson solvers: FFT (exact discrete), multigrid, CG.

Solves the 7-point (2*ndim+1) finite-difference Poisson problem
``Lap(phi) = rhs`` with periodic boundaries on a [*spatial] grid.

Reference equivalents: per-level multigrid ``multigrid_fine``
(``poisson/multigrid_fine_commons.f90:25-305``) with red-black Gauss-Seidel
smoothing (``poisson/multigrid_fine_fine.f90:332``), and the conjugate
gradient alternative ``phi_fine_cg`` (``poisson/phi_fine_cg.f90:5-625``).
The FFT path solves the same discrete operator exactly (eigenvalues of the
periodic difference Laplacian), so MG/CG can be validated against it — and
on TPU it is usually the fastest option for the base level.

All functions are shape-generic over ndim 1/2/3 and jit-friendly (static
iteration counts; convergence checks by fixed cycle count like the
reference's MAXITER=10 cap, ``multigrid_fine_commons.f90:33-34``).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def laplacian(phi, dx: float):
    """Periodic 2*ndim+1-point Laplacian, all spatial axes of ``phi``."""
    nd = phi.ndim
    out = -2.0 * nd * phi
    for ax in range(nd):
        out = out + jnp.roll(phi, 1, axis=ax) + jnp.roll(phi, -1, axis=ax)
    return out / (dx * dx)


def residual(phi, rhs, dx: float):
    return rhs - laplacian(phi, dx)


def _parity_mask(shape: Tuple[int, ...]):
    """Checkerboard mask: True on 'red' cells (sum of indices even)."""
    idx = sum(np.indices(shape))
    return jnp.asarray(idx % 2 == 0)


def gauss_seidel(phi, rhs, dx: float, iters: int, red_mask=None):
    """Red-black Gauss-Seidel sweeps (``gauss_seidel_mg_fine``,
    ``poisson/multigrid_fine_fine.f90:332``): one call = ``iters`` full
    (red+black) relaxations."""
    if red_mask is None:
        red_mask = _parity_mask(phi.shape)
    nd = phi.ndim
    dx2 = dx * dx
    inv = 1.0 / (2.0 * nd)

    def half_sweep(phi, mask):
        nb = jnp.zeros_like(phi)
        for ax in range(nd):
            nb = nb + jnp.roll(phi, 1, axis=ax) + jnp.roll(phi, -1, axis=ax)
        upd = (nb - dx2 * rhs) * inv
        return jnp.where(mask, upd, phi)

    def body(phi, _):
        phi = half_sweep(phi, red_mask)
        phi = half_sweep(phi, ~red_mask)
        return phi, None

    phi, _ = jax.lax.scan(body, phi, None, length=iters)
    return phi


def restrict(r):
    """Full restriction: average over 2^ndim children (the reference
    restricts residuals by child averaging, ``restrict_residual_fine``,
    ``poisson/multigrid_fine_fine.f90:457``)."""
    nd = r.ndim
    for ax in range(nd):
        shape = r.shape[:ax] + (r.shape[ax] // 2, 2) + r.shape[ax + 1:]
        r = r.reshape(shape).mean(axis=ax + 1)
    return r


def prolong(e, fine_shape: Tuple[int, ...]):
    """Cell-centered linear prolongation, periodic wrap
    (``interpolate_and_correct_fine``,
    ``poisson/multigrid_fine_fine.f90:596``): a child at offset -/+1/4 of
    its parent gets ``3/4 parent + 1/4 neighbour``, per axis."""
    for ax in range(e.ndim):
        lo = 0.75 * e + 0.25 * jnp.roll(e, 1, axis=ax)
        hi = 0.75 * e + 0.25 * jnp.roll(e, -1, axis=ax)
        e = jnp.stack([lo, hi], axis=ax + 1)
        shape = e.shape[:ax] + (e.shape[ax] * 2,) + e.shape[ax + 2:]
        e = e.reshape(shape)
    return e


def _mg_levels(shape: Tuple[int, ...], min_size: int = 4) -> int:
    """Number of coarsenings possible (all dims halve evenly, stay >= min)."""
    lv = 0
    s = list(shape)
    while all(n % 2 == 0 and n // 2 >= min_size for n in s):
        s = [n // 2 for n in s]
        lv += 1
    return lv


def vcycle(phi, rhs, dx: float, nlevel: int, npre: int = 2, npost: int = 2,
           ncoarse_iter: int = 32):
    """One V-cycle over ``nlevel`` coarsenings (statically unrolled)."""
    if nlevel == 0:
        return gauss_seidel(phi, rhs, dx, ncoarse_iter)
    phi = gauss_seidel(phi, rhs, dx, npre)
    r = restrict(residual(phi, rhs, dx))
    e = vcycle(jnp.zeros_like(r), r, 2.0 * dx, nlevel - 1, npre, npost,
               ncoarse_iter)
    phi = phi + prolong(e, phi.shape)
    return gauss_seidel(phi, rhs, dx, npost)


@partial(jax.jit, static_argnames=("ncycle", "npre", "npost"))
def mg_solve(rhs, dx: float, phi0=None, ncycle: int = 10, npre: int = 2,
             npost: int = 2):
    """Multigrid solve: fixed ``ncycle`` V-cycles (the reference caps at
    MAXITER=10, ``multigrid_fine_commons.f90:33``).  Periodic compatibility
    (zero mean) is enforced on the rhs; the returned phi has zero mean."""
    rhs = rhs - jnp.mean(rhs)
    phi = jnp.zeros_like(rhs) if phi0 is None else phi0
    nlevel = _mg_levels(rhs.shape)
    for _ in range(ncycle):
        phi = vcycle(phi, rhs, dx, nlevel)
    return phi - jnp.mean(phi)


@jax.jit
def fft_solve(rhs, dx: float):
    """Exact solve of the discrete periodic problem via FFT.

    Divides by the eigenvalues of the 2*ndim+1-point Laplacian
    ``sum_d (2 cos(2 pi k_d / N_d) - 2) / dx^2`` so the result satisfies
    the *same discrete equations* as MG/CG (not the continuum solution).
    """
    nd = rhs.ndim
    shape = rhs.shape
    # The spectral solve is inherently global (all-to-all); under a
    # sharded jit, force a replicated layout around the FFT — XLA's CPU
    # FFT thunk cannot run on partitioned operands, and on TPU a
    # partitioned FFT would all-to-all anyway.
    try:
        from jax.sharding import PartitionSpec
        rhs = jax.lax.with_sharding_constraint(rhs, PartitionSpec())
    except (ValueError, RuntimeError, TypeError):
        pass  # no mesh in scope: single-device path
    rhat = jnp.fft.rfftn(rhs)
    lam = jnp.zeros(rhat.shape, rhs.dtype)
    for ax in range(nd):
        n = shape[ax]
        if ax == nd - 1:  # rfft axis: only n//2+1 freqs
            k = jnp.arange(rhat.shape[ax])
        else:
            k = jnp.arange(n)
        ev = 2.0 * jnp.cos(2.0 * jnp.pi * k / n) - 2.0
        bshape = [1] * len(rhat.shape)
        bshape[ax] = rhat.shape[ax]
        lam = lam + ev.reshape(bshape)
    lam = lam / (dx * dx)
    # zero mode: set phi_0 = 0 (zero-mean solution)
    lam0 = jnp.where(lam == 0.0, 1.0, lam)
    phat = jnp.where(lam == 0.0, 0.0, rhat / lam0)
    return jnp.fft.irfftn(phat, s=shape)


@partial(jax.jit, static_argnames=("iters", "tol"))
def cg_solve(rhs, dx: float, phi0=None, iters: int = 200,
             tol: float = 0.0):
    """Conjugate gradient on the periodic Laplacian (``phi_fine_cg``,
    ``poisson/phi_fine_cg.f90:5``): fixed iteration count under jit,
    iterations frozen once ``|r|/|r0| < tol`` (&POISSON_PARAMS epsilon)
    or the residual hits rounding level."""
    rhs = rhs - jnp.mean(rhs)
    phi = jnp.zeros_like(rhs) if phi0 is None else phi0
    r = residual(phi, rhs, dx)
    p = r
    rs = jnp.vdot(r, r)
    rs0 = rs
    eps = jnp.asarray(jnp.finfo(rhs.dtype).eps, rhs.dtype)
    cut = jnp.maximum(eps * eps, jnp.asarray(tol * tol, rhs.dtype))
    floor = cut * jnp.maximum(rs0, 1e-300)

    def body(carry, _):
        phi, r, p, rs = carry
        live = rs > floor  # freeze once converged (or rounding takes over)
        ap = laplacian(p, dx)
        denom = jnp.vdot(p, ap)
        alpha = jnp.where(live & (denom != 0.0),
                          rs / jnp.where(denom == 0, 1, denom), 0.0)
        phi = phi + alpha * p
        r_new = r - alpha * ap
        rs_new = jnp.vdot(r_new, r_new)
        beta = jnp.where(live, rs_new / jnp.where(rs == 0, 1, rs), 0.0)
        p = jnp.where(live, r_new + beta * p, p)
        return (phi, jnp.where(live, r_new, r),
                p, jnp.where(live, rs_new, rs)), None

    (phi, r, p, rs), _ = jax.lax.scan(body, (phi, r, p, rs), None,
                                      length=iters)
    return phi - jnp.mean(phi)
