"""Turbulence forcing (SURVEY.md §2.8): Ornstein-Uhlenbeck process in
k-space with solenoidal/compressive Helmholtz projection, applied as a
body acceleration.  The reference's FFTW-on-rank-1-then-broadcast design
(``turb/``) becomes a device-resident ``jnp.fft`` field — no broadcast,
no dedicated rank."""
