"""Native host kernels: build-on-demand C++ with ctypes bindings.

``lib()`` returns the loaded shared library, compiling
``src/ramses_native.cpp`` with g++ on first use; ``None`` when no
compiler is available (callers fall back to numpy).  Set
``RAMSES_TPU_NATIVE=0`` to force the numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "ramses_native.cpp")
_SO = os.path.join(_HERE, "_ramses_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_u64p = np.ctypeslib.ndpointer(dtype=np.uint64, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if os.environ.get("RAMSES_TPU_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (os.path.getmtime(_SO)
                                       < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        L.morton_encode.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int,
                                    _i64p]
        L.hilbert_encode.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int,
                                     ctypes.c_int, _u64p]
        L.searchsorted_i64.argtypes = [_i64p, ctypes.c_int64, _i64p,
                                       ctypes.c_int64, _i64p]
        L.lookup_i64.argtypes = [_i64p, ctypes.c_int64, _i64p,
                                 ctypes.c_int64, _i64p]
        L.neighbor_lookup.argtypes = [_i64p, _i64p, ctypes.c_int64,
                                      ctypes.c_int, ctypes.c_int64,
                                      _i64p, ctypes.c_int64, _i64p]
        _lib = L
        return _lib


def morton_encode(og: np.ndarray, ndim: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    og = np.ascontiguousarray(og, dtype=np.int64)
    out = np.empty(len(og), dtype=np.int64)
    L.morton_encode(og, len(og), ndim, out)
    return out


def hilbert_encode(og: np.ndarray, ndim: int,
                   nbits: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    og = np.ascontiguousarray(og, dtype=np.int64)
    out = np.empty(len(og), dtype=np.uint64)
    L.hilbert_encode(og, len(og), ndim, nbits, out)
    return out


def lookup_sorted(sorted_keys: np.ndarray,
                  queries: np.ndarray) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    s = np.ascontiguousarray(sorted_keys, dtype=np.int64)
    q = np.ascontiguousarray(queries, dtype=np.int64)
    out = np.empty(len(q), dtype=np.int64)
    L.lookup_i64(s, len(s), q, len(q), out)
    return out


def neighbor_lookup(sorted_keys: np.ndarray, og: np.ndarray, ndim: int,
                    level_size: int,
                    offsets: np.ndarray) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    s = np.ascontiguousarray(sorted_keys, dtype=np.int64)
    o = np.ascontiguousarray(og, dtype=np.int64)
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    out = np.empty(len(o) * len(offs), dtype=np.int64)
    L.neighbor_lookup(s, o, len(o), ndim, level_size, offs, len(offs), out)
    return out.reshape(len(o), len(offs))
