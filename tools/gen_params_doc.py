#!/usr/bin/env python
"""Generate ``docs/params.md`` from ``ramses_tpu/config.py``.

Every namelist group the runtime parses (``_GROUP_MAP``) becomes one
section: a table of every field with its default (rendered in namelist
syntax) and its semantics, harvested mechanically from the dataclass
source — the comment block directly above a field plus any trailing
comment on its line.  Because the tables are derived from the config
module itself, the doc cannot drift from the code: ``--check`` re-
renders and fails when ``docs/params.md`` is stale (wired into CI and
``tests/test_params_doc.py``).

Usage:
    python tools/gen_params_doc.py           # rewrite docs/params.md
    python tools/gen_params_doc.py --check   # exit 1 when stale
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONFIG_PY = os.path.join(REPO, "ramses_tpu", "config.py")
DOC_PATH = os.path.join(REPO, "docs", "params.md")

HEADER = """\
# Namelist parameters (generated)

Every namelist key the runtime parses, with defaults and semantics —
generated from `ramses_tpu/config.py` by `tools/gen_params_doc.py`.
**Do not edit by hand**: rerun the generator after changing a config
dataclass; CI and `tests/test_params_doc.py` fail when this file is
stale.  For the curated per-group prose see
[runtime_parameters.md](runtime_parameters.md) and
[namelists.md](namelists.md).

Defaults are rendered in namelist syntax (`.true.`/`.false.`, quoted
strings).  Long per-level/per-region list defaults are abbreviated as
`v,... (Nx)`.  `ndim`/`nvar`/`nener`/`npassive` are load-time
arguments (`--ndim` on the CLI), not namelist keys.
"""


def _field_comments(src: str):
    """Map (class_name, field_name) -> semantics string harvested from
    the source: contiguous ``#`` lines directly above the field plus a
    trailing comment on the field's own (possibly wrapped) statement."""
    lines = src.splitlines()
    tree = ast.parse(src)
    out = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in cls.body:
            if not isinstance(node, ast.AnnAssign) \
                    or not isinstance(node.target, ast.Name):
                continue
            name = node.target.id
            # comment block above (stop at code or blank line)
            block = []
            i = node.lineno - 2
            while i >= 0:
                s = lines[i].strip()
                if s.startswith("#"):
                    block.insert(0, s.lstrip("#").strip())
                    i -= 1
                else:
                    break
            # trailing comments on the statement's own lines
            trail = []
            end = getattr(node, "end_lineno", node.lineno)
            for j in range(node.lineno - 1, end):
                m = re.search(r"#\s?(.*)$", lines[j])
                if m:
                    trail.append(m.group(1).strip())
            text = " ".join(block + trail)
            out[(cls.name, name)] = re.sub(r"\s+", " ", text).strip()
    return out


def _render_default(v) -> str:
    if isinstance(v, bool):
        return ".true." if v else ".false."
    if isinstance(v, str):
        return f"`'{v}'`"
    if isinstance(v, float):
        return f"`{v!r}`"
    if isinstance(v, int):
        return f"`{v}`"
    if isinstance(v, list):
        if not v:
            return "—"
        if len(v) > 3 and len({repr(x) for x in v}) == 1:
            inner = _render_default(v[0]).strip("`")
            return f"`{inner},...` ({len(v)}x)"
        return "`" + ",".join(
            _render_default(x).strip("`") for x in v) + "`"
    return f"`{v!r}`"


def _md_escape(s: str) -> str:
    return s.replace("|", "\\|")


def render() -> str:
    from ramses_tpu import config as cfg

    with open(CONFIG_PY) as f:
        src = f.read()
    comments = _field_comments(src)
    p = cfg.Params()
    out = io.StringIO()
    out.write(HEADER)
    for gname, attr in cfg._GROUP_MAP.items():
        sub = getattr(p, attr)
        cls = type(sub)
        out.write(f"\n## &{gname.upper()} — `params.{attr}`\n\n")
        doc = (cls.__doc__ or "").strip()
        if doc:
            out.write(re.sub(r"\s+", " ", doc) + "\n\n")
        out.write("| parameter | default | semantics |\n")
        out.write("|---|---|---|\n")
        for fld in dataclasses.fields(cls):
            default = _render_default(getattr(sub, fld.name))
            sem = comments.get((cls.__name__, fld.name), "")
            out.write(f"| `{fld.name}` | {default} "
                      f"| {_md_escape(sem)} |\n")
    out.write(
        "\n## Raw groups\n\n"
        "Groups not in the table above stay verbatim in `params.raw` "
        "and are parsed by their owning subsystem (`&SF_PARAMS`, "
        "`&FEEDBACK_PARAMS`, `&SINK_PARAMS`, `&STELLAR_PARAMS`, "
        "`&MOVIE_PARAMS`, `&TURB_PARAMS`) — see "
        "[namelists.md](namelists.md).\n")
    return out.getvalue()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    text = render()
    if "--check" in argv:
        try:
            with open(DOC_PATH) as f:
                cur = f.read()
        except FileNotFoundError:
            cur = ""
        if cur != text:
            print("docs/params.md is STALE — rerun "
                  "`python tools/gen_params_doc.py`", file=sys.stderr)
            return 1
        print("docs/params.md is up to date")
        return 0
    with open(DOC_PATH, "w") as f:
        f.write(text)
    print(f"wrote {os.path.relpath(DOC_PATH, REPO)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
