"""Isolated (non-periodic) self-gravity: multipole Dirichlet boundary +
zero-ghost CG (``pm/rho_fine.f90:666`` multipole_fine,
``poisson/boundary_potential.f90:5-341``), open-box particles."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.poisson.isolated import grad_isolated, isolated_solve

OUTFLOW_BOX = {"nboundary": 6,
               "ibound_min": [-1, 1, 0, 0, 0, 0],
               "ibound_max": [-1, 1, 0, 0, 0, 0],
               "jbound_min": [0, 0, -1, 1, 0, 0],
               "jbound_max": [0, 0, -1, 1, 0, 0],
               "kbound_min": [0, 0, 0, 0, -1, 1],
               "kbound_max": [0, 0, 0, 0, -1, 1],
               "bound_type": [2, 2, 2, 2, 2, 2]}


def test_isolated_point_mass_force():
    """Force of a compact blob matches -GM/r^2 far from it (1% level)."""
    n = 32
    dx = 1.0 / n
    ax = (np.arange(n) + 0.5) * dx
    X, Y, Z = np.meshgrid(ax, ax, ax, indexing="ij")
    r2 = (X - 0.5) ** 2 + (Y - 0.5) ** 2 + (Z - 0.5) ** 2
    a = 0.03
    rho = (1 + r2 / a ** 2) ** -2.5
    rho = jnp.asarray(rho / (rho.sum() * dx ** 3))      # M = 1
    coeff = 4 * np.pi                                   # G = 1
    phi, gh = isolated_solve(rho, dx, coeff, iters=400)
    f = grad_isolated(phi, gh, dx)
    i, j, k = int(0.9 * n), n // 2, n // 2
    rr = abs(ax[i] - 0.5)
    fr = float(f[0][i, j, k])
    assert fr < 0                                       # inward
    assert abs(fr / (-1.0 / rr ** 2) - 1.0) < 0.02
    # potential wells are negative and decay outward
    assert float(phi.min()) < float(phi[0, 0, 0]) < 0.0


def test_isolated_vs_periodic_differ():
    """The isolated solve must NOT equal the periodic FFT solve — the
    image masses are gone."""
    from ramses_tpu.poisson.solver import fft_solve
    n = 16
    dx = 1.0 / n
    rho = np.zeros((n, n, n))
    rho[4:6, 4:6, 4:6] = 1.0
    rhs = jnp.asarray(4 * np.pi * rho)
    phi_per = fft_solve(rhs - jnp.mean(rhs), dx)
    phi_iso, _ = isolated_solve(jnp.asarray(rho), dx, 4 * np.pi,
                                iters=300)
    # same discrete operator, different BCs: interior shapes differ
    d_per = float(phi_per[5, 5, 5] - phi_per[12, 12, 12])
    d_iso = float(phi_iso[5, 5, 5] - phi_iso[12, 12, 12])
    assert abs(d_per - d_iso) > 1e-3 * abs(d_iso)


@pytest.mark.slow
def test_amr_isolated_gravity_blob():
    """Open-box AMR run: blob force points inward at ~-M/r^2, and the
    hierarchy steps stay finite (the old periodic-only raise is gone)."""
    from ramses_tpu.amr.hierarchy import AmrSim
    groups = {
        "run_params": {"hydro": True, "poisson": True},
        "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0},
        "boundary_params": dict(OUTFLOW_BOX),
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [0.01, 20.0],
                        "p_region": [0.01, 1.0]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "refine_params": {"err_grad_d": 0.2},
        "output_params": {"tend": 0.01},
    }
    sim = AmrSim(params_from_dict(groups, ndim=3), dtype=jnp.float64)
    assert not sim.grav_periodic
    sim.solve_gravity()
    l = sim.lmin
    fg = np.asarray(sim.fg[l])
    xc = sim.tree.cell_centers(l, sim.boxlen)
    r = xc - 0.5
    rr = np.sqrt((r ** 2).sum(1))
    sel = (rr > 0.3) & (rr < 0.45)
    fr = (fg[:len(xc)][sel] * (r[sel] / rr[sel, None])).sum(1)
    M = sim.totals()[0]
    ana = -(M / rr[sel] ** 2)
    assert fr.mean() < 0
    assert abs(fr.mean() / ana.mean() - 1.0) < 0.1
    sim.evolve(0.01)
    assert all(np.isfinite(np.asarray(sim.u[l])).all()
               for l in sim.levels())


def test_uniform_isolated_gravity():
    """Uniform driver with outflow walls uses the isolated solve."""
    from ramses_tpu.driver import Simulation
    groups = {
        "run_params": {"hydro": True, "poisson": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "boundary_params": dict(OUTFLOW_BOX),
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [0.01, 20.0],
                        "p_region": [0.01, 1.0]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "output_params": {"tend": 0.005},
    }
    sim = Simulation(params_from_dict(groups, ndim=3), dtype=jnp.float64)
    assert not sim.gspec.periodic
    f = np.asarray(sim.state.f)
    n = 16
    # x-face probe: force toward the centre from both sides
    assert f[0][1, n // 2, n // 2] > 0 > f[0][-2, n // 2, n // 2]
    sim.evolve()
    assert np.isfinite(np.asarray(sim.state.u)).all()


def test_open_box_particles_escape_and_deposit():
    """Open-box particles: an escaping particle deactivates; CIC
    corners outside the box drop (deposited mass < particle mass)."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.pm import amr_pm
    from ramses_tpu.pm.particles import ParticleSet, drift

    groups = {
        "run_params": {"hydro": True, "poisson": True, "pic": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "boundary_params": dict(OUTFLOW_BOX),
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "output_params": {"tend": 0.1},
    }
    x = jnp.asarray([[0.5, 0.5, 0.5], [0.98, 0.5, 0.5]])
    v = jnp.asarray([[0.0, 0.0, 0.0], [1.0, 0.0, 0.0]])
    m = jnp.asarray([1.0, 1.0])
    p = ParticleSet.make(x, v, m)
    sim = AmrSim(params_from_dict(groups, ndim=3), dtype=jnp.float64,
                 particles=p)
    # edge particle: CIC corner past the wall is dropped
    ncp = {l: sim.maps[l].ncell_pad for l in sim.levels()}
    maps = amr_pm.build_pm_maps(sim.tree, np.asarray(p.x, np.float64),
                                sim.boxlen, sim.bc_kinds, ncp)
    mp = maps[4]
    rho = amr_pm.deposit_flat(jnp.asarray(mp.idx), jnp.asarray(mp.w),
                              p.m, p.active, ncp[4], sim.dx(4) ** 3)
    dep = float(rho.sum()) * sim.dx(4) ** 3
    assert 1.0 < dep < 2.0         # centre particle full, edge partial

    # escaping particle deactivates on drift
    p2 = drift(p, 0.05, 1.0, periodic=False)
    act = np.asarray(p2.active)
    assert act[0] and not act[1]

    sim.evolve(0.02, nstepmax=4)
    assert int(np.asarray(sim.p.active).sum()) >= 1
