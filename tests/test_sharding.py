"""Multi-device decomposition invariance.

The reference's own distributed test strategy (SURVEY.md §4.3): the same
aggregates must come out regardless of the decomposition.  Here: a sharded
run over the 8-device CPU mesh must match the single-device run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.config import params_from_string
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import run_steps
from ramses_tpu.parallel.mesh import factorize, make_mesh
from ramses_tpu.parallel.sharded import ShardedSim

from tests.test_hydro_3d import SEDOV


def test_factorize():
    assert factorize(8, 3) == (2, 2, 2)
    assert factorize(4, 3) == (2, 2, 1)
    assert factorize(8, 1) == (8,)
    assert factorize(6, 2) == (3, 2)
    assert factorize(1, 3) == (1, 1, 1)


@pytest.mark.parametrize("ndim", [2, 3])
def test_sharded_matches_single_device(ndim):
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    p = params_from_string(SEDOV.format(lmin=4, tout=1.0, nstep=100),
                           ndim=ndim)
    # single device
    sim = Simulation(p, dtype=jnp.float64)
    u1, t1, n1 = run_steps(sim.grid, sim.state.u,
                           jnp.asarray(0.0, jnp.float64),
                           jnp.asarray(1e9, jnp.float64), 5)
    # 8-device sharded
    ssim = ShardedSim(p, dtype=jnp.float64)
    ssim.run(5)
    assert int(n1) == ssim.nstep
    np.testing.assert_allclose(np.asarray(u1), np.asarray(ssim.u),
                               rtol=1e-12, atol=1e-13)
    assert ssim.t == pytest.approx(float(t1), rel=1e-12)


def test_mesh_shape():
    mesh = make_mesh(3)
    assert mesh.devices.size == len(jax.devices())


@pytest.mark.smoke
@pytest.mark.slow
def test_sharded_amr_matches_single_device():
    """Decomposition invariance for the AMR path: identical aggregates
    from the 8-device sharded run and the single-device run."""
    from ramses_tpu.config import params_from_dict
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 3, "levelmax": 5, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "y_center": [0.5, 0.5],
                        "length_x": [0.5, 0.5], "length_y": [10.0, 10.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.8,
                         "riemann": "hllc", "slope_type": 1},
        "refine_params": {"err_grad_d": 0.05, "err_grad_p": 0.05},
        "output_params": {"tend": 0.05},
    }
    p1 = params_from_dict({k: dict(v) for k, v in groups.items()}, ndim=2)
    p2 = params_from_dict({k: dict(v) for k, v in groups.items()}, ndim=2)
    sim1 = AmrSim(p1, dtype=jnp.float64)
    sim8 = ShardedAmrSim(p2, dtype=jnp.float64)
    sim1.evolve(0.03)
    sim8.evolve(0.03)
    assert sim1.nstep == sim8.nstep
    for l in sim1.levels():
        assert sim1.tree.noct(l) == sim8.tree.noct(l)
    t1 = sim1.totals()
    t8 = sim8.totals()
    np.testing.assert_allclose(t1, t8, rtol=1e-13)
    # leaf state bitwise-comparable on the base level
    nc = sim1.maps[sim1.lmin].noct * 4
    np.testing.assert_allclose(
        np.asarray(sim1.u[sim1.lmin])[:nc],
        np.asarray(sim8.u[sim8.lmin])[:nc], rtol=1e-13, atol=1e-14)


def test_sharded_pm_matches_single_device():
    """Decomposition invariance with particles + self-gravity."""
    from ramses_tpu.config import params_from_string
    from ramses_tpu.pm.particles import ParticleSet

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0", "/",
        "&POISSON_PARAMS", "solver='cg'", "/",
        "&OUTPUT_PARAMS", "noutput=1", "tout=1.0", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
    ])
    rng = np.random.default_rng(0)
    x0 = rng.uniform(0, 1, (64, 3))
    v0 = rng.standard_normal((64, 3)) * 0.01
    m0 = np.full(64, 0.01)

    p1 = params_from_string(nml)
    sim = Simulation(p1, dtype=jnp.float64,
                     particles=ParticleSet.make(x0, v0, m0))
    from ramses_tpu.pm.coupling import run_steps_pm
    u1, pp1, f1, t1, _d, n1 = run_steps_pm(
        sim.grid, sim.gspec, sim.pspec, sim.state.u, sim.state.p,
        sim.state.f, jnp.asarray(0.0, jnp.float64),
        jnp.asarray(1e9, jnp.float64), jnp.asarray(0.0, jnp.float64), 4)

    p2 = params_from_string(nml)
    ssim = ShardedSim(p2, dtype=jnp.float64)
    # note: ShardedSim builds its own empty particle set only if driver
    # created one; inject the same particles sharded
    from ramses_tpu.parallel.sharded import ShardedSim as _SS
    sim2 = Simulation(p2, dtype=jnp.float64,
                      particles=ParticleSet.make(x0, v0, m0))
    ss = _SS.__new__(_SS)
    ss.inner = sim2
    ss.mesh = make_mesh(3)
    from ramses_tpu.parallel.mesh import spatial_sharding
    ss.sharding = spatial_sharding(ss.mesh, n_leading=1)
    ss.u = jax.device_put(sim2.state.u, ss.sharding)
    ss.gspec, ss.pspec, ss.cosmo = sim2.gspec, sim2.pspec, sim2.cosmo
    ss.f = jax.device_put(sim2.state.f, ss.sharding)
    ss.p = sim2.state.p
    ss.t, ss.dt_old, ss.nstep = 0.0, 0.0, 0
    ss.run(4)
    assert int(n1) == ss.nstep
    np.testing.assert_allclose(np.asarray(u1), np.asarray(ss.u),
                               rtol=1e-12, atol=1e-14)
    np.testing.assert_allclose(np.asarray(pp1.x), np.asarray(ss.p.x),
                               rtol=1e-12)
