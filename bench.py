#!/usr/bin/env python
"""Benchmark driver: sedov3d uniform-grid hydro throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric is cell-updates/sec/chip on the sedov3d config (BASELINE.md §
protocol, config 1: levelmin=levelmax uniform).  ``vs_baseline`` compares
against the 64-rank MPI CPU reference baseline figure when one has been
recorded in BASELINE.json ("published"); until then we report against the
reference's self-measured single-core class figure of ~1 microsecond per
cell-update (mus/pt, ``amr/adaptive_loop.f90:204-212``) scaled to 64 ranks
=> 6.4e7 cell-updates/sec — the conservative stand-in the driver's
north-star ratio is measured against.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from ramses_tpu.config import load_params
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import run_steps

# 64-rank MPI CPU baseline stand-in: 1 mus per cell-update per rank (the
# classic RAMSES mus/pt figure) x 64 ranks => 64e6 updates/sec.
BASELINE_CELL_UPDATES_PER_SEC = 64e6


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    nml = os.path.join(here, "namelists", "sedov3d.nml")
    params = load_params(nml, ndim=3)
    # levelmin=8 -> 256^3; keep the reference config. On small hosts allow
    # override via BENCH_LEVEL.
    lvl = int(os.environ.get("BENCH_LEVEL", params.amr.levelmin))
    params.amr.levelmin = params.amr.levelmax = lvl
    params.run.nstepmax = 10 ** 9

    dtype = jnp.bfloat16 if os.environ.get("BENCH_BF16") else jnp.float32
    sim = Simulation(params, dtype=dtype)

    nsteps = int(os.environ.get("BENCH_STEPS", "20"))
    u = sim.state.u
    t = jnp.asarray(0.0, jnp.float32)   # time in f32 even for bf16 state
    tend = jnp.asarray(1e9, jnp.float32)

    # warmup (compile)
    u1, t1, _ = run_steps(sim.grid, u, t, tend, 2)
    u1.block_until_ready()

    t0 = time.perf_counter()
    u2, t2, ndone = run_steps(sim.grid, u1, t1, tend, nsteps)
    u2.block_until_ready()
    wall = time.perf_counter() - t0

    ncell = sim.grid.ncell
    updates = ncell * int(ndone)
    rate = updates / wall
    out = {
        "metric": f"cell-updates/sec/chip sedov3d uniform 2^{lvl}^3",
        "value": rate,
        "unit": "cell-updates/s",
        "vs_baseline": rate / BASELINE_CELL_UPDATES_PER_SEC,
        "detail": {
            "device": str(jax.devices()[0].platform),
            "n": ncell,
            "steps": int(ndone),
            "wall_s": wall,
            "mus_per_cell_update": 1e6 * wall / max(updates, 1),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
