"""Every shipped namelist runs through the CLI — the role of the
reference's ``tests/run_test_suite.sh`` over its per-test ``.nml``
configs (SURVEY.md §2.11): each config must dispatch to the right
solver family, take real steps, and write a snapshot, with no
special-casing beyond the command line.

The suite copies each namelist to tmp with the step count clamped and
the resolution capped (CPU-host budget); physics and structure are the
shipped file's.
"""

import os
import re

import pytest

jnp = pytest.importorskip("jax.numpy")

NMLDIR = os.path.join(os.path.dirname(__file__), "..", "namelists")

# namelist -> (ndim, extra CLI flags); cosmo.nml needs external grafic
# IC files and is exercised by tests/test_cosmo_ics.py instead
CONFIGS = {
    "sedov1d.nml": (1, []),
    "advect1d.nml": (1, []),
    "blast1d.nml": (1, []),
    "detente.nml": (1, []),
    "tube1d.nml": (1, []),
    "tube_mhd.nml": (1, []),
    "orszag2d.nml": (2, []),
    "implosion.nml": (2, []),
    "stromgren2d.nml": (2, []),
    "smbh_bondi.nml": (2, []),
    "tracer_sedov.nml": (2, []),
    "sedov2d.nml": (2, []),
    "sedov2d_balance.nml": (2, []),
    "sedov3d.nml": (3, []),
    "sedov3d_telemetry.nml": (3, []),
    "static.nml": (3, []),
    "iliev1.nml": (3, []),
    "pointmass.nml": (3, []),
    "collapse_iso.nml": (3, []),
    "stromgren3.nml": (3, []),
    "turb_driving.nml": (3, []),
    "twin_rad_src.nml": (2, []),
    "rad_beams.nml": (2, []),
}


def _shrunk_copy(name: str, tmp_path) -> str:
    src = os.path.join(NMLDIR, name)
    txt = open(src).read()

    def clamp(m, cap):
        return f"{m.group(1)}{min(int(m.group(2)), cap)}"

    txt = re.sub(r"(levelmin=)(\d+)", lambda m: clamp(m, 4), txt)
    txt = re.sub(r"(levelmax=)(\d+)", lambda m: clamp(m, 5), txt)
    if "nstepmax" in txt:
        txt = re.sub(r"nstepmax=\d+", "nstepmax=2", txt)
    else:
        txt = txt.replace("&RUN_PARAMS", "&RUN_PARAMS\nnstepmax=2", 1)
    dst = str(tmp_path / name)
    open(dst, "w").write(txt)
    return dst


# the longest-running configs ride the nightly tier only
_SLOW_NMLS = {"collapse_iso.nml", "tube_mhd.nml", "smbh_bondi.nml"}


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_NMLS else n
    for n in sorted(CONFIGS)
])
def test_namelist_runs_through_cli(name, tmp_path, monkeypatch):
    from ramses_tpu.__main__ import main

    ndim, flags = CONFIGS[name]
    nml = _shrunk_copy(name, tmp_path)
    monkeypatch.chdir(tmp_path)
    assert main([nml, "--ndim", str(ndim), "--dtype", "float64",
                 *flags]) == 0
    outs = [d for d in os.listdir(tmp_path) if d.startswith("output_")]
    assert outs, f"{name}: no snapshot written"


def test_suite_covers_all_shipped_namelists():
    shipped = {f for f in os.listdir(NMLDIR) if f.endswith(".nml")}
    # the grafic-IC configs run in test_cosmo_ics instead; the ensemble
    # config must stay uniform (levelmin == levelmax), which the level
    # clamp here would break — tests/test_ensemble.py runs it through
    # the CLI instead
    elsewhere = {"cosmo.nml", "mergertree.nml", "cosmo_gal.nml",
                 "sedov_ensemble.nml"}
    assert shipped - elsewhere == set(CONFIGS)
