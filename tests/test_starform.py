"""Star formation / SN feedback / sink particle tests."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.pm.particles import FAM_STAR, ParticleSet
from ramses_tpu.pm.sinks import (SinkSet, SinkSpec, accrete, create_sinks,
                                 drift_kick, merge_sinks)
from ramses_tpu.pm.star_formation import (FLAG_SN_DONE, SfSpec, star_formation, thermal_feedback)
from ramses_tpu.units import Units, yr2sec


def _units():
    # 1 cc at mH, Myr timescale, pc lengths
    return Units(scale_l=3.086e18, scale_t=3.156e13, scale_d=1.66e-24)


def _empty_particles(ndim=3, nmax=4096):
    return ParticleSet.make(np.zeros((0, ndim)), np.zeros((0, ndim)),
                            np.zeros(0), nmax=nmax)


def _box(n=8, rho=100.0, ndim=3, p=1.0):
    u = np.zeros((ndim + 2,) + (n,) * ndim)
    u[0] = rho
    u[ndim + 1] = p / 0.4
    return u


def test_sf_threshold():
    """No stars below the density threshold."""
    un = _units()
    spec = SfSpec(enabled=True, n_star=1e4, t_star=1.0)
    u = _box(rho=1.0)          # nH ~ 0.76 << 1e4
    p = _empty_particles()
    rng = np.random.default_rng(0)
    u2, p2, nid = star_formation(u, p, rng, spec, un, 1.0 / 8, 0.0, 0.1, 1)
    assert int(np.asarray(p2.active).sum()) == 0


def test_sf_expected_mass_and_conservation():
    """Poisson-sampled stellar mass ≈ mgas·dt/t_star; total conserved."""
    un = _units()
    spec = SfSpec(enabled=True, n_star=1.0, t_star=0.1)
    n = 8
    dx = 1.0 / n
    u = _box(n=n, rho=100.0)
    p = _empty_particles(nmax=200000)
    rng = np.random.default_rng(1)
    m_gas0 = u[0].sum() * dx ** 3
    dt = 0.01
    # expected: lam_cell = mcell/mstar * dt/tstar(rho)
    u2, p2, nid = star_formation(u, p, rng, spec, un, dx, 0.0, dt, 1)
    m_star = float(np.asarray(p2.m)[np.asarray(p2.active)].sum())
    m_gas1 = u2[0].sum() * dx ** 3
    assert np.isclose(m_gas0, m_gas1 + m_star, rtol=1e-12)
    nH = 100.0 * un.scale_nH
    tstar_code = (0.1 * 1e9 * yr2sec * np.sqrt(1.0 / nH)) / un.scale_t
    expected = m_gas0 * dt / tstar_code
    assert abs(m_star - expected) < 0.2 * expected
    fam = np.asarray(p2.family)[np.asarray(p2.active)]
    assert np.all(fam == FAM_STAR)


def test_sn_feedback_once():
    """SN fires once after t_sne, returns mass and energy."""
    un = _units()
    spec = SfSpec(enabled=True, eta_sn=0.2, t_sne=10.0)
    n = 4
    dx = 1.0 / n
    u = _box(n=n, rho=1.0, ndim=3)
    p = ParticleSet.make(np.array([[0.4, 0.4, 0.4]]),
                         np.array([[0.5, 0.0, 0.0]]), np.array([2.0]),
                         family=np.array([FAM_STAR], dtype=np.int8),
                         nmax=4)
    t_sne_code = 10.0 * 1e6 * yr2sec / un.scale_t
    e0 = u[4].sum() * dx ** 3
    m0 = u[0].sum() * dx ** 3 + 2.0
    # before the delay: nothing
    u1, p1 = thermal_feedback(u.copy(), p, spec, un, dx, 0.5 * t_sne_code)
    assert np.allclose(u1, u)
    # after the delay: explosion
    u2, p2 = thermal_feedback(u.copy(), p, spec, un, dx, 2.0 * t_sne_code)
    mej = 0.2 * 2.0
    assert np.isclose(float(np.asarray(p2.m)[0]), 2.0 - mej)
    assert np.isclose(u2[0].sum() * dx ** 3 + float(np.asarray(p2.m)[0]),
                      m0, rtol=1e-12)
    de = u2[4].sum() * dx ** 3 - e0
    esn_code = (1e51 / (10 * 1.9891e33)) / un.scale_v ** 2
    ek_ej = 0.5 * mej * 0.25
    assert np.isclose(de, mej * esn_code + ek_ej, rtol=1e-10)
    assert int(np.asarray(p2.flags)[0]) & FLAG_SN_DONE
    # and not twice
    u3, p3 = thermal_feedback(u2.copy(), p2, spec, un, dx,
                              3.0 * t_sne_code)
    assert np.allclose(u3, u2)


def test_sink_creation_and_threshold_accretion():
    un = _units()
    spec = SinkSpec(enabled=True, n_sink=1e3 / un.scale_nH * un.scale_nH,
                    accretion_scheme="threshold", c_acc=0.5)
    spec = SinkSpec(enabled=True, n_sink=1e3,
                    accretion_scheme="threshold", c_acc=0.5)
    n = 8
    dx = 1.0 / n
    u = _box(n=n, rho=1.0)
    peak_rho = 5e3 / un.scale_nH
    u[0][4, 4, 4] = peak_rho
    m0 = u[0].sum() * dx ** 3
    sinks = SinkSet.empty(3)
    u, sinks = create_sinks(u, sinks, spec, un, dx, 0.0, 1.4)
    assert sinks.n == 1
    d_thr = 1e3 / un.scale_nH
    assert np.isclose(sinks.m[0], (peak_rho - d_thr) * dx ** 3)
    assert np.isclose(u[0].sum() * dx ** 3 + sinks.m.sum(), m0, rtol=1e-12)
    # refill the cell above threshold and accrete
    u[0][4, 4, 4] = 2e3 / un.scale_nH
    m1 = u[0].sum() * dx ** 3 + sinks.m.sum()
    u, sinks = accrete(u, sinks, spec, un, dx, 1.0, 1.4)
    assert np.isclose(u[0].sum() * dx ** 3 + sinks.m.sum(), m1, rtol=1e-12)
    assert u[0][4, 4, 4] * un.scale_nH > 1e3 * 0.49  # half the excess left


def test_sink_bondi_rate():
    """Bondi accretion matches the analytic rate on a uniform medium."""
    un = _units()
    spec = SinkSpec(enabled=True, accretion_scheme="bondi")
    n = 8
    dx = 10.0 / n
    u = _box(n=n, rho=2.0, p=0.5)
    sinks = SinkSet.empty(3)
    sinks.x = np.array([[5.0, 5.0, 5.0]])
    sinks.v = np.zeros((1, 3))
    sinks.m = np.array([3.0])
    sinks.tform = np.zeros(1)
    sinks.idp = np.array([1], dtype=np.int64)
    from ramses_tpu.units import factG_in_cgs
    g_code = factG_in_cgs * un.scale_d * un.scale_t ** 2
    cs2 = 1.4 * 0.5 / 2.0
    expected = 4 * np.pi * g_code ** 2 * 9.0 * 2.0 / cs2 ** 1.5
    dt = 1e-3
    m0 = sinks.m[0]
    u, sinks = accrete(u, sinks, spec, un, dx, dt, 1.4)
    assert np.isclose(sinks.m[0] - m0, expected * dt, rtol=1e-6)


def test_sink_merging():
    spec = SinkSpec(enabled=True, merging_cells=2.0)
    s = SinkSet.empty(2)
    s.x = np.array([[0.5, 0.5], [0.52, 0.5], [0.9, 0.9]])
    s.v = np.array([[1.0, 0.0], [0.0, 1.0], [0.0, 0.0]])
    s.m = np.array([2.0, 1.0, 5.0])
    s.tform = np.zeros(3)
    s.idp = np.arange(3, dtype=np.int64)
    s2 = merge_sinks(s, spec, dx=0.05)
    assert s2.n == 2
    i = np.argmin(s2.m)  # merged pair has mass 3
    assert np.isclose(s2.m[i], 3.0)
    assert np.allclose(s2.v[i], [2.0 / 3.0, 1.0 / 3.0])


def test_sink_drift():
    s = SinkSet.empty(2)
    s.x = np.array([[0.9, 0.5]])
    s.v = np.array([[0.3, 0.0]])
    s.m = np.array([1.0])
    s.tform = np.zeros(1)
    s.idp = np.array([1], dtype=np.int64)
    s = drift_kick(s, None, 0.1, 0.5, boxlen=1.0)
    assert np.isclose(s.x[0, 0], 0.05)  # periodic wrap


def test_restart_star_id_counter_and_headroom(tmp_path):
    """Restart bookkeeping for particle-creating runs: the star-id
    counter resumes past the restored ids (no idp collisions) and the
    restored set keeps free lanes (``npartmax`` headroom) so SF can
    continue (``pm/init_part.f90`` restart semantics)."""
    import jax
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string

    txt = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", "levelmin=4", "levelmax=4", "boxlen=1.0", "/",
        "&HYDRO_PARAMS", "courant_factor=0.5", "/",
        "&SF_PARAMS", "n_star=1e12", "t_star=1.0", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/"])
    p = params_from_string(txt, ndim=2)
    rng = np.random.default_rng(9)
    n = 17
    ps = ParticleSet.make(rng.uniform(0.1, 0.9, (n, 2)),
                          np.zeros((n, 2)), np.full(n, 1.0 / n),
                          idp=np.arange(5, 5 + n))
    sim = AmrSim(p, dtype=jnp.float64, particles=jax.device_put(ps))
    out = sim.dump(1, str(tmp_path))
    back = AmrSim.from_snapshot(p, out, dtype=jnp.float64)
    assert back._next_star_id == 5 + n
    assert int((~np.asarray(back.p.active)).sum()) > 0   # free lanes


def test_kinetic_feedback_wind():
    """f_w>0 kinetic winds: mass conserved (star ejecta + swept gas
    stay in the box), total injected energy == E_SN, net momentum
    unchanged for a star at rest in gas at rest (radial kicks cancel),
    and a radial outflow forms around the host cell."""
    from ramses_tpu.pm.star_formation import kinetic_feedback

    un = _units()
    spec = SfSpec(enabled=True, eta_sn=0.2, t_sne=10.0, f_w=5.0)
    n = 8
    dx = 1.0 / n
    u = _box(n=n, rho=1.0, ndim=3)
    p = ParticleSet.make(np.array([[0.5, 0.5, 0.5]]),
                         np.zeros((1, 3)), np.array([2.0]),
                         family=np.array([FAM_STAR], dtype=np.int8),
                         nmax=4)
    t_sne_code = 10.0 * 1e6 * yr2sec / un.scale_t
    m0 = u[0].sum() * dx ** 3 + 2.0
    e0 = u[4].sum() * dx ** 3
    mom0 = np.array([u[1 + d].sum() for d in range(3)]) * dx ** 3
    u2, p2 = kinetic_feedback(u.copy(), p, spec, un, dx,
                              2.0 * t_sne_code)
    mej = 0.2 * 2.0
    assert np.isclose(float(np.asarray(p2.m)[0]), 2.0 - mej)
    # mass conservation (gas + star)
    assert np.isclose(u2[0].sum() * dx ** 3 + float(np.asarray(p2.m)[0]),
                      m0, rtol=1e-12)
    # energy: the full SN budget arrives (kinetic shell + central
    # thermal share); the swept gas was cold and at rest
    esn_code = (1e51 / (10 * 1.9891e33)) / un.scale_v ** 2
    de = u2[4].sum() * dx ** 3 - e0
    assert np.isclose(de, mej * esn_code, rtol=1e-10)
    # momentum: radial kicks cancel for the symmetric bubble
    mom1 = np.array([u2[1 + d].sum() for d in range(3)]) * dx ** 3
    assert np.allclose(mom1, mom0, atol=1e-12)
    # a genuine outflow: neighbours carry momentum pointing away
    c = n // 2
    px_hi = u2[1][c + 1, c, c]
    px_lo = u2[1][c - 1, c, c]
    assert px_hi > 0 and px_lo < 0
    # once only
    u3, p3 = kinetic_feedback(u2.copy(), p2, spec, un, dx,
                              3.0 * t_sne_code)
    assert np.allclose(u3, u2)


def test_kinetic_feedback_amr_matches_budget():
    """The hierarchy wind pass conserves gas+star mass and injects the
    SN energy budget on a refined tree."""
    import jax
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string
    from ramses_tpu.pm import amr_physics as ap

    txt = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", "levelmin=4", "levelmax=5", "boxlen=1.0", "/",
        "&HYDRO_PARAMS", "courant_factor=0.5", "/",
        "&SF_PARAMS", "n_star=1e12", "t_star=1.0", "/",
        "&FEEDBACK_PARAMS", "eta_sn=0.2", "t_sne=10.0", "f_w=5.0", "/",
        "&REFINE_PARAMS", "x_refine=0,0,0,0.5", "y_refine=0,0,0,0.5",
        "r_refine=-1,-1,-1,0.25", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/"])
    p = params_from_string(txt, ndim=2)
    star = ParticleSet.make(np.array([[0.5, 0.5]]), np.zeros((1, 2)),
                            np.array([0.5]),
                            family=np.array([FAM_STAR], dtype=np.int8),
                            nmax=4)
    sim = AmrSim(p, dtype=jnp.float64, particles=jax.device_put(star))
    assert sim.sf_spec.f_w == 5.0
    assert sim.tree.noct(5) > 0
    m0 = sim.totals()[0] + float(jnp.sum(sim.p.m * sim.p.active))
    e0 = sim.totals()[3]
    t_sne_code = 10.0 * 1e6 * yr2sec / sim.units.scale_t
    sim.t = 2.0 * t_sne_code
    ap.kinetic_feedback_amr(sim)
    mej = 0.2 * 0.5
    m1 = sim.totals()[0] + float(jnp.sum(sim.p.m * sim.p.active))
    assert np.isclose(m1, m0, rtol=1e-12)
    esn_code = (1e51 / (10 * 1.9891e33)) / sim.units.scale_v ** 2
    assert np.isclose(sim.totals()[3] - e0, mej * esn_code, rtol=1e-9)


def test_agn_thermal_feedback():
    """agn=.true.: the sink keeps (1-eps_r) of the accreted mass and
    the host cell gains eps_c*eps_r*dM c^2 of thermal energy
    (Teyssier+11 quasar mode)."""
    from ramses_tpu.pm.sinks import C_CGS

    un = _units()
    spec = SinkSpec(enabled=True, n_sink=1e3,
                    accretion_scheme="threshold", c_acc=0.5,
                    agn=True, eps_r=0.1, eps_c=0.15)
    n = 8
    dx = 1.0 / n
    u = _box(n=n, rho=1.0)
    u[0][4, 4, 4] = 5e3 / un.scale_nH
    sinks = SinkSet.empty(3)
    u, sinks = create_sinks(u, sinks, spec, un, dx, 0.0, 1.4)
    assert sinks.n == 1
    u[0][4, 4, 4] = 2e3 / un.scale_nH
    m_s0 = sinks.m[0]
    e0 = u[4].sum() * dx ** 3
    mgas0 = u[0].sum() * dx ** 3
    u, sinks = accrete(u, sinks, spec, un, dx, 1.0, 1.4)
    dm = mgas0 - u[0].sum() * dx ** 3           # gas actually removed
    assert dm > 0
    assert np.isclose(sinks.m[0] - m_s0, 0.9 * dm, rtol=1e-12)
    de = u[4].sum() * dx ** 3 - e0
    c_code = C_CGS / un.scale_v
    assert np.isclose(de, 0.15 * 0.1 * dm * c_code ** 2, rtol=1e-10)


def test_sink_direct_force_binary():
    """direct_force: two sinks attract each other (N^2 pairwise with
    Plummer softening) — velocities turn toward the companion."""
    from ramses_tpu.pm.sinks import drift_kick

    un = _units()
    spec = SinkSpec(enabled=True, direct_force=True)
    s = SinkSet(x=np.array([[0.4, 0.5, 0.5], [0.6, 0.5, 0.5]]),
                v=np.zeros((2, 3)), m=np.array([1.0, 1.0]),
                tform=np.zeros(2), idp=np.array([1, 2]), next_id=3)
    s = drift_kick(s, None, 1.0 / 16, 1e-3, boxlen=1.0, spec=spec,
                   units=un)
    assert s.v[0, 0] > 0 and s.v[1, 0] < 0          # mutual attraction
    assert np.allclose(s.v[0], -s.v[1])             # Newton's third law
    # without the flag: no self-force
    s2 = SinkSet(x=np.array([[0.4, 0.5, 0.5], [0.6, 0.5, 0.5]]),
                 v=np.zeros((2, 3)), m=np.array([1.0, 1.0]),
                 tform=np.zeros(2), idp=np.array([1, 2]), next_id=3)
    s2 = drift_kick(s2, None, 1.0 / 16, 1e-3, boxlen=1.0,
                    spec=SinkSpec(enabled=True), units=un)
    assert np.allclose(s2.v, 0.0)


def test_kinetic_feedback_colocated_sne_conserve():
    """Two SNe in ONE host cell in the same step must debit the cell
    once for their combined draw — mass and energy budgets stay exact
    (the last-write-wins fancy-index hazard)."""
    from ramses_tpu.pm.star_formation import kinetic_feedback

    un = _units()
    spec = SfSpec(enabled=True, eta_sn=0.2, t_sne=10.0, f_w=50.0)
    n = 8
    dx = 1.0 / n
    u = _box(n=n, rho=1.0, ndim=3)
    x0 = [0.5 + 0.2 * dx, 0.5 + 0.3 * dx]
    p = ParticleSet.make(
        np.array([[x0[0], 0.5, 0.5], [x0[1], 0.5, 0.5]]),
        np.zeros((2, 3)), np.array([2.0, 3.0]),
        family=np.array([FAM_STAR, FAM_STAR], dtype=np.int8), nmax=4)
    t_sne_code = 10.0 * 1e6 * yr2sec / un.scale_t
    m0 = u[0].sum() * dx ** 3 + 5.0
    e0 = u[4].sum() * dx ** 3
    u2, p2 = kinetic_feedback(u.copy(), p, spec, un, dx,
                              2.0 * t_sne_code)
    mej = 0.2 * 5.0
    m1 = u2[0].sum() * dx ** 3 + float(np.asarray(p2.m).sum())
    assert np.isclose(m1, m0, rtol=1e-12)
    assert (u2[0] > 0).all()                 # over-debit would go < 0
    esn_code = (1e51 / (10 * 1.9891e33)) / un.scale_v ** 2
    de = u2[4].sum() * dx ** 3 - e0
    assert np.isclose(de, mej * esn_code, rtol=1e-9)


def test_sink_direct_force_minimum_image():
    """A binary straddling the periodic face attracts ACROSS it."""
    from ramses_tpu.pm.sinks import drift_kick

    un = _units()
    spec = SinkSpec(enabled=True, direct_force=True)
    s = SinkSet(x=np.array([[0.05, 0.5, 0.5], [0.95, 0.5, 0.5]]),
                v=np.zeros((2, 3)), m=np.array([1.0, 1.0]),
                tform=np.zeros(2), idp=np.array([1, 2]), next_id=3)
    s = drift_kick(s, None, 1.0 / 16, 1e-3, boxlen=1.0, spec=spec,
                   units=un)
    # nearest image of sink 1 is across x=0: sink 0 accelerates in -x
    assert s.v[0, 0] < 0 and s.v[1, 0] > 0
    assert np.allclose(s.v[0], -s.v[1])


def test_kinetic_feedback_wall_no_wraparound():
    """A SN beside OUTFLOW walls must not inject through the wall onto
    the far side of the box (the periodic image); out-of-box bubble
    shares fold into the host cell and the budget stays exact."""
    from ramses_tpu.grid.boundary import OUTFLOW, BoundarySpec, FaceBC
    from ramses_tpu.pm.star_formation import kinetic_feedback

    un = _units()
    spec = SfSpec(enabled=True, eta_sn=0.2, t_sne=10.0, f_w=5.0)
    n = 8
    dx = 1.0 / n
    u = _box(n=n, rho=1.0, ndim=3)
    # star in the corner cell: most bubble cells fall outside the box
    x0 = 0.5 * dx
    p = ParticleSet.make(np.array([[x0, x0, x0]]), np.zeros((1, 3)),
                         np.array([2.0]),
                         family=np.array([FAM_STAR], dtype=np.int8),
                         nmax=4)
    ob = FaceBC(OUTFLOW)
    bc = BoundarySpec(faces=((ob, ob),) * 3)
    t_sne_code = 10.0 * 1e6 * yr2sec / un.scale_t
    m0 = u[0].sum() * dx ** 3 + 2.0
    e0 = u[4].sum() * dx ** 3
    u2, p2 = kinetic_feedback(u.copy(), p, spec, un, dx,
                              2.0 * t_sne_code, bc=bc)
    # the wrap targets (far faces) are untouched
    assert np.allclose(u2[0][-1, :, :], u[0][-1, :, :])
    assert np.allclose(u2[0][:, -1, :], u[0][:, -1, :])
    assert np.allclose(u2[0][:, :, -1], u[0][:, :, -1])
    # exact budgets regardless of the folding
    mej = 0.2 * 2.0
    assert np.isclose(u2[0].sum() * dx ** 3 + float(np.asarray(p2.m)[0]),
                      m0, rtol=1e-12)
    esn_code = (1e51 / (10 * 1.9891e33)) / un.scale_v ** 2
    assert np.isclose(u2[4].sum() * dx ** 3 - e0, mej * esn_code,
                      rtol=1e-10)


def test_kinetic_feedback_amr_refined_bubble_no_leak():
    """A star at level l beside a refined region: bubble targets that
    are COVERED by finer cells fold into the host cell — depositing
    into a covered cell would be erased by the next restriction sweep
    (leaf totals lose the share).  The leaf-cell budget must be exact."""
    import jax
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string
    from ramses_tpu.pm import amr_physics as ap

    txt = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", "levelmin=4", "levelmax=5", "boxlen=1.0", "/",
        "&HYDRO_PARAMS", "courant_factor=0.5", "/",
        "&SF_PARAMS", "n_star=1e12", "t_star=1.0", "/",
        "&FEEDBACK_PARAMS", "eta_sn=0.2", "t_sne=10.0", "f_w=5.0", "/",
        "&REFINE_PARAMS", "x_refine=0,0,0,0.5", "y_refine=0,0,0,0.5",
        "r_refine=-1,-1,-1,0.25", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/"])
    p = params_from_string(txt, ndim=2)
    # place the star in a level-4 LEAF cell whose +x neighbour is
    # refined (covered) at level 4 — found programmatically since
    # gradedness smoothing widens the refined disc
    star = ParticleSet.make(np.array([[0.03, 0.03]]), np.zeros((1, 2)),
                            np.array([0.5]),
                            family=np.array([FAM_STAR], dtype=np.int8),
                            nmax=4)
    sim = AmrSim(p, dtype=jnp.float64, particles=jax.device_put(star))
    from dataclasses import replace as dreplace

    from ramses_tpu.pm.amr_physics import ngp_rows
    from ramses_tpu.pm.amr_pm import assign_levels
    ref = np.asarray(sim.tree.refined_mask(4))
    cen = sim.tree.cell_centers(4, sim.boxlen)
    nb = ngp_rows(sim.tree, cen + np.array([sim.dx(4), 0.0]), 4,
                  sim.boxlen, sim.bc_kinds)
    cand = np.nonzero(~ref & (nb >= 0) & ref[np.maximum(nb, 0)])[0]
    assert len(cand), "no leaf cell borders the refined region"
    host = cen[cand[0]]
    assert assign_levels(sim.tree, host[None], sim.boxlen)[0] == 4
    px = np.array(sim.p.x)
    px[0] = host
    sim.p = dreplace(sim.p, x=jnp.asarray(px))
    m0 = sim.totals()[0] + float(jnp.sum(sim.p.m * sim.p.active))
    e0 = sim.totals()[3]
    t_sne_code = 10.0 * 1e6 * yr2sec / sim.units.scale_t
    sim.t = 2.0 * t_sne_code
    ap.kinetic_feedback_amr(sim)
    mej = 0.2 * 0.5
    m1 = sim.totals()[0] + float(jnp.sum(sim.p.m * sim.p.active))
    assert np.isclose(m1, m0, rtol=1e-12)
    esn_code = (1e51 / (10 * 1.9891e33)) / sim.units.scale_v ** 2
    assert np.isclose(sim.totals()[3] - e0, mej * esn_code, rtol=1e-9)
