"""SRHD sweep kernels with the hydro ``muscl.unsplit`` interface.

The AMR level machinery (``amr/kernels.py``) is physics-parametric: it
needs ``unsplit`` (per-direction low-face fluxes already scaled by
dt/dx), ``cell_dt`` and ``grad_flags`` with the hydro signatures, keyed
off the static cfg.  This module provides the special-relativistic set —
the rhd solver's own ``umuscl.f90``/``godunov_utils.f90`` re-expressed
as whole-array ops (same pipeline as ``rhd/uniform.py``: primitive TVD
slopes, conservative Hancock half-step, relativistic HLL), valid on
ghost-padded grids AND on the AMR 6^d oct-stencil batches (via
``cfg.trailing_batch``, see ``hydro/muscl._axis``).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ramses_tpu.hydro import muscl as hmuscl
from ramses_tpu.rhd import core
from ramses_tpu.rhd.core import RhdStatic


def _hll(ql, qr, d: int, cfg: RhdStatic):
    """Relativistic HLL flux (Mignone-Bodo wave-speed bounds)."""
    lm_l, lp_l = core.wave_speeds(ql, d, cfg)
    lm_r, lp_r = core.wave_speeds(qr, d, cfg)
    SL = jnp.minimum(jnp.minimum(lm_l, lm_r), 0.0)
    SR = jnp.maximum(jnp.maximum(lp_l, lp_r), 0.0)
    fl = core.flux_along(ql, d, cfg)
    fr = core.flux_along(qr, d, cfg)
    ul = core.prim_to_cons(ql, cfg)
    ur = core.prim_to_cons(qr, cfg)
    den = SR - SL + 1e-30
    return (SR * fl - SL * fr + SL * SR * (ur - ul)) / den


def unsplit(u, grav, dt, dx: Sequence[float], cfg: RhdStatic):
    """One unsplit SRHD MUSCL-Hancock step on a (ghost-padded) array.

    Matches ``hydro/muscl.unsplit``: returns (flux, tmp) with
    ``flux[d]`` the Godunov flux at the LOW face of each cell along
    direction d, pre-scaled by dt/dx — the conservative update is then
    ``u += flux[d] - roll(flux[d], -1)`` per direction.  ``grav`` is
    ignored (RHD-AMR runs without self-gravity).  ``tmp`` is None (no
    dual-energy machinery in the SRHD solver).
    """
    nd = cfg.ndim
    q = core.cons_to_prim(u, cfg)
    dq = hmuscl.uslope(q, cfg)                       # [ndim, nvar, ...]

    # conservative Hancock predictor from the face-extrapolated fluxes
    du_half = jnp.zeros_like(u)
    face = []
    for d in range(nd):
        q_hi = q + 0.5 * dq[d]
        q_lo = q - 0.5 * dq[d]
        f_hi = core.flux_along(q_hi, d, cfg)
        f_lo = core.flux_along(q_lo, d, cfg)
        du_half = du_half - (0.5 * dt / dx[d]) * (f_hi - f_lo)
        face.append((q_lo, q_hi))

    fluxes = []
    for d in range(nd):
        ax = hmuscl._axis(cfg, d, u)
        q_lo, q_hi = face[d]
        ul_c = core.prim_to_cons(q_hi, cfg) + du_half
        ur_c = core.prim_to_cons(q_lo, cfg) + du_half
        ql = core.cons_to_prim(jnp.roll(ul_c, 1, axis=ax), cfg)
        qr = core.cons_to_prim(ur_c, cfg)
        fluxes.append(_hll(ql, qr, d, cfg) * (dt / dx[d]))
    return jnp.stack(fluxes), None


def cell_dt(u, grav, dx: float, cfg: RhdStatic):
    """Per-cell Courant dt from the relativistic characteristic speeds
    (the rhd ``cmpdt``; wave speeds are bounded by c=1 so
    dt >= courant_factor*dx)."""
    q = core.cons_to_prim(u, cfg)
    ws = jnp.zeros(u.shape[1:], u.dtype)
    for d in range(cfg.ndim):
        lm, lp = core.wave_speeds(q, d, cfg)
        ws = ws + jnp.maximum(jnp.abs(lm), jnp.abs(lp))
    return cfg.courant_factor * dx / jnp.maximum(ws, 1e-10)


def grad_flags(uloc, err_grad, floors, spatial0: int, cfg: RhdStatic):
    """Refinement criteria: relative two-sided gradients of the rest-mass
    density, pressure, and Lorentz factor (the rhd ``hydro_flag`` with
    the Lorentz-gradient criterion of ``rhd/uniform.lorentz_refine_flags``
    taking the role of the Mach-normalized velocity test)."""
    nd = cfg.ndim
    q = core.cons_to_prim(uloc, cfg)
    rho = q[0]
    p = q[4]
    lor = core.lorentz(q)
    egd, egu, egp = err_grad
    fld, flu, flp = floors
    ok = jnp.zeros_like(rho, dtype=bool)

    def two_sided(f, floor):
        from ramses_tpu.amr.kernels import two_sided_rel_err
        return two_sided_rel_err(f, floor, nd, spatial0)

    if egd >= 0.0:
        ok = ok | (two_sided(rho, fld) > egd)
    if egp >= 0.0:
        ok = ok | (two_sided(p, flp) > egp)
    if egu >= 0.0:
        # W >= 1 always, so the relative two-sided difference is already
        # well-conditioned; flu guards the ultra-cold static case
        ok = ok | (two_sided(lor, max(flu, 1e-10)) > egu)
    return ok
