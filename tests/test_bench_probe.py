"""bench.py pre-flight tunnel probe: a dead device tunnel must read as
a single top-level ``{"tunnel": {"ok": false}}`` in BOTH the final
bench JSON and BENCH_PARTIAL.json — not as four identical per-sub
timeout errors.  The parent process never imports jax, so these tests
exercise the real subprocess plumbing cheaply."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench

pytestmark = pytest.mark.smoke


def test_tunnel_probe_failure(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_CODE", "import sys; sys.exit(3)")
    r = bench.tunnel_probe(timeout_s=30.0)
    assert r["ok"] is False
    assert "rc=3" in r["error"]


def test_tunnel_probe_hang_hits_hard_timeout(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_CODE",
                        "import time; time.sleep(600)")
    r = bench.tunnel_probe(timeout_s=2.0)
    assert r["ok"] is False
    assert "timed out" in r["error"]


def test_tunnel_probe_marker_parse(monkeypatch):
    code = ('import json\n'
            'print("noise")\n'
            'print("##TUNNEL##" + json.dumps('
            '{"ok": True, "ndev": 8, "platform": "cpu",'
            ' "elapsed_s": 0.1}))\n')
    monkeypatch.setattr(bench, "_PROBE_CODE", code)
    r = bench.tunnel_probe(timeout_s=30.0)
    assert r == {"ok": True, "ndev": 8, "platform": "cpu",
                 "elapsed_s": 0.1}


def test_dead_tunnel_tops_both_jsons(monkeypatch, tmp_path, capsys):
    """main() with a dead tunnel and stubbed subs: the top-level
    ``tunnel`` key lands in stdout JSON and in BENCH_PARTIAL.json."""
    dead = {"ok": False, "error": "probe timed out after 60s "
                                  "(device tunnel dead or backend hung)"}
    monkeypatch.setattr(bench, "tunnel_probe", lambda *a, **k: dead)
    monkeypatch.setattr(
        bench, "run_sub",
        lambda name, deadline, weight=None, reserve=0.0:
            {"error": "sub-bench timed out after 45s", "attempt": 2})
    partial = tmp_path / "BENCH_PARTIAL.json"
    monkeypatch.setenv("BENCH_PARTIAL_PATH", str(partial))
    monkeypatch.setenv("BENCH_TOTAL_BUDGET", "900")
    monkeypatch.delenv("BENCH_ONLY", raising=False)
    bench.main()
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["tunnel"] == dead
    assert out["value"] is None
    part = json.loads(partial.read_text())
    assert part["tunnel"] == dead
    # the default protocol runs DEFAULT_SUBS; profile_amr is opt-in
    # (BENCH_ONLY=profile_amr) or auto-escalated on an amr hang
    assert set(part["sub"]) == set(bench.DEFAULT_SUBS)
