"""Namelist parser + config tests."""

from ramses_tpu.config import params_from_string
from ramses_tpu.nml import parse_nml

SOD = """
This is the parameter file for Sod's shock tube test.

&RUN_PARAMS
hydro=.true.
nsubcycle=3*1,2
/

&AMR_PARAMS
levelmin=3
levelmax=10
ngridmax=2000
boxlen=1.0
/

&BOUNDARY_PARAMS
nboundary=2
ibound_min=-1,+1
ibound_max=-1,+1
bound_type= 1, 1
/

&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='square'
x_center=0.25,0.75
length_x=0.5,0.5
d_region=1.0,0.125
u_region=0.0,0.0
p_region=1.0,0.1
/

&OUTPUT_PARAMS
noutput=1
tout=0.245
/

&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
slope_type=2
riemann='hllc'
/
"""


import pytest

pytestmark = pytest.mark.smoke

def test_parse_groups():
    g = parse_nml(SOD)
    assert g["run_params"]["hydro"] is True
    assert g["run_params"]["nsubcycle"] == [1, 1, 1, 2]
    assert g["amr_params"]["levelmin"] == 3
    assert g["hydro_params"]["riemann"] == "hllc"
    assert g["boundary_params"]["ibound_min"] == [-1, 1]
    assert g["init_params"]["region_type"] == {1: ["square"], 2: ["square"]}


def test_params_object():
    p = params_from_string(SOD, ndim=1)
    assert p.run.hydro and p.amr.levelmin == 3
    assert p.hydro.riemann == "hllc" and p.hydro.slope_type == 2
    assert p.init.nregion == 2
    assert p.init.region_type == ["square", "square"]
    assert p.init.d_region == [1.0, 0.125]
    assert p.init.length_y == [1e10, 1e10]  # densified default
    assert p.boundary.bound_type == [1, 1]
    assert p.output.tout == [0.245]
    assert p.run.nsubcycle[:5] == [1, 1, 1, 2, 2]
    assert p.nvar == 3  # 1D: rho, mom, E


def test_fortran_literals():
    g = parse_nml("&X\na=1d-3\nb=.false.\nc=2*0.5\nd='hi'\n/\n")
    x = g["x"]
    assert x["a"] == 1e-3 and x["b"] is False
    assert x["c"] == [0.5, 0.5] and x["d"] == "hi"


def test_continuation_after_scalar_first_line():
    """A value list split across lines where the first line holds a single
    value must append, not overwrite (regression: first value was lost)."""
    g = parse_nml("&OUTPUT_PARAMS\ntout=0.1,\n0.2,0.3\n/")
    assert g["output_params"]["tout"] == [0.1, 0.2, 0.3]


def test_indexed_output_times_densified():
    """tout(1)=... indexed assignment must produce a flat float list the
    driver can iterate (regression: left as {index: values} dict)."""
    p = params_from_string("&OUTPUT_PARAMS\nnoutput=2\ntout(1)=0.1\n"
                           "tout(2)=0.245\n/", ndim=1)
    assert p.output.tout == [0.1, 0.245]
    assert p.output.noutput == 2


def test_tend_delta_tout_ladder():
    """tend/delta_tout style outputs synthesise the tout ladder."""
    p = params_from_string("&OUTPUT_PARAMS\ntend=0.5\ndelta_tout=0.2\n/",
                           ndim=1)
    assert p.output.tout == [0.2, 0.4, 0.5]
    p = params_from_string("&OUTPUT_PARAMS\ntend=0.5\n/", ndim=1)
    assert p.output.tout == [0.5]
