"""Hilbert space-filling-curve keys for domain decomposition.

The role of ``amr/hilbert.f90:5-196`` (P1 of SURVEY.md §2.12): order octs
along a locality-preserving curve so contiguous key ranges become compact
spatial domains (the shard boundaries of the multi-chip mesh).  Uses
Skilling's transpose formulation (AIP Conf. Proc. 707, 381, 2004) —
int64-clean, no ``real*16 QUADHILBERT`` workaround, supporting 21
bits/dim in 3D vs the reference's float-key cap of 19 levels.

Native C++ fast path (``ramses_tpu.native``), vectorized numpy fallback.
"""

from __future__ import annotations

import numpy as np

from ramses_tpu import native


def hilbert_key(og: np.ndarray, ndim: int, nbits: int) -> np.ndarray:
    """uint64 Hilbert indices of integer coords ``og [n, ndim]``,
    coordinates in [0, 2^nbits)."""
    og = np.asarray(og, dtype=np.int64).reshape(-1, ndim)
    if ndim == 1:
        return og[:, 0].astype(np.uint64)
    nat = native.hilbert_encode(og, ndim, nbits)
    if nat is not None:
        return nat
    return _hilbert_numpy(og, ndim, nbits)


def _hilbert_numpy(og: np.ndarray, ndim: int, nbits: int) -> np.ndarray:
    """Vectorized Skilling AxesToTranspose + bit interleave."""
    X = [og[:, d].astype(np.uint64).copy() for d in range(ndim)]
    M = np.uint64(1 << (nbits - 1))
    Q = int(M)
    while Q > 1:
        P = np.uint64(Q - 1)
        Qu = np.uint64(Q)
        for i in range(ndim):
            hi = (X[i] & Qu) != 0
            # branch 1: X[0] ^= P where bit set
            X[0] = np.where(hi, X[0] ^ P, X[0])
            # branch 2: swap low bits of X[0], X[i]
            t = np.where(hi, np.uint64(0), (X[0] ^ X[i]) & P)
            X[0] ^= t
            X[i] ^= t
        Q >>= 1
    for i in range(1, ndim):
        X[i] ^= X[i - 1]
    t = np.zeros_like(X[0])
    Q = int(M)
    while Q > 1:
        Qu = np.uint64(Q)
        t = np.where((X[ndim - 1] & Qu) != 0, t ^ np.uint64(Q - 1), t)
        Q >>= 1
    for i in range(ndim):
        X[i] ^= t
    # interleave transpose bits
    key = np.zeros(len(og), dtype=np.uint64)
    for j in range(nbits - 1, -1, -1):
        for i in range(ndim):
            key = (key << np.uint64(1)) | ((X[i] >> np.uint64(j))
                                           & np.uint64(1))
    return key


def hilbert_order(og: np.ndarray, ndim: int, nbits: int) -> np.ndarray:
    """argsort of the Hilbert keys — the domain-decomposition order."""
    return np.argsort(hilbert_key(og, ndim, nbits), kind="stable")
