"""Multi-device uniform-grid simulation (global-view SPMD).

Design (SURVEY.md §7 stage 6): the state array stays a single global-view
jax.Array sharded over the device mesh; the unchanged solver kernels run
under jit and XLA's SPMD partitioner inserts the halo collective-permutes
(P2), min-reductions for CFL (P7), and keeps everything on ICI.  This
replaces the reference's hand-written message schedule
(``amr/virtual_boundaries.f90:373-533``) with compiler-scheduled
communication — the idiomatic TPU answer to two-sided MPI halos.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ramses_tpu.config import Params
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import run_steps
from ramses_tpu.parallel.mesh import make_mesh, spatial_sharding
from ramses_tpu.pm.coupling import run_steps_pm


class ShardedSim:
    """Uniform-grid simulation with the state sharded over a device mesh."""

    def __init__(self, params: Params,
                 devices: Optional[Sequence[jax.Device]] = None,
                 dtype=jnp.float32):
        self.inner = Simulation(params, dtype=dtype)
        self.mesh = make_mesh(params.ndim, devices)
        self.sharding = spatial_sharding(self.mesh, n_leading=1)
        self.u = jax.device_put(self.inner.state.u, self.sharding)
        self.inner.state.u = None  # drop the unsharded copy (memory)
        # particles: data-parallel over lanes (flattened mesh); deposits
        # into the spatially-sharded grid become partitioned scatters
        self.p = None
        if self.inner.pspec.enabled and self.inner.state.p is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            flat = Mesh(np.asarray(self.mesh.devices).reshape(-1),
                        ("lane",))
            lane = NamedSharding(flat, PartitionSpec("lane"))
            lane2 = NamedSharding(flat, PartitionSpec("lane", None))
            rep = NamedSharding(flat, PartitionSpec())
            import dataclasses as _dc
            p0 = self.inner.state.p
            ndev = flat.devices.size

            def put(a):
                if a is None:
                    return None
                if a.ndim >= 1 and a.shape[0] % ndev == 0:
                    return jax.device_put(
                        a, lane2 if a.ndim > 1 else lane)
                return jax.device_put(a, rep)

            self.p = _dc.replace(
                p0, **{f.name: put(getattr(p0, f.name))
                       for f in _dc.fields(p0)})
            self.inner.state.p = None
        self.gspec = self.inner.gspec
        if self.gspec.enabled and self.gspec.solver == "fft":
            # the spectral solve is global (all-to-all) and XLA's CPU FFT
            # thunk rejects partitioned layouts; the CG stencil solver
            # partitions cleanly over the mesh (halo permutes only)
            import dataclasses as _dc
            self.gspec = _dc.replace(self.gspec, solver="cg")
        self.pspec = self.inner.pspec
        self.cosmo = self.inner.cosmo
        self.f = (jax.device_put(self.inner.state.f, self.sharding)
                  if self.inner.state.f is not None else None)
        self.inner.state.f = None  # likewise
        self.t = float(self.inner.state.t)
        self.dt_old = 0.0
        self.nstep = 0

    @property
    def grid(self):
        return self.inner.grid

    def run(self, nsteps: int, tend: float = 1e30):
        tdtype = (jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
        t0 = jnp.asarray(self.t, tdtype)
        t1 = jnp.asarray(tend, tdtype)
        if (self.gspec.enabled or self.cosmo is not None
                or self.pspec.enabled):
            u, p, f, t, dt_old, ndone = run_steps_pm(
                self.grid, self.gspec, self.pspec, self.u, self.p, self.f,
                t0, t1, jnp.asarray(self.dt_old, tdtype), nsteps,
                cosmo=self.cosmo)
            self.f, self.p, self.dt_old = f, p, float(dt_old)
        else:
            u, t, ndone = run_steps(self.grid, self.u, t0, t1, nsteps)
        u.block_until_ready()
        self.u, self.t = u, float(t)
        self.nstep += int(ndone)
        return self
