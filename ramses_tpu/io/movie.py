"""On-the-fly movie frames: projections and slices.

The movie engine (``amr/movie.f90:5-1169``): per-output 2D maps of
density/pressure/velocity etc. along a camera axis, written as simple
binary frame files.  Maps are device reductions (sum/mean/max along the
projection axis — a ``segment_mean`` in the AMR case); frame files carry
the reference's layout: time + bounds header, [nw, nh], float32 data.
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ramses_tpu.io import fortran as frt


def project(field, axis: int, kind: str = "mean", weights=None):
    """2D map from a dense 3D (or 2D) field: mean|sum|max|slice along
    ``axis``; mass-weighted mean when ``weights`` given."""
    field = jnp.asarray(field)
    if field.ndim == 2:
        return field
    if kind == "slice":
        idx = [slice(None)] * field.ndim
        idx[axis] = field.shape[axis] // 2
        return field[tuple(idx)]
    if kind == "sum":
        return jnp.sum(field, axis=axis)
    if kind == "max":
        return jnp.max(field, axis=axis)
    if weights is not None:
        w = jnp.asarray(weights)
        return (jnp.sum(field * w, axis=axis)
                / jnp.maximum(jnp.sum(w, axis=axis), 1e-300))
    return jnp.mean(field, axis=axis)


def write_frame(path: str, data, t: float = 0.0,
                bounds: Sequence[float] = (0, 1, 0, 1)) -> None:
    """Binary frame file (``output_frame`` map layout): record [t, xmin,
    xmax, ymin, ymax], record [nw, nh], record float32 data."""
    arr = np.asarray(data, dtype=np.float32)
    with open(path, "wb") as f:
        frt.write_record(f, np.asarray([t, *bounds], dtype=np.float64))
        frt.write_record(f, np.asarray(arr.shape[::-1], dtype=np.int32))
        frt.write_record(f, arr.T.ravel())


def read_frame(path: str):
    with open(path, "rb") as f:
        head = frt.read_reals(f)
        nw, nh = frt.read_ints(f)
        data = frt.read_array(f, np.float32).reshape(nw, nh).T
    return dict(t=head[0], bounds=tuple(head[1:5]), data=data)


class MovieWriter:
    """Camera config + frame emission (the &MOVIE_PARAMS NMOV cameras)."""

    def __init__(self, outdir: str, axis: int = 2, kind: str = "mean",
                 fields: Sequence[str] = ("density",)):
        self.outdir = outdir
        self.axis = axis
        self.kind = kind
        self.fields = list(fields)
        self.iframe = 0
        os.makedirs(outdir, exist_ok=True)

    def emit(self, sim) -> list:
        """Write one frame set from a uniform Simulation-like object."""
        u = np.asarray(sim.state.u if hasattr(sim, "state") else sim.u)
        ndim = u.ndim - 1
        cfg = sim.cfg
        paths = []
        for name in self.fields:
            if name == "density":
                field = u[0]
            elif name.startswith("velocity_"):
                d = "xyz".index(name[-1])
                field = u[1 + d] / np.maximum(u[0], 1e-300)
            elif name == "pressure":
                ek = sum(u[1 + d] ** 2 for d in range(ndim)) \
                    / (2 * np.maximum(u[0], 1e-300))
                field = (cfg.gamma - 1.0) * (u[1 + ndim] - ek)
            else:
                raise ValueError(f"unknown movie field {name!r}")
            m = project(field, self.axis if ndim == 3 else 0,
                        self.kind, weights=u[0]
                        if self.kind == "mean" else None)
            path = os.path.join(
                self.outdir, f"{name}_{self.iframe:05d}.map")
            t = float(sim.state.t if hasattr(sim, "state") else sim.t)
            write_frame(path, np.asarray(m), t=t)
            paths.append(path)
        self.iframe += 1
        return paths
