"""Run-telemetry subsystem: one recorder, three sinks.

See :mod:`ramses_tpu.telemetry.recorder` for the design; drivers only
need :func:`make_telemetry` (returns the shared no-op :data:`NULL`
when &OUTPUT_PARAMS leaves telemetry off — the zero-overhead-off
contract) and the :mod:`~ramses_tpu.telemetry.screen` formatting.
"""

from ramses_tpu.telemetry import hlo                       # noqa: F401
from ramses_tpu.telemetry.recorder import (                # noqa: F401
    NULL,
    REQUIRED_STEP_KEYS,
    SCHEMA_VERSION,
    NullTelemetry,
    Telemetry,
    TelemetrySpec,
    cell_updates_per_step,
    compile_count,
    make_telemetry,
    mesh_census,
    sim_run_info,
)
