"""Per-level Poisson solve on the AMR hierarchy.

The ``multigrid_fine``/``phi_fine_cg`` capability (SURVEY.md §3.3):
levels are solved coarse→fine with a one-way interface — each level's
solve sees Dirichlet boundary values interpolated from the coarser φ
(``make_fine_bc_rhs``), exactly the reference's masked level solve.  The
base level is complete, so its solve is the exact FFT inversion; finer
levels run preconditioned-free CG (the reference's own fallback,
``amr/amr_step.f90:250-258``) with matvec = one gather over the
face-neighbour index map.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def _ext(phi, ghosts):
    zero = jnp.zeros((1,), phi.dtype)
    return jnp.concatenate([phi, ghosts, zero])


def laplacian(phi, ghosts, nb, dx, valid, ndim: int):
    """7-point Laplacian over the face-neighbour map; zero on pad rows."""
    ext = _ext(phi, ghosts)
    s = jnp.zeros_like(phi)
    for d in range(ndim):
        s = s + ext[nb[:, d, 0]] + ext[nb[:, d, 1]]
    lap = (s - 2.0 * ndim * phi) / dx ** 2
    return jnp.where(valid, lap, 0.0)


@partial(jax.jit, static_argnames=("ndim", "iters"))
def cg_level(rhs, ghosts, nb, dx, valid, ndim: int, iters: int = 200,
             phi0=None):
    """CG solve of Δφ = rhs with fixed Dirichlet ghosts.

    The affine split: A(φ) ≡ lap(φ, 0); b ≡ rhs − lap(0, ghosts).  A is
    symmetric negative definite on the masked cells; CG runs on −A.
    """
    zero_g = jnp.zeros_like(ghosts)
    b = jnp.where(valid,
                  rhs - laplacian(jnp.zeros_like(rhs), ghosts, nb, dx,
                                  valid, ndim), 0.0)

    def A(x):
        return -laplacian(x, zero_g, nb, dx, valid, ndim)

    x = (phi0 if phi0 is not None else jnp.zeros_like(rhs))
    r = jnp.where(valid, -b - A(x), 0.0)
    p = r
    rs = jnp.sum(r * r)

    def body(i, state):
        x, r, p, rs = state
        Ap = A(p)
        denom = jnp.sum(p * Ap)
        alpha = jnp.where(denom != 0.0, rs / denom, 0.0)
        x = x + alpha * p
        r = r - alpha * Ap
        rs_new = jnp.sum(r * r)
        beta = jnp.where(rs != 0.0, rs_new / rs, 0.0)
        p = r + beta * p
        return x, r, p, rs_new

    x, r, p, rs = jax.lax.fori_loop(0, iters, body, (x, r, p, rs))
    return jnp.where(valid, x, 0.0)


@partial(jax.jit, static_argnames=("ndim",))
def grad_phi(phi, ghosts, nb, dx, valid, ndim: int):
    """Central-difference force f = −∇φ, [ncell_pad, ndim]
    (``force_fine``'s 5-point gradient)."""
    ext = _ext(phi, ghosts)
    comps = []
    for d in range(ndim):
        g = -(ext[nb[:, d, 1]] - ext[nb[:, d, 0]]) / (2.0 * dx)
        comps.append(jnp.where(valid, g, 0.0))
    return jnp.stack(comps, axis=1)


@partial(jax.jit, static_argnames=("ndim",))
def grad_dense(phi_dense, dx, ndim: int):
    """f = −∇φ on a dense periodic grid by central differences; returns
    raveled rows [ncell, ndim] (the complete-level companion of
    :func:`grad_phi`)."""
    comps = [-(jnp.roll(phi_dense, -1, axis=d)
               - jnp.roll(phi_dense, 1, axis=d)) / (2.0 * dx)
             for d in range(ndim)]
    return jnp.stack(comps, axis=-1).reshape(-1, ndim)


@partial(jax.jit, static_argnames=("ndim",))
def kick_flat(u, f, dteff, ndim: int, smallr: float):
    """Gravity momentum kick on flat cells [ncell, nvar] at fixed
    internal energy (``synchro_hydro_fine``)."""
    r = jnp.maximum(u[:, 0], smallr)
    ek_old = sum(0.5 * u[:, 1 + d] ** 2 for d in range(ndim)) / r
    mom = [u[:, 1 + d] + r * f[:, d] * dteff for d in range(ndim)]
    ek_new = sum(0.5 * m * m for m in mom) / r
    e = u[:, 1 + ndim] - ek_old + ek_new
    cols = [u[:, 0:1]] + [m[:, None] for m in mom] + [e[:, None]]
    if u.shape[1] > ndim + 2:
        cols.append(u[:, ndim + 2:])
    return jnp.concatenate(cols, axis=1)
