"""Static (trace-time) hydro solver configuration.

The reference bakes these into the binary via cpp defines and module
parameters (``bin/Makefile:7-45``, ``hydro/hydro_parameters.f90:75-90``).
Here they are a frozen, hashable dataclass captured as a static argument of
every jitted kernel, so XLA specializes exactly as the Fortran compiler did.

State vector layout (channel-first, conservative):
    ``u[0]`` = density rho
    ``u[1 : 1+ndim]`` = momentum rho*v
    ``u[1+ndim]`` = total energy E
    ``u[2+ndim : 2+ndim+nener]`` = non-thermal energies
    ``u[2+ndim+nener : nvar]`` = passive scalars (rho*X)
Primitive layout is identical with velocity/pressure/specific scalars.
This matches the reference's per-cell ordering (``hydro/condinit.f90:17-22``)
transposed to channel-first so the innermost (spatial) axes map onto TPU
vector lanes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from ramses_tpu.config import Params


@dataclass(frozen=True)
class HydroStatic:
    ndim: int = 3
    nener: int = 0
    npassive: int = 0
    gamma: float = 1.4
    gamma_rad: Tuple[float, ...] = ()
    smallr: float = 1e-10
    smallc: float = 1e-10
    slope_type: int = 1
    slope_theta: float = 1.5
    scheme: str = "muscl"
    riemann: str = "llf"
    niter_riemann: int = 10
    courant_factor: float = 0.5
    difmag: float = 0.0
    pressure_fix: bool = False
    beta_fix: float = 0.0       # truncation-error threshold coefficient
    # Array-layout switch: spatial axes 1..ndim with a trailing batch axis
    # ([nvar, *spatial, batch]) instead of trailing spatial.  The AMR oct
    # batches use this so the (large) oct axis is minor-most — TPU tiles
    # the two minor dims to (8, 128), and a [..., 6, 6] minor layout would
    # waste ~28x HBM in padding.
    trailing_batch: bool = False

    @property
    def nvar(self) -> int:
        return self.ndim + 2 + self.nener + self.npassive

    @property
    def ienergy(self) -> int:
        """Index of total energy / pressure in the state vector."""
        return self.ndim + 1

    @property
    def smallp(self) -> float:
        return self.smallc ** 2 / self.gamma

    @property
    def smalle(self) -> float:
        return self.smallc ** 2 / self.gamma / (self.gamma - 1.0)

    @classmethod
    def from_params(cls, p: Params) -> "HydroStatic":
        h = p.hydro
        # gamma_rad: namelist values (hydro/read_hydro_params.f90:46),
        # padded with the reference default 4/3 per non-thermal group.
        grad = [float(g) for g in (h.gamma_rad or [])][:p.nener]
        grad += [4.0 / 3.0] * (p.nener - len(grad))
        return cls(ndim=p.ndim, nener=p.nener, npassive=p.npassive,
                   gamma=float(h.gamma),
                   gamma_rad=tuple(grad),
                   smallr=float(h.smallr), smallc=float(h.smallc),
                   slope_type=int(h.slope_type),
                   slope_theta=float(h.slope_theta),
                   scheme=str(h.scheme), riemann=str(h.riemann),
                   niter_riemann=int(h.niter_riemann),
                   courant_factor=float(h.courant_factor),
                   difmag=float(h.difmag),
                   pressure_fix=bool(h.pressure_fix),
                   beta_fix=float(getattr(h, "beta_fix", 0.0)))
