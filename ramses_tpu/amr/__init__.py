"""Adaptive mesh refinement: host-resident octree + device level batches.

TPU-native redesign of the reference's fully-threaded octree
(``amr/amr_commons.f90``, ``amr/refine_utils.f90``, ``amr/flag_utils.f90``)
per SURVEY.md §7: the tree topology (Morton-keyed oct coordinate sets, one
sorted array per level) lives on the host; all field data lives on device as
dense per-level batches ``[ncell, nvar]``; the ``build_comm``-shaped metadata
passes (stencil gather maps, interpolation maps, flux-correction maps) are
rebuilt on the host after each refinement and applied as XLA gathers and
scatter-adds.
"""

from ramses_tpu.amr.hierarchy import AmrSim  # noqa: F401
