"""Fleet observability plane over the run-service queue directory.

Four pieces, all reading artifacts the queue machinery already writes
(zero added device fetches):

  * :mod:`ramses_tpu.obs.server` — streaming results/metrics HTTP
    service (``--obs-port`` on a serve worker, or standalone
    ``python -m ramses_tpu --obs <queue_dir>``);
  * :mod:`ramses_tpu.obs.metrics` — Prometheus text exposition
    scraped from queue records + worker telemetry sinks;
  * :mod:`ramses_tpu.obs.trace` — the ``trace_id`` stamped at submit
    and propagated into telemetry, failure logs, heartbeat sidecars
    and checkpoint manifests;
  * :mod:`ramses_tpu.obs.profile` — on-demand jax.profiler captures
    armed by flag file / POST and picked up at chunk boundaries.

Only :mod:`~ramses_tpu.obs.trace` is imported eagerly — it is the one
piece the jax-free submit path (``ensemble/queue.py``) needs, and it
must stay a leaf.  Server/metrics/profile resolve lazily.
"""

from __future__ import annotations

from ramses_tpu.obs.trace import new_trace_id, worker_id  # noqa: F401

_LAZY = {
    "ObsServer": ("ramses_tpu.obs.server", "ObsServer"),
    "ProfileRequestWatcher": ("ramses_tpu.obs.profile",
                              "ProfileRequestWatcher"),
    "request_profile": ("ramses_tpu.obs.profile", "request_profile"),
    "render_queue_metrics": ("ramses_tpu.obs.metrics",
                             "render_queue_metrics"),
}


def __getattr__(name):
    try:
        modname, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib
    return getattr(importlib.import_module(modname), attr)


__all__ = ["new_trace_id", "worker_id", *sorted(_LAZY)]
