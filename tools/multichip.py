"""Multi-chip dryrun with SPMD partitioner-health gating.

Runs ``__graft_entry__.dryrun_multichip(n)`` in a subprocess (CPU
host-device mesh), captures stderr, and counts XLA's "Involuntary full
rematerialization" SPMD warnings — the signature of a global-view op
the partitioner could only reshard by replicating the full tensor
(MULTICHIP_r05 showed the complete-level dense sweep doing exactly
that every coarse step).  Writes ``MULTICHIP_local.json`` with the
same shape as the driver's ``MULTICHIP_*.json`` plus a top-level
``remat_warnings`` count, and exits nonzero when the count is > 0 so
CI fails loudly on a partitioner regression.

Usage::

    python tools/multichip.py [--devices N] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REMAT_MARK = "Involuntary full rematerialization"
TAIL_BYTES = 8000


def run_dryrun(n_devices: int, repo: str) -> dict:
    """One subprocess dryrun; returns the result record."""
    env = dict(os.environ)
    # force the CPU backend even where an accelerator plugin's
    # sitecustomize overrides JAX_PLATFORMS
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.setdefault("XLA_FLAGS", "")
    code = (f"import __graft_entry__ as g; "
            f"g.dryrun_multichip({n_devices})")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=env,
        capture_output=True, text=True, timeout=1800)
    stderr = proc.stderr or ""
    tail = (proc.stdout or "")[-TAIL_BYTES:] + stderr[-TAIL_BYTES:]
    remat = stderr.count(REMAT_MARK)
    return {
        "n_devices": n_devices,
        "rc": proc.returncode,
        "ok": proc.returncode == 0 and remat == 0,
        "skipped": False,
        "remat_warnings": remat,
        "tail": tail,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default="MULTICHIP_local.json")
    args = ap.parse_args(argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = run_dryrun(args.devices, repo)
    with open(args.out, "w") as f:
        json.dump(res, f, indent=1)
    print(f"dryrun on {res['n_devices']} devices: rc={res['rc']} "
          f"remat_warnings={res['remat_warnings']} -> {args.out}")
    if res["rc"] != 0:
        sys.stderr.write(res["tail"] + "\n")
        return res["rc"]
    if res["remat_warnings"]:
        sys.stderr.write(
            f"FAIL: {res['remat_warnings']} involuntary full "
            "rematerialization warning(s) — a global-view op reached "
            "the SPMD partitioner (see parallel/dense_slab.py)\n")
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
