#!/usr/bin/env python
"""Component-level device-time breakdown of the steady-state AMR step.

The VERDICT-r04 mandate: find the measured 678x per-cell-update overhead
of the AMR path vs the uniform kernel WITH A MEASUREMENT, not a guess.
This tool times each device kernel of the fused coarse step in
isolation, at the exact live shapes of the bench configuration
(sedov3d levelmin=7 levelmax=9 by default), plus the candidate
conversions (index-gather vs bit-permutation transpose) side by side.

Emits one JSON object; tools/write_trace_doc.py renders it into
docs/perf-trace-r05.md.

Optionally wraps 3 steady-state steps in a ``jax.profiler.trace``
(PROFILE_TRACE_DIR env) for op-level inspection where the tensorboard
profile plugin exists.

Env: PROF_LMIN, PROF_LMAX, PROF_WARM, PROF_REPS, PROFILE_TRACE_DIR.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, reps, sync):
    """Median-free simple wall: warm once (compile), sync, run reps,
    sync; returns seconds per call."""
    out = fn()
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    sync(out)
    return (time.perf_counter() - t0) / reps


def _sync(x):
    """Hard sync: host-fetch one element of every leaf (block_until_ready
    alone can return early over a tunneled device)."""
    leaves = jax.tree_util.tree_leaves(x)
    jax.device_get([l.ravel()[:1] for l in leaves if hasattr(l, "ravel")])


def main():
    from ramses_tpu.amr import bitperm
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.amr.hierarchy import (AmrSim, _fused_coarse_step,
                                          _fused_courant)
    from ramses_tpu.config import load_params

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lmin = int(os.environ.get("PROF_LMIN", "7"))
    lmax = int(os.environ.get("PROF_LMAX", "9"))
    warm = int(os.environ.get("PROF_WARM", "15"))
    reps = int(os.environ.get("PROF_REPS", "10"))
    params = load_params(os.path.join(here, "namelists", "sedov3d.nml"),
                        ndim=3)
    params.amr.levelmin, params.amr.levelmax = lmin, lmax
    params.refine.err_grad_d = 0.1
    params.refine.err_grad_p = 0.1
    sim = AmrSim(params, dtype=jnp.float32)
    sim.evolve(1e9, nstepmax=warm)          # develop the blast + compile
    sim.regrid_interval = 0                 # freeze the tree
    spec = sim._fused_spec()
    dt = jnp.asarray(sim.coarse_dt(), sim.dtype)
    res = {"device": str(jax.devices()[0].platform),
           "octs_per_level": {str(l): sim.tree.noct(l)
                              for l in sim.levels()},
           "levels": list(sim.levels()), "reps": reps}

    t = {}

    # --- full fused coarse step (the steady-state unit of work) ------
    # the step jit donates its state argument, so thread the returned
    # state through exactly like the evolve loop does
    def _step():
        out = _fused_coarse_step(sim.u, sim.dev, {}, dt, spec, None)
        sim.u = out[0]
        return out
    t["fused_coarse_step"] = timeit(_step, reps, _sync)

    # --- per-component, exact live shapes ----------------------------
    lb = sim.lmin
    d = sim.dev[lb]
    u0 = sim.u[lb]
    shape = (1 << lb,) * sim.cfg.ndim
    ncell = shape[0] ** sim.cfg.ndim

    t["dense_sweep_base"] = timeit(
        lambda: K.dense_sweep(u0, d.get("inv_perm"), d.get("perm"),
                              d["ok_dense"], dt, sim.dx(lb), shape,
                              sim.bspec, sim.cfg), reps, _sync)

    # conversions: bit-permutation transpose vs index gather
    f2d = jax.jit(lambda u: bitperm.flat_to_dense(u, lb, 3))
    d2f = jax.jit(lambda ud: bitperm.dense_to_flat(ud, lb, 3))
    ud = f2d(u0)
    t["flat_to_dense_bitperm"] = timeit(lambda: f2d(u0), reps, _sync)
    t["dense_to_flat_bitperm"] = timeit(lambda: d2f(ud), reps, _sync)
    m = sim.maps[lb]
    inv_perm = jnp.asarray(m.inv_perm)
    perm = jnp.asarray(m.perm)
    gat = jax.jit(lambda u, i: u[i])
    t["flat_to_dense_gather"] = timeit(lambda: gat(u0, inv_perm), reps,
                                       _sync)
    rows = u0[:ncell]
    t["dense_to_flat_gather"] = timeit(lambda: gat(rows, perm), reps,
                                       _sync)

    # pure dense kernel (what the uniform bench runs per 128^3)
    from ramses_tpu.hydro import pallas_muscl as pk
    if pk.kernel_available(sim.cfg, shape, sim.bspec.faces, u0.dtype):
        ok = (d["ok_dense"].reshape(shape)
              if d.get("ok_dense") is not None else None)
        udm = jnp.moveaxis(ud, -1, 0)

        @jax.jit
        def dense_kernel(udm):
            up, okp = pk.pad_xy(udm, sim.bspec, sim.cfg, ok=ok)
            return pk.fused_step_padded(up, dt, sim.cfg, sim.dx(lb),
                                        shape, ok_pad=okp)
        t["pallas_dense_kernel"] = timeit(lambda: dense_kernel(udm),
                                          reps, _sync)

    for l in sim.levels():
        if sim.maps[l].complete:
            continue
        dl = sim.dev[l]
        itp = K.interp_cells(sim.u[l - 1], dl["interp_cell"],
                             dl["interp_nb"], dl["interp_sgn"], sim.cfg,
                             itype=spec.itype)
        t[f"interp_cells_L{l}"] = timeit(
            lambda: K.interp_cells(sim.u[l - 1], dl["interp_cell"],
                                   dl["interp_nb"], dl["interp_sgn"],
                                   sim.cfg, itype=spec.itype), reps,
            _sync)
        t[f"level_sweep_L{l}"] = timeit(
            lambda: K.level_sweep(sim.u[l], itp, dl["stencil_src"],
                                  dl["vsgn"], dl["ok_ref"], None, dt,
                                  sim.dx(l), sim.cfg), reps, _sync)
        t[f"scatter_corr_L{l}"] = timeit(
            lambda: K.scatter_corrections(
                sim.u[l - 1],
                jnp.zeros((sim.maps[l].noct_pad, 3, 2, sim.cfg.nvar),
                          sim.dtype), dl["corr_idx"], sim.cfg),
            reps, _sync)

    t["restrict_upload_base"] = timeit(
        lambda: K.restrict_upload(sim.u[lb], sim.u[lb + 1],
                                  d["ref_cell"], d["son_oct"], sim.cfg),
        reps, _sync) if sim.tree.has(lb + 1) else None

    t["fused_courant"] = timeit(
        lambda: _fused_courant(sim.u, sim.dev, spec), reps, _sync)

    # steady-state chunk throughput (the bench's steady_state number)
    nss = 8
    n0 = sim.nstep
    sim.evolve(1e9, nstepmax=sim.nstep + nss)   # warm the scan chunks
    sim.drain()
    ttd = 2 ** sim.cfg.ndim
    upd = sum(sim.tree.noct(l) * ttd * 2 ** (l - sim.lmin)
              for l in sim.levels())
    t0 = time.perf_counter()
    sim.evolve(1e9, nstepmax=sim.nstep + nss)
    sim.drain()
    wss = time.perf_counter() - t0
    res["steady_state_cell_updates_per_sec"] = nss * upd / wss
    res["steady_state_s_per_coarse_step"] = wss / nss
    res["updates_per_coarse_step"] = upd

    tdir = os.environ.get("PROFILE_TRACE_DIR")
    if tdir:
        with jax.profiler.trace(tdir):
            sim.evolve(1e9, nstepmax=sim.nstep + 3)
            sim.drain()
        res["trace_dir"] = tdir

    res["timings_s"] = {k: (round(v, 6) if v is not None else None)
                        for k, v in t.items()}
    print("##PROF##" + json.dumps(res))


if __name__ == "__main__":
    main()
