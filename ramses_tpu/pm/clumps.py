"""Clump finder: watershed peak-patch segmentation (PHEW).

Reference: ``pm/clump_finder.f90`` (``count_peaks:428``,
``propagate_flag:499``, ``saddlepoint_search:524``; doc
``doc/wiki/PHEW.md``).  The reference's serial flag-propagation over
linked cells becomes: steepest-ascent parent assignment (one gather over
the 3^ndim neighbourhood) + pointer-jumping label propagation
(O(log L) device gathers), then host-side saddle merging — peaks are few,
cells are many, so the device does the O(N) work and the host the O(npeaks²).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _neighbor_offsets(ndim: int):
    return [off for off in itertools.product((-1, 0, 1), repeat=ndim)
            if any(off)]


def steepest_parent(rho, ndim: int):
    """Flat index of the densest 3^ndim neighbour (self if local max)."""
    shape = rho.shape
    flat_idx = jnp.arange(rho.size).reshape(shape)
    best_rho = rho
    best_idx = flat_idx
    for off in _neighbor_offsets(ndim):
        r = rho
        i = flat_idx
        for d, o in enumerate(off):
            if o:
                r = jnp.roll(r, -o, axis=d)
                i = jnp.roll(i, -o, axis=d)
        # strict ascent, with an index tie-break so equal-density plateaus
        # (e.g. a peak centred exactly on a cell face) drain to one cell
        take = (r > best_rho) | ((r == best_rho) & (i > best_idx))
        best_rho = jnp.where(take, r, best_rho)
        best_idx = jnp.where(take, i, best_idx)
    return best_idx


@jax.jit
def _pointer_jump(parent):
    """Iterate parent ← parent[parent] to the fixed point (peak labels)."""
    def body(carry):
        p, _ = carry
        p2 = p.reshape(-1)[p]
        return p2, jnp.any(p2 != p)

    def cond(carry):
        return carry[1]

    p, _ = jax.lax.while_loop(cond, body, (parent, jnp.array(True)))
    return p


def watershed(rho, threshold: float, ndim: int):
    """Label array: flat peak index per cell above threshold, -1 outside."""
    rho = jnp.asarray(rho)
    parent = steepest_parent(rho, ndim)
    labels = _pointer_jump(parent)
    return jnp.where(rho > threshold, labels, -1)


@dataclass
class Clump:
    """One clump's properties (``pm/clump_merger.f90``
    ``write_clump_properties`` columns)."""
    index: int
    peak_cell: Tuple[int, ...]
    peak_rho: float
    ncell: int
    mass: float
    pos: np.ndarray          # mass-weighted centre [ndim]
    relevance: float         # peak / max saddle
    # saddle-threshold halo membership (merge_clumps('saddleden')):
    # the surviving peak index of this clump's halo (= index when the
    # HOP-style halo pass is off or the clump is its own halo)
    parent: int = -1
    rho_min: float = 0.0
    rho_av: float = 0.0
    max_saddle: float = 0.0


def _saddles(rho, labels, ndim: int) -> Dict[Tuple[int, int], float]:
    """Max over faces of min(rho_a, rho_b) for neighbouring labels."""
    rho = np.asarray(rho)
    lab = np.asarray(labels)
    out: Dict[Tuple[int, int], float] = {}
    for d in range(ndim):
        la, lb = lab, np.roll(lab, -1, axis=d)
        ra, rb = rho, np.roll(rho, -1, axis=d)
        m = (la != lb) & (la >= 0) & (lb >= 0)
        if not m.any():
            continue
        key_a, key_b = la[m], lb[m]
        val = np.minimum(ra[m], rb[m])
        for a, b, v in zip(key_a, key_b, val):
            k = (min(a, b), max(a, b))
            if v > out.get(k, -np.inf):
                out[k] = v
    return out


def _merge_pass(rho, labels, ndim: int, action: str, thresh: float,
                density_threshold: float) -> np.ndarray:
    """Iterative peak merging to a fixed point — the two actions of
    ``merge_clumps`` (``pm/clump_merger.f90:560-640``):

    * ``'relevance'``: a peak whose relevance
      ``max_dens / max_saddle`` (``max_dens / density_threshold``
      when it has no saddle) is below ``thresh`` merges into the
      neighbour across its HIGHEST saddle;
    * ``'saddleden'``: a peak whose highest saddle density exceeds
      ``thresh`` merges the same way (the HOP-style halo grouping of
      ``saddle_threshold > 0`` cosmo runs).

    Both actions only move a peak into a DENSER partner (equal
    densities tie-break to the smaller index), exactly like the
    reference's ``max_dens(jpeak) > max_dens(ipeak)`` guard — the
    fixed point is therefore order-independent.
    """
    flat_rho = rho.reshape(-1)
    changed = True
    while changed:
        changed = False
        saddles = _saddles(rho, labels, ndim)
        best: Dict[int, Tuple[float, int]] = {}
        for (a, b), v in saddles.items():
            if v > best.get(a, (-np.inf, -1))[0]:
                best[a] = (v, b)
            if v > best.get(b, (-np.inf, -1))[0]:
                best[b] = (v, a)
        peaks = np.unique(labels[labels >= 0])
        peak_rho = {p: flat_rho[p] for p in peaks}
        # process the least dense peak first (deterministic; the fixed
        # point matches any order by the denser-partner guard)
        for p in sorted(peaks, key=lambda q: (peak_rho[q], q)):
            s, partner = best.get(p, (0.0, -1))
            if action == "relevance":
                denom = s if s > 0 else max(density_threshold, 1e-300)
                do = peak_rho[p] / denom < thresh
            else:
                do = s > thresh
            if not (do and partner >= 0):
                continue
            rp = peak_rho[partner]
            if rp > peak_rho[p] or (rp == peak_rho[p] and partner < p):
                labels[labels == p] = partner
                changed = True
                break
    return labels


def find_clumps(rho, threshold: float, relevance: float = 2.0,
                dx: float = 1.0, merge: bool = True,
                saddle_threshold: float = 0.0):
    """Full PHEW pass: watershed → relevance merge → properties
    [→ saddle-threshold halo grouping].

    ``saddle_threshold > 0`` additionally runs the HOP-style
    ``merge_clumps('saddleden')`` pass AFTER the clump properties are
    taken: clumps whose mutual saddle exceeds the threshold group into
    halos, recorded per clump as ``parent`` (the reference's two-level
    clump→halo hierarchy for cosmo runs).  Returns
    (labels [same shape, -1 outside], [Clump]) — with the halo pass,
    ``labels`` carries the HALO segmentation and each ``Clump.parent``
    names its halo peak.
    """
    rho_j = jnp.asarray(rho)
    ndim = rho_j.ndim
    labels = np.array(watershed(rho_j, threshold, ndim))
    rho = np.asarray(rho_j)

    if merge:
        labels = _merge_pass(rho, labels, ndim, "relevance", relevance,
                             threshold)

    clumps: List[Clump] = []
    vol = dx ** ndim
    peaks = np.unique(labels[labels >= 0])
    saddles = _saddles(rho, labels, ndim)
    for p in peaks:
        m = labels == p
        cells = np.argwhere(m)
        rr = rho[m]
        mass = rr.sum() * vol
        pos = (cells * rr[:, None]).sum(0) / rr.sum()
        smax = max([v for (a, b), v in saddles.items()
                    if p in (a, b)] or [0.0])
        pk = np.unravel_index(p, rho.shape)
        clumps.append(Clump(
            index=int(p), peak_cell=tuple(int(c) for c in pk),
            peak_rho=float(rho.reshape(-1)[p]), ncell=int(m.sum()),
            mass=float(mass), pos=(pos + 0.5) * dx,
            relevance=float(rho.reshape(-1)[p] / max(smax, 1e-300)),
            parent=int(p), rho_min=float(rr.min()),
            rho_av=float(rr.mean()), max_saddle=float(smax)))

    if saddle_threshold > 0.0 and len(clumps) > 1:
        labels = _merge_pass(rho, labels, ndim, "saddleden",
                             saddle_threshold, threshold)
        flat = labels.reshape(-1)
        for c in clumps:
            # the halo this clump's peak cell ended up in
            c.parent = int(flat[c.index])

    clumps.sort(key=lambda c: -c.mass)
    return labels, clumps


def write_clump_table(clumps: List[Clump], path: str):
    """``output_clump``-style ascii table (the
    ``write_clump_properties`` column set incl. the halo parent and
    the rho min/av/max summary)."""
    with open(path, "w") as f:
        f.write("# index parent ncell peak_x peak_y peak_z rho- rho+ "
                "rho_av mass relevance\n")
        for c in clumps:
            pk = list(c.peak_cell) + [0] * (3 - len(c.peak_cell))
            f.write(f"{c.index:8d} {c.parent:8d} {c.ncell:8d} "
                    f"{pk[0]:6d} {pk[1]:6d} {pk[2]:6d} "
                    f"{c.rho_min:12.4e} {c.peak_rho:12.4e} "
                    f"{c.rho_av:12.4e} {c.mass:14.6e} "
                    f"{c.relevance:10.3f}\n")
