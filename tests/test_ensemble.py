"""Batched ensemble engine + run service (``ramses_tpu/ensemble/``).

Pins the tentpole contracts:

  * member-of-batch == solo run BITWISE for hydro, MHD and RHD (the
    vmap axis must be numerically invisible);
  * a traced-only sweep compiles exactly as many programs as one solo
    member (recompile-counter pin); static sweeps split into one
    sub-batch per frozen config;
  * per-member completion masking — a finished member idles at its own
    tend while the batch drains;
  * queue claim/requeue/reclaim atomicity with stale-worker takeover;
  * a served job publishes telemetry JSONL and a manifest-valid
    resumable checkpoint.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from ramses_tpu.config import params_from_dict
from ramses_tpu.ensemble import queue as jq
from ramses_tpu.ensemble.batch import (EnsembleEngine, EnsembleSpec,
                                       apply_override, build_member)
from ramses_tpu.ensemble.service import parse_sweep_args, serve

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------
# small uniform Sedov-style bases (2D hydro, 2D MHD, 1D RHD)
# ---------------------------------------------------------------------
def _hydro_params(nstepmax=6, gamma=1.4):
    return params_from_dict({
        "run_params": {"hydro": True, "nstepmax": nstepmax},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "point"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "length_x": [10.0, 1.0], "length_y": [10.0, 1.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.0],
                        "p_region": [1e-5, 0.1]},
        "hydro_params": {"gamma": gamma, "courant_factor": 0.8,
                         "riemann": "hllc"},
        "output_params": {"tend": 1e9},
    }, ndim=2)


def _mhd_params(nstepmax=4):
    return params_from_dict({
        "run_params": {"hydro": True, "nstepmax": nstepmax},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0],
                        "u_region": [0.5], "v_region": [-0.3],
                        "A_region": [0.3], "B_region": [0.4],
                        "C_region": [0.5]},
        "hydro_params": {"gamma": 5.0 / 3.0, "riemann": "hlld",
                         "courant_factor": 0.8},
        "output_params": {"tend": 1e9},
    }, ndim=2)


def _rhd_params(nstepmax=3):
    return params_from_dict({
        "run_params": {"hydro": True, "nstepmax": nstepmax},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75],
                        "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [10.0, 1.0],
                        "p_region": [13.33, 1e-2]},
        "hydro_params": {"gamma": 5.0 / 3.0},
        "output_params": {"tend": 1e9},
    }, ndim=1)


def _solo_windows(spec, k, windows, runner):
    """Replay the engine's exact fused-window sequence on one member."""
    grid, state, tend, _ = build_member(spec, k, dtype=jnp.float64)
    t = jnp.asarray(0.0, jnp.float64)
    te = jnp.asarray(tend, jnp.float64)
    for n in windows:
        state, t = runner(grid, state, t, te, n)
    return state, float(t)


# ---------------------------------------------------------------------
# spec expansion
# ---------------------------------------------------------------------
def test_apply_override_paths():
    p = _hydro_params()
    apply_override(p, "hydro.gamma", 1.62)
    assert p.hydro.gamma == 1.62
    apply_override(p, "init.p_region[1]", 0.25)
    assert p.init.p_region[1] == 0.25
    apply_override(p, "run.nstepmax", 7.0)      # coerced to the field's
    assert p.run.nstepmax == 7                  # current type
    with pytest.raises(AttributeError):
        apply_override(p, "hydro.no_such_field", 1.0)
    with pytest.raises(ValueError):
        apply_override(p, "gamma", 1.0)         # not group.field


def test_from_params_namelist_ramp():
    p = _hydro_params()
    p.ensemble.nmember = 4
    p.ensemble.sweep_name = ["hydro.gamma"]
    p.ensemble.sweep_start = [1.4]
    p.ensemble.sweep_stop = [1.7]
    spec = EnsembleSpec.from_params(p)
    assert spec.nmember == 4
    assert spec.sweeps["hydro.gamma"] == pytest.approx(
        [1.4, 1.5, 1.6, 1.7])
    # explicit sweeps win over the namelist ramp on key collision
    spec2 = EnsembleSpec.from_params(
        p, sweeps={"hydro.gamma": [2.0, 2.0, 2.0, 2.0]})
    assert spec2.sweeps["hydro.gamma"] == [2.0] * 4
    # length mismatch is an error, not a silent truncation
    with pytest.raises(ValueError, match="3 values for 4"):
        EnsembleSpec.from_params(p, sweeps={"init.d_region[0]":
                                            [1.0, 1.1, 1.2]})


def test_parse_sweep_args():
    s = parse_sweep_args(["hydro.gamma=1.4,1.6",
                          "hydro.riemann=hllc,hll"])
    assert s["hydro.gamma"] == [1.4, 1.6]
    assert s["hydro.riemann"] == ["hllc", "hll"]
    with pytest.raises(ValueError):
        parse_sweep_args(["hydro.gamma"])


def test_amr_namelist_rejected():
    p = _hydro_params()
    p.amr.levelmax = 5
    spec = EnsembleSpec(base=p, nmember=2, perturb_amp=0.01)
    with pytest.raises(NotImplementedError, match="uniform"):
        build_member(spec, 0)


# ---------------------------------------------------------------------
# bitwise member-vs-solo + compile-count pin
# ---------------------------------------------------------------------
def test_hydro_member_bitwise_and_compile_once():
    """A traced sweep (region pressure + IC perturbations) batches into
    ONE compile group; the whole batch-of-4 run costs exactly the
    compiles of one solo member, and member k is bitwise the solo run
    through the same fused windows."""
    from ramses_tpu.grid.uniform import run_steps
    from ramses_tpu.telemetry.recorder import (_install_compile_listener,
                                               compile_count)

    _install_compile_listener()
    spec = EnsembleSpec(
        base=_hydro_params(nstepmax=6), nmember=4,
        sweeps={"init.p_region[1]": [0.08, 0.1, 0.12, 0.14]},
        perturb_amp=0.01)

    # engine chunk sequence for nstepmax=6, chunk=4: windows (4, 2)
    def runner(grid, state, t, te, n):
        u, t, _ = run_steps(grid, state[0], t, te, n)
        return (u,), t

    jax.clear_caches()
    # build ICs BEFORE the count so both sides measure pure step-chain
    # compiles (the engine builds members in __init__, pre-snapshot)
    grid, state, tend, _ = build_member(spec, 0, dtype=jnp.float64)
    t = jnp.asarray(0.0, jnp.float64)
    te = jnp.asarray(tend, jnp.float64)
    c0 = compile_count()
    for n in (4, 2):
        state, t = runner(grid, state, t, te, n)
    solo_compiles = compile_count() - c0
    solo_u, solo_t = {0: state}, {0: float(t)}
    for k in (1, 3):
        solo_u[k], solo_t[k] = _solo_windows(spec, k, (4, 2), runner)

    jax.clear_caches()
    eng = EnsembleEngine(spec, dtype=jnp.float64)
    assert len(eng.groups) == 1        # traced sweep: one jit cache key
    c1 = compile_count()
    eng.run(chunk=4)
    batch_compiles = compile_count() - c1
    assert batch_compiles == solo_compiles
    assert eng.run_complete() and eng.nstep == 6

    for k in (0, 1, 3):
        ms = eng.member_state(k)
        assert np.asarray(ms["u"]).tobytes() == \
            np.asarray(solo_u[k][0]).tobytes(), k
        assert ms["t"] == solo_t[k]
        assert ms["nstep"] == 6


def test_mhd_member_bitwise():
    from ramses_tpu.mhd.uniform import run_steps

    spec = EnsembleSpec(
        base=_mhd_params(nstepmax=4), nmember=2,
        sweeps={"init.d_region[0]": [1.0, 1.15]}, solver="mhd")

    def runner(grid, state, t, te, n):
        u, bf, t, _ = run_steps(grid, state[0], state[1], t, te, n)
        return (u, bf), t

    eng = EnsembleEngine(spec, dtype=jnp.float64).run(chunk=4)
    assert eng.run_complete()
    for k in range(2):
        state, t = _solo_windows(spec, k, (4,), runner)
        ms = eng.member_state(k)
        assert np.asarray(ms["u"]).tobytes() == \
            np.asarray(state[0]).tobytes(), k
        assert np.asarray(ms["bf"]).tobytes() == \
            np.asarray(state[1]).tobytes(), k
        assert ms["t"] == t


def test_rhd_member_bitwise():
    from ramses_tpu.rhd.uniform import run_steps

    spec = EnsembleSpec(base=_rhd_params(nstepmax=3), nmember=2,
                        perturb_amp=0.005, solver="rhd")

    def runner(grid, state, t, te, n):
        u, t, _ = run_steps(grid, state[0], t, te, n)
        return (u,), t

    eng = EnsembleEngine(spec, dtype=jnp.float64).run(chunk=4)
    assert eng.run_complete()
    for k in range(2):
        state, _ = _solo_windows(spec, k, (3,), runner)
        assert np.asarray(eng.member_state(k)["u"]).tobytes() == \
            np.asarray(state[0]).tobytes(), k


def test_static_sweep_splits_groups():
    """gamma is baked into the frozen HydroStatic — a two-value sweep
    over 4 members makes exactly two sub-batches of two, and members
    land in their group in submission order."""
    spec = EnsembleSpec(
        base=_hydro_params(nstepmax=2), nmember=4,
        sweeps={"hydro.gamma": [1.4, 5.0 / 3.0, 1.4, 5.0 / 3.0]})
    eng = EnsembleEngine(spec, dtype=jnp.float64)
    assert sorted(g.members for g in eng.groups) == [[0, 2], [1, 3]]
    eng.run(chunk=4)
    assert eng.run_complete() and eng.nstep == 2
    # and the two groups really ran different physics
    u0 = np.asarray(eng.member_state(0)["u"])
    u1 = np.asarray(eng.member_state(1)["u"])
    assert not np.array_equal(u0, u1)


def test_completion_masking():
    """Members with different tend finish independently: the early one
    idles at ITS tend (in-scan mask) while the late one keeps stepping
    in the same compiled program."""
    p = _hydro_params(nstepmax=64)
    # tend rides &OUTPUT_PARAMS tout (the last entry is the run's end)
    spec = EnsembleSpec(base=p, nmember=2,
                        sweeps={"output.tout[0]": [0.05, 0.4]})
    eng = EnsembleEngine(spec, dtype=jnp.float64).run(chunk=8)
    assert eng.run_complete()
    m0, m1 = eng.member_state(0), eng.member_state(1)
    assert m0["t"] >= 0.05 and m1["t"] >= 0.4
    assert m0["t"] < m1["t"]          # member 0 did NOT ride to 0.4
    assert m0["nstep"] < m1["nstep"] < 64


def test_step_budget_freezes_member():
    """nstepmax is per-member: a member that exhausts the budget before
    tend is frozen (clamped effective tend) and counts as complete."""
    spec = EnsembleSpec(base=_hydro_params(nstepmax=3), nmember=2,
                        perturb_amp=0.01)
    eng = EnsembleEngine(spec, dtype=jnp.float64).run(chunk=2)
    assert eng.run_complete()
    assert all(eng.member_state(k)["nstep"] == 3 for k in range(2))


# ---------------------------------------------------------------------
# checkpoint roundtrip
# ---------------------------------------------------------------------
def test_checkpoint_roundtrip_and_fingerprint(tmp_path):
    from ramses_tpu.resilience.checkpoint import (latest_valid_checkpoint,
                                                  validate_checkpoint)

    spec = EnsembleSpec(base=_hydro_params(nstepmax=6), nmember=3,
                        sweeps={"init.p_region[1]": [0.08, 0.1, 0.12]})
    eng = EnsembleEngine(spec, dtype=jnp.float64)
    eng.run(chunk=2, nstepmax=2)
    snap = eng.save(str(tmp_path))
    ok, why = validate_checkpoint(snap)
    assert ok, why
    assert latest_valid_checkpoint(str(tmp_path), log=None) == snap

    # restore is bitwise and continues exactly like the original
    r = EnsembleEngine.from_checkpoint(spec, snap, dtype=jnp.float64)
    for k in range(3):
        a, b = eng.member_state(k), r.member_state(k)
        assert np.asarray(a["u"]).tobytes() == np.asarray(b["u"]).tobytes()
        assert a["t"] == b["t"] and a["nstep"] == b["nstep"]
    eng.run(chunk=2)
    r.run(chunk=2)
    for k in range(3):
        assert np.asarray(eng.member_state(k)["u"]).tobytes() == \
            np.asarray(r.member_state(k)["u"]).tobytes(), k

    # a different expansion must refuse the checkpoint
    other = EnsembleSpec(base=_hydro_params(nstepmax=6), nmember=3,
                         sweeps={"init.p_region[1]": [0.2, 0.3, 0.4]})
    with pytest.raises(ValueError, match="different"):
        EnsembleEngine.from_checkpoint(other, snap, dtype=jnp.float64)


# ---------------------------------------------------------------------
# queue (no jax needed)
# ---------------------------------------------------------------------
def test_queue_fifo_claim_and_states(tmp_path):
    q = str(tmp_path / "q")
    ids = [jq.submit(q, "&RUN_PARAMS\n/", job_id=f"job-{i:03d}")
           for i in range(3)]
    assert jq.queue_counts(q)["queued"] == 3
    with pytest.raises(FileExistsError):
        jq.submit(q, "&RUN_PARAMS\n/", job_id=ids[0])
    a = jq.claim(q, worker="w1")
    b = jq.claim(q, worker="w2")
    assert (a.id, b.id) == (ids[0], ids[1])     # oldest first
    assert a.state == "running" and a.record["attempts"] == 1
    assert a.record["worker"] == "w1"
    jq.complete(a, result={"ok": True})
    assert jq.job_status(q, a.id).state == "done"
    assert jq.job_status(q, a.id).record["result"] == {"ok": True}
    # requeue keeps the attempt count; the NEXT claim bumps it
    jq.requeue(b, error="boom")
    assert jq.job_status(q, b.id).state == "queued"
    b2 = jq.claim(q, worker="w3")
    assert b2.id == ids[1] and b2.record["attempts"] == 2
    jq.fail(b2, error="boom again")
    assert jq.job_status(q, b2.id).record["error"] == "boom again"
    jq.claim(q)                                  # drains ids[2]
    assert jq.claim(q) is None                   # empty queue -> None


def test_queue_stale_reclaim(tmp_path):
    q = str(tmp_path / "q")
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-stale")
    job = jq.claim(q, worker="dead-worker")
    # a live heartbeat protects the claim ...
    jq.heartbeat(job)
    assert jq.reclaim_stale(q, stale_s=300.0, log=None) == 0
    # ... a worker dead for an hour (stale content heartbeat) is
    # taken over, and the takeover bumps the fencing token
    jq._age_heartbeat(job.path, 3600.0)
    assert jq.reclaim_stale(q, stale_s=300.0, max_attempts=3,
                            log=None) == 1
    j = jq.job_status(q, "job-stale")
    assert j.state == "queued" and j.record["attempts"] == 1
    assert j.record["fence"] == 2        # claim=1, reclaim=2
    # the zombie's writes are now fenced off
    with pytest.raises(jq.FenceLost):
        jq.heartbeat(job)
    # at the attempt ceiling the takeover fails the job instead
    job = jq.claim(q)
    assert job.record["attempts"] == 2
    jq._age_heartbeat(job.path, 3600.0)
    jq.reclaim_stale(q, stale_s=300.0, max_attempts=2, log=None)
    j = jq.job_status(q, "job-stale")
    assert j.state == "failed" and "no heartbeat" in j.record["error"]


# ---------------------------------------------------------------------
# run service end-to-end
# ---------------------------------------------------------------------
SERVICE_NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "nstepmax=4", "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=4", "boxlen=1.0", "/",
    "&INIT_PARAMS", "nregion=2",
    "region_type(1)='square'", "region_type(2)='point'",
    "x_center=0.5,0.5", "y_center=0.5,0.5",
    "length_x=10.0,1.0", "length_y=10.0,1.0",
    "exp_region=10.0,10.0", "d_region=1.0,0.0", "p_region=1e-5,0.1", "/",
    "&HYDRO_PARAMS", "gamma=1.4", "riemann='hllc'", "/",
    "&OUTPUT_PARAMS", "tend=1e9", "/",
    "&ENSEMBLE_PARAMS", "nmember=2", "perturb_amp=0.01",
    "chunk_steps=2", "/",
])


def test_serve_drains_queue_with_artifacts(tmp_path):
    from ramses_tpu.resilience.checkpoint import validate_checkpoint

    q = str(tmp_path / "q")
    ids = [jq.submit(q, SERVICE_NML, ndim=2, dtype="float64",
                     sweeps={"init.p_region[1]": [0.08 + 0.02 * i,
                                                  0.12 + 0.02 * i]})
           for i in range(2)]
    counts = serve(q, worker="t", idle_exit=True, max_attempts=2,
                   log=lambda *a: None)
    assert counts == {"done": 2, "failed": 0, "requeued": 0}
    assert jq.queue_counts(q) == {"queued": 0, "running": 0,
                                  "done": 2, "failed": 0, "parked": 0}
    for jid in ids:
        job = jq.job_status(q, jid)
        res = job.record["result"]
        assert res["nmember"] == 2 and res["nstep_max"] == 4
        ok, why = validate_checkpoint(res["snapshot"])
        assert ok, why
        kinds = [json.loads(line).get("kind")
                 for line in open(res["telemetry"])]
        assert "ensemble_chunk" in kinds and "ensemble_done" in kinds
        assert "run_header" in kinds
        # the job dir is self-contained: namelist + resumable snapshot
        assert os.path.isfile(os.path.join(res["results_dir"], "run.nml"))


def test_serve_retries_then_fails(tmp_path):
    """A job whose namelist the engine rejects is requeued once (the
    attempt budget) and then lands in failed/ with the error string."""
    q = str(tmp_path / "q")
    bad = SERVICE_NML.replace("levelmax=4", "levelmax=5")
    jid = jq.submit(q, bad, ndim=2)
    counts = serve(q, worker="t", idle_exit=True, max_attempts=2,
                   log=lambda *a: None)
    assert counts == {"done": 0, "failed": 1, "requeued": 1}
    job = jq.job_status(q, jid)
    assert job.state == "failed" and job.record["attempts"] == 2
    assert "uniform" in job.record["error"]


def test_driver_dispatches_ensemble(tmp_path):
    """run_namelist hands an &ENSEMBLE_PARAMS nmember>1 namelist to the
    engine (one process, no queue)."""
    from ramses_tpu.driver import run_namelist
    nml = tmp_path / "ens.nml"
    nml.write_text(SERVICE_NML)
    eng = run_namelist(str(nml), ndim=2, dtype=jnp.float64,
                       verbose=False)
    assert isinstance(eng, EnsembleEngine)
    assert eng.run_complete() and eng.nmember == 2 and eng.nstep == 4


# ---------------------------------------------------------------------
# member isolation ladder (batched step-guard -> retry -> quarantine)
# ---------------------------------------------------------------------
class _CapTel:
    """Minimal telemetry stand-in capturing record_event calls."""

    def __init__(self):
        self.events = []

    def record_event(self, kind, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]


def _armed_params(nstepmax=6, retries=2, quarantine=False, fault=""):
    p = _hydro_params(nstepmax=nstepmax)
    p.ensemble.max_member_retries = retries
    p.ensemble.member_quarantine = quarantine
    if fault:
        p.run.fault_inject = fault
    return p


def test_member_fault_recovery_bitwise():
    """The acceptance pin: ``nan@3:member=1`` in a 4-member batch is
    recovered by the masked retry and the OTHER members finish bitwise
    identical to a fault-free run.  (The pending fault clamps the
    faulty run's fused windows to (3, 3); the clean twin runs chunk=3
    so the healthy members see the identical window sequence.)"""
    kw = dict(nmember=4,
              sweeps={"init.p_region[1]": [0.08, 0.1, 0.12, 0.14]},
              perturb_amp=0.01)
    clean = EnsembleEngine(EnsembleSpec(base=_armed_params(), **kw),
                           dtype=jnp.float64,
                           telemetry=_CapTel()).run(chunk=3)
    tel = _CapTel()
    faulty = EnsembleEngine(
        EnsembleSpec(base=_armed_params(fault="nan@3:member=1"), **kw),
        dtype=jnp.float64, telemetry=tel).run(chunk=4)
    assert faulty.run_complete() and not faulty.quarantined
    for k in (0, 2, 3):
        a, b = faulty.member_state(k), clean.member_state(k)
        assert np.asarray(a["u"]).tobytes() == \
            np.asarray(b["u"]).tobytes(), k
        assert a["t"] == b["t"] and a["nstep"] == 6
    # member 1 took the ladder: tripped exactly at its step 3 (the
    # fused-window clamp), recovered at halved dt, and still completed
    m1 = faulty.member_state(1)
    assert m1["nstep"] == 6 and np.isfinite(np.asarray(m1["u"])).all()
    faults = [f for k, f in tel.events if k == "fault"]
    assert faults == [{"member": 1, "reason": "nonfinite",
                       "nstep": 3, "t": faults[0]["t"]}]
    assert "member_rollback" in tel.kinds()
    rec = [f for k, f in tel.events if k == "member_recovered"]
    assert rec and rec[0]["member"] == 1 and rec[0]["attempt"] == 1
    g = faulty._bguard
    assert (g.trips, g.rollbacks, g.recovered, g.quarantined) == \
        (1, 1, 1, 0)
    # the chunk records carry the (zero) quarantine count
    chunks = [f for k, f in tel.events if k == "ensemble_chunk"]
    assert chunks and all(c["quarantined"] == 0 for c in chunks)


def test_member_quarantine_census_and_checkpoint(tmp_path):
    """Quarantine-only mode: a poisoned member is evicted with a
    manifest-valid emergency dump of its last clean state, the census
    rides the ensemble checkpoint manifest, and a restore keeps both
    the census and the run-complete verdict."""
    from ramses_tpu.resilience.checkpoint import (latest_valid_checkpoint,
                                                  read_quarantine_census,
                                                  validate_checkpoint)
    tel = _CapTel()
    p = _armed_params(retries=0, quarantine=True,
                      fault="nan@3:member=1")
    p.output.output_dir = str(tmp_path)
    spec = EnsembleSpec(base=p, nmember=4, perturb_amp=0.01)
    eng = EnsembleEngine(spec, dtype=jnp.float64,
                         telemetry=tel).run(chunk=4)
    assert eng.run_complete()
    assert list(eng.quarantined) == [1]
    info = eng.quarantined[1]
    assert info["reason"] == "nonfinite_state" and info["nstep"] == 3
    assert eng.member_state(1)["quarantined"] is True
    assert eng.member_state(1)["nstep"] == 3       # frozen at eviction
    assert all(eng.member_state(k)["nstep"] == 6 for k in (0, 2, 3))
    q = [f for k, f in tel.events if k == "quarantine"]
    assert q and q[0]["member"] == 1
    # the emergency dump is manifest-valid and holds finite state
    ok, why = validate_checkpoint(info["dump"])
    assert ok, why
    dump = np.load(os.path.join(info["dump"], "member_state.npz"))
    assert np.isfinite(dump["s0"]).all() and int(dump["nstep"]) == 3
    # census rides the checkpoint manifest; the quarantine dump is NOT
    # a resume candidate (no output_ prefix)
    snap = eng.save(str(tmp_path))
    assert latest_valid_checkpoint(str(tmp_path), log=None) == snap
    census = read_quarantine_census(snap)
    assert census[1]["reason"] == "nonfinite_state"
    assert census[1]["nstep"] == 3
    r = EnsembleEngine.from_checkpoint(spec, snap, dtype=jnp.float64)
    assert r.quarantined[1]["nstep"] == 3
    assert r.member_state(1)["quarantined"] is True
    assert r.run_complete()


def test_member_retry_llf_escalation(monkeypatch):
    """When the halved-dt retry fails too, attempt 2 regroups the
    tripped member into an LLF escalation sub-batch (the Riemann knob
    is a jit cache key, never a traced branch) — the parent group's
    config stays untouched."""
    from ramses_tpu.resilience.stepguard import BatchGuard

    tel = _CapTel()
    spec = EnsembleSpec(
        base=_armed_params(retries=2, fault="nan@3:member=1"),
        nmember=2, perturb_amp=0.01)
    eng = EnsembleEngine(spec, dtype=jnp.float64, telemetry=tel)
    real = BatchGuard.screen
    forced = {"done": False}

    def fake(t_host, summ=None, active=None):
        bad = real(t_host, summ, active)
        # retry-ladder checks pass active=None (the main-window check
        # passes active=~done): fail the FIRST retry so the ladder
        # reaches the attempt-2 escalation
        if active is None and not forced["done"]:
            forced["done"] = True
            return np.ones_like(bad)
        return bad

    monkeypatch.setattr(BatchGuard, "screen", staticmethod(fake))
    eng.run(chunk=4)
    assert eng.run_complete() and not eng.quarantined
    rb = [f for k, f in tel.events if k == "member_rollback"]
    assert [(r["attempt"], r["escalated"]) for r in rb] == \
        [(1, False), (2, True)]
    rec = [f for k, f in tel.events if k == "member_recovered"]
    assert rec == [{"member": 1, "attempt": 2}]
    assert eng.groups[0].grid.cfg.riemann == "hllc"
    assert np.isfinite(np.asarray(eng.member_state(1)["u"])).all()


def test_batched_zero_overhead_device_get_pin(monkeypatch):
    """Arming the batched guard must not add host<->device fetches:
    the per-member summary is folded into the single per-dispatch
    ``jax.device_get`` tuple fetch (one per fused window — windows
    (4, 2) for nstepmax=6, chunk=4)."""
    counts = {}
    for name, p in (("off", _hydro_params()),
                    ("armed", _armed_params(retries=2))):
        kw = dict(nmember=2, perturb_amp=0.01)
        # warm the compile caches so the counted run is pure dispatch
        EnsembleEngine(EnsembleSpec(base=p, **kw), dtype=jnp.float64,
                       telemetry=_CapTel()).run(chunk=4)
        eng = EnsembleEngine(EnsembleSpec(base=p, **kw),
                             dtype=jnp.float64, telemetry=_CapTel())
        calls = {"n": 0}
        real = jax.device_get

        def counted(x, _c=calls, _r=real):
            _c["n"] += 1
            return _r(x)

        with monkeypatch.context() as m:
            m.setattr(jax, "device_get", counted)
            eng.run(chunk=4)
        assert eng.run_complete()
        counts[name] = calls["n"]
    assert counts["off"] == counts["armed"] == 2, counts


def test_bench_ensemble_poison_degrades_to_quarantine_count(
        monkeypatch):
    """BENCH_ENS_POISON=J: one poisoned member degrades the ensemble
    sub-bench to a quarantined count (healthy-member throughput)
    instead of erroring the capture."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    monkeypatch.setenv("BENCH_ENS_LEVEL", "4")
    monkeypatch.setenv("BENCH_ENS_STEPS", "2")
    monkeypatch.setenv("BENCH_ENS_BATCHES", "1,4")
    monkeypatch.setenv("BENCH_ENS_POISON", "1")
    marks = []
    p = _hydro_params(nstepmax=8)
    d = bench.bench_ensemble(p, jnp.float32, jnp,
                             hb=lambda *a, **k: marks.append(a))
    assert d["quarantined"] == 1
    assert d["per_batch"]["4"]["quarantined"] == 1
    assert d["per_batch"]["1"]["quarantined"] == 0   # member 1 absent
    assert d["per_batch"]["4"]["scenarios_per_sec"] > 0
    assert any(a and a[0] == "quarantine" for a in marks)


# ---------------------------------------------------------------------
# queue failure log + serve heartbeat / partial completion
# ---------------------------------------------------------------------
def test_queue_failure_log_accumulates_across_requeues(tmp_path):
    tel = _CapTel()
    q = str(tmp_path / "q")
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-log")
    job = jq.claim(q, worker="w1")
    jq.requeue(job, error="first boom", telemetry=tel)
    job = jq.claim(q, worker="w2")
    jq._age_heartbeat(job.path, 3600.0)
    assert jq.reclaim_stale(q, stale_s=300.0, max_attempts=3,
                            log=None, telemetry=tel) == 1
    j = jq.job_status(q, "job-log")
    assert j.state == "queued"
    assert "error" not in j.record     # stale note is not the verdict
    job = jq.claim(q, worker="w3")
    jq.fail(job, error="final boom", telemetry=tel)
    j = jq.job_status(q, "job-log")
    flog = j.record["failure_log"]
    assert [e["stage"] for e in flog] == ["requeue", "stale", "fail"]
    assert [e["attempt"] for e in flog] == [1, 2, 3]
    assert [e["worker"] for e in flog] == ["w1", "w2", "w3"]
    assert flog[0]["error"] == "first boom"
    assert "no heartbeat" in flog[1]["error"]
    assert j.record["error"] == "final boom"
    assert tel.kinds() == ["queue_requeue", "queue_reclaim",
                           "queue_fail"]
    reclaim = tel.events[1][1]
    assert reclaim["to"] == "queued"
    assert reclaim["heartbeat_age_s"] >= 300.0


def test_serve_idle_prints_queue_counts(tmp_path):
    logs = []
    counts = serve(str(tmp_path / "q"), idle_exit=True,
                   log=logs.append)
    assert counts == {"done": 0, "failed": 0, "requeued": 0}
    assert any("serve: idle, exiting — queued=0 running=0 done=0 "
               "failed=0 parked=0" in m for m in logs)


#: SERVICE_NML with a member-targeted NaN fault + quarantine-only mode:
#: member 1 is evicted at its step 3 while member 0 completes
POISON_NML = (SERVICE_NML
              .replace("&RUN_PARAMS",
                       "&RUN_PARAMS\nfault_inject='nan@3:member=1'")
              .replace("chunk_steps=2",
                       "chunk_steps=2\nmember_quarantine=.true."))


def test_partial_completion_never_requeues(tmp_path):
    """A quarantined member is a property of the job's RESULT, not a
    worker failure: the job lands in done/ with ``failed_members`` on
    the FIRST attempt — the queue never burns an attempt on it."""
    q = str(tmp_path / "q")
    jid = jq.submit(q, POISON_NML, ndim=2, dtype="float64")
    counts = serve(q, worker="t", idle_exit=True, max_attempts=2,
                   log=lambda *a: None)
    assert counts == {"done": 1, "failed": 0, "requeued": 0}
    job = jq.job_status(q, jid)
    assert job.state == "done" and job.record["attempts"] == 1
    assert "failure_log" not in job.record
    res = job.record["result"]
    assert res["partial"] is True
    assert [m["member"] for m in res["failed_members"]] == [1]
    assert res["failed_members"][0]["nstep"] == 3
    kinds = [json.loads(line).get("kind")
             for line in open(res["telemetry"])]
    assert "fault" in kinds and "quarantine" in kinds
    assert "ensemble_done" in kinds


def test_sigterm_mid_ensemble_serve_drain_resume_bitwise(tmp_path):
    """satellite: SIGTERM@K mid-ensemble under ``--serve`` is now a
    graceful DRAIN, not a crash: the worker finishes its chunk, saves
    a checkpoint, hands the job back with a refunded attempt and a
    ``stage="drain"`` failure_log entry, and exits 0.  A second worker
    resumes from the drain checkpoint and the final state — healthy
    member AND the quarantined member's census — is bitwise identical
    to an uninterrupted serve of the same job.  (The SIGTERM lands at
    step 4 — after the nan@3 quarantine is durably in the engine
    state — because a drain checkpoint taken exactly AT a fault's
    trigger step strictly disarms it on resume, by design.)"""
    nml = POISON_NML.replace("nstepmax=4", "nstepmax=8")
    q = str(tmp_path / "q")
    jid = jq.submit(q, nml, ndim=2, dtype="float64")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, RAMSES_FAULT_INJECT="sigterm@4",
               JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1",
               PYTHONPATH=os.pathsep.join(
                   p for p in (root, os.environ.get("PYTHONPATH", ""))
                   if p))
    r = subprocess.run(
        [sys.executable, "-m", "ramses_tpu", "--serve", q,
         "--idle-exit", "--max-attempts", "2"],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=str(tmp_path))
    assert r.returncode == 0, \
        (r.returncode, r.stdout[-2000:], r.stderr[-2000:])
    assert "drain" in (r.stdout + r.stderr)
    job = jq.job_status(q, jid)
    assert job.state == "queued", (job.state, job.record)
    assert [e["stage"] for e in job.record["failure_log"]] == ["drain"]
    # the drain refunds the attempt: the handover costs no budget
    assert job.record["attempts"] == 0
    logs = []
    counts = serve(q, worker="resumer", idle_exit=True, max_attempts=2,
                   log=logs.append)
    assert counts == {"done": 1, "failed": 0, "requeued": 0}
    assert any("auto-resume from" in m or "resuming from" in m
               for m in logs), \
        "the next claim must resume from the drain checkpoint"
    job = jq.job_status(q, jid)
    assert job.state == "done" and job.record["attempts"] == 1
    res = job.record["result"]

    # uninterrupted twin of the same job (fresh queue, no env fault)
    q2 = str(tmp_path / "q2")
    jid2 = jq.submit(q2, nml, ndim=2, dtype="float64")
    counts2 = serve(q2, worker="twin", idle_exit=True, max_attempts=2,
                    log=lambda *a: None)
    assert counts2 == {"done": 1, "failed": 0, "requeued": 0}
    res2 = jq.job_status(q2, jid2).record["result"]
    a = np.load(os.path.join(res["snapshot"], "ensemble_state.npz"))
    b = np.load(os.path.join(res2["snapshot"], "ensemble_state.npz"))
    # both lanes bitwise — the healthy member's full history AND the
    # quarantined member's restored last-clean state
    assert a["g0_s0"].tobytes() == b["g0_s0"].tobytes()
    assert a["g0_t"].tobytes() == b["g0_t"].tobytes()
    assert np.array_equal(a["g0_nstep"], b["g0_nstep"])
    fm = [{k: v for k, v in m.items() if k != "dump"}
          for m in res["failed_members"]]
    fm2 = [{k: v for k, v in m.items() if k != "dump"}
           for m in res2["failed_members"]]
    assert fm == fm2 and fm[0]["member"] == 1 and fm[0]["nstep"] == 3


def test_shipped_ensemble_namelist_through_cli(tmp_path, monkeypatch):
    """The shipped sedov_ensemble.nml runs through the CLI and writes a
    snapshot — its slot in the tests/test_namelist_suite.py coverage
    contract (that suite's level clamp would break the uniform-grid
    requirement, so the shrink here keeps levelmin == levelmax)."""
    import re

    from ramses_tpu.__main__ import main
    src = os.path.join(os.path.dirname(__file__), "..", "namelists",
                       "sedov_ensemble.nml")
    txt = open(src).read()
    txt = re.sub(r"levelmin=\d+", "levelmin=4", txt)
    txt = re.sub(r"levelmax=\d+", "levelmax=4", txt)
    txt = re.sub(r"nstepmax=\d+", "nstepmax=2", txt)
    nml = tmp_path / "sedov_ensemble.nml"
    nml.write_text(txt)
    monkeypatch.chdir(tmp_path)
    assert main([str(nml), "--ndim", "2", "--dtype", "float64"]) == 0
    outs = [d for d in os.listdir(tmp_path) if d.startswith("output_")]
    assert outs, "ensemble CLI run wrote no snapshot"
