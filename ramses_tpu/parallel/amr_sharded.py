"""Multi-device AMR: level batches sharded over the device mesh.

Design (SURVEY.md §2.12 P1-P4): each level's dense cell batch
``[ncell_pad, nvar]`` is a global-view jax.Array sharded by rows over a
1D "oct" mesh axis.  Rows follow the Morton/Hilbert key order, so equal
row-splits are compact spatial domains (P1) that are balanced by
construction — the reference's cost-weighted ``cmp_new_cpu_map``
re-partition (P4) degenerates to "re-sort after refinement", which the
regrid pass already does.  Stencil gathers that cross shard boundaries
become compiler-inserted collectives (P2/P3); CFL min-reduction is a
``jnp.min`` → ``AllReduce`` (P7).

Cost weights (P4): the reference decomposes SPACE once — one Hilbert
interval per rank spanning all levels — so a rank owning more fine
octs does 2^(l-lmin)× more substep work, and ``load_balance`` must
weight the cuts by measured cost (``amr/load_balance.f90:285``).
Here every LEVEL is row-sharded independently, so equal splits already
balance the SWEEP work; what they do NOT balance is per-oct cost that
varies within a level (particles piled into a few octs) or the
trailing-pad remainder of skewed partial levels.  The opt-in
``&AMR_PARAMS load_balance`` path (:mod:`ramses_tpu.parallel.balance`)
closes that: at regrid time each partial level's rows are re-laid-out
as per-device contiguous Hilbert-key ranges whose summed cost
(solver sweeps + particle counts) is balanced within the
bucket-padding bound, and the explicit comm schedules below are
rebuilt against the new cuts.

Two comm backends coexist: the default global-view formulation (GSPMD
inserts the collectives) and, with ``explicit_comm=True``, precomputed
per-shard halo schedules for partial levels — ring-offset halos plus a
deterministic owner-fold, rebuilt at regrid like the reference's
``build_comm`` (:mod:`ramses_tpu.parallel.amr_comm`; the uniform
path's analogue is :mod:`ramses_tpu.parallel.halo`).  Complete levels
take the EXPLICIT slab-sharded dense path whenever the level is a
fully periodic unpadded power-of-two cube on a power-of-two device
count (:mod:`ramses_tpu.parallel.dense_slab`): shard-local bitperm +
ring halos, so the GSPMD partitioner never sees the bit-interleaved
transpose that previously degenerated to involuntary full
rematerialization (MULTICHIP_r05).  Levels outside that envelope keep
the global-view sweep with compiler-inserted collectives.

Every explicit ring halo above rides the backend-dispatched exchange
engine (:mod:`ramses_tpu.parallel.dma_halo`): Pallas async
remote-copy DMA kernels with comm/compute overlap on TPU,
``lax.ppermute`` elsewhere, selected by the ``&AMR_PARAMS
halo_backend`` knob (``auto``/``dma``/``ppermute``) — the two agree
bitwise, so the choice is pure performance.

Fault tolerance is inherited from :class:`~ramses_tpu.amr.hierarchy.
AmrSim` unchanged: atomic manifest-validated dumps, the
``max_step_retries`` non-finite step guard (capture → probe → rollback
with halved dt), and supervised auto-resume all operate on the
host-side level dict, so the retained pre-step state re-shards exactly
like fresh init when a retry or restore replays it onto the mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import Params
from ramses_tpu.parallel.mesh import oct_mesh


class ShardedAmrSim(AmrSim):
    """AmrSim with per-level state sharded over an ``oct`` mesh axis."""

    # row-sharded partial levels take the gather-fused blocked tile
    # sweep too: tile tables are row-sharded like the stencil ones and
    # FusedSpec.pallas_tiles=False forces the XLA tile formulation, so
    # GSPMD partitions the compact tile batch the same way it used to
    # partition the 6^d gather (explicit-comm schedules still take the
    # stencil path — see AmrSim._block_level_ok)
    _oct_blocked = True

    def __init__(self, params: Params,
                 devices: Optional[Sequence[jax.Device]] = None,
                 dtype=jnp.float32, particles=None, init_tree=None,
                 init_dense_u=None, seed_tracers: bool = True,
                 explicit_comm: bool = False):
        devices = list(devices if devices is not None else jax.devices())
        self.ndev = len(devices)
        self._explicit_comm = explicit_comm and len(devices) > 1
        self.mesh = oct_mesh(devices)
        self._row_sharding = NamedSharding(self.mesh, P("oct"))
        self._row2_sharding = NamedSharding(self.mesh, P("oct", None))
        self._rep_sharding = NamedSharding(self.mesh, P())
        self._warned_rep = set()
        if particles is not None:
            # particle rows shard over the mesh when the lane count
            # divides (deposit gathers/scatters stay global-view, so
            # GSPMD inserts the collectives either way); non-divisible
            # sets replicate — memory stops scaling, so warn at size
            import dataclasses as _dc

            def put(a):
                if (getattr(a, "ndim", 0) >= 1
                        and a.shape[0] % self.ndev == 0):
                    return jax.device_put(
                        a, self._row2_sharding if a.ndim > 1
                        else self._row_sharding)
                return jax.device_put(a, self._rep_sharding)

            n = particles.n
            if n % self.ndev and n > 1_000_000:
                import warnings
                warnings.warn(
                    f"particle count {n} not divisible by the "
                    f"{self.ndev}-device mesh: arrays REPLICATE on "
                    "every device (per-device memory stops scaling); "
                    "pad npartmax to a mesh multiple")
            particles = _dc.replace(
                particles, **{f.name: put(getattr(particles, f.name))
                              for f in _dc.fields(particles)})
        super().__init__(params, dtype=dtype, particles=particles,
                         init_tree=init_tree, init_dense_u=init_dense_u,
                         seed_tracers=seed_tracers)

    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path=None, ncpu: Optional[int] = None) -> str:
        """Per-shard checkpoint files by default (one writer per domain,
        the pario/§2.10 role)."""
        return super().dump(iout, base_dir, namelist_path=namelist_path,
                            ncpu=self.ndev if ncpu is None else ncpu)

    # dump_pario: inherited from AmrSim — every host writes only its
    # addressable shard rows into its own validated shard dirs under
    # the two-phase global commit (io/pario.py format 2), io_group_size
    # bounding concurrent writers (the IOGROUPSIZE ring).  Restore onto
    # ANY device count via AmrSim.from_checkpoint_dir.

    def _slab_spec(self, lvl: int):
        """Explicit slab decomposition for a complete level, or None
        when the level falls outside the slab envelope (non-periodic,
        non-cubic root, padded rows, non-power-of-two mesh) and must
        keep the global-view sweep."""
        from ramses_tpu.parallel import dense_slab
        root = self.root or (1,) * self.cfg.ndim
        shape = tuple(r << lvl for r in root[:self.cfg.ndim])
        ncell_pad = self.maps[lvl].noct_pad * 2 ** self.cfg.ndim
        return dense_slab.build_slab_spec(
            self.mesh, lvl, self.cfg.ndim, shape, ncell_pad,
            self.bc_kinds,
            halo_backend=getattr(self.params.amr, "halo_backend",
                                 "auto"))

    def _noct_pad(self, lvl: int, noct: int) -> int:
        """Bucketed oct count (with the base class's hysteresis) rounded
        to a multiple of the device count (shardable rows; cells stay
        2^d-aligned automatically)."""
        b = super()._noct_pad(lvl, noct)
        if b % self.ndev:
            b += self.ndev - (b % self.ndev)
            self._pad_hist[lvl] = b
        return b

    def _rebuild_maps(self, old_tree=None, old_maps=None, old_dev=None):
        """Base maps + the explicit per-shard comm schedules (the
        ``build_comm`` analogue, parallel/amr_comm.py) for partial
        levels when ``explicit_comm=True``."""
        super()._rebuild_maps(old_tree, old_maps, old_dev)
        if not self._explicit_comm:
            return
        from ramses_tpu.parallel import amr_comm
        specs = getattr(self, "_comm_specs", {})
        self._comm_specs = {}
        for l, m in self.maps.items():
            if m.complete or l <= self.lmin or l - 1 not in self.maps:
                continue
            if "comm" in self.dev[l] and l in specs:
                self._comm_specs[l] = specs[l]     # reused with the maps
                continue
            built = amr_comm.build_sweep_comm(
                m, self.maps[l - 1], self.ndev, self.mesh,
                int(self.params.refine.interpol_type),
                halo_backend=getattr(self.params.amr, "halo_backend",
                                     "auto"))
            if built is None:
                # build_sweep_comm bails only for a 1-device mesh, and
                # _explicit_comm requires ndev > 1 — anything else here
                # would be a silent GSPMD fallback, so refuse loudly
                raise RuntimeError(
                    f"explicit comm schedule missing for partial level "
                    f"{l} on a {self.ndev}-device mesh")
            spec, arrays = built
            self._comm_specs[l] = spec
            sh = NamedSharding(self.mesh, P("oct"))
            self.dev[l]["comm"] = {
                k: jax.device_put(
                    jnp.asarray(v, self.dtype if v.dtype == np.float64
                                else None), sh)
                for k, v in arrays.items()}
        self._spec = None                          # comm is part of the key

    def _place(self, arr, kind: str):
        if kind == "rep":
            return jax.device_put(arr, self._rep_sharding)
        if arr.shape[0] % self.ndev:
            # cells/octs rows must divide the mesh to shard; the
            # bucketed pads normally guarantee that, so a replicated
            # fallback at scale signals a padding bug — say so once
            if arr.shape[0] > 1_000_000 and kind not in self._warned_rep:
                import warnings
                self._warned_rep.add(kind)
                warnings.warn(
                    f"sharded-AMR: a {kind!r} array of {arr.shape[0]} "
                    f"rows is not divisible by the {self.ndev}-device "
                    "mesh and REPLICATES (memory/work stop scaling); "
                    "check the _noct_pad mesh alignment")
            return jax.device_put(arr, self._rep_sharding)
        return jax.device_put(arr, self._row_sharding if arr.ndim == 1
                              else self._row2_sharding)


from ramses_tpu.mhd.amr import MhdAmrSim as _MhdAmrSim  # noqa: E402


class ShardedMhdAmrSim(ShardedAmrSim, _MhdAmrSim):
    """MHD AMR on a device mesh: the sharded state layout / placement /
    slab machinery of :class:`ShardedAmrSim` composed with the CT
    physics of :class:`ramses_tpu.mhd.amr.MhdAmrSim` (cooperative MRO —
    both defer to :class:`~ramses_tpu.amr.hierarchy.AmrSim`).  Complete
    levels run the slab-sharded CT advance
    (:func:`ramses_tpu.parallel.dense_slab.mhd_ct_slab`) with the
    Morton-flat EMF override, so the multichip gate sees no global
    index scatter from the MHD path either."""
