"""Self-gravity ↔ hydro coupling on the uniform grid.

Replicates the reference's per-step gravity sequence
(``amr/amr_step.f90:219-293,423-428``):

  1. remove the half gravity kick applied with the *old* force
     (``synchro_hydro_fine(ilevel, -0.5*dt, 1)``)
  2. solve Poisson for the new potential, compute f = -grad(phi)
  3. add the half kick with the *new* force (``+0.5*dt``)
  4. hydro Godunov step with the gravity predictor in ctoprim
  5. final half kick (``synchro_hydro_fine(+0.5*dt)``, amr_step.f90:427)

The kick updates momentum at fixed internal energy
(``hydro/synchro_hydro_fine.f90:56-141``: eint extracted, momentum kicked,
total energy rebuilt).

Poisson RHS: ``Lap(phi) = fourpi * (rho - mean(rho))`` with
``fourpi = 4*pi`` in code units (G=1) or ``1.5*omega_m*aexp`` under
supercomoving cosmology (``poisson/multigrid_fine_commons.f90:1082-1112``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.hydro.timestep import compute_dt
from ramses_tpu.poisson import force as fmod
from ramses_tpu.poisson import solver as smod
from ramses_tpu.poisson.gravana import cell_centers, gravana


@dataclass(frozen=True)
class GravitySpec:
    """Static gravity configuration (jit-static argument)."""
    enabled: bool = False
    gravity_type: int = 0               # 0: self-gravity; >0: analytic
    gravity_params: Tuple[float, ...] = ()
    solver: str = "fft"                  # fft | mg | cg
    epsilon: float = 1e-4                # &POISSON_PARAMS epsilon
    ncycle: int = 10                     # MG V-cycle cap (MAXITER=10)
    cg_iters: int = 150
    boxlen: float = 1.0
    fourpi: float = 4.0 * 3.14159265358979323846  # rhs factor (cosmo varies)

    @classmethod
    def from_params(cls, p) -> "GravitySpec":
        if not p.run.poisson:
            return cls(enabled=False)
        # solver selection: the reference uses MG below cg_levelmin and CG
        # at/above it (amr/amr_step.f90:250-258); our uniform-grid default
        # is the exact FFT solve, overridable via &POISSON_PARAMS solver=.
        raw = p.raw.get("poisson_params", {}) if p.raw else {}
        default = "cg" if p.poisson.cg_levelmin <= p.amr.levelmin else "fft"
        solver = str(raw.get("solver", default)).strip("'\" ").lower()
        return cls(enabled=True,
                   gravity_type=int(p.poisson.gravity_type),
                   gravity_params=tuple(float(v)
                                        for v in p.poisson.gravity_params),
                   epsilon=float(p.poisson.epsilon),
                   solver=solver,
                   boxlen=float(p.amr.boxlen))


def solve_phi(spec: GravitySpec, rho, dx: float):
    """Potential of the density contrast (zero-mean rhs, periodic)."""
    rhs = spec.fourpi * (rho - jnp.mean(rho))
    if spec.solver == "fft":
        return smod.fft_solve(rhs, dx)
    if spec.solver == "mg":
        return smod.mg_solve(rhs, dx, ncycle=spec.ncycle)
    if spec.solver == "cg":
        return smod.cg_solve(rhs, dx, iters=spec.cg_iters, tol=spec.epsilon)
    raise ValueError(spec.solver)


def gravity_field(spec: GravitySpec, rho, dx: float):
    """Acceleration [ndim, *sp]: analytic model or self-gravity solve."""
    if spec.gravity_type > 0:
        x = cell_centers(rho.shape, dx, dtype=rho.dtype)
        return gravana(x, spec.gravity_type, spec.gravity_params,
                       spec.boxlen)
    phi = solve_phi(spec, rho, dx)
    return fmod.force(phi, dx)


def kick(u, f, dteff, cfg: HydroStatic):
    """Momentum kick at fixed internal energy (synchydrofine1)."""
    r = jnp.maximum(u[0], cfg.smallr)
    ekin_old = sum(0.5 * u[1 + d] ** 2 for d in range(cfg.ndim)) / r
    mom = [u[1 + d] + r * f[d] * dteff for d in range(cfg.ndim)]
    ekin_new = sum(0.5 * m * m for m in mom) / r
    e = u[cfg.ndim + 1] - ekin_old + ekin_new
    return jnp.concatenate(
        [u[0:1], jnp.stack(mom), e[None], u[cfg.ndim + 2:]], axis=0)


@partial(jax.jit, static_argnames=("grid", "spec"))
def grav_hydro_step(grid: UniformGrid, spec: GravitySpec, u, f_old, dt):
    """One coupled gravity+hydro step; returns (u_new, f_new)."""
    cfg = grid.cfg
    u = kick(u, f_old, -0.5 * dt, cfg)
    f = gravity_field(spec, u[0], grid.dx)
    u = kick(u, f, +0.5 * dt, cfg)
    up = bmod.pad(u, grid.bc, cfg, muscl.NGHOST)
    mode = "wrap" if _all_periodic(grid.bc) else "edge"
    fp = _pad_force(f, cfg.ndim, mode)
    grav = [fp[d] for d in range(cfg.ndim)]
    flux, _tmp = muscl.unsplit(up, grav, dt, (grid.dx,) * cfg.ndim, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    u = bmod.unpad(un, cfg.ndim, muscl.NGHOST)
    u = kick(u, f, +0.5 * dt, cfg)
    return u, f


def _all_periodic(bc: bmod.BoundarySpec) -> bool:
    return all(f.kind == bmod.PERIODIC for pair in bc.faces for f in pair)


def _pad_force(f, ndim: int, mode: str, ng: int = muscl.NGHOST):
    """Ghost-pad the force field (wrap for periodic, edge otherwise)."""
    pads = [(0, 0)] * (f.ndim - ndim) + [(ng, ng)] * ndim
    return jnp.pad(f, pads, mode=mode)


@partial(jax.jit, static_argnames=("grid", "spec", "nsteps"))
def run_steps_grav(grid: UniformGrid, spec: GravitySpec, u, f, t, tend,
                   nsteps: int):
    """Advance up to nsteps coupled steps on device (cf. run_steps)."""
    cfg = grid.cfg

    def body(carry, _):
        u, f, t, ndone = carry
        dt = compute_dt(u, [f[d] for d in range(cfg.ndim)], grid.dx, cfg)
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = t < tend
        un, fn = grav_hydro_step(grid, spec, u, f, jnp.where(active, dt, 0.0))
        u = jnp.where(active, un, u)
        f = jnp.where(active, fn, f)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, f, t, ndone), None

    (u, f, t, ndone), _ = jax.lax.scan(body, (u, f, t, jnp.array(0)), None,
                                       length=nsteps)
    return u, f, t, ndone
