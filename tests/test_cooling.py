"""Cooling/microphysics tests.

Anchors: the implicit solver against a brute-force explicit ODE
integration of the same tabulated rate, physical shape of the cooling
function, equilibrium behavior, unconditional stability for huge dt,
polytrope floor, EOS forms, and the driver wiring.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.hydro import cooling as cm
from ramses_tpu.hydro.eos import barotropic_eos_temperature



pytestmark = pytest.mark.smoke

@pytest.fixture(scope="module")
def tables():
    return cm.build_tables(aexp=1.0, J21=0.0)


@pytest.fixture(scope="module")
def tables_uv():
    return cm.build_tables(aexp=0.25, J21=1.0)  # z=3, UV on


def test_cooling_function_shape(tables):
    """Primordial Lambda(T): negligible below 1e4 K, peaks near 1e5 K,
    Bremsstrahlung ~sqrt(T) tail at high T."""
    cool = np.asarray(tables.cool)
    log_T2 = np.asarray(tables.log_T2)
    i_n0 = 80  # nH ~ 1 /cc column
    lam = 10.0 ** cool[i_n0]
    T2 = 10.0 ** log_T2
    assert lam[np.searchsorted(log_T2, 3.0)] < 1e-25   # cold: no cooling
    ipeak = np.argmax(lam)
    # CIE primordial curve: H excitation peak at T≈2e4 K (logT2≈4.3-4.6)
    assert 4.2 < log_T2[ipeak] < 5.7
    # free-free tail slope ~ 0.5 between 1e8 and 1e9
    i1 = np.searchsorted(log_T2, 8.0)
    i2 = np.searchsorted(log_T2, 8.8)
    slope = (np.log10(lam[i2]) - np.log10(lam[i1])) / (log_T2[i2]
                                                       - log_T2[i1])
    assert 0.3 < slope < 0.7


def test_solve_cooling_matches_explicit_ode(tables):
    """The implicit integrator must track a high-resolution explicit
    integration of the same interpolated rate."""
    nH = jnp.asarray([0.1, 1.0, 10.0])
    T2 = jnp.asarray([1e6, 1e6, 1e6])
    one = jnp.ones(3)
    dt_s = 3.15e13  # ~1 Myr
    out = np.asarray(cm.solve_cooling(tables, nH, T2, 0.0 * one, one,
                                      dt_s))

    # explicit reference: many tiny implicit steps through the same entry
    nsub = 4000
    T = np.array([1e6, 1e6, 1e6])
    for _ in range(nsub):
        cur = np.asarray(cm.solve_cooling(tables, nH, jnp.asarray(T),
                                          0.0 * one, one, dt_s / nsub))
        T = cur
    # compare in dex: near the 1e4 K cutoff the rate is extremely steep,
    # so pointwise agreement between time-discretizations is log-scale
    assert np.allclose(np.log10(out), np.log10(T), atol=0.05)


def test_solve_cooling_stability_huge_dt(tables):
    """Stiff limit: dt of a Hubble time must return finite positive T2
    near the thermal equilibrium/floor, never negative."""
    nH = jnp.asarray([1e-4, 1.0, 1e4])
    T2 = jnp.asarray([1e7, 1e7, 1e7])
    one = jnp.ones(3)
    out = np.asarray(cm.solve_cooling(tables, nH, T2, one, one, 4e17))
    assert np.all(np.isfinite(out))
    assert np.all(out > 0.0)
    assert np.all(out < 1e7)   # it cooled


def test_heating_equilibrium_with_uv(tables_uv):
    """With a UV background, low-density gas warms toward ~1e4 K
    photoheating equilibrium instead of cooling to the floor."""
    nH = jnp.asarray([1e-5])
    cold = np.asarray(cm.solve_cooling(tables_uv, nH,
                                       jnp.asarray([100.0]),
                                       jnp.zeros(1), jnp.ones(1), 1e18))
    assert cold[0] > 1e3   # heated by orders of magnitude


def test_metal_cooling_scales(tables):
    nH = jnp.asarray([1.0])
    T2 = jnp.asarray([10 ** 5.3])
    dt = 1e13
    t_prim = np.asarray(cm.solve_cooling(tables, nH, T2, jnp.zeros(1),
                                         jnp.ones(1), dt))[0]
    t_meta = np.asarray(cm.solve_cooling(tables, nH, T2, jnp.ones(1),
                                         jnp.ones(1), dt))[0]
    assert t_meta < t_prim  # metals cool faster


def test_eos_forms():
    nH = jnp.asarray([0.1, 1.0, 10.0, 1000.0])
    iso = np.asarray(barotropic_eos_temperature(nH, "isothermal", 10.0,
                                                1.0, 1.4))
    assert np.allclose(iso, 10.0)
    poly = np.asarray(barotropic_eos_temperature(nH, "polytrope", 10.0,
                                                 1.0, 1.4))
    assert np.allclose(poly, 10.0 * np.asarray(nH) ** 0.4)
    cust = np.asarray(barotropic_eos_temperature(nH, "custom", 10.0,
                                                 1.0, 1.4))
    assert np.allclose(cust[:2], 10.0)
    assert cust[3] > 10.0


def test_cooling_step_energy_decrease(tables):
    """Hot dense box: cooling_step removes thermal energy, leaves kinetic
    energy and mass untouched."""
    from ramses_tpu.hydro.core import HydroStatic
    cfg = HydroStatic(ndim=2, gamma=5.0 / 3.0)
    spec = cm.CoolingSpec(enabled=True, scale_T2=1e7, scale_nH=1.0,
                          scale_t=1e15)
    n = 8
    rho = jnp.ones((n, n))
    vx = 0.3 * jnp.ones((n, n))
    p = jnp.ones((n, n)) * 0.1      # T2 = 1e6/mu-ish after scaling
    u = jnp.stack([rho, rho * vx, jnp.zeros((n, n)),
                   p / (cfg.gamma - 1) + 0.5 * rho * vx ** 2])
    un = cm.cooling_step(u, tables, spec, 1.0, cfg)
    assert float(jnp.max(jnp.abs(un[0] - u[0]))) == 0.0
    assert float(jnp.max(jnp.abs(un[1] - u[1]))) == 0.0
    assert float(un[3].sum()) < float(u[3].sum())
    # kinetic part preserved exactly: E_new - E_old is thermal only
    eint_old = u[3] - 0.5 * rho * vx ** 2
    eint_new = un[3] - 0.5 * rho * vx ** 2
    assert float(jnp.min(eint_new)) > 0.0
    assert float(jnp.max(eint_new / eint_old)) < 1.0


def test_polytrope_floor(tables):
    """With a barotropic floor the gas cannot cool below it."""
    from ramses_tpu.hydro.core import HydroStatic
    cfg = HydroStatic(ndim=1, gamma=5.0 / 3.0)
    spec = cm.CoolingSpec(enabled=True, scale_T2=1e7, scale_nH=10.0,
                          scale_t=1e18, floor_form="isothermal",
                          T2_eos=3e4)
    rho = jnp.ones((16,))
    p = jnp.ones((16,)) * 0.1
    u = jnp.stack([rho, jnp.zeros(16), p / (cfg.gamma - 1)])
    un = cm.cooling_step(u, tables, spec, 10.0, cfg)
    T2 = np.asarray((cfg.gamma - 1) * un[2] / un[0] * spec.scale_T2)
    assert np.all(T2 > 0.9 * 3e4)


def test_driver_wiring(tmp_path):
    """A sedov-like hot blast with cooling on runs and loses energy."""
    from ramses_tpu.driver import Simulation
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "point"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "length_x": [10.0, 1.0], "length_y": [10.0, 1.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.0],
                        "p_region": [1e-3, 20.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc"},
        "cooling_params": {"cooling": True},
        "units_params": {"units_density": 1.66e-24, "units_time": 3.15e13,
                         "units_length": 3.086e18},
        "output_params": {"noutput": 1, "tout": [0.02], "tend": 0.02},
    }
    p = params_from_dict(groups, ndim=2)
    sim = Simulation(p, dtype=jnp.float64)
    from ramses_tpu.grid.uniform import totals
    e0 = float(totals(sim.state.u, sim.cfg, sim.dx)["energy"])
    sim.evolve()
    e1 = float(totals(sim.state.u, sim.cfg, sim.dx)["energy"])
    assert sim.state.nstep > 0
    assert e1 < e0
    assert np.all(np.isfinite(np.asarray(sim.state.u)))
