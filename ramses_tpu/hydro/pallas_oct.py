"""Fused MUSCL-Hancock TPU kernel for AMR oct-stencil batches (Pallas).

The partial-level sweep (``godfine1`` on an incomplete level,
``hydro/godunov_fine.f90:486-910``) runs on gathered ``[nvar, 6,6,6,
noct]`` stencil blocks (:func:`ramses_tpu.amr.kernels.level_sweep`).
The XLA formulation materializes ~60 block-sized intermediates in HBM;
at a few thousand octs that traffic — not the flops — is the whole cost,
and on the Sedov benchmark the fine-level sweeps end up costing as much
as the complete base level's fused kernel.  This kernel keeps every
intermediate in VMEM: HBM sees one read of the stencil block (+ mask)
and one write of (du, coarse-correction fluxes).

Layout: the oct axis is minor (lane dimension, 128-multiple — the
bucket padding guarantees this beyond tiny levels); the three 6-cell
stencil axes lead.  Neighbour access is ``jnp.roll`` along the leading
axes, wrap-around junk confined to stencil cells the 2³ interior never
consumes — exactly the XLA path's contract.

Scope (gated by :func:`available` / :func:`tile_available`, falls
back to the XLA formulation otherwise): ndim=3 hydro,
nener=npassive=0, no pressure_fix, scheme=muscl, slope_type∈{1,2,8},
riemann∈{llf, hllc}, f32, single device.  The gate only selects the
KERNEL, not the blocked decomposition: sharded meshes, f64, and MHD
still run the blocked Morton-tile sweep in its XLA formulation
(``FusedSpec.pallas_tiles=False``; ``mhd/amr.py mhd_tile_sweep``),
bitwise-identical to this kernel where both apply.  Self-gravity
needs NO kernel support: the hierarchy applies
it as a separate traced half-kick around the sweep
(``kick_flat`` — ``amr/hierarchy.py _advance_traced``), so gravity
production runs take this kernel too.  ``want_flux=True`` adds the MC
gas-tracer per-cell face mass-flux capture as a third output
(``godunov_fine.f90:685-715``), covering tracer runs as well.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.hydro.pallas_muscl import (DISABLED, _hllc_flux, _llf_flux,
                                           _slopes)

# jax renamed TPUCompilerParams → CompilerParams between releases
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


# Test hook: force the kernel branch on any backend, run it in Pallas
# interpreter mode — lets CI drive level_sweep's REAL pallas branch (not
# a replica) on the CPU test backend.  Module attribute so tests can
# monkeypatch; also settable via env for whole-suite sweeps.
FORCE_INTERPRET = bool(__import__("os").environ
                       .get("RAMSES_PALLAS_OCT_INTERPRET"))


def available(cfg: HydroStatic, noct_pad: int, dtype) -> bool:
    """Availability gate for the oct-batch kernel (see module docstring;
    the single-device restriction mirrors ``pallas_muscl.kernel_available``
    — sharded levels keep the XLA formulation so GSPMD can partition;
    with blocking on they still get the compact tile batch)."""
    if DISABLED:
        return False
    if not FORCE_INTERPRET and (jax.default_backend() != "tpu"
                                or jax.device_count() != 1):
        return False
    if getattr(cfg, "physics", "hydro") != "hydro":
        return False
    if cfg.ndim != 3 or cfg.nener != 0 or cfg.npassive != 0:
        return False
    if cfg.pressure_fix or cfg.scheme != "muscl":
        return False
    if cfg.slope_type not in (1, 2, 8):
        return False
    if cfg.riemann not in ("llf", "hllc"):
        return False
    if dtype not in (jnp.float32, jnp.dtype("float32")):
        return False
    return noct_pad % 128 == 0


def _tile(noct_pad: int) -> int:
    """Lane-tile size: ~45 live [6,6,6,NT] f32 arrays must fit VMEM."""
    for nt in (512, 256, 128):
        if noct_pad % nt == 0:
            return nt
    raise AssertionError("gated by available()")


def _make_kernel(cfg: HydroStatic, dx: float, want_flux: bool = False):
    """Kernel body; refs: u [5,6,6,6,NT], ok [6,6,6,NT] (state-dtype
    0/1 refined mask), dt [1,1] SMEM → du [5,2,2,2,NT] (interior
    update), corr [5,3,2,NT] (dt/dx-scaled boundary-face flux sums)
    [, phi [3,2,2,2,2,NT] (d, side, interior) dt/dx-scaled per-cell
    face MASS fluxes — the MC-tracer capture]."""
    st = cfg.slope_type
    theta = float(getattr(cfg, "slope_theta", 1.5))
    solver = _llf_flux if cfg.riemann == "llf" else _hllc_flux
    core = (slice(2, 4), slice(2, 4), slice(2, 4))

    def kernel(u_ref, ok_ref, dt_ref, du_ref, corr_ref, *phi_ref):
        dt = dt_ref[0, 0]
        # ---- ctoprim ----
        r = jnp.maximum(u_ref[0], cfg.smallr)
        ir = 1.0 / r
        v = [u_ref[1] * ir, u_ref[2] * ir, u_ref[3] * ir]
        ek = 0.5 * (v[0] * v[0] + v[1] * v[1] + v[2] * v[2])
        eint = jnp.maximum(u_ref[4] * ir - ek, cfg.smalle)
        p = (cfg.gamma - 1.0) * r * eint
        q = (r, v[0], v[1], v[2], p)
        # ---- uslope ----
        dq = []
        for d in range(3):
            qm1 = tuple(jnp.roll(c, 1, axis=d) for c in q)
            qp1 = tuple(jnp.roll(c, -1, axis=d) for c in q)
            dq.append(tuple(_slopes(a, b, c, st, theta)
                            for a, b, c in zip(qm1, q, qp1)))
        # ---- trace3d source terms ----
        divv = dq[0][1] + dq[1][2] + dq[2][3]
        adv = lambda comp: (v[0] * dq[0][comp] + v[1] * dq[1][comp]
                            + v[2] * dq[2][comp])
        sr0 = -adv(0) - divv * r
        sp0 = -adv(4) - divv * cfg.gamma * p
        sv0 = [-adv(1 + j) - dq[j][4] * ir for j in range(3)]
        dtdx2 = 0.5 * dt / dx
        okf = ok_ref[:]
        scale = dt / dx

        du = [None] * 5
        for d in range(3):
            def face_state(sgn):
                rho = r + sgn * 0.5 * dq[d][0] + sr0 * dtdx2
                rho = jnp.where(rho < cfg.smallr, r, rho)
                vs = [v[j] + sgn * 0.5 * dq[d][1 + j] + sv0[j] * dtdx2
                      for j in range(3)]
                pp = p + sgn * 0.5 * dq[d][4] + sp0 * dtdx2
                return (rho, vs[0], vs[1], vs[2], pp)
            qm = face_state(+1.0)
            qp = face_state(-1.0)
            ql5 = tuple(jnp.roll(c, 1, axis=d) for c in qm)
            qr5 = qp
            ql5 = (jnp.maximum(ql5[0], cfg.smallr), ql5[1], ql5[2], ql5[3],
                   jnp.maximum(ql5[4], ql5[0] * cfg.smallp))
            qr5 = (jnp.maximum(qr5[0], cfg.smallr), qr5[1], qr5[2], qr5[3],
                   jnp.maximum(qr5[4], qr5[0] * cfg.smallp))
            flux = solver(ql5, qr5, d, cfg)
            # refined-face zeroing (godunov_fine.f90:718-747): a face is
            # dropped when either adjacent cell is refined
            keepf = (1.0 - okf) * (1.0 - jnp.roll(okf, 1, axis=d))
            flux = tuple(f * keepf for f in flux)
            # coarse-correction sums: low face idx 2 / high face idx 4,
            # summed over the 2x2 transverse interior, ×dt/dx
            lo_ix = tuple(2 if dd == d else slice(2, 4) for dd in range(3))
            hi_ix = tuple(4 if dd == d else slice(2, 4) for dd in range(3))
            for c in range(5):
                corr_ref[c, d, 0] = flux[c][lo_ix].sum(axis=(0, 1)) * scale
                corr_ref[c, d, 1] = flux[c][hi_ix].sum(axis=(0, 1)) * scale
                contrib = (flux[c] - jnp.roll(flux[c], -1, axis=d)) * scale
                du[c] = contrib if du[c] is None else du[c] + contrib
            if want_flux:
                # per-cell (low, high) face mass flux: the cell's low
                # face sits at its own stencil slot, its high face at
                # the next slot along d
                phi_ref[0][d, 0] = (flux[0] * scale)[core]
                phi_ref[0][d, 1] = (jnp.roll(flux[0], -1, axis=d)
                                    * scale)[core]
        for c in range(5):
            du_ref[c] = du[c][core]

    return kernel


@partial(jax.jit, static_argnames=("cfg", "dx", "interpret",
                                   "want_flux"))
def oct_sweep(uloc, ok, dt, cfg: HydroStatic, dx: float,
              interpret: bool = False, want_flux: bool = False):
    """Fused partial-level sweep on a gathered stencil batch.

    uloc: [5, 6, 6, 6, N] (N = padded oct count, 128-multiple);
    ok: [6, 6, 6, N] refined-cell mask in the state dtype (0/1).
    Returns (du [5, 2, 2, 2, N], corr [5, 3, 2, N]) with corr already
    ×dt/dx — the :func:`~ramses_tpu.amr.kernels.level_sweep` convention
    — plus, with ``want_flux``, phi [3, 2, 2, 2, 2, N]: per-cell
    (d, side, interior) dt/dx-scaled face mass fluxes (the MC-tracer
    capture).
    """
    n = uloc.shape[-1]
    nt = _tile(n)
    dt2 = jnp.asarray(dt, uloc.dtype).reshape(1, 1)
    kern = _make_kernel(cfg, dx, want_flux)
    interpret = interpret or FORCE_INTERPRET
    out_specs = [
        pl.BlockSpec((5, 2, 2, 2, nt), lambda i: (0, 0, 0, 0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((5, 3, 2, nt), lambda i: (0, 0, 0, i),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((5, 2, 2, 2, n), uloc.dtype),
        jax.ShapeDtypeStruct((5, 3, 2, n), uloc.dtype),
    ]
    if want_flux:
        out_specs.append(
            pl.BlockSpec((3, 2, 2, 2, 2, nt),
                         lambda i: (0, 0, 0, 0, 0, i),
                         memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((3, 2, 2, 2, 2, n), uloc.dtype))
    return pl.pallas_call(
        kern,
        grid=(n // nt,),
        in_specs=[
            pl.BlockSpec((5, 6, 6, 6, nt), lambda i: (0, 0, 0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 6, 6, nt), lambda i: (0, 0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(uloc, ok, dt2)


# ---------------------------------------------------------------------------
# Blocked Morton tile kernel (gather-fused oct path)
# ---------------------------------------------------------------------------

_NG = 2                                   # tile halo width (MUSCL stencil)


def tile_available(cfg: HydroStatic, ntile_pad: int, dtype) -> bool:
    """Availability gate for the blocked tile kernel — same physics scope
    as :func:`available`; tile counts are power-of-2 bucketed (>=8)."""
    if DISABLED:
        return False
    if not FORCE_INTERPRET and (jax.default_backend() != "tpu"
                                or jax.device_count() != 1):
        return False
    if getattr(cfg, "physics", "hydro") != "hydro":
        return False
    if cfg.ndim != 3 or cfg.nener != 0 or cfg.npassive != 0:
        return False
    if cfg.pressure_fix or cfg.scheme != "muscl":
        return False
    if cfg.slope_type not in (1, 2, 8):
        return False
    if cfg.riemann not in ("llf", "hllc"):
        return False
    if dtype not in (jnp.float32, jnp.dtype("float32")):
        return False
    return ntile_pad % 8 == 0


def _tile_nt(ntile_pad: int, td: int) -> int:
    """Lane-tile size: keep slots*lanes near the 6^3 kernel's proven
    VMEM budget (216 slots x 512 lanes)."""
    cap = max(8, (216 * 512) // td ** 3)
    nt = 8
    while nt * 2 <= cap and ntile_pad % (nt * 2) == 0:
        nt *= 2
    return nt


def _make_tile_kernel(cfg: HydroStatic, dx: float, c: int,
                      want_flux: bool = False):
    """Tile-kernel body; refs: u [5,td,td,td,NT], ok [td,td,td,NT],
    dt [1,1] SMEM → du [5,c,c,c,NT] (interior update), corrp
    [5,3,c//2+1,c,c,NT] (dt/dx-scaled per-oct-face flux planes,
    transverse interior, in increasing-dim order) [, phip
    [3,c+1,c,c,NT] (dt/dx-scaled per-cell-face mass-flux planes)].
    Physics body identical to :func:`_make_kernel`; only the geometry
    (interior core, plane outputs) differs."""
    st = cfg.slope_type
    theta = float(getattr(cfg, "slope_theta", 1.5))
    solver = _llf_flux if cfg.riemann == "llf" else _hllc_flux
    o = c // 2
    core = (slice(_NG, _NG + c),) * 3

    def kernel(u_ref, ok_ref, dt_ref, du_ref, corrp_ref, *phi_ref):
        dt = dt_ref[0, 0]
        # ---- ctoprim ----
        r = jnp.maximum(u_ref[0], cfg.smallr)
        ir = 1.0 / r
        v = [u_ref[1] * ir, u_ref[2] * ir, u_ref[3] * ir]
        ek = 0.5 * (v[0] * v[0] + v[1] * v[1] + v[2] * v[2])
        eint = jnp.maximum(u_ref[4] * ir - ek, cfg.smalle)
        p = (cfg.gamma - 1.0) * r * eint
        q = (r, v[0], v[1], v[2], p)
        # ---- uslope ----
        dq = []
        for d in range(3):
            qm1 = tuple(jnp.roll(cc, 1, axis=d) for cc in q)
            qp1 = tuple(jnp.roll(cc, -1, axis=d) for cc in q)
            dq.append(tuple(_slopes(a, b, cc, st, theta)
                            for a, b, cc in zip(qm1, q, qp1)))
        # ---- trace3d source terms ----
        divv = dq[0][1] + dq[1][2] + dq[2][3]
        adv = lambda comp: (v[0] * dq[0][comp] + v[1] * dq[1][comp]
                            + v[2] * dq[2][comp])
        sr0 = -adv(0) - divv * r
        sp0 = -adv(4) - divv * cfg.gamma * p
        sv0 = [-adv(1 + j) - dq[j][4] * ir for j in range(3)]
        dtdx2 = 0.5 * dt / dx
        okf = ok_ref[:]
        scale = dt / dx

        du = [None] * 5
        for d in range(3):
            def face_state(sgn):
                rho = r + sgn * 0.5 * dq[d][0] + sr0 * dtdx2
                rho = jnp.where(rho < cfg.smallr, r, rho)
                vs = [v[j] + sgn * 0.5 * dq[d][1 + j] + sv0[j] * dtdx2
                      for j in range(3)]
                pp = p + sgn * 0.5 * dq[d][4] + sp0 * dtdx2
                return (rho, vs[0], vs[1], vs[2], pp)
            qm = face_state(+1.0)
            qp = face_state(-1.0)
            ql5 = tuple(jnp.roll(cc, 1, axis=d) for cc in qm)
            qr5 = qp
            ql5 = (jnp.maximum(ql5[0], cfg.smallr), ql5[1], ql5[2], ql5[3],
                   jnp.maximum(ql5[4], ql5[0] * cfg.smallp))
            qr5 = (jnp.maximum(qr5[0], cfg.smallr), qr5[1], qr5[2], qr5[3],
                   jnp.maximum(qr5[4], qr5[0] * cfg.smallp))
            flux = solver(ql5, qr5, d, cfg)
            keepf = (1.0 - okf) * (1.0 - jnp.roll(okf, 1, axis=d))
            flux = tuple(f * keepf for f in flux)
            # per-oct-face flux planes at positions _NG + 2k, transverse
            # interior — the 2x2 per-oct sums happen outside the kernel
            for k in range(o + 1):
                ix = tuple(_NG + 2 * k if dd == d else slice(_NG, _NG + c)
                           for dd in range(3))
                for cv in range(5):
                    corrp_ref[cv, d, k] = (flux[cv] * scale)[ix]
            for cv in range(5):
                contrib = (flux[cv] - jnp.roll(flux[cv], -1, axis=d)) * scale
                du[cv] = contrib if du[cv] is None else du[cv] + contrib
            if want_flux:
                # all c+1 cell-face mass-flux planes along d
                for j in range(c + 1):
                    ix = tuple(_NG + j if dd == d else slice(_NG, _NG + c)
                               for dd in range(3))
                    phi_ref[0][d, j] = (flux[0] * scale)[ix]
        for cv in range(5):
            du_ref[cv] = du[cv][core]

    return kernel


@partial(jax.jit, static_argnames=("cfg", "dx", "shift", "interpret",
                                   "want_flux"))
def tile_sweep(ut, ok, dt, cfg: HydroStatic, dx: float, shift: int,
               interpret: bool = False, want_flux: bool = False):
    """Fused partial-level sweep on a compact blocked tile batch.

    ut: [5, td, td, td, N] (td = 2**(shift+1)+4, N = padded tile count);
    ok: [td, td, td, N] refined-cell mask in the state dtype (0/1).
    Returns (du [5, c, c, c, N], corrp [5, 3, c//2+1, c, c, N]) with
    fluxes already ×dt/dx, plus, with ``want_flux``, phip
    [3, c+1, c, c, N].  Per-oct/per-cell reordering happens in the
    caller (:func:`ramses_tpu.amr.kernels.tile_sweep`).
    """
    c = 1 << (shift + 1)
    td = c + 2 * _NG
    o = c // 2
    n = ut.shape[-1]
    nt = _tile_nt(n, td)
    dt2 = jnp.asarray(dt, ut.dtype).reshape(1, 1)
    kern = _make_tile_kernel(cfg, dx, c, want_flux)
    interpret = interpret or FORCE_INTERPRET
    out_specs = [
        pl.BlockSpec((5, c, c, c, nt), lambda i: (0, 0, 0, 0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((5, 3, o + 1, c, c, nt),
                     lambda i: (0, 0, 0, 0, 0, i),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((5, c, c, c, n), ut.dtype),
        jax.ShapeDtypeStruct((5, 3, o + 1, c, c, n), ut.dtype),
    ]
    if want_flux:
        out_specs.append(
            pl.BlockSpec((3, c + 1, c, c, nt),
                         lambda i: (0, 0, 0, 0, i),
                         memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((3, c + 1, c, c, n), ut.dtype))
    return pl.pallas_call(
        kern,
        grid=(n // nt,),
        in_specs=[
            pl.BlockSpec((5, td, td, td, nt), lambda i: (0, 0, 0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((td, td, td, nt), lambda i: (0, 0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(ut, ok, dt2)
