"""Particles on the AMR hierarchy.

The reference attaches particles to tree grids with per-grid linked lists
(``pm/particle_tree.f90:174-646``), deposits their mass level-by-level
with CIC (``cic_amr``, ``pm/rho_fine.f90:343``), interpolates forces back
at each particle's level (``move1``, ``pm/move_fine.f90:193``), and
kick/drifts them inside ``amr_step`` (``amr/amr_step.f90:219-236,
268-273, 479-486``).

TPU-native redesign: no linked lists and no per-grid walks.  Once per
coarse step the host builds *flat CIC index maps* from the sorted-key
octree — for every (particle, CIC corner) the flat cell row of that
corner at each level, or a dump row where the level does not cover the
corner — and the device then runs pure segment-sum deposits and dense
gathers with those maps.  This is the same "metadata pass on the host,
arithmetic on the device" split the hydro sweep uses (the reference
amortizes ``build_comm`` the same way, ``amr/virtual_boundaries.f90``).

Level semantics match the reference:
  * a particle is *assigned* to the finest level whose oct covers it
    (``make_tree_fine``); forces are gathered at that level;
  * its mass is deposited at *every* level that covers it (coverage is
    nested), so each level's Poisson rhs sees all mass in its domain —
    CIC corners falling outside a level's coverage are dropped there,
    like mass leaving the masked MG domain in the reference.

Indices AND weights are built on the host in float64 from one snapshot
of the positions, so they are mutually consistent and the device work is
deterministic segment arithmetic (no float-rounding disagreement between
index builder and weight evaluation).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr.tree import Octree, map_coords


@dataclass
class PmLevelMap:
    """Host-built CIC maps of one level for one position snapshot."""
    lvl: int
    idx: np.ndarray       # [npart, ncorner] int32 flat cell row;
    #                       ncorner = 1|2^d|3^d (ngp/cic/tsc);
    #                       ncell_pad = dump row
    w: np.ndarray         # [npart, ncorner] float64 weights (0=dropped)
    assigned: np.ndarray  # [npart] bool: particle's finest covering level


def assign_levels(tree: Octree, x: np.ndarray, boxlen: float) -> np.ndarray:
    """Finest level whose oct covers each particle (``make_tree_fine``)."""
    n = len(x)
    lv = np.full(n, tree.levelmin, dtype=np.int32)
    for l in range(tree.levelmin + 1, tree.levelmax + 1):
        if not tree.has(l):
            break
        dx_oct = boxlen / (1 << (l - 1))       # oct size at level l
        og = np.floor(x / dx_oct).astype(np.int64)
        og = np.clip(og, 0, (1 << (l - 1)) - 1)
        found = tree.lookup(l, og)
        lv[found >= 0] = l
    return lv


def _stencil_1d(s: np.ndarray, scheme: str):
    """Per-dim (base index, [(offset, weight)]) for one coordinate
    ``s = x/dx`` (cells [i, i+1)).  ``rho_fine``'s CIC plus the NGP and
    TSC alternatives (``pm/rho_fine.f90`` deposition kernels)."""
    if scheme == "ngp":
        return np.floor(s).astype(np.int64), [(0, np.ones_like(s))]
    if scheme == "cic":
        i0 = np.floor(s - 0.5).astype(np.int64)
        f = (s - 0.5) - i0
        return i0, [(0, 1.0 - f), (1, f)]
    if scheme == "tsc":
        ic = np.floor(s).astype(np.int64)
        f = s - (ic + 0.5)                     # in [-0.5, 0.5)
        return ic, [(-1, 0.5 * (0.5 - f) ** 2),
                    (0, 0.75 - f ** 2),
                    (1, 0.5 * (0.5 + f) ** 2)]
    raise ValueError(f"deposit scheme {scheme!r}")


def build_pm_maps(tree: Octree, x: np.ndarray, boxlen: float,
                  bc_kinds: List[tuple],
                  ncell_pad: Dict[int, int],
                  scheme: str = "cic") -> Dict[int, PmLevelMap]:
    """Deposition index/weight maps for every populated level.

    ``x`` is a host float64 snapshot of positions; ``ncell_pad[l]`` the
    padded flat-cell count of the level batch (its value doubles as the
    dump row index); ``scheme`` ∈ ngp|cic|tsc selects the kernel (1,
    2^d, or 3^d corners per particle).
    """
    import itertools

    ndim = tree.ndim
    ttd = 1 << ndim
    if any(k == 1 for pair in bc_kinds for k in pair):
        # reflecting walls need the wall-normal force sign flip on
        # mirrored corners and a bouncing (not wrapping) drift — neither
        # is implemented; reject loudly rather than silently mis-force
        raise NotImplementedError(
            "AMR particles: reflecting boundaries unsupported")
    # open (outflow/inflow) dims: corners falling outside the box are
    # dropped — mass near the edge leaks like in the reference's
    # isolated runs; escaped particles are deactivated by the drift
    open_dim = [bc_kinds[d] != (0, 0) for d in range(ndim)]
    levels = assign_levels(tree, x, boxlen)
    out: Dict[int, PmLevelMap] = {}
    for l in range(tree.levelmin, tree.levelmax + 1):
        if not tree.has(l):
            break
        dx = boxlen / (1 << l)
        base = []
        offw = []
        for d in range(ndim):
            i0, ow = _stencil_1d(x[:, d] / dx, scheme)
            base.append(i0)
            offw.append(ow)
        npart = len(x)
        ncorner = len(offw[0]) ** ndim
        idx = np.full((npart, ncorner), ncell_pad[l], dtype=np.int32)
        w = np.zeros((npart, ncorner), dtype=np.float64)
        nl = 1 << l
        base_cc = np.stack(base, axis=1)
        for corner, combo in enumerate(
                itertools.product(*[range(len(ow)) for ow in offw])):
            cc = base_cc.copy()
            wc = np.ones(npart, dtype=np.float64)
            for d, k in enumerate(combo):
                off, wd = offw[d][k]
                cc[:, d] += off
                wc = wc * wd
            oob = np.zeros(npart, dtype=bool)
            for d in range(ndim):
                if open_dim[d]:
                    oob |= (cc[:, d] < 0) | (cc[:, d] >= nl)
            cc, _refl = map_coords(cc, l, bc_kinds, ndim)
            wc = np.where(oob, 0.0, wc)
            og = cc >> 1
            oi = tree.lookup(l, og)
            off = np.zeros(npart, dtype=np.int64)
            for d in range(ndim):
                off = (off << 1) | (cc[:, d] & 1)
            hit = oi >= 0
            idx[hit, corner] = (oi[hit] * ttd + off[hit]).astype(np.int32)
            w[:, corner] = np.where(hit, wc, 0.0)
        out[l] = PmLevelMap(lvl=l, idx=idx, w=w, assigned=(levels == l))
    return out


@partial(jax.jit, static_argnames=("ncell_pad",))
def deposit_flat(idx, w, m, active, ncell_pad: int, cell_vol):
    """Segment-sum CIC mass deposition into a flat level batch.

    Returns density [ncell_pad] (the dump row is discarded)."""
    contrib = (m * active)[:, None] * w
    rho = jnp.zeros((ncell_pad + 1,), w.dtype)
    rho = rho.at[idx.reshape(-1)].add(contrib.reshape(-1))
    return rho[:ncell_pad] / cell_vol


@jax.jit
def gather_flat(field, idx, w, mask):
    """Inverse-CIC gather of a per-cell field at mapped positions.

    ``field`` [ncell_pad, ncomp]; ``idx``/``w`` [npart, ncorner];
    returns [npart, ncomp], zero rows for particles with ``mask`` False
    (their corners may carry dump-row indices from another level's
    map)."""
    ext = jnp.concatenate(
        [field, jnp.zeros((1, field.shape[1]), field.dtype)])
    vals = ext[idx]                            # [npart, 2^d, ncomp]
    out = jnp.sum(vals * w[..., None], axis=1)
    return jnp.where(mask[:, None], out, 0.0)
