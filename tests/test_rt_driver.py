"""RT inside the main driver (rt=.true.) + multigroup/helium chemistry.

Oracles: the classical Stromgren solution through the full namelist →
``Simulation`` path (the reference's ``tests/rt/stromgren2d`` in 3D
analytic form), and physical sanity of the SED-integrated group
properties and the 3-ion ladder.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import load_params
from ramses_tpu.rt import chem as chem_mod
from ramses_tpu.rt import spectra
from ramses_tpu.rt.driver import stromgren_radius

NML = "namelists/stromgren3.nml"


def test_blackbody_group_props():
    g3 = spectra.blackbody_groups(1e5, spectra.DEFAULT_BOUNDS)
    assert len(g3) == 3
    # group 1 (13.6-24.6 eV) ionizes HI but (essentially) not He —
    # the 24.59 eV bound sits a sliver above the 24.5874 eV threshold
    assert g3[0].sigmaN[0] > 1e-18
    assert g3[0].sigmaN[1] < 1e-20 and g3[0].sigmaN[2] == 0.0
    # group 2 reaches HeI, group 3 reaches HeII (boundary slivers again)
    assert g3[1].sigmaN[1] > 1e-18 and g3[1].sigmaN[2] < 1e-21
    assert g3[2].sigmaN[2] > 1e-19
    # mean photon energies sit inside their bounds and increase
    EV = spectra.EV
    for g in g3:
        assert g.e_lo * EV < g.e_photon
    assert g3[0].e_photon < g3[1].e_photon < g3[2].e_photon
    # photon shares sum to one, softest group dominates a 1e5 K SED
    assert sum(g.frac for g in g3) == pytest.approx(1.0, rel=1e-6)
    assert g3[0].frac > 0.4


def test_3ion_ladder_equilibrium():
    """Strong ionizing field fully ionizes H and He; no field lets it
    recombine — the chem ladder must move both ways."""
    groups = spectra.blackbody_groups(1e5, spectra.DEFAULT_BOUNDS)
    shape = (8,)
    nH = jnp.full(shape, 1e-3)
    nHe = nH * 0.0789            # Y=0.24
    T = jnp.full(shape, 2e4)
    xs = (jnp.full(shape, 1e-3), jnp.full(shape, 1e-3),
          jnp.full(shape, 1e-6))
    c_red = 3e6
    for _ in range(40):
        # a source resupplies an intense field every step
        Ns = [jnp.full(shape, 1e-2) for _ in groups]
        Ns, xs, T = chem_mod.chem_step_3ion(Ns, xs, T, nH, nHe, 1e13,
                                            c_red, groups)
    xH, xHe2, xHe3 = [np.asarray(v) for v in xs]
    assert (xH > 0.99).all()
    assert (xHe3 > 0.9).all()            # hard field doubly ionizes He
    # switch the field off: recombination pulls H back down
    Ns0 = [jnp.zeros(shape) for _ in groups]
    T = jnp.full(shape, 1e4)
    xs2 = xs
    for _ in range(40):
        Ns0, xs2, T = chem_mod.chem_step_3ion(Ns0, xs2, T, nH, nHe,
                                              1e13, c_red, groups,
                                              heating=False)
    assert (np.asarray(xs2[0]) < np.asarray(xs[0])).all()


def test_stromgren_through_driver():
    """rt=.true. namelist → Simulation: ionized volume matches the
    analytic Stromgren growth at t = 0.5 t_rec."""
    from ramses_tpu.driver import Simulation

    p = load_params(NML, ndim=3)
    sim = Simulation(p, dtype=jnp.float64)
    assert sim.rt is not None
    sim.evolve(verbose=False)
    t = sim.state.t
    nH = 1e-2
    ndot = 5e48
    # recombination balance is set by the IONIZED gas temperature
    # (photoheated): evaluate alpha_B there
    xf0 = np.asarray(sim.rt.sim.x)
    Tf = np.asarray(sim.rt.sim.T)
    T_ion = float(np.median(Tf[xf0 > 0.9])) if (xf0 > 0.9).any() else 1e4
    rs = stromgren_radius(ndot, nH, T=T_ion)
    t_rec = 1.0 / (float(chem_mod.alpha_B(jnp.asarray(T_ion))) * nH)
    v_exp = 4.0 / 3.0 * np.pi * rs ** 3 * (1.0 - np.exp(-t / t_rec))
    # x²-weighted volume: the recombination-balance measure (∫αx²nH²dV
    # = consumed rate) — ∫x dV overcounts the GLF-diffused front
    xf = np.asarray(sim.rt.sim.x)
    v_got = float((xf ** 2).sum()) * sim.rt.sim.dx ** 3
    assert v_got == pytest.approx(v_exp, rel=0.3)
    # photoheating raised the ionized gas temperature and the gas
    # energy feedback made it into the hydro state
    assert T_ion < 5e4
    hot = Tf[xf0 > 0.9]
    assert hot.size and np.median(hot) > 5e3
    u = np.asarray(sim.state.u)
    eint0 = 1.38e-15 / (sim.cfg.gamma - 1.0)
    assert np.max(u[4]) > 1.5 * eint0     # heated cells


def test_rt_photon_budget_stats():
    """``rt_stats`` (the reference ``output_rt_stats`` role): cumulative
    injected photons, photons in the box, and their conservation ratio
    — and the screen block prints them."""
    from ramses_tpu.driver import Simulation
    from ramses_tpu.utils.ops import OpsGuard

    p = load_params(NML, ndim=3)
    p.amr.levelmin = p.amr.levelmax = 4     # shrink for speed
    p.output.tout = [4e13]
    sim = Simulation(p, dtype=jnp.float64)
    assert sim.rt is not None
    sim.evolve(verbose=False)
    st = sim.rt.rt_stats()
    assert set(st) >= {"photons", "injected", "ratio"}
    # the source injected ndot*t photons; what's still in the box is
    # positive and no more than that (absorption only removes)
    assert st["injected"] > 0.0
    assert 0.0 < st["photons"] <= st["injected"] * 1.05
    assert st["ratio"] == pytest.approx(st["photons"] / st["injected"])
    line = OpsGuard(sim, install_signals=False).screen_block()
    assert " rt[" in line and "ratio=" in line


def test_rt_cli_smoke(tmp_path, capsys):
    """python -m ramses_tpu with rt=.true. runs end to end."""
    from ramses_tpu.__main__ import main

    p = load_params(NML, ndim=3)
    import shutil
    nml2 = tmp_path / "strom.nml"
    shutil.copy(NML, nml2)
    # shrink for speed: fewer cells, earlier stop
    text = nml2.read_text().replace("levelmin=5", "levelmin=4") \
        .replace("levelmax=5", "levelmax=4") \
        .replace("tout=1.9e14", "tout=4e13")
    nml2.write_text(text)
    import os
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        assert main([str(nml2), "--ndim", "3", "--dtype", "float64"]) == 0
    finally:
        os.chdir(cwd)
