"""AMR machinery validation.

The decisive oracle is the reference suite's own invariance trick
(SURVEY.md §4.3): decomposition must not change physics.  Here the
decompositions compared are *mesh* decompositions —
(a) a fully-refined two-level hierarchy must reproduce the uniform fine
grid, (b) conservation must hold to machine precision across coarse-fine
boundaries (the flux-correction path), (c) an adaptive Sod run must beat
the coarse uniform run against the exact Riemann solution.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.amr import keys as kmod
from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.amr.tree import Octree
from ramses_tpu.config import params_from_string
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid, step as ustep
from ramses_tpu.init.regions import condinit
from tests.exact_riemann import exact_riemann

SOD = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmax}
boxlen=1.0
/
&BOUNDARY_PARAMS
nboundary=2
ibound_min=-1,+1
ibound_max=-1,+1
bound_type= 2, 2
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='square'
x_center=0.25,0.75
length_x=0.5,0.5
d_region=1.0,0.125
p_region=1.0,0.1
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
slope_type=1
riemann='hllc'
/
&REFINE_PARAMS
err_grad_d={err}
err_grad_p={err}
/
"""

SEDOV2D = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmax}
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.7
slope_type=1
riemann='llf'
/
&REFINE_PARAMS
err_grad_p={err}
/
"""


def test_morton_roundtrip():
    rng = np.random.default_rng(0)
    for ndim in (1, 2, 3):
        ig = rng.integers(0, 2 ** 20 if ndim < 3 else 2 ** 20,
                          size=(1000, ndim))
        ks = kmod.encode(ig, ndim)
        back = kmod.decode(ks, ndim)
        assert np.array_equal(back, ig)
        # ordering is a total order (unique keys for unique coords)
        assert len(np.unique(ks)) == len(np.unique(ig, axis=0))


def _full_tree(ndim, lmin, lmax):
    """Every level fully refined."""
    t = Octree.base(ndim, lmin, lmax)
    for l in range(lmin + 1, lmax + 1):
        n = 1 << (l - 1)
        ax = np.arange(n, dtype=np.int64)
        grids = np.meshgrid(*([ax] * ndim), indexing="ij")
        t.set_level(l, np.stack([g.ravel() for g in grids], axis=1))
    return t


@pytest.mark.smoke
@pytest.mark.parametrize("ndim", [1, 2])
def test_fully_refined_matches_uniform(ndim):
    """Two-level hierarchy, everything refined: leaf level must evolve
    exactly as the uniform fine grid (gather/scatter machinery is a
    no-op re-indexing in this limit)."""
    lmin, lmax = 4, 5
    nml = SOD.format(lmin=lmin, lmax=lmax, err=-1.0)
    p = params_from_string(nml, ndim=ndim)
    tree = _full_tree(ndim, lmin, lmax)
    sim = AmrSim(p, dtype=jnp.float64, init_tree=tree)

    cfg = sim.cfg
    nfine = 1 << lmax
    dxf = 1.0 / nfine
    grid = UniformGrid(cfg=cfg, shape=(nfine,) * ndim, dx=dxf,
                       bc=bmod.BoundarySpec.from_params(p))
    u = jnp.asarray(condinit((nfine,) * ndim, dxf, p, cfg))

    dt = 1e-3
    for _ in range(4):
        sim.step_coarse(2 * dt)
        u = ustep(grid, u, dt)
        u = ustep(grid, u, dt)

    x, ul = sim.leaf_sample(lmax)
    assert len(ul) == nfine ** ndim
    # reorder leaf cells to grid order
    idx = np.zeros(len(x), dtype=np.int64)
    cc = np.round(np.asarray(x) / dxf - 0.5).astype(np.int64)
    for d in range(ndim):
        idx = idx * nfine + cc[:, d]
    uref = np.moveaxis(np.asarray(u), 0, -1).reshape(-1, cfg.nvar)
    assert np.array_equal(np.sort(idx), np.arange(len(uref)))
    err = np.abs(ul[np.argsort(idx)] - uref)
    assert np.max(err) < 1e-11


@pytest.mark.slow
def test_conservation_2d_sedov_amr():
    """Mass & energy conserved to machine precision through refinement,
    subcycling, and flux correction (periodic box)."""
    p = params_from_string(SEDOV2D.format(lmin=4, lmax=6, err=0.1), ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    assert sim.tree.has(6)          # blast refined to finest
    t0 = sim.totals()
    sim.evolve(0.02)
    t1 = sim.totals()
    assert sim.nstep > 2
    assert abs(t1[0] - t0[0]) < 1e-12 * abs(t0[0])
    assert abs(t1[3] - t0[3]) < 1e-11 * abs(t0[3])


def test_gradedness_invariant():
    """Every oct's 3^ndim father-cell neighbourhood exists (2:1 rule,
    ``amr/flag_utils.f90:213``)."""
    p = params_from_string(SEDOV2D.format(lmin=4, lmax=6, err=0.1), ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    tree = sim.tree
    for l in sim.levels():
        if l == sim.lmin:
            continue
        og = tree.levels[l].og
        for offs in itertools.product((-1, 0, 1), repeat=2):
            nc = og + np.asarray(offs)
            nc = np.mod(nc, 1 << (l - 1))      # periodic box
            f = tree.lookup(l - 1, nc >> 1)
            assert (f >= 0).all(), f"level {l} offset {offs}"


@pytest.mark.slow
def test_sod_amr_beats_coarse():
    """Adaptive 1D Sod: leaf solution closer to the exact Riemann
    solution than the uniform levelmin run."""
    tend = 0.14
    p = params_from_string(SOD.format(lmin=5, lmax=8, err=0.05), ndim=1)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(tend)

    # piece together leaf profile
    xs, ds = [], []
    for l in sim.levels():
        x, u = sim.leaf_sample(l)
        xs.append(x[:, 0])
        ds.append(u[:, 0])
    x = np.concatenate(xs)
    d = np.concatenate(ds)
    order = np.argsort(x)
    x, d = x[order], d[order]
    dex = exact_riemann(1.0, 0.0, 1.0, 0.125, 0.0, 0.1, 1.4, x, tend)[0]
    l1_amr = np.mean(np.abs(d - dex))

    pc = params_from_string(SOD.format(lmin=5, lmax=5, err=-1.0), ndim=1)
    simc = AmrSim(pc, dtype=jnp.float64)
    simc.evolve(tend)
    xc, uc = simc.leaf_sample(5)
    dexc = exact_riemann(1.0, 0.0, 1.0, 0.125, 0.0, 0.1, 1.4,
                         xc[:, 0], tend)[0]
    l1_coarse = np.mean(np.abs(uc[:, 0] - dexc))

    assert l1_amr < 0.6 * l1_coarse
    assert l1_amr < 0.01


@pytest.mark.slow
def test_outflow_momentum_flux():
    """Waves leaving through outflow boundaries change totals only via
    boundary fluxes — no NaNs, positive density everywhere."""
    p = params_from_string(SOD.format(lmin=5, lmax=7, err=0.05), ndim=1)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(0.25)
    for l in sim.levels():
        _, u = sim.leaf_sample(l)
        assert np.isfinite(u).all()
        assert (u[:, 0] > 0).all()


class TestBitperm:
    """flat↔dense bit-permutation conversion vs the index maps
    (amr/bitperm.py vs LevelMaps.perm/inv_perm)."""

    def _check(self, ndim, lvl):
        import numpy as np

        from ramses_tpu.amr import bitperm
        from ramses_tpu.amr import maps as mapmod
        from ramses_tpu.amr.tree import Octree

        tree = Octree.base(ndim, lvl, lvl)
        m = mapmod.build_level_maps(tree, lvl, [(0, 0)] * ndim)
        assert m.complete
        n = 1 << lvl
        ncell = n ** ndim
        rng = np.random.default_rng(lvl * 10 + ndim)
        rows = rng.standard_normal((ncell, 3)).astype(np.float32)
        dense_ref = rows[m.inv_perm].reshape((n,) * ndim + (3,))
        dense = np.asarray(bitperm.flat_to_dense(
            jnp.asarray(rows), lvl, ndim))
        assert np.array_equal(dense, dense_ref)
        back = np.asarray(bitperm.dense_to_flat(
            jnp.asarray(dense), lvl, ndim))
        assert np.array_equal(back, rows)
        # scalar trailing-free arrays too
        d1 = np.asarray(bitperm.flat_to_dense(
            jnp.asarray(rows[:, 0]), lvl, ndim))
        assert np.array_equal(d1, rows[m.inv_perm, 0].reshape((n,) * ndim))

    def test_3d(self):
        for lvl in (1, 2, 3, 4):
            self._check(3, lvl)

    def test_2d(self):
        for lvl in (1, 2, 3, 5):
            self._check(2, lvl)

    def test_1d(self):
        for lvl in (1, 3, 6):
            self._check(1, lvl)


class TestNonCubicAmr:
    """Non-cubic coarse grids on the hierarchy (VERDICT-r04 Missing #4;
    ``amr/init_amr.f90:37-60`` builds over an arbitrary nx,ny,nz root
    grid)."""

    NML = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmax}
boxlen={boxlen}
nx={nx}
ny={ny}
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='square'
x_center={xc1},{xc2}
y_center={yc},{yc}
length_x={lx},{lx}
length_y=10.0,10.0
exp_region=10.0,10.0
d_region=1.0,0.125
p_region=1.0,0.1
/
&HYDRO_PARAMS
riemann='hllc'
/
&REFINE_PARAMS
err_grad_d=0.05
err_grad_p=0.05
/
&OUTPUT_PARAMS
tend=0.05
/
"""

    def _mk(self, nx, ny, lmin, lmax, boxlen):
        # same PHYSICAL setup on [0,1]^2 whatever the root grid:
        # interface at x=0.5 (plus the periodic seam at 0/1)
        ext = nx * boxlen
        nml = self.NML.format(lmin=lmin, lmax=lmax, boxlen=boxlen,
                              nx=nx, ny=ny, xc1=0.25 * ext / boxlen,
                              xc2=0.75 * ext / boxlen,
                              yc=0.5 * ny, lx=0.5 * ext / boxlen)
        return params_from_string(nml, ndim=2)

    @pytest.mark.slow
    def test_matches_equivalent_cubic_run(self):
        # nx=ny=2, boxlen=0.5, lmin=4  ==  nx=ny=1, boxlen=1, lmin=5:
        # identical cells (dx=1/32 on [0,1]^2), identical physics
        pa = self._mk(2, 2, 4, 5, 0.5)
        pa.init.x_center = [0.25, 0.75]
        pa.init.y_center = [0.5, 0.5]
        pa.init.length_x = [0.5, 0.5]
        pb = self._mk(1, 1, 5, 6, 1.0)
        pb.init.x_center = [0.25, 0.75]
        pb.init.y_center = [0.5, 0.5]
        pb.init.length_x = [0.5, 0.5]
        sa = AmrSim(pa, dtype=jnp.float64)
        sb = AmrSim(pb, dtype=jnp.float64)
        assert sa.tree.cell_dims(4) == (32, 32)
        # same refined geometry: A's level-5 octs at B's level-6 coords
        for la, lb in ((5, 6),):
            ka = set(map(tuple, sa.tree.levels[la].og)) \
                if sa.tree.has(la) else set()
            kb = set(map(tuple, sb.tree.levels[lb].og)) \
                if sb.tree.has(lb) else set()
            assert ka == kb and ka
        sa.evolve(0.02, nstepmax=8)
        sb.evolve(0.02, nstepmax=8)
        assert sa.nstep == sb.nstep
        # same leaf field on the shared cells
        ca, ua = sa.leaf_sample(4)
        cb, ub = sb.leaf_sample(5)
        oa = np.lexsort(ca.T)
        ob = np.lexsort(cb.T)
        assert np.allclose(ca[oa], cb[ob], atol=1e-12)
        assert np.allclose(ua[oa], ub[ob], rtol=1e-10, atol=1e-12)
        m0, m1 = sa.totals()[0], sb.totals()[0]
        assert abs(m0 - m1) < 1e-12

    def test_snapshot_restart_roundtrip(self, tmp_path):
        p = self._mk(2, 1, 4, 5, 1.0)
        sim = AmrSim(p, dtype=jnp.float64)
        assert sim.tree.has(5)                  # refinement present
        sim.evolve(0.02, nstepmax=4)
        out = sim.dump(iout=1, base_dir=str(tmp_path))
        p2 = self._mk(2, 1, 4, 5, 1.0)
        sim2 = AmrSim.from_snapshot(p2, out, dtype=jnp.float64)
        assert sim2.tree.root == (2, 1)
        assert sim2.t == sim.t and sim2.nstep == sim.nstep
        for l in sim.levels():
            assert np.array_equal(sim.tree.levels[l].og,
                                  sim2.tree.levels[l].og)
            nc = sim.maps[l].noct * 4
            a = np.asarray(sim.u[l])[:nc]
            b = np.asarray(sim2.u[l])[:nc]
            assert np.allclose(a, b, rtol=1e-12, atol=1e-14), l
        # both continue identically (restart oracle)
        sim.evolve(0.04, nstepmax=sim.nstep + 3)
        sim2.evolve(0.04, nstepmax=sim2.nstep + 3)
        for l in sim.levels():
            nc = sim.maps[l].noct * 4
            assert np.allclose(np.asarray(sim.u[l])[:nc],
                               np.asarray(sim2.u[l])[:nc],
                               rtol=1e-10, atol=1e-12), l

    def test_sharded_matches_single_device(self):
        """Non-cubic roots on the 8-device mesh: the sharded run is
        numerically identical to the single-device run (the serial-
        fallback invariance, P11, now including nx>1)."""
        import jax

        from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

        p = self._mk(2, 1, 4, 5, 1.0)
        ss = ShardedAmrSim(p, devices=jax.devices()[:8],
                           dtype=jnp.float64)
        ss.evolve(0.02, nstepmax=4)
        s1 = AmrSim(self._mk(2, 1, 4, 5, 1.0), dtype=jnp.float64)
        s1.evolve(0.02, nstepmax=4)
        assert ss.nstep == s1.nstep
        for l in s1.levels():
            nc = s1.maps[l].noct * 4
            a = np.asarray(ss.u[l])[:nc]
            b = np.asarray(s1.u[l])[:nc]
            assert np.allclose(a, b, rtol=1e-10, atol=1e-12), l
