"""MHD state layout, conversions, wave speeds.

Reference: ``mhd/`` solver (``mhd/init_hydro.f90:29``,
``mhd/hydro_parameters.f90``).  The reference stores 8+ cell variables
[ρ, ρv(3), E, B_left(3)] plus right-face B in slots nvar+1:nvar+3 — i.e.
BOTH faces per cell per dim.  Here the staggered field is stored once:
``bf[d]`` holds B_d on the LOW face of each cell along axis d (the high
face is the neighbour's low face), which halves the memory and makes the
divergence stencil exact by construction.  Velocity and B always carry 3
components regardless of grid dimensionality, as in the reference.

Cell state ``u``: [ρ, ρv_x, ρv_y, ρv_z, E, Bc_x, Bc_y, Bc_z, passives…]
Primitive ``q``:  [ρ, v_x, v_y, v_z, P, Bc_x, Bc_y, Bc_z, passives…]
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax.numpy as jnp

from ramses_tpu.config import Params

IRHO, IVX, IVY, IVZ, IP, IBX, IBY, IBZ = 0, 1, 2, 3, 4, 5, 6, 7
NCOMP = 3  # velocity/field components (always 3, mhd convention)


@dataclass(frozen=True)
class MhdStatic:
    """Static solver config (hashable; jit static arg)."""
    ndim: int = 3               # grid dimensionality (1/2/3)
    npassive: int = 0
    gamma: float = 1.6666667
    smallr: float = 1e-10
    smallc: float = 1e-10
    slope_type: int = 1
    slope_theta: float = 1.5
    riemann: str = "hlld"
    riemann2d: str = "average"
    courant_factor: float = 0.8
    # arrays carry a trailing batch axis (the AMR oct-stencil path);
    # read by hydro.muscl._axis which the slope bank shares
    trailing_batch: bool = False

    @property
    def nvar(self) -> int:
        return 8 + self.npassive

    @classmethod
    def from_params(cls, p: Params) -> "MhdStatic":
        h = p.hydro
        riemann = str(h.riemann)
        if riemann not in ("llf", "hll", "hlld", "roe", "upwind"):
            # refuse-or-implement: no silent physics substitution
            raise NotImplementedError(
                f"mhd riemann={riemann!r}: implemented solvers are "
                "llf|hll|hlld|roe|upwind "
                "(reference bank: hydro/read_hydro_params.f90:184-204)")
        r2d = str(h.riemann2d)
        if r2d not in ("llf", "roe", "upwind", "hll", "hlla", "hlld",
                       "average"):
            raise NotImplementedError(
                f"mhd riemann2d={r2d!r}: implemented corner solvers are "
                "llf|roe|upwind|hll|hlla|hlld|average "
                "(reference bank: hydro/read_hydro_params.f90:207-221)")
        return cls(ndim=p.ndim, npassive=p.npassive, gamma=float(h.gamma),
                   smallr=float(h.smallr), smallc=float(h.smallc),
                   slope_type=int(h.slope_type),
                   slope_theta=float(h.slope_theta),
                   riemann=riemann, riemann2d=str(h.riemann2d),
                   courant_factor=float(h.courant_factor))


def cell_center_b(bf: Sequence, ndim: int) -> list:
    """Cell-centered B from staggered faces: mean of low/high faces for
    staggered dims, identity for degenerate (cell-centered) components."""
    out = []
    for c in range(NCOMP):
        b = bf[c]
        if c < ndim:
            ax = b.ndim - ndim + c
            out.append(0.5 * (b + jnp.roll(b, -1, axis=ax)))
        else:
            out.append(b)
    return out


def ctoprim(u, cfg: MhdStatic):
    """Conservative → primitive (``mhd/umuscl.f90`` ctoprim equivalent)."""
    r = jnp.maximum(u[IRHO], cfg.smallr)
    inv_r = 1.0 / r
    v = [u[1 + c] * inv_r for c in range(NCOMP)]
    b = [u[IBX + c] for c in range(NCOMP)]
    eken = 0.5 * sum(vc * vc for vc in v)
    emag = 0.5 * sum(bc * bc for bc in b) * inv_r
    eint = jnp.maximum(u[IP] * inv_r - eken - emag,
                       cfg.smallc ** 2 / cfg.gamma / (cfg.gamma - 1.0))
    p = (cfg.gamma - 1.0) * r * eint
    comps = [r] + v + [p] + b
    for s in range(cfg.npassive):
        comps.append(u[8 + s] * inv_r)
    return jnp.stack(comps)


def prim_to_cons(q, cfg: MhdStatic):
    r = jnp.maximum(q[IRHO], cfg.smallr)
    v = [q[1 + c] for c in range(NCOMP)]
    b = [q[IBX + c] for c in range(NCOMP)]
    e = (q[IP] / (cfg.gamma - 1.0)
         + 0.5 * r * sum(vc * vc for vc in v)
         + 0.5 * sum(bc * bc for bc in b))
    comps = [r] + [r * vc for vc in v] + [e] + b
    for s in range(cfg.npassive):
        comps.append(r * q[8 + s])
    return jnp.stack(comps)


def fast_speed(q, d: int, cfg: MhdStatic):
    """Fast magnetosonic speed along component d
    (``mhd/courant_fine.f90`` / ``godunov_utils`` cmpdt)."""
    r = jnp.maximum(q[IRHO], cfg.smallr)
    c2 = cfg.gamma * jnp.maximum(q[IP], cfg.smallr * cfg.smallc ** 2) / r
    b2 = sum(q[IBX + c] ** 2 for c in range(NCOMP)) / r
    bd2 = q[IBX + d] ** 2 / r
    s = c2 + b2
    disc = jnp.sqrt(jnp.maximum(s * s - 4.0 * c2 * bd2, 0.0))
    return jnp.sqrt(jnp.maximum(0.5 * (s + disc), cfg.smallc ** 2))


def flux_along(q, d: int, cfg: MhdStatic):
    """Ideal-MHD physical flux along component d from primitives.

    F(ρ)    = ρ v_d
    F(ρv_c) = ρ v_d v_c − B_d B_c + δ_cd (P + B²/2)
    F(E)    = (E + P + B²/2) v_d − B_d (v·B)
    F(B_c)  = v_d B_c − v_c B_d   (zero for c=d)
    """
    r = jnp.maximum(q[IRHO], cfg.smallr)
    v = [q[1 + c] for c in range(NCOMP)]
    b = [q[IBX + c] for c in range(NCOMP)]
    p = q[IP]
    b2 = sum(bc * bc for bc in b)
    ptot = p + 0.5 * b2
    vdotb = sum(vc * bc for vc, bc in zip(v, b))
    e = (p / (cfg.gamma - 1.0) + 0.5 * r * sum(vc * vc for vc in v)
         + 0.5 * b2)
    vd = v[d]
    comps = [r * vd]
    for c in range(NCOMP):
        f = r * vd * v[c] - b[d] * b[c]
        if c == d:
            f = f + ptot
        comps.append(f)
    comps.append((e + ptot) * vd - b[d] * vdotb)
    for c in range(NCOMP):
        if c == d:
            comps.append(jnp.zeros_like(vd))
        else:
            comps.append(vd * b[c] - v[c] * b[d])
    for s in range(cfg.npassive):
        comps.append(comps[0] * q[8 + s])
    return jnp.stack(comps)


def div_b(bf: Sequence, dx: Sequence[float], ndim: int):
    """Exact staggered divergence (machine-zero under CT)."""
    out = None
    for d in range(ndim):
        ax = bf[d].ndim - ndim + d
        t = (jnp.roll(bf[d], -1, axis=ax) - bf[d]) / dx[d]
        out = t if out is None else out + t
    return out
