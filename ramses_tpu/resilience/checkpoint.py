"""Atomic validated checkpoints: manifest + staged rename + scanning.

The reference restarts from whatever ``output_NNNNN/`` it finds
(``nrestart>0``); a job killed mid-dump leaves a directory that parses
until a reader hits the truncation.  Here every dump is staged into
``output_NNNNN.tmp/``, every file is fsynced and hashed into a
``manifest.json``, and only then does one ``os.replace`` make the
checkpoint visible — readers either see a complete validated directory
or nothing.  ``validate_checkpoint`` re-checks the manifest against
the bytes on disk, so auto-resume (``resolve_restart_dir``) can skip
bit-rotted or truncated checkpoints with a logged reason instead of
crashing into them.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = 1


def _fsync_path(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def write_manifest(stage_dir: str, meta: Optional[Dict[str, Any]] = None
                   ) -> str:
    """Hash + size every file under ``stage_dir`` (recursively) into
    ``manifest.json``, fsync it and the directory.  Returns the
    manifest path."""
    files: Dict[str, Dict[str, Any]] = {}
    for root, _dirs, names in os.walk(stage_dir):
        for name in sorted(names):
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            rel = os.path.relpath(p, stage_dir)
            files[rel] = {"size": os.path.getsize(p), "sha256": _sha256(p)}
            _fsync_path(p)
    mpath = os.path.join(stage_dir, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump({"schema": MANIFEST_SCHEMA,
                   "meta": dict(meta or {}),
                   "files": files}, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(stage_dir)
    return mpath


def finalize_checkpoint(stage_dir: str, final_dir: str,
                        meta: Optional[Dict[str, Any]] = None) -> str:
    """Manifest the staged directory and atomically rename it into
    place.  A pre-existing ``final_dir`` is REMOVED first (replaced,
    never merged — the stale same-iout mixing hazard), and the parent
    directory is fsynced so the rename survives a crash."""
    write_manifest(stage_dir, meta)
    if os.path.isdir(final_dir):
        shutil.rmtree(final_dir)
    os.replace(stage_dir, final_dir)
    parent = os.path.dirname(os.path.abspath(final_dir))
    try:
        _fsync_path(parent)
    except OSError:
        pass                      # e.g. parent on a non-fsyncable mount
    return final_dir


def validate_checkpoint(outdir: str,
                        verify_hash: bool = True) -> Tuple[bool, str]:
    """(ok, reason): does ``outdir`` hold a complete checkpoint whose
    bytes match its manifest?  ``verify_hash=False`` checks existence
    and sizes only (cheap scan mode)."""
    mpath = os.path.join(outdir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return False, "no manifest.json (pre-atomic or partial dump)"
    try:
        with open(mpath) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"unreadable manifest: {e}"
    if man.get("schema") != MANIFEST_SCHEMA:
        return False, f"unknown manifest schema {man.get('schema')!r}"
    files = man.get("files")
    if not isinstance(files, dict):
        return False, "manifest has no file table"
    for rel, ent in files.items():
        p = os.path.join(outdir, rel)
        if not os.path.isfile(p):
            return False, f"missing file {rel}"
        if os.path.getsize(p) != int(ent.get("size", -1)):
            return False, f"size mismatch on {rel}"
        if verify_hash and _sha256(p) != ent.get("sha256"):
            return False, f"checksum mismatch on {rel}"
    return True, "ok"


def read_manifest_meta(outdir: str) -> Dict[str, Any]:
    """The manifest's ``meta`` block ({} when absent/unreadable)."""
    try:
        with open(os.path.join(outdir, MANIFEST_NAME)) as f:
            return dict(json.load(f).get("meta") or {})
    except (OSError, json.JSONDecodeError):
        return {}


def read_quarantine_census(outdir: str) -> Dict[int, Dict[str, Any]]:
    """Per-member quarantine census from an ensemble checkpoint's
    manifest meta: ``{member: {reason, nstep, t, dump}}`` ({} when the
    checkpoint predates member isolation or nothing is quarantined).
    Written by ``EnsembleEngine.save`` whenever the batched step-guard
    evicted members — the durable record of *which* members' results
    in this checkpoint are last-clean-state rather than completed."""
    census = read_manifest_meta(outdir).get("quarantined") or {}
    return {int(k): dict(v) for k, v in census.items()}


def scan_checkpoints(base_dir: str, log: Optional[Callable] = None,
                     prefix: str = "output_"
                     ) -> List[Tuple[str, Dict[str, Any]]]:
    """Manifest-valid checkpoints under ``base_dir``, newest first by
    (nstep, t, iout) — so an emergency dump (high iout, current step)
    correctly outranks an older scheduled output.  Invalid candidates
    are skipped with a logged reason."""
    try:
        names = sorted(os.listdir(base_dir))
    except OSError:
        return []
    found = []
    for name in names:
        if not (name.startswith(prefix)
                and name[len(prefix):].isdigit()):
            continue
        outdir = os.path.join(base_dir, name)
        if not os.path.isdir(outdir):
            continue
        ok, reason = validate_checkpoint(outdir)
        if not ok:
            if log is not None:
                log(f"resilience: skipping {name}: {reason}")
            continue
        meta = read_manifest_meta(outdir)
        found.append((outdir, meta))
    found.sort(key=lambda e: (int(e[1].get("nstep", 0)),
                              float(e[1].get("t", 0.0)),
                              int(e[1].get("iout", 0))),
               reverse=True)
    return found


def latest_valid_checkpoint(base_dir: str,
                            log: Optional[Callable] = print
                            ) -> Optional[str]:
    """Newest manifest-valid ``output_NNNNN`` under ``base_dir`` (by
    stored nstep/t, not by directory number), or None."""
    found = scan_checkpoints(base_dir, log=log)
    return found[0][0] if found else None


def rotate_checkpoints(base_dir: str, keep: int,
                       protect: Optional[str] = None):
    """Remove the oldest manifest-valid checkpoints beyond ``keep``.
    Only validated checkpoints are rotation candidates — pre-atomic
    output dirs (science products without manifests) are never
    touched.  ``protect`` is exempt regardless of age."""
    if keep <= 0:
        return
    found = scan_checkpoints(base_dir, log=None)
    prot = os.path.abspath(protect) if protect else None
    for outdir, _meta in found[keep:]:
        if prot and os.path.abspath(outdir) == prot:
            continue
        shutil.rmtree(outdir, ignore_errors=True)


def resolve_restart_dir(params, base_dir: Optional[str] = None,
                        log: Optional[Callable] = print
                        ) -> Optional[str]:
    """The checkpoint directory a run should restore from, or None for
    a fresh start.

    ``nrestart > 0``: the explicit ``output_NNNNN`` (missing → error;
    a manifest that fails validation → error — restarting from known
    corruption must be loud; a pre-manifest directory passes with a
    warning for backward compatibility).  ``nrestart == -1`` or
    ``auto_resume=.true.``: newest manifest-valid checkpoint, or None
    when there is none yet (first launch of a supervised run)."""
    run = getattr(params, "run", None)
    nrestart = int(getattr(run, "nrestart", 0))
    auto = bool(getattr(run, "auto_resume", False)) or nrestart == -1
    base = base_dir if base_dir is not None else str(
        getattr(getattr(params, "output", None), "output_dir", "."))
    if nrestart > 0:
        outdir = os.path.join(base, f"output_{nrestart:05d}")
        if not os.path.isdir(outdir):
            raise FileNotFoundError(
                f"nrestart={nrestart}: {outdir} does not exist")
        if os.path.isfile(os.path.join(outdir, MANIFEST_NAME)):
            ok, reason = validate_checkpoint(outdir)
            if not ok:
                raise RuntimeError(
                    f"nrestart={nrestart}: {outdir} fails validation "
                    f"({reason}); use nrestart=-1 to auto-select the "
                    "newest valid checkpoint instead")
        elif log is not None:
            log(f"resilience: {outdir} has no manifest (pre-atomic "
                "dump); restoring without validation")
        return outdir
    if auto:
        out = latest_valid_checkpoint(base, log=log)
        if out is not None and log is not None:
            log(f"resilience: auto-resume from {out}")
        return out
    return None
