"""Render a telemetry JSONL event log as a markdown report.

Companion of :mod:`ramses_tpu.telemetry`: reads the file written by
``&OUTPUT_PARAMS telemetry='run.jsonl'`` and produces the human/CI
summary — run header, per-step table (nstep, t, dt, wall, µs/pt, octs,
memory), aggregated phase breakdown, captured warnings, footer totals.
Stdlib-only so CI can run it without the jax stack.

Usage::

    python tools/telemetry_report.py RUN.jsonl [-o REPORT.md]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def load_records(path: str) -> List[Dict[str, Any]]:
    recs = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{ln}: bad JSONL record: {e}")
    return recs


def _fmt(v, spec: str = "") -> str:
    if v is None:
        return "-"
    return format(v, spec) if spec else str(v)


def _octs_str(octs: Dict[str, int]) -> str:
    if not octs:
        return "-"
    return " ".join(f"{l}:{n}" for l, n in sorted(
        octs.items(), key=lambda kv: int(kv[0])))


def render(recs: List[Dict[str, Any]], source: str = "") -> str:
    header = next((r for r in recs if r.get("kind") == "run_header"), {})
    footer = next((r for r in recs if r.get("kind") == "run_footer"), {})
    steps = [r for r in recs if r.get("kind") == "step"]
    events = [r for r in recs
              if r.get("kind") not in ("run_header", "run_footer", "step")]

    out = ["# Telemetry report", ""]
    if source:
        out.append(f"Source: `{source}`")
        out.append("")

    info = header.get("run_info", {})
    out.append("## Run")
    out.append("")
    out.append("| key | value |")
    out.append("|---|---|")
    out.append(f"| schema | {header.get('schema_version', '-')} |")
    for k in ("trace_id", "job", "worker"):
        if header.get(k):
            out.append(f"| {k} | {header[k]} |")
    for k in ("driver", "ndev", "ndim", "levelmin", "levelmax",
              "boxlen", "nvar", "nmember", "ngroup", "halo_backend",
              "halo_bytes", "halo_exchanges", "halo_overlap_frac",
              "offload", "offload_hbm_budget_mb"):
        if k in info:
            out.append(f"| {k} | {info[k]} |")
    packing = info.get("packing")
    if isinstance(packing, dict):
        out.append(f"| packing | {packing.get('mode', '-')} over "
                   f"{len(packing.get('device_ids') or [])} device(s) |")
    out.append(f"| interval | {header.get('telemetry_interval', '-')} |")
    out.append(f"| step records | {len(steps)} |")
    if footer:
        out.append(f"| total wall [s] | {_fmt(footer.get('wall_s'))} |")
        out.append(f"| recompiles | "
                   f"{_fmt(footer.get('recompiles_total'))} |")
        out.append(f"| compile time [s] | "
                   f"{_fmt(footer.get('compile_s_total'))} |")
        out.append(f"| RSS high-water [MiB] | "
                   f"{_fmt(footer.get('rss_hwm_mb'))} |")
        out.append(f"| device high-water [MiB] | "
                   f"{_fmt(footer.get('device_hwm_mb'))} |")
        out.append(f"| warnings | {_fmt(footer.get('warnings_total'))} |")
    out.append("")

    if steps:
        out.append("## Steps")
        out.append("")
        out.append("| nstep | t | dt | wall [s] | µs/pt | octs "
                   "| RSS [MiB] | dev [MiB] | recompiles |")
        out.append("|---|---|---|---|---|---|---|---|---|")
        for r in steps:
            out.append(
                f"| {r.get('nstep')} "
                f"| {_fmt(r.get('t'), '.6e')} "
                f"| {_fmt(r.get('dt'), '.3e')} "
                f"| {_fmt(r.get('wall_s'), '.4f')} "
                f"| {_fmt(r.get('mus_per_cell_update'), '.4f')} "
                f"| {_octs_str(r.get('octs', {}))} "
                f"| {_fmt(r.get('rss_mb'))} "
                f"| {_fmt(r.get('device_mb'))} "
                f"| {_fmt(r.get('recompiles'))} |")
        out.append("")

        # aggregated phase wallclock over all step records
        phases: Dict[str, float] = {}
        for r in steps:
            for k, v in (r.get("phases_s") or {}).items():
                phases[k] = phases.get(k, 0.0) + float(v)
        if phases:
            total = sum(phases.values()) or 1.0
            out.append("## Phases")
            out.append("")
            out.append("| phase | time [s] | % |")
            out.append("|---|---|---|")
            for k, v in sorted(phases.items(), key=lambda kv: -kv[1]):
                out.append(f"| {k} | {v:.4f} | {100 * v / total:.1f} |")
            out.append("")

        cons = [r["cons"] for r in steps if "cons" in r]
        if cons:
            last = cons[-1]
            out.append("## Conservation")
            out.append("")
            out.append(f"- mass drift: {_fmt(last.get('mcons_drift'), '.3e')}"
                       f" (over {len(cons)} audits)")
            if "econs_drift" in last:
                out.append("- energy drift: "
                           f"{_fmt(last.get('econs_drift'), '.3e')}")
            out.append("")

    warns = []
    for r in recs:
        for w in r.get("warnings", []) or []:
            warns.append(w)
    if warns:
        out.append("## Warnings")
        out.append("")
        for w in warns[:50]:
            src = f" ({w['source']})" if w.get("source") else ""
            out.append(f"- {w.get('msg', '')}{src}")
        out.append("")

    # run-service economics (PR 18 packing fields): the job_summary
    # event each completed queue job emits, plus the worker's last
    # gang_schedule and the idle-heartbeat census
    summaries = [r for r in events if r.get("kind") == "job_summary"]
    gangs = [r for r in events if r.get("kind") == "gang_schedule"]
    idles = [r for r in events if r.get("kind") == "serve_idle"]
    if summaries or gangs or idles:
        out.append("## Service")
        out.append("")
        out.append("| key | value |")
        out.append("|---|---|")
        if summaries:
            s = summaries[-1]
            for k in ("queue_wait_s", "scenarios_per_device_s",
                      "busy_frac", "gang_jobs", "nmember",
                      "quarantined", "compile_cache_hits",
                      "compile_cache_misses"):
                if k in s:
                    out.append(f"| {k} | {_fmt(s[k])} |")
        if gangs:
            g = gangs[-1]
            out.append(f"| last gang | {_fmt(g.get('jobs'))} job(s), "
                       f"{_fmt(g.get('busy_devices'))}/"
                       f"{_fmt(g.get('ndev'))} devices, "
                       f"busy_frac={_fmt(g.get('busy_frac'))} |")
        if idles:
            last = idles[-1]
            out.append(f"| idle beats | {len(idles)} (last census: "
                       f"queued={_fmt(last.get('queued'))} "
                       f"running={_fmt(last.get('running'))} "
                       f"done={_fmt(last.get('done'))} "
                       f"failed={_fmt(last.get('failed'))}) |")
        out.append("")

    # out-of-core residency footer totals (&AMR_PARAMS offload)
    if any(k.startswith("offload_") for k in footer):
        out.append("## Offload")
        out.append("")
        out.append("| key | value |")
        out.append("|---|---|")
        for k in ("offload_stalls", "offload_prefetches",
                  "offload_fetches", "offload_overlapped",
                  "offload_overlap_frac", "offload_bytes_parked",
                  "offload_bytes_fetched",
                  "offload_device_hwm_bytes"):
            if k in footer:
                out.append(f"| {k} | {_fmt(footer[k])} |")
        out.append("")

    if events:
        out.append("## Events")
        out.append("")
        kinds: Dict[str, int] = {}
        for r in events:
            kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
        for k, n in sorted(kinds.items()):
            out.append(f"- {k}: {n}")
        out.append("")

    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl", help="telemetry JSONL event log")
    ap.add_argument("-o", "--out", default=None,
                    help="write markdown here (default: stdout)")
    args = ap.parse_args(argv)
    recs = load_records(args.jsonl)
    if not recs:
        raise SystemExit(f"{args.jsonl}: no records")
    md = render(recs, source=args.jsonl)
    if args.out:
        with open(args.out, "w") as f:
            f.write(md + "\n")
        print(f"wrote {args.out} ({len(recs)} records)")
    else:
        sys.stdout.write(md + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
