"""Prometheus text-format metrics over a queue directory.

Everything here is derived from artifacts already on disk — queue
record JSONs, running-record heartbeat mtimes, the per-worker
telemetry JSONL under ``<queue_dir>/workers/`` — so a scrape NEVER
touches a device or a worker process (the PR 3 zero-added-fetch
contract extends to the whole observability plane).  Stdlib-only
(plus the jax-free ``ensemble/queue``): a scrape allocates nothing on
any accelerator and works with no worker process alive at all.

Counters are *reconstructed* from the durable records on every scrape
(failure_log entries, attempt counts, quarantine censuses), so they
are monotone for as long as the records exist — a restarted obs
server resumes the same counter values, which is exactly the Prometheus
counter contract (resets are handled by ``rate()`` anyway).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ramses_tpu.ensemble import queue as jq

#: subdir where serve workers keep their own telemetry JSONL; the file
#: mtime doubles as the worker liveness signal scraped below
WORKERS_DIR = "workers"

_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _esc(v: str) -> str:
    return "".join(_LABEL_ESC.get(ch, ch) for ch in str(v))


class Family:
    """One metric family: name/type/help + labelled samples."""

    def __init__(self, name: str, typ: str, help_: str):
        self.name, self.typ, self.help = name, typ, help_
        self.samples: List[Tuple[Dict[str, str], float]] = []

    def add(self, value, **labels) -> "Family":
        self.samples.append((dict(labels), float(value)))
        return self


def _iter_records(queue_dir: str) -> Iterator[Tuple[str, Dict[str, Any]]]:
    for state in jq.STATES:
        d = os.path.join(queue_dir, state)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    yield state, json.load(f)
            except (OSError, ValueError):
                continue        # claimed under us / submit mid-flight


def _tail_events(path: str, kinds: Tuple[str, ...],
                 max_bytes: int = 1 << 18) -> Dict[str, Dict[str, Any]]:
    """Last record of each ``kind`` near the end of a JSONL file (one
    bounded read — scrapes stay O(1) however long the log grows)."""
    out: Dict[str, Dict[str, Any]] = {}
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - max_bytes))
            data = f.read(max_bytes)
    except OSError:
        return out
    for line in data.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue            # torn first line of the window
        if rec.get("kind") in kinds:
            out[rec["kind"]] = rec
    return out


def collect(queue_dir: str, now: Optional[float] = None) -> List[Family]:
    """One scan of the queue directory into metric families."""
    now = time.time() if now is None else float(now)
    counts = jq.queue_counts(queue_dir)

    depth = Family("ramses_queue_jobs", "gauge",
                   "Jobs per queue lifecycle directory.")
    for state in jq.STATES:
        depth.add(counts.get(state, 0), state=state)

    attempts = Family("ramses_job_attempts_total", "counter",
                      "Claim attempts accumulated across all job "
                      "records still on disk.")
    failures = Family("ramses_failure_events_total", "counter",
                      "failure_log entries by stage (requeue, hang, "
                      "stale, fail).")
    quarantined = Family("ramses_quarantined_members_total", "counter",
                         "Ensemble members evicted by the member "
                         "isolation ladder (from done-record censuses).")
    partial = Family("ramses_jobs_partial_total", "counter",
                     "Completed jobs with at least one quarantined "
                     "member.")
    cache_hits = Family("ramses_compile_cache_hits_total", "counter",
                        "Persistent compile-cache hits recorded on "
                        "completed jobs.")
    cache_miss = Family("ramses_compile_cache_misses_total", "counter",
                        "Persistent compile-cache misses recorded on "
                        "completed jobs.")
    cells = Family("ramses_cell_updates_total", "counter",
                   "Subcycle-weighted cell updates summed over "
                   "completed jobs.")
    qwait = Family("ramses_queue_wait_seconds_sum", "counter",
                   "Summed submit->claim latency of completed jobs.")
    qwait_n = Family("ramses_queue_wait_seconds_count", "counter",
                     "Completed jobs with a queue_wait_s sample.")
    spd = Family("ramses_scenarios_per_device_seconds", "gauge",
                 "scenarios_per_device_s of the most recently "
                 "finished job.")
    hb = Family("ramses_job_heartbeat_age_seconds", "gauge",
                "Age of each running job's claim heartbeat (stale "
                "workers are reclaimed past the staleness timeout).")
    fenced = Family("ramses_fenced_writes_total", "counter",
                    "Worker-side queue writes refused because the "
                    "claim's fencing token was superseded (zombie "
                    "reclaim protection).")

    n_attempts = n_quar = n_partial = n_hits = n_miss = 0
    n_cells = 0
    wait_sum, wait_n = 0.0, 0
    by_stage: Dict[str, int] = {}
    last_spd: Optional[Tuple[float, float]] = None   # (finished, value)
    for state, rec in _iter_records(queue_dir):
        n_attempts += int(rec.get("attempts", 0) or 0)
        for entry in rec.get("failure_log") or []:
            stage = str(entry.get("stage") or "unknown")
            by_stage[stage] = by_stage.get(stage, 0) + 1
        result = rec.get("result") or {}
        if state == "done":
            failed = result.get("failed_members") or []
            n_quar += len(failed)
            n_partial += 1 if result.get("partial") else 0
            n_hits += int(result.get("compile_cache_hits") or 0)
            n_miss += int(result.get("compile_cache_misses") or 0)
            n_cells += int(result.get("cell_updates") or 0)
            w = result.get("queue_wait_s")
            if w is not None:
                wait_sum += float(w)
                wait_n += 1
            v = result.get("scenarios_per_device_s")
            fin = float(rec.get("finished_unix") or 0.0)
            if v is not None and (last_spd is None or fin > last_spd[0]):
                last_spd = (fin, float(v))
        if state == "running":
            path = os.path.join(queue_dir, "running",
                                str(rec.get("id", "?")) + ".json")
            # content-heartbeat sidecar first (fenced claims write
            # <id>.json.hb); pre-fencing records fall back to the
            # record file's own mtime
            try:
                hb.add(round(now - os.path.getmtime(
                    path + jq.HB_SUFFIX), 3),
                    job=str(rec.get("id", "?")))
            except OSError:
                try:
                    hb.add(round(now - os.path.getmtime(path), 3),
                           job=str(rec.get("id", "?")))
                except OSError:
                    pass
    attempts.add(n_attempts)
    for stage in sorted(by_stage):
        failures.add(by_stage[stage], stage=stage)
    fenced.add(by_stage.get("fenced", 0))
    quarantined.add(n_quar)
    partial.add(n_partial)
    cache_hits.add(n_hits)
    cache_miss.add(n_miss)
    cells.add(n_cells)
    qwait.add(round(wait_sum, 3))
    qwait_n.add(wait_n)
    if last_spd is not None:
        spd.add(last_spd[1])

    whb = Family("ramses_worker_heartbeat_age_seconds", "gauge",
                 "Age of each serve worker's telemetry sink (workers "
                 "write serve_idle/queue events through it).")
    busy = Family("ramses_gang_busy_frac", "gauge",
                  "Device-busy fraction of each worker's most recent "
                  "gang schedule.")
    wdir = os.path.join(queue_dir, WORKERS_DIR)
    try:
        wnames = sorted(n for n in os.listdir(wdir)
                        if n.endswith(".jsonl"))
    except OSError:
        wnames = []
    for name in wnames:
        path = os.path.join(wdir, name)
        worker = name[:-len(".jsonl")]
        try:
            whb.add(round(now - os.path.getmtime(path), 3),
                    worker=worker)
        except OSError:
            continue
        ev = _tail_events(path, ("gang_schedule",))
        gs = ev.get("gang_schedule")
        if gs is not None and gs.get("busy_frac") is not None:
            busy.add(float(gs["busy_frac"]), worker=worker)

    brk = Family("ramses_breaker_state", "gauge",
                 "Poison-config circuit breakers by config "
                 "fingerprint (0 closed, 1 half-open, 2 open).")
    try:
        from ramses_tpu.ensemble import breaker as bk
        for b in bk.list_breakers(queue_dir):
            brk.add(bk.STATE_VALUE.get(str(b.get("state")), 0),
                    fp=str(b.get("fp", "?")),
                    stage=str(b.get("stage", "")))
    except Exception:
        pass
    disk = Family("ramses_disk_free_bytes", "gauge",
                  "Free bytes on the filesystem holding the queue "
                  "directory (diskguard watermarks gate checkpoints "
                  "and claims on it).")
    try:
        st = os.statvfs(queue_dir)
        disk.add(float(st.f_bavail) * float(st.f_frsize))
    except OSError:
        pass

    fams = [depth, attempts, failures, fenced, quarantined, partial,
            cache_hits, cache_miss, cells, qwait, qwait_n, spd,
            hb, whb, busy, brk, disk]
    return [f for f in fams if f.samples]


def render(families: List[Family]) -> str:
    """Prometheus text exposition format, version 0.0.4."""
    out: List[str] = []
    for fam in families:
        out.append(f"# HELP {fam.name} {fam.help}")
        out.append(f"# TYPE {fam.name} {fam.typ}")
        for labels, value in fam.samples:
            lab = ",".join(f'{k}="{_esc(v)}"'
                           for k, v in sorted(labels.items()))
            text = f"{value:.10g}"
            out.append(f"{fam.name}{{{lab}}} {text}" if lab
                       else f"{fam.name} {text}")
    return "\n".join(out) + "\n"


def render_queue_metrics(queue_dir: str,
                         now: Optional[float] = None) -> str:
    return render(collect(queue_dir, now=now))


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                             float]:
    """Parse an exposition back into ``{(name, ((k, v), ...)): value}``
    — the round-trip half the tests and the CI smoke assert through."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparsable metrics line: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        key = tuple(sorted(
            (k, re.sub(r"\\(.)",
                       lambda m: "\n" if m.group(1) == "n"
                       else m.group(1), v))
            for k, v in _LABEL_RE.findall(labels)))
        out[(name, key)] = float(value)
    return out
