"""Isolated-boundary Poisson solve: multipole Dirichlet + dense CG.

Reference: ``pm/rho_fine.f90:666`` (multipole_fine — mass moments of the
density) + ``poisson/boundary_potential.f90:5-341`` (phi_boundary: the
ghost potential on non-periodic faces from the multipole expansion),
then the usual interior solve.  Here the expansion is monopole +
quadrupole about the centre of mass (the dipole vanishes there), the
ghost layer enters the right-hand side of a zero-Dirichlet 7-point
Laplacian (SPD), and a fixed-iteration CG solves it — all dense
whole-grid ops, jit-friendly.

Sign convention matches the rest of the package: ``Lap(phi) = coeff*rho``
with attractive force ``-grad phi`` applied as ``+f`` in the kick, i.e.
``f = -grad phi``; a positive mass produces ``phi < 0`` wells via the
Green's function ``phi = -coeff M / (4 pi r)`` (3D).
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp


def _shift0(a, s: int, ax: int):
    """Shift with zero fill (Dirichlet-0 ghost)."""
    z = jnp.zeros_like(a)
    if s == 1:
        sl_src = [slice(None)] * a.ndim
        sl_dst = [slice(None)] * a.ndim
        sl_src[ax] = slice(0, -1)
        sl_dst[ax] = slice(1, None)
    else:
        sl_src = [slice(None)] * a.ndim
        sl_dst = [slice(None)] * a.ndim
        sl_src[ax] = slice(1, None)
        sl_dst[ax] = slice(0, -1)
    return z.at[tuple(sl_dst)].set(a[tuple(sl_src)])


def lap_dirichlet0(phi, dx: float):
    """7-point Laplacian with zero Dirichlet ghosts (SPD operator)."""
    nd = phi.ndim
    out = -2.0 * nd * phi
    for ax in range(nd):
        out = out + _shift0(phi, 1, ax) + _shift0(phi, -1, ax)
    return out / (dx * dx)


def multipole_moments(rho, dx: float):
    """(M, com, Q) — total mass, centre of mass, and (3D) the symmetric
    quadrupole tensor about it (``multipole_fine``; 6 unique components,
    Q_ij = Σ ρ (3 x_i x_j − |x|² δ_ij) dV).  One set of whole-grid
    reductions, shared by every boundary-face evaluation."""
    nd = rho.ndim
    vol = dx ** nd
    axes = [(jnp.arange(n) + 0.5) * dx for n in rho.shape]
    grids = jnp.meshgrid(*axes, indexing="ij")
    M = jnp.sum(rho) * vol
    Msafe = jnp.where(jnp.abs(M) > 1e-300, M, 1.0)
    com = jnp.stack([jnp.sum(rho * g) * vol / Msafe for g in grids])
    Q = None
    if nd == 3:
        rel = [g - com[d] for d, g in enumerate(grids)]
        x2 = sum(x * x for x in rel)
        Q = jnp.zeros((3, 3), rho.dtype)
        for i in range(3):
            for j in range(i, 3):
                qij = jnp.sum(rho * (3.0 * rel[i] * rel[j]
                                     - (x2 if i == j else 0.0))) * vol
                Q = Q.at[i, j].set(qij)
                if i != j:
                    Q = Q.at[j, i].set(qij)
    return M, com, Q


def multipole_phi(rho, dx: float, coeff, points, moments=None):
    """Multipole potential at ``points`` [n, ndim] (box coordinates).

    Monopole + quadrupole about the centre of mass (the dipole is zero
    there) — ``boundary_potential.f90`` keeps the same orders.  3D uses
    the 1/r kernel, 2D the log kernel.  Pass precomputed ``moments``
    to amortize the grid reductions over many evaluation batches.
    """
    nd = rho.ndim
    M, com, Q = (multipole_moments(rho, dx) if moments is None
                 else moments)
    r = points - com[None, :]                       # [n, ndim]
    r2 = jnp.maximum((r ** 2).sum(axis=1), (0.5 * dx) ** 2)
    if nd == 3:
        quad = jnp.einsum("ni,ij,nj->n", r, Q, r)
        rr = jnp.sqrt(r2)
        return -coeff / (4.0 * jnp.pi) * (M / rr + 0.5 * quad / rr ** 5)
    if nd == 2:
        return coeff / (2.0 * jnp.pi) * 0.5 * M * jnp.log(r2)
    # 1D: |x| kernel (phi'' = coeff*rho → phi = coeff*M*|x|/2)
    return coeff * 0.5 * M * jnp.sqrt(r2)


def _face_points(shape: Tuple[int, ...], dx: float, d: int, side: int,
                 dtype):
    """Ghost-cell centre coordinates of one face, flat [nface, ndim]."""
    nd = len(shape)
    axes = []
    for dd in range(nd):
        if dd == d:
            x = jnp.asarray([-0.5 * dx if side == 0
                             else (shape[d] + 0.5) * dx], dtype)
        else:
            x = (jnp.arange(shape[dd], dtype=dtype) + 0.5) * dx
        axes.append(x)
    grids = jnp.meshgrid(*axes, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=1)


@partial(jax.jit, static_argnames=("iters",))
def isolated_solve(rho, dx: float, coeff, iters: int = 300, tol: float = 1e-6,
                   phi0=None):
    """Solve ``Lap(phi) = coeff*rho`` with open (isolated) boundaries.

    Returns (phi, ghost_faces) where ``ghost_faces[d][side]`` is the
    multipole Dirichlet layer used — callers feed it to
    :func:`grad_isolated` so the boundary force is consistent with the
    solve.  No mean subtraction: the isolated problem is well-posed.
    """
    nd = rho.ndim
    rhs = coeff * rho
    moments = multipole_moments(rho, dx)   # grid reductions ONCE
    ghosts: List[List[jnp.ndarray]] = []
    for d in range(nd):
        pair = []
        for side in (0, 1):
            pts = _face_points(rho.shape, dx, d, side, rho.dtype)
            g = multipole_phi(rho, dx, coeff, pts, moments=moments)
            fshape = tuple(1 if dd == d else rho.shape[dd]
                           for dd in range(nd))
            pair.append(g.reshape(fshape))
        ghosts.append(pair)

    # Dirichlet layer folds into the rhs: Lap0(phi) = rhs - ghosts/dx^2
    rhs_adj = rhs
    dx2 = dx * dx
    for d in range(nd):
        lo_idx = [slice(None)] * nd
        hi_idx = [slice(None)] * nd
        lo_idx[d] = slice(0, 1)
        hi_idx[d] = slice(-1, None)
        rhs_adj = rhs_adj.at[tuple(lo_idx)].add(-ghosts[d][0] / dx2)
        rhs_adj = rhs_adj.at[tuple(hi_idx)].add(-ghosts[d][1] / dx2)

    phi = jnp.zeros_like(rhs) if phi0 is None else phi0
    r = rhs_adj - lap_dirichlet0(phi, dx)
    p = r
    rs = jnp.vdot(r, r)
    rs0 = rs
    eps = jnp.asarray(jnp.finfo(rhs.dtype).eps, rhs.dtype)
    cut = jnp.maximum(eps * eps, jnp.asarray(tol * tol, rhs.dtype))
    floor = cut * jnp.maximum(rs0, 1e-300)

    def body(carry, _):
        phi, r, p, rs = carry
        live = rs > floor
        ap = lap_dirichlet0(p, dx)
        denom = jnp.vdot(p, ap)
        alpha = jnp.where(live & (denom != 0.0),
                          rs / jnp.where(denom == 0, 1, denom), 0.0)
        phi = phi + alpha * p
        r_new = r - alpha * ap
        rs_new = jnp.vdot(r_new, r_new)
        beta = jnp.where(live, rs_new / jnp.where(rs == 0, 1, rs), 0.0)
        p = jnp.where(live, r_new + beta * p, p)
        return (phi, jnp.where(live, r_new, r), p,
                jnp.where(live, rs_new, rs)), None

    (phi, r, p, rs), _ = jax.lax.scan(body, (phi, r, p, rs), None,
                                      length=iters)
    return phi, ghosts


@jax.jit
def grad_isolated(phi, ghosts, dx: float):
    """Central-difference force ``f = -grad(phi)`` [ndim, *sp] using the
    multipole Dirichlet ghost layers at the boundary."""
    nd = phi.ndim
    comps = []
    for d in range(nd):
        padded = jnp.concatenate([ghosts[d][0], phi, ghosts[d][1]], axis=d)
        lo = [slice(None)] * nd
        hi = [slice(None)] * nd
        lo[d] = slice(0, -2)
        hi[d] = slice(2, None)
        comps.append(-(padded[tuple(hi)] - padded[tuple(lo)])
                     / (2.0 * dx))
    return jnp.stack(comps)
