"""Hang watchdog (ramses_tpu/resilience/watchdog.py).

Pins the hang pillar of the resilience layer:

  * deadline expiry raises :class:`HangDetected` in the main thread,
    records a ``hang`` telemetry event, and writes a manifest-valid
    ``hang_NNNNN/`` diagnostics dump that is NEVER an auto-resume
    candidate;
  * ``Watchdog.from_params`` is ``None`` with every deadline unset
    (the zero-overhead off switch) and the env overrides arm it;
  * ``hang@K[:member=J]`` fault injection parses, clamps fused
    windows, and fires exactly once per PROCESS (so the hang-policy
    resume completes instead of re-hanging forever);
  * arming the watchdog adds zero host<->device fetches (same
    device_get-counting pin as the step guard);
  * a supervised ``hang@K`` run resumes immediately — no backoff, its
    own retry budget — and reproduces an uninterrupted run within
    round-off (same contract as the SIGTERM test in
    tests/test_resilience.py).
"""

import json
import os
import threading
import time
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ramses_tpu.config import params_from_string
from ramses_tpu.resilience import checkpoint as ckpt
from ramses_tpu.resilience import faultinject as finj
from ramses_tpu.resilience import supervisor as rsup
from ramses_tpu.resilience import watchdog as wdog

pytestmark = pytest.mark.smoke

UNI2D = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
ncontrol=1
{run_extra}
/
&AMR_PARAMS
levelmin=4
levelmax=4
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&OUTPUT_PARAMS
noutput=1
tout=1.0
{out_extra}
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
/
"""

AMR2D = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
ncontrol=1
{run_extra}
/
&AMR_PARAMS
levelmin=4
levelmax=5
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&OUTPUT_PARAMS
tend=1.0
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""


@pytest.fixture(autouse=True)
def _watchdog_hygiene():
    """Process-wide state the watchdog/injector touch: the shared
    SIGALRM handler and the once-per-process hang-fired set."""
    yield
    wdog._uninstall_handler()
    finj.reset_fired()


def _uni_params(nstep=6, run_extra="", out_extra=""):
    return params_from_string(
        UNI2D.format(nstep=nstep, run_extra=run_extra,
                     out_extra=out_extra), ndim=2)


def _uni_sim(nstep=6, run_extra="", out_extra="", dtype=jnp.float64):
    from ramses_tpu.driver import Simulation
    return Simulation(_uni_params(nstep, run_extra, out_extra),
                      dtype=dtype)


class _FakeTel:
    def __init__(self):
        self.events = []

    def record_event(self, kind, **fields):
        self.events.append((kind, fields))


# ---------------------------------------------------------------------
# construction: the zero-overhead off switch
# ---------------------------------------------------------------------
def test_config_keys_parse_and_from_params_off_by_default(monkeypatch):
    for key in ("RAMSES_COMPILE_DEADLINE_S", "RAMSES_STEP_DEADLINE_S",
                "RAMSES_IO_DEADLINE_S"):
        monkeypatch.delenv(key, raising=False)
    p = _uni_params()
    assert wdog.Watchdog.from_params(p) is None, \
        "no deadlines set must mean NO watchdog (zero-overhead off)"

    p2 = _uni_params(run_extra=("compile_deadline_s=600.0\n"
                                "step_deadline_s=120.0\n"
                                "io_deadline_s=300.0"))
    assert p2.run.compile_deadline_s == 600.0
    assert p2.run.step_deadline_s == 120.0
    assert p2.run.io_deadline_s == 300.0
    wd = wdog.Watchdog.from_params(p2)
    assert wd is not None
    assert wd.deadlines == {"compile": 600.0, "step": 120.0,
                            "io": 300.0}

    # env overrides arm an unconfigured run and win over the namelist
    monkeypatch.setenv("RAMSES_STEP_DEADLINE_S", "7.5")
    wd2 = wdog.Watchdog.from_params(p)
    assert wd2 is not None and wd2.deadlines["step"] == 7.5
    assert wdog.Watchdog.from_params(p2).deadlines["step"] == 7.5

    # the ensemble scope reads &ENSEMBLE_PARAMS, not &RUN_PARAMS
    monkeypatch.delenv("RAMSES_STEP_DEADLINE_S")
    ens = types.SimpleNamespace(
        run=None, output=None,
        ensemble=types.SimpleNamespace(compile_deadline_s=0.0,
                                       step_deadline_s=30.0,
                                       io_deadline_s=0.0))
    assert wdog.Watchdog.from_params(p2, scope="ensemble") is None
    wd3 = wdog.Watchdog.from_params(ens, scope="ensemble")
    assert wd3 is not None and wd3.deadlines["step"] == 30.0


def test_unarmed_guard_spawns_no_monitor_thread():
    wd = wdog.Watchdog(io_deadline_s=5.0, hard_exit=False)
    before = threading.active_count()
    with wd.guard("step"):                 # step deadline unset
        assert threading.active_count() == before, \
            "a phase with no deadline must not start a monitor thread"
    assert wd.hangs == 0


# ---------------------------------------------------------------------
# expiry: HangDetected + telemetry + manifest-valid hang dump
# ---------------------------------------------------------------------
def test_guard_expiry_raises_dumps_and_never_resumes_from_it(tmp_path):
    tel = _FakeTel()
    wd = wdog.Watchdog(step_deadline_s=0.3, telemetry=tel,
                       base_dir=str(tmp_path), hard_exit=False)
    wd.note(nstep=3, t=0.125)
    with pytest.raises(wdog.HangDetected) as ei:
        with wd.guard("step"):
            time.sleep(30.0)               # wedged fetch stand-in
    assert ei.value.phase == "step"
    assert ei.value.deadline_s == 0.3
    assert ei.value.nstep == 3
    assert wd.hangs == 1

    kinds = [k for k, _ in tel.events]
    assert kinds == ["hang"]
    ev = tel.events[0][1]
    assert ev["phase"] == "step" and ev["nstep"] == 3

    # the diagnostics dump is manifest-valid but NEVER a resume
    # candidate: the scanner only ranks output_NNNNN directories
    dump = os.path.join(str(tmp_path), "hang_00001")
    assert os.path.isdir(dump)
    ok, reason = ckpt.validate_checkpoint(dump)
    assert ok, reason
    with open(os.path.join(dump, "hang.json")) as f:
        payload = json.load(f)
    assert payload["phase"] == "step" and payload["nstep"] == 3
    assert ckpt.latest_valid_checkpoint(
        str(tmp_path), log=lambda *_: None) is None


def test_fast_completion_never_trips():
    wd = wdog.Watchdog(step_deadline_s=5.0, hard_exit=False)
    for _ in range(3):
        with wd.guard("step"):
            pass
    with wd.guard("io"):                   # io deadline unset: off
        pass
    time.sleep(0.05)                       # let monitors drain
    assert wd.hangs == 0


def test_first_step_window_runs_under_compile_budget(tmp_path):
    wd = wdog.Watchdog(compile_deadline_s=60.0, step_deadline_s=0.2,
                       base_dir=str(tmp_path), hard_exit=False)
    assert wd._effective("step") == ("compile", 60.0)
    with wd.guard("step"):                 # compiling window: generous
        time.sleep(0.4)                    # > step deadline, no trip
    assert wd.hangs == 0
    # warmed: later windows run under the tight step budget
    assert wd._effective("step") == ("step", 0.2)
    with pytest.raises(wdog.HangDetected) as ei:
        with wd.guard("step"):
            time.sleep(30.0)
    assert ei.value.phase == "step"
    # with no compile budget the first window is a plain step window
    wd2 = wdog.Watchdog(step_deadline_s=9.0, hard_exit=False)
    assert wd2._effective("step") == ("step", 9.0)


# ---------------------------------------------------------------------
# hang fault injection
# ---------------------------------------------------------------------
def test_hang_fault_parse_and_window_clamp():
    inj = finj.FaultInjector("hang@5")
    assert inj.faults == [("hang", 5)]
    assert inj.member_of == {}
    inj2 = finj.FaultInjector("hang@3:member=1,nan@7")
    assert inj2.faults == [("hang", 3), ("nan", 7)]
    assert inj2.member_of == {0: 1}
    with pytest.raises(ValueError, match="member"):
        finj.FaultInjector("hang@3:lane=1")
    # pending hangs clamp fused windows to land exactly on step K
    assert inj.clamp_window(0, 16) == 5
    assert inj.clamp_window(3, 16) == 2
    # strict arming: first observed at nstep >= K never fires
    assert finj.FaultInjector("hang@5").maybe_hang(7) is False


def test_hang_fires_once_per_process(monkeypatch):
    monkeypatch.setenv("RAMSES_HANG_INJECT_CAP_S", "0")
    inj = finj.FaultInjector("hang@5")
    assert inj.maybe_hang(0) is False      # arms below K
    assert inj.maybe_hang(5) is True
    assert inj.maybe_hang(5) is False      # exactly-once per injector
    # a FRESH injector (the hang-policy resume rebuilds the sim inside
    # the same process) must NOT re-fire, or the bounded retry budget
    # would hang forever
    fresh = finj.FaultInjector("hang@5")
    assert fresh.maybe_hang(0) is False
    assert fresh.maybe_hang(5) is False
    # ...and once fired, the clamp stops carving windows around K
    assert fresh.clamp_window(0, 16) == 16
    finj.reset_fired()                     # test isolation hook
    again = finj.FaultInjector("hang@5")
    assert again.maybe_hang(0) is False
    assert again.maybe_hang(5) is True


def test_member_targeted_hang_batched_only(monkeypatch):
    monkeypatch.setenv("RAMSES_HANG_INJECT_CAP_S", "0")
    inj = finj.FaultInjector("hang@2:member=1")
    # the solo drivers never fire a member-targeted hang
    assert inj.maybe_hang(0) is False
    assert inj.maybe_hang(2) is False
    # the batched engine keys on that member's OWN step count
    grp = types.SimpleNamespace(members=[0, 1],
                                nstep=np.array([5, 0]))
    assert inj.maybe_hang_batch(grp, nstep_global=5) is False  # arms
    grp.nstep = np.array([7, 2])
    assert inj.maybe_hang_batch(grp, nstep_global=7) is True
    assert inj.maybe_hang_batch(grp, nstep_global=7) is False
    # a group without the member never triggers
    inj2 = finj.FaultInjector("hang@2:member=9")
    other = types.SimpleNamespace(members=[0, 1],
                                  nstep=np.array([0, 0]))
    assert inj2.maybe_hang_batch(other, nstep_global=0) is False
    other.nstep = np.array([4, 4])
    assert inj2.maybe_hang_batch(other, nstep_global=4) is False
    # clamping against member J's own (lagging) step count
    inj3 = finj.FaultInjector("hang@5:member=2")
    assert inj3.clamp_window_batch(16, 9, lambda j: {2: 3}[j]) == 2


# ---------------------------------------------------------------------
# supervisor classification + hang policy
# ---------------------------------------------------------------------
def test_classify_taxonomy():
    from ramses_tpu.resilience.stepguard import StepRetryExhausted
    assert rsup.classify(None) == "none"
    assert rsup.classify(wdog.HangDetected("step", 5.0)) == "hang"
    assert rsup.classify(StepRetryExhausted("nan ladder")) == "nan"
    assert rsup.classify(RuntimeError("boom")) == "crash"


def test_hang_policy_immediate_resume_no_backoff(tmp_path, monkeypatch):
    sleeps = []
    monkeypatch.setattr(rsup.time, "sleep", lambda s: sleeps.append(s))
    p = _uni_params(nstep=5)
    calls = {"n": 0}

    def build(restart):
        assert restart is None             # no checkpoints on disk
        return types.SimpleNamespace(nstep=0, t=0.0, telemetry=None)

    def drive(sim):
        calls["n"] += 1
        raise wdog.HangDetected("step", 2.0, nstep=3)

    with pytest.raises(wdog.HangDetected):
        rsup.supervise(build, drive, p, base_dir=str(tmp_path),
                       max_attempts=3, hang_retries=2,
                       log=lambda *_: None)
    # hang retries ride their OWN budget (2 resumes + the initial
    # attempt), never consume the 3 crash attempts, and never back off
    assert calls["n"] == 3
    assert sleeps == []

    # hang_retries=0 (the serve loop's setting): first hang escapes
    calls["n"] = 0
    with pytest.raises(wdog.HangDetected):
        rsup.supervise(build, drive, p, base_dir=str(tmp_path),
                       max_attempts=3, hang_retries=0,
                       log=lambda *_: None)
    assert calls["n"] == 1


def test_queue_requeue_and_fail_carry_hang_stage(tmp_path):
    from ramses_tpu.ensemble import queue as jq
    q = jq.init_queue(str(tmp_path / "q"))
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-hang")
    job = jq.claim(q, worker="w1")
    jq.requeue(job, error="phase 'step' exceeded 2s deadline",
               stage="hang")
    job2 = jq.claim(q, worker="w2")
    assert [e["stage"] for e in job2.record["failure_log"]] == ["hang"]
    jq.fail(job2, error="hung again", stage="hang")
    rec = jq.job_status(q, "job-hang").record
    assert [e["stage"] for e in rec["failure_log"]] == ["hang", "hang"]


# ---------------------------------------------------------------------
# zero overhead when off AND when armed (device_get pin)
# ---------------------------------------------------------------------
@pytest.mark.parametrize("armed", [False, True])
def test_watchdog_adds_zero_device_fetches(tmp_path, monkeypatch,
                                           armed):
    from ramses_tpu.amr.hierarchy import AmrSim
    extra = ("compile_deadline_s=600.0\nstep_deadline_s=600.0"
             if armed else "")
    p = params_from_string(AMR2D.format(nstep=16, run_extra=extra),
                           ndim=2)
    sim = AmrSim(p)
    assert (sim._wd is not None) is armed, \
        "the watchdog must be OFF (None) unless a deadline is set"
    sim.regrid_interval = 0
    sim.evolve(1e9, nstepmax=4)            # warm the fused chunk
    calls = {"n": 0}
    real = jax.device_get

    def counted(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counted)
    sim.evolve(1e9, nstepmax=sim.nstep + 8)
    assert calls["n"] == 0, \
        "the watchdog must never add host<->device fetches"


# ---------------------------------------------------------------------
# supervised hang-resume reproduces an uninterrupted run
# ---------------------------------------------------------------------
def test_hang_resume_matches_uninterrupted_run(tmp_path, monkeypatch):
    """Same contract as the SIGTERM test in tests/test_resilience.py:
    an injected ``hang@4`` trips the step deadline, the supervisor
    classifies it as a hang and immediately resumes from the newest
    checkpoint, and the finished run matches a clean one within
    round-off."""
    from ramses_tpu.driver import Simulation
    monkeypatch.setenv("RAMSES_HANG_INJECT_CAP_S", "30")

    ref = _uni_sim(nstep=8, dtype=jnp.float64)
    ref.evolve()
    assert ref.nstep == 8

    outdir = str(tmp_path / "run")
    os.makedirs(outdir)
    # a mid-run checkpoint for the hang policy to resume from (the
    # fused windows land exactly on step 4 thanks to the injector's
    # window clamp — here we dump that state explicitly)
    pre = _uni_sim(nstep=4, dtype=jnp.float64)
    pre.evolve()
    assert pre.nstep == 4
    # emergency-range output number (like an OpsGuard stop dump):
    # restore then re-derives the next scheduled iout from t instead
    # of skipping past the output table
    pre.dump(900, outdir)

    p = _uni_params(
        nstep=8,
        run_extra=("fault_inject='hang@4'\n"
                   "compile_deadline_s=120.0\nstep_deadline_s=2.0"),
        out_extra=f"output_dir='{outdir}'")

    def build(restart):
        return (Simulation.from_snapshot(p, restart, dtype=jnp.float64)
                if restart else Simulation(p, dtype=jnp.float64))

    logs = []
    sim = rsup.supervise(build, lambda s: s.evolve(), p,
                         base_dir=outdir, max_attempts=3,
                         hang_retries=2,
                         log=lambda m: logs.append(str(m)))
    assert any("classified hang" in m for m in logs), \
        "the deadline expiry must be classified as a hang, not a crash"
    assert any("hang retry" in m for m in logs)
    assert any("resuming from" in m for m in logs), \
        "the hang policy resumes from the newest valid checkpoint"
    assert sim.nstep == 8
    np.testing.assert_allclose(
        np.asarray(sim.state.u), np.asarray(ref.state.u),
        rtol=1e-9, atol=1e-12)
    assert abs(sim.t - ref.t) <= 1e-12 * max(abs(ref.t), 1.0)
    # the expiry left a hang diagnostics dump that the resume scanner
    # ignored (it resumed from output_00900, not hang_00001)
    assert os.path.isdir(os.path.join(outdir, "hang_00001"))
