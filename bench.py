#!/usr/bin/env python
"""Benchmark driver — the BASELINE.md protocol metrics, measured.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Sub-benchmarks (BASELINE.md / BASELINE.json "configs"):
  1. uniform  — sedov3d.nml levelmin=levelmax (config 1): pure hydro
     kernel throughput, cell-updates/sec/chip.
  2. amr      — sedov3d.nml with AMR levelmax=9 (config 2): per-level
     batched sweeps + flux correction + subcycling; cell-updates/sec/chip
     counted like the reference's mus/pt (all cells at each level x its
     substep count per coarse step, amr/adaptive_loop.f90:204-212).
  3. mg       — Poisson multigrid V-cycles/sec at 128^3 (config 3 class;
     the reference's "multigrid iters/sec" driver metric).

The headline metric is the driver's: AMR cell-updates/sec/chip on
sedov3d levelmax=9.  ``vs_baseline`` divides it by the *measured* 64-rank
CPU baseline recorded in BASELINE.json["published"] (produced by
baseline/run_baseline.py; C++ proxy kernels of the reference's hot loops
— no Fortran compiler exists in this image to build the reference
itself).  Nothing here is hard-coded.

Fail-soft design: the parent process never imports jax.  Each sub-bench
runs in its own subprocess with a hard timeout; a backend hang, Mosaic
crash, or OOM in one sub produces a structured ``{"error": ...}`` entry
for that sub and the rest still run.  Backend-init failures and timeouts
are retried once (tunnel hiccups are transient).  A GLOBAL wall-clock
budget (BENCH_TOTAL_BUDGET, default 900 s) bounds the whole protocol —
per-sub timeouts are clipped to the remaining budget, retries never
sleep past it, and every completed sub is written incrementally to
BENCH_PARTIAL.json so a driver kill still leaves results on record.
The parent ALWAYS prints the JSON line and exits 0.

Env knobs (small hosts / quick checks): BENCH_LEVEL, BENCH_STEPS,
BENCH_AMR_LMIN, BENCH_AMR_LMAX, BENCH_AMR_STEPS, BENCH_AMR_SS_STEPS,
BENCH_AMR_PROD_STEPS, BENCH_MG_N, BENCH_BF16,
BENCH_ONLY=<comma list of uniform|amr|mg|amr_poisson|ensemble|
profile_amr|halo|offload|grad — profile_amr runs tools/profile_amr.py's
per-kernel probes with incremental partial capture (also auto-escalated
after a hang-classified amr sub); halo times the explicit halo pipeline
(ppermute vs DMA, 1/2/8 shards, bytes/s + fused step time); offload
times the out-of-core deep hierarchy (&AMR_PARAMS offload) on vs off;
grad times the checkpointed adjoint rollout (ramses_tpu/diff) —
grad/forward wall-time and peak-temp-memory ratios at nstep 8 and 32 —
all opt-in like profile_amr>,
BENCH_HALO_LEVEL, BENCH_HALO_STEPS,
BENCH_OFF_LMIN, BENCH_OFF_LMAX, BENCH_OFF_STEPS, BENCH_OFF_WARM,
BENCH_GRAD_N, BENCH_GRAD_REPS,
BENCH_SUB_TIMEOUT, BENCH_TOTAL_BUDGET, BENCH_PARTIAL_PATH,
BENCH_ENS_LEVEL, BENCH_ENS_STEPS, BENCH_ENS_BATCHES,
BENCH_HANG_SUB=<sub> (deliberately wedge that child before its jax
import — the hang-isolation test hook).

Each child writes a phase-marker heartbeat sidecar
(BENCH_HEARTBEAT_<sub>.jsonl, format: ramses_tpu/telemetry/heartbeat.py)
plus an atomic result sidecar (BENCH_RESULT_<sub>.json) once its
measurement finishes; on a timeout the parent folds the child's last
phase into the error object as ``phase_at_timeout`` with
``classification: "hang"`` (also set when a child exits with the
watchdog's hang status 87), or recovers the completed result from the
sidecar when only the exit hung.  A per-pending-sub budget reserve
means one hung sub can never exhaust the global budget for the rest.
"""

import json
import os
import subprocess
import sys
import time
import traceback
import uuid

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

HERE = os.path.dirname(os.path.abspath(__file__))
MARKER = "##BENCH_SUB##"

# one trace id for the whole protocol run (same env contract as
# ramses_tpu/obs/trace, duplicated because this parent never imports
# ramses_tpu): every child heartbeat line and BENCH_RESULT_* sidecar
# carries it, so hang-classified sub-benches join worker telemetry
TRACE_ID = (os.environ.get("RAMSES_TRACE_ID", "").strip()
            or uuid.uuid4().hex)


def _stamp_ids(d):
    """trace_id + worker_id (host:pid) onto a result dict, in place."""
    d.setdefault("trace_id",
                 os.environ.get("RAMSES_TRACE_ID", "") or TRACE_ID)
    d.setdefault("worker_id", f"{os.uname().nodename}:{os.getpid()}")
    return d


def _hb_path(name):
    return os.path.join(HERE, f"BENCH_HEARTBEAT_{name}.jsonl")


def _result_path(name):
    return os.path.join(HERE, f"BENCH_RESULT_{name}.json")


def _write_result(name, d):
    """Atomic sidecar copy of the sub's result dict: the parent reads
    it back when the child was deadline-killed (or its captured stdout
    truncated) AFTER the measurement finished — the healthy value
    still lands in the driver JSON instead of a timeout error."""
    path = _result_path(name)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _read_result(name):
    try:
        with open(_result_path(name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_phases(path):
    """Inline reader for the heartbeat sidecar format
    (ramses_tpu/telemetry/heartbeat.py): the parent must never import
    ramses_tpu — the package __init__ may pull jax in."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def _load_heartbeat_mod():
    """Child-side loader of the canonical heartbeat module BY FILE PATH
    so marking 'start' doesn't first import the ramses_tpu package
    (whose compile-cache setup can import jax — the very phase the
    heartbeat exists to time)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_heartbeat",
        os.path.join(HERE, "ramses_tpu", "telemetry", "heartbeat.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_baseline():
    with open(os.path.join(HERE, "BASELINE.json")) as f:
        return json.load(f).get("published", {})


def measure_rtt(jnp, n=5):
    """Median host→device→host round trip of a trivial fetch — the
    tunnel-latency floor every sync in this process pays.  Reported
    per sub so a degraded tunnel (r04's amr capture ran alongside a
    backend-unavailable failure) can't masquerade as device time."""
    import numpy as np
    x = jnp.zeros((8,))
    float(jnp.sum(x))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(jnp.sum(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def bench_uniform(params, dtype, jnp, hb=lambda *a, **k: None):
    from ramses_tpu.driver import Simulation
    from ramses_tpu.grid.uniform import run_steps

    lvl = int(os.environ.get("BENCH_LEVEL", params.amr.levelmin))
    params.amr.levelmin = params.amr.levelmax = lvl
    sim = Simulation(params, dtype=dtype)
    hb("init")
    nsteps = int(os.environ.get("BENCH_STEPS", "20"))
    u = sim.state.u
    t = jnp.asarray(0.0, jnp.float32)
    tend = jnp.asarray(1e9, jnp.float32)
    # warm with the SAME static nsteps so the timed region holds zero
    # compiles, then hard-sync (block_until_ready alone can return early
    # over a tunneled device)
    u1, t1, _ = run_steps(sim.grid, u, t, tend, nsteps)
    float(jnp.sum(u1[0]))
    hb("warm")
    t0 = time.perf_counter()
    u2, t2, ndone = run_steps(sim.grid, u1, t1, tend, nsteps)
    float(jnp.sum(u2[0]))
    wall = time.perf_counter() - t0
    updates = sim.grid.ncell * int(ndone)
    return {
        "config": f"sedov3d uniform 2^{lvl}^3",
        "cell_updates_per_sec": updates / wall,
        "mus_per_cell_update": 1e6 * wall / max(updates, 1),
        "n": sim.grid.ncell, "steps": int(ndone), "wall_s": wall,
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_ensemble(params, dtype, jnp, hb=lambda *a, **k: None):
    """Batched ensemble throughput (ensemble/batch.py): the uniform
    Sedov scenario vmapped over batch sizes {1, 8, 32} through ONE
    compiled fused step chain.  Reports scenarios/sec (batched scenario
    windows drained per second) and aggregate cell-updates/sec per
    batch size — the fleet-amortisation curve the run service rides."""
    import numpy as np

    from ramses_tpu.ensemble.batch import EnsembleSpec, build_member
    from ramses_tpu.grid.uniform import run_steps_batch

    lvl = int(os.environ.get("BENCH_ENS_LEVEL", "6"))
    nsteps = int(os.environ.get("BENCH_ENS_STEPS", "8"))
    batches = tuple(int(b) for b in os.environ.get(
        "BENCH_ENS_BATCHES", "1,8,32").split(","))
    # BENCH_ENS_POISON=J NaN-poisons member J before the warm window —
    # the chaos hook proving a bad sweep point degrades the sub-bench
    # to a quarantine count instead of killing the whole capture
    poison = os.environ.get("BENCH_ENS_POISON", "")
    params.amr.levelmin = params.amr.levelmax = lvl
    params.ensemble.nmember = max(batches)
    # small IC perturbations make every member's data distinct without
    # splitting the compile group (traced values, not jit keys)
    params.ensemble.perturb_amp = 1e-3
    spec = EnsembleSpec.from_params(params, solver="hydro")
    hb("spec")
    per_batch = {}
    grid = None
    quarantined_max = 0
    for b in batches:
        members = [build_member(spec, k, dtype=dtype) for k in range(b)]
        grid = members[0][0]
        u = jnp.stack([m[1][0] for m in members])
        if poison != "" and int(poison) < b:
            u = u.at[(int(poison),) + (0,) * (u.ndim - 1)].set(
                float("nan"))
        t = jnp.zeros((b,), jnp.float32)
        tend = jnp.full((b,), 1e9, jnp.float32)
        # warm with the SAME (grid, nsteps) so the timed window holds
        # zero compiles — only the leading batch dim changes per b
        u1, t1, _ = run_steps_batch(grid, u, t, tend, nsteps)
        float(jnp.sum(u1[:, 0]))
        hb(f"warm_b{b}")
        t0 = time.perf_counter()
        u2, t2, nd = run_steps_batch(grid, u1, t1, tend, nsteps)
        float(jnp.sum(u2[:, 0]))
        wall = time.perf_counter() - t0
        # a poisoned member freezes (NaN time fails the in-scan
        # t < tend mask) — report it as quarantined and take the
        # throughput numbers over the healthy members only, so one bad
        # sweep point degrades the report instead of erroring it
        finite = np.isfinite(np.asarray(t2, np.float64))
        nq = int((~finite).sum())
        quarantined_max = max(quarantined_max, nq)
        if nq:
            hb("quarantine")
        b_eff = int(finite.sum())
        nd_arr = np.asarray(nd)
        steps = int(nd_arr[finite].min()) if b_eff else 0
        updates = grid.ncell * steps * b_eff
        per_batch[str(b)] = {
            "scenarios_per_sec": b_eff / wall,
            "cell_updates_per_sec": updates / wall,
            "mus_per_cell_update": 1e6 * wall / max(updates, 1),
            "steps_per_member": steps, "wall_s": wall,
            "quarantined": nq,
        }
        hb(f"timed_b{b}")
    one = per_batch.get("1", {}).get("cell_updates_per_sec")
    for d in per_batch.values():
        if one:
            # >1 means the batch amortises fixed per-step costs (launch
            # overhead, reductions) across members
            d["efficiency_vs_solo"] = d["cell_updates_per_sec"] / one
    big = per_batch[str(max(batches))]
    return {
        "config": f"sedov3d ensemble 2^{lvl}^3 x batch "
                  f"{{{','.join(str(b) for b in batches)}}}",
        "cell_updates_per_sec": big["cell_updates_per_sec"],
        "scenarios_per_sec": big["scenarios_per_sec"],
        "n": grid.ncell if grid else 0,
        "quarantined": quarantined_max,
        "per_batch": per_batch,
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_ensemble_sharded(params, dtype, jnp,
                           hb=lambda *a, **k: None):
    """Two-level parallelism throughput (ensemble/meshplan + the gang
    service): the same small-job workload served at (members x shards)
    in {(8,1) vmap, (8,8) packed, (1,8) slab}, against the
    one-device-at-a-time FIFO baseline — eight single-member jobs
    claimed and run sequentially on one device, the pre-two-level serve
    behaviour.  Every config goes through the real queue->claim->
    run_job->complete path so per-job costs (params expansion, engine
    build, checkpoint, heartbeat, result record) are in the numbers;
    the grid is deliberately tiny (BENCH_ENSH_LEVEL, default 2^2^3)
    because the subject is job-processing amortisation, not FLOPs — on
    real multi-chip meshes the packed replicas also compute
    concurrently, which forced-host devices on one core cannot show.
    Each config is timed over BENCH_ENSH_ROUNDS rounds and reports the
    minimum (job walls are ~10ms; min-of-rounds is the stable
    structural cost)."""
    import tempfile

    import numpy as np

    from ramses_tpu.ensemble import queue as jq
    from ramses_tpu.ensemble.meshplan import MeshPlan
    from ramses_tpu.ensemble.service import run_job

    lvl = int(os.environ.get("BENCH_ENSH_LEVEL", "2"))
    slab_lvl = int(os.environ.get("BENCH_ENSH_SLAB_LEVEL", "4"))
    nsteps = int(os.environ.get("BENCH_ENSH_STEPS", "4"))
    rounds = int(os.environ.get("BENCH_ENSH_ROUNDS", "5"))
    ndev = min(8, len(__import__("jax").devices()))

    def nml(level, nmember):
        return (
            "&RUN_PARAMS\nhydro=.true.\nnstepmax=%d\n/\n"
            "&AMR_PARAMS\nlevelmin=%d\nlevelmax=%d\n/\n"
            "&OUTPUT_PARAMS\ntend=1e9\n/\n"
            "&INIT_PARAMS\nd_region=1.0\np_region=1e-5\n/\n"
            "&ENSEMBLE_PARAMS\nnmember=%d\nperturb_amp=1e-3\n"
            "perturb_seed=7\nchunk_steps=%d\n/\n"
            % (nsteps, level, level, nmember, nsteps))

    def serve_round(qd, tag, jobs, device_ids, plan):
        # jobs: list of (level, nmember); timed region is the worker
        # side — claim, run, complete — exactly what a serve loop pays
        ids = [jq.submit(qd, nml(lv, nm), job_id=f"{tag}-{i}",
                         dtype=str(dtype.__name__))
               for i, (lv, nm) in enumerate(jobs)]
        t0 = time.perf_counter()
        for jid in ids:
            job = jq.claim(qd, worker="bench", job_id=jid)
            run_job(qd, job, device_ids=device_ids, plan=plan,
                    log=lambda *a, **k: None)
            jq.complete(job, {})
        return time.perf_counter() - t0

    def measure(qd, name_, jobs, device_ids, plan, rep=1):
        # rep repeats the job list back-to-back inside one timed round
        # (wall divided by rep): single-job configs are ~15ms walls and
        # need the smoothing the 8-job FIFO round gets for free
        serve_round(qd, f"warm-{name_}", jobs, device_ids, plan)
        hb(f"warm_{name_}")
        wall = min(serve_round(qd, f"{name_}-r{r}", jobs * rep,
                               device_ids, plan) / rep
                   for r in range(rounds))
        members = sum(nm for _, nm in jobs)
        updates = sum((2 ** lv) ** 3 * nsteps * nm for lv, nm in jobs)
        hb(f"timed_{name_}")
        return {"scenarios_per_sec": members / wall,
                "cell_updates_per_sec": updates / wall,
                "members": members, "n_jobs": len(jobs),
                "devices": len(device_ids), "wall_s": wall}

    small = [(lvl, 1)] * 8
    one8 = [(lvl, 8)]
    all_dev = tuple(range(ndev))
    per_config = {}
    with tempfile.TemporaryDirectory() as td:
        qd = os.path.join(td, "queue")
        per_config["fifo_1x1"] = measure(
            qd, "fifo", small, (0,), MeshPlan.single())
        per_config["8x1"] = measure(
            qd, "8x1", one8, (0,), MeshPlan.single(), rep=3)
        per_config["8x8_packed"] = measure(
            qd, "8x8", one8, all_dev, MeshPlan.packed(all_dev), rep=3)
        try:
            per_config["1x8_slab"] = measure(
                qd, "slab", [(slab_lvl, 1)], all_dev,
                MeshPlan.slab(all_dev))
        except Exception as e:  # slab needs nx % ndev == 0, >= NGHOST
            per_config["1x8_slab"] = {"error": f"{type(e).__name__}: {e}"}
    packed = per_config["8x8_packed"]
    fifo = per_config["fifo_1x1"]
    return {
        "config": f"two-level 2^{lvl}^3 x {{8x1, 8x8, 1x8@2^{slab_lvl}}} "
                  f"on {ndev} devices, min of {rounds} rounds",
        "scenarios_per_sec": packed["scenarios_per_sec"],
        "cell_updates_per_sec": packed["cell_updates_per_sec"],
        "n": (2 ** lvl) ** 3,
        "speedup_packed_vs_fifo": (fifo["wall_s"] / packed["wall_s"]),
        "per_config": per_config,
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_amr(params, dtype, jnp, hb=lambda *a, **k: None):
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.utils.timers import Timers

    lmin = int(os.environ.get("BENCH_AMR_LMIN", "7"))
    lmax = int(os.environ.get("BENCH_AMR_LMAX", "9"))
    nsteps = int(os.environ.get("BENCH_AMR_STEPS", "10"))
    params.amr.levelmin, params.amr.levelmax = lmin, lmax
    # The reference sedov3d.nml carries no refinement criteria (it is a
    # uniform-grid production file); the driver's AMR variant needs
    # some — relative density/pressure jumps, the standard shock-
    # tracking choice (hydro/godunov_utils.f90:125-260 semantics).
    params.refine.err_grad_d = 0.1
    params.refine.err_grad_p = 0.1
    sim = AmrSim(params, dtype=dtype)
    # un-instrumented sims now default to NullTimers (telemetry's
    # zero-overhead contract); this bench reads the growth-phase
    # breakdown, so it opts back into live timers explicitly
    sim.timers = Timers()
    hb("init")
    # develop the blast until the refined shell is a real working set
    warm = int(os.environ.get("BENCH_AMR_WARM", "10"))
    sim.evolve(1e9, nstepmax=warm)       # compile + develop the blast
    hb("warm")
    sim.timers.acc.clear()
    ttd = 2 ** sim.cfg.ndim

    def count_updates():
        per = {l: sim.tree.noct(l) * ttd * 2 ** (l - sim.lmin)
               for l in sim.levels()}
        return sum(per.values()), per

    n0 = sim.nstep
    updates = 0
    upd_fine = 0
    t0 = time.perf_counter()
    while sim.nstep < n0 + nsteps:
        tot, per = count_updates()      # octs move per step: count per step
        updates += tot
        upd_fine += sum(v for l, v in per.items() if l > lmin)
        if sim.regrid_interval and sim.nstep % sim.regrid_interval == 0:
            sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    sim.drain()
    wall = time.perf_counter() - t0
    sim.timers.stop()
    hb("growth")
    growth_timers = {k: round(v, 3) for k, v in sim.timers.acc.items()}

    # instrumented pass: drain the device at every section switch so the
    # breakdown attributes device time to the section that enqueued it
    # (async dispatch otherwise books everything on the next sync)
    sim.timers = Timers(sync=sim.drain)
    for _ in range(3):
        if sim.regrid_interval:
            sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    sim.timers.stop()
    inst_timers = {k: round(v, 3) for k, v in sim.timers.acc.items()}
    sim.timers = Timers()
    hb("instrumented")

    # steady-state: frozen tree -> static shapes, the whole window runs
    # as a handful of fused multi-step scans (zero host round-trips).
    # Warm with the SAME step count so the canonical chunk decomposition
    # (evolve's power-of-two scan lengths) is fully compiled before the
    # timed window — the timed region must hold zero compiles.
    sim.regrid_interval = 0
    nss = int(os.environ.get("BENCH_AMR_SS_STEPS", "10"))
    sim.evolve(1e9, nstepmax=sim.nstep + nss)
    sim.drain()
    upd1, _ = count_updates()
    t0 = time.perf_counter()
    sim.evolve(1e9, nstepmax=sim.nstep + nss)
    sim.drain()
    wss = time.perf_counter() - t0
    hb("steady_state")

    # production cadence (VERDICT-r04 Weak #9): regrids back ON at the
    # per-step cadence, on the developed quasi-static blast — the
    # apples-to-apples analogue of the reference's running mus/pt
    # average over normal operation (amr/adaptive_loop.f90:204-212)
    nprod = int(os.environ.get("BENCH_AMR_PROD_STEPS", "6"))
    sim.regrid()
    sim.step_coarse(sim.coarse_dt())        # absorb any fresh compiles
    sim.drain()
    updp = 0
    t0 = time.perf_counter()
    n0p = sim.nstep
    while sim.nstep < n0p + nprod:
        updp += count_updates()[0]
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
    sim.drain()
    wprod = time.perf_counter() - t0
    hb("production")

    # per-phase regrid wallclock (flag / balance / maps / migrate /
    # upload — hierarchy.regrid timer sections), folded out of the
    # mixed timer dicts so the regrid cost trend is directly readable:
    # "growth" covers the cadenced-growth window, "production" the
    # regrid-every-step window above
    def _regrid_fold(acc):
        return {k[len("regrid: "):]: round(float(v), 3)
                for k, v in acc.items() if k.startswith("regrid: ")}
    regrid_phases = {"growth": _regrid_fold(growth_timers),
                     "production": _regrid_fold(sim.timers.acc)}

    # run-to-run determinism: the same 3 steps from the same state must
    # be BITWISE identical on this device (north-star "bitwise-stable")
    import numpy as np
    # deep-copy: the fused step donates its state input, so a dict of
    # bare references would be dead buffers after the first replay
    u_saved = {l: jnp.array(v) for l, v in sim.u.items()}
    dt_saved, t_saved, n_saved = sim._dt_cache, sim.t, sim.nstep
    sim.evolve(1e9, nstepmax=sim.nstep + 3)
    run1 = {l: np.asarray(sim.u[l]) for l in sim.levels()}
    sim.u, sim._dt_cache, sim.t, sim.nstep = (dict(u_saved), dt_saved,
                                              t_saved, n_saved)
    sim.evolve(1e9, nstepmax=sim.nstep + 3)
    bitwise = all(run1[l].tobytes() == np.asarray(sim.u[l]).tobytes()
                  for l in sim.levels())
    hb("bitwise")
    return {
        "config": f"sedov3d AMR levelmin={lmin} levelmax={lmax}",
        # headline: all-in growth phase (every regrid + recompile cost)
        "cell_updates_per_sec": updates / wall,
        "mus_per_cell_update": 1e6 * wall / max(updates, 1),
        "steps": nsteps, "wall_s": wall,
        "refined_update_fraction": upd_fine / max(updates, 1),
        "timers_s": growth_timers,
        "timers_instrumented_s": inst_timers,
        "regrid_phase_s": regrid_phases,
        "blocked_frac": float(sim.block_stats.get("blocked_frac", 1.0)),
        "octs_per_level": {l: sim.tree.noct(l) for l in sim.levels()},
        "leaf_cells": sim.ncell_leaf(),
        "tunnel_rtt_s": measure_rtt(jnp),
        "steady_state": {
            "cell_updates_per_sec": nss * upd1 / wss,
            "mus_per_cell_update": 1e6 * wss / (nss * upd1),
            "steps": nss, "wall_s": wss,
        },
        "production_cadence": {
            "cell_updates_per_sec": updp / wprod,
            "mus_per_cell_update": 1e6 * wprod / max(updp, 1),
            "steps": nprod, "wall_s": wprod,
        },
        "bitwise_repeatable": bool(bitwise),
    }


def bench_amr_poisson(params, dtype, jnp, hb=lambda *a, **k: None):
    """AMR Poisson: live PCG iterations/sec on the hierarchy (the
    'multigrid iters/sec' driver metric covering partial levels —
    multigrid_fine's role; uniform V-cycles are bench_mg)."""
    from ramses_tpu.amr.hierarchy import AmrSim

    lmin = int(os.environ.get("BENCH_AMR_LMIN", "7"))
    lmax = int(os.environ.get("BENCH_AMR_LMAX", "9"))
    params.amr.levelmin, params.amr.levelmax = lmin, lmax
    params.refine.err_grad_d = 0.1
    params.refine.err_grad_p = 0.1
    params.run.poisson = True
    sim = AmrSim(params, dtype=dtype)
    hb("init")
    sim.evolve(1e9, nstepmax=6)          # compile + develop + warm start
    hb("warm")
    nst = 4
    iters = 0
    t0 = time.perf_counter()
    for _ in range(nst):
        sim.regrid()
        sim.step_coarse(sim.coarse_dt())
        iters += sum(int(v) for v in sim.poisson_iters.values())
    sim.drain()
    wall = time.perf_counter() - t0
    return {
        "config": f"sedov3d AMR+selfgrav levelmin={lmin} levelmax={lmax}",
        "pcg_iters_per_sec": iters / wall,
        "pcg_iters_per_step": iters / nst,
        "steps": nst, "wall_s": wall,
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_mg(dtype, jnp, hb=lambda *a, **k: None):
    import numpy as np

    from ramses_tpu.poisson.solver import mg_solve, residual

    n = int(os.environ.get("BENCH_MG_N", "128"))
    rng = np.random.default_rng(0)
    rhs = jnp.asarray(rng.standard_normal((n, n, n)), jnp.float32)
    rhs = rhs - jnp.mean(rhs)
    dx = 1.0 / n
    ncyc = 10
    # warm with the phi0 form so the timed calls hit the same compile
    phi = mg_solve(rhs, dx, phi0=rhs * 0.0, ncycle=ncyc)
    float(jnp.sum(phi))    # hard sync (block_until_ready can return
                           # early over the tunneled device)
    hb("warm")

    def run(reps):
        # feed phi*0 back as phi0: same problem (phi0 defaults to
        # zeros), but each call now DEPENDS on the previous one, so
        # the final fetch provably waits for all reps — r04's 50,613
        # vcycles/s came from timing independent enqueues
        p = phi
        t0 = time.perf_counter()
        for _ in range(reps):
            p = mg_solve(rhs, dx, phi0=p * 0.0, ncycle=ncyc)
        float(jnp.sum(p))
        return time.perf_counter() - t0, p

    # auto-scale reps until the window is >= 1s of real device work
    reps = 3
    wall, phi = run(reps)
    while wall < 1.0 and reps < 8192:
        reps = min(8192, max(reps * 2, int(reps * 1.3 / max(wall, 1e-3))))
        wall, phi = run(reps)
    r = residual(phi, rhs, dx)
    rel = float(jnp.linalg.norm(r) / jnp.linalg.norm(rhs))
    # HBM-bandwidth sanity bound: one V-cycle touches every level's phi
    # and rhs a handful of times; >=4 full-grid (phi+rhs) read+write
    # passes at the finest level alone is a generous floor.  Anything
    # faster than streaming that from HBM at 4 TB/s is a measurement
    # artifact, not a solve.
    bytes_per_cycle = 4 * (2 * 4 * n ** 3)
    vmax = 4e12 / bytes_per_cycle
    vps = ncyc * reps / wall
    return {
        "config": f"poisson multigrid {n}^3 f32",
        "vcycles_per_sec": vps,
        "rel_residual_after_10_vcycles": rel,
        "n": n, "wall_s": wall, "reps": reps,
        "sanity_max_vcycles_per_sec": vmax,
        "plausible": bool(vps <= vmax),
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_halo(params, dtype, jnp, hb=lambda *a, **k: None):
    """Explicit halo pipeline: fused sweep step time + halo bytes/s at
    1/2/8 shards, ppermute vs DMA.  The DMA backend is measured only on
    a real TPU (the interpreter is a correctness vehicle, not a perf
    path); elsewhere it reports "unavailable" so the ppermute baseline
    still lands."""
    import jax

    from ramses_tpu.driver import Simulation
    from ramses_tpu.parallel import dma_halo
    from ramses_tpu.parallel.halo import make_halo_mesh, run_steps_halo

    lvl = int(os.environ.get("BENCH_HALO_LEVEL", "6"))
    nsteps = int(os.environ.get("BENCH_HALO_STEPS", "8"))
    params.amr.levelmin = params.amr.levelmax = lvl
    sim = Simulation(params, dtype=dtype)
    u0 = sim.state.u
    nvar = int(u0.shape[0])
    ncell = int(u0.size // nvar)
    t0 = jnp.asarray(0.0, u0.dtype)
    tend = jnp.asarray(1e9, u0.dtype)
    hb("init", level=lvl)

    ndev = len(jax.devices())
    shard_counts = [k for k in (1, 2, 8)
                    if k <= ndev and (1 << lvl) % k == 0]
    backends = ["ppermute"] + (["dma"] if dma_halo.available() else [])
    runs = {}
    for k in shard_counts:
        mesh = make_halo_mesh(jax.devices()[:k])
        for backend in backends:
            key = f"{backend}_x{k}"
            dma_halo.reset_traffic()
            # warm: compile the whole window once
            u, t, n = run_steps_halo(sim.grid, mesh, u0, t0, tend,
                                     nsteps, halo_backend=backend)
            float(jnp.sum(u))
            snap = dma_halo.traffic_snapshot()   # per-STEP traced bytes
            hb("warm", config=key)
            reps, wall = 1, 0.0
            while wall < 0.5 and reps < 512:
                tstart = time.perf_counter()
                for _ in range(reps):
                    u, t, n = run_steps_halo(sim.grid, mesh, u0, t0,
                                             tend, nsteps,
                                             halo_backend=backend)
                float(jnp.sum(u))
                wall = time.perf_counter() - tstart
                if wall < 0.5:
                    reps = min(512, reps * 4)
            steps_per_sec = nsteps * reps / wall
            runs[key] = {
                "steps_per_sec": steps_per_sec,
                "step_ms": 1e3 / steps_per_sec,
                "halo_bytes_per_step": snap["halo_bytes"],
                "halo_bytes_per_sec": snap["halo_bytes"] * steps_per_sec,
                "halo_exchanges_per_step": snap["halo_exchanges"],
                "overlap_frac": snap["halo_overlap_frac"],
            }
            hb("timed", config=key)
    if "dma" not in backends:
        runs["dma"] = "unavailable (no TPU backend)"
    return {
        "config": f"halo sweep sedov3d {1 << lvl}^3 "
                  f"{str(dtype.__name__)} nsteps={nsteps}",
        "ncell": ncell,
        "runs": runs,
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_offload(dtype, jnp, hb=lambda *a, **k: None):
    """Out-of-core AMR (amr/offload.py): deep-hierarchy per-step time
    and managed-state device high-water at ``offload=off`` vs ``on``
    under a simulated HBM cap.  Both runs step the SAME schedule from
    the same ICs (the engine is pinned bitwise-identical by
    tests/test_offload.py), so the step-time ratio IS the offload
    overhead and the high-water ratio IS the capacity win."""
    import numpy as np

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string

    lmin = int(os.environ.get("BENCH_OFF_LMIN", "4"))
    lmax = int(os.environ.get("BENCH_OFF_LMAX", "8"))
    nsteps = int(os.environ.get("BENCH_OFF_STEPS", "6"))
    warm = int(os.environ.get("BENCH_OFF_WARM", "4"))
    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "nremap=1", "/",
        "&AMR_PARAMS", f"levelmin={lmin}", f"levelmax={lmax}",
        "boxlen=1.0", "offload='{mode}'", "/",
        "&INIT_PARAMS", "nregion=2", "region_type(1)='square'",
        "region_type(2)='point'", "x_center=0.5,0.5",
        "y_center=0.5,0.5", "length_x=10.0,1.0", "length_y=10.0,1.0",
        "exp_region=10.0,10.0", "d_region=1.0,0.0",
        "p_region=1e-5,0.1", "/",
        "&OUTPUT_PARAMS", "tend=1.0", "/",
        "&HYDRO_PARAMS", "gamma=1.4", "courant_factor=0.8", "/",
        "&REFINE_PARAMS", "err_grad_p=0.1", "/",
    ])

    def run(mode):
        p = params_from_string(nml.format(mode=mode), ndim=2)
        sim = AmrSim(p, dtype=dtype)
        sim.evolve(1e9, nstepmax=warm)     # compile + develop the blast
        sim.drain()
        hb("warm", mode=mode)
        stats = dict(stalls=0, prefetches=0, fetches=0, bytes_parked=0,
                     bytes_fetched=0)
        hwm = 0
        t0 = time.perf_counter()
        for _ in range(nsteps):
            if sim.regrid_interval and \
                    sim.nstep % sim.regrid_interval == 0:
                sim.regrid()
            sim.step_coarse(sim.coarse_dt())
            eng = sim._offload
            if eng is not None and eng.last_step_stats is not None:
                for k in stats:
                    stats[k] += int(eng.last_step_stats.get(k, 0))
                hwm = max(hwm, int(eng.last_step_stats
                                   .get("device_hwm_bytes", 0)))
        sim.drain()
        wall = time.perf_counter() - t0
        hb("timed", mode=mode)
        managed = sum(int(np.asarray(sim.u[l]).nbytes)
                      for l in sim.levels())
        return sim, wall, managed, stats, hwm

    s_off, w_off, managed, _, _ = run("off")
    s_on, w_on, _, stats, hwm = run("on")
    engaged = (s_on._offload is not None
               and s_on._offload.engaged(s_on))
    # cheap end-to-end cross-check: both runs stepped the same physics
    bitwise = all(
        np.array_equal(np.asarray(s_off.u[l]), np.asarray(s_on.u[l]))
        for l in s_off.levels()) and s_off.t == s_on.t
    fetches = max(stats["fetches"], 1)
    return {
        "config": f"offload sedov2d lmin={lmin} lmax={lmax} "
                  f"{str(dtype.__name__)} nsteps={nsteps}",
        "engaged": engaged,
        "bitwise_equal_on_vs_off": bitwise,
        "nsteps": nsteps,
        "off": {"step_ms": 1e3 * w_off / nsteps,
                "managed_resident_bytes": managed},
        "on": {"step_ms": 1e3 * w_on / nsteps,
               "device_hwm_bytes": hwm, **stats,
               "overlap_frac": round(
                   (stats["fetches"] - stats["stalls"]) / fetches, 3)},
        "overhead_frac": round(w_on / max(w_off, 1e-9) - 1.0, 3),
        "hwm_reduction_frac": round(1.0 - hwm / max(managed, 1), 3),
        "tunnel_rtt_s": measure_rtt(jnp),
    }


def bench_grad(dtype, jnp, hb=lambda *a, **k: None):
    """Checkpointed adjoint rollout cost profile (ramses_tpu/diff):
    grad/forward wall-time ratio and adjoint peak-temp-memory ratio at
    nstep in {8, 32} on a 2D Sedov uniform grid.  The memory baseline
    is the UN-checkpointed adjoint of the plain driver's scan (what a
    naive jax.grad would pay, O(nstep) residuals), so
    ``mem_vs_plain_adjoint < 1`` is direct evidence the sqrt-schedule
    remat (diff/rollout._scan_windows) is engaged — reported as
    ``checkpoint_engaged``."""
    import numpy as np

    import jax
    from ramses_tpu.diff.rollout import (checkpointed_run_steps,
                                         default_inner)
    from ramses_tpu.grid.boundary import BoundarySpec
    from ramses_tpu.grid.uniform import UniformGrid, run_steps
    from ramses_tpu.hydro.core import HydroStatic

    n = int(os.environ.get("BENCH_GRAD_N", "64"))
    reps = int(os.environ.get("BENCH_GRAD_REPS", "5"))
    cfg = HydroStatic(ndim=2, riemann="llf")
    grid = UniformGrid(cfg=cfg, shape=(n, n), dx=1.0 / n,
                       bc=BoundarySpec.periodic(2))
    c = n // 2
    p = np.full((n, n), 1e-5)
    p[c - 1:c + 1, c - 1:c + 1] = 0.1
    u = np.zeros((cfg.nvar, n, n))
    u[0], u[cfg.ndim + 1] = 1.0, p / (cfg.gamma - 1.0)
    uj = jnp.asarray(u, dtype)
    t0 = jnp.zeros((), uj.dtype)
    tend = jnp.asarray(1e9, uj.dtype)

    def best_of(fn, *a):
        w = []
        for _ in range(reps):
            t = time.perf_counter()
            jax.block_until_ready(fn(*a))
            w.append(time.perf_counter() - t)
        return min(w)

    def temp_bytes(compiled):
        ma = compiled.memory_analysis()
        return int(getattr(ma, "temp_size_in_bytes", 0) or 0)

    out = {"config": f"grad sedov2d n={n} {str(dtype.__name__)} "
                     f"inner=sqrt reps={reps}"}
    engaged = True
    for ns in (8, 32):
        def loss_fwd(u, ns=ns):
            return jnp.mean(run_steps(grid, u, t0, tend, ns)[0] ** 2)

        def loss_ckpt(u, ns=ns):
            return jnp.mean(
                checkpointed_run_steps(grid, u, t0, tend, ns)[0] ** 2)

        cf = jax.jit(loss_fwd).lower(uj).compile()
        cg = jax.jit(jax.grad(loss_ckpt)).lower(uj).compile()
        # memory baseline only — never timed (its compile alone shows
        # the O(nstep) residual footprint remat exists to avoid)
        cgp = jax.jit(jax.grad(loss_fwd)).lower(uj).compile()
        hb("compiled", nstep=ns)
        f_ms = 1e3 * best_of(cf, uj)
        g_ms = 1e3 * best_of(cg, uj)
        hb("timed", nstep=ns)
        fb, gb, pb = temp_bytes(cf), temp_bytes(cg), temp_bytes(cgp)
        engaged = engaged and 0 < gb < pb
        out[f"nstep{ns}"] = {
            "inner": default_inner(ns),
            "forward_ms": round(f_ms, 3),
            "grad_ms": round(g_ms, 3),
            "grad_over_forward": round(g_ms / max(f_ms, 1e-9), 3),
            "forward_temp_bytes": fb,
            "grad_temp_bytes": gb,
            "plain_adjoint_temp_bytes": pb,
            "mem_vs_forward": round(gb / max(fb, 1), 3),
            "mem_vs_plain_adjoint": round(gb / max(pb, 1), 3),
        }
    out["checkpoint_engaged"] = engaged
    out["tunnel_rtt_s"] = measure_rtt(jnp)
    return out


# the default protocol; profile_amr (the per-kernel breakdown of
# tools/profile_amr.py) and halo (the backend comparison above) are
# opt-in via BENCH_ONLY — too slow for every protocol run
DEFAULT_SUBS = ("uniform", "amr", "mg", "amr_poisson", "ensemble")
SUBS = DEFAULT_SUBS + ("profile_amr", "halo", "offload", "grad",
                       "ensemble_sharded")
# ceilings per sub; the GLOBAL budget (BENCH_TOTAL_BUDGET) always wins —
# four rounds of rc=124 driver kills came from these summing past the
# driver's wall clock whenever the tunnel hung
SUB_TIMEOUTS = {"uniform": 300, "amr": 700, "mg": 240, "amr_poisson": 500,
                "ensemble": 300, "profile_amr": 700, "halo": 300,
                "offload": 600, "grad": 400, "ensemble_sharded": 400}
# share of the REMAINING budget each sub may claim at launch
SUB_WEIGHTS = {"uniform": 0.20, "amr": 0.50, "mg": 0.35,
               "amr_poisson": 0.95, "ensemble": 0.95,
               "profile_amr": 0.95, "halo": 0.95, "offload": 0.95,
               "grad": 0.95, "ensemble_sharded": 0.95}


def run_sub_inproc(name):
    """Child-process entry: run ONE sub-bench, print its dict after MARKER."""
    hb = _load_heartbeat_mod().Heartbeat.from_env()
    hb.mark("start", sub=name)

    if os.environ.get("BENCH_HANG_SUB", "") == name:
        # deliberate-hang hook (CI/tests): wedge BEFORE the jax import
        # so the parent's deadline-kill + hang-classification path is
        # exercised in seconds, not a backend-init timeout
        hb.mark("deliberate_hang")
        while True:
            time.sleep(0.5)

    if name == "ensemble_sharded" and \
            os.environ.get("BENCH_ENSH_FORCE_CPU", "1") != "0":
        # the two-level sub runs against 8 forced host devices by
        # default (its subject is packing/claim amortisation, not
        # FLOPs); BENCH_ENSH_FORCE_CPU=0 opts into the real backend
        from ramses_tpu.platform import force_cpu_mesh
        force_cpu_mesh(8)

    import jax
    import jax.numpy as jnp
    hb.mark("import jax")

    from ramses_tpu.config import load_params
    hb.mark("load params")

    dtype = jnp.bfloat16 if os.environ.get("BENCH_BF16") else jnp.float32
    nml = os.path.join(HERE, "namelists", "sedov3d.nml")
    if name == "uniform":
        d = bench_uniform(load_params(nml, ndim=3), dtype, jnp,
                          hb=hb.mark)
    elif name == "amr":
        d = bench_amr(load_params(nml, ndim=3), dtype, jnp, hb=hb.mark)
    elif name == "mg":
        d = bench_mg(dtype, jnp, hb=hb.mark)
    elif name == "amr_poisson":
        d = bench_amr_poisson(load_params(nml, ndim=3), dtype, jnp,
                              hb=hb.mark)
    elif name == "ensemble":
        d = bench_ensemble(load_params(nml, ndim=3), dtype, jnp,
                           hb=hb.mark)
    elif name == "ensemble_sharded":
        d = bench_ensemble_sharded(load_params(nml, ndim=3), dtype, jnp,
                                   hb=hb.mark)
    elif name == "halo":
        d = bench_halo(load_params(nml, ndim=3), dtype, jnp, hb=hb.mark)
    elif name == "offload":
        d = bench_offload(dtype, jnp, hb=hb.mark)
    elif name == "grad":
        d = bench_grad(dtype, jnp, hb=hb.mark)
    elif name == "profile_amr":
        # per-kernel breakdown (tools/profile_amr.py): its probes emit
        # incrementally into the result sidecar with completed=False,
        # so a deadline-killed child still leaves a classified partial
        # capture with the phase timings gathered so far
        from tools.profile_amr import collect
        os.environ.setdefault("PROF_PROBE_DEADLINE_S", "120")
        d = collect(hb=hb.mark,
                    emit=lambda r: _write_result(name,
                                                 _stamp_ids(dict(r))))
        d["tunnel_rtt_s"] = measure_rtt(jnp)
    else:
        raise SystemExit(f"unknown sub-bench {name!r}")
    hb.mark("done")
    d["_device"] = str(jax.devices()[0].platform)
    d["_dtype"] = str(dtype.__name__)
    _stamp_ids(d)
    _write_result(name, d)
    print(MARKER + json.dumps(d), flush=True)


_PROBE_CODE = """
import json, time
t0 = time.perf_counter()
import jax
import jax.numpy as jnp
devs = jax.devices()
x = float(jnp.sum(jnp.zeros((8,))))   # one trivial device fetch
print("##TUNNEL##" + json.dumps({
    "ok": True, "ndev": len(devs),
    "platform": str(devs[0].platform),
    "elapsed_s": round(time.perf_counter() - t0, 3)}), flush=True)
"""


def tunnel_probe(timeout_s=60.0):
    """Pre-flight device-tunnel health check: a subprocess imports jax,
    lists devices, and round-trips one trivial fetch under a hard
    timeout.  Returns ``{"ok": True, ...}`` or ``{"ok": False,
    "error": ...}`` — NEVER raises, never hangs past the timeout.
    Written at the TOP level of the bench JSON so a dead tunnel is a
    first-class diagnosis, not four identical per-sub timeout errors.
    """
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=HERE)
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("##TUNNEL##"):
                return json.loads(line[len("##TUNNEL##"):])
        tail = (r.stderr or r.stdout or "")[-1000:]
        return {"ok": False,
                "error": f"probe exited rc={r.returncode} without "
                         "result", "tail": tail}
    except subprocess.TimeoutExpired:
        return {"ok": False,
                "error": f"probe timed out after {timeout_s:.0f}s "
                         "(device tunnel dead or backend hung)"}
    except Exception:
        return {"ok": False, "error": traceback.format_exc()[-1000:]}


def _backend_ish(msg):
    return any(s in msg for s in (
        "UNAVAILABLE", "Unable to initialize backend", "DEADLINE",
        "timed out", "TimeoutExpired", "backend setup",
        "Socket closed", "Connection reset"))


def run_sub(name, deadline, weight=None, reserve=0.0):
    """Parent side: launch the sub-bench subprocess with a timeout
    bounded by BOTH the per-sub ceiling and this sub's share of the
    remaining global budget; retry on backend-init failures/timeouts
    only while budget remains.  ``reserve`` (seconds) is held back for
    the subs still pending after this one, so one hung sub burns its
    own share of the budget, never the whole remainder.  Returns the
    sub dict (or error)."""
    ceiling = float(os.environ.get("BENCH_SUB_TIMEOUT",
                                   SUB_TIMEOUTS.get(name, 600)))
    if weight is None:
        weight = SUB_WEIGHTS.get(name, 0.5)
    hb_path = _hb_path(name)
    # RAMSES_TRACE_ID: the child's Heartbeat.from_env stamps it (plus
    # its host:pid) onto every sidecar marker and result JSON
    env = dict(os.environ, BENCH_HEARTBEAT_PATH=hb_path,
               RAMSES_TRACE_ID=TRACE_ID)

    def _hb_diag():
        """phase_at_timeout + recent phase trail from the child's
        heartbeat sidecar — the diagnosis BENCH_r05's four identical
        timeout errors lacked."""
        phases = _read_phases(hb_path)
        if not phases:
            return {"phase_at_timeout": "no heartbeat (child never "
                                        "started or sidecar unwritable)"}
        return {"phase_at_timeout": phases[-1].get("phase"),
                "phase_t_s": phases[-1].get("t_s"),
                "heartbeat": phases[-5:]}

    last = None
    for attempt in (1, 2):
        remaining = deadline - time.monotonic()
        if remaining < 45.0:
            return last or {"error": "skipped: global bench budget "
                                     "exhausted", "attempt": attempt}
        timeout = min(ceiling, max(45.0, weight * remaining))
        if reserve > 0.0:
            # hold back >=45s for each still-pending sub (never raising
            # the per-sub ceiling)
            timeout = min(timeout, max(45.0, remaining - reserve))
        for stale in (hb_path, _result_path(name)):
            try:
                # stale sidecars from a previous attempt/run must not
                # masquerade as this child's phase trail or result
                os.path.exists(stale) and os.remove(stale)
            except OSError:
                pass
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--sub", name],
                capture_output=True, text=True, timeout=timeout,
                cwd=HERE, env=env)
            for line in reversed(r.stdout.splitlines()):
                if line.startswith(MARKER):
                    return json.loads(line[len(MARKER):])
            got = _read_result(name)
            if got is not None and got.get("completed") is not False:
                return got        # stdout lost, sidecar survived
            tail = (r.stderr or r.stdout or "")[-2000:]
            last = {"error": f"sub-bench exited rc={r.returncode} "
                             f"without result", "tail": tail,
                    "attempt": attempt, **_hb_diag()}
            if got is not None:
                # incremental sidecar (profile_amr): keep the partial
                # phase timings alongside the diagnosis
                last["partial"] = got
            if r.returncode == 87:
                # the watchdog's HANG_EXIT_CODE, as a literal — the
                # parent never imports ramses_tpu
                last["classification"] = "hang"
                return last
            if not _backend_ish(tail):
                return last
        except subprocess.TimeoutExpired:
            got = _read_result(name)
            if got is not None and got.get("completed") is False:
                # incremental sidecar: the child was killed mid-capture
                # — classify as hang but KEEP the partial phase timings
                got.update({"error": f"sub-bench timed out after "
                                     f"{timeout:.0f}s",
                            "classification": "hang",
                            "attempt": attempt, **_hb_diag()})
                return got
            if got is not None:
                # the measurement finished; the child hung afterwards
                got["late"] = True
                return got
            last = {"error": f"sub-bench timed out after {timeout:.0f}s",
                    "classification": "hang",
                    "attempt": attempt, **_hb_diag()}
        except Exception:
            last = {"error": traceback.format_exc()[-2000:],
                    "attempt": attempt}
        if attempt == 1:
            # tunnel hiccups can outlast a short pause — but never
            # sleep the budget away; pacing shared with the namelist
            # supervisor so both retry loops back off identically
            from ramses_tpu.resilience.supervisor import backoff_delay
            time.sleep(min(backoff_delay(attempt, base=30.0, cap=30.0),
                           max(0.0,
                               deadline - time.monotonic() - 60.0)))
    return last


def main():
    only = os.environ.get("BENCH_ONLY", "")
    wanted = (tuple(s.strip() for s in only.split(",") if s.strip())
              if only else DEFAULT_SUBS)
    bad = [s for s in wanted if s not in SUBS]
    if bad:
        raise SystemExit(
            f"BENCH_ONLY={only!r}: unknown sub(s) {bad}; expected a "
            f"comma list of "
            f"uniform|amr|mg|amr_poisson|ensemble|profile_amr|halo")
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "900"))
    deadline = time.monotonic() + budget
    partial_path = os.environ.get(
        "BENCH_PARTIAL_PATH", os.path.join(HERE, "BENCH_PARTIAL.json"))

    sub = {}
    device = dtype_name = None
    # pre-flight tunnel probe: runs BEFORE any sub so a dead tunnel
    # reads {"tunnel": {"ok": false}} at the top level instead of four
    # identical per-sub timeout errors
    tunnel = tunnel_probe(
        float(os.environ.get("BENCH_PROBE_TIMEOUT", "60")))
    # clear any stale partial from a previous run BEFORE the first sub:
    # a driver kill during sub 1 must not leave run N-1's numbers
    # masquerading as run N's
    try:
        with open(partial_path, "w") as f:
            json.dump({"budget_s": budget, "tunnel": tunnel,
                       "sub": {}}, f)
    except OSError:
        pass
    for i, name in enumerate(wanted):
        sub[name] = run_sub(name, deadline,
                            weight=0.95 if len(wanted) == 1 else None,
                            reserve=45.0 * (len(wanted) - 1 - i))
        device = device or sub[name].pop("_device", None)
        dtype_name = dtype_name or sub[name].pop("_dtype", None)
        sub[name].pop("_device", None)
        sub[name].pop("_dtype", None)
        # incremental emission: whatever has completed is ALWAYS on
        # record, even if the driver kills this process mid-protocol
        try:
            with open(partial_path, "w") as f:
                json.dump({"budget_s": budget, "tunnel": tunnel,
                           "device": device, "dtype": dtype_name,
                           "sub": sub}, f)
        except OSError:
            pass

    # amr-hang escalation: a hang-classified amr capture alone says
    # nothing about WHERE the step wedged — run the per-kernel
    # breakdown (incremental sidecar) so even a degraded tunnel leaves
    # classified partial phase timings on record
    if (sub.get("amr", {}).get("classification") == "hang"
            and "profile_amr" not in wanted
            and deadline - time.monotonic() > 60.0):
        sub["profile_amr"] = run_sub("profile_amr", deadline, weight=0.95)
        sub["profile_amr"]["escalated_from"] = "amr hang"
        try:
            with open(partial_path, "w") as f:
                json.dump({"budget_s": budget, "tunnel": tunnel,
                           "device": device, "dtype": dtype_name,
                           "sub": sub}, f)
        except OSError:
            pass

    published = _load_baseline()
    base_hydro = (published.get("hydro", {})
                  .get("cell_updates_per_sec_64rank"))
    base_mg = (published.get("multigrid", {})
               .get("vcycles_per_sec_128_64rank"))
    if base_mg and "vcycles_per_sec" in sub.get("mg", {}):
        sub["mg"]["vs_baseline_64rank"] = (
            sub["mg"]["vcycles_per_sec"] / base_mg)
    if base_hydro and "cell_updates_per_sec" in sub.get("uniform", {}):
        sub["uniform"]["vs_baseline_64rank"] = (
            sub["uniform"]["cell_updates_per_sec"] / base_hydro)
    if base_hydro and "steady_state" in sub.get("amr", {}):
        sub["amr"]["steady_state"]["vs_baseline_64rank"] = (
            sub["amr"]["steady_state"]["cell_updates_per_sec"] / base_hydro)

    def ok(name):
        d = sub.get(name)
        return d if d and "error" not in d else None

    head = (ok("amr") or ok("uniform") or ok("mg") or ok("amr_poisson")
            or ok("ensemble") or {"config": "all sub-benches failed"})
    hydro_head = "cell_updates_per_sec" in head
    value = head.get("cell_updates_per_sec",
                     head.get("vcycles_per_sec",
                              head.get("pcg_iters_per_sec")))
    vs = (value / base_hydro if base_hydro and hydro_head else
          (value / base_mg if base_mg and value is not None
           and "vcycles_per_sec" in head else None))
    out = {
        "tunnel": tunnel,
        "trace_id": TRACE_ID,
        "metric": (f"cell-updates/sec/chip {head['config']}" if hydro_head
                   else (f"vcycles/sec/chip {head['config']}"
                         if "vcycles_per_sec" in head
                         else f"pcg-iters/sec/chip {head['config']}")),
        "value": value,
        "unit": ("cell-updates/s" if "cell_updates_per_sec" in head
                 else ("vcycles/s" if "vcycles_per_sec" in head
                       else "pcg-iters/s")),
        "vs_baseline": vs,
        "detail": {
            "device": device,
            "dtype": dtype_name,
            "baseline": {"hydro_64rank_cell_updates_per_sec": base_hydro,
                         "mg_64rank_vcycles_per_sec": base_mg,
                         "method": published.get("method", "unpublished")},
            "sub": sub,
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--sub":
        run_sub_inproc(sys.argv[2])
    else:
        main()
