"""Crash-consistency scanner/repairer for the run-service queue.

A fleet of workers can die at any instruction, so the queue directory
accumulates a known taxonomy of wreckage.  ``scan`` classifies it,
``repair`` fixes what is mechanically safe to fix, and
``tools/queue_fsck.py`` is the operator CLI (also invoked — repair of
the always-safe classes only — from serve startup).

Corruption classes:

* ``torn_tmp`` — a ``*.tmp`` left by a worker killed inside the
  tmp+fsync+replace record write (queue records, heartbeats, breaker
  state).  Repair: unlink; the target file is either the old or the
  new complete version by construction.
* ``orphan_heartbeat`` — a ``running/<id>.json.hb`` whose record
  moved on (finish/reclaim unlink raced a crash).  Repair: unlink.
* ``dead_running`` — a ``running/`` record whose fencing token is
  provably dead: its heartbeat carries a *superseded* fence, or both
  the heartbeat's wall stamp and file mtime agree it stopped longer
  ago than ``stale_s``.  Repair: the same fence-bumping reclaim the
  serve loop performs (requeue or fail by attempt budget).
* ``duplicate_id`` — the same job id in two state dirs (torn rename
  semantics on exotic filesystems, operator copies).  Repair: the
  record in the most-final state wins (done > failed > running >
  parked > queued); losers move to ``fsck_quarantine/``.
* ``half_staged`` — a ``results/<job>/output_*.tmp`` (or pario) left
  by a worker killed mid-checkpoint-stage, older than ``stale_s`` (a
  LIVE worker's in-flight staging is never touched).  Repair: remove
  — the atomic-commit contract says a ``.tmp`` is never a checkpoint.
* ``orphan_parked`` — a ``parked/`` job whose breaker no longer
  exists or is closed (crash between breaker close and release).
  Repair: unpark back to ``queued/``.

Exit-code contract of :func:`fsck` (what CI pins): check mode exits 0
on a clean queue and 1 when findings exist (every class above is
repairable, so 1 == "repairable verdict"); repair mode exits 0 when
everything found was repaired, 2 when something resisted.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ramses_tpu.ensemble import queue as jq
from ramses_tpu.ensemble import breaker as bk

#: duplicate_id precedence — most final wins
_FINALITY = ("done", "failed", "running", "parked", "queued")

#: classes safe to auto-repair at serve startup (no policy judgement,
#: no touching another worker's live state)
STARTUP_SAFE = ("torn_tmp", "orphan_heartbeat", "orphan_parked")


@dataclass
class Finding:
    kind: str
    path: str
    detail: str
    repair: str
    repaired: bool = False
    error: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = {"kind": self.kind, "path": self.path,
             "detail": self.detail, "repair": self.repair,
             "repaired": self.repaired}
        if self.error:
            d["error"] = self.error
        d.update(self.extra)
        return d


def _tmp_dirs(queue_dir: str) -> List[str]:
    return ([os.path.join(queue_dir, s) for s in jq.STATES]
            + [os.path.join(queue_dir, bk.BREAKERS_DIR)])


def _listdir(d: str) -> List[str]:
    try:
        return sorted(os.listdir(d))
    except OSError:
        return []


def scan(queue_dir: str, stale_s: float = 300.0) -> List[Finding]:
    """Classify every piece of wreckage in ``queue_dir`` (read-only)."""
    out: List[Finding] = []
    now = time.time()

    # torn_tmp: killed mid tmp+fsync+replace anywhere we write records
    for d in _tmp_dirs(queue_dir):
        for name in _listdir(d):
            if name.endswith(".tmp"):
                out.append(Finding(
                    "torn_tmp", os.path.join(d, name),
                    "torn record write (crash inside tmp+fsync+replace)",
                    "unlink"))

    running = os.path.join(queue_dir, "running")
    rec_names = [n for n in _listdir(running) if n.endswith(".json")]
    rec_set = set(rec_names)

    # orphan_heartbeat: sidecar outlived its record
    for name in _listdir(running):
        if not name.endswith(".json" + jq.HB_SUFFIX):
            continue
        if name[:-len(jq.HB_SUFFIX)] not in rec_set:
            out.append(Finding(
                "orphan_heartbeat", os.path.join(running, name),
                "heartbeat sidecar with no running record",
                "unlink"))

    # dead_running: provably dead fencing tokens
    for name in rec_names:
        path = os.path.join(running, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        fence = int(rec.get("fence", 0) or 0)
        hb = jq._read_hb(path)
        why = None
        if hb is not None and int(hb.get("fence", -1)) != fence:
            why = (f"heartbeat carries superseded fence "
                   f"{hb.get('fence')} (record at {fence})")
        else:
            # both wall stamp and mtime must agree it is old — a
            # skewed clock alone never condemns a live worker
            if hb is not None:
                wall_age = max(0.0, now - float(
                    hb.get("wall_unix", now)))
                try:
                    m_age = max(0.0, now - os.path.getmtime(
                        jq._hb_path(path)))
                except OSError:
                    m_age = 0.0
                age = min(wall_age, m_age)
            else:
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    continue
            if age >= float(stale_s):
                why = (f"no heartbeat progress for {age:.0f}s "
                       f"(stale_s={stale_s:.0f})")
        if why is not None:
            out.append(Finding(
                "dead_running", path, why, "reclaim (fence bump)",
                extra={"job": str(rec.get("id", "")),
                       "attempts": int(rec.get("attempts", 0))}))

    # duplicate_id: same id in >1 state dir
    seen: Dict[str, List[str]] = {}
    for state in jq.STATES:
        for name in _listdir(os.path.join(queue_dir, state)):
            if name.endswith(".json"):
                seen.setdefault(name, []).append(state)
    for name, states in sorted(seen.items()):
        if len(states) < 2:
            continue
        keep = min(states, key=_FINALITY.index)
        for state in states:
            if state == keep:
                continue
            out.append(Finding(
                "duplicate_id", os.path.join(queue_dir, state, name),
                f"job id also present in {keep}/ (which wins)",
                "quarantine", extra={"winner_state": keep}))

    # half_staged: *.tmp checkpoint stagings older than stale_s
    from ramses_tpu.resilience.checkpoint import CHECKPOINT_PREFIXES
    results = os.path.join(queue_dir, "results")
    for job in _listdir(results):
        rdir = os.path.join(results, job)
        if not os.path.isdir(rdir):
            continue
        for name in _listdir(rdir):
            if not (name.endswith(".tmp")
                    and name.startswith(CHECKPOINT_PREFIXES)):
                continue
            path = os.path.join(rdir, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age < float(stale_s):
                continue               # possibly a live worker staging
            out.append(Finding(
                "half_staged", path,
                f"checkpoint staging abandoned {age:.0f}s ago",
                "remove", extra={"job": job}))

    # orphan_parked: parked jobs whose breaker is gone or closed
    parked = os.path.join(queue_dir, "parked")
    breakers = {str(b.get("fp", "")): str(b.get("state", ""))
                for b in bk.list_breakers(queue_dir)}
    for name in _listdir(parked):
        if not name.endswith(".json"):
            continue
        path = os.path.join(parked, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        fp = bk.fingerprint_of(rec)
        state = breakers.get(fp, "")
        if state in ("open", "half_open"):
            continue
        out.append(Finding(
            "orphan_parked", path,
            f"parked but breaker {fp} is "
            + (f"'{state}'" if state else "gone"),
            "unpark", extra={"job": str(rec.get("id", ""))}))

    return out


def repair(queue_dir: str, findings: List[Finding],
           max_attempts: int = 3, backoff_base_s: float = 0.0,
           only: Optional[tuple] = None, log=print) -> List[Finding]:
    """Apply each finding's repair in place (mutates ``repaired`` /
    ``error``).  ``only`` restricts to a subset of classes (serve
    startup passes :data:`STARTUP_SAFE`)."""
    qdir = os.path.join(queue_dir, "fsck_quarantine")
    for f in findings:
        if only is not None and f.kind not in only:
            continue
        try:
            if f.kind in ("torn_tmp", "orphan_heartbeat"):
                os.unlink(f.path)
            elif f.kind == "half_staged":
                if os.path.isdir(f.path):
                    shutil.rmtree(f.path)
                else:
                    os.unlink(f.path)
            elif f.kind == "dead_running":
                name = os.path.basename(f.path)
                with open(f.path) as fh:
                    rec = json.load(fh)
                state = jq._reclaim_one(
                    queue_dir, name, rec, float("inf"), max_attempts,
                    time.time(), backoff_base_s=backoff_base_s)
                if state is None:
                    raise OSError("lost reclaim race")
                f.extra["reclaimed_to"] = state
            elif f.kind == "duplicate_id":
                os.makedirs(qdir, exist_ok=True)
                state = os.path.basename(os.path.dirname(f.path))
                dst = os.path.join(
                    qdir, f"{state}__{os.path.basename(f.path)}")
                os.replace(f.path, dst)
                jq._unlink_hb(f.path)
                f.extra["quarantined_as"] = dst
            elif f.kind == "orphan_parked":
                job = f.extra.get("job") or os.path.basename(
                    f.path)[:-len(".json")]
                if not jq.unpark(queue_dir, job,
                                 note="fsck: orphaned park released"):
                    raise OSError("unpark raced away")
            else:
                raise ValueError(f"no repair for kind {f.kind!r}")
            f.repaired = True
            if log is not None:
                log(f"fsck: repaired {f.kind}: {f.path}")
        except Exception as e:            # keep repairing the rest
            f.error = f"{type(e).__name__}: {e}"
            if log is not None:
                log(f"fsck: FAILED to repair {f.kind} {f.path}: "
                    f"{f.error}")
    return findings


def fsck(queue_dir: str, do_repair: bool = False,
         stale_s: float = 300.0, max_attempts: int = 3,
         log=print) -> "tuple[int, List[Finding]]":
    """Scan (and optionally repair); returns ``(exit_code, findings)``
    per the module-level exit-code contract."""
    findings = scan(queue_dir, stale_s=stale_s)
    if log is not None:
        for f in findings:
            log(f"fsck: [{f.kind}] {f.path} — {f.detail} "
                f"(repair: {f.repair})")
    if not do_repair:
        return (1 if findings else 0), findings
    repair(queue_dir, findings, max_attempts=max_attempts, log=log)
    bad = [f for f in findings if not f.repaired]
    return (2 if bad else 0), findings


def startup_repair(queue_dir: str, log=print) -> int:
    """Serve-startup hook: repair only the always-safe classes
    (:data:`STARTUP_SAFE`); everything else is logged and left for the
    operator CLI.  Returns the number of repairs made."""
    findings = scan(queue_dir)
    if not findings:
        return 0
    repair(queue_dir, findings, only=STARTUP_SAFE, log=log)
    n = sum(1 for f in findings if f.repaired)
    left = [f for f in findings
            if not f.repaired and f.kind not in STARTUP_SAFE]
    if left and log is not None:
        log(f"fsck: {len(left)} finding(s) need `queue_fsck --repair` "
            f"({', '.join(sorted({f.kind for f in left}))})")
    return n
