"""Turbulence forcing tests."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.turb.forcing import (TurbForcing, TurbSpec, apply_forcing)



pytestmark = pytest.mark.smoke

def _div_curl(acc, ndim):
    """Spectral divergence and curl magnitude of a real field."""
    div = sum(np.gradient(np.asarray(acc[d]), axis=d) for d in range(ndim))
    if ndim == 3:
        a = np.asarray(acc)
        curl = [np.gradient(a[2], axis=1) - np.gradient(a[1], axis=2),
                np.gradient(a[0], axis=2) - np.gradient(a[2], axis=0),
                np.gradient(a[1], axis=0) - np.gradient(a[0], axis=1)]
        curl_mag = np.sqrt(sum(c ** 2 for c in curl))
    else:
        a = np.asarray(acc)
        curl_mag = np.abs(np.gradient(a[1], axis=0)
                          - np.gradient(a[0], axis=1))
    return div, curl_mag


def test_solenoidal_projection():
    """comp_frac=0: k·f̂ = 0 exactly (spectral divergence)."""
    spec = TurbSpec(enabled=True, comp_frac=0.0, turb_rms=1.0, seed=3)
    f = TurbForcing((32, 32, 32), spec)
    kdotf = sum(np.asarray(f.khat[d]) * np.asarray(f.fhat[d])
                for d in range(3))
    scale = np.abs(np.asarray(f.fhat)).max()
    assert np.abs(kdotf).max() < 1e-12 * scale


def test_compressive_projection():
    """comp_frac=1: f̂ ∥ k (zero solenoidal part) exactly."""
    spec = TurbSpec(enabled=True, comp_frac=1.0, turb_rms=1.0, seed=3)
    f = TurbForcing((32, 32, 32), spec)
    kdotf = sum(np.asarray(f.khat[d]) * np.asarray(f.fhat[d])
                for d in range(3))
    sol = [np.asarray(f.fhat[d]) - np.asarray(f.khat[d]) * kdotf
           for d in range(3)]
    scale = np.abs(np.asarray(f.fhat)).max()
    assert max(np.abs(s).max() for s in sol) < 1e-12 * scale


def test_rms_normalization():
    spec = TurbSpec(enabled=True, turb_rms=2.5, seed=1)
    f = TurbForcing((16, 16), spec)
    acc = np.asarray(f.acceleration())
    rms = np.sqrt((acc ** 2).sum(axis=0).mean())
    assert np.isclose(rms, 2.5, rtol=1e-10)


def test_ou_decorrelation():
    """Spectral correlation decays as exp(-t/T) (sampled over many
    modes: kmax=8 on 32³ so the estimator noise is small)."""
    spec = TurbSpec(enabled=True, turb_T=1.0, seed=5, comp_frac=0.3,
                    kmax=8.0)
    f = TurbForcing((32, 32, 32), spec)
    f0 = np.asarray(f.fhat).ravel()

    def corr():
        f1 = np.asarray(f.fhat).ravel()
        return (np.real(np.vdot(f0, f1))
                / np.sqrt(np.vdot(f0, f0).real * np.vdot(f1, f1).real))

    f.update(0.25)
    assert abs(corr() - np.exp(-0.25)) < 0.12
    for _ in range(11):
        f.update(0.25)
    assert abs(corr()) < 0.2     # 3 autocorrelation times: ~e^-3


def test_decaying_mode():
    spec = TurbSpec(enabled=True, turb_type=3, turb_T=1.0, seed=2)
    f = TurbForcing((16, 16), spec)
    e0 = float(jnp.sum(jnp.abs(f.fhat) ** 2))
    f.update(1.0)
    e1 = float(jnp.sum(jnp.abs(f.fhat) ** 2))
    assert np.isclose(e1 / e0, np.exp(-2.0), rtol=1e-6)


def test_apply_forcing_conservation():
    rng = np.random.default_rng(0)
    n = 8
    u = jnp.asarray(np.abs(rng.standard_normal((4, n, n))) + 1.0)
    spec = TurbSpec(enabled=True, seed=1)
    f = TurbForcing((n, n), spec)
    acc = f.acceleration()
    dt = 0.01
    un = apply_forcing(u, acc, dt)
    # mass unchanged
    assert np.allclose(np.asarray(un[0]), np.asarray(u[0]))
    # momentum kick = rho a dt
    assert np.allclose(np.asarray(un[1] - u[1]),
                       np.asarray(u[0] * acc[0] * dt))
    # internal energy unchanged: E change equals kinetic change
    ek0 = np.asarray((u[1] ** 2 + u[2] ** 2) / (2 * u[0]))
    ek1 = np.asarray((un[1] ** 2 + un[2] ** 2) / (2 * un[0]))
    assert np.allclose(np.asarray(un[3] - u[3]), ek1 - ek0, atol=1e-14)


def test_checkpoint_roundtrip(tmp_path):
    spec = TurbSpec(enabled=True, seed=9)
    f = TurbForcing((8, 8, 8), spec)
    f.update(0.3)
    p = str(tmp_path / "turb.npz")
    f.save(p)
    g = TurbForcing.load(p, spec)
    assert np.allclose(np.asarray(f.fhat), np.asarray(g.fhat))
    f.update(0.1)
    g.update(0.1)
    assert np.allclose(np.asarray(f.acceleration()),
                       np.asarray(g.acceleration()))


def test_driver_turb_stirring():
    """Quiescent box gains kinetic energy under driving."""
    from ramses_tpu.driver import Simulation
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc"},
        "turb_params": {"turb": True, "turb_rms": 2.0, "turb_t": 0.5,
                        "turb_seed": 11},
        "output_params": {"noutput": 1, "tout": [0.1], "tend": 0.1},
    }
    p = params_from_dict(groups, ndim=2)
    sim = Simulation(p, dtype=jnp.float64)
    sim.evolve(chunk=4)
    u = np.asarray(sim.state.u)
    ke = ((u[1] ** 2 + u[2] ** 2) / (2 * u[0])).sum()
    assert ke > 1e-4
    assert np.all(np.isfinite(u))
    # mass conserved
    assert np.isclose(u[0].mean(), 1.0, rtol=1e-12)


def test_driver_dump_restart_same_forcing(tmp_path):
    """A driven-turbulence restart continues the SAME OU realization:
    dump mid-run, restore, and the restarted sim's next forcing update
    must match the continuous run's bitwise (VERDICT-r04 Missing #2;
    ``turb/write_turb_fields.f90`` role)."""
    from ramses_tpu.driver import Simulation
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 3, "levelmax": 3, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc"},
        "turb_params": {"turb": True, "turb_rms": 2.0, "turb_t": 0.5,
                        "turb_seed": 3},
        "output_params": {"noutput": 1, "tout": [0.05], "tend": 0.05},
    }
    p = params_from_dict(groups, ndim=2)
    sim = Simulation(p, dtype=jnp.float64)
    sim.evolve(chunk=2)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    import os
    assert os.path.exists(os.path.join(out, "turb_fields.npz"))
    sim2 = Simulation.from_snapshot(p, out, dtype=jnp.float64)
    # same spectral state restored...
    assert np.array_equal(np.asarray(sim.turb.fhat),
                          np.asarray(sim2.turb.fhat))
    assert np.array_equal(np.asarray(sim.turb.key),
                          np.asarray(sim2.turb.key))
    # ...and the NEXT update (same dt) produces bitwise-identical
    # forcing on both
    sim.turb.update(0.01)
    sim2.turb.update(0.01)
    assert np.array_equal(np.asarray(sim.turb.acceleration()),
                          np.asarray(sim2.turb.acceleration()))
