"""Sink particles: creation, accretion, merging, motion.

Capability core of ``pm/sink_particle.f90`` (3,010 LoC): density-threshold
creation at local maxima (the clump-finder-seeded path reduces to this on
a uniform grid), Bondi and threshold accretion (``grow_sink:575``,
``accrete_sink:722``), pairwise merging, leapfrog motion in the gas
gravity field.  Sinks are few (≤ thousands): all bookkeeping is host
numpy; only the gas-side mass removal touches device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from ramses_tpu.units import Units, factG_in_cgs


@dataclass(frozen=True)
class SinkSpec:
    """&SINK_PARAMS subset (pm/read_sink_feedback_params.f90)."""
    enabled: bool = False
    n_sink: float = 1e10           # creation threshold [H/cc]
    accretion_scheme: str = "bondi"   # bondi | threshold | none
    c_acc: float = 0.75            # threshold-accretion fraction
    r_acc_cells: float = 2.0       # accretion radius in cells
    merging_cells: float = 2.0     # merge radius in cells
    nsinkmax: int = 1000

    @classmethod
    def from_params(cls, p) -> "SinkSpec":
        raw = p.raw.get("sink_params", {}) if p.raw else {}

        def g(k, dflt):
            v = raw.get(k, dflt)
            return v[0] if isinstance(v, list) else v

        return cls(enabled=bool(g("create_sinks", False)),
                   n_sink=float(g("n_sink", 1e10)),
                   accretion_scheme=str(g("accretion_scheme", "bondi")),
                   c_acc=float(g("c_acc", 0.75)),
                   r_acc_cells=float(g("r_acc_cells", 2.0)),
                   merging_cells=float(g("merging_cells", 2.0)),
                   nsinkmax=int(g("nsinkmax", 1000)))


@dataclass
class SinkSet:
    """SoA sink arrays (host)."""
    x: np.ndarray          # [n, ndim]
    v: np.ndarray          # [n, ndim]
    m: np.ndarray          # [n]
    tform: np.ndarray      # [n]
    idp: np.ndarray        # [n]
    next_id: int = 1

    @classmethod
    def empty(cls, ndim: int) -> "SinkSet":
        return cls(x=np.zeros((0, ndim)), v=np.zeros((0, ndim)),
                   m=np.zeros(0), tform=np.zeros(0),
                   idp=np.zeros(0, dtype=np.int64))

    @property
    def n(self) -> int:
        return len(self.m)


def create_sinks(u, sinks: SinkSet, spec: SinkSpec, units: Units,
                 dx: float, t: float, gamma: float):
    """Threshold creation (``create_sink:6``): cells above n_sink that are
    local density maxima and farther than the merge radius from existing
    sinks convert their excess gas into a new sink."""
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    rho = u[0]
    nH = rho * units.scale_nH
    d_thr = spec.n_sink / units.scale_nH
    cand = nH > spec.n_sink
    if not cand.any() or sinks.n >= spec.nsinkmax:
        return u, sinks

    # local maximum over the 3^ndim neighbourhood (periodic)
    is_max = np.ones_like(cand)
    for d in range(ndim):
        for s in (-1, 1):
            is_max &= rho >= np.roll(rho, s, axis=d)
    cand &= is_max
    idx = np.argwhere(cand)
    if len(idx) == 0:
        return u, sinks

    xnew = (idx + 0.5) * dx
    # respect exclusion radius around existing sinks
    if sinks.n:
        d2 = ((xnew[:, None, :] - sinks.x[None, :, :]) ** 2).sum(-1)
        ok = (d2 > (spec.merging_cells * dx) ** 2).all(axis=1)
        idx, xnew = idx[ok], xnew[ok]
    room = spec.nsinkmax - sinks.n
    idx, xnew = idx[:room], xnew[:room]
    if len(idx) == 0:
        return u, sinks

    cells = tuple(idx.T)
    dm_rho = np.maximum(rho[cells] - d_thr, 0.0)
    mnew = dm_rho * vol
    vel = np.stack([u[1 + d][cells] / rho[cells] for d in range(ndim)],
                   axis=1)
    frac = 1.0 - dm_rho / rho[cells]
    for iv in range(u.shape[0]):
        u[iv][cells] = u[iv][cells] * frac

    sinks = SinkSet(
        x=np.concatenate([sinks.x, xnew]),
        v=np.concatenate([sinks.v, vel]),
        m=np.concatenate([sinks.m, mnew]),
        tform=np.concatenate([sinks.tform, np.full(len(idx), t)]),
        idp=np.concatenate([sinks.idp, sinks.next_id
                            + np.arange(len(idx), dtype=np.int64)]),
        next_id=sinks.next_id + len(idx))
    return u, sinks


def accrete(u, sinks: SinkSet, spec: SinkSpec, units: Units, dx: float,
            dt: float, gamma: float):
    """Accretion onto sinks (``grow_sink:575``, ``accrete_sink:722``).

    bondi:     mdot = 4π G² M² ρ / (c_s² + v_rel²)^{3/2}
    threshold: remove c_acc of the gas above n_sink in the host cell
    Both capped at 90% of the host cell's gas.
    """
    if sinks.n == 0 or spec.accretion_scheme == "none":
        return u, sinks
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    shape = u.shape[1:]
    cells = tuple(np.clip((sinks.x[:, d] / dx).astype(np.int64), 0,
                          shape[d] - 1) for d in range(ndim))
    rho = u[0][cells]
    vgas = np.stack([u[1 + d][cells] / np.maximum(rho, 1e-300)
                     for d in range(ndim)], axis=1)
    ek = 0.5 * (np.stack([u[1 + d][cells] for d in range(ndim)], axis=1)
                ** 2).sum(1) / np.maximum(rho, 1e-300)
    press = (gamma - 1.0) * (u[1 + ndim][cells] - ek)
    cs2 = gamma * np.maximum(press, 1e-300) / np.maximum(rho, 1e-300)

    if spec.accretion_scheme == "bondi":
        # G in code units: G_code = G_cgs * scale_d * scale_t^2
        g_code = factG_in_cgs * units.scale_d * units.scale_t ** 2
        vrel2 = ((sinks.v - vgas) ** 2).sum(1)
        mdot = (4 * np.pi * g_code ** 2 * sinks.m ** 2 * rho
                / np.maximum(cs2 + vrel2, 1e-300) ** 1.5)
        dm = np.minimum(mdot * dt, 0.9 * rho * vol)
    else:  # threshold
        d_thr = spec.n_sink / units.scale_nH
        dm = np.minimum(spec.c_acc * np.maximum(rho - d_thr, 0.0) * vol,
                        0.9 * rho * vol)

    dm_rho = dm / vol
    frac = 1.0 - dm_rho / np.maximum(rho, 1e-300)
    # conservative momentum transfer: sink absorbs gas momentum
    mom_g = np.stack([u[1 + d][cells] for d in range(ndim)], axis=1)
    p_acc = mom_g * (dm_rho / np.maximum(rho, 1e-300))[:, None] * vol
    for iv in range(u.shape[0]):
        np.multiply.at(u[iv], cells, frac)
    newm = sinks.m + dm
    sinks.v = (sinks.v * sinks.m[:, None] + p_acc) \
        / np.maximum(newm, 1e-300)[:, None]
    sinks.m = newm
    return u, sinks


def merge_sinks(sinks: SinkSet, spec: SinkSpec, dx: float) -> SinkSet:
    """Pairwise merge within the merge radius, conserving mass/momentum."""
    n = sinks.n
    if n < 2:
        return sinks
    alive = np.ones(n, dtype=bool)
    r2 = (spec.merging_cells * dx) ** 2
    order = np.argsort(-sinks.m)            # heaviest survives
    for a in order:
        if not alive[a]:
            continue
        d2 = ((sinks.x - sinks.x[a]) ** 2).sum(1)
        near = alive & (d2 < r2)
        near[a] = False
        if near.any():
            mt = sinks.m[a] + sinks.m[near].sum()
            sinks.x[a] = (sinks.x[a] * sinks.m[a]
                          + (sinks.x[near] * sinks.m[near, None]).sum(0)) / mt
            sinks.v[a] = (sinks.v[a] * sinks.m[a]
                          + (sinks.v[near] * sinks.m[near, None]).sum(0)) / mt
            sinks.m[a] = mt
            alive[near] = False
    return SinkSet(x=sinks.x[alive], v=sinks.v[alive], m=sinks.m[alive],
                   tform=sinks.tform[alive], idp=sinks.idp[alive],
                   next_id=sinks.next_id)


def drift_kick(sinks: SinkSet, f_field, dx: float, dt: float,
               boxlen: float) -> SinkSet:
    """Leapfrog sink motion in the gas gravity field (NGP gather)."""
    if sinks.n == 0:
        return sinks
    if f_field is not None:
        f = np.asarray(f_field)
        ndim = sinks.x.shape[1]
        shape = f.shape[1:]
        cells = tuple(np.clip((sinks.x[:, d] / dx).astype(np.int64), 0,
                              shape[d] - 1) for d in range(ndim))
        acc = np.stack([f[d][cells] for d in range(ndim)], axis=1)
        sinks.v = sinks.v + acc * dt
    sinks.x = np.mod(sinks.x + sinks.v * dt, boxlen)
    return sinks
