"""Shard-invariance suite for the explicit slab-sharded dense path.

The contract of :mod:`ramses_tpu.parallel.dense_slab`: on the XLA
path, mesh-of-1 (global-view ``dense_sweep``) and mesh-of-8 (slab
``shard_map`` + ppermute halos) agree BITWISE — ghost cells are exact
copies of their global-periodic values and the per-cell arithmetic is
the shared :func:`ramses_tpu.amr.kernels.dense_interior_update`, so no
float differs.  Both sides must be jitted: XLA's fusion differs from
eager op-by-op execution at the ULP level, but is shape-stable, which
is exactly what the slab decomposition relies on.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from functools import partial

from ramses_tpu.amr import bitperm
from ramses_tpu.amr import kernels as K
from ramses_tpu.grid.boundary import BoundarySpec
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.parallel import dense_slab as DS
from ramses_tpu.parallel.mesh import oct_mesh


def _kinds(bc):
    return tuple((f[0].kind, f[1].kind) for f in bc.faces)


def _sedov_like(ncell, nvar, ndim, seed=0):
    """Smooth random periodic state: positive density/energy, small
    velocities (keeps the hllc solver away from vacuum floors)."""
    rng = np.random.default_rng(seed)
    u = np.ones((ncell, nvar), np.float32)
    u[:, 0] = 1.0 + 0.1 * rng.random(ncell, dtype=np.float64)
    u[:, 1:1 + ndim] = 0.05 * rng.standard_normal(
        (ncell, ndim)).astype(np.float32)
    u[:, nvar - 1] = 1.0 + 0.1 * rng.random(ncell, dtype=np.float64)
    return jnp.asarray(u)


def _oct_mask(ncell, ndim, frac=0.3, seed=1):
    """Oct-aligned refined mask (flat order) + its dense-ravel twin."""
    rng = np.random.default_rng(seed)
    noct = ncell // (1 << ndim)
    lvl = 0
    n = ncell
    # recover lvl from ncell = 2**(ndim*lvl)
    while (1 << (ndim * lvl)) != ncell:
        lvl += 1
    ok_flat = np.repeat(rng.random(noct) < frac, 1 << ndim)
    ok_dense = np.asarray(
        bitperm.flat_to_dense(jnp.asarray(ok_flat), lvl, ndim)
    ).reshape(-1)
    return jnp.asarray(ok_flat), jnp.asarray(ok_dense), n


# ----------------------------------------------------------------------
# bitperm slab locality
# ----------------------------------------------------------------------
@pytest.mark.parametrize("ndim,lvl,mbits", [
    (3, 3, 0), (3, 3, 1), (3, 3, 2), (3, 3, 3), (3, 4, 4),
    (2, 4, 3), (2, 3, 1), (1, 5, 3),
])
def test_bitperm_slab_locality(ndim, lvl, mbits):
    """Per-chunk conversion == global conversion sliced: a contiguous
    flat row chunk IS an axis-aligned dense sub-box, converted with
    zero cross-chunk data motion."""
    ncell = 1 << (ndim * lvl)
    rows = jnp.arange(ncell * 2, dtype=jnp.int64).reshape(ncell, 2)
    dense = np.asarray(bitperm.flat_to_dense(rows, lvl, ndim))
    loc = bitperm.slab_shape(lvl, ndim, mbits)
    coords = bitperm.chunk_coords(lvl, ndim, mbits)
    csz = ncell >> mbits
    for D, g in enumerate(coords):
        chunk = rows[D * csz:(D + 1) * csz]
        got = np.asarray(
            bitperm.flat_to_dense_slab(chunk, lvl, ndim, mbits))
        sl = tuple(slice(g[d] * loc[d], (g[d] + 1) * loc[d])
                   for d in range(ndim))
        np.testing.assert_array_equal(got, dense[sl])
        # and the inverse round-trips
        back = np.asarray(
            bitperm.dense_to_flat_slab(jnp.asarray(got), lvl, ndim,
                                       mbits))
        np.testing.assert_array_equal(back, np.asarray(chunk))


def test_slab_spec_geometry():
    """z is cut first: 2 devices -> z-slabs, 8 -> octants (3D); the
    2D lvl-4 8-way cut is a (2, 4) pencil grid."""
    mesh = oct_mesh(jax.devices())
    bc = _kinds(BoundarySpec.periodic(3))
    spec = DS.build_slab_spec(mesh, 3, 3, (8, 8, 8), 512, bc)
    assert spec is not None
    assert spec.grid == (2, 2, 2) and spec.loc == (4, 4, 4)
    bc2 = _kinds(BoundarySpec.periodic(2))
    spec2 = DS.build_slab_spec(mesh, 4, 2, (16, 16), 256, bc2)
    assert spec2 is not None
    assert spec2.grid == (2, 4) and spec2.loc == (8, 4)
    # gates: padded rows, non-cubic shape, non-periodic bc, tiny shards
    assert DS.build_slab_spec(mesh, 3, 3, (8, 8, 8), 520, bc) is None
    assert DS.build_slab_spec(mesh, 3, 3, (8, 8, 16), 1024, bc) is None
    assert DS.build_slab_spec(mesh, 3, 3, (8, 8, 8), 512,
                              ((0, 0), (0, 0), (2, 2))) is None
    assert DS.build_slab_spec(mesh, 1, 3, (2, 2, 2), 8, bc) is None


# ----------------------------------------------------------------------
# hydro sweep shard invariance (mask + ret_flux included)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("ndim,lvl", [(3, 3), (2, 4)])
@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("ret_flux", [False, True])
def test_dense_sweep_slab_bitwise(ndim, lvl, masked, ret_flux):
    cfg = HydroStatic(ndim=ndim, gamma=1.4, riemann="hllc")
    bc = BoundarySpec.periodic(ndim)
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    u = _sedov_like(ncell, cfg.nvar, ndim)
    ok_flat = ok_dense = None
    if masked:
        ok_flat, ok_dense, _ = _oct_mask(ncell, ndim)
    dt = jnp.float32(1e-3)
    dx = 1.0 / n
    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              _kinds(bc))
    assert spec is not None
    slab = jax.jit(partial(DS.dense_sweep_slab, spec=spec, cfg=cfg,
                           dx=dx, ret_flux=ret_flux))
    ref = K.dense_sweep(u, None, None, ok_dense, dt, dx, shape, bc,
                        cfg, ret_flux=ret_flux)
    got = slab(u, ok_flat, dt)
    if ret_flux:
        np.testing.assert_array_equal(np.asarray(ref[0]),
                                      np.asarray(got[0]))
        np.testing.assert_array_equal(np.asarray(ref[1]),
                                      np.asarray(got[1]))
    else:
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


# ----------------------------------------------------------------------
# refine flags shard invariance (hydro + MHD criteria)
# ----------------------------------------------------------------------
def test_refine_flags_slab_bitwise():
    ndim, lvl = 2, 4
    cfg = HydroStatic(ndim=ndim, gamma=1.4)
    bc = BoundarySpec.periodic(ndim)
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    u = _sedov_like(ncell, cfg.nvar, ndim, seed=2)
    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              _kinds(bc))
    eg = (0.05, 0.05, -1.0)
    fls = (1e-10, 1e-10, 1e-10)
    ref = K.dense_refine_flags(u, None, None, eg, fls, shape, bc, cfg,
                               dx=1.0 / n)
    fn = partial(K._flags_fn(cfg), err_grad=eg, floors=fls, spatial0=0,
                 cfg=cfg)
    got = jax.jit(partial(DS.dense_flags_slab, spec=spec, flags_fn=fn,
                          twotondim=2 ** ndim))(u)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_mhd_flags_slab_bitwise():
    from ramses_tpu.mhd import uniform as mu
    from ramses_tpu.mhd.amr import _mhd_grad_flags
    from ramses_tpu.mhd.core import MhdStatic

    ndim, lvl = 2, 4
    cfg = MhdStatic(ndim=ndim, gamma=1.4)
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    rng = np.random.default_rng(3)
    u = np.zeros((ncell, cfg.nvar), np.float32)
    u[:, 0] = 1.0 + 0.1 * rng.random(ncell)
    u[:, 4] = 1.0 + 0.1 * rng.random(ncell)      # E (mhd IP slot)
    u[:, 5] = 0.1 * rng.standard_normal(ncell)   # B_left x
    u = jnp.asarray(u)
    eg = (0.05, 0.05, -1.0)
    fls = (1e-10, 1e-10, 1e-10)
    bc_kinds = ((0, 0),) * ndim

    def global_flags(u_flat):
        ud = jnp.moveaxis(K.rows_to_dense(u_flat, None, shape), -1, 0)
        up = mu._pad(ud, ndim, bc_kinds, 1)
        ok = _mhd_grad_flags(up, eg, fls, 0, cfg)
        ok = ok[tuple(slice(1, -1) for _ in range(ndim))]
        return K.dense_to_rows(ok, None, shape).reshape(
            ncell // 2 ** ndim, 2 ** ndim)

    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell, bc_kinds)
    fn = partial(_mhd_grad_flags, eg=eg, fls=fls, spatial0=0, cfg=cfg)
    ref = jax.jit(global_flags)(u)
    got = jax.jit(partial(DS.dense_flags_slab, spec=spec, flags_fn=fn,
                          twotondim=2 ** ndim))(u)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


# ----------------------------------------------------------------------
# RT transport shard invariance
# ----------------------------------------------------------------------
def test_rt_transport_slab_bitwise():
    from ramses_tpu.rt import m1

    ndim, lvl = 2, 4
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    ncols = 1 + ndim
    rng = np.random.default_rng(4)
    rad = jnp.asarray(rng.random((ncell, ncols)).astype(np.float64))
    dt, dx, c_red = 1e-3, 1.0 / n, 1.0

    def global_step(rows):
        dense = K.rows_to_dense(rows, None, shape)
        N, F = dense[..., 0], jnp.stack(
            [dense[..., 1 + c] for c in range(ndim)])
        N, F = m1.transport_step(N, F, dt, dx, c_red, ndim,
                                 periodic=True)
        cols = [N[..., None]] + [F[c][..., None] for c in range(ndim)]
        return K.dense_to_rows(jnp.concatenate(cols, axis=-1), None,
                               shape)

    def local_fn(ext):
        N, F = ext[..., 0], jnp.stack(
            [ext[..., 1 + c] for c in range(ndim)])
        N, F = m1.transport_step(N, F, dt, dx, c_red, ndim,
                                 periodic=True)
        cols = [N[..., None]] + [F[c][..., None] for c in range(ndim)]
        out = jnp.concatenate(cols, axis=-1)
        return out[tuple(slice(1, -1) for _ in range(ndim))]

    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              ((0, 0),) * ndim)
    ref = jax.jit(global_step)(rad)
    got = jax.jit(partial(DS.dense_apply_slab, spec=spec,
                          local_fn=local_fn, ng=1))(rad)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


# ----------------------------------------------------------------------
# full coarse step: mesh-of-1 sim vs mesh-of-8 sharded sim
# ----------------------------------------------------------------------
def test_sedov_step_shard_invariance():
    """Complete-level 3D sedov: two coarse steps of the single-device
    AmrSim vs the 8-device ShardedAmrSim (slab path), bitwise."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "/",
        "&OUTPUT_PARAMS", "tend=0.01", "/",
    ])
    p1 = params_from_string(nml, ndim=3)
    s1 = AmrSim(p1, dtype=jnp.float32)
    p8 = params_from_string(nml, ndim=3)
    s8 = ShardedAmrSim(p8, devices=jax.devices(), dtype=jnp.float32)
    spec8 = s8._fused_spec()
    assert spec8.slab and spec8.slab[0] is not None, \
        "slab path did not engage on the 8-device mesh"
    for _ in range(2):
        dt = min(s1.coarse_dt(), s8.coarse_dt())
        s1.step_coarse(dt)
        s8.step_coarse(dt)
    for l in s1.levels():
        np.testing.assert_array_equal(np.asarray(s1.u[l]),
                                      np.asarray(s8.u[l]))


def test_multi_step_donation_no_warnings():
    """The steady-state jits donate the state dict: compiling and
    running them must not emit donation warnings, and threading the
    returned state back in must work (buffers alias)."""
    import warnings

    from ramses_tpu.amr.hierarchy import (AmrSim, _fused_coarse_step,
                                          _fused_multi_step)
    from ramses_tpu.config import params_from_string

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
        "&OUTPUT_PARAMS", "tend=0.01", "/",
    ])
    sim = AmrSim(params_from_string(nml, ndim=3), dtype=jnp.float32)
    spec = sim._fused_spec()
    dt = jnp.asarray(1e-4, sim.dtype)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        u, dtn = _fused_coarse_step(sim.u, sim.dev, {}, dt, spec, None)
        u, t, dtc, ndone = _fused_multi_step(
            u, sim.dev, jnp.asarray(0.0), jnp.asarray(1e9),
            dtn.astype(jnp.result_type(float)), spec, 4, None)
        jax.block_until_ready(u)
    donate_msgs = [str(w.message) for w in rec
                   if "donat" in str(w.message).lower()]
    assert not donate_msgs, donate_msgs
    assert int(ndone) == 4
