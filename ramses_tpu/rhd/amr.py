"""Special-relativistic hydrodynamics on the AMR hierarchy.

The rhd solver family of the reference shadows the amr driver files with
relativistic kernels (``rhd/`` own umuscl/godunov_utils/condinit,
SURVEY.md §2.4); here the same inversion happens through the physics
dispatch in ``amr/kernels.py``: :class:`RhdAmrSim` IS :class:`AmrSim`
with the static cfg swapped to :class:`~ramses_tpu.rhd.core.RhdStatic`,
so prolongation/restriction/flux-correction/subcycling/regrid machinery
is shared and only the sweep kernels, the Courant evaluation, and the
refinement criteria (Lorentz-gradient) are relativistic.

Restrictions (the reference rhd solver has the same shape): no
self-gravity coupling, no particles, no cosmology — SRHD in c=1 units.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.rhd import core
from ramses_tpu.rhd.core import RhdStatic
from ramses_tpu.rhd.driver import rhd_region_prims


class RhdAmrSim(AmrSim):
    """Adaptive SRHD run: region ICs, Lorentz/gradient refinement."""

    @staticmethod
    def _make_cfg(params: Params):
        return RhdStatic.from_params(params)

    def __init__(self, params: Params, dtype=jnp.float64, **kw):
        if bool(params.run.poisson) or bool(params.run.pic):
            raise NotImplementedError(
                "rhd-amr: self-gravity/particles are not part of the "
                "SRHD solver family (reference rhd/ has no poisson "
                "coupling)")
        if bool(params.run.cosmo):
            raise NotImplementedError("rhd-amr: no cosmology (c=1 units)")
        spec = bmod.BoundarySpec.from_params(params)
        for lo, hi in ((f[0].kind, f[1].kind) for f in spec.faces):
            for k in (lo, hi):
                if k == bmod.INFLOW:
                    raise NotImplementedError(
                        "rhd boundaries: periodic/outflow/reflect only")
        super().__init__(params, dtype=dtype, **kw)

    def _ic_state(self, lvl: int) -> jnp.ndarray:
        """Relativistic conservative ICs on this level's padded cells."""
        m = self.maps[lvl]
        centers = self.tree.cell_centers(lvl, self.boxlen)
        x = [centers[:, d] for d in range(self.cfg.ndim)]
        q = rhd_region_prims(x, self.params, self.cfg)   # [nvar, ncell]
        u = np.asarray(core.prim_to_cons(jnp.asarray(q), self.cfg))
        # pad rows: floor-state vacuum (D=smallr at rest)
        qvac = np.zeros((self.cfg.nvar, 1))
        qvac[0] = self.cfg.smallr
        qvac[4] = self.cfg.smallp
        uvac = np.asarray(core.prim_to_cons(jnp.asarray(qvac), self.cfg))
        out = np.tile(uvac.T, (m.ncell_pad, 1))
        out[:u.shape[1]] = u.T
        return self._place(jnp.asarray(out, dtype=self.dtype), "cells")

    # ------------------------------------------------------------------
    # snapshot guard: the inherited writer converts with the Newtonian
    # prim/cons relations (io/snapshot.cons_to_prim_out) which would
    # silently corrupt (D, S, τ) state — refuse until the rhd format
    # (the reference rhd solver's own output_hydro shadow) exists
    # ------------------------------------------------------------------
    def dump(self, *a, **kw):
        raise NotImplementedError("rhd-amr snapshots: not yet supported")

    @classmethod
    def from_snapshot(cls, *a, **kw):
        raise NotImplementedError("rhd-amr restart: not yet supported")

    # ------------------------------------------------------------------
    # diagnostics
    # ------------------------------------------------------------------
    def leaf_prims(self, lvl: int):
        """(centers, primitives [n, nvar]) of leaf cells at one level."""
        xc, u = self.leaf_sample(lvl)
        q = np.asarray(core.cons_to_prim(jnp.asarray(u.T), self.cfg))
        return xc, q.T

    def max_lorentz(self) -> float:
        w = 1.0
        for l in self.levels():
            _, q = self.leaf_prims(l)
            if len(q):
                v2 = (q[:, 1:4] ** 2).sum(axis=1)
                w = max(w, float(
                    (1.0 / np.sqrt(np.maximum(1.0 - v2, 1e-14))).max()))
        return w
