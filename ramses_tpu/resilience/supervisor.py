"""Supervised retry-with-resume loop for namelist-driven runs.

``supervise(build, drive, params, ...)`` runs a bounded attempt loop:
attempt 1 resolves the restart directory from the namelist
(``nrestart``/``auto_resume``), later attempts always pick the newest
manifest-valid checkpoint — so a SIGTERM/preemption mid-run (whose
OpsGuard stop path flushes queued dumps) resumes from the last good
output instead of failing the allocation.  Backoff between attempts is
exponential and capped; :func:`backoff_delay` is shared with bench.py
so both supervisors pace retries identically.

Failures are *classified*: a :class:`HangDetected` from the watchdog
(resilience/watchdog.py) is a hang, a :class:`StepRetryExhausted` from
the step-guard ladder is a NaN, anything else is a crash.  Hangs get a
hang-specific policy — immediate resume from the newest checkpoint
with NO backoff and NO dt-halving (the state is stale, not numerically
suspect) under a separate bounded ``hang_retries`` budget that never
consumes regular crash attempts; once that budget is spent the hang
re-raises so a process-level parent (serve loop, bench, batch system)
can apply ITS hang policy (requeue with ``stage="hang"``, exit-code
classification).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ramses_tpu.resilience.checkpoint import (latest_valid_checkpoint,
                                              resolve_restart_dir)
from ramses_tpu.resilience.stepguard import StepRetryExhausted
from ramses_tpu.resilience.watchdog import HangDetected


def classify(err: Optional[BaseException]) -> str:
    """Supervisor fault taxonomy: hang vs nan vs crash (vs none)."""
    if err is None:
        return "none"
    if isinstance(err, HangDetected):
        return "hang"
    if isinstance(err, StepRetryExhausted):
        return "nan"
    return "crash"


def backoff_delay(attempt: int, base: float = 1.0,
                  cap: float = 30.0) -> float:
    """Exponential backoff (attempt 1 -> base, doubling), capped."""
    return float(min(cap, base * (2.0 ** max(0, int(attempt) - 1))))


def _sim_t(sim) -> float:
    st = getattr(sim, "state", None)
    if st is not None and hasattr(st, "t"):
        return float(st.t)
    return float(getattr(sim, "t", 0.0))


def _sim_nstep(sim) -> int:
    st = getattr(sim, "state", None)
    if st is not None and hasattr(st, "nstep"):
        return int(st.nstep)
    return int(getattr(sim, "nstep", 0))


def run_complete(sim, params, tend: Optional[float] = None) -> bool:
    """Did the run reach its configured end (tend or nstepmax)?

    A sim may own the answer: when it defines a ``run_complete``
    method that wins (the ensemble engine does — "complete" there
    means every *member* reached its own tend/budget, which the
    scalar t/nstep probes below cannot express)."""
    own = getattr(sim, "run_complete", None)
    if callable(own):
        return bool(own(params, tend=tend))
    run = getattr(params, "run", None)
    nmax = getattr(run, "nstepmax", None)
    if nmax is not None and int(nmax) > 0 \
            and _sim_nstep(sim) >= int(nmax):
        return True
    end = tend
    if end is None:
        touts = getattr(getattr(params, "output", None), "tout",
                        None) or ()
        end = max(touts) if touts else None
    if end is None:
        return True               # nothing to measure against
    # Round-off slack: the drivers stop at t >= tend - eps*tend.
    return _sim_t(sim) >= float(end) * (1.0 - 1e-12) - 1e-300


def _close_tel(tel, sim):
    """Close an attempt's telemetry so the resumed one appends
    cleanly."""
    if tel is not None:
        try:
            tel.close(sim, print_timers=False)
        except Exception:
            pass


def supervise(build: Callable, drive: Callable, params,
              base_dir: str = ".", max_attempts: int = 3,
              backoff_s: float = 1.0, tend: Optional[float] = None,
              log: Callable = print, hang_retries: int = 2,
              escalate: tuple = ()):
    """Run ``drive(build(restart_dir))`` until complete or attempts
    are exhausted.

    ``build(restart_dir)`` constructs the simulation (fresh when
    restart_dir is None, else restored from that checkpoint);
    ``drive(sim)`` evolves it and returns normally on a clean stop
    (including an OpsGuard-handled SIGTERM).  Returns the final sim.

    ``hang_retries`` bounds hang-classified resumes separately from
    ``max_attempts`` (see module docstring); ``hang_retries=0`` makes
    a hang escape on first detection — the serve loop uses that to
    kill-and-requeue rather than retry in-worker.

    ``escalate`` is a tuple of exception types that are control flow
    for the CALLER, not failures of the run — they re-raise
    immediately with no retry and no backoff.  The serve loop passes
    its fence-loss and drain-request types: a worker that lost its
    claim must stop touching the job, not resume it.
    """
    max_attempts = max(1, int(max_attempts))
    hang_retries = max(0, int(hang_retries))
    last_err = None
    sim = None
    attempt = 0
    hang_used = 0
    nbuild = 0
    while attempt < max_attempts:
        attempt += 1
        nbuild += 1
        if nbuild == 1:
            restart = resolve_restart_dir(params, base_dir=base_dir,
                                          log=log)
        else:
            restart = latest_valid_checkpoint(base_dir, log=log)
            if restart is not None:
                log(f"resilience: attempt {attempt}/{max_attempts} "
                    f"resuming from {restart}")
            else:
                log(f"resilience: attempt {attempt}/{max_attempts} "
                    "found no valid checkpoint; restarting fresh")
        sim = build(restart)
        tel = getattr(sim, "telemetry", None)
        if tel is not None and (restart is not None or nbuild > 1):
            # any rebuild appends — even a fresh restart after a failed
            # attempt must not truncate the earlier attempts' fault
            # events (hang/rollback/...) out of the JSONL log
            try:
                tel.mark_resumed(restart, attempt)
            except AttributeError:
                pass
        try:
            drive(sim)
            last_err = None
        except escalate:
            raise                # caller-owned control flow, no retry
        except Exception as e:   # noqa: BLE001 — supervisor boundary
            last_err = e
            log(f"resilience: attempt {attempt} failed "
                f"(classified {classify(e)}): {e!r}")
        if last_err is None and run_complete(sim, params, tend=tend):
            return sim
        if classify(last_err) == "hang":
            # hang policy: immediate resume (no backoff, no
            # dt-halving — the ladder never saw a trip), bounded by
            # its own budget, never converted into a crash attempt
            _close_tel(tel, sim)
            if hang_used >= hang_retries:
                log(f"resilience: hang budget exhausted "
                    f"({hang_used}/{hang_retries}); re-raising for "
                    "process-level classification")
                raise last_err
            hang_used += 1
            attempt -= 1
            log(f"resilience: hang retry {hang_used}/{hang_retries}: "
                "immediate resume from newest checkpoint")
            continue
        if attempt == max_attempts:
            break
        # Interrupted (stop flag / SIGTERM / crash): close this
        # attempt's telemetry so the resumed one appends cleanly.
        _close_tel(tel, sim)
        delay = backoff_delay(attempt, base=backoff_s)
        log(f"resilience: run incomplete at nstep={_sim_nstep(sim)} "
            f"t={_sim_t(sim):.6g}; retrying in {delay:.1f}s")
        time.sleep(delay)
    if last_err is not None:
        raise last_err
    log(f"resilience: giving up after {max_attempts} attempts "
        f"(nstep={_sim_nstep(sim)} t={_sim_t(sim):.6g})")
    return sim
