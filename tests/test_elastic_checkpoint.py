"""Elastic sharded checkpointing (ISSUE 11): two-phase global commit,
torn-shard quarantine with fall-back, die-mid-commit leaving nothing a
scanner selects, and mesh-shape-elastic restore (write on 8, restore
on 4 or 1)."""

import importlib.util
import json
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import params_from_string
from ramses_tpu.io.pario import dump_pario, restore_pario
from ramses_tpu.resilience import (latest_valid_checkpoint,
                                   resolve_restart_dir,
                                   scrub_checkpoints,
                                   validate_checkpoint)
from ramses_tpu.resilience.faultinject import (DIE_EXIT_CODE,
                                               FaultInjector)

NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=5", "boxlen=1.0", "/",
    "&INIT_PARAMS", "nregion=2",
    "region_type(1)='square'", "region_type(2)='square'",
    "x_center=0.25,0.75", "length_x=0.5,0.5",
    "exp_region=10.0,10.0", "d_region=1.0,0.125",
    "p_region=1.0,0.1", "/",
    "&HYDRO_PARAMS", "riemann='hllc'", "/",
    "&REFINE_PARAMS", "err_grad_d=0.05", "err_grad_p=0.05", "/",
    "&OUTPUT_PARAMS", "tend=0.01", "/",
])


def _sim(extra_run="", dtype=None):
    nml = NML
    if extra_run:
        nml = nml.replace("hydro=.true.", "hydro=.true.\n" + extra_run)
    return AmrSim(params_from_string(nml, ndim=2),
                  dtype=dtype or jnp.float64)


# ------------------------------------------------- fault-spec contract

def test_faultinject_torn_die_parse():
    inj = FaultInjector("torn@3:shard=1,die@5:host=2,nan@7:member=0")
    assert inj.faults == [("torn", 3), ("die", 5), ("nan", 7)]
    assert inj.shard_of == {0: 1}
    assert inj.host_of == {1: 2}
    assert inj.member_of == {2: 0}
    with pytest.raises(ValueError, match="expected shard=J"):
        FaultInjector("torn@3:member=1")
    with pytest.raises(ValueError, match="expected host=J"):
        FaultInjector("die@3:shard=1")


def test_faultinject_torn_clamps_and_arms(tmp_path):
    """torn/die share nan@K's contracts: the fused-window clamp never
    fuses past K, and a run first observed at nstep >= K never fires
    (strict arming — a resume past K must not re-tear)."""
    inj = FaultInjector("torn@3:shard=0")
    assert inj.clamp_window(0, 10) == 3     # clamp to land exactly at 3
    sdir = tmp_path / "shard_00000"
    sdir.mkdir()
    (sdir / "data.npz").write_bytes(b"x" * 256)
    assert not inj.maybe_torn(str(sdir), 0, 2)   # before K
    assert not inj.maybe_torn(str(sdir), 1, 5)   # wrong shard
    assert inj.maybe_torn(str(sdir), 0, 5)       # fires once
    assert (sdir / "data.npz").read_bytes() != b"x" * 256
    assert os.path.getsize(sdir / "data.npz") == 256   # size-preserving
    assert not inj.maybe_torn(str(sdir), 0, 6)   # exactly-once

    late = FaultInjector("torn@3:shard=0")
    late.observe(4)                          # resumed past K
    assert not late.maybe_torn(str(sdir), 0, 5)
    assert late.clamp_window(4, 10) == 10    # disarmed: no clamping


def test_faultinject_die_respects_host(monkeypatch):
    import ramses_tpu.resilience.faultinject as fi
    died = []
    monkeypatch.setattr(fi, "_die", lambda code: died.append(code))
    inj = FaultInjector("die@2:host=1")
    inj.observe(0)
    assert not inj.maybe_die(5, host=0)      # not this host
    assert not died
    assert inj.maybe_die(5, host=1)
    assert died == [DIE_EXIT_CODE]
    assert not inj.maybe_die(6, host=1)      # exactly-once


# ------------------------------------------- die-mid-commit: never valid

def test_die_mid_commit_never_scans_valid(tmp_path, monkeypatch):
    """A host death between shard staging and the global commit leaves
    only the .tmp staging dir: nothing validates, nothing is scanned,
    resolve_restart_dir selects nothing (the acceptance criterion)."""
    import ramses_tpu.resilience.faultinject as fi

    def raise_die(code):
        raise SystemExit(code)

    monkeypatch.setattr(fi, "_die", raise_die)
    sim = _sim("fault_inject='die@2:host=0'")
    sim.evolve(0.05, nstepmax=3)             # arms at nstep 0
    assert sim.nstep >= 2                    # past the trigger step
    with pytest.raises(SystemExit) as ei:
        dump_pario(sim, 1, str(tmp_path))
    assert ei.value.code == DIE_EXIT_CODE
    stage = os.path.join(str(tmp_path), "pario_00001.tmp")
    assert os.path.isdir(stage)              # shards staged...
    assert not os.path.exists(                # ...but never sealed
        os.path.join(stage, "manifest.json"))
    assert not os.path.isdir(os.path.join(str(tmp_path),
                                          "pario_00001"))
    assert latest_valid_checkpoint(str(tmp_path), log=None) is None
    p = params_from_string(NML, ndim=2)
    p.run.auto_resume = True
    assert resolve_restart_dir(p, str(tmp_path), log=None) is None

    # the NEXT dump (a resumed run at a later nstep) sweeps the stale
    # stage — observable as io_degraded telemetry — and commits clean
    events = []

    class Tel:
        def record_event(self, kind, **kw):
            events.append((kind, kw))

    sim2 = _sim()
    sim2.evolve(0.004, nstepmax=4)
    sim2.telemetry = Tel()
    out = dump_pario(sim2, 1, str(tmp_path))
    assert out.endswith("pario_00001")
    assert ("io_degraded", ) == tuple(
        {k for k, _ in events if k == "io_degraded"})
    reasons = [kw["reason"] for k, kw in events if k == "io_degraded"]
    assert "stale_stage" in reasons
    ok, reason = validate_checkpoint(out, verify_hash=True)
    assert ok, reason


# ------------------------------------- torn shard: quarantine, fall back

def test_torn_shard_quarantined_falls_back(tmp_path):
    """torn@K:shard=J ships a committed checkpoint whose cheap
    (size-only) commit scan passed; restore-side full-hash validation
    convicts the shard, quarantines it, and — the shard held rows the
    survivors can't cover — falls back to the next-oldest valid
    checkpoint with a logged reason."""
    sim = _sim("fault_inject='torn@2:shard=0'")
    sim.evolve(0.003, nstepmax=1)
    out1 = dump_pario(sim, 1, str(tmp_path), split_hosts=2)
    assert out1.endswith("pario_00001")      # nstep < K: untouched
    nstep1, t1 = sim.nstep, sim.t
    sim.evolve(0.005, nstepmax=3)
    out2 = dump_pario(sim, 2, str(tmp_path), split_hosts=2)
    # the torn shard COMMITTED: size-only scan can't see byte flips
    assert out2.endswith("pario_00002")
    ok, _ = validate_checkpoint(out2, verify_hash=False)
    assert ok
    ok, reason = validate_checkpoint(out2, verify_hash=True)
    assert not ok and "shard_00000" in reason

    logged = []
    r = AmrSim.from_checkpoint_dir(params_from_string(NML, ndim=2),
                                   out2, dtype=jnp.float64,
                                   log=logged.append)
    assert r.nstep == nstep1 and r.t == t1   # fell back to pario_00001
    assert os.path.isdir(os.path.join(out2,
                                      "shard_00000.quarantined"))
    assert any("quarantined" in m for m in logged)
    assert any("falling back" in m for m in logged)
    # the torn checkpoint no longer scans as valid either
    assert latest_valid_checkpoint(str(tmp_path), log=None) == out1


def test_torn_shard_covered_subset_restores(tmp_path):
    """When the surviving shards still cover every row interval, the
    restore proceeds from the subset: the corrupt shard is quarantined
    and the state comes back bitwise from the covering shards."""
    sim = _sim("fault_inject='torn@2:shard=1'")
    sim.evolve(0.05, nstepmax=3)
    assert sim.nstep >= 2
    ref = {l: np.asarray(sim.u[l]) for l in sim.levels()}
    # single-device blocks all land in group 0: shard_00001 carries no
    # rows, so tearing it must not cost the checkpoint
    out = dump_pario(sim, 1, str(tmp_path), split_hosts=2)
    assert out.endswith("pario_00001")
    ok, reason = validate_checkpoint(out, verify_hash=True)
    assert not ok and "shard_00001" in reason

    logged = []
    r = restore_pario(AmrSim, params_from_string(NML, ndim=2), out,
                      dtype=jnp.float64, log=logged.append)
    assert r.nstep == sim.nstep
    for l in sim.levels():
        nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r.u[l])[:nc], ref[l][:nc]), l
    assert os.path.isdir(os.path.join(out,
                                      "shard_00001.quarantined"))
    assert any("full row coverage" in m for m in logged)


def test_scrub_checkpoints_quarantines_torn_pario(tmp_path):
    """The run service's pre-resume scrub renames a torn pario
    checkpoint to <name>.corrupt so the auto-resume scan loop can
    never pick a dir that validates at scan time but fails restore."""
    sim = _sim()
    sim.evolve(0.003, nstepmax=1)
    out = dump_pario(sim, 1, str(tmp_path))
    data = os.path.join(out, "shard_00000", "data.npz")
    sz = os.path.getsize(data)
    with open(data, "r+b") as f:            # size-preserving tear
        f.seek(sz // 2)
        chunk = f.read(32)
        f.seek(sz // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    moved = scrub_checkpoints(str(tmp_path), log=None)
    assert len(moved) == 1
    assert moved[0][0].endswith("pario_00001.corrupt")
    assert not os.path.isdir(out)


# ----------------------------------------------------- elastic controls

def test_elastic_restore_off_refuses_mesh_change(tmp_path, monkeypatch):
    import jax
    sim = _sim()
    sim.evolve(0.003, nstepmax=1)
    out = dump_pario(sim, 1, str(tmp_path))
    p = params_from_string(NML, ndim=2)
    p.run.elastic_restore = False
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(RuntimeError, match="elastic_restore"):
        restore_pario(AmrSim, p, out, dtype=jnp.float64)
    # elastic (the default) restores fine across the mesh change
    p2 = params_from_string(NML, ndim=2)
    r = restore_pario(AmrSim, p2, out, dtype=jnp.float64)
    assert r.nstep == sim.nstep


# ------------------------------------------------------ offline scrubber

def _load_tool():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "validate_checkpoint.py")
    spec = importlib.util.spec_from_file_location("validate_checkpoint",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_validate_checkpoint_tool(tmp_path):
    """The offline scrubber convicts a torn-but-committed checkpoint
    (full hash + shard count cross-checks), reports machine-readable
    JSON, and exits nonzero."""
    sim = _sim("fault_inject='torn@2:shard=0'")
    sim.evolve(0.003, nstepmax=1)
    dump_pario(sim, 1, str(tmp_path), split_hosts=2)
    sim.evolve(0.005, nstepmax=3)
    dump_pario(sim, 2, str(tmp_path), split_hosts=2)

    tool = _load_tool()
    jpath = str(tmp_path / "verdicts.json")
    rc = tool.main([str(tmp_path), "--json", jpath])
    assert rc == 1                          # a torn checkpoint exists
    res = json.load(open(jpath))
    by = {r["name"]: r for r in res["checkpoints"]}
    assert by["pario_00001"]["verdict"] == "valid"
    assert "shards" in by["pario_00001"]
    assert by["pario_00002"]["verdict"] == "torn"
    assert res["n_valid"] == 1 and res["n_torn"] == 1
    # clean dir after --quarantine: rc 0 and the torn dir is renamed
    rc = tool.main([str(tmp_path), "--json", jpath, "--quarantine"])
    assert rc == 1
    assert os.path.isdir(str(tmp_path / "pario_00002.corrupt"))
    rc = tool.main([str(tmp_path), "--json", jpath])
    assert rc == 0


# ------------------------------------------- mesh-shape-elastic restore

@pytest.mark.slow
def test_elastic_mesh_roundtrip_8_to_4_to_1(tmp_path):
    """The acceptance criterion: a checkpoint written by an 8-device
    run restores on 4 devices and on 1 device with particle/sink/
    tracer state intact (no gas-only warning), and the restored runs
    continue within round-off of the uninterrupted one."""
    import warnings as wmod

    import jax

    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim
    from ramses_tpu.pm.particles import ParticleSet
    from ramses_tpu.pm.sinks import SinkSet

    devices = jax.devices()
    assert len(devices) >= 8
    rng = np.random.default_rng(7)
    ps = ParticleSet.make(rng.uniform(0, 1, (16, 2)),
                          rng.normal(0, 0.1, (16, 2)),
                          np.full(16, 1.0 / 16), nmax=24)
    params = params_from_string(NML, ndim=2)
    sim = ShardedAmrSim(params, devices=devices[:8],
                        dtype=jnp.float64, particles=ps)
    sim.evolve(0.004, nstepmax=3)
    # attach census state AFTER evolve: stepping sink physics needs
    # &SINK_PARAMS units, and the claim here is about persistence
    sim.sinks = SinkSet(x=np.asarray([[0.5, 0.5]]),
                        v=np.asarray([[0.1, 0.0]]),
                        m=np.asarray([2.5]), tform=np.asarray([0.001]),
                        idp=np.asarray([7]), next_id=8)
    sim.tracer_x = np.asarray([[0.25, 0.25], [0.75, 0.75]])
    sim.tracer_id = np.asarray([11, 12])
    ref = {l: np.asarray(sim.u[l]) for l in sim.levels()}

    with wmod.catch_warnings():
        wmod.simplefilter("error")          # no gas-only warning, ever
        out = dump_pario(sim, 1, str(tmp_path), split_hosts=4)
    ok, reason = validate_checkpoint(out, verify_hash=True)
    assert ok, reason

    def check_state(r):
        assert r.t == sim.t and r.nstep == sim.nstep
        for l in sim.levels():
            nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
            assert np.array_equal(np.asarray(r.u[l])[:nc],
                                  ref[l][:nc]), l
        for f in ("x", "v", "m", "active", "idp"):
            assert np.array_equal(np.asarray(getattr(r.p, f)),
                                  np.asarray(getattr(sim.p, f))), f
        assert np.array_equal(r.sinks.x, sim.sinks.x)
        assert r.sinks.next_id == sim.sinks.next_id
        assert np.array_equal(r.tracer_x, sim.tracer_x)
        assert np.array_equal(r.tracer_id, sim.tracer_id)

    with wmod.catch_warnings():
        wmod.simplefilter("error")
        r4 = restore_pario(ShardedAmrSim, params_from_string(NML,
                                                             ndim=2),
                           out, dtype=jnp.float64,
                           devices=devices[:4])
        r1 = restore_pario(AmrSim, params_from_string(NML, ndim=2),
                           out, dtype=jnp.float64)
    check_state(r4)
    check_state(r1)

    # step-record equivalence: the degraded-mesh restores and the
    # uninterrupted run keep evolving within round-off of each other
    # (drop the hand-attached census state first — see above)
    sim.sinks = r4.sinks = r1.sinks = None
    sim.tracer_x = r4.tracer_x = r1.tracer_x = None
    sim.evolve(0.006, nstepmax=sim.nstep + 2)
    r4.evolve(0.006, nstepmax=r4.nstep + 2)
    r1.evolve(0.006, nstepmax=r1.nstep + 2)
    assert r4.nstep == sim.nstep == r1.nstep
    assert r4.t == pytest.approx(sim.t, rel=1e-12)
    for l in sim.levels():
        nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
        a = np.asarray(sim.u[l])[:nc]
        assert np.allclose(np.asarray(r4.u[l])[:nc], a,
                           rtol=2e-6, atol=1e-7), l
        nc1 = r1.maps[l].noct * 2 ** r1.cfg.ndim
        assert np.allclose(np.asarray(r1.u[l])[:nc1], a[:nc1],
                           rtol=2e-6, atol=1e-7), l
