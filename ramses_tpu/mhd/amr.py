"""MHD on the AMR hierarchy: constrained transport on per-level oct
batches with divergence-free (Balsara-style) prolongation/restriction.

Reference scope: ``mhd/godunov_fine.f90`` (per-level CT sweep + EMF
bookkeeping), ``mhd/interpol_hydro.f90`` (interpol_mag: div-free
interpolation of face fields).  TPU re-design decisions:

* **Face storage is duplicated per cell** — ``bf[l]`` holds
  ``[ncell_pad, 3, 2]`` = (low, high) face field per dim per cell,
  exactly the reference's cell variables 6:8 + nvar+1:nvar+3.  Both
  copies of a shared face are updated from the SAME edge EMFs (each
  oct's stencil sees identical neighbourhood values), so they stay
  bitwise equal and ``divB`` per cell is a machine-exact telescoping
  sum — no linked-list face identity needed.
* **Prolongation** (ghosts + regrid) is the linear-normal Balsara
  reconstruction: a child's outer face injects the coarse face, the
  mid-face takes the coarse (lo+hi)/2 mean — child divB equals father
  divB exactly (= 0), the invariant ``interpol_mag`` maintains.
* **Restriction** is the area mean of son faces onto the covered
  coarse cell's faces (``upload_fine`` for face variables).
* The level sweep batches every oct's 6^ndim stencil and runs the SAME
  ``ct_core`` pipeline as the uniform solver (``mhd/uniform.py``), with
  the batch as a trailing axis.  Interior (2:4) results are extracted;
  roll wrap-around only touches discarded stencil margins.

Coarse-fine EMF matching (``mhd/godunov_fine.f90:826-973``) replaces
coarse corner EMFs with time-averaged fine EMFs on DENSE parent
levels; a partial-level parent keeps its own EMFs there (first-order
coupling; each level's own divB stays machine-zero regardless, by the
duplicated-face construction above).  Self-gravity rides the hydro
hierarchy's per-level Poisson solve with MHD-layout kicks
(:func:`mhd_kick_flat`); particles ride the shared PM layer
(``pm/amr_pm.py`` deposits into the Poisson rhs, ``synchro_fine``/
``move_fine`` KDK via the base class's ``_grav_pm_pre``/``_pm_drift``).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr import kernels as K
from ramses_tpu.amr.hierarchy import AmrSim, FusedSpec
from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.mhd import core, uniform as mu
from ramses_tpu.mhd.core import IBX, IP, MhdStatic, NCOMP


# ----------------------------------------------------------------------
# div-free face prolongation (interpol_mag, mhd/interpol_hydro.f90)
# ----------------------------------------------------------------------
def _balsara_system(nd: int):
    """Minimal-norm solve for the interior fine faces of a refined
    cell: children's divB=0 conditions are A·m = c where m are the
    mid-face corrections to the two-point means.  A is a fixed ±1
    pattern; its pseudoinverse is precomputed (the closed forms in
    Balsara 2001 are exactly this least-squares solution)."""
    children = np.indices((2,) * nd).reshape(nd, -1).T   # x slowest
    nsub = 2 ** (nd - 1)
    A = np.zeros((2 ** nd, nd * nsub))
    submap = np.zeros((2 ** nd, nd), dtype=np.int64)
    for ci, ch in enumerate(children):
        for d in range(nd):
            sub = 0
            for dd in range(nd):
                if dd != d:
                    sub = sub * 2 + ch[dd]
            submap[ci, d] = sub
            A[ci, d * nsub + sub] = 1.0 - 2.0 * ch[d]    # +1 low child
    return np.linalg.pinv(A), submap, children


_BALSARA = {nd: _balsara_system(nd) for nd in (1, 2, 3)}


@partial(jax.jit, static_argnames=("nd",))
def matched_child_faces(father_bf, outer, nd: int):
    """Child faces of newly-refined cells, matched to their fine
    neighbours' stored sub-faces.

    ``father_bf`` [n, NCOMP, 2] (degenerate components + fallback);
    ``outer`` [n, nd, 2, nsub]: the cell's outer fine sub-face values —
    a donor neighbour's stored face where one exists, the injected
    coarse face otherwise.  Interior faces solve the children's
    divB = 0 system (minimal-norm correction to the two-point means);
    with divergence-consistent outer faces (the EMF-matching
    invariant), every child is divergence-free to round-off.
    Returns [n * 2^nd, NCOMP, 2] rows in flat-cell order.
    """
    pinv, submap, children = _BALSARA[nd]
    nsub = 2 ** (nd - 1)
    n = father_bf.shape[0]
    D = outer[:, :, 1, :] - outer[:, :, 0, :]            # [n, nd, nsub]
    mean = 0.5 * (outer[:, :, 0, :] + outer[:, :, 1, :])
    # c_child = -(1/2) sum_d D[d, sub_d(child)]
    cs = []
    for ci in range(2 ** nd):
        acc = 0.0
        for d in range(nd):
            acc = acc + D[:, d, submap[ci, d]]
        cs.append(-0.5 * acc)
    c = jnp.stack(cs, axis=-1)                           # [n, 2^nd]
    m = c @ jnp.asarray(pinv.T, dtype=c.dtype)           # [n, nd*nsub]
    m = m.reshape(n, nd, nsub)
    mid = mean + m                                       # [n, nd, nsub]

    rows = []
    for ci, ch in enumerate(children):
        comps = []
        for comp in range(NCOMP):
            if comp < nd:
                sub = submap[ci, comp]
                lo_out = outer[:, comp, 0, sub]
                hi_out = outer[:, comp, 1, sub]
                mid_c = mid[:, comp, sub]
                if ch[comp] == 0:
                    lo, hi = lo_out, mid_c
                else:
                    lo, hi = mid_c, hi_out
            else:
                ctr = 0.5 * (father_bf[:, comp, 0] + father_bf[:, comp, 1])
                lo = hi = ctr
            comps.append(jnp.stack([lo, hi], axis=-1))
        rows.append(jnp.stack(comps, axis=1))            # [n, NCOMP, 2]
    out = jnp.stack(rows, axis=1)                        # [n, 2^nd, ...]
    return out.reshape(n * 2 ** nd, NCOMP, 2)


def balsara_child_faces(bff, sgn, nd: int):
    """Child (lo, hi) faces from the father's: outer face = injection,
    mid face = (lo+hi)/2.  ``bff`` [n, NCOMP, 2]; ``sgn`` [n, nd] ±1
    child offsets.  Child divB == father divB exactly."""
    out = []
    for c in range(NCOMP):
        lo, hi = bff[:, c, 0], bff[:, c, 1]
        if c < nd:
            mid = 0.5 * (lo + hi)
            low_child = sgn[:, c] < 0
            clo = jnp.where(low_child, lo, mid)
            chi = jnp.where(low_child, mid, hi)
        else:
            clo = chi = 0.5 * (lo + hi)
        out.append(jnp.stack([clo, chi], axis=-1))
    return jnp.stack(out, axis=1)                      # [n, NCOMP, 2]


def _gather_faces(bf_flat, interp_faces, stencil_src, nd: int):
    """[NCOMP, 2, 6…, noct] stencil face batch (cf. K._gather_uloc)."""
    trash = jnp.zeros((1, NCOMP, 2), bf_flat.dtype)
    src = jnp.concatenate([bf_flat, interp_faces, trash], axis=0)
    g = src[stencil_src]                               # [noct, 6^d, 3, 2]
    noct = g.shape[0]
    g = jnp.moveaxis(g, (2, 3), (0, 1))                # [3, 2, noct, 6^d]
    g = jnp.swapaxes(g, 2, 3)                          # [3, 2, 6^d, noct]
    return g.reshape((NCOMP, 2) + (6,) * nd + (noct,))


def _gather_ftile(bf_flat, interp_faces, tile_src, nd: int, td: int):
    """[NCOMP, 2, td…, ntile] blocked face batch (cf. K._gather_utile):
    each Morton tile's staggered faces once plus the 2-cell halo instead
    of the ~(3^d)x-duplicated per-oct stencil copy."""
    trash = jnp.zeros((1, NCOMP, 2), bf_flat.dtype)
    src = jnp.concatenate([bf_flat, interp_faces, trash], axis=0)
    g = src[tile_src]                                  # [ntile, td^d, 3, 2]
    ntile = g.shape[0]
    g = jnp.moveaxis(g, (2, 3), (0, 1))                # [3, 2, ntile, td^d]
    g = jnp.swapaxes(g, 2, 3)                          # [3, 2, td^d, ntile]
    return g.reshape((NCOMP, 2) + (td,) * nd + (ntile,))


# ----------------------------------------------------------------------
# per-level sweep on the oct-stencil batch
# ----------------------------------------------------------------------
@partial(jax.jit, static_argnames=("cfg",))
def mhd_level_sweep(u_flat, interp_u, bf_flat, interp_bf, stencil_src,
                    ok_ref, dt, dx: float, cfg: MhdStatic):
    """CT MUSCL-Hancock for one level's octs.

    Returns (du_flat [ncell, nvar], bf_new [ncell, NCOMP, 2],
    corr [noct, nd, 2, nvar], emf [noct, npairs, 2, 2] | None) over the
    interior (2:4) cells of every oct, in flat-cell order; ``corr`` is
    the hydro-style coarse flux-correction payload (already × dt/dx);
    ``emf`` holds the oct's father-cell edge EMFs (per staggered pair,
    corner-low/high × corner-low/high, averaged along the edge) — the
    payload of the coarse-fine EMF matching.
    """
    nd = cfg.ndim
    uloc = K._gather_uloc(u_flat, interp_u, stencil_src, None, cfg)
    floc = _gather_faces(bf_flat, interp_bf, stencil_src, nd)
    noct = uloc.shape[-1]
    # real-cell mask: rows below ncell_pad are this level's own cells
    real = (stencil_src < u_flat.shape[0])             # [noct, 6^d]
    real = real.T.reshape((6,) * nd + (noct,))
    okl = ok_ref.T.reshape((6,) * nd + (noct,))        # refined cells

    # cell-centred B from the duplicated faces (valid in EVERY stencil
    # cell — no roll needed, unlike the low-face-only dense layout)
    centers = 0.5 * (floc[:, 0] + floc[:, 1])          # [NCOMP, 6…, noct]
    uloc = uloc.at[IBX:IBX + NCOMP].set(centers)

    # Riemann normal faces: prefer stored values on faces adjacent to a
    # real cell (a ghost's injected coarse value must not override the
    # fine stored field on a shared coarse-fine face)
    bn_faces = []
    for c in range(NCOMP):
        lo_c = floc[c, 0]
        if c < nd:
            ax = c
            hi_m1 = jnp.roll(floc[c, 1], 1, axis=ax)
            real_m1 = jnp.roll(real, 1, axis=ax)
            bn_faces.append(jnp.where(real, lo_c,
                                      jnp.where(real_m1, hi_m1, lo_c)))
        else:
            bn_faces.append(lo_c)

    flux_mask = []
    for d in range(nd):
        keep = jnp.logical_not(jnp.logical_or(okl, jnp.roll(okl, 1,
                                                            axis=d)))
        flux_mask.append(keep.astype(uloc.dtype))
    un, bfn, fl_cell, e_edges = mu.ct_core(
        uloc, [floc[c, 0] for c in range(NCOMP)], dt, (dx,) * nd, cfg,
        bax=1, bn_faces=bn_faces, flux_mask=flux_mask)

    interior = tuple(slice(2, 4) for _ in range(nd))
    du = (un - uloc)[(slice(None),) + interior]        # [nvar, 2…, noct]
    du_flat = jnp.transpose(
        du, (nd + 1,) + tuple(range(1, nd + 1)) + (0,)
    ).reshape(noct * 2 ** nd, cfg.nvar)

    # coarse flux-correction payload (cf. K.level_sweep): summed
    # boundary fluxes of the oct, already scaled by dt/dx
    corr = []
    for d in range(nd):
        f = fl_cell[d] * (dt / dx)
        idx_lo = [slice(None)]
        idx_hi = [slice(None)]
        for d2 in range(nd):
            if d2 == d:
                idx_lo.append(2)
                idx_hi.append(4)
            else:
                idx_lo.append(slice(2, 4))
                idx_hi.append(slice(2, 4))
        red = tuple(range(1, 1 + nd - 1))
        lo = f[tuple(idx_lo)].sum(axis=red) if nd > 1 else f[tuple(idx_lo)]
        hi = f[tuple(idx_hi)].sum(axis=red) if nd > 1 else f[tuple(idx_hi)]
        corr.append(jnp.stack([lo, hi], axis=-1))      # [nvar, noct, 2]
    corr = jnp.stack(corr, axis=-2)                    # [nvar, noct, nd, 2]
    corr = jnp.moveaxis(corr, 0, -1)                   # [noct, nd, 2, nvar]

    # interior faces: child lo at its own position, hi one step up in d
    def _cells(a):
        """[2…, noct] → flat [noct*2^nd]."""
        return jnp.transpose(a, (nd,) + tuple(range(nd))).reshape(-1)

    comps = []
    for c in range(NCOMP):
        if c < nd:
            lo_sl = tuple(slice(2, 4) for _ in range(nd))
            hi_sl = tuple(slice(3, 5) if d == c else slice(2, 4)
                          for d in range(nd))
            lo = _cells(bfn[c][lo_sl])
            hi = _cells(bfn[c][hi_sl])
        else:
            ctr = _cells(un[IBX + c][interior])
            lo = hi = ctr
        comps.append(jnp.stack([lo, hi], axis=-1))
    bf_new = jnp.stack(comps, axis=1)                  # [ncell, NCOMP, 2]

    # father-cell edge EMFs: fine corner EMFs at the oct surface corners
    # (positions {2,4} in the pair plane), edge-averaged over the
    # remaining interior positions (2:4)
    pairs = [(d1, d2) for d1 in range(nd) for d2 in range(d1 + 1, nd)]
    emf = None
    if pairs:
        outp = []
        for (d1, d2) in pairs:
            e = e_edges[(d1, d2)]                      # [6…, noct]
            sl = [slice(2, 4)] * nd + [slice(None)]
            corners = []
            for o1 in (2, 4):
                row = []
                for o2 in (2, 4):
                    s = list(sl)
                    s[d1] = o1
                    s[d2] = o2
                    v = e[tuple(s)]                    # [(2,)*rest, noct]
                    red = tuple(range(v.ndim - 1))
                    row.append(v.mean(axis=red) if red else v)
                corners.append(jnp.stack(row, axis=-1))
            outp.append(jnp.stack(corners, axis=-2))   # [noct, 2, 2]
        emf = jnp.stack(outp, axis=1)                  # [noct, np, 2, 2]
    return du_flat, bf_new, corr, emf


@partial(jax.jit, static_argnames=("cfg", "shift"))
def mhd_tile_sweep(u_flat, interp_u, bf_flat, interp_bf, tile_src,
                   tile_ok, cell_tile, cell_slot, oct_tile, oct_slot,
                   dt, dx: float, cfg: MhdStatic, shift: int):
    """CT MUSCL-Hancock on the compact blocked tile batch — the
    gather-fused replacement for :func:`mhd_level_sweep` (same return
    convention: du_flat [ncell_pad, nvar], bf_new [ncell_pad, NCOMP, 2],
    corr [noct_pad, nd, 2, nvar], emf [noct_pad, npairs, 2, 2] | None).

    MHD never passes ``pallas_oct.tile_available`` (that kernel is
    hydro-only), so this is always the trailing-batch XLA tile
    formulation; what it removes is the 6^d-duplicated stencil gather
    of cells AND staggered faces.  Every interior cell/face/corner sees
    the same radius-2 neighbourhood values as the stencil batch (tile
    halo = NGHOST_TILE = 2, shared ``maps._interp_requests`` ghost
    semantics) and ``mu.ct_core`` is shift-invariant, so the extracted
    du/bf/corr/EMF rows are bitwise identical to
    :func:`mhd_level_sweep` (pinned by tests/test_oct_blocking.py)."""
    nd = cfg.ndim
    c = 1 << (shift + 1)
    td = c + 2 * K._NG
    ut = K._gather_utile(u_flat, interp_u, tile_src, None, cfg, td)
    floc = _gather_ftile(bf_flat, interp_bf, tile_src, nd, td)
    ntile = ut.shape[-1]
    real = (tile_src < u_flat.shape[0]).T.reshape((td,) * nd + (ntile,))
    okl = tile_ok.T.reshape((td,) * nd + (ntile,))

    # cell-centred B from the duplicated faces (valid in every tile
    # cell, halo included — cf. mhd_level_sweep)
    centers = 0.5 * (floc[:, 0] + floc[:, 1])          # [NCOMP, td…, ntile]
    ut = ut.at[IBX:IBX + NCOMP].set(centers)

    # Riemann normal faces: stored values win next to a real cell (a
    # ghost's injected coarse value must not override the fine stored
    # field on a shared coarse-fine face)
    bn_faces = []
    for comp in range(NCOMP):
        lo_c = floc[comp, 0]
        if comp < nd:
            hi_m1 = jnp.roll(floc[comp, 1], 1, axis=comp)
            real_m1 = jnp.roll(real, 1, axis=comp)
            bn_faces.append(jnp.where(real, lo_c,
                                      jnp.where(real_m1, hi_m1, lo_c)))
        else:
            bn_faces.append(lo_c)

    flux_mask = []
    for d in range(nd):
        keep = jnp.logical_not(jnp.logical_or(okl,
                                              jnp.roll(okl, 1, axis=d)))
        flux_mask.append(keep.astype(ut.dtype))
    un, bfn, fl_cell, e_edges = mu.ct_core(
        ut, [floc[comp, 0] for comp in range(NCOMP)], dt, (dx,) * nd,
        cfg, bax=1, bn_faces=bn_faces, flux_mask=flux_mask)

    # interior update → flat rows.  Pad cell rows carry slot c^d /
    # tile 0 (maps.py), which flattens one past the interior batch —
    # the appended zero column — so they come out exactly 0 (K.tile_sweep
    # does the same)
    interior = tuple(slice(K._NG, K._NG + c) for _ in range(nd))
    du = (un - ut)[(slice(None),) + interior]          # [nvar, c…, ntile]
    flat_idx = cell_slot * ntile + cell_tile
    du_flat = jnp.concatenate(
        [du.reshape((cfg.nvar, c ** nd * ntile)),
         jnp.zeros((cfg.nvar, 1), du.dtype)], axis=1)[:, flat_idx].T

    # coarse flux-correction payload: the kernels tile helpers' per-oct
    # boundary-plane sums, gathered back to tree oct rows
    corr = []
    for d in range(nd):
        planes = K._face_planes(fl_cell[d] * (dt / dx), d, nd, c)
        lo, hi = K._corr_from_planes(planes, d, nd, c)
        corr.append(jnp.stack([lo[:, oct_slot, oct_tile],
                               hi[:, oct_slot, oct_tile]], axis=-1))
    corr = jnp.stack(corr, axis=-2)                    # [nvar, noct, nd, 2]
    corr = jnp.moveaxis(corr, 0, -1)                   # [noct, nd, 2, nvar]

    def _flat_cells(a):
        """[c…, ntile] → flat cell rows [ncell_pad] (pad rows 0)."""
        af = jnp.concatenate([a.reshape(c ** nd * ntile),
                              jnp.zeros((1,), a.dtype)])
        return af[flat_idx]

    # interior faces: cell's lo at its own position, hi one step up in d
    comps = []
    for comp in range(NCOMP):
        if comp < nd:
            hi_sl = tuple(slice(K._NG + 1, K._NG + c + 1) if dd == comp
                          else slice(K._NG, K._NG + c) for dd in range(nd))
            lo = _flat_cells(bfn[comp][interior])
            hi = _flat_cells(bfn[comp][hi_sl])
        else:
            lo = hi = _flat_cells(un[IBX + comp][interior])
        comps.append(jnp.stack([lo, hi], axis=-1))
    bf_new = jnp.stack(comps, axis=1)                  # [ncell, NCOMP, 2]

    # father-cell edge EMFs: corner-lattice planes at even cell offsets
    # (the stencil path's positions {2, 4} generalised to every oct in
    # the tile), edge-averaged over the remaining interior positions
    pairs = [(d1, d2) for d1 in range(nd) for d2 in range(d1 + 1, nd)]
    emf = None
    if pairs:
        o = c // 2
        outp = []
        for (d1, d2) in pairs:
            idx = tuple(slice(K._NG, K._NG + c + 1, 2) if dd in (d1, d2)
                        else slice(K._NG, K._NG + c) for dd in range(nd))
            g = e_edges[(d1, d2)][idx]
            # collapse each non-pair dim c → (o, 2) and average the
            # 2-subaxis (the stencil slice(2,4).mean edge average)
            shp, red, ax = [], [], 0
            for dd in range(nd):
                if dd in (d1, d2):
                    shp.append(o + 1)
                    ax += 1
                else:
                    shp += [o, 2]
                    red.append(ax + 1)
                    ax += 2
            g = g.reshape(shp + [ntile])
            if red:
                g = g.mean(axis=tuple(red))
            corners = []
            for i1 in (0, 1):
                row = []
                for i2 in (0, 1):
                    sl = [slice(None)] * (nd + 1)
                    sl[d1] = slice(i1, i1 + o)
                    sl[d2] = slice(i2, i2 + o)
                    row.append(g[tuple(sl)].reshape(o ** nd, ntile))
                corners.append(jnp.stack(row, axis=-1))
            pv = jnp.stack(corners, axis=-2)           # [o^nd, ntile, 2, 2]
            outp.append(pv[oct_slot, oct_tile])        # [noct, 2, 2]
        emf = jnp.stack(outp, axis=1)                  # [noct, np, 2, 2]
    return du_flat, bf_new, corr, emf


@partial(jax.jit, static_argnames=("cfg",))
def mhd_level_courant(u_flat, bf_flat, valid_cell, dx: float,
                      cfg: MhdStatic, fg=None):
    """Fast-magnetosonic CFL dt over the level (mhd courant_fine).

    ``fg`` [ncell, ndim]: enables the gravity-strength dt correction of
    ``cmpdt`` (``hydro/godunov_utils.f90:100-110``) so self-gravity
    kicks cannot outrun the step in near-free-fall cells."""
    u = jnp.moveaxis(u_flat, -1, 0)                    # [nvar, ncell]
    ctr = 0.5 * (bf_flat[:, :, 0] + bf_flat[:, :, 1])  # [ncell, NCOMP]
    u = u.at[IBX:IBX + NCOMP].set(ctr.T)
    q = core.ctoprim(u, cfg)
    ws = jnp.zeros_like(q[0])
    for d in range(cfg.ndim):
        ws = ws + jnp.abs(q[1 + d]) + core.fast_speed(q, d, cfg)
    ws = jnp.maximum(ws, cfg.smallc)
    dtc = dx / ws
    if fg is not None:
        gnorm = sum(jnp.abs(fg[:, d]) for d in range(cfg.ndim))
        ratio = jnp.maximum(gnorm * dx / ws ** 2, 1e-4)
        cf = cfg.courant_factor
        dtc = dtc * (jnp.sqrt(1.0 + 2.0 * cf * ratio) - 1.0) \
            / (cf * ratio)
    dtc = jnp.where(valid_cell, dtc, jnp.inf)
    return cfg.courant_factor * jnp.min(dtc)


@partial(jax.jit, static_argnames=("cfg",))
def mhd_restrict_upload(u_level, bf_level, u_fine, bf_fine, ref_cell,
                        son_oct, cfg: MhdStatic):
    """upload_fine for MHD: covered cells take the son means; covered
    FACES take the area mean of the son faces on that side (staggered
    dims) — the div-free restriction."""
    nd = cfg.ndim
    ttd = 2 ** nd
    valid = ref_cell >= 0
    safe_cell = jnp.where(valid, ref_cell, 0)
    rows = son_oct[:, None] * ttd + jnp.arange(ttd)[None, :]  # [nref, 2^d]
    umean = u_fine[rows].mean(axis=1)                  # [nref, nvar]
    bsub = bf_fine[rows]                               # [nref, 2^d, 3, 2]
    # child offset bits in flat order: x slowest
    offs = np.indices((2,) * nd).reshape(nd, -1).T     # [2^d, nd]
    comps = []
    for c in range(NCOMP):
        if c < nd:
            lo_children = jnp.asarray(offs[:, c] == 0)
            wlo = lo_children.astype(bsub.dtype)
            lo = (bsub[:, :, c, 0] * wlo).sum(1) / wlo.sum()
            hi = (bsub[:, :, c, 1] * (1 - wlo)).sum(1) / (ttd - wlo.sum())
        else:
            lo = hi = bsub[:, :, c, 0].mean(axis=1)
        comps.append(jnp.stack([lo, hi], axis=-1))
    bmean = jnp.stack(comps, axis=1)                   # [nref, NCOMP, 2]
    # refresh the covered cells' centred B from the restricted faces
    ctr = 0.5 * (bmean[:, :nd, 0] + bmean[:, :nd, 1])
    umean = umean.at[:, IBX:IBX + nd].set(ctr)

    cur_u = u_level[safe_cell]
    cur_b = bf_level[safe_cell]
    u_out = u_level.at[safe_cell].set(
        jnp.where(valid[:, None], umean, cur_u).astype(u_level.dtype))
    b_out = bf_level.at[safe_cell].set(
        jnp.where(valid[:, None, None], bmean, cur_b).astype(
            bf_level.dtype))
    return u_out, b_out


# ----------------------------------------------------------------------
# refinement criteria (mhd hydro_refine: err_grad_d/p/b)
# ----------------------------------------------------------------------
def _mhd_grad_flags(uloc, eg, fls, spatial0: int, cfg: MhdStatic):
    nd = cfg.ndim
    r = jnp.maximum(uloc[0], cfg.smallr)
    inv_r = 1.0 / r
    v2 = sum((uloc[1 + c] * inv_r) ** 2 for c in range(NCOMP))
    b = [uloc[IBX + c] for c in range(NCOMP)]
    b2 = sum(bc * bc for bc in b)
    p = jnp.maximum((cfg.gamma - 1.0) * (uloc[IP] - 0.5 * r * v2
                                         - 0.5 * b2),
                    cfg.smallr * cfg.smallc ** 2)
    bmag = jnp.sqrt(b2)
    egd, egp, egb = eg
    fld, flp, flb = fls

    def two_sided(f, floor):
        err = jnp.zeros_like(f)
        for d in range(nd):
            ax = spatial0 + d
            flf = jnp.roll(f, 1, axis=ax)
            frt = jnp.roll(f, -1, axis=ax)
            e1 = jnp.abs(frt - f) / (jnp.abs(frt) + jnp.abs(f) + floor)
            e2 = jnp.abs(f - flf) / (jnp.abs(f) + jnp.abs(flf) + floor)
            err = jnp.maximum(err, 2.0 * jnp.maximum(e1, e2))
        return err

    ok = jnp.zeros_like(r, dtype=bool)
    if egd >= 0.0:
        ok = ok | (two_sided(r, fld) > egd)
    if egp >= 0.0:
        ok = ok | (two_sided(p, flp) > egp)
    if egb >= 0.0:
        ok = ok | (two_sided(bmag, flb) > egb)
    return ok


@partial(jax.jit, static_argnames=("spec", "eg", "fls", "itype"))
def _mhd_fused_flags(u, dev, spec: FusedSpec, eg, fls, itype: int):
    cfg = spec.cfg
    nd = cfg.ndim
    bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec.bspec.faces)
    out = []
    for i, l in enumerate(spec.levels):
        d = dev[l]
        if spec.complete[i]:
            sl = spec.slab[i] if spec.slab else None
            if sl is not None:
                # explicit slab-sharded flags (parallel/dense_slab.py):
                # shard-local bitperm + depth-1 ring halos (DMA or
                # ppermute per the halo_backend knob) instead of the
                # global-view transpose
                from functools import partial as _partial

                from ramses_tpu.parallel import dense_slab
                fn = _partial(_mhd_grad_flags, eg=eg, fls=fls,
                              spatial0=0, cfg=cfg)
                fl = dense_slab.dense_flags_slab(u[l], sl, fn, 2 ** nd)
            else:
                shape = (1 << l,) * nd
                ncell = shape[0] ** nd
                ud = jnp.moveaxis(
                    K.rows_to_dense(u[l], d.get("inv_perm"), shape),
                    -1, 0)
                # ghost-pad per the physical BCs: a raw roll would wrap
                # the two domain edges together and flag phantom
                # gradients there
                up = mu._pad(ud, nd, bc_kinds, 1)
                ok = _mhd_grad_flags(up, eg, fls, 0, cfg)
                ok = ok[tuple(slice(1, -1) for _ in range(nd))]
                fl = K.dense_to_rows(ok, d.get("perm"), shape).reshape(
                    ncell // 2 ** nd, 2 ** nd)
        elif spec.blocked and spec.blocked[i]:
            # flags reuse the blocked shared gather (tile batch) —
            # cf. K.tile_refine_flags
            if l == spec.lmin:
                interp = jnp.zeros((d["b_interp_cell"].shape[0],
                                    cfg.nvar), u[l].dtype)
            else:
                interp = K.interp_cells(u[l - 1], d["b_interp_cell"],
                                        d["b_interp_nb"],
                                        d["b_interp_sgn"],
                                        cfg, itype=itype)
            c = 1 << (spec.block_shift + 1)
            td = c + 2 * K._NG
            ut = K._gather_utile(u[l], interp, d["tile_src"], None,
                                 cfg, td)
            ntile = ut.shape[-1]
            ok = _mhd_grad_flags(ut, eg, fls, 0, cfg)
            oki = ok[tuple(slice(K._NG, K._NG + c) for _ in range(nd))]
            okc = jnp.concatenate([oki.reshape(c ** nd * ntile),
                                   jnp.zeros((1,), ok.dtype)])
            rows = okc[d["cell_slot"] * ntile + d["cell_tile"]]
            fl = rows.reshape(rows.shape[0] // 2 ** nd, 2 ** nd)
        else:
            if l == spec.lmin:
                interp = jnp.zeros((d["interp_cell"].shape[0], cfg.nvar),
                                   u[l].dtype)
            else:
                interp = K.interp_cells(u[l - 1], d["interp_cell"],
                                        d["interp_nb"], d["interp_sgn"],
                                        cfg, itype=itype)
            uloc = K._gather_uloc(u[l], interp, d["stencil_src"], None,
                                  cfg)
            ok = _mhd_grad_flags(uloc, eg, fls, 0, cfg)
            okc = ok[tuple(slice(2, 4) for _ in range(nd))]
            okc = jnp.moveaxis(okc, -1, 0)
            fl = okc.reshape(okc.shape[0], 2 ** nd)
        out.append(fl)
    return tuple(out)


# ----------------------------------------------------------------------
# fused coarse step
# ----------------------------------------------------------------------
def _dense_hi(lo_dense, d: int, periodic: bool):
    """High faces from a dense low-face field: the next cell's low face;
    non-periodic top plane keeps its own low value (zero-gradient)."""
    hi = jnp.roll(lo_dense, -1, axis=d)
    if not periodic:
        idx = [slice(None)] * lo_dense.ndim
        idx[d] = slice(-1, None)
        hi = hi.at[tuple(idx)].set(lo_dense[tuple(idx)])
    return hi


def mhd_kick_flat(u_rows, fg_rows, dteff, ndim: int, smallr: float):
    """Gravity momentum kick at fixed internal+magnetic energy on flat
    MHD rows (the ``synchro_hydro_fine`` step with the MHD layout:
    momentum always 3 components at 1..3, total energy at IP)."""
    r = jnp.maximum(u_rows[:, 0], smallr)
    ek_old = sum(0.5 * u_rows[:, 1 + c] ** 2 for c in range(NCOMP)) / r
    mom = [u_rows[:, 1 + c]
           + (r * fg_rows[:, c] * dteff if c < ndim else 0.0)
           for c in range(NCOMP)]
    ek_new = sum(0.5 * m * m for m in mom) / r
    e = u_rows[:, IP] - ek_old + ek_new
    out = u_rows
    for c in range(ndim):
        out = out.at[:, 1 + c].set(mom[c])
    return out.at[:, IP].set(e)


def _mhd_advance_traced(u, bf, dev, fg, dt, spec: FusedSpec):
    """Recursive subcycled MHD coarse step (cf. hydro _advance_traced).

    Cell-state conservation at coarse-fine interfaces follows the hydro
    scheme exactly: refined-face fluxes are zeroed in the coarse sweep
    and the fine level scatters its summed boundary fluxes into the
    unrefined coarse neighbours.  B-center rows are excluded from the
    correction (they must remain the face mean; face-field interface
    accounting is the EMF-matching step)."""
    cfg = spec.cfg
    nd = cfg.ndim
    u = dict(u)
    unew = dict(u)
    bf = dict(bf)
    levels = spec.levels
    bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec.bspec.faces)

    def dx(l):
        return spec.boxlen / (1 << l)

    pairs = [(d1, d2) for d1 in range(nd) for d2 in range(d1 + 1, nd)]

    def advance(i, dtl):
        l = levels[i]
        d = dev[l]
        if spec.gravity:
            u[l] = mhd_kick_flat(u[l], fg[l], 0.5 * dtl, nd, cfg.smallr)
        unew[l] = u[l]
        child_emf = None
        if i + 1 < len(levels):
            e1 = advance(i + 1, 0.5 * dtl)
            e2 = advance(i + 1, 0.5 * dtl)
            if e1 is not None:
                child_emf = 0.5 * (e1 + e2)   # time-averaged fine EMFs
        my_emf = None
        if spec.complete[i]:
            shape = (1 << l,) * nd
            ncell = shape[0] ** nd
            from ramses_tpu.parallel import dense_slab
            sl = spec.slab[i] if spec.slab else None
            use_slab = sl is not None and dense_slab.mhd_slab_ok(sl)
            if use_slab and child_emf is not None:
                cd = dev[levels[i + 1]]
                if (cd.get("emf_dense_idx") is not None
                        and cd.get("emf_flat_idx") is None):
                    use_slab = False      # no Morton scatter map built
            if use_slab:
                # explicit slab-sharded CT (parallel/dense_slab.py):
                # shard-local bitperm + ring halos; the coarse-fine EMF
                # override becomes a row-order scatter OUTSIDE the
                # shard_map (emf_flat_idx), so the partitioned program
                # never sees a global index scatter
                ovr_flat = None
                if child_emf is not None:
                    fidx = dev[levels[i + 1]].get("emf_flat_idx")
                    if fidx is not None:
                        npair = len(pairs)
                        om = jnp.zeros((ncell, npair), u[l].dtype)
                        ov = jnp.zeros((ncell, npair), u[l].dtype)
                        for pi in range(npair):
                            rows = fidx[:, pi].reshape(-1)
                            ov = ov.at[rows, pi].set(
                                child_emf[:, pi].reshape(-1).astype(
                                    u[l].dtype), mode="drop")
                            om = om.at[rows, pi].set(1.0, mode="drop")
                        ovr_flat = (om, ov)
                du_rows, b_rows = dense_slab.mhd_ct_slab(
                    u[l], bf[l], dtl, dx(l), sl, cfg,
                    ok_flat=d.get("ok_flat"), ovr_flat=ovr_flat)
                unew[l] = unew[l] + du_rows.astype(u[l].dtype)
                bf[l] = b_rows.astype(bf[l].dtype)
            else:
                grid = mu.MhdGrid(cfg=cfg, shape=shape, dx=dx(l),
                                  bc_kinds=bc_kinds)
                ud = jnp.moveaxis(
                    K.rows_to_dense(u[l], d.get("inv_perm"), shape),
                    -1, 0)
                bld = K.rows_to_dense(bf[l], d.get("inv_perm"),
                                      shape)           # [*shape, 3, 2]
                bfd = jnp.stack([bld[..., c, 0] for c in range(NCOMP)])
                ok_d = (d["ok_dense"].reshape(shape)
                        if d.get("ok_dense") is not None else None)
                override = None
                if child_emf is not None:
                    idx = dev[levels[i + 1]].get("emf_dense_idx")
                    if idx is not None:
                        override = {}
                        for pi, pair in enumerate(pairs):
                            rows = idx[:, pi].reshape(-1)
                            vals = jnp.zeros(
                                (ncell,), child_emf.dtype).at[rows].set(
                                    child_emf[:, pi].reshape(-1),
                                    mode="drop")
                            msk = jnp.zeros((ncell,), bool).at[rows].set(
                                True, mode="drop")
                            override[pair] = (msk.reshape(shape),
                                              vals.reshape(shape))
                un_d, bfn_d = mu.step(grid, ud, bfd, dtl, ok=ok_d,
                                      emf_override=override)
                du_rows = K.dense_to_rows(
                    jnp.moveaxis(un_d - ud, 0, -1), d.get("perm"), shape)
                if u[l].shape[0] > ncell:
                    du_rows = jnp.zeros_like(u[l]).at[:ncell].set(
                        du_rows.astype(u[l].dtype))
                unew[l] = unew[l] + du_rows
                comps = []
                for c in range(NCOMP):
                    lo_d = bfn_d[c]
                    if c < nd:
                        hi_d = _dense_hi(lo_d, c, bc_kinds[c][0] == 0)
                    else:
                        hi_d = lo_d
                    comps.append(jnp.stack([lo_d, hi_d], axis=-1))
                b_rows = K.dense_to_rows(jnp.stack(comps, axis=-2),
                                         d.get("perm"), shape)
                bf[l] = (bf[l].at[:ncell].set(b_rows.astype(bf[l].dtype))
                         if bf[l].shape[0] > ncell
                         else b_rows.astype(bf[l].dtype))
        else:
            # gather-fused blocked tile path: the compact Morton-tile
            # batch replaces the 6^d-duplicated stencil gather of cells
            # and staggered faces (see AmrSim._advance_traced)
            blocked = bool(spec.blocked and spec.blocked[i])
            ic = "b_interp_cell" if blocked else "interp_cell"
            if l == spec.lmin:
                interp_u = jnp.zeros((d[ic].shape[0], cfg.nvar),
                                     u[l].dtype)
                interp_bf = jnp.zeros(
                    (d[ic].shape[0], NCOMP, 2), bf[l].dtype)
            elif blocked:
                interp_u = K.interp_cells(u[l - 1], d["b_interp_cell"],
                                          d["b_interp_nb"],
                                          d["b_interp_sgn"],
                                          cfg, itype=spec.itype)
                interp_bf = balsara_child_faces(
                    bf[l - 1][d["b_interp_cell"]],
                    d["b_interp_sgn"].astype(bf[l - 1].dtype), nd)
            else:
                interp_u = K.interp_cells(u[l - 1], d["interp_cell"],
                                          d["interp_nb"], d["interp_sgn"],
                                          cfg, itype=spec.itype)
                interp_bf = balsara_child_faces(
                    bf[l - 1][d["interp_cell"]],
                    d["interp_sgn"].astype(bf[l - 1].dtype), nd)
            if blocked:
                du, bfn, corr, my_emf = mhd_tile_sweep(
                    u[l], interp_u, bf[l], interp_bf, d["tile_src"],
                    d["tile_ok"], d["cell_tile"], d["cell_slot"],
                    d["oct_tile"], d["oct_slot"], dtl, dx(l), cfg,
                    spec.block_shift)
            else:
                du, bfn, corr, my_emf = mhd_level_sweep(
                    u[l], interp_u, bf[l], interp_bf, d["stencil_src"],
                    d["ok_ref"], dtl, dx(l), cfg)
            unew[l] = unew[l] + du
            if l > spec.lmin:
                # staggered B centers are face means, not flux-updated
                # cell variables — exclude them; degenerate components
                # (c >= ndim) are genuinely conserved and keep theirs
                corr = corr.at[..., IBX:IBX + min(nd, NCOMP)].set(0.0)
                if spec.comm and spec.comm[i] is not None:
                    # sharded mesh with an explicit schedule: the CT
                    # sweep stays global-view (staggered faces + child
                    # EMF), but the coarse fold goes through the
                    # deterministic owner-fold instead of a GSPMD
                    # scatter-add (parallel/amr_comm.py)
                    from ramses_tpu.parallel import amr_comm
                    unew[l - 1] = amr_comm.fold_corrections_explicit(
                        corr, unew[l - 1], d, spec.comm[i])
                else:
                    unew[l - 1] = K.scatter_corrections(
                        unew[l - 1], corr, d["corr_idx"], cfg)
            bf[l] = bfn
        u[l] = unew[l]
        if spec.gravity:
            u[l] = mhd_kick_flat(u[l], fg[l], 0.5 * dtl, nd, cfg.smallr)
        if i + 1 < len(levels):
            u[l], bf[l] = mhd_restrict_upload(
                u[l], bf[l], u[levels[i + 1]], bf[levels[i + 1]],
                d["ref_cell"], d["son_oct"], cfg)
            unew[l] = u[l]
        return my_emf

    advance(0, dt)
    # degenerate (cell-centred) components are DEFINED as the cell value:
    # re-sync their face slots after corrections/restriction so the next
    # sweep's face-derived centers see the corrected state
    if nd < NCOMP:
        for l in levels:
            ctr = u[l][:, IBX + nd:IBX + NCOMP]
            bf[l] = bf[l].at[:, nd:NCOMP, 0].set(ctr)
            bf[l] = bf[l].at[:, nd:NCOMP, 1].set(ctr)
    return u, bf


def _mhd_courant_traced(u, bf, dev, spec: FusedSpec, fg=None):
    dts = []
    for i, l in enumerate(spec.levels):
        dt_l = mhd_level_courant(u[l], bf[l], dev[l]["valid_cell"],
                                 spec.boxlen / (1 << l), spec.cfg,
                                 fg.get(l) if fg else None)
        dts.append(dt_l * (2.0 ** (l - spec.lmin)))
    return jnp.stack(dts)


@partial(jax.jit, static_argnames=("spec",), donate_argnums=(0, 1))
def _mhd_fused_coarse_step(u, bf, dev, dt, spec: FusedSpec, fg=None):
    u, bf = _mhd_advance_traced(u, bf, dev, fg, dt, spec)
    return u, bf, jnp.min(_mhd_courant_traced(
        u, bf, dev, spec, fg if spec.gravity else None))


@partial(jax.jit, static_argnames=("spec",))
def _mhd_fused_courant(u, bf, dev, spec: FusedSpec, fg=None):
    return _mhd_courant_traced(u, bf, dev, spec, fg)


@partial(jax.jit, static_argnames=("spec", "nsteps", "trace"))
def _mhd_fused_multi_step(u, bf, dev, t, tend, dt0, spec: FusedSpec,
                          nsteps: int, trace: bool = False):
    def body(carry, _):
        u, bf, t, dtc, ndone = carry
        dt = jnp.minimum(dtc, jnp.maximum(tend - t, 0.0))
        active = t < tend
        sdt = jnp.where(active, dt, 0.0).astype(u[spec.lmin].dtype)
        un, bfn, dtn = _mhd_fused_coarse_step(u, bf, dev, sdt, spec)
        # (gravity runs step-at-a-time; the multi-step chunk path is
        # hydro-only like the base class)
        u = {l: jnp.where(active, un[l], u[l]) for l in u}
        bf = {l: jnp.where(active, bfn[l], bf[l]) for l in bf}
        t = jnp.where(active, t + dt, t)
        dtc = jnp.where(active, dtn.astype(dtc.dtype), dtc)
        ndone = ndone + jnp.where(active, 1, 0)
        ys = (t, jnp.where(active, dt, 0.0)) if trace else None
        return (u, bf, t, dtc, ndone), ys

    (u, bf, t, dtc, ndone), hist = jax.lax.scan(
        body, (u, bf, t, dt0, jnp.array(0)), None, length=nsteps)
    if trace:
        return u, bf, t, dtc, ndone, hist
    return u, bf, t, dtc, ndone


# ----------------------------------------------------------------------
# driver
# ----------------------------------------------------------------------
class MhdAmrSim(AmrSim):
    """Adaptive MHD simulation (CT + div-free AMR transfer operators).

    Reuses the hydro hierarchy's octree, index maps, regrid machinery,
    and evolve loop; overrides the state layout (adds ``self.bfs``),
    the fused step, the CFL, the refinement criteria, and the
    migration/restriction to carry the staggered field."""

    _needs_mig_log = True
    _pm_physics = False      # MHD state layout carries cell-centred B
    _noncubic_ok = False     # dense CT path assumes one root cube
    # out-of-core offload drives the base class's per-level segmented
    # step, which doesn't carry the staggered face state — MHD keeps
    # its own fused step chain and opts out (amr/offload.py)
    _offload_capable = False
    # partial levels take the gather-fused blocked tile sweep too:
    # mhd_tile_sweep runs ct_core on the compact Morton-tile batch (XLA
    # tile formulation — the Pallas oct kernel stays hydro-only), so
    # cells AND staggered faces stop paying the 6^d stencil gather
    _oct_blocked = True

    def __init__(self, params: Params, dtype=jnp.float32, **kw):
        from ramses_tpu import patch
        patch.maybe_install_from_params(params)
        if patch.hook("condinit") is not None:
            import warnings
            warnings.warn(
                "patch condinit hook is not applied to the MHD solver: "
                "MHD ICs need divergence-free STAGGERED face fields; "
                "using &INIT_PARAMS regions instead")
        self.mcfg = MhdStatic.from_params(params)
        spec = bmod.BoundarySpec.from_params(params)
        for lo, hi in ((f[0].kind, f[1].kind) for f in spec.faces):
            for k in (lo, hi):
                if k not in (bmod.PERIODIC, bmod.OUTFLOW):
                    raise NotImplementedError(
                        "MHD-AMR boundaries: periodic/outflow only")
        super().__init__(params, dtype=dtype, **kw)

    # ---- state allocation -------------------------------------------
    def _mhd_region_state(self, lvl: int):
        """(u rows, bf rows) from &INIT_PARAMS regions (driver.py
        ``mhd_condinit`` semantics per arbitrary cell list)."""
        from ramses_tpu.mhd.driver import _region_mask
        init = self.params.init
        cfg = self.mcfg
        m = self.maps[lvl]
        centers = self.tree.cell_centers(lvl, self.boxlen)
        x = [centers[:, d] for d in range(cfg.ndim)]
        n = len(centers)
        q = np.zeros((cfg.nvar, n))
        q[0] = cfg.smallr
        q[IP] = cfg.smallr * cfg.smallc ** 2 / cfg.gamma
        bf = np.zeros((n, NCOMP, 2))
        vels = [init.u_region, init.v_region, init.w_region]
        bvals = [init.A_region, init.B_region, init.C_region]
        for k in range(init.nregion):
            if str(init.region_type[k]).strip() != "square":
                raise NotImplementedError("mhd ICs: square regions only")
            msk = _region_mask(x, k, init, cfg.ndim)
            q[0][msk] = init.d_region[k]
            for c in range(NCOMP):
                q[1 + c][msk] = vels[c][k]
                bf[msk, c, 0] = bvals[c][k]
                bf[msk, c, 1] = bvals[c][k]
            q[IP][msk] = init.p_region[k]
        for c in range(NCOMP):
            q[IBX + c] = 0.5 * (bf[:, c, 0] + bf[:, c, 1])
        u = np.asarray(core.prim_to_cons(jnp.asarray(q), cfg)).T
        u_pad = np.zeros((m.ncell_pad, cfg.nvar))
        u_pad[:n] = u
        u_pad[n:, 0] = cfg.smallr
        u_pad[n:, IP] = cfg.smallr * cfg.smallc ** 2 / cfg.gamma
        bf_pad = np.zeros((m.ncell_pad, NCOMP, 2))
        bf_pad[:n] = bf
        return (self._place(jnp.asarray(u_pad, self.dtype), "cells"),
                self._place(jnp.asarray(bf_pad, self.dtype), "cells"))

    def _alloc_from_ics(self):
        self.u = {}
        self.bfs: Dict[int, jnp.ndarray] = {}
        for l in self.levels():
            self.u[l], self.bfs[l] = self._mhd_region_state(l)
        self._restrict_all()
        self._dt_cache = None

    def _donor_maps(self, l: int, new_octs) -> np.ndarray:
        """Per new oct: flat cell index of the existing (OLD) fine
        neighbour owning each outer sub-face, -1 where none —
        [nnew, nd, 2, nsub].  The donor's stored face on the shared
        side is copied verbatim (``interpol_mag``'s use of fine
        neighbour faces) so duplicated faces stay single-valued."""
        from ramses_tpu.amr.tree import map_coords
        nd = self.tree_ndim
        tree = self.tree
        lev = tree.levels[l]
        og = lev.og[new_octs]                  # [nnew, nd]
        nnew = len(og)
        nsub = 2 ** (nd - 1)
        is_new = np.zeros(tree.noct(l), dtype=bool)
        is_new[new_octs] = True
        offs = np.indices((2,) * nd).reshape(nd, -1).T
        out = np.full((nnew, nd, 2, nsub), -1, dtype=np.int64)
        for d in range(nd):
            side_offs = {s: offs[offs[:, d] == s] for s in (0, 1)}
            for s in (0, 1):
                for k, off in enumerate(side_offs[s]):
                    q = 2 * og + off               # fine cell coords
                    nq = q.copy()
                    nq[:, d] += 2 * s - 1
                    nqm, _ = map_coords(nq, l, self.bc_kinds, nd)
                    valid = np.ones(nnew, dtype=bool)
                    nmax = 1 << l
                    for dd in range(nd):
                        if self.bc_kinds[dd] != (0, 0):
                            valid &= ((nq[:, dd] >= 0)
                                      & (nq[:, dd] < nmax))
                    doct = tree.lookup(l, nqm >> 1)
                    ok = (doct >= 0) & valid
                    okn = ok & ~is_new[np.clip(doct, 0, None)]
                    doff = np.zeros(nnew, dtype=np.int64)
                    for dd in range(nd):
                        doff = doff * 2 + (nqm[:, dd] & 1)
                    out[:, d, s, k] = np.where(okn,
                                               doct * 2 ** nd + doff, -1)
        return out

    def _rebuild_maps(self, *a, **k):
        super()._rebuild_maps(*a, **k)
        self._build_emf_maps()

    def _build_emf_maps(self):
        """Scatter targets of the coarse-fine EMF matching: for each
        PARTIAL level whose parent level is dense, map every fine oct's
        father-cell edges onto the parent's dense corner lattice
        (corner of cell (i,j,…) ↔ array position (i,j,…)).  Out-of-
        domain corners (non-periodic walls) get an out-of-range index
        so the device scatter drops them.

        Two index layouts per level: ``emf_dense_idx`` (C-order ravel
        of the parent's dense box — the global-view ``mu.step`` path)
        and ``emf_flat_idx`` (the parent's Morton FLAT row order,
        :func:`ramses_tpu.amr.bitperm.flat_index_np`) — the
        slab-sharded CT path scatters the override into row-sharded
        flat arrays OUTSIDE the shard_map, so no global index scatter
        ever enters the partitioned program."""
        from ramses_tpu.amr import bitperm
        nd = self.tree_ndim
        pairs = [(d1, d2) for d1 in range(nd)
                 for d2 in range(d1 + 1, nd)]
        for l in self.levels():
            d = self.dev.get(l)
            if d is None:
                continue
            if (not pairs or l == self.lmin or self.maps[l].complete
                    or not self.maps[l - 1].complete):
                d.pop("emf_dense_idx", None)
                d.pop("emf_flat_idx", None)
                continue
            og = self.tree.levels[l].og        # father cells at l-1
            noct = len(og)
            n1 = 1 << (l - 1)
            ncell1 = n1 ** nd
            m = self.maps[l]
            idx = np.full((m.noct_pad, len(pairs), 2, 2), ncell1,
                          dtype=np.int64)
            fidx = np.full_like(idx, ncell1)
            cubic = tuple(self.root or (1,) * nd) == (1,) * nd
            for pi, (d1, d2) in enumerate(pairs):
                for o1 in (0, 1):
                    for o2 in (0, 1):
                        cc = og.copy()
                        cc[:, d1] += o1
                        cc[:, d2] += o2
                        oob = np.zeros(noct, dtype=bool)
                        for dd in range(nd):
                            lo_k, hi_k = self.bc_kinds[dd]
                            if lo_k == 0 and hi_k == 0:
                                cc[:, dd] %= n1
                            else:
                                oob |= (cc[:, dd] < 0) | (cc[:, dd] >= n1)
                                cc[:, dd] = np.clip(cc[:, dd], 0, n1 - 1)
                        flat = np.ravel_multi_index(
                            tuple(cc[:, dd] for dd in range(nd)),
                            (n1,) * nd)
                        idx[:noct, pi, o1, o2] = np.where(oob, ncell1,
                                                          flat)
                        if cubic:
                            mflat = bitperm.flat_index_np(cc, l - 1, nd)
                            fidx[:noct, pi, o1, o2] = np.where(
                                oob, ncell1, mflat)
                # shared corners are written by up to 2^(nd-1) fine
                # octs; their values agree only to roundoff, so the
                # scatter winner would be resolution-order dependent.
                # Keep ONE canonical writer (first in oct enumeration)
                # and drop the rest — applied identically to both
                # layouts so dense and flat scatters stay bitwise equal.
                v = idx[:noct, pi].reshape(-1).copy()
                _, first = np.unique(v, return_index=True)
                dup = np.ones(v.size, dtype=bool)
                dup[first] = False
                oi, a1, a2 = np.unravel_index(np.flatnonzero(dup),
                                              (noct, 2, 2))
                idx[oi, pi, a1, a2] = ncell1
                fidx[oi, pi, a1, a2] = ncell1
            d["emf_dense_idx"] = self._place(jnp.asarray(idx), "octs")
            if cubic:
                d["emf_flat_idx"] = self._place(jnp.asarray(fidx), "octs")
            else:
                d.pop("emf_flat_idx", None)

    # ---- transfer operators ------------------------------------------
    def _restrict_all(self):
        # during super().regrid() u is migrated before bf: skip the base
        # class's restrict call and run it after the bf migration
        if not hasattr(self, "bfs") or getattr(self, "_regridding", False):
            return
        for l in sorted(self.levels(), reverse=True):
            if self.tree.has(l + 1):
                d = self.dev[l]
                self.u[l], self.bfs[l] = mhd_restrict_upload(
                    self.u[l], self.bfs[l], self.u[l + 1],
                    self.bfs[l + 1], d["ref_cell"], d["son_oct"],
                    self.mcfg)

    def regrid(self):
        old_bf = dict(getattr(self, "bfs", {}))
        self._mig_log = {}
        oldtree = self.tree
        self._regridding = True
        try:
            super().regrid()
        finally:
            self._regridding = False
        if self.tree is oldtree and not self._mig_log:
            return                                     # unchanged
        nd = self.mcfg.ndim
        ttd = 2 ** nd
        nsub = 2 ** (nd - 1)
        new_bf: Dict[int, jnp.ndarray] = {}
        for l in self.levels():
            info = self._mig_log.get(l)
            if info is None:
                new_bf[l] = old_bf[l]
                continue
            (rows_d, rows_s, cell_rep, sgn_rep, rows_new, ncell_pad,
             new_octs, f_cell, _nb_rep) = info
            old = old_bf.get(l)
            if old is None:
                old = jnp.zeros((1, NCOMP, 2), self.dtype)
            buf = jnp.zeros((ncell_pad, NCOMP, 2), self.dtype)
            buf = buf.at[rows_d].set(old[rows_s], mode="drop")
            nnew = len(new_octs)
            if nnew:
                from ramses_tpu.amr.maps import bucket
                npad = bucket(nnew, 256)
                donor = self._donor_maps(l, new_octs)
                donor_p = np.full((npad, nd, 2, nsub), -1, dtype=np.int64)
                donor_p[:nnew] = donor
                f_p = np.zeros(npad, dtype=np.int64)
                f_p[:nnew] = f_cell
                oct_p = np.full(npad, ncell_pad, dtype=np.int64)  # drop
                oct_p[:nnew] = new_octs
                father = new_bf[l - 1][jnp.asarray(f_p)]  # [npad, 3, 2]
                outer_ds = []
                for d in range(nd):
                    per_s = []
                    for s in (0, 1):
                        di = jnp.asarray(donor_p[:, d, s])   # [npad,nsub]
                        val = buf[jnp.clip(di, 0, None), d, 1 - s]
                        inj = father[:, d, s][:, None]
                        per_s.append(jnp.where(di >= 0, val, inj))
                    outer_ds.append(jnp.stack(per_s, axis=1))
                outer = jnp.stack(outer_ds, axis=1)  # [npad, nd, 2, nsub]
                vals = matched_child_faces(father, outer, nd)
                rows_cells = (oct_p[:, None] * ttd
                              + np.arange(ttd)).reshape(-1)
                buf = buf.at[jnp.asarray(rows_cells)].set(
                    vals.astype(buf.dtype), mode="drop")
            new_bf[l] = self._place(buf, "cells")
            # re-derive the stored cell-centred B from the div-free
            # migrated faces — the conservative-variable interpolation
            # of u's B slots is NOT the face mean, and the sweep's
            # center/face invariant must hold
            ctr = 0.5 * (new_bf[l][:, :, 0] + new_bf[l][:, :, 1])
            self.u[l] = self.u[l].at[:, IBX:IBX + NCOMP].set(
                ctr.astype(self.u[l].dtype))
        self.bfs = new_bf
        self._restrict_all()
        self._dt_cache = None

    # ---- refinement criteria -----------------------------------------
    def _criteria_flags(self, spec):
        r = self.params.refine
        eg = (float(r.err_grad_d), float(r.err_grad_p),
              float(r.err_grad_b))
        fls = (float(r.floor_d), float(r.floor_p), float(r.floor_b))
        return _mhd_fused_flags(self.u, self.dev, spec, eg, fls,
                                int(self.params.refine.interpol_type))

    # ---- stepping ------------------------------------------------------
    def _fused_spec(self) -> FusedSpec:
        if self._spec is None:
            lv = tuple(self.levels())
            cspecs = getattr(self, "_comm_specs", {})
            self._spec = FusedSpec(
                cfg=self.mcfg, bspec=self.bspec, lmin=self.lmin,
                boxlen=self.boxlen, levels=lv,
                complete=tuple(self.maps[l].complete for l in lv),
                gravity=self.gravity,
                itype=int(self.params.refine.interpol_type),
                # explicit-comm meshes: partial levels route the coarse
                # correction fold through the deterministic owner-fold
                # (fold_corrections_explicit) — the CT sweep itself
                # stays global-view
                comm=(tuple(cspecs.get(l) for l in lv) if cspecs
                      else ()))
            # slab-sharded complete levels: gradient flags AND the CT
            # advance (mhd_ct_slab — the EMF override scatters into
            # flat rows via emf_flat_idx, so no global index scatter
            # remains); levels whose local box is too thin for the
            # deeper face halos fall back at advance time (mhd_slab_ok)
            slab = tuple(self._slab_spec(l) if self.maps[l].complete
                         else None for l in lv)
            if any(s is not None for s in slab):
                self._spec = self._spec._replace(slab=slab)
            blocked = tuple(l in self.blocks for l in lv)
            if any(blocked):
                self._spec = self._spec._replace(
                    blocked=blocked,
                    block_shift=int(getattr(self.params.amr,
                                            "oct_block_shift", 2)))
        return self._spec

    def coarse_dt(self) -> float:
        with self.timers.section("courant"):
            if self._dt_cache is not None:
                dts = [float(self._dt_cache)]
            else:
                dts = [float(jnp.min(_mhd_fused_courant(
                    self.u, self.bfs, self.dev, self._fused_spec(),
                    self.fg if (self.gravity and self.fg) else None)))]
            dts.extend(self._aux_dts())
            return min(dts)

    def step_coarse(self, dt: float):
        self._grav_pm_pre(float(dt))
        with self.timers.section("hydro - godunov"):
            self.u, self.bfs, self._dt_cache = _mhd_fused_coarse_step(
                self.u, self.bfs, self.dev,
                jnp.asarray(float(dt), self.dtype), self._fused_spec(),
                self.fg if self.gravity else None)
        self._pm_drift(float(dt))
        self.t += float(dt)
        # coarse-cadence source passes (for MHD the patch 'source'
        # hook and gas tracers are live — SF/sinks stay
        # _pm_physics-gated)
        self._source_passes(float(dt))
        self.dt_old = float(dt)
        self.nstep += 1

    def step_chunk(self, nsteps: int, tend: float, trace: bool = False):
        assert not self.gravity and not self.pic  # chunks are solver-only
        spec = self._fused_spec()
        tdtype = jnp.result_type(float)
        if self._dt_cache is not None:
            dt0 = jnp.asarray(self._dt_cache, tdtype)
        else:
            dt0 = jnp.min(_mhd_fused_courant(
                self.u, self.bfs, self.dev, spec)).astype(tdtype)
        with self.timers.section("hydro - godunov"):
            out = _mhd_fused_multi_step(
                self.u, self.bfs, self.dev, jnp.asarray(self.t, tdtype),
                jnp.asarray(tend, tdtype), dt0, spec, nsteps,
                trace=trace)
            if trace:
                u, bf, t, dtn, ndone, hist = out
            else:
                u, bf, t, dtn, ndone = out
            self.u, self.bfs = u, bf
            self._dt_cache = dtn
        self.t = float(t)
        n = int(ndone)
        self.nstep += n
        self.dt_old = float(dtn)
        if trace:
            ts, dts = jax.device_get(hist)
            return n, (ts[:n], dts[:n])
        return n

    # ---- diagnostics ---------------------------------------------------
    def totals(self):
        """Conservation audit over leaf cells (nvar = MHD layout)."""
        tot = np.zeros(self.mcfg.nvar)
        for l in self.levels():
            m = self.maps[l]
            vol = self.dx(l) ** self.tree_ndim
            u = np.asarray(self.u[l])[:m.noct * 2 ** self.tree_ndim]
            leaf = ~self.tree.refined_mask(l)
            tot += u[leaf].sum(axis=0) * vol
        return tot

    def max_divb(self) -> float:
        """Max |divB| over LEAF cells of every level (duplicated-face
        staggered divergence — machine-zero under CT + div-free
        transfer)."""
        worst = 0.0
        for l in self.levels():
            m = self.maps[l]
            dxl = self.dx(l)
            bf = np.asarray(self.bfs[l])[:m.noct * 2 ** self.cfg.ndim]
            leaf = ~self.tree.refined_mask(l)
            if not leaf.any():
                continue
            div = sum((bf[:, d, 1] - bf[:, d, 0]) / dxl
                      for d in range(self.tree_ndim))
            bscale = np.abs(bf).max() / dxl + 1e-300
            worst = max(worst, float(np.abs(div[leaf]).max()) / bscale)
        return worst

    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path=None, ncpu: int = 1) -> str:
        """Reference-format snapshot with the MHD column set (density,
        velocity, B_left/right faces, pressure —
        ``mhd/output_hydro.f90:82-150``); the duplicated staggered
        faces round-trip exactly."""
        from ramses_tpu.io import snapshot as snapmod
        snap = snapmod.snapshot_from_mhd_amr(self, iout)
        return snapmod.dump_all(snap, iout, base_dir,
                                namelist_path=namelist_path, ncpu=ncpu)

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float32) -> "MhdAmrSim":
        """Resume from an MHD snapshot (``mhd/init_hydro.f90`` restart
        read: the face fields come back verbatim, the cell-centred B is
        their mean)."""
        from ramses_tpu.amr.hierarchy import restore_amr_scaffold
        from ramses_tpu.io.snapshot import mhd_out_to_state
        mcfg = MhdStatic.from_params(params)
        ttd = 2 ** params.ndim

        def place(sim, l, q, og, order):
            m = sim.maps[l]
            u_rows, bf_rows = mhd_out_to_state(q, mcfg)
            u_out = np.array(sim.u[l])
            bf_out = np.array(sim.bfs[l])
            u_out[:m.noct * ttd] = u_rows.reshape(
                len(og), ttd, mcfg.nvar)[order].reshape(-1, mcfg.nvar)
            bf_out[:m.noct * ttd] = bf_rows.reshape(
                len(og), ttd, 3, 2)[order].reshape(-1, 3, 2)
            sim.u[l] = jnp.asarray(u_out, dtype=dtype)
            sim.bfs[l] = jnp.asarray(bf_out, dtype=dtype)

        sim, _parts = restore_amr_scaffold(
            cls, params, outdir, dtype, to_cons=lambda q: q,
            place_level=place)
        return sim
