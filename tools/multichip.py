"""Multi-chip dryrun with SPMD partitioner-health gating.

Runs ``__graft_entry__.dryrun_multichip(n)`` in a subprocess (CPU
host-device mesh), captures stderr, and counts XLA's "Involuntary full
rematerialization" SPMD warnings — the signature of a global-view op
the partitioner could only reshard by replicating the full tensor
(MULTICHIP_r05 showed the complete-level dense sweep doing exactly
that every coarse step).  Writes ``MULTICHIP_local.json`` with the
same shape as the driver's ``MULTICHIP_*.json`` plus a top-level
``remat_warnings`` count, and exits nonzero when the count is > 0 so
CI fails loudly on a partitioner regression.

Usage::

Also mirrors the result into a telemetry JSONL event log (run-header +
``dryrun`` event + one ``xla_warning`` event per captured remat line)
next to ``--out`` so ``tools/telemetry_report.py`` renders dryruns and
runs from the same schema.

Usage::

    python tools/multichip.py [--devices N] [--out PATH]
                              [--telemetry PATH.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REMAT_MARK = "Involuntary full rematerialization"
TAIL_BYTES = 8000


def run_dryrun(n_devices: int, repo: str):
    """One subprocess dryrun; returns (result record, raw stderr)."""
    env = dict(os.environ)
    # force the CPU backend even where an accelerator plugin's
    # sitecustomize overrides JAX_PLATFORMS
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.setdefault("XLA_FLAGS", "")
    code = (f"import __graft_entry__ as g; "
            f"g.dryrun_multichip({n_devices})")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=env,
        capture_output=True, text=True, timeout=1800)
    stderr = proc.stderr or ""
    tail = (proc.stdout or "")[-TAIL_BYTES:] + stderr[-TAIL_BYTES:]
    remat = stderr.count(REMAT_MARK)
    return {
        "n_devices": n_devices,
        "rc": proc.returncode,
        "ok": proc.returncode == 0 and remat == 0,
        "skipped": False,
        "remat_warnings": remat,
        "tail": tail,
    }, stderr


def run_lint(n_devices: int, repo: str):
    """Static-analysis leg: ``__graft_entry__.dryrun_lint(n)`` in a
    subprocess — the same engine and baseline as ``tools/lint.py
    --check``, on the same CPU mesh as the dryrun, so a partitioner-
    visible hazard (dropped donation, non-unique scatter-add, gather
    budget blowout) fails this gate even when it does not remat."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    code = (f"import __graft_entry__ as g; "
            f"g.dryrun_lint({n_devices})")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=env,
        capture_output=True, text=True, timeout=1800)
    tail = ((proc.stdout or "")[-TAIL_BYTES:]
            + (proc.stderr or "")[-TAIL_BYTES:])
    return {
        "n_devices": n_devices,
        "rc": proc.returncode,
        "ok": proc.returncode == 0,
        "tail": tail,
    }


def emit_telemetry(path: str, res: dict, stderr: str, repo: str):
    """Mirror the dryrun result into a telemetry JSONL event log: a
    run-header, one ``dryrun`` event, one ``xla_warning`` event per
    rematerialization line XLA wrote to the subprocess's raw stderr
    (C++ warnings never reach Python's ``warnings`` machinery — this
    fold is how they land next to the step records CI plots)."""
    sys.path.insert(0, repo)
    from ramses_tpu.telemetry import Telemetry, TelemetrySpec
    tel = Telemetry(TelemetrySpec(path=path),
                    run_info={"driver": "multichip_dryrun",
                              "ndev": res["n_devices"]})
    for line in stderr.splitlines():
        if REMAT_MARK in line:
            tel.warn(line.strip(), source="xla:stderr")
    tel.record_event("dryrun", n_devices=res["n_devices"],
                     rc=res["rc"], ok=res["ok"],
                     remat_warnings=res["remat_warnings"])
    for line in stderr.splitlines():
        if REMAT_MARK in line:
            tel.record_event("xla_warning", msg=line.strip()[:500],
                             source="xla:stderr")
    tel.close(print_timers=False)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default="MULTICHIP_local.json")
    ap.add_argument("--telemetry", default=None,
                    help="telemetry JSONL path (default: --out with a "
                         ".jsonl suffix)")
    ap.add_argument("--lint", action="store_true",
                    help="also run the static-analysis leg "
                         "(__graft_entry__.dryrun_lint) and fail on "
                         "unbaselined findings")
    args = ap.parse_args(argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res, stderr = run_dryrun(args.devices, repo)
    if args.lint:
        res["lint"] = run_lint(args.devices, repo)
    tpath = args.telemetry or (
        os.path.splitext(args.out)[0] + ".jsonl")
    try:
        emit_telemetry(tpath, res, stderr, repo)
        res["telemetry"] = tpath
    except Exception as e:      # the gate result must survive regardless
        print(f"multichip: telemetry emit failed: {e}", file=sys.stderr)
    with open(args.out, "w") as f:
        json.dump(res, f, indent=1)
    print(f"dryrun on {res['n_devices']} devices: rc={res['rc']} "
          f"remat_warnings={res['remat_warnings']} -> {args.out}")
    if res["rc"] != 0:
        sys.stderr.write(res["tail"] + "\n")
        return res["rc"]
    if res["remat_warnings"]:
        sys.stderr.write(
            f"FAIL: {res['remat_warnings']} involuntary full "
            "rematerialization warning(s) — a global-view op reached "
            "the SPMD partitioner (see parallel/dense_slab.py)\n")
        return 3
    if args.lint and not res["lint"]["ok"]:
        sys.stderr.write("FAIL: static-analysis leg found unbaselined "
                         "findings\n" + res["lint"]["tail"] + "\n")
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
