"""Snapshot post-processing: amr2map / part2map equivalents.

The reference ships 56 standalone f90 analysis programs (``utils/f90``,
SURVEY.md §2.11); the two workhorses project AMR snapshots
(``amr2map``) and particle snapshots (``part2map``) to 2D maps.  These
read our ``output_NNNNN`` directories through :mod:`ramses_tpu.io.reader`
and write the movie frame format.

CLI:  ``python -m ramses_tpu.utils.maps amr2map output_00001 out.map
      --var density --dir z --nx 256``
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from ramses_tpu.io import reader as rdr
from ramses_tpu.io.movie import write_frame


def amr2map(outdir: str, var: str = "density", axis: int = 2,
            nx: int = 256, kind: str = "mean") -> np.ndarray:
    """Project leaf cells onto a 2D grid (mass/volume-weighted)."""
    snap = rdr.load_snapshot(outdir)
    cells = rdr.leaf_cells(snap)
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    axes2d = [d for d in range(ndim) if d != axis][:2]
    if ndim == 1:
        axes2d = [0]
    vals = cells[var]
    dx = cells["dx"]
    w = dx ** ndim                     # volume weight
    if kind == "max":
        grid = np.full((nx,) * min(len(axes2d), 2), -np.inf)
    else:
        grid = np.zeros((nx,) * min(len(axes2d), 2))
        wsum = np.zeros_like(grid)
    coords = [np.clip((cells["xyz"[d]] / boxlen * nx).astype(int),
                      0, nx - 1) for d in axes2d]
    idx = tuple(coords)
    if kind == "max":
        np.maximum.at(grid, idx, vals)
        grid[np.isneginf(grid)] = 0.0
        return grid
    np.add.at(grid, idx, vals * w)
    np.add.at(wsum, idx, w)
    return grid / np.maximum(wsum, 1e-300)


def part2map(outdir: str, axis: int = 2, nx: int = 256) -> np.ndarray:
    """Mass-weighted particle surface density map."""
    snap = rdr.load_snapshot(outdir)
    if "part" not in snap:
        raise FileNotFoundError(f"no particle files in {outdir}")
    part = snap["part"][0]
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    axes2d = [d for d in range(ndim) if d != axis][:2]
    grid = np.zeros((nx,) * min(len(axes2d), 2))
    coords = [np.clip((part[f"position_{'xyz'[d]}"] / boxlen * nx)
                      .astype(int), 0, nx - 1) for d in axes2d]
    np.add.at(grid, tuple(coords), part["mass"])
    return grid * (nx / boxlen) ** len(axes2d)


def read_map(path: str):
    """Read a ``.map`` binary frame (the amr2map/movie format; one
    parser — :func:`ramses_tpu.io.movie.read_frame` — serves both
    consumers).  Returns (map [nx, ny] float64, meta dict with ``t``
    and the window ``bounds``)."""
    from ramses_tpu.io.movie import read_frame
    fr = read_frame(path)
    return (np.asarray(fr["data"], dtype=np.float64),
            dict(t=float(fr["t"]), bounds=tuple(fr["bounds"])))


# a compact viridis-like ramp (anchor RGB rows, linearly interpolated)
_RAMP = np.array([[68, 1, 84], [59, 82, 139], [33, 145, 140],
                  [94, 201, 98], [253, 231, 37]], dtype=np.float64)


def map2img(map_path: str, img_path: str, log: bool = False,
            vmin=None, vmax=None) -> tuple:
    """``.map`` frame → image (the ``map2bmp.c`` / ``map2img.py``
    role): log/linear scaling with optional clipping, colormapped to
    a dependency-free binary PPM (or grayscale PGM with ``.pgm``).
    ``vmin``/``vmax`` are in DATA units; with ``log`` they are
    log10'd alongside the data (non-positive thresholds fall back to
    the data range)."""
    m, _meta = read_map(map_path)
    if log:
        m = np.log10(np.maximum(m, 1e-300))
        vmin = np.log10(vmin) if vmin is not None and vmin > 0 else None
        vmax = np.log10(vmax) if vmax is not None and vmax > 0 else None
    lo = float(np.min(m) if vmin is None else vmin)
    hi = float(np.max(m) if vmax is None else vmax)
    u = np.clip((m - lo) / max(hi - lo, 1e-300), 0.0, 1.0)
    img = u.T[::-1]                       # y up, like map2img.py
    h, w = img.shape
    if img_path.endswith(".pgm"):
        with open(img_path, "wb") as f:
            f.write(f"P5\n{w} {h}\n255\n".encode())
            f.write((img * 255).astype(np.uint8).tobytes())
    else:
        pos = img * (len(_RAMP) - 1)
        i0 = np.clip(pos.astype(int), 0, len(_RAMP) - 2)
        fr = pos - i0
        rgb = (_RAMP[i0] * (1 - fr[..., None])
               + _RAMP[i0 + 1] * fr[..., None])
        with open(img_path, "wb") as f:
            f.write(f"P6\n{w} {h}\n255\n".encode())
            f.write(rgb.astype(np.uint8).tobytes())
    return w, h


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ramses_tpu.utils.maps")
    ap.add_argument("tool", choices=["amr2map", "part2map", "map2img"])
    ap.add_argument("src", help="output_NNNNN directory "
                    "(amr2map/part2map) or .map file (map2img)")
    ap.add_argument("dst", help=".map file (amr2map/part2map) or "
                    "image file .ppm/.pgm (map2img)")
    ap.add_argument("--var", default="density")
    ap.add_argument("--dir", default="z", choices=["x", "y", "z"])
    ap.add_argument("--nx", type=int, default=256)
    ap.add_argument("--kind", default="mean",
                    choices=["mean", "max"])
    ap.add_argument("--log", action="store_true")
    ap.add_argument("--min", type=float, default=None)
    ap.add_argument("--max", type=float, default=None)
    args = ap.parse_args(argv)
    if args.tool == "map2img":
        w, h = map2img(args.src, args.dst, log=args.log,
                       vmin=args.min, vmax=args.max)
        print(f"map2img: {w}x{h} -> {args.dst}")
        return 0
    axis = "xyz".index(args.dir)
    if args.tool == "amr2map":
        m = amr2map(args.src, var=args.var, axis=axis, nx=args.nx,
                    kind=args.kind)
    else:
        m = part2map(args.src, axis=axis, nx=args.nx)
    write_frame(args.dst, m)
    print(f"{args.tool}: {m.shape} map -> {args.dst} "
          f"(min {m.min():.4e} max {m.max():.4e})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
