"""Source-level rules: host-sync hazards the lowered HLO cannot show.

A ``jax.device_get`` / ``.block_until_ready()`` in a kernel-layer
module serializes the dispatch pipeline — the class of bug the
telemetry "zero added device fetches" pins guard dynamically; this
rule catches new ones statically, at the AST level, before any test
runs.  The driver layer (``driver.py`` modules, ensemble engine,
resilience, io, telemetry, utils) is allowlisted: that is where the
one designed sync per fused window lives.

Also covers the non-hashable jit static-arg hazard: a function
jitted with ``static_argnums``/``static_argnames`` whose static
parameter defaults to a list/dict/set literal fails at call time
with an unhashable-type error — but only on the first call with the
default, which is exactly the path tests skip.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Tuple

from ramses_tpu.analysis.rules import Finding, Rule, Severity, register

# module prefixes (relative to the package root) where host syncs are
# the designed fetch boundary, not a hazard
HOST_SYNC_ALLOW_PREFIXES = (
    "telemetry/", "utils/", "resilience/", "io/", "ensemble/",
)
# file basenames allowlisted anywhere: the driver layer owns the one
# sync per fused window, and the platform/__main__ shims run at startup
HOST_SYNC_ALLOW_BASENAMES = (
    "driver.py", "__main__.py", "platform.py", "patch.py",
)

_SYNC_CALLS = ("device_get", "block_until_ready")
# state-array roots: float()/int()/np.asarray() directly on a device
# state attribute is an implicit transfer + sync in a hot loop
_STATE_ATTRS = ("u", "bfs", "fg", "dev")
_CAST_FUNCS = ("float", "int")
_NP_FUNCS = ("asarray", "array")


def _pkg_root() -> str:
    import ramses_tpu
    return os.path.dirname(os.path.abspath(ramses_tpu.__file__))


def _iter_sources(root: str):
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _relmod(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _enclosing_func(stack: List[ast.AST]) -> str:
    names = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    return ".".join(names) or "<module>"


def _state_attr_root(node: ast.AST) -> Optional[str]:
    """``self.u[...]`` / ``sim.bfs`` style roots of a device state
    array, or None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _STATE_ATTRS \
            and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "sim"):
        return f"{node.value.id}.{node.attr}"
    return None


class _SyncVisitor(ast.NodeVisitor):
    def __init__(self, module: str):
        self.module = module
        self.stack: List[ast.AST] = []
        # {(func, callname): count}
        self.hits: dict = {}

    def _record(self, callname: str):
        key = (_enclosing_func(self.stack), callname)
        self.hits[key] = self.hits.get(key, 0) + 1

    def visit_FunctionDef(self, node):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_CALLS:
                # jax.device_get(...) / arr.block_until_ready()
                self._record(f.attr)
            elif f.attr in _NP_FUNCS and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy") and node.args \
                    and _state_attr_root(node.args[0]):
                self._record(
                    f"np.{f.attr}({_state_attr_root(node.args[0])})")
        elif isinstance(f, ast.Name) and f.id in _CAST_FUNCS \
                and node.args and _state_attr_root(node.args[0]):
            self._record(f"{f.id}({_state_attr_root(node.args[0])})")
        self.generic_visit(node)


def _allowlisted(rel: str) -> bool:
    return rel.startswith(HOST_SYNC_ALLOW_PREFIXES) \
        or os.path.basename(rel) in HOST_SYNC_ALLOW_BASENAMES


def _check_host_sync(root: Optional[str] = None) -> List[Finding]:
    root = root or _pkg_root()
    out: List[Finding] = []
    for path in _iter_sources(root):
        rel = _relmod(path, root)
        if _allowlisted(rel):
            continue
        try:
            tree = ast.parse(open(path).read(), filename=path)
        except SyntaxError as e:    # a broken file is its own finding
            out.append(Finding(
                rule="host-sync", severity=Severity.ERROR,
                program=rel, message=f"unparseable module: {e}",
                key="syntax-error"))
            continue
        v = _SyncVisitor(rel)
        v.visit(tree)
        for (func, callname), n in sorted(v.hits.items()):
            # explicit sync calls (device_get / block_until_ready)
            # gate at WARN; implicit transfers (float()/np.asarray()
            # on a state root) are INFO — usually host-side
            # IC/diagnostic passes, but worth surfacing in the report
            sev = Severity.WARN if callname in _SYNC_CALLS \
                else Severity.INFO
            out.append(Finding(
                rule="host-sync", severity=sev,
                program=rel,
                message=(f"{callname} in {rel}:{func} ({n} site(s)) "
                         "— a host sync in a kernel-layer module "
                         "serializes the dispatch pipeline; move the "
                         "fetch to the driver layer or baseline it "
                         "as a designed sync point"),
                key=f"{func}:{callname}",
                detail={"function": func, "call": callname,
                        "count": n}))
    return out


register(Rule(
    id="host-sync", kind="source", check=_check_host_sync,
    doc=("The telemetry zero-overhead pins count device fetches "
         "dynamically; this is the static version.  Flags "
         "jax.device_get / .block_until_ready() / float(state) / "
         "np.asarray(state) in kernel-layer modules outside the "
         "driver/telemetry/guard allowlist.")))


# ---------------------------------------------------------------------
# static-arg-hazard: non-hashable jit static arguments
# ---------------------------------------------------------------------
def _jit_static_args(dec: ast.AST) -> Optional[Tuple[List[int],
                                                     List[str]]]:
    """``(static_argnums, static_argnames)`` when ``dec`` is a
    ``jax.jit`` / ``partial(jax.jit, ...)`` decorator, else None."""
    if not isinstance(dec, ast.Call):
        return None
    f = dec.func
    is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") or \
        (isinstance(f, ast.Name) and f.id == "jit")
    if isinstance(f, ast.Name) and f.id == "partial" and dec.args:
        inner = dec.args[0]
        if (isinstance(inner, ast.Attribute) and inner.attr == "jit") \
                or (isinstance(inner, ast.Name) and inner.id == "jit"):
            is_jit = True
    if not is_jit:
        return None
    nums: List[int] = []
    names: List[str] = []
    for kw in dec.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        vals = kw.value.elts if isinstance(
            kw.value, (ast.Tuple, ast.List)) else [kw.value]
        for v in vals:
            if isinstance(v, ast.Constant):
                if isinstance(v.value, int):
                    nums.append(v.value)
                elif isinstance(v.value, str):
                    names.append(v.value)
    return nums, names


_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _check_static_args(root: Optional[str] = None) -> List[Finding]:
    root = root or _pkg_root()
    out: List[Finding] = []
    for path in _iter_sources(root):
        rel = _relmod(path, root)
        try:
            tree = ast.parse(open(path).read(), filename=path)
        except SyntaxError:
            continue                # host-sync already reports this
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            spec = None
            for dec in node.decorator_list:
                spec = _jit_static_args(dec)
                if spec:
                    break
            if not spec:
                continue
            nums, names = spec
            args = node.args.args
            ndef = len(node.args.defaults)
            for i, a in enumerate(args):
                static = i in nums or a.arg in names
                if not static:
                    continue
                di = i - (len(args) - ndef)
                if di < 0:
                    continue        # no default
                if isinstance(node.args.defaults[di],
                              _MUTABLE_LITERALS):
                    out.append(Finding(
                        rule="static-arg-hazard",
                        severity=Severity.ERROR, program=rel,
                        message=(f"{rel}:{node.name} jits "
                                 f"{a.arg!r} as a static argument "
                                 "with a mutable (unhashable) "
                                 "default — the first call relying "
                                 "on the default raises TypeError "
                                 "at the jit cache lookup"),
                        key=f"{node.name}:{a.arg}",
                        detail={"function": node.name,
                                "arg": a.arg}))
    return out


register(Rule(
    id="static-arg-hazard", kind="source", check=_check_static_args,
    doc=("jit static arguments are dict keys of the compile cache; a "
         "mutable default (list/dict/set) on a static parameter is "
         "unhashable and explodes only on the rarely-tested "
         "default-argument path.  Flags jitted functions whose "
         "static args default to mutable literals.")))


# ---------------------------------------------------------------------
# differentiability: the double-where gradient hazard
# ---------------------------------------------------------------------
# ``jnp.where(p, f(x), g(x))`` evaluates BOTH branches; reverse-mode AD
# multiplies each branch cotangent by 0/1 *after* differentiating it,
# so an Inf/NaN in the untaken branch (division by a quantity that can
# vanish there, fractional powers or sqrt/log at 0) becomes 0 * Inf =
# NaN and poisons the whole gradient even though the forward value is
# clamped.  The repaired idiom guards the hazardous sub-expression
# with a second where that feeds it safe inputs where the branch is
# unconsumed — which this rule recognizes as a denominator/base/arg
# that is itself a ``jnp.where`` call, or a name bound to one.
#
# Scope: the differentiable step-chain kernels (``hydro/``, ``mhd/``)
# only — the adjoint rollout (ramses_tpu/diff) differentiates through
# those; AMR/driver layers run forward-only.
DIFF_PREFIXES = ("hydro/", "mhd/")
_SQRT_LIKE = ("sqrt", "rsqrt", "cbrt", "log", "log2", "log10", "log1p")


def _is_where_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "where") or \
        (isinstance(f, ast.Name) and f.id == "where")


def _where_bound_names(tree: ast.AST) -> set:
    """Names assigned from a ``jnp.where(...)`` call anywhere in the
    module — the hoisted-guard idiom (``den = jnp.where(p, x, 1.0)``;
    ``jnp.where(p, a / den, 0.0)``)."""
    bound = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_where_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    bound.add(tgt.id)
    return bound


def _mentions_guard(node: ast.AST, bound: set) -> bool:
    """True when the expression is visibly guarded: it is (or
    contains) a where call or a where-bound name."""
    for sub in ast.walk(node):
        if _is_where_call(sub):
            return True
        if isinstance(sub, ast.Name) and sub.id in bound:
            return True
    return False


def _safe_denominator(node: ast.AST, bound: set) -> bool:
    # literal constants, static config scalars (cfg.smallr, self.dx)
    # and guarded expressions cannot vanish in the untaken branch
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                     ast.Name):
        return True
    return _mentions_guard(node, bound)


def _branch_hazards(branch: ast.AST, bound: set):
    """``kind`` strings for unguarded hazards inline in one where
    branch (nested where calls own their branches and are skipped —
    the visitor reaches them separately)."""
    stack = [branch]
    while stack:
        node = stack.pop()
        if node is not branch and _is_where_call(node):
            continue                # its branches get their own visit
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            if not _safe_denominator(node.right, bound):
                yield "div"
        elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                        ast.Pow):
            exp = node.right
            fractional = not (isinstance(exp, ast.Constant)
                              and isinstance(exp.value, (int, bool)))
            if fractional and not _mentions_guard(node.left, bound):
                yield "pow"
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SQRT_LIKE and node.args \
                and not _mentions_guard(node.args[0], bound):
            yield node.func.attr
        stack.extend(ast.iter_child_nodes(node))


class _DiffVisitor(ast.NodeVisitor):
    def __init__(self, bound: set):
        self.bound = bound
        self.stack: List[ast.AST] = []
        self.hits: dict = {}        # {(func, kind): count}

    def visit_FunctionDef(self, node):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        if _is_where_call(node) and len(node.args) >= 3:
            func = _enclosing_func(self.stack)
            for branch in node.args[1:3]:
                for kind in _branch_hazards(branch, self.bound):
                    key = (func, kind)
                    self.hits[key] = self.hits.get(key, 0) + 1
        self.generic_visit(node)


def _check_differentiability(root: Optional[str] = None) -> List[Finding]:
    root = root or _pkg_root()
    out: List[Finding] = []
    for path in _iter_sources(root):
        rel = _relmod(path, root)
        if not rel.startswith(DIFF_PREFIXES):
            continue
        try:
            tree = ast.parse(open(path).read(), filename=path)
        except SyntaxError:
            continue                # host-sync already reports this
        v = _DiffVisitor(_where_bound_names(tree))
        v.visit(tree)
        for (func, kind), n in sorted(v.hits.items()):
            out.append(Finding(
                rule="differentiability", severity=Severity.WARN,
                program=rel,
                message=(f"unguarded {kind} inside a where branch in "
                         f"{rel}:{func} ({n} site(s)) — both where "
                         "branches are differentiated, so an Inf in "
                         "the untaken branch turns into 0*Inf = NaN "
                         "in the cotangent; guard the hazardous "
                         "sub-expression with a second where "
                         "(double-where idiom) or baseline it if the "
                         "kernel is outside the adjoint rollout"),
                key=f"{func}:{kind}",
                detail={"function": func, "hazard": kind,
                        "count": n}))
    return out


register(Rule(
    id="differentiability", kind="source",
    check=_check_differentiability,
    doc=("The adjoint rollout (ramses_tpu/diff) differentiates the "
         "hydro/mhd step chains; jnp.where evaluates both branches, "
         "so an unguarded division / fractional power / sqrt-like "
         "call inline in a where branch NaN-poisons reverse-mode "
         "gradients (0 * Inf) even when the forward value is "
         "clamped.  Flags those sites; the accepted remainder "
         "(forward-only kernels) lives in the baseline.")))
