"""Poisson solver + gravity coupling tests.

Oracle strategy (SURVEY.md §4): the FFT path is the *exact* solution of
the discrete 7-point system, so MG and CG are validated against it; the
force gradient and analytic models are validated against closed forms
(the reference's poisson/ana-disk-potential test pattern).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.poisson import solver as ps
from ramses_tpu.poisson import force as pf
from ramses_tpu.poisson.gravana import cell_centers, gravana
from ramses_tpu.poisson.coupling import GravitySpec, kick
from ramses_tpu.pm.coupling import PMSpec, pm_hydro_step
from ramses_tpu.hydro.core import HydroStatic



pytestmark = pytest.mark.smoke

def _random_rhs(shape, seed=0):
    rng = np.random.default_rng(seed)
    r = rng.standard_normal(shape)
    return jnp.asarray(r - r.mean())


@pytest.mark.parametrize("shape", [(64,), (32, 32), (16, 16, 16)])
def test_fft_solves_discrete_laplacian(shape):
    rhs = _random_rhs(shape)
    dx = 1.0 / shape[0]
    phi = ps.fft_solve(rhs, dx)
    res = ps.residual(phi, rhs, dx)
    assert float(jnp.max(jnp.abs(res))) < 1e-8 * float(jnp.max(jnp.abs(rhs)))


@pytest.mark.parametrize("shape", [(64,), (32, 32), (16, 16, 16)])
def test_mg_matches_fft(shape):
    rhs = _random_rhs(shape, seed=1)
    dx = 1.0 / shape[0]
    ref = ps.fft_solve(rhs, dx)
    phi = ps.mg_solve(rhs, dx, ncycle=10)
    scale = float(jnp.max(jnp.abs(ref))) + 1e-30
    assert float(jnp.max(jnp.abs(phi - ref))) / scale < 1e-6


@pytest.mark.parametrize("shape", [(64,), (16, 16, 16)])
def test_cg_matches_fft(shape):
    rhs = _random_rhs(shape, seed=2)
    dx = 1.0 / shape[0]
    ref = ps.fft_solve(rhs, dx)
    phi = ps.cg_solve(rhs, dx, iters=300)
    scale = float(jnp.max(jnp.abs(ref))) + 1e-30
    assert float(jnp.max(jnp.abs(phi - ref))) / scale < 1e-6


def test_sine_wave_continuum_limit():
    # Lap(phi) = -k^2 sin(kx) -> phi = sin(kx); discrete answer converges.
    errs = []
    for n in (32, 64, 128):
        dx = 1.0 / n
        x = (jnp.arange(n) + 0.5) * dx
        k = 2 * jnp.pi
        rhs = -k * k * jnp.sin(k * x)
        phi = ps.fft_solve(rhs, dx)
        ref = jnp.sin(k * x)
        ref = ref - jnp.mean(ref)
        errs.append(float(jnp.max(jnp.abs(phi - ref))))
    assert errs[1] < errs[0] / 3.5 and errs[2] < errs[1] / 3.5  # ~2nd order


def test_force_fourth_order_gradient():
    errs = []
    k = 2 * jnp.pi
    for n in (16, 32):
        dx = 1.0 / n
        x = (jnp.arange(n) + 0.5) * dx
        phi = jnp.sin(k * x)
        f = pf.force(phi, dx)[0]
        ref = -k * jnp.cos(k * x)
        errs.append(float(jnp.max(jnp.abs(f - ref))))
    assert errs[1] < errs[0] / 14.0  # 4th order: factor 16 per halving


def test_gravana_point_mass():
    shape = (16, 16, 16)
    dx = 1.0 / 16
    x = cell_centers(shape, dx)
    c = (8 + 0.5) * dx  # a cell center, so off-axis components vanish
    f = gravana(x, 2, (2.0, 0.0, c, c, c), 1.0)
    # acceleration points toward the center, GM/r^2 magnitude
    i = (2, 8, 8)
    r = c - (2 + 0.5) * dx
    assert np.isclose(float(f[(0,) + i]), 2.0 / r ** 2, rtol=1e-12)
    assert abs(float(f[(1,) + i])) < 1e-12


def test_gravana_constant():
    shape = (8, 8)
    x = cell_centers(shape, 1.0 / 8)
    f = gravana(x, 1, (-0.1, 0.3), 1.0)
    assert np.allclose(np.asarray(f[0]), -0.1)
    assert np.allclose(np.asarray(f[1]), 0.3)


def test_kick_preserves_internal_energy():
    cfg = HydroStatic(ndim=2, gamma=1.4)
    rng = np.random.default_rng(3)
    u = jnp.asarray(np.abs(rng.standard_normal((cfg.nvar, 8, 8))) + 1.0)
    f = jnp.asarray(rng.standard_normal((2, 8, 8)))
    u2 = kick(u, f, 0.1, cfg)
    def eint(u):
        r = u[0]
        return u[cfg.ndim + 1] - 0.5 * (u[1] ** 2 + u[2] ** 2) / r
    assert np.allclose(np.asarray(eint(u2)), np.asarray(eint(u)), rtol=1e-12)
    # momentum kicked by rho*f*dt
    assert np.allclose(np.asarray(u2[1] - u[1]),
                       np.asarray(u[0] * f[0] * 0.1), rtol=1e-12)


def test_uniform_medium_stays_uniform_under_selfgravity():
    """Jeans-stable uniform state: f=0 (zero density contrast), u frozen."""
    cfg = HydroStatic(ndim=3, gamma=1.4)
    from ramses_tpu.grid.uniform import UniformGrid
    from ramses_tpu.grid.boundary import BoundarySpec
    grid = UniformGrid(cfg=cfg, shape=(16, 16, 16), dx=1.0 / 16,
                       bc=BoundarySpec.periodic(3))
    spec = GravitySpec(enabled=True)
    n = 16
    u = jnp.zeros((cfg.nvar, n, n, n), jnp.float64)
    u = u.at[0].set(1.0).at[4].set(1.0 / (1.4 - 1.0) / 1.0)
    f0 = jnp.zeros((3, n, n, n), jnp.float64)
    pspec = PMSpec(enabled=False, hydro=True)
    u1, _p, f1 = pm_hydro_step(grid, spec, pspec, u, None, f0,
                               jnp.asarray(0.01), jnp.asarray(0.0))
    assert float(jnp.max(jnp.abs(f1))) < 1e-10
    assert float(jnp.max(jnp.abs(u1 - u))) < 1e-10


def test_plummer_like_collapse_accelerates_inward():
    """A central overdensity must produce inward acceleration."""
    spec = GravitySpec(enabled=True)
    n = 32
    dx = 1.0 / n
    x = cell_centers((n, n, n), dx)
    r2 = sum((x[d] - 0.5) ** 2 for d in range(3))
    rho = 1.0 + 10.0 * jnp.exp(-r2 / (2 * 0.05 ** 2))
    from ramses_tpu.poisson.coupling import gravity_field
    f = gravity_field(spec, rho, dx)
    # at (0.75, 0.5, 0.5): f_x must point in -x (toward center)
    assert float(f[0][24, 16, 16]) < 0.0
    assert float(f[0][8, 16, 16]) > 0.0
