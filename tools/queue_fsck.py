#!/usr/bin/env python3
"""Operator CLI for queue crash-consistency and breaker control.

    python tools/queue_fsck.py QUEUE_DIR --check
    python tools/queue_fsck.py QUEUE_DIR --repair
    python tools/queue_fsck.py QUEUE_DIR --check --json findings.json
    python tools/queue_fsck.py QUEUE_DIR --reset-breaker <fp|all>

Exit codes: ``--check`` — 0 clean, 1 repairable findings exist;
``--repair`` — 0 everything repaired, 2 something resisted.
``--reset-breaker`` half-opens the named poison-config breaker(s)
(one parked probe job released each) and exits 0.

Thin shell over :mod:`ramses_tpu.ensemble.fsck` and
:mod:`ramses_tpu.ensemble.breaker` — jax-free, safe to run on a live
queue (a live worker's in-flight staging is never touched).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="queue_fsck",
        description="scan/repair a run-service queue directory")
    ap.add_argument("queue_dir", help="queue directory (--queue of "
                    "submit/serve)")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="scan only (default); exit 1 on findings")
    mode.add_argument("--repair", action="store_true",
                      help="scan and repair; exit 2 on failures")
    ap.add_argument("--json", metavar="PATH", default="",
                    help="also write findings as JSON ('-' = stdout)")
    ap.add_argument("--stale-timeout", type=float, default=300.0,
                    metavar="S", help="heartbeat age beyond which a "
                    "running record counts as dead (default 300)")
    ap.add_argument("--max-attempts", type=int, default=3,
                    help="attempt budget when a dead_running repair "
                    "requeues vs fails (default 3)")
    ap.add_argument("--reset-breaker", metavar="FP", default="",
                    help="half-open the poison-config breaker with "
                    "this fingerprint ('all' = every open breaker) "
                    "and exit")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.queue_dir):
        print(f"queue_fsck: no such queue dir: {args.queue_dir}",
              file=sys.stderr)
        return 2

    from ramses_tpu.ensemble import breaker as bk
    from ramses_tpu.ensemble import fsck as qfsck

    if args.reset_breaker:
        done = bk.reset(args.queue_dir, args.reset_breaker, log=print)
        if not done:
            print(f"queue_fsck: no open breaker matched "
                  f"{args.reset_breaker!r}")
        return 0

    code, findings = qfsck.fsck(
        args.queue_dir, do_repair=bool(args.repair),
        stale_s=args.stale_timeout, max_attempts=args.max_attempts,
        log=print)
    if args.json:
        payload = json.dumps({"exit_code": code, "findings": [
            f.to_dict() for f in findings]}, indent=1)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    verdict = ("clean" if not findings else
               f"{len(findings)} finding(s), "
               f"{sum(1 for f in findings if f.repaired)} repaired")
    print(f"queue_fsck: {verdict}")
    return code


if __name__ == "__main__":
    raise SystemExit(main())
