"""Particle-mesh layer: particles, deposition, dynamics, cosmology.

TPU-native replacement of the reference ``pm/`` layer (SURVEY.md §2.7).
The Fortran's per-grid linked lists (``pm/pm_commons.f90:46-96``) become
fixed-size SoA device arrays with an active mask; the tree sort becomes
index arithmetic; CIC/TSC deposition becomes scatter-add; the halo
migration (``virtual_tree_fine``) becomes resharding of the particle
arrays over the device mesh.
"""
