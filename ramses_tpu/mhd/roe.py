"""Roe-type characteristic-upwind solver for adiabatic MHD.

Counterpart of the reference's ``athena_roe`` 1D solver
(``mhd/godunov_utils.f90:878``, dispatched from ``mhd/umuscl.f90:1396``
for ``riemann='roe'`` and for the 2D corner solver ``riemann2d='roe'``).

Built from the published formulation, not the reference's code:

* Cargo & Gallice (1997) Roe averages — sqrt-density-weighted
  velocities and enthalpy, OPPOSITE-weighted transverse field, and the
  X/Y correction terms in the effective sound speed.
* Roe & Balsara (1996) normalized magnetosonic eigenvectors
  (alpha_f/alpha_s/beta with the degenerate-limit conventions), written
  in PRIMITIVE variables where they are compact and well-conditioned.
* Wave strengths are recovered by a batched 7x7 linear solve
  ``R_p @ alpha = dW`` instead of hand-coded left eigenvectors: the
  expansion is then complete by construction (machine-exact
  ``sum_k alpha_k R_k = dW``), which is the property conservation
  depends on.  The dissipation is mapped to conserved variables through
  the analytic dU/dW Jacobian at the Roe mean.

The 7-wave system (Bn is a constant parameter of the interface):
entropy, 2 Alfven, 2 slow, 2 fast.  No entropy fix (the reference
applies none either).

``zero_flux`` multiplies the centered flux part — the reference's
convention that lets the 2D corner solver reuse the 1D dissipation
(``mhd/umuscl.f90:1978`` passes 0).
"""

from __future__ import annotations

import jax.numpy as jnp

from ramses_tpu.mhd.core import MhdStatic

_EPS = 1e-30


def _prim_jacobian_apply_check():  # pragma: no cover - documentation
    """The quasi-linear primitive system dW/dt + A_p dW/dx = 0 with
    W = (rho, vn, vt1, vt2, P, Bt1, Bt2) and Bn constant:

      rho' : vn rho_x + rho vn_x
      vn'  : vn vn_x + P_x/rho + (Bt1 Bt1_x + Bt2 Bt2_x)/rho
      vt'  : vn vt_x - Bn Bt_x/rho
      P'   : vn P_x + gamma P vn_x
      Bt'  : vn Bt_x + Bt vn_x - Bn vt_x

    tests/test_mhd.py builds this matrix numerically and asserts
    A_p r = lambda r for every eigenvector below at a point state.
    """


def roe_mean(ql, qr, bn, g):
    """Cargo-Gallice averaged state and wave speeds.

    Returns a dict of mean quantities; all arrays broadcast over the
    trailing interface batch."""
    g1, g2 = g - 1.0, g - 2.0
    rl, rr = ql[0], qr[0]
    wl, wr = jnp.sqrt(rl), jnp.sqrt(rr)
    nrm = wl + wr
    d = wl * wr                                   # Roe density
    v = [(wl * ql[k] + wr * qr[k]) / nrm for k in (1, 2, 3)]
    # total enthalpy per unit mass H = (E + Ptot)/rho
    def hside(q, r):
        b2 = bn ** 2 + q[6] ** 2 + q[7] ** 2
        e = q[4] / g1 + 0.5 * r * (q[1] ** 2 + q[2] ** 2 + q[3] ** 2) \
            + 0.5 * b2
        return (e + q[4] + 0.5 * b2) / r
    h = (wl * hside(ql, rl) + wr * hside(qr, rr)) / nrm
    # transverse field: OPPOSITE sqrt-rho weights (CG97)
    bt1 = (wl * qr[6] + wr * ql[6]) / nrm
    bt2 = (wl * qr[7] + wr * ql[7]) / nrm
    x = ((qr[6] - ql[6]) ** 2 + (qr[7] - ql[7]) ** 2) / (2.0 * nrm ** 2)
    y = (rl + rr) / (2.0 * d)

    vsq = v[0] ** 2 + v[1] ** 2 + v[2] ** 2
    btsq = bt1 ** 2 + bt2 ** 2
    bt_starsq = (g1 - g2 * y) * btsq
    vaxsq = bn ** 2 / d
    hp = h - (vaxsq + btsq / d)
    asq = jnp.maximum(g1 * (hp - 0.5 * vsq) - g2 * x, _EPS)
    ct2 = bt_starsq / d
    tsum = vaxsq + ct2 + asq
    tdif = vaxsq + ct2 - asq
    cf2_cs2 = jnp.sqrt(tdif * tdif + 4.0 * asq * ct2)
    cfsq = 0.5 * (tsum + cf2_cs2)
    cf = jnp.sqrt(cfsq)
    cssq = asq * vaxsq / jnp.maximum(cfsq, _EPS)
    cs = jnp.sqrt(cssq)
    a = jnp.sqrt(asq)
    ca = jnp.sqrt(vaxsq)

    bt = jnp.sqrt(jnp.maximum(btsq, 0.0))
    deg_t = bt < 1e-12 * jnp.sqrt(asq * d)        # no transverse field
    isq2 = 1.0 / jnp.sqrt(2.0)
    b1h = jnp.where(deg_t, isq2, bt1 / jnp.maximum(bt, _EPS))
    b2h = jnp.where(deg_t, isq2, bt2 / jnp.maximum(bt, _EPS))
    # alpha_f/alpha_s with the triple-umbilic conventions
    den = jnp.maximum(cfsq - cssq, _EPS)
    af2 = jnp.clip((asq - cssq) / den, 0.0, 1.0)
    as2 = jnp.clip((cfsq - asq) / den, 0.0, 1.0)
    degen = (cfsq - cssq) <= 1e-12 * asq
    alf = jnp.where(degen, 1.0, jnp.sqrt(af2))
    als = jnp.where(degen, 0.0, jnp.sqrt(as2))
    s = jnp.where(bn >= 0.0, 1.0, -1.0)
    return dict(d=d, v=v, h=h, bt1=bt1, bt2=bt2, a=a, asq=asq, ca=ca,
                cf=cf, cs=cs, b1h=b1h, b2h=b2h, alf=alf, als=als, s=s)


def _right_eigenvectors(m):
    """Primitive-variable right eigenvectors (Roe-Balsara normalized).

    Returns (lams [7, ...], R [7 rows(W), 7 waves, ...])."""
    d, v = m["d"], m["v"]
    a, ca, cf, cs = m["a"], m["ca"], m["cf"], m["cs"]
    b1h, b2h, alf, als, s = (m["b1h"], m["b2h"], m["alf"], m["als"],
                             m["s"])
    sqd = jnp.sqrt(d)
    vn = v[0]
    zero = jnp.zeros_like(d)
    one = jnp.ones_like(d)

    def fast(sgn):
        # sgn = -1 for vn - cf, +1 for vn + cf
        return [d * alf,
                sgn * cf * alf,
                -sgn * cs * als * b1h * s,
                -sgn * cs * als * b2h * s,
                d * m["asq"] * alf,
                als * sqd * a * b1h,
                als * sqd * a * b2h]

    def slow(sgn):
        return [d * als,
                sgn * cs * als,
                sgn * cf * alf * b1h * s,
                sgn * cf * alf * b2h * s,
                d * m["asq"] * als,
                -alf * sqd * a * b1h,
                -alf * sqd * a * b2h]

    def alfven(sgn):
        # lambda = vn + sgn*ca ; dvt = -sgn*s*dBt/sqrt(d)
        dbt1, dbt2 = -b2h * sqd, b1h * sqd
        return [zero,
                zero,
                -sgn * s * dbt1 / sqd,
                -sgn * s * dbt2 / sqd,
                zero,
                dbt1,
                dbt2]

    entropy = [one, zero, zero, zero, zero, zero, zero]
    cols = [fast(-1.0), alfven(-1.0), slow(-1.0), entropy,
            slow(1.0), alfven(1.0), fast(1.0)]
    lams = jnp.stack([vn - cf, vn - ca, vn - cs, vn,
                      vn + cs, vn + ca, vn + cf])
    R = jnp.stack([jnp.stack(col) for col in cols], axis=1)  # [row, wave]
    return lams, R


def _cons_of_prim_jac(m, bn, g):
    """dU/dW at the mean state; U=(rho, Mn, Mt1, Mt2, E, Bt1, Bt2)."""
    d, v = m["d"], m["v"]
    bt1, bt2 = m["bt1"], m["bt2"]
    vsq = v[0] ** 2 + v[1] ** 2 + v[2] ** 2
    z = jnp.zeros_like(d)
    o = jnp.ones_like(d)
    ig1 = 1.0 / (g - 1.0)
    rows = [
        [o, z, z, z, z, z, z],
        [v[0], d, z, z, z, z, z],
        [v[1], z, d, z, z, z, z],
        [v[2], z, z, d, z, z, z],
        [0.5 * vsq, d * v[0], d * v[1], d * v[2], ig1 * o, bt1, bt2],
        [z, z, z, z, z, o, z],
        [z, z, z, z, z, z, o],
    ]
    return jnp.stack([jnp.stack(r) for r in rows])   # [7, 7, ...]


def roe_dissipation(ql, qr, bn, cfg: MhdStatic):
    """0.5 * sum_k |lam_k| alpha_k R^cons_k — the upwind half of the Roe
    flux, shared by the 1D solver and the 2D corner EMF."""
    g = cfg.gamma
    m = roe_mean(ql, qr, bn, g)
    lams, R = _right_eigenvectors(m)
    dW = jnp.stack([qr[0] - ql[0], qr[1] - ql[1], qr[2] - ql[2],
                    qr[3] - ql[3], qr[4] - ql[4], qr[6] - ql[6],
                    qr[7] - ql[7]])
    # batched 7x7 solve: move the state axes to batch position
    batch_shape = dW.shape[1:]
    Rb = jnp.moveaxis(R.reshape(7, 7, -1), -1, 0)        # [B, 7, 7]
    dWb = jnp.moveaxis(dW.reshape(7, -1), -1, 0)[..., None]
    alpha = jnp.linalg.solve(Rb, dWb)[..., 0]            # [B, 7]
    alpha = jnp.moveaxis(alpha, 0, -1).reshape((7,) + batch_shape)
    M = _cons_of_prim_jac(m, bn, g)
    # R^cons[:, k] = M @ R[:, k]
    Rc = jnp.einsum("ij...,jk...->ik...", M, R)
    return 0.5 * jnp.einsum("k...,ik...->i...", jnp.abs(lams) * alpha, Rc)


def _flux_cons(q, bn, g):
    """(U, F) with the 7-row layout (Bn row dropped)."""
    r, vn, vt1, vt2, p, bt1, bt2 = (q[0], q[1], q[2], q[3], q[4],
                                    q[6], q[7])
    b2 = bn ** 2 + bt1 ** 2 + bt2 ** 2
    ptot = p + 0.5 * b2
    vdotb = vn * bn + vt1 * bt1 + vt2 * bt2
    e = p / (g - 1.0) + 0.5 * r * (vn ** 2 + vt1 ** 2 + vt2 ** 2) \
        + 0.5 * b2
    U = [r, r * vn, r * vt1, r * vt2, e, bt1, bt2]
    F = [r * vn,
         r * vn * vn - bn * bn + ptot,
         r * vn * vt1 - bn * bt1,
         r * vn * vt2 - bn * bt2,
         (e + ptot) * vn - bn * vdotb,
         vn * bt1 - vt1 * bn,
         vn * bt2 - vt2 * bn]
    return jnp.stack(U), jnp.stack(F)


def _expand8(f7):
    """Insert the zero Bn-flux row back (solver bank layout has 8)."""
    z = jnp.zeros_like(f7[0])
    return jnp.stack([f7[0], f7[1], f7[2], f7[3], f7[4], z, f7[5],
                      f7[6]])


def roe(ql, qr, bn, cfg: MhdStatic, zero_flux=1.0):
    """Roe flux in the rotated interface layout of the solver bank."""
    g = cfg.gamma
    rl = jnp.maximum(ql[0], cfg.smallr)
    rr = jnp.maximum(qr[0], cfg.smallr)
    pl = jnp.maximum(ql[4], cfg.smallr * cfg.smallc ** 2)
    pr = jnp.maximum(qr[4], cfg.smallr * cfg.smallc ** 2)
    qls = ql.at[0].set(rl).at[4].set(pl)
    qrs = qr.at[0].set(rr).at[4].set(pr)
    _, Fl = _flux_cons(qls, bn, g)
    _, Fr = _flux_cons(qrs, bn, g)
    diss = roe_dissipation(qls, qrs, bn, cfg)
    f7 = zero_flux * 0.5 * (Fl + Fr) - diss
    return _expand8(f7)


def upwind(ql, qr, bn, cfg: MhdStatic, zero_flux=1.0):
    """The reference's 1D 'upwind' solver semantics
    (``mhd/godunov_utils.f90:313``): centered flux minus |mean normal
    velocity| times the state jump."""
    g = cfg.gamma
    Ul, Fl = _flux_cons(ql, bn, g)
    Ur, Fr = _flux_cons(qr, bn, g)
    vmean = 0.5 * (ql[1] + qr[1])
    f7 = zero_flux * 0.5 * (Fl + Fr) - 0.5 * jnp.abs(vmean) * (Ur - Ul)
    return _expand8(f7)


def llf_dissipation(ql, qr, bn, cfg: MhdStatic):
    """0.5 * max(|vn|+cfast) * dU in the 7-row layout (for the 2D corner
    assembly; the 1D llf lives in mhd.riemann)."""
    g = cfg.gamma
    Ul, _ = _flux_cons(ql, bn, g)
    Ur, _ = _flux_cons(qr, bn, g)
    from ramses_tpu.mhd.riemann import _fast

    def speed(q):
        return jnp.abs(q[1]) + _fast(q[0], q[4], bn, q[6], q[7], g,
                                     cfg.smallc)
    a = jnp.maximum(speed(ql), speed(qr))
    return 0.5 * a * (Ur - Ul)


def upwind_dissipation(ql, qr, bn, cfg: MhdStatic):
    Ul, _ = _flux_cons(ql, bn, cfg.gamma)
    Ur, _ = _flux_cons(qr, bn, cfg.gamma)
    vmean = 0.5 * (ql[1] + qr[1])
    return 0.5 * jnp.abs(vmean) * (Ur - Ul)
