"""Special-relativistic hydro tests."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.rhd import core
from ramses_tpu.rhd.core import RhdStatic
from ramses_tpu.rhd.driver import RhdSimulation
from ramses_tpu.rhd.uniform import lorentz_refine_flags


@pytest.mark.parametrize("eos", ["ideal", "tm"])
def test_cons_prim_roundtrip(eos):
    cfg = RhdStatic(ndim=3, eos=eos, niter=60)
    rng = np.random.default_rng(0)
    n = 500
    rho = 10.0 ** rng.uniform(-3, 2, n)
    p = 10.0 ** rng.uniform(-4, 2, n)
    # velocities up to Γ ~ 7
    vmag = rng.uniform(0, 0.99, n)
    phi = rng.uniform(0, 2 * np.pi, n)
    mu = rng.uniform(-1, 1, n)
    st = np.sqrt(1 - mu ** 2)
    v = np.stack([vmag * st * np.cos(phi), vmag * st * np.sin(phi),
                  vmag * mu])
    q = jnp.asarray(np.concatenate([rho[None], v, p[None]]))
    u = core.prim_to_cons(q, cfg)
    q2 = core.cons_to_prim(u, cfg)
    assert np.allclose(np.asarray(q2[0]), rho, rtol=1e-8)
    assert np.allclose(np.asarray(q2[4]), p, rtol=1e-7)
    assert np.allclose(np.asarray(q2[1:4]), v, atol=1e-8)


def test_tm_eos_limits():
    """TM enthalpy: γ_eff→5/3 cold, →4/3 hot."""
    cfg = RhdStatic(eos="tm")
    cold = float(core.enthalpy(jnp.asarray(1.0), jnp.asarray(1e-6), cfg))
    assert np.isclose(cold, 1.0 + 2.5e-6, rtol=1e-3)
    hot = float(core.enthalpy(jnp.asarray(1.0), jnp.asarray(1e4), cfg))
    assert np.isclose(hot, 4e4, rtol=1e-3)
    # θ(h) inversion is exact
    th = 0.37
    h = 2.5 * th + np.sqrt(2.25 * th ** 2 + 1)
    assert np.isclose(float(core.theta_of_h(jnp.asarray(h))), th,
                      rtol=1e-12)


def test_wave_speeds_subluminal():
    cfg = RhdStatic(ndim=1)
    q = jnp.asarray([[1.0], [0.9], [0.0], [0.0], [10.0]])
    lm, lp = core.wave_speeds(q, 0, cfg)
    assert -1.0 < float(lm[0]) < float(lp[0]) < 1.0


def _tube_params(lmin=7, d=(10.0, 1.0), p=(13.33, 1e-2), gamma=5.0 / 3.0):
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmin, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1], "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [d[0], d[1]],
                        "p_region": [p[0], p[1]]},
        "hydro_params": {"gamma": gamma, "courant_factor": 0.5,
                         "slope_type": 1},
        "output_params": {"tend": 0.4},
    }
    return params_from_dict(groups, ndim=1)


def test_relativistic_blast_tube():
    """Mildly relativistic blast wave (Marti-Mueller problem 1 style):
    bounded velocities, intact end states, positive density/pressure,
    relativistic shell forms."""
    sim = RhdSimulation(_tube_params(), dtype=jnp.float64)
    sim.evolve(0.35)
    q = sim.prims()
    assert np.isclose(q[0][0], 10.0, atol=1e-6)
    assert np.isclose(q[0][-1], 1.0, atol=1e-6)
    assert q[0].min() > 0 and q[4].min() > 0
    v = q[1]
    assert np.abs(v).max() < 1.0
    # the shocked shell is relativistic: v_max ~ 0.7c for this setup
    assert 0.5 < v.max() < 0.95
    assert np.all(np.isfinite(q))


def test_nonrelativistic_limit_matches_hydro():
    """v << c: SRHD sod profile matches the Newtonian solver."""
    from ramses_tpu.driver import Simulation

    eps = 1e-4   # pressures scaled so v ~ sqrt(eps)
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 7, "levelmax": 7, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1], "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [eps, 0.1 * eps]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc", "slope_type": 1},
        "output_params": {"noutput": 1, "tout": [0.1 / np.sqrt(eps)],
                          "tend": 0.1 / np.sqrt(eps)},
    }
    ph = params_from_dict({k: dict(v) for k, v in groups.items()}, ndim=1)
    hsim = Simulation(ph, dtype=jnp.float64)
    hsim.evolve()
    rho_h = np.asarray(hsim.state.u)[0]

    pr = params_from_dict({k: dict(v) for k, v in groups.items()}, ndim=1)
    rsim = RhdSimulation(pr, dtype=jnp.float64)
    rsim.evolve(0.1 / np.sqrt(eps))
    rho_r = rsim.prims()[0]
    l1 = np.mean(np.abs(rho_h - rho_r))
    assert l1 < 5e-3, f"nonrel limit L1 {l1}"


def test_conservation_periodic_2d():
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "point"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "length_x": [10.0, 1.0], "length_y": [10.0, 1.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.0],
                        "p_region": [0.1, 1.0]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "output_params": {"tend": 0.1},
    }
    p = params_from_dict(groups, ndim=2)
    sim = RhdSimulation(p, dtype=jnp.float64)
    u0 = np.asarray(sim.u).copy()
    sim.evolve(0.1)
    u1 = np.asarray(sim.u)
    for row in (0, 1, 2, 4):      # D, S, τ conserved
        assert np.isclose(u1[row].sum(), u0[row].sum(), rtol=1e-11,
                          atol=1e-12)
    assert sim.nstep > 3


def test_lorentz_refine_flags():
    cfg = RhdStatic(ndim=1)
    q = np.zeros((5, 32))
    q[0] = 1.0
    q[4] = 1.0
    q[1, 16:] = 0.9           # jump in velocity → Γ jump
    u = core.prim_to_cons(jnp.asarray(q), cfg)
    fl = np.asarray(lorentz_refine_flags(u, cfg, err=0.1))
    assert fl[15] and fl[16]
    assert not fl[5] and not fl[28]


def test_uniform_rhd_snapshot_roundtrip(tmp_path):
    """Uniform SRHD dump + restart: the relativistic prim<->cons
    conversions round-trip through the reference-format snapshot and
    the restored run continues (``rhd`` shadow of ``output_hydro`` /
    ``init_hydro``)."""
    sim = RhdSimulation(_tube_params(), dtype=jnp.float64)
    sim.evolve(0.1)
    out = sim.dump(1, str(tmp_path))
    back = RhdSimulation.from_snapshot(_tube_params(), out,
                                       dtype=jnp.float64)
    assert back.t == pytest.approx(sim.t, rel=1e-12)
    assert back.nstep == sim.nstep
    np.testing.assert_allclose(np.asarray(back.u), np.asarray(sim.u),
                               rtol=1e-10, atol=1e-12)
    back.evolve(0.15)
    q = back.prims()
    assert np.all(np.isfinite(q)) and np.abs(q[1]).max() < 1.0
