"""Analytic (imposed) gravity fields — ``gravity_type > 0``.

Mirrors ``poisson/gravana.f90:5-95``: when an analytic model is selected,
the Poisson solve is bypassed entirely
(``poisson/multigrid_fine_commons.f90:46-48``) and the acceleration is a
fixed function of position:
  type 1: constant vector  ``gravity_params(1:ndim)``
  type 2: softened point mass — GM=params[0], softening=params[1],
          center=params[2:5]
  type 3: vertical galactic field (Kuijken & Gilmore 1989) —
          a1, a2, z0 = params[0:3] (already in code units here; the
          reference converts from kpc/Myr^2 internally)
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np


def cell_centers(shape: Sequence[int], dx: float, dtype=jnp.float64):
    """Cell-center coordinates [ndim, *spatial] in user units [0, boxlen]."""
    coords = [(np.arange(n) + 0.5) * dx for n in shape]
    mesh = np.meshgrid(*coords, indexing="ij")
    return jnp.asarray(np.stack(mesh), dtype=dtype)


def gravana(x, gravity_type: int, gravity_params: Sequence[float],
            boxlen: float):
    """Analytic acceleration at positions x [ndim, *spatial] (the
    installed patch's ``gravana`` hook replaces the stock models —
    the ``poisson/gravana.f90`` shadowing point)."""
    from ramses_tpu import patch
    hk = patch.hook("gravana")
    if hk is not None:
        return jnp.asarray(hk(x, gravity_type, gravity_params, boxlen))
    nd = x.shape[0]
    gp = list(gravity_params) + [0.0] * 10
    if gravity_type == 1:
        g = [jnp.full(x.shape[1:], gp[d], x.dtype) for d in range(nd)]
        return jnp.stack(g)
    if gravity_type == 2:
        gmass, emass = gp[0], gp[1]
        center = gp[2:2 + nd]
        rvec = [x[d] - center[d] for d in range(nd)]
        rr = jnp.sqrt(sum(r * r for r in rvec) + emass * emass)
        return jnp.stack([-gmass * r / rr ** 3 for r in rvec])
    if gravity_type == 3:
        a1, a2, z0 = gp[0], gp[1], gp[2]
        rz = x[nd - 1] - 0.5 * boxlen
        g = [jnp.zeros(x.shape[1:], x.dtype) for _ in range(nd)]
        g[nd - 1] = -a1 * rz / jnp.sqrt(rz * rz + z0 * z0) - a2 * rz
        return jnp.stack(g)
    raise ValueError(f"gravity_type={gravity_type}")
