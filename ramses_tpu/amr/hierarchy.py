"""AMR simulation driver: recursive subcycled level stepping.

The host-side recursion of ``amr_step`` (``amr/amr_step.f90:1-586``) with
the hydro-only operation order preserved:

    set_unew(l) → recurse(l+1) ×2 → godunov(l) [+ coarse corrections]
    → set_uold(l) → upload_fine(l)

Timestep policy: one CFL evaluation per coarse step,
``dt = min_l courant(l) · 2^(l-levelmin)``, then exact factor-2 subcycling
(the reference's per-level adaptive ``dtnew``/``dtold`` bookkeeping,
``amr/update_time.f90``, is replaced by this stricter-but-simpler global
choice — fine dts are exact halves, so the flux-correction weights of
``godfine1`` are exact).  Refinement runs at coarse-step boundaries
(the reference refines every level substep; coarse-step granularity is the
standard regrid-interval relaxation).
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from functools import lru_cache, partial
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr import flag as flagmod
from ramses_tpu.amr import kernels as K
from ramses_tpu.amr import maps as mapmod
from ramses_tpu.amr.tree import Octree, cell_offsets
from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.init import regions
from ramses_tpu.telemetry import make_telemetry, sim_run_info
from ramses_tpu.telemetry import screen as telemetry_screen
from ramses_tpu.utils.timers import NullTimers, Timers


class _Cfg1:
    """Minimal cfg shim for interp_cells on a single-column array."""

    def __init__(self, ndim: int):
        self.ndim = ndim


def _sample_dense_periodic(dense: np.ndarray, x01: np.ndarray) -> np.ndarray:
    """Periodic multilinear interpolation of a cell-centred dense field
    ``[nvar, n, n, …]`` at unit-box positions ``x01 [npts, ndim]`` —
    used to seed refined levels from base-resolution IC grids."""
    nvar = dense.shape[0]
    nd = x01.shape[1]
    n = dense.shape[1]
    g = x01 * n - 0.5
    i0 = np.floor(g).astype(np.int64)
    w1 = g - i0
    out = np.zeros((nvar, len(x01)))
    for corner in range(1 << nd):
        idx = []
        w = np.ones(len(x01))
        for d in range(nd):
            bit = (corner >> d) & 1
            idx.append(np.mod(i0[:, d] + bit, n))
            w = w * (w1[:, d] if bit else 1.0 - w1[:, d])
        out += dense[(slice(None),) + tuple(idx)] * w
    return out


class FusedSpec(NamedTuple):
    """Static description of one coarse step's level structure — the jit
    cache key for :func:`_fused_coarse_step` (hashable; re-derived per
    regrid, identical across steady-state steps)."""
    cfg: HydroStatic
    bspec: bmod.BoundarySpec
    lmin: int
    boxlen: float
    levels: tuple          # populated levels, ascending
    complete: tuple        # per-level bool
    gravity: bool
    itype: int
    # coarse root-cell counts per dim (nx, ny, nz); level-l dense
    # shape is root[d]·2^l (all-ones = the single-cube default)
    root: tuple = ()
    # static cooling config; None disables the in-step cooling source
    # (``cooling_fine`` after ``godunov_fine``, amr/amr_step.f90:448-474)
    cool: Optional[object] = None
    # per-level explicit comm schedule (SweepCommSpec or None); empty
    # tuple = global-view GSPMD everywhere (the default)
    comm: tuple = ()
    # capture per-cell face mass fluxes for the MC gas tracers
    # (godunov_fine.f90:685-715); hydro single-device path only
    want_flux: bool = False
    # per-level slab decomposition (parallel/dense_slab.SlabSpec or
    # None) for COMPLETE levels on a multi-chip mesh; empty tuple =
    # global-view dense sweep everywhere (the single-device default)
    slab: tuple = ()
    # per-level bool: partial level runs the gather-fused blocked tile
    # sweep (Morton-aligned oct tiles, amr/maps.build_block_maps)
    # instead of the 6^d stencil gather; empty tuple = never
    blocked: tuple = ()
    # octs per tile side = 2**block_shift for the blocked levels
    block_shift: int = 2
    # allow the Pallas tile kernel inside K.tile_sweep (single-device
    # meshes); multi-device row-sharded trees force the XLA tile
    # formulation so GSPMD can partition the sweep
    pallas_tiles: bool = True


def _advance_traced(u, dev, fg, dt, spec: FusedSpec, cool_tables=None):
    """One ENTIRE coarse step (recursive subcycled ``amr_step``) traced
    as straight-line XLA.

    The host recursion of the round-1 driver dispatched ~15 device calls
    per step; over a remote-tunnel TPU each call costs dispatch latency,
    which dominated the AMR profile.  Tracing the recursion turns a
    coarse step into ONE program; recompiles happen only when the
    bucketed level structure changes (the jit key is ``spec`` + shapes).
    """
    cfg = spec.cfg
    u = dict(u)
    unew = dict(u)
    levels = spec.levels
    # MC-tracer flux capture: per-level [ncell, ndim, 2] signed face
    # mass fluxes, accumulated over every substep of the coarse step
    phi = ({l: jnp.zeros((u[l].shape[0], cfg.ndim, 2), u[l].dtype)
            for l in levels} if spec.want_flux else None)

    def dx(l):
        return spec.boxlen / (1 << l)

    def shape(l):
        root = spec.root or (1,) * cfg.ndim
        return tuple(r << l for r in root[:cfg.ndim])

    def advance(i, dtl):
        from ramses_tpu.poisson.amr_solve import kick_flat

        l = levels[i]
        d = dev[l]
        if spec.gravity:
            u[l] = kick_flat(u[l], fg[l], 0.5 * dtl, cfg.ndim, cfg.smallr)
        unew[l] = u[l]
        if i + 1 < len(levels):
            advance(i + 1, 0.5 * dtl)
            advance(i + 1, 0.5 * dtl)
        if spec.complete[i]:
            sl = spec.slab[i] if spec.slab else None
            if sl is not None:
                # explicit slab-sharded formulation: shard-local bitperm
                # + backend-dispatched ring halos with DMA overlap
                # (parallel/dense_slab.py, dma_halo.py) — the GSPMD
                # partitioner never sees the bit-interleaved transpose,
                # so no involuntary full rematerialization
                from ramses_tpu.parallel import dense_slab
                out = dense_slab.dense_sweep_slab(
                    u[l], d.get("ok_flat"), dtl, dx(l), sl, cfg,
                    ret_flux=spec.want_flux)
            else:
                out = K.dense_sweep(u[l], d.get("inv_perm"),
                                    d.get("perm"), d["ok_dense"], dtl,
                                    dx(l), shape(l), spec.bspec, cfg,
                                    ret_flux=spec.want_flux)
            du = out[0] if spec.want_flux else out
            if spec.want_flux:
                phi[l] = phi[l] + out[1]
            corr = None
        elif spec.comm and spec.comm[i] is not None:
            # explicit per-shard schedule (shard_map + backend-
            # dispatched ring halos, deterministic owner-fold) —
            # parallel/amr_comm.py
            from ramses_tpu.parallel import amr_comm
            du, unew[l - 1] = amr_comm.sweep_correct_explicit(
                u[l], u[l - 1], unew[l - 1], d, dtl, dx(l), cfg,
                spec.comm[i])
            corr = None
        elif spec.blocked and spec.blocked[i]:
            # gather-fused blocked tile path: the compact Morton-tile
            # batch replaces the ~(3^d)x-duplicated stencil gather
            interp = K.interp_cells(u[l - 1], d["b_interp_cell"],
                                    d["b_interp_nb"], d["b_interp_sgn"],
                                    cfg, itype=spec.itype)
            out = K.tile_sweep(
                u[l], interp, d["tile_src"], d["tile_vsgn"], d["tile_ok"],
                d["cell_tile"], d["cell_slot"], d["oct_tile"],
                d["oct_slot"], dtl, dx(l), cfg, spec.block_shift,
                ret_flux=spec.want_flux, pallas_ok=spec.pallas_tiles)
            # pad cell rows index the kernels' appended zero column
            # (maps.py), so du/phi pad rows are exactly 0 — no masking
            du, corr = out[0], out[1]
            if spec.want_flux:
                phi[l] = phi[l] + out[2]
        else:
            interp = K.interp_cells(u[l - 1], d["interp_cell"],
                                    d["interp_nb"], d["interp_sgn"], cfg,
                                    itype=spec.itype)
            out = K.level_sweep(
                u[l], interp, d["stencil_src"], d["vsgn"], d["ok_ref"],
                None, dtl, dx(l), cfg, ret_flux=spec.want_flux)
            du, corr = out[0], out[1]
            if spec.want_flux:
                phi[l] = phi[l] + out[2]
        unew[l] = unew[l] + du
        if corr is not None and l > spec.lmin:
            unew[l - 1] = K.scatter_corrections(unew[l - 1], corr,
                                                d["corr_idx"], cfg)
            if spec.want_flux:
                phi[l - 1] = K.scatter_corr_flux(phi[l - 1], corr,
                                                 d["corr_idx"], cfg)
        u[l] = unew[l]
        if spec.gravity:
            u[l] = kick_flat(u[l], fg[l], 0.5 * dtl, cfg.ndim, cfg.smallr)
        if spec.cool is not None:
            # cooling_fine follows godunov_fine at every level substep
            # (amr/amr_step.f90:448-474); pointwise, so the flat cell
            # batch transposes straight into the dense-grid kernel.
            # cool_tables = (tables, [scale_T2, scale_nH, scale_t]) —
            # the scales ride as traced values so cosmological epochs
            # don't recompile the fused program
            from ramses_tpu.hydro.cooling import cooling_step
            tabs, scl = cool_tables
            u[l] = cooling_step(u[l].T, tabs, spec.cool, dtl, cfg,
                                scales=scl).T
        if i + 1 < len(levels):
            u[l] = K.restrict_upload(u[l], u[levels[i + 1]], d["ref_cell"],
                                     d["son_oct"], cfg)

    advance(0, dt)
    return (u, phi) if spec.want_flux else (u, None)


def _courant_traced(u, dev, spec: FusedSpec, fg=None):
    """All levels' CFL dts, [nlevel] coarse-step equivalents (already
    scaled by the exact factor-2 subcycle count).  ``fg`` enables the
    gravity-strength correction (one solve stale, like the reference's
    ``courant_fine`` reading the last force)."""
    cfg = spec.cfg
    dts = []
    for i, l in enumerate(spec.levels):
        dt_l = K.level_courant(u[l], dev[l]["valid_cell"],
                               spec.boxlen / (1 << l), cfg,
                               fg.get(l) if fg else None)
        dts.append(dt_l * (2.0 ** (l - spec.lmin)))
    return jnp.stack(dts)


@partial(jax.jit, static_argnames=("spec",), donate_argnums=(0,))
def _fused_coarse_step(u, dev, fg, dt, spec: FusedSpec, cool_tables=None):
    """One coarse step + the NEXT step's Courant dt, one dispatch.

    The state dict ``u`` is DONATED: the output state aliases the input
    buffers, so the dense base level exists once in HBM instead of
    twice.  Callers must rebind their reference to the returned state
    (``sim.u = out[0]``) — the argument arrays die with the call.

    Returning dt(u^{n+1}) from the same program is the reference's
    ``dtnew`` bookkeeping (``amr/update_time.f90``): the next coarse
    step starts without a host round-trip to evaluate CFL.

    With ``spec.want_flux`` the result carries a third element: the
    per-level MC-tracer flux capture dict.
    """
    u, phi = _advance_traced(u, dev, fg, dt, spec, cool_tables)
    dtn = jnp.min(_courant_traced(u, dev, spec,
                                  fg if spec.gravity else None))
    return (u, dtn, phi) if spec.want_flux else (u, dtn)


@partial(jax.jit, static_argnames=("spec",))
def _fused_courant(u, dev, spec: FusedSpec, fg=None):
    return _courant_traced(u, dev, spec, fg)


@partial(jax.jit, static_argnames=("ncell_pad", "cfg", "itype"))
def _migrate_level(old_u, u_coarse, rows_d, rows_s, cell_rep, nb_rep,
                   sgn_rep, rows_new, ncell_pad: int, cfg, itype: int):
    """Device-side regrid migration of one level: copy surviving cells
    from the old batch, interpolate brand-new octs from the (already
    migrated) coarser level (``make_grid_fine``,
    ``amr/refine_utils.f90:590``).  All index arrays are bucket-padded
    with out-of-range targets so jit shapes stay stable; the scatter
    drops them."""
    buf = jnp.zeros((ncell_pad, old_u.shape[1]), old_u.dtype)
    buf = buf.at[rows_d].set(old_u[rows_s], mode="drop")
    vals = K.interp_cells(u_coarse, cell_rep, nb_rep, sgn_rep, cfg,
                          itype=itype)
    return buf.at[rows_new].set(vals.astype(buf.dtype), mode="drop")


@lru_cache(maxsize=None)
def _mig_consts(ndim: int):
    """Per-ndim constant migrate tables (child offsets, ±1 prolongation
    signs, intra-oct arange) — built once instead of on every regrid."""
    offs = cell_offsets(ndim)
    return offs, (offs * 2 - 1).astype(np.float64), np.arange(1 << ndim)


@partial(jax.jit, static_argnames=("ttd",))
def _pack_flag_bits(flags, ttd: int):
    """Bitpack per-oct refinement flags ([n, 2^d] bool each) into one
    uint8 per oct, so the regrid flag fetch moves 2^d× fewer bytes over
    the (remote-tunnel) device link."""
    shifts = jnp.arange(ttd, dtype=jnp.uint32)
    return tuple((fl.astype(jnp.uint32) << shifts[None, :])
                 .sum(axis=1).astype(jnp.uint8) for fl in flags)


@partial(jax.jit, static_argnames=("spec", "eg", "fls", "itype"))
def _fused_flags(u, dev, spec: FusedSpec, eg, fls, itype: int):
    """Every level's gradient refinement criteria in ONE dispatch (the
    per-level ``hydro_refine`` kernels of ``flag_fine``); the host
    fetches the whole tuple with a single device round-trip."""
    cfg = spec.cfg
    root = spec.root or (1,) * cfg.ndim
    out = []
    for i, l in enumerate(spec.levels):
        d = dev[l]
        if spec.complete[i]:
            sl = spec.slab[i] if spec.slab else None
            if sl is not None:
                from functools import partial as _partial

                from ramses_tpu.parallel import dense_slab
                fn = _partial(K._flags_fn(cfg), err_grad=eg, floors=fls,
                              spatial0=0, cfg=cfg)
                fl = dense_slab.dense_flags_slab(u[l], sl, fn,
                                                 2 ** cfg.ndim)
            else:
                shp = tuple(r << l for r in root[:cfg.ndim])
                fl = K.dense_refine_flags(u[l], d.get("inv_perm"),
                                          d.get("perm"), eg,
                                          fls, shp,
                                          spec.bspec, cfg,
                                          dx=spec.boxlen / (1 << l))
        elif spec.blocked and spec.blocked[i]:
            # flags reuse the blocked shared gather (tile batch)
            if l == spec.lmin:
                interp = jnp.zeros((d["b_interp_cell"].shape[0],
                                    cfg.nvar), u[l].dtype)
            else:
                interp = K.interp_cells(u[l - 1], d["b_interp_cell"],
                                        d["b_interp_nb"],
                                        d["b_interp_sgn"],
                                        cfg, itype=itype)
            fl = K.tile_refine_flags(u[l], interp, d["tile_src"],
                                     d["tile_vsgn"], d["cell_tile"],
                                     d["cell_slot"], eg, fls, cfg,
                                     spec.block_shift)
        else:
            if l == spec.lmin:
                interp = jnp.zeros((d["interp_cell"].shape[0], cfg.nvar),
                                   u[l].dtype)
            else:
                interp = K.interp_cells(u[l - 1], d["interp_cell"],
                                        d["interp_nb"], d["interp_sgn"],
                                        cfg, itype=itype)
            fl = K.refine_flags(u[l], interp, d["stencil_src"], d["vsgn"],
                                eg, fls, cfg)
        out.append(fl)
    return tuple(out)


@partial(jax.jit, static_argnames=("spec", "nsteps", "trace"),
         donate_argnums=(0,))
def _fused_multi_step(u, dev, t, tend, dt0, spec: FusedSpec, nsteps: int,
                      cool_tables=None, trace: bool = False):
    """``nsteps`` hydro-only coarse steps as ONE device program
    (``lax.scan``), zero host round-trips between steps.

    ``u`` is donated (the scan carry aliases the input buffers — one
    copy of the dense base level in HBM); callers rebind to the
    returned state.

    Steps past ``tend`` become no-ops (the ``run_steps`` active-flag
    pattern).  Only valid while the tree is frozen — callers chunk by
    the regrid interval.  Returns (u, t, dt_next, n_done); with
    ``trace=True`` (telemetry-instrumented runs) the scan also stacks
    per-step ``(t_after, dt)`` so one summary fetch yields exact
    per-coarse-step records without leaving the fused fast path.
    """
    def body(carry, _):
        u, t, dtc, ndone = carry
        dt = jnp.minimum(dtc, jnp.maximum(tend - t, 0.0))
        active = t < tend
        # state dtype for the step (t/dt may carry f64 on x64 hosts)
        sdt = jnp.where(active, dt, 0.0).astype(u[spec.lmin].dtype)
        un, dtn = _fused_coarse_step(u, dev, {}, sdt, spec, cool_tables)
        u = {l: jnp.where(active, un[l], u[l]) for l in u}
        t = jnp.where(active, t + dt, t)
        dtc = jnp.where(active, dtn.astype(dtc.dtype), dtc)
        ndone = ndone + jnp.where(active, 1, 0)
        ys = (t, jnp.where(active, dt, 0.0)) if trace else None
        return (u, t, dtc, ndone), ys

    (u, t, dtc, ndone), hist = jax.lax.scan(
        body, (u, t, dt0, jnp.array(0)), None, length=nsteps)
    if trace:
        return u, t, dtc, ndone, hist
    return u, t, dtc, ndone


def restore_amr_scaffold(cls, params: Params, outdir: str, dtype,
                         to_cons, place_level):
    """Shared restart scaffold (the ``nrestart`` path) used by the
    hydro, MHD, and SRHD AMR sims: rebuild the octree from the file
    oct coords, construct the sim on it, place each level's restored
    rows (re-mapped defensively through the rebuilt tree's key order),
    then restrict.  ``to_cons(q_rows)`` converts file output columns
    to the solver's stored rows; ``place_level(sim, l, rows, og,
    order)`` writes them into the sim state.  Returns (sim, parts)."""
    from ramses_tpu.io.restart import restore_particles, restore_tree_state
    tree_og, rows_lv, meta, parts = restore_tree_state(
        outdir, None, params.amr.levelmin, to_cons=to_cons)
    root = [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim]
    tree = Octree(params.ndim, params.amr.levelmin, params.amr.levelmax,
                  root=root)
    for l, og in tree_og.items():
        tree.set_level(l, og)
    ps = None
    tracer_x = None
    tracer_id = None
    if parts:
        from ramses_tpu.pm.particles import (FAM_GAS_TRACER,
                                             lane_headroom)
        from ramses_tpu.pm.sinks import SinkSpec
        from ramses_tpu.pm.star_formation import SfSpec
        # gas tracers ride the part files as massless family-0 rows:
        # split them back out (they are host positions, not lanes)
        fam = parts.get("family")
        if fam is not None and (fam == FAM_GAS_TRACER).any():
            sel = fam == FAM_GAS_TRACER
            dims = "xyz"[:params.ndim]
            tracer_x = np.stack(
                [parts[f"position_{d}"][sel] for d in dims], axis=1)
            tracer_id = (parts["identity"][sel].astype(np.int64)
                         if "identity" in parts else None)
            npart = len(fam)
            parts = {k: (v[~sel] if isinstance(v, np.ndarray)
                         and len(v) == npart else v)
                     for k, v in parts.items()}
        # runs that keep creating particles need free lanes after the
        # restart too (the fresh-start path's npartmax headroom) — but
        # only for solver families whose __init__ keeps SF/sinks live
        grows = (cls._pm_family(cls._make_cfg(params))
                 and (SfSpec.from_params(params).enabled
                      or SinkSpec.from_params(params).enabled))
        if len(parts.get("mass", ())):
            ps = restore_particles(parts, params.ndim,
                                   nmax=lane_headroom(params, grows))
    # restarts never re-seed tracers: the restored population is the
    # truth, INCLUDING the empty one (e.g. every tracer escaped an
    # open box) — resurrecting a fresh population would fabricate
    # trajectories
    sim = cls(params, dtype=dtype, init_tree=tree, particles=ps,
              seed_tracers=False)
    if tracer_x is not None:
        sim.tracer_x = tracer_x
        sim.tracer_id = tracer_id
        sim._spec = None               # enable the MC flux capture
    elif bool(getattr(params.run, "tracer", False)) \
            and cls._tracer_physics:
        sim.tracer_x = np.zeros((0, params.ndim))
        sim.tracer_id = np.zeros(0, dtype=np.int64)
        sim._spec = None
    for l, rows in rows_lv.items():
        og = tree_og[l]
        pos = tree.lookup(l, og)
        place_level(sim, l, rows, og, np.argsort(pos))
    sim._restrict_all()
    sim._dt_cache = None
    sim.t = float(meta["t"])
    sim.nstep = int(meta["nstep"])
    if bool(params.run.lightcone) and sim.cosmo is not None:
        # seed the shell chain from the restored epoch so the first
        # post-restart coarse step emits its shell instead of silently
        # dropping it (the lazily-initialized prev-aexp would skip it)
        sim._cone_aexp_prev = sim.aexp_now()
    # the pending closing half-kick of the pre-dump step needs the old
    # coarse dt (KDK: the first post-restart kick is 0.5*(dtold + dt)),
    # and the stored dtnew makes the restart take the SAME next step a
    # continuous run would (its cached CFL dt included the gravity
    # term the fresh sim's empty force field cannot reproduce)
    lm = params.amr.levelmin
    dtold = np.atleast_1d(np.asarray(meta.get("dtold", 0.0)))
    if len(dtold) >= lm:
        sim.dt_old = float(dtold[lm - 1])
    dtnew = np.atleast_1d(np.asarray(meta.get("dtnew", 0.0)))
    if len(dtnew) >= lm and dtnew[lm - 1] > 0.0:
        sim._dt_cache = float(dtnew[lm - 1])
    if ps is not None:
        # new star ids must not collide with restored particles'
        sim._next_star_id = int(np.asarray(ps.idp).max()) + 1
    if sim.gravity:
        # prime the force field and the deposited-density maximum so
        # the first post-restart coarse_dt carries the same free-fall
        # cap (and the PCG the same warm start) a continuous run would
        if sim.pic:
            sim._build_pm()
        sim.solve_gravity()
    return sim, parts


def _place_u_rows(sim, l: int, rows: np.ndarray, og: np.ndarray,
                  order: np.ndarray):
    """Default row placement: cell-state array only (hydro/SRHD)."""
    nvar = sim.cfg.nvar
    ttd = 2 ** sim.cfg.ndim
    out = np.array(sim.u[l])
    out[sim.cell_rows(l)] = rows.reshape(
        len(og), ttd, nvar)[order].reshape(-1, nvar)
    sim.u[l] = jnp.asarray(out, dtype=sim.dtype)


class AmrSim:
    """Adaptive simulation: host octree + per-level device states.

    ``_needs_mig_log``: subclasses carrying extra per-cell state set
    this to retain the regrid migration maps (see ``regrid``).

    ``particles`` (a :class:`~ramses_tpu.pm.particles.ParticleSet`)
    enables the particle-mesh layer on the hierarchy: per-coarse-step
    host-built CIC maps (``pm/amr_pm.py``), deposits into every level's
    Poisson rhs, force gather at each particle's finest covering level,
    and a split-kick KDK matching the uniform stepper's order
    (``amr/amr_step.f90:219-236,268-273,479-486``).
    """

    _needs_mig_log = False
    ndev = 1          # device count of the row sharding (sharded subclass)
    # gather-fused blocked tile sweep on partial levels: the universal
    # default — hydro, MHD (XLA tile formulation), load-balance layouts
    # (tables layout-composed at emission time), and row-sharded meshes
    # all take it; only explicit-comm schedules keep the stencil path
    # (their per-shard owner-fold owns the gather).  Attr so a solver
    # family can still opt out wholesale.
    _oct_blocked = True
    # solver families whose state layout differs from the hydro
    # [rho, mom, E, ...] convention opt out of the shared SF/sink passes
    _pm_physics = True
    # families whose kernels handle non-cubic root grids (the MHD/SRHD
    # dense paths still assume one root cube and opt out)
    _noncubic_ok = True
    # velocity tracers only need momentum/density at the hydro column
    # positions — true for hydro AND MHD layouts; SRHD's (D, S) are
    # not coordinate velocities, so RhdAmrSim opts out
    _tracer_physics = True
    # out-of-core residency (amr/offload.py): families whose coarse
    # step is the shared fused hydro window may run it as per-level
    # segments with host-parked inactive levels; MHD drives its own
    # step chain (CT staggered fields) and opts out
    _offload_capable = True

    @staticmethod
    def _make_cfg(params: Params):
        """Static solver cfg — the physics of the hierarchy (subclass
        hook; ``RhdAmrSim`` swaps in :class:`rhd.core.RhdStatic`)."""
        return HydroStatic.from_params(params)

    @classmethod
    def _pm_family(cls, cfg) -> bool:
        """True when SF/sinks/cooling/movie are live for this
        solver family: the Newtonian hydro state layout only (MHD
        carries cell-B, SRHD stores (D,S,tau))."""
        return (getattr(cfg, "physics", "hydro") == "hydro"
                and cls._pm_physics)

    def __init__(self, params: Params, dtype=jnp.float32,
                 init_tree: Optional[Octree] = None,
                 particles=None, init_dense_u=None,
                 seed_tracers: bool = True):
        from ramses_tpu import patch
        patch.maybe_install_from_params(params)
        self.params = params
        self.cfg = self._make_cfg(params)
        self.dtype = dtype
        self.boxlen = float(params.amr.boxlen)
        spec = bmod.BoundarySpec.from_params(params)
        self.bspec = spec
        self.bc_kinds = [(f[0].kind, f[1].kind) for f in spec.faces]
        self.root = tuple(
            int(b) for b in
            [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim])
        if any(b != 1 for b in self.root):
            # non-cubic coarse grids run the hydro solver family only
            # for now: the PM/RT/physics layers still wrap positions at
            # a scalar boxlen, and the non-hydro state layouts have
            # their own dense paths
            blocked = []
            if getattr(self.cfg, "physics", "hydro") != "hydro" \
                    or not self._noncubic_ok:
                blocked.append(f"{type(self).__name__} solver family")
            for flagname in ("pic", "rt", "tracer", "cosmo",
                             "clumpfind", "mhd"):
                if bool(getattr(params.run, flagname, False)):
                    blocked.append(flagname)
            if (params.raw or {}).get("sf_params"):
                blocked.append("star formation")
            if (params.raw or {}).get("sink_params"):
                blocked.append("sinks")
            if blocked:
                raise NotImplementedError(
                    f"non-cubic coarse grid {self.root} currently "
                    f"supports the plain hydro hierarchy only (got: "
                    f"{', '.join(blocked)})")
        self.lmin = params.amr.levelmin
        self.lmax = params.amr.levelmax
        self.t = 0.0
        self.nstep = 0
        # regrid cadence: the reference re-flags every level substep but
        # amortizes the expensive rebuild (load_balance) every ``nremap``
        # coarse steps (amr/amr_step.f90:100-123); our regrid is the
        # rebuild, so nremap maps onto its interval (>=1).
        self.regrid_interval = max(1, int(getattr(params.run, "nremap", 0)))
        # telemetry recorder (&OUTPUT_PARAMS telemetry=): the shared
        # no-op NULL when off.  Timers follow the same contract — an
        # un-instrumented run makes zero label switches (instrumented
        # passes, e.g. bench.py, install a real Timers explicitly).
        self.telemetry = make_telemetry(params)
        self.timers = Timers() if self.telemetry.enabled else NullTimers()
        # in-run fault recovery (&RUN_PARAMS max_step_retries): None
        # when off — evolve then captures nothing and fetches nothing
        from ramses_tpu.resilience.faultinject import FaultInjector
        from ramses_tpu.resilience.stepguard import StepGuard
        self._sguard = StepGuard.from_params(params,
                                             telemetry=self.telemetry)
        self._fault = FaultInjector.from_params(params)
        # out-of-core residency engine (&AMR_PARAMS offload): None when
        # off — the monolithic fused window then runs bit-for-bit
        # untouched with zero added device fetches
        from ramses_tpu.amr.offload import OffloadEngine
        self._offload = OffloadEngine.from_params(params)
        from ramses_tpu.resilience.watchdog import Watchdog
        self._wd = Watchdog.from_params(params, telemetry=self.telemetry)
        self._guard_snap = None
        # cosmology: supercomoving conformal-time integration
        # (``amr/update_time.f90``; aexp/hexp from the Friedmann tables)
        self.cosmo = None
        if bool(params.run.cosmo):
            from ramses_tpu.pm.cosmology import Cosmology
            self.cosmo = Cosmology.from_params(params)
            self.t = float(self.cosmo.tau_ini)
            if bool(params.run.lightcone):
                # seed the lightcone shell chain at the run's start so
                # the FIRST coarse step emits its shell (restarts
                # re-seed from the restored epoch in
                # restore_amr_scaffold)
                self._cone_aexp_prev = self.cosmo.aexp_ini
        # dense base-grid gas ICs (grafic baryons) sampled per level
        self._init_dense = (np.asarray(init_dense_u)
                            if init_dense_u is not None else None)
        # cooling microphysics inside the fused step (&COOLING_PARAMS)
        self.cool_spec = None
        self.cool_tables = None
        self._cool_aexp = 1.0
        if getattr(params.cooling, "cooling", False) \
                and self._pm_family(self.cfg):
            from ramses_tpu.hydro.cooling import CoolingSpec, build_tables
            from ramses_tpu.units import units as units_fn
            cosmo0 = None
            if bool(params.run.cosmo):
                from ramses_tpu.pm.cosmology import Cosmology
                cosmo0 = Cosmology.from_params(params)
            aexp0 = cosmo0.aexp_ini if cosmo0 is not None else 1.0
            un = units_fn(params, cosmo=cosmo0, aexp=aexp0)
            self.cool_spec = CoolingSpec.from_params(params, un)
            c = params.cooling
            self._cool_aexp = aexp0
            self._cool_scales = jnp.asarray(
                [un.scale_T2, un.scale_nH, un.scale_t])
            self.cool_tables = build_tables(
                aexp=aexp0, J21=float(c.J21), a_spec=float(c.a_spec),
                z_reion=float(c.z_reion),
                haardt_madau=bool(c.haardt_madau))
        # self-gravity (per-level Poisson, SURVEY.md §3.3): periodic
        # boxes solve the zero-mean problem; any non-periodic face
        # switches the base solve to the isolated multipole-Dirichlet
        # path (poisson/isolated.py; boundary_potential.f90)
        self.gravity = bool(params.run.poisson)
        self.grav_periodic = all(k == 0 for pair in self.bc_kinds
                                 for k in pair)
        if self.gravity:
            if not self.grav_periodic and bool(params.run.cosmo):
                raise NotImplementedError(
                    "cosmology requires a periodic box")
            if any(k == 1 for pair in self.bc_kinds for k in pair):
                # mirror walls need image masses, which the isolated
                # multipole solve does not provide — refuse rather than
                # silently drop the image attraction
                raise NotImplementedError(
                    "self-gravity with reflecting walls is unsupported "
                    "(isolated solve covers outflow/inflow boxes)")
            self.fourpi = 4.0 * np.pi
        self.phi: Dict[int, jnp.ndarray] = {}
        self.fg: Dict[int, jnp.ndarray] = {}
        self.poisson_iters: Dict[int, jnp.ndarray] = {}
        self._rho_dev: Dict[int, jnp.ndarray] = {}
        # particle-mesh layer
        self.p = particles
        self.pic = bool(params.run.pic) and particles is not None
        # star formation / feedback / sinks / tracers on the hierarchy
        # (pm/amr_physics.py; coarse-step cadence like the reference's
        # per-level calls folded through the subcycle)
        from ramses_tpu.pm.particles import ParticleSet
        from ramses_tpu.pm.sinks import SinkSet, SinkSpec
        from ramses_tpu.pm.star_formation import SfSpec
        from ramses_tpu.units import units as units_fn
        self.sf_spec = SfSpec.from_params(params)
        self.sink_spec = SinkSpec.from_params(params)
        self.sinks = (SinkSet.empty(params.ndim)
                      if self.sink_spec.enabled else None)
        # stellar objects from sinks (&STELLAR_PARAMS,
        # pm/stellar_particle.f90 + sink_sn_feedback.f90)
        from ramses_tpu.pm.stellar import StellarSet, StellarSpec
        self.stellar_spec = StellarSpec.from_params(params)
        self.stellar = (StellarSet.empty(params.ndim)
                        if (self.stellar_spec.enabled
                            and self.sinks is not None) else None)
        self.tracer_x = None          # optional [ntr, ndim] host array
        self.tracer_id = None         # stable per-tracer ids [ntr]
        # &MOVIE_PARAMS on-the-fly frames (amr/movie.f90); the frame
        # field extraction uses Newtonian hydro relations, so non-hydro
        # state layouts (MHD cell-B, SRHD (D,S,τ)) refuse loudly rather
        # than render physically wrong maps
        self.movie, self.movie_imov = None, 0
        if self._pm_family(self.cfg):
            from ramses_tpu.io.movie import MovieWriter
            self.movie, self.movie_imov = MovieWriter.from_params(params)
        elif (params.raw or {}).get("movie_params", {}).get("movie"):
            import warnings
            warnings.warn("&MOVIE_PARAMS is only wired for the hydro "
                          "solver family; no frames will be written")
        self._sf_rng = np.random.default_rng(1234)
        self._tracer_rng = np.random.default_rng(20481)
        self._tracer_phi = None        # MC flux capture of the last step
        self._next_star_id = 1
        if not self._pm_family(self.cfg):
            self.sf_spec = SfSpec(enabled=False)
            self.sinks = None
        self.units = None
        if (self.sf_spec.enabled or self.sinks is not None
                or getattr(params.cooling, "cooling", False)):
            cosmo0 = None
            if bool(params.run.cosmo):
                from ramses_tpu.pm.cosmology import Cosmology
                cosmo0 = Cosmology.from_params(params)
            self.units = units_fn(
                params, cosmo=cosmo0,
                aexp=(cosmo0.aexp_ini if cosmo0 is not None else 1.0))
        if self.sf_spec.enabled and self.p is None:
            from ramses_tpu.pm.particles import lane_headroom
            self.p = ParticleSet.make(
                jnp.zeros((0, params.ndim)), jnp.zeros((0, params.ndim)),
                jnp.zeros((0,)), nmax=lane_headroom(params, True))
        if self.sf_spec.enabled:
            self.pic = True           # stars deposit/drift like DM
        self.dt_old = 0.0
        self._pm_dev: Dict[int, dict] = {}
        self._rho_max: Optional[float] = None
        # next-step CFL dt (device scalar) emitted by the previous fused
        # step; None whenever u changed outside step_coarse (regrid, ICs,
        # restart) and a fresh synchronous evaluation is needed
        self._dt_cache = None
        self._pad_hist: Dict[int, int] = {}
        # per-regrid migration maps, logged for subclasses that carry
        # extra per-cell state (the MHD staggered field); gated so the
        # plain hydro driver doesn't pin ncell-sized index buffers
        self._mig_log: Dict[int, tuple] = {}
        # cost-weighted Hilbert load balancing (parallel/balance.py):
        # per-level row layouts of partial levels (absent == identity,
        # the seed's tree-order rows).  ``_built_lay`` records the
        # (l-1, l, l+1) layout signatures each cached map was built
        # under so map reuse stays layout-aware.
        self.layouts: Dict[int, "object"] = {}
        self._built_lay: Dict[int, tuple] = {}
        self._rebalance_count = 0
        self.balance_stats = None
        self._force_rebalance = False

        if init_tree is not None:
            self.tree = init_tree
            self._rebuild_maps()
            self._alloc_from_ics()
        else:
            self._init_refine()

        # &RUN_PARAMS tracer: seed velocity tracers on the leaf cells
        # (``pm/tracer_utils.f90`` initial seeding): Poisson-sampled
        # per cell at mean ``tracer_per_cell`` (fractional thinning and
        # oversampling both work) and jittered inside the cell so
        # coincident tracers don't ride identical trajectories
        if bool(getattr(params.run, "tracer", False)) and seed_tracers:
            from ramses_tpu.pm.particles import TRACER_ID0
            if not self._tracer_physics:
                import warnings
                warnings.warn("tracer=.true. needs coordinate "
                              "velocities (hydro/MHD layouts); no "
                              "tracers seeded for this solver family")
            else:
                rng = np.random.default_rng(20480)
                tpc = float(params.run.tracer_per_cell)
                # mass-proportional seeding (``tracer_utils.f90`` init:
                # tracers sample the GAS MASS distribution, not the
                # leaf-cell count — a refined region must not be
                # over-weighted 2^d-fold per level)
                cen, mass, dxs = [], [], []
                for l in self.levels():
                    m = self.maps[l]
                    leaf = ~self.tree.refined_mask(l)
                    c = self.tree.cell_centers(l, self.boxlen)[leaf]
                    rho = np.asarray(
                        self.u[l])[:m.noct * 2 ** self.cfg.ndim, 0][leaf]
                    cen.append(c)
                    mass.append(rho * self.dx(l) ** self.cfg.ndim)
                    dxs.append(np.full(len(c), self.dx(l)))
                cen = np.concatenate(cen)
                mass = np.concatenate(mass)
                dxs = np.concatenate(dxs)
                lam = tpc * len(cen) * mass / max(mass.sum(), 1e-300)
                nper = rng.poisson(lam)
                rep = np.repeat(cen, nper, axis=0)
                jit = rng.uniform(-0.5, 0.5, rep.shape) \
                    * np.repeat(dxs, nper)[:, None]
                self.tracer_x = rep + jit if len(rep) else None
                # ids are assigned ONCE at seeding and ride through
                # dump/restore — cross-snapshot trajectory tracking by
                # id must survive star formation changing the live
                # particle population.  Base 2^30 keeps them clear of
                # the incremental star/DM id space.
                if self.tracer_x is not None:
                    self.tracer_id = (TRACER_ID0 + np.arange(
                        len(self.tracer_x), dtype=np.int64))
                    self._spec = None    # enable the MC flux capture

        # radiative transfer on the hierarchy (rt=.true.; gray or
        # multigroup/He via &RT_PARAMS rt_ngroups/rt_y_he,
        # rt/amr.py) — built after the tree/maps exist
        self.rt_amr = None
        if bool(params.run.rt):
            if self._pm_family(self.cfg):
                from ramses_tpu.rt.amr import RtAmrCoupled
                from ramses_tpu.units import units as units_fn
                un = self.units if self.units is not None else units_fn(
                    params, cosmo=self.cosmo,
                    aexp=(self.cosmo.aexp_ini if self.cosmo else 1.0))
                self.rt_amr = RtAmrCoupled(self, params, un)
                self._needs_mig_log = True  # rad/xion migrate on regrid
            else:
                import warnings
                warnings.warn("rt=.true. is only wired for the hydro "
                              "solver family on the AMR hierarchy; no "
                              "radiative transfer will run")

    # ------------------------------------------------------------------
    # setup
    # ------------------------------------------------------------------
    def dx(self, lvl: int) -> float:
        return self.boxlen / (1 << lvl)

    def _noct_pad(self, lvl: int, noct: int) -> Optional[int]:
        """Padded oct count with hysteresis: keep the previous bucket
        while the level still fits in it and fills >1/4 — the growing
        blast then changes jit shapes (→ recompiles) only on 4x growth,
        the ``ngridmax`` headroom idea of the reference's static
        allocation.  Subclasses align the result to the device mesh."""
        pad = mapmod.bucket(noct)
        prev = self._pad_hist.get(lvl)
        if prev is not None and pad <= prev and noct * 4 > prev:
            pad = prev
        self._pad_hist[lvl] = pad
        return pad

    def _place(self, arr, kind: str):
        """Placement hook: ``kind`` ∈ {octs, cells, rep} row semantics.
        Single-device base class keeps arrays as-is; the sharded subclass
        device_puts octs/cells-row arrays across the mesh."""
        return arr

    def _keys_same(self, other: Optional[Octree], l: int) -> bool:
        """True when level ``l`` has identical oct sets in self.tree and
        ``other`` (both absent counts as same)."""
        if other is None:
            return False
        ha, hb = self.tree.has(l), other.has(l)
        if ha != hb:
            return False
        if not ha:
            return True
        a, b = self.tree.levels[l].keys, other.levels[l].keys
        return len(a) == len(b) and np.array_equal(a, b)

    # ---------------------------------------------------------- layouts
    def oct_rows(self, l: int) -> np.ndarray:
        """Row slot of each tree oct of level ``l`` (identity when the
        level has no layout)."""
        lay = self.layouts.get(l)
        if lay is None:
            return np.arange(self.tree.noct(l), dtype=np.int64)
        return lay.oct_row

    def cell_rows(self, l: int) -> np.ndarray:
        """Flat row of each tree cell of level ``l`` in tree order."""
        ttd = 1 << self.tree.ndim
        return (self.oct_rows(l)[:, None] * ttd
                + np.arange(ttd, dtype=np.int64)).reshape(-1)

    def tree_order_cells(self, arr, l: int) -> np.ndarray:
        """Host copy of a cells-row array's REAL rows in tree order —
        under a layout real rows are scattered between pads, so
        ``[:ncell]`` slicing is only valid on identity levels."""
        a = np.asarray(arr)
        if l in self.layouts:
            return a[self.cell_rows(l)]
        ttd = 1 << self.tree.ndim
        return a[:self.tree.noct(l) * ttd]

    def _lay_triple(self, l: int) -> tuple:
        from ramses_tpu.parallel import balance
        return tuple(balance.layout_sig(self.layouts.get(j))
                     for j in (l - 1, l, l + 1))

    def request_rebalance(self):
        """Force a layout recompute at the next regrid regardless of the
        imbalance threshold."""
        self._force_rebalance = True

    def _maybe_rebalance(self, old_tree: Optional[Octree]):
        """Regrid-time balance pass: drop layouts stale against the new
        tree, measure imbalance under the surviving ones, and adopt
        cost-weighted Hilbert cuts when over threshold (the
        ``load_balance`` analog of the reference)."""
        from ramses_tpu.parallel import balance
        for l in list(self.layouts):
            if (not self.tree.has(l)
                    or not self._keys_same(old_tree, l)
                    or self.tree.noct(l) == int(
                        np.prod(self.tree.oct_dims(l)))):
                del self.layouts[l]
        if not balance.enabled(self):
            self.layouts = {}
            self.balance_stats = None
            self._force_rebalance = False
            return
        stats = balance.measure(self)
        thr = float(getattr(self.params.amr, "load_balance_threshold", 1.1))
        if stats.imbalance > thr or self._force_rebalance:
            cand = balance.compute_layouts(self)
            cstats = balance.measure(self, cand)
            # adopt only a meaningful improvement (or on request):
            # re-cutting for noise would churn jit inputs every regrid
            if self._force_rebalance or \
                    cstats.imbalance < stats.imbalance * 0.95:
                self.layouts = cand
                self._rebalance_count += 1
                stats = cstats
        self._force_rebalance = False
        self.balance_stats = stats

    def _block_level_ok(self, l: int) -> bool:
        """Gate: is a PARTIAL level eligible for the gather-fused blocked
        tile sweep?  Universal since the layouts/sharded/MHD lift: tiles
        are always built in tree/Morton order and composed with
        row-permutation layouts at table-emission time
        (``balance.apply_layout_blocks``), and row-sharded meshes run the
        XLA tile formulation GSPMD can partition
        (``FusedSpec.pallas_tiles``).  Documented carve-out: explicit
        comm schedules keep the 6^d stencil path —
        ``amr_comm.sweep_correct_explicit`` owns both the per-shard
        gather and the deterministic owner-fold, and ``_advance_traced``
        dispatches the comm branch before the blocked one."""
        if not self._oct_blocked:
            return False
        if not bool(getattr(self.params.amr, "oct_blocking", True)):
            return False
        if getattr(self, "_comm_specs", {}):
            return False
        return True

    def _rebuild_maps(self, old_tree: Optional[Octree] = None,
                      old_maps: Optional[dict] = None,
                      old_dev: Optional[dict] = None):
        """(Re)build per-level index maps, reusing cached maps for levels
        whose (l-1, l, l+1) oct sets are unchanged — the ``build_comm``
        amortization: steady-state steps do no host map construction."""
        from ramses_tpu.parallel import balance
        prev_maps = old_maps or {}
        prev_dev = old_dev or {}
        prev_blocks = getattr(self, "blocks", {})
        prev_lay = getattr(self, "_built_lay", {})
        self._spec = None
        self.maps: Dict[int, mapmod.LevelMaps] = {}
        self.dev: Dict[int, dict] = {}
        self.blocks: Dict[int, mapmod.BlockMaps] = {}
        self.block_stats = {"blocks_total": 0, "blocks_rebuilt": 0}
        self._built_lay = {}
        for l in range(self.lmin, self.lmax + 1):
            if not self.tree.has(l):
                break
            self._built_lay[l] = self._lay_triple(l)
            if (l in prev_maps
                    and self._keys_same(old_tree, l - 1)
                    and self._keys_same(old_tree, l)
                    and self._keys_same(old_tree, l + 1)
                    and prev_lay.get(l) == self._built_lay[l]):
                self.maps[l] = prev_maps[l]
                self.dev[l] = prev_dev[l]
                if l in prev_blocks:
                    # unchanged (l-1, l, l+1) oct sets: every per-block
                    # map is still valid — zero blocks rebuilt
                    self.blocks[l] = prev_blocks[l]
                    self.block_stats["blocks_total"] += \
                        prev_blocks[l].ntile
                continue
            if (l in prev_maps and prev_maps[l].complete
                    and self._keys_same(old_tree, l)):
                # COMPLETE level with unchanged oct set: the dense
                # permutation depends only on this level's keys — only
                # the restriction/ok_dense maps (which read l+1) need a
                # rebuild.  This skips the dominant host cost of the
                # regrid (the base level's 2^(3·lmin)-cell perm).
                m = mapmod.refresh_restriction(prev_maps[l], self.tree)
                lay_p1 = self.layouts.get(l + 1)
                if lay_p1 is not None:
                    m = balance.remap_son_oct(m, lay_p1)
                self.maps[l] = m
                self.dev[l] = dict(
                    prev_dev[l],
                    ok_dense=(self._place(jnp.asarray(m.ok_dense), "cells")
                              if m.ok_dense is not None else None),
                    ok_flat=(self._place(jnp.asarray(m.ok_flat), "cells")
                             if m.ok_flat is not None else None),
                    ref_cell=self._place(jnp.asarray(m.ref_cell), "rep"),
                    son_oct=self._place(jnp.asarray(m.son_oct), "rep"),
                )
                continue
            m = mapmod.build_level_maps(
                self.tree, l, self.bc_kinds,
                noct_pad=self._noct_pad(l, self.tree.noct(l)))
            lay_m1, lay_l, lay_p1 = (self.layouts.get(l - 1),
                                     self.layouts.get(l),
                                     self.layouts.get(l + 1))
            if lay_m1 is not None or lay_l is not None or lay_p1 is not None:
                m = balance.apply_layout_level(m, lay_m1, lay_l, lay_p1)
            self.maps[l] = m
            valid_cell = np.repeat(m.valid_oct, 2 ** self.tree.ndim)
            if m.complete:
                # dense path: restriction (+ refined mask) only.  The
                # flat↔dense permutation is a bit-permutation transpose
                # on cubic levels (amr/bitperm.py) — no device index
                # arrays needed; NON-cubic roots keep the index-gather
                # conversion and ship the perm maps.
                self.dev[l] = dict(
                    ok_dense=(self._place(jnp.asarray(m.ok_dense), "cells")
                              if m.ok_dense is not None else None),
                    ok_flat=(self._place(jnp.asarray(m.ok_flat), "cells")
                             if m.ok_flat is not None else None),
                    ref_cell=self._place(jnp.asarray(m.ref_cell), "rep"),
                    son_oct=self._place(jnp.asarray(m.son_oct), "rep"),
                    valid_cell=self._place(jnp.asarray(valid_cell),
                                           "cells"),
                )
                if not K.pow2_cube(self.tree.cell_dims(l)):
                    self.dev[l].update(
                        perm=self._place(jnp.asarray(m.perm), "cells"),
                        inv_perm=self._place(jnp.asarray(m.inv_perm),
                                             "cells"))
                continue
            self.dev[l] = dict(
                stencil_src=self._place(jnp.asarray(m.stencil_src), "octs"),
                vsgn=(self._place(jnp.asarray(m.vsgn), "octs")
                      if m.vsgn is not None else None),
                ok_ref=self._place(jnp.asarray(m.ok_ref), "octs"),
                interp_cell=self._place(jnp.asarray(m.interp_cell), "rep"),
                interp_nb=self._place(jnp.asarray(m.interp_nb), "rep"),
                interp_sgn=self._place(
                    jnp.asarray(m.interp_sgn, dtype=self.dtype), "rep"),
                corr_idx=self._place(jnp.asarray(m.corr_idx), "rep"),
                ref_cell=self._place(jnp.asarray(m.ref_cell), "rep"),
                son_oct=self._place(jnp.asarray(m.son_oct), "rep"),
                valid_cell=self._place(jnp.asarray(valid_cell), "cells"),
            )
            if self._block_level_ok(l):
                b = mapmod.build_block_maps(
                    self.tree, l, self.bc_kinds,
                    shift=int(getattr(self.params.amr,
                                      "oct_block_shift", 2)),
                    noct_pad=m.noct_pad, prev=prev_blocks.get(l))
                # cached/prev-reused in TREE order; layout-composed copy
                # (flat-row values and scatter rows permuted, tile
                # geometry untouched) is what ships to the device
                self.blocks[l] = b
                self.block_stats["blocks_total"] += b.ntile
                self.block_stats["blocks_rebuilt"] += b.blocks_rebuilt
                bt = (balance.apply_layout_blocks(b, lay_m1, lay_l)
                      if (lay_m1 is not None or lay_l is not None) else b)
                self.dev[l].update(
                    tile_src=self._place(jnp.asarray(bt.tile_src), "octs"),
                    tile_vsgn=(self._place(jnp.asarray(bt.tile_vsgn),
                                           "octs")
                               if bt.tile_vsgn is not None else None),
                    tile_ok=self._place(jnp.asarray(bt.tile_ok), "octs"),
                    cell_tile=self._place(jnp.asarray(bt.cell_tile),
                                          "cells"),
                    cell_slot=self._place(jnp.asarray(bt.cell_slot),
                                          "cells"),
                    oct_tile=self._place(jnp.asarray(bt.oct_tile), "octs"),
                    oct_slot=self._place(jnp.asarray(bt.oct_slot), "octs"),
                    b_interp_cell=self._place(
                        jnp.asarray(bt.interp_cell), "rep"),
                    b_interp_nb=self._place(jnp.asarray(bt.interp_nb),
                                            "rep"),
                    b_interp_sgn=self._place(
                        jnp.asarray(bt.interp_sgn, dtype=self.dtype),
                        "rep"),
                )
            if self.gravity:
                g = mapmod.build_gravity_maps(self.tree, l, self.bc_kinds,
                                              noct_pad=m.noct_pad)
                if lay_m1 is not None or lay_l is not None:
                    g = balance.apply_layout_gravity(g, lay_m1, lay_l)
                self.dev[l].update(
                    g_nb=self._place(jnp.asarray(g.nb), "cells"),
                    g_cell=self._place(jnp.asarray(g.g_cell), "rep"),
                    g_gnb=self._place(jnp.asarray(g.g_nb), "rep"),
                    g_sgn=self._place(jnp.asarray(g.g_sgn), "rep"),
                    g_octnb=self._place(jnp.asarray(g.oct_nb), "octs"),
                    g_valid=self._place(jnp.asarray(g.valid_cell),
                                        "cells"),
                    # masked-multigrid ladder: the depth-0 parent map
                    # is oct-row-sized (shards with the octs); deeper
                    # lattices are genuinely small and replicate
                    g_mg=tuple((self._place(jnp.asarray(nb_j), "rep"),
                                self._place(jnp.asarray(par_j),
                                            "octs" if j == 0 else "rep"))
                               for j, (nb_j, par_j, _n)
                               in enumerate(g.mg)))
        # coverage telemetry: fraction of partial-level octs swept via
        # the blocked tile path (1.0 when every partial level is blocked
        # or there is none to block)
        part = [l for l, lm in self.maps.items() if not lm.complete]
        tot = sum(self.tree.noct(l) for l in part)
        blk = sum(self.tree.noct(l) for l in part if l in self.blocks)
        self.block_stats["blocked_frac"] = (blk / tot) if tot else 1.0

    # ------------------------------------------------------------------
    # cosmology helpers (host interpolation of the Friedmann tables)
    # ------------------------------------------------------------------
    def aexp_now(self) -> float:
        if self.cosmo is None:
            return 1.0
        return float(np.interp(self.t, self.cosmo.tau_frw,
                               self.cosmo.axp_frw))

    def hexp_now(self) -> float:
        if self.cosmo is None:
            return 0.0
        return float(np.interp(self.t, self.cosmo.tau_frw,
                               self.cosmo.hexp_frw))

    def grav_coeff(self) -> float:
        """Poisson source coefficient: 4π, or the supercomoving
        ``1.5·Ωm·aexp`` (``poisson/multigrid_fine_commons.f90`` rhs)."""
        if self.cosmo is None:
            return self.fourpi
        return 1.5 * self.cosmo.omega_m * self.aexp_now()

    def _ic_state(self, lvl: int) -> jnp.ndarray:
        """Analytic conservative ICs on this level's (padded) cells, or
        periodic-trilinear samples of a dense IC grid (grafic baryons)."""
        m = self.maps[lvl]
        if self._init_dense is not None:
            centers = self.tree.cell_centers(lvl, self.boxlen)
            u = _sample_dense_periodic(
                self._init_dense, centers / self.boxlen)  # [nvar, ncell]
        else:
            centers = self.tree.cell_centers(lvl, self.boxlen)
            x = [centers[:, d] for d in range(self.cfg.ndim)]
            q = regions.region_condinit(x, self.dx(lvl), self.params,
                                        self.cfg)
            u = regions.prim_to_cons(q, self.cfg)      # [nvar, ncell]
        out = np.zeros((m.ncell_pad, self.cfg.nvar))
        out[:, 0] = self.cfg.smallr
        out[:, self.cfg.ndim + 1] = self.cfg.smalle * self.cfg.smallr
        out[self.cell_rows(lvl)] = u.T
        return self._place(jnp.asarray(out, dtype=self.dtype), "cells")

    def _alloc_from_ics(self):
        self.u: Dict[int, jnp.ndarray] = {}
        for l in self.levels():
            self.u[l] = self._ic_state(l)
        self._restrict_all()
        self._dt_cache = None

    def _init_refine(self):
        """Iterative initial mesh build (``amr/init_refine.f90:5-154``):
        apply analytic ICs, flag, rebuild, repeat until stable."""
        self.tree = Octree.base(self.tree_ndim, self.lmin,
                                self.lmax, root=self.root)
        self._rebuild_maps()
        self._alloc_from_ics()
        for _ in range(self.lmax - self.lmin + 2):
            newtree = self._flag_and_tree()
            same = True
            for l in range(self.lmin, self.lmax + 1):
                if newtree.has(l) != self.tree.has(l):
                    same = False
                elif newtree.has(l) and not np.array_equal(
                        newtree.levels[l].keys, self.tree.levels[l].keys):
                    same = False
            if same:
                break
            self.tree = newtree
            self._rebuild_maps()
            self._alloc_from_ics()

    @property
    def tree_ndim(self) -> int:
        return self.params.ndim

    def levels(self):
        return [l for l in range(self.lmin, self.lmax + 1)
                if self.tree.has(l)]

    # ------------------------------------------------------------------
    # refinement
    # ------------------------------------------------------------------
    def _criteria_flags(self, spec: FusedSpec):
        """Device tuple of per-level gradient criteria flags — the
        solver-specific half of ``flag_fine`` (subclass hook)."""
        r = self.params.refine
        eg = (float(r.err_grad_d), float(r.err_grad_u),
              float(r.err_grad_p))
        fls = (float(r.floor_d), float(r.floor_u), float(r.floor_p))
        return _fused_flags(self.u, self.dev, spec, eg, fls,
                            int(self.params.refine.interpol_type))

    def _flag_and_tree(self) -> Octree:
        r = self.params.refine
        spec = self._fused_spec()
        ttd = 2 ** self.tree_ndim
        # flags bitpacked on device (one uint8 per oct) so the single
        # flag fetch — the only device→host copy of a steady regrid —
        # moves 2^d× fewer bytes; unpacked to per-cell bools below
        if self._offload is not None and self._offload.engaged(self):
            # out-of-core: per-level flag segments so parked levels are
            # fetched one (plus interp source) at a time
            rr = self.params.refine
            eg = (float(rr.err_grad_d), float(rr.err_grad_u),
                  float(rr.err_grad_p))
            fls = (float(rr.floor_d), float(rr.floor_u),
                   float(rr.floor_p))
            flags = jax.device_get(self._offload.criteria_flags_packed(
                self, spec, eg, fls,
                int(self.params.refine.interpol_type), ttd))
        else:
            flags = jax.device_get(_pack_flag_bits(
                self._criteria_flags(spec), ttd))       # ONE trip
        crit: Dict[int, np.ndarray] = {}
        for fl, l in zip(flags, spec.levels):
            m = self.maps[l]
            fl = ((np.asarray(fl)[:, None] >> np.arange(ttd)) & 1) \
                .astype(bool)
            if l in self.layouts:      # rows → tree oct order first
                fl = fl[self.layouts[l].oct_row]
            else:
                fl = fl[:m.noct]
            fl = fl.reshape(-1)                        # flat-cell order
            i = l - 1                                  # 1-based level lists
            if i < len(r.r_refine) and r.r_refine[i] > 0.0:
                fl = fl | flagmod.geometry_flags(
                    self.tree.cell_centers(l, self.boxlen), l, self.params)
            if self.pic and i < len(r.m_refine) and r.m_refine[i] >= 0.0:
                # quasi-Lagrangian refinement (``flag_utils.f90``
                # m_refine): flag cells holding more than m_refine mean
                # particle masses.  Use the gravity solve's cached total
                # density when available; deposit on demand otherwise
                # (m_refine must not silently require poisson=.true.)
                rho_dev = self._rho_dev.get(l)
                if rho_dev is None or rho_dev.shape[0] < len(fl):
                    if not self._pm_dev:
                        self._build_pm()
                    if l in self._pm_dev:
                        rho_dev = (self.u[l][:, 0]
                                   + self._pm_rho(l).astype(
                                       self.u[l].dtype))
                if rho_dev is not None and rho_dev.shape[0] >= len(fl):
                    mp = float(jnp.sum(self.p.m * self.p.active)) \
                        / max(int(jnp.sum(self.p.active)), 1)
                    thr = r.m_refine[i] * mp \
                        / self.dx(l) ** self.tree_ndim
                    rho_np = self.tree_order_cells(rho_dev, l)[:len(fl)]
                    fl = fl | (rho_np > thr)
            crit[l] = fl
        with self.timers.section("regrid: tree build"):
            return flagmod.compute_new_tree(self.tree, crit, self.bc_kinds,
                                            self.params)

    def _bc_sig(self) -> tuple:
        """Hashable (lo, hi) bc-kind tuple per dim — jit static key."""
        return tuple(tuple(int(k) for k in f) for f in self.bc_kinds)

    def _device_regrid_ok(self) -> bool:
        """Gate for the jitted device-resident migrate
        (``amr/device_regrid.py``).  Families that replay migration into
        side-channel state (MHD face fields, RT) need the host prolong
        maps (``_mig_log``), and layout-permuted levels keep the host
        path (the row-remap tables are host objects) — both fall back to
        the bitwise-identical host reference, as does a key range too
        deep for the device integer width."""
        if not bool(getattr(self.params.amr, "device_regrid", True)):
            return False
        if self._needs_mig_log:
            return False
        from ramses_tpu.amr import device_regrid as dregrid
        return dregrid.keys_fit(self.tree_ndim, max(self.levels()),
                                self.root)

    def regrid(self):
        """Flag, rebuild the tree, and migrate device state
        (``flag_fine`` + ``refine_fine``/``kill_grid``,
        ``amr/refine_utils.f90:332,953``)."""
        if self.lmax == self.lmin:
            return
        with self.timers.section("regrid: flag"):
            newtree = self._flag_and_tree()
        old_u = self.u
        oldtree = self.tree
        old_maps, old_dev = self.maps, self.dev
        old_layouts = dict(self.layouts)
        self.tree = newtree
        with self.timers.section("regrid: balance"):
            self._maybe_rebalance(oldtree)
        from ramses_tpu.parallel import balance
        lay_range = range(self.lmin, self.lmax + 2)
        unchanged = (all(self._keys_same(oldtree, l) for l in lay_range)
                     and balance.layouts_same(old_layouts, self.layouts,
                                              lay_range))
        if unchanged:
            self.tree = oldtree
            if getattr(self, "blocks", None):
                # steady-state regrid: tree untouched, every per-block
                # map stays live — zero blocks rebuilt
                self.block_stats = {
                    "blocks_total": sum(b.ntile
                                        for b in self.blocks.values()),
                    "blocks_rebuilt": 0,
                    "blocked_frac": self.block_stats.get(
                        "blocked_frac", 1.0)}
            return
        with self.timers.section("regrid: maps"):
            self._rebuild_maps(oldtree, old_maps, old_dev)
        self.timers.timer("regrid: migrate")
        twotondim = 2 ** self.cfg.ndim
        offs, sgn_tab, oct_ar = _mig_consts(self.cfg.ndim)
        self._mig_log = {}
        dregrid = None
        if self._device_regrid_ok():
            from ramses_tpu.amr import device_regrid as dregrid
        dev_keys: Dict[tuple, jnp.ndarray] = {}

        def _keys_dev(tree_, l_, pad_):
            kk = (id(tree_), l_, pad_)
            if kk not in dev_keys:
                kn = (tree_.levels[l_].keys if tree_.has(l_)
                      else np.zeros(0, np.int64))
                dev_keys[kk] = dregrid.upload_keys(kn, pad_)
            return dev_keys[kk]

        new_u: Dict[int, jnp.ndarray] = {}
        from ramses_tpu.amr import offload as offmod

        def _coarse_dev(l_):
            # a parked (HostBuffer) coarse level must be device-resident
            # to serve as the prolongation source; fetch once and write
            # the device copy back so every finer level reuses it
            if offmod.is_parked(new_u[l_]):
                new_u[l_] = offmod.as_device(new_u[l_])
            return new_u[l_]

        for l in self.levels():
            m = self.maps[l]
            lay_new = self.layouts.get(l)
            lay_old = old_layouts.get(l)
            lay_m1 = self.layouts.get(l - 1)
            same_lay = (balance.layout_sig(lay_new)
                        == balance.layout_sig(lay_old))
            if (l == self.lmin or self._keys_same(oldtree, l)) \
                    and same_lay and old_u[l].shape[0] == m.ncell_pad:
                # identical oct set and identical padded layout: reuse
                new_u[l] = old_u[l]
                continue
            if dregrid is not None and lay_new is None \
                    and lay_old is None and lay_m1 is None:
                # device-resident migrate: survivor copy + new-oct
                # prolongation maps derived on device from the sorted
                # level key arrays (amr/device_regrid.py) — no per-level
                # host table construction, bitwise-identical to the
                # host reference path below
                old = offmod.as_device(old_u.get(l))
                if old is None:
                    old = jnp.zeros((1, new_u[l - 1].shape[1]),
                                    self.dtype)
                onoct = oldtree.noct(l) if oldtree.has(l) else 0
                new_u[l] = self._place(dregrid.migrate_level(
                    old, _coarse_dev(l - 1),
                    _keys_dev(self.tree, l, m.noct_pad),
                    _keys_dev(oldtree, l,
                              mapmod.bucket(max(onoct, 1), 8)),
                    _keys_dev(self.tree, l - 1,
                              self.maps[l - 1].noct_pad),
                    m.ncell_pad, self.cfg.ndim, self._bc_sig(),
                    tuple(int(n) for n in self.tree.cell_dims(l - 1)),
                    self.cfg,
                    int(self.params.refine.interpol_type)), "cells")
                continue
            cd, cs, new_octs, f_cell, nb = mapmod.build_prolong_maps(
                self.tree, oldtree, l, self.bc_kinds)
            # convert tree-order oct/cell indices to row slots: dst via
            # the NEW layouts, src via the OLD ones (both identity when
            # absent); f_cell/nb point at l-1 cells already migrated to
            # the new layout
            if lay_new is not None:
                cd_r = lay_new.oct_row[cd]
                new_r = lay_new.oct_row[new_octs] if len(new_octs) \
                    else new_octs
            else:
                cd_r, new_r = cd, new_octs
            cs_r = lay_old.oct_row[cs] if lay_old is not None else cs
            if lay_m1 is not None:
                f_cell = balance.remap_cells(f_cell, lay_m1, twotondim)
                nb = balance.remap_cells(nb, lay_m1, twotondim)
            # Device-side migration with bucket-padded index maps: no
            # whole-level host round-trips, and jit shapes only change
            # when a bucket boundary is crossed.
            ncopy = len(cd) * twotondim
            nnew = len(new_octs) * twotondim
            cpad = mapmod.bucket(max(ncopy, 1), 1024)
            npad = mapmod.bucket(max(nnew, 1), 1024)
            rows_d = np.full(cpad, m.ncell_pad, dtype=np.int64)   # drop
            rows_s = np.zeros(cpad, dtype=np.int64)
            if ncopy:
                rows_d[:ncopy] = (cd_r[:, None] * twotondim
                                  + oct_ar).reshape(-1)
                rows_s[:ncopy] = (cs_r[:, None] * twotondim
                                  + oct_ar).reshape(-1)
            cell_rep = np.zeros(npad, dtype=np.int64)
            nb_rep = np.zeros((npad, self.cfg.ndim, 2), dtype=np.int64)
            sgn_rep = np.ones((npad, self.cfg.ndim))
            rows_new = np.full(npad, m.ncell_pad, dtype=np.int64)  # drop
            if nnew:
                cell_rep[:nnew] = np.repeat(f_cell, twotondim)
                nb_rep[:nnew] = np.repeat(nb, twotondim, axis=0)
                sgn_rep[:nnew] = np.tile(sgn_tab, (len(new_octs), 1))
                rows_new[:nnew] = (new_r[:, None] * twotondim
                                   + oct_ar).reshape(-1)
            old = offmod.as_device(old_u.get(l))
            if old is None:
                old = jnp.zeros((1, new_u[l - 1].shape[1]), self.dtype)
            rows_d = jnp.asarray(rows_d)
            rows_s = jnp.asarray(rows_s)
            cell_rep = jnp.asarray(cell_rep)
            sgn_dev = jnp.asarray(sgn_rep, dtype=self.dtype)
            rows_new = jnp.asarray(rows_new)
            if self._needs_mig_log:
                self._mig_log[l] = (rows_d, rows_s, cell_rep, sgn_dev,
                                    rows_new, m.ncell_pad, new_octs,
                                    f_cell, jnp.asarray(nb_rep))
            new_u[l] = self._place(_migrate_level(
                old, _coarse_dev(l - 1), rows_d, rows_s, cell_rep,
                jnp.asarray(nb_rep), sgn_dev, rows_new, m.ncell_pad,
                self.cfg,
                int(self.params.refine.interpol_type)), "cells")
        self.u = new_u
        if getattr(self, "rt_amr", None) is not None:
            self.rt_amr.apply_migration(self)
        # prune stale gravity state: a level whose bucketed size changed,
        # vanished, or moved to a different row layout must not seed the
        # next solve's warm start
        for l in list(self.phi):
            if (l not in self.maps
                    or self.phi[l].shape[0] != self.maps[l].ncell_pad
                    or not balance.layouts_same(old_layouts, self.layouts,
                                                (l,))):
                self.phi.pop(l, None)
                self.fg.pop(l, None)
                self.poisson_iters.pop(l, None)
                self._rho_dev.pop(l, None)
        self.timers.stop()
        with self.timers.section("regrid: upload"):
            self._restrict_all()
        self._dt_cache = None          # u changed: stale CFL dt

    def _restrict_all(self):
        """Restriction sweep fine→coarse so non-leaf cells hold son means."""
        if self._offload is not None and self._offload.engaged(self):
            # out-of-core: sweep with at most two levels resident,
            # re-parking each fine source as soon as it is consumed
            self._offload.restrict_all_segmented(self, self._fused_spec())
            return
        for l in sorted(self.levels(), reverse=True):
            if self.tree.has(l + 1):
                d = self.dev[l]
                self.u[l] = K.restrict_upload(self.u[l], self.u[l + 1],
                                              d["ref_cell"], d["son_oct"],
                                              self.cfg)

    # ------------------------------------------------------------------
    # time stepping
    # ------------------------------------------------------------------
    def _interp_for(self, l: int) -> jnp.ndarray:
        d = self.dev[l]
        if l == self.lmin:
            return jnp.zeros((self.maps[l].ni_pad, self.cfg.nvar),
                             self.dtype)
        return K.interp_cells(self.u[l - 1], d["interp_cell"],
                              d["interp_nb"], d["interp_sgn"], self.cfg,
                              itype=int(self.params.refine.interpol_type))

    def _fused_spec(self) -> FusedSpec:
        if self._spec is None:
            lv = tuple(self.levels())
            cspecs = getattr(self, "_comm_specs", {})
            self._spec = FusedSpec(
                cfg=self.cfg, bspec=self.bspec, lmin=self.lmin,
                boxlen=self.boxlen, levels=lv,
                complete=tuple(self.maps[l].complete for l in lv),
                gravity=self.gravity,
                itype=int(self.params.refine.interpol_type),
                root=self.root, cool=self.cool_spec,
                comm=(tuple(cspecs.get(l) for l in lv) if cspecs
                      else ()),
                want_flux=(self.tracer_x is not None
                           and len(self.tracer_x) > 0
                           and getattr(self.cfg, "physics",
                                       "hydro") == "hydro"
                           and not cspecs))
            slab = tuple(self._slab_spec(l) if self.maps[l].complete
                         else None for l in lv)
            if any(s is not None for s in slab):
                self._spec = self._spec._replace(slab=slab)
            blocked = tuple(l in self.blocks for l in lv)
            if any(blocked):
                self._spec = self._spec._replace(
                    blocked=blocked,
                    block_shift=int(getattr(self.params.amr,
                                            "oct_block_shift", 2)),
                    pallas_tiles=(int(getattr(self, "ndev", 1)) == 1))
        return self._spec

    def _slab_spec(self, l: int):
        """SlabSpec for a complete level's explicit slab-sharded dense
        path, or None for the global-view sweep.  The single-device sim
        has no mesh — :class:`ramses_tpu.parallel.amr_sharded.
        ShardedAmrSim` overrides this with the real gate."""
        return None

    def _cool_bundle(self):
        """(tables, traced [scale_T2, scale_nH, scale_t]) for the fused
        step, or None when cooling is off."""
        if self.cool_tables is None:
            return None
        return (self.cool_tables, self._cool_scales)

    def coarse_dt(self) -> float:
        with self.timers.section("courant"):
            if self._dt_cache is not None:
                # emitted by the previous fused step (dtnew bookkeeping):
                # u is unchanged since, so this IS the current CFL dt
                dts = [float(self._dt_cache)]
            elif self._offload is not None and self._offload.engaged(self):
                # out-of-core: per-level Courant segments so parked
                # levels are fetched one at a time (same stack-then-min
                # reduction order — bitwise equal to the fused program)
                dts = [self._offload.coarse_dt_min(self,
                                                   self._fused_spec())]
            else:
                dts = [float(jnp.min(_fused_courant(
                    self.u, self.dev, self._fused_spec(),
                    self.fg if (self.gravity and self.fg) else None)))]
            dts.extend(self._aux_dts())
            return min(dts)

    def _aux_dts(self) -> list:
        """Non-solver dt caps shared by every solver family: particle
        Courant + lagged free-fall, cosmological expansion."""
        dts = []
        if self.pic:
            from ramses_tpu.pm import particles as pmod
            cf = float(self.cfg.courant_factor)
            # particle Courant: a level-l particle moves cf*dx(l) per
            # level substep, i.e. cf*dx(lmin) per coarse step
            # (pm/newdt_fine.f90:186-233 folded through the exact
            # factor-2 subcycling)
            dts.append(float(pmod.particle_dt(
                self.p, self.dx(self.lmin), cf)))
            if self.gravity and self._rho_max:
                # free-fall cap from the previous step's deposited
                # density (one step lagged; pm/newdt_fine.f90:51-60)
                dts.append(float(pmod.freefall_dt(
                    jnp.asarray(self._rho_max), cf,
                    self.grav_coeff())))
        if self.cosmo is not None:
            # expansion cap (amr/update_time.f90 cosmo branch)
            dts.append(0.1 / abs(self.hexp_now()))
        return dts

    # ------------------------------------------------------------------
    # particle-mesh on the hierarchy (pm/amr_pm.py)
    # ------------------------------------------------------------------
    def _build_pm(self):
        """Host CIC metadata pass, once per coarse step
        (``make_tree_fine`` + the index part of ``cic_amr``)."""
        from ramses_tpu.pm import amr_pm
        x_host = np.asarray(self.p.x, dtype=np.float64)
        ncp = {l: self.maps[l].ncell_pad for l in self.levels()}
        from ramses_tpu.pm.coupling import deposit_scheme_from_params
        pm_maps = amr_pm.build_pm_maps(
            self.tree, x_host, self.boxlen, self.bc_kinds, ncp,
            scheme=deposit_scheme_from_params(self.params))
        if self.layouts:
            from ramses_tpu.parallel import balance
            ttd = 1 << self.tree.ndim
            for l, mp in pm_maps.items():
                lay = self.layouts.get(l)
                if lay is not None:   # ncell_pad drop-sentinel unchanged
                    mp.idx = balance.remap_cells(mp.idx, lay, ttd)
        wdtype = self.dtype if self.p.x.dtype != jnp.float64 \
            else jnp.float64
        self._pm_dev = {
            l: dict(idx=self._place(jnp.asarray(mp.idx), "rep"),
                    w=self._place(jnp.asarray(mp.w, dtype=wdtype), "rep"),
                    mask=self._place(jnp.asarray(mp.assigned), "rep"))
            for l, mp in pm_maps.items()}

    def _pm_rho(self, l: int):
        """Particle density on level ``l``'s flat cells (``rho_fine``)."""
        from ramses_tpu.pm import amr_pm
        pd = self._pm_dev[l]
        return amr_pm.deposit_flat(
            pd["idx"], pd["w"], self.p.m.astype(pd["w"].dtype),
            self.p.active, self.maps[l].ncell_pad,
            self.dx(l) ** self.cfg.ndim)

    def _pm_force(self):
        """Force at particle positions, gathered at each particle's
        finest covering level (``move1``, ``pm/move_fine.f90:193``)."""
        from ramses_tpu.pm import amr_pm
        f = None
        for l in self.levels():
            pd = self._pm_dev[l]
            fl = amr_pm.gather_flat(self.fg[l].astype(pd["w"].dtype),
                                    pd["idx"], pd["w"], pd["mask"])
            f = fl if f is None else f + fl
        return f

    def solve_gravity(self):
        """Per-level Poisson solve, coarse→fine one-way interface
        (``multigrid_fine``): exact periodic FFT on any COMPLETE level
        (the base always; fully-refined levels above too),
        Dirichlet-ghost CG on partial levels; then the gradient force."""
        from ramses_tpu.poisson import amr_solve as gs
        from ramses_tpu.poisson.solver import fft_solve

        nd = self.cfg.ndim
        coeff = self.grav_coeff()
        if self.grav_periodic:
            # mean density over leaves + particles (periodic solvability)
            mtot = float(self.totals()[0])
            if self.pic:
                mtot += float(jnp.sum(self.p.m * self.p.active))
            vol_box = self.boxlen ** nd
            for r in self.root:
                vol_box *= r
            rho_mean = mtot / vol_box
        else:
            rho_mean = 0.0       # isolated problem is well-posed as-is
        rho_max = None
        for l in self.levels():
            m = self.maps[l]
            d = self.dev[l]
            dx = self.dx(l)
            rho = self.u[l][:, 0]
            if self.pic:
                rho = rho + self._pm_rho(l).astype(rho.dtype)
                self._rho_dev[l] = rho     # m_refine criterion input
                mx = jnp.max(rho)
                rho_max = mx if rho_max is None else jnp.maximum(rho_max,
                                                                 mx)
            rhs = coeff * (rho - rho_mean)
            if m.complete:
                # whole-box level: exact periodic FFT solve on the dense
                # grid (or the isolated multipole-Dirichlet CG when the
                # box is open), force by central differences
                ncell = m.noct * (1 << nd)
                shp = self.tree.cell_dims(l)
                dense = K.rows_to_dense(rhs, d.get("inv_perm"), shp)
                if self.grav_periodic:
                    phi_dense = fft_solve(dense, dx)
                    fg_rows = K.dense_to_rows(
                        gs.grad_dense(phi_dense,
                                      jnp.asarray(dx, rhs.dtype), nd),
                        d.get("perm"), shp)
                else:
                    from ramses_tpu.poisson.isolated import (
                        grad_isolated, isolated_solve)
                    # dense already includes coeff: pass rho = dense/coeff
                    phi_dense, gh = isolated_solve(
                        dense / coeff, dx, jnp.asarray(coeff, rhs.dtype),
                        iters=300, tol=float(self.params.poisson.epsilon))
                    fg_rows = K.dense_to_rows(jnp.moveaxis(
                        grad_isolated(phi_dense, gh, dx), 0, -1),
                        d.get("perm"), shp)
                phi = jnp.zeros((m.ncell_pad,), rhs.dtype)
                phi = phi.at[:ncell].set(
                    K.dense_to_rows(phi_dense, d.get("perm"), shp))
                if m.ncell_pad > ncell:
                    fg_rows = jnp.zeros(
                        (m.ncell_pad, nd), fg_rows.dtype
                    ).at[:ncell].set(fg_rows)
                self.phi[l] = phi
                self.fg[l] = fg_rows.astype(self.dtype)
                continue
            else:
                ghosts = K.interp_cells(
                    self.phi[l - 1][:, None], d["g_cell"], d["g_gnb"],
                    d["g_sgn"].astype(self.phi[l - 1].dtype),
                    _Cfg1(nd), itype=1)[:, 0]
                phi, nit = gs.pcg_level(
                    rhs, ghosts, d["g_nb"], d["g_octnb"],
                    jnp.asarray(dx, rhs.dtype), d["g_valid"], nd,
                    tol=float(self.params.poisson.epsilon), iters=200,
                    phi0=self.phi.get(l), mg=d.get("g_mg", ()))
                self.poisson_iters[l] = nit
            self.phi[l] = phi
            self.fg[l] = gs.grad_phi(phi, ghosts, d["g_nb"],
                                     jnp.asarray(dx, phi.dtype),
                                     d["g_valid"], nd).astype(self.dtype)
        if self.pic and rho_max is not None:
            self._rho_max = float(rho_max)   # one host sync per solve

    def _grav_pm_pre(self, dt: float):
        """Pre-sweep gravity/PM sequence shared by the solver families:
        rebuild particle maps, solve the per-level Poisson problem, and
        complete the previous half-kick + this step's opening half-kick
        with the new force at x^n (``synchro_fine``)."""
        from ramses_tpu.pm import particles as pmod
        if self.pic:
            with self.timers.section("particles: maps"):
                self._build_pm()
        if self.gravity:
            with self.timers.section("poisson"):
                self.solve_gravity()
        if self.pic and self.gravity:
            with self.timers.section("particles: kick"):
                f_at_p = self._pm_force()
                self.p = pmod.kick(self.p, f_at_p,
                                   0.5 * (self.dt_old + dt))

    def _pm_drift(self, dt: float):
        """``move_fine``: drift with the coarse dt (fine levels would
        split it into exact halves with the same frozen force)."""
        from ramses_tpu.pm import particles as pmod
        if self.pic:
            with self.timers.section("particles: drift"):
                self.p = pmod.drift(self.p, dt, self.boxlen,
                                    periodic=self.grav_periodic)

    def step_coarse(self, dt: float):
        if self.cosmo is not None and (self.cool_tables is not None
                                       or self.units is not None):
            # supercomoving unit scales are aexp-dependent
            # (``amr/units.f90``): refresh the host Units (SF/sinks) and
            # the traced cooling scales EVERY coarse step, and
            # re-tabulate the UV/cooling tables at 2% aexp granularity
            # (``set_table(aexp)`` per coarse step)
            from ramses_tpu.units import units as units_fn
            a = self.aexp_now()
            un = units_fn(self.params, cosmo=self.cosmo, aexp=a)
            if self.units is not None:
                self.units = un
            if self.cool_tables is not None:
                self._cool_scales = jnp.asarray(
                    [un.scale_T2, un.scale_nH, un.scale_t])
                if abs(a - self._cool_aexp) > 0.02 * self._cool_aexp:
                    from ramses_tpu.hydro.cooling import build_tables
                    c = self.params.cooling
                    self.cool_tables = build_tables(
                        aexp=a, J21=float(c.J21), a_spec=float(c.a_spec),
                        z_reion=float(c.z_reion),
                        haardt_madau=bool(c.haardt_madau))
                    self._cool_aexp = a
        self._grav_pm_pre(float(dt))
        spec = self._fused_spec()
        if spec.want_flux:
            # density BEFORE the step: the tracer jump probability
            # denominator (move_tracer.f90 uses the pre-step cell mass)
            self._tracer_rho0 = {l: self.u[l][:, 0] for l in self.levels()}
        with self.timers.section("hydro - godunov"):
            if self._offload is not None and self._offload.engaged(self):
                # out-of-core: the same step as per-level segments with
                # host-park/prefetch swap points (amr/offload.py) —
                # bitwise identical to the monolithic window
                self.u, self._dt_cache = self._offload.run_step(
                    self, float(dt), spec)
            else:
                out = _fused_coarse_step(
                    self.u, self.dev, self.fg if self.gravity else {},
                    jnp.asarray(float(dt), self.dtype), spec,
                    self._cool_bundle())
                if spec.want_flux:
                    self.u, self._dt_cache, self._tracer_phi = out
                else:
                    self.u, self._dt_cache = out
        self._pm_drift(float(dt))
        self.t += float(dt)
        self._source_passes(float(dt))
        self.dt_old = float(dt)
        self.nstep += 1

    def _source_passes(self, dt: float):
        """Coarse-cadence source physics on the hierarchy: star
        formation, SN feedback, sink passes, tracer advection
        (``amr_step`` order ``:369-380,493,549-567``)."""
        from ramses_tpu.pm import amr_physics as ap

        if self.sf_spec.enabled:
            with self.timers.section("star formation"):
                ap.star_formation_amr(self, dt)
                # f_w > 0 selects the mass-loaded kinetic wind scheme
                if self.sf_spec.f_w > 0:
                    ap.kinetic_feedback_amr(self)
                else:
                    ap.thermal_feedback_amr(self)
        if self.sinks is not None:
            with self.timers.section("sinks"):
                ap.sink_passes_amr(self, dt)
        if self.stellar is not None:
            from ramses_tpu.pm import stellar as stmod
            with self.timers.section("stellar"):
                self.stellar = stmod.make_stellar_from_sinks(
                    self.sinks, self.stellar, self.stellar_spec,
                    self._sf_rng, self.t)
                self.stellar = stmod.sn_from_stellar(
                    self, self.stellar, self.stellar_spec)
        if self.tracer_x is not None:
            with self.timers.section("tracers"):
                if getattr(self, "_tracer_phi", None) is not None:
                    # MC flux-probability jumps (pm/move_tracer.f90) —
                    # the fused step captured this step's face fluxes
                    ap.mc_tracer_amr(self)
                else:
                    # no flux capture on this path (MHD hierarchy,
                    # explicit-comm sharding): velocity tracers
                    ap.tracer_drift_amr(self, dt)
        if self.movie is not None and self.nstep % self.movie_imov == 0:
            with self.timers.section("movie"):
                self.movie.emit_amr(self)
        if bool(self.params.run.lightcone) and self.cosmo is not None \
                and self.p is not None:
            # output_cone every coarse step (amr_step.f90:177-178)
            from ramses_tpu.pm import lightcone as lcmod
            with self.timers.section("lightcone"):
                lcmod.emit_coarse_step(
                    self, outdir=str(self.params.output.output_dir))
        if self.rt_amr is not None:
            with self.timers.section("rt"):
                self.rt_amr.advance(self, dt)
        from ramses_tpu import patch
        user_source = patch.hook("source")
        if user_source is not None:
            with self.timers.section("patch source"):
                user_source(self, dt)
        if (self.sf_spec.enabled or self.sinks is not None
                or user_source is not None):
            # the passes changed u AFTER the fused step emitted the next
            # CFL dt — an SN dump makes that cached dt ~1000x too large
            # (the reference re-evaluates courant_fine after the source
            # sweep for the same reason); force a fresh evaluation
            self._dt_cache = None

    def step_chunk(self, nsteps: int, tend: float, trace: bool = False):
        """Run up to ``nsteps`` hydro-only coarse steps in ONE device
        dispatch (``_fused_multi_step``); returns steps done.  Callers
        guarantee no regrid is due inside the chunk.

        ``trace=True`` (telemetry-instrumented runs only): also return
        per-step ``(t, dt)`` host arrays from the scan's stacked
        outputs — one extra summary fetch, the fused program itself is
        unchanged in structure."""
        assert not self.gravity and not self.pic
        if self._offload is not None:
            # the multi-step window keeps the whole hierarchy in one
            # donated scan carry — callers gate chunking on engagement,
            # this is the defensive unpark for direct calls
            self._offload.unpark_all(self)
        spec = self._fused_spec()
        tdtype = jnp.result_type(float)
        if self._dt_cache is not None:
            dt0 = jnp.asarray(self._dt_cache, tdtype)
        else:
            dt0 = jnp.min(_fused_courant(self.u, self.dev, spec)) \
                .astype(tdtype)
        with self.timers.section("hydro - godunov"):
            out = _fused_multi_step(
                self.u, self.dev, jnp.asarray(self.t, tdtype),
                jnp.asarray(tend, tdtype), dt0, spec, nsteps,
                self._cool_bundle(), trace=trace)
            if trace:
                u, t, dtn, ndone, hist = out
            else:
                u, t, dtn, ndone = out
            self.u = u
            self._dt_cache = dtn
        self.t = float(t)
        n = int(ndone)
        self.nstep += n
        self.dt_old = float(dtn)
        if trace:
            ts, dts = jax.device_get(hist)
            return n, (ts[:n], dts[:n])
        return n

    # ------------------------------------------------------------------
    # in-run fault recovery (resilience/stepguard; &RUN_PARAMS
    # max_step_retries) — shared by every AmrSim solver family via
    # inheritance (sharded, MHD, RHD)
    # ------------------------------------------------------------------
    def _guard_capture(self):
        """Retain a pre-step device-side copy of the advancing state.
        The fused steps DONATE their input buffers, so the capture must
        be real device copies (``.copy()`` — no host transfer), not
        references; the tree/layouts are untouched by step_coarse/
        step_chunk so host references suffice for everything else."""
        snap = {
            "u": {l: self.u[l].copy() for l in self.levels()},
            "t": float(self.t), "nstep": int(self.nstep),
            "dt_old": float(getattr(self, "dt_old", 0.0)),
            "dt_cache": (float(self._dt_cache)
                         if self._dt_cache is not None else None),
        }
        bf = getattr(self, "bf", None)
        if isinstance(bf, dict):
            snap["bf"] = {l: v.copy() for l, v in bf.items()}
        self._guard_snap = snap

    def _guard_restore(self):
        """Reinstate the captured pre-step state with FRESH copies —
        a retried step donates its inputs too, so handing out the
        capture itself would die on the first retry."""
        snap = self._guard_snap
        self.u = {l: v.copy() for l, v in snap["u"].items()}
        if "bf" in snap:
            self.bf = {l: v.copy() for l, v in snap["bf"].items()}
        self.t = snap["t"]
        self.nstep = snap["nstep"]
        self.dt_old = snap["dt_old"]
        self._dt_cache = snap["dt_cache"]

    def _probe_finite(self) -> bool:
        """Did the step just taken stay finite?  Reads the dtnew the
        next ``coarse_dt`` fetches anyway (the fused step's Courant
        reduction touches every updated cell, so a NaN anywhere
        poisons it); when source passes invalidated the cache, one
        Courant fetch is paid and stashed back for coarse_dt."""
        from ramses_tpu.resilience.stepguard import StepGuard
        if self._dt_cache is None:
            self._dt_cache = float(jnp.min(_fused_courant(
                self.u, self.dev, self._fused_spec(),
                self.fg if (self.gravity and self.fg) else None)))
        return StepGuard.ok(float(self._dt_cache), self.t,
                            getattr(self, "dt_old", 0.0))

    def _recover_step(self, tend: float):
        """Redo-step ladder: restore the retained capture, retry ONE
        coarse step at dt halved per attempt, escalating the Riemann
        solver to diffusive LLF from the second attempt
        (``dataclasses.replace`` + spec rebuild; not sticky).  When the
        ladder is spent: restore the clean state, emergency-dump it
        (iout 999) and raise :class:`StepRetryExhausted`."""
        import dataclasses as _dc

        from ramses_tpu.resilience.stepguard import (StepGuard,
                                                     StepRetryExhausted)
        sg = self._sguard
        if self._guard_snap is None:
            raise StepRetryExhausted(
                "non-finite state with no retained pre-step capture "
                "(initial conditions already non-finite?)")
        sg.record_trip(self)
        cfg0 = self.cfg
        can_escalate = hasattr(cfg0, "riemann")   # RhdStatic has none
        try:
            for attempt in range(1, sg.max_retries + 1):
                self._guard_restore()
                escalated = attempt >= 2 and can_escalate
                if escalated:
                    self.cfg = _dc.replace(cfg0, riemann="llf")
                    self._spec = None
                dt = min(self.coarse_dt(), tend - self.t) \
                    * (0.5 ** attempt)
                if not StepGuard.ok(dt) or dt <= 0.0:
                    continue
                sg.record_rollback(self, attempt, dt, escalated)
                t0 = time.perf_counter()
                try:
                    self.step_coarse(dt)
                except FloatingPointError:
                    continue      # jax_debug_nans raised mid-retry
                if self._probe_finite():
                    sg.record_recovered(self, attempt)
                    if self.telemetry.enabled:
                        # one record for the recovered step, keeping
                        # the step-record count identical to a clean
                        # run's (the poisoned window emitted none)
                        self.telemetry.record_step(
                            self, dt=dt,
                            wall_s=time.perf_counter() - t0)
                    return
        finally:
            if self.cfg is not cfg0:
                self.cfg = cfg0
                self._spec = None
        self._guard_restore()     # the abort path leaves a CLEAN state
        out = None
        try:
            out = self.dump(999, str(self.params.output.output_dir))
        except Exception as e:    # the abort itself must not be masked
            print(f"resilience: emergency dump failed: {e}")
        sg.record_abort(self, out)
        raise StepRetryExhausted(
            f"coarse step {self.nstep} non-finite after "
            f"{sg.max_retries} retries (t={self.t:.6g}); last clean "
            f"state dumped to {out}")

    def evolve(self, tend: float, nstepmax: int = 10 ** 9,
               verbose: bool = False, guard=None):
        """Advance to ``tend``.  ``guard``: optional
        :class:`ramses_tpu.utils.ops.OpsGuard` — signal/walltime/stop-file
        handling + the per-``ncontrol`` screen block."""
        ncontrol = max(1, int(self.params.run.ncontrol))
        telem = self.telemetry
        # verbose/telemetry are pure reporting: the chunked fast path
        # stays eligible and reports from its summary (``trace``) —
        # the old behaviour of dropping to the per-step slow path on
        # ``verbose=True`` silently benchmarked a different program
        instrumented = telem.enabled or verbose
        if telem.enabled and not telem.run_info:
            telem.run_info.update(sim_run_info(self))
            import os as _os

            from ramses_tpu.telemetry import hlo as _hlo
            if _os.environ.get("RAMSES_TELEMETRY_HLO", "1") != "0":
                # static gather-traffic inventory of the fused coarse
                # step for this tree: a lowering (trace, no compile),
                # recorded once per run for offline trend tracking
                try:
                    txt = _hlo.lower_fused_step(self)
                    inv = _hlo.gather_inventory(txt)
                    telem.run_info["hlo_gather_elems"] = \
                        sum(n for n, _ in inv)
                    telem.run_info["hlo_gather_ops"] = len(inv)
                    # static-analysis audit of the same lowering:
                    # severity counts of UNBASELINED findings (see
                    # ramses_tpu/analysis) — nonzero error/warn here
                    # means this exact run pays for a hazard the lint
                    # gate would flag
                    from ramses_tpu.analysis import engine as _aeng
                    telem.run_info["analysis_findings"] = \
                        _aeng.audit_sim(self, text=txt)
                except Exception as e:  # pragma: no cover - best effort
                    telem.run_info["hlo_gather_elems"] = None
                    telem.run_info["hlo_gather_error"] = repr(e)
        sguard = self._sguard
        while self.t < tend * (1 - 1e-12) and self.nstep < nstepmax:
            if guard is not None:
                if not guard.check():
                    break
                if self.nstep % ncontrol == 0:
                    print(guard.screen_block())
            if self.regrid_interval and \
                    self.nstep % self.regrid_interval == 0:
                self.regrid()
            # chunk until the next regrid / nstepmax boundary: hydro-only
            # steps need no host work in between, so they run as one
            # fused multi-step program
            if self.regrid_interval:
                to_regrid = self.regrid_interval \
                    - self.nstep % self.regrid_interval
            else:
                to_regrid = 1 << 30
            # cap: bounds compiled-scan length AND the post-tend no-op
            # tail (masked steps still execute inside the scan)
            from ramses_tpu import patch as _patch
            lim = min(to_regrid, nstepmax - self.nstep, 64)
            # canonical power-of-two scan lengths: every (regrid-interval,
            # nstepmax) combination decomposes into the same handful of
            # compiled programs instead of compiling one per remainder
            chunk = 1 << (max(lim, 1).bit_length() - 1)
            if self._fault is not None:
                # pending step-indexed faults must land exactly at
                # their target step, not at a chunk boundary (clamped
                # to 1 this drops to the per-step path below)
                chunk = self._fault.clamp_window(self.nstep, chunk)
            if not self.gravity and not self.pic \
                    and self.cosmo is None and self.sinks is None \
                    and self.tracer_x is None and self.movie is None \
                    and getattr(self, "rt_amr", None) is None \
                    and _patch.hook("source") is None and chunk > 1 \
                    and (self._offload is None
                         or not self._offload.engaged(self)):
                if sguard is not None:
                    # capture BEFORE injection: the injected NaN plays
                    # a transient solver fault, so the retained state
                    # must be the clean pre-fault one
                    self._guard_capture()
                if self._fault is not None:
                    self._fault.maybe_nan(self)
                if not instrumented:
                    with self._step_guard():
                        if self._fault is not None:
                            self._fault.maybe_hang(self.nstep)
                        n = self.step_chunk(chunk, tend)
                    self._wd_note()
                    if sguard is not None \
                            and not sguard.ok(self.t, self.dt_old):
                        self._recover_step(tend)
                        continue
                    if n == 0:
                        break
                    continue
                t0 = time.perf_counter()
                with self._step_guard():
                    if self._fault is not None:
                        self._fault.maybe_hang(self.nstep)
                    n, (ts, dts) = self.step_chunk(chunk, tend,
                                                   trace=True)
                self._wd_note()
                if sguard is not None \
                        and not sguard.ok(self.t, self.dt_old):
                    # rolled-back window: its poisoned records are
                    # dropped; the recovery emits one step record
                    self._recover_step(tend)
                    continue
                if n == 0:
                    break
                wall = time.perf_counter() - t0
                telem.record_chunk(self, ts, dts, n, wall)
                if verbose:
                    print(telemetry_screen.step_line(
                        self, dt=float(dts[-1]), chunk=n))
                continue
            dt = min(self.coarse_dt(), tend - self.t)
            if sguard is not None:
                self._guard_capture()
            if self._fault is not None:
                self._fault.maybe_nan(self)
            t0 = time.perf_counter() if instrumented else 0.0
            with self._step_guard():
                if self._fault is not None:
                    self._fault.maybe_hang(self.nstep)
                self.step_coarse(dt)
            self._wd_note()
            # trip detection BEFORE the telemetry record and before the
            # next iteration's regrid rebuilds the tree on a poisoned
            # state (which would make the capture unrestorable): the
            # probe reads the dtnew the next coarse_dt fetches anyway
            if sguard is not None and not self._probe_finite():
                self._recover_step(tend)
                continue
            if instrumented:
                if telem.enabled:
                    telem.record_step(
                        self, dt=dt, wall_s=time.perf_counter() - t0)
                if verbose:
                    print(telemetry_screen.step_line(self, dt=dt))

    def _step_guard(self):
        """Watchdog deadline guard for one fused window / coarse step
        (nullcontext when the watchdog is off — zero added fetches)."""
        return (self._wd.guard("step") if self._wd is not None
                else nullcontext())

    def _wd_note(self):
        if self._wd is not None:
            self._wd.note(nstep=self.nstep, t=self.t)

    # ------------------------------------------------------------------
    # diagnostics
    # ------------------------------------------------------------------
    def drain(self):
        """Hard device sync: fetch one row per level.  (On tunneled
        devices ``block_until_ready`` can return before completion;
        a host fetch cannot.)"""
        jax.device_get([self.u[l][:1, 0] for l in self.levels()])

    def totals(self):
        """Conservation audit over leaf cells (``check_cons``)."""
        cfg = self.cfg
        tot = np.zeros(cfg.nvar)
        for l in self.levels():
            vol = self.dx(l) ** cfg.ndim
            u = self.tree_order_cells(self.u[l], l)
            leaf = ~self.tree.refined_mask(l)
            tot += u[leaf].sum(axis=0) * vol
        return tot

    def leaf_sample(self, lvl: int):
        """(centers [n, ndim], u [n, nvar]) of leaf cells at one level."""
        u = self.tree_order_cells(self.u[lvl], lvl)
        leaf = ~self.tree.refined_mask(lvl)
        return self.tree.cell_centers(lvl, self.boxlen)[leaf], u[leaf]

    def ncell_leaf(self) -> int:
        return sum(int((~self.tree.refined_mask(l)).sum())
                   for l in self.levels())

    # ------------------------------------------------------------------
    # snapshot / restart (SURVEY.md §3.4, §5.4)
    # ------------------------------------------------------------------
    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path: Optional[str] = None, ncpu: int = 1,
             dumper=None) -> str:
        """Write a reference-format ``output_NNNNN/`` snapshot
        (``ncpu > 1``: one file set per domain — multi-domain
        checkpoint restorable onto any device count).

        ``dumper``: optional :class:`~ramses_tpu.io.async_writer.
        AsyncDumper` — the host-resident snapshot is assembled
        synchronously, the file writing happens on its background
        thread (the ``pario`` offload, SURVEY.md §2.10).

        ``&OUTPUT_PARAMS pario=.true.`` routes to the elastic sharded
        checkpoint instead (``pario_NNNNN/`` shard dirs, two-phase
        global commit, mesh-shape-elastic restore)."""
        import os
        import shutil

        if bool(getattr(self.params.output, "pario", False)):
            return self.dump_pario(iout, base_dir)

        from ramses_tpu.io import snapshot as snapmod
        snap = snapmod.snapshot_from_amr(self, iout)
        final = os.path.join(base_dir, f"output_{iout:05d}")
        # driver extras (sink/stellar CSVs, clump catalogues, merger
        # tree) are gathered synchronously into a staging dir that
        # dump_all folds into the checkpoint BEFORE the manifest +
        # atomic rename — writing them into the final directory
        # afterwards would leave them outside the manifest
        extra = final + ".extras.tmp"
        if os.path.isdir(extra):
            shutil.rmtree(extra)
        self._dump_csv_extras(extra, iout)
        self._clumpfind_pass(extra, iout)
        if not os.path.isdir(extra) or not os.listdir(extra):
            shutil.rmtree(extra, ignore_errors=True)
            extra = None
        keep = int(getattr(self.params.output, "checkpoint_keep", 0))
        if dumper is not None:
            dumper.submit(snap, iout, base_dir,
                          namelist_path=namelist_path, ncpu=ncpu,
                          extra_dir=extra, keep_last=keep)
            out = final
        else:
            out = snapmod.dump_all(snap, iout, base_dir,
                                   namelist_path=namelist_path,
                                   ncpu=ncpu, extra_dir=extra,
                                   keep_last=keep)
        return out

    def dump_pario(self, iout: int = 1, base_dir: str = ".",
                   io_group_size: Optional[int] = None,
                   split_hosts: Optional[int] = None) -> str:
        """Elastic sharded checkpoint (:mod:`ramses_tpu.io.pario`
        format 2): every process stages its own validated shard dir,
        process 0 seals the set under the watchdogged two-phase
        commit.  Defaults come from ``&OUTPUT_PARAMS io_group_size`` /
        ``pario_split_hosts``; ``checkpoint_keep`` rotation covers
        pario and snapshot checkpoints alike."""
        import os

        import jax

        from ramses_tpu.io.pario import dump_pario as _dp
        out = self.params.output
        if io_group_size is None:
            g = int(getattr(out, "io_group_size", 0))
            io_group_size = g if g > 0 else None
        if split_hosts is None:
            s = int(getattr(out, "pario_split_hosts", 0))
            split_hosts = s if s > 0 else None
        path = _dp(self, iout, base_dir,
                   io_group_size=io_group_size,
                   split_hosts=split_hosts)
        keep = int(getattr(out, "checkpoint_keep", 0))
        if keep > 0 and jax.process_index() == 0 \
                and not path.endswith(".tmp"):
            from ramses_tpu.resilience import rotate_checkpoints
            rotate_checkpoints(os.path.dirname(os.path.abspath(path))
                               or ".", keep, protect=path)
        return path

    def _clumpfind_pass(self, out: str, iout: int):
        """In-run PHEW chain at output time (``clumpfind=.true.``,
        ``pm/clump_finder.f90`` called from ``amr_step``/outputs):
        deposit the LIVE particles, watershed with saddle-relevance
        merging, unbind, write the clump table, and grow the run's
        merger tree across outputs (``pm/merger_tree.f90``).

        Runs synchronously inside ``dump`` (cost bounded by
        ``nx_clump^ndim`` + per-clump unbinding) — an AsyncDumper
        offloads the FILE writing only, like the reference whose
        clump finder also runs inline at outputs.  The tree's halo
        catalogues persist per output (``clump_cat_NNNNN.npz``) so a
        restart rebuilds the cross-output links (the reference
        re-reads progenitor data from prior outputs the same way)."""
        import glob
        import os

        if not bool(getattr(self.params.run, "clumpfind", False)):
            return
        if self.p is None:
            import warnings
            warnings.warn("clumpfind=.true. needs particles (pic or "
                          "SF); no clump tables will be written")
            return
        from ramses_tpu.pm.halo import (Halo, MergerTree,
                                        write_halo_table)
        from ramses_tpu.utils.halos import catalogue_from_arrays
        cf = self.params.clumpfind
        act = np.asarray(self.p.active)
        x = np.asarray(self.p.x)[act]
        if len(x) == 0:
            return
        halos = catalogue_from_arrays(
            x, np.asarray(self.p.v)[act], np.asarray(self.p.m)[act],
            np.asarray(self.p.idp)[act], self.boxlen,
            nx=int(cf.nx_clump), threshold=float(cf.density_threshold),
            relevance=float(cf.relevance_threshold),
            npart_min=int(cf.npart_min), unbind=bool(cf.unbind),
            saddle_pot=bool(cf.saddle_pot),
            nmassbins=int(cf.nmassbins),
            saddle_threshold=max(float(cf.saddle_threshold), 0.0))
        if cf.mass_threshold > 0 and act.any():
            mp = float(np.asarray(self.p.m)[act].mean())
            halos = [h for h in halos
                     if h.mass >= cf.mass_threshold * mp]
        os.makedirs(out, exist_ok=True)
        write_halo_table(halos,
                         os.path.join(out, f"clump_{iout:05d}.txt"))
        if not hasattr(self, "_mergertree"):
            self._mergertree = MergerTree()
            # restart: rebuild the tree from the catalogues persisted
            # alongside earlier outputs (they carry the particle ids
            # the id-based linking needs).  ids ride as a flat int
            # array + offsets — no object arrays, no allow_pickle —
            # and the output index comes from the filename pattern,
            # skipping anything that doesn't match.
            import re
            base = os.path.dirname(os.path.abspath(out))
            for f in sorted(glob.glob(
                    os.path.join(base, "output_*",
                                 "clump_cat_*.npz"))):
                mm_ = re.search(r"clump_cat_(\d+)\.npz$",
                                os.path.basename(f))
                # only catalogues from BEFORE this output (a restart
                # may overwrite later outputs of the aborted run)
                if mm_ is None or int(mm_.group(1)) >= iout:
                    continue
                try:
                    z = np.load(f)
                    if "ids_off" in z.files:
                        off = np.asarray(z["ids_off"], dtype=np.int64)
                        flat = np.asarray(z["ids_flat"], dtype=np.int64)
                        ids = [flat[off[k]:off[k + 1]]
                               for k in range(len(off) - 1)]
                    elif "ids" in z.files:
                        # legacy r04 object-array layout: the one case
                        # allow_pickle is still accepted for, so an
                        # existing run's history survives the format
                        # change
                        z = np.load(f, allow_pickle=True)
                        ids = [np.asarray(i, dtype=np.int64)
                               for i in z["ids"]]
                    else:
                        raise KeyError("no ids_off/ids record")
                    old = [Halo(index=int(i), mass=float(mm),
                                npart=len(hid), pos=pp, vel=vv,
                                ekin=0.0, epot=0.0, ids=hid)
                           for i, mm, pp, vv, hid in zip(
                               z["index"], z["mass"], z["pos"],
                               z["vel"], ids)]
                    t_snap = float(z["t"])
                except Exception as e:      # truncated zip, missing keys
                    import warnings
                    warnings.warn(f"skipping malformed clump "
                                  f"catalogue {f}: {e}")
                    continue
                self._mergertree.add_snapshot(t_snap, old)
        ids_off = np.concatenate(
            [[0], np.cumsum([len(h.ids) for h in halos])]
        ).astype(np.int64)
        np.savez_compressed(
            os.path.join(out, f"clump_cat_{iout:05d}.npz"),
            t=float(self.t),
            index=np.array([h.index for h in halos]),
            mass=np.array([h.mass for h in halos]),
            pos=np.array([h.pos for h in halos]),
            vel=np.array([h.vel for h in halos]),
            ids_off=ids_off,
            ids_flat=(np.concatenate([h.ids for h in halos])
                      if halos else np.zeros(0)).astype(np.int64))
        self._mergertree.add_snapshot(float(self.t), halos)
        if len(self._mergertree.snapshots) > 1:
            self._mergertree.write(
                os.path.join(out, f"mergertree_{iout:05d}.txt"))

    def _dump_csv_extras(self, out: str, iout: int):
        """Sink/stellar CSV companions for the output
        (``pm/output_sink.f90``, ``pm/output_stellar.f90`` — the
        reference oracle reads both).  Tiny host writes into the
        extras staging dir, folded under the checkpoint manifest by
        dump_all before the atomic rename."""
        import os

        from ramses_tpu.io import snapshot as snapmod
        if self.sinks is None and getattr(self, "stellar", None) is None:
            return
        os.makedirs(out, exist_ok=True)
        if self.sinks is not None:
            dmf = (self.stellar.dmf
                   if getattr(self, "stellar", None) is not None else None)
            snapmod.write_sink_csv(
                os.path.join(out, f"sink_{iout:05d}.csv"), self.sinks,
                dmf)
        if getattr(self, "stellar", None) is not None:
            snapmod.write_stellar_csv(
                os.path.join(out, f"stellar_{iout:05d}.csv"),
                self.stellar)

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float32) -> "AmrSim":
        """Resume from a snapshot directory (``nrestart`` path)."""
        from ramses_tpu.io.snapshot import prim_out_to_cons
        cfg = cls._make_cfg(params)
        sim, _parts = restore_amr_scaffold(
            cls, params, outdir, dtype,
            to_cons=lambda q: prim_out_to_cons(q, cfg),
            place_level=_place_u_rows)
        return sim

    @classmethod
    def from_checkpoint_dir(cls, params: Params, outdir: str,
                            dtype=jnp.float32, log=print,
                            **kw) -> "AmrSim":
        """Restore from any checkpoint directory: ``pario_NNNNN``
        elastic sharded dumps go through the mesh-shape-elastic
        reader, everything else through :meth:`from_snapshot`.  A
        pario checkpoint whose surviving shards cannot cover the
        hierarchy is quarantined shard-by-shard and the restore falls
        back to the next-oldest globally-valid checkpoint — the same
        degrade-don't-die contract ``resolve_restart_dir`` applies to
        whole-checkpoint rot."""
        import os

        from ramses_tpu.io import pario as pariomod
        from ramses_tpu.resilience import latest_valid_checkpoint
        cur = outdir
        seen = set()
        while True:
            seen.add(os.path.abspath(cur))
            name = os.path.basename(os.path.normpath(cur))
            if not name.startswith("pario_"):
                return cls.from_snapshot(params, cur, dtype=dtype)
            try:
                return pariomod.restore_pario(cls, params, cur,
                                              dtype=dtype, log=log,
                                              **kw)
            except pariomod.CorruptShardError as e:
                if log is not None:
                    log(f"resilience: {e}; falling back to the "
                        "next-oldest valid checkpoint")
                base = os.path.dirname(os.path.abspath(cur)) or "."
                nxt = latest_valid_checkpoint(base, log=log)
                if nxt is None or os.path.abspath(nxt) in seen:
                    raise
                cur = nxt
