"""Clump finder + Monte-Carlo tracer tests."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from ramses_tpu.pm.clumps import find_clumps, write_clump_table
from ramses_tpu.pm.tracers import mc_tracer_step


def _two_blobs(n=48, sep=0.45, amp2=0.6, sigma=0.05):
    x = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(x, x, indexing="ij")
    blob = lambda cx, cy, a: a * np.exp(
        -((X - cx) ** 2 + (Y - cy) ** 2) / (2 * sigma ** 2))
    return 0.01 + blob(0.3, 0.5, 1.0) + blob(0.3 + sep, 0.5, amp2)


def test_watershed_two_peaks():
    rho = _two_blobs()
    labels, clumps = find_clumps(rho, threshold=0.05, relevance=1.5,
                                 dx=1.0 / 48, merge=False)
    assert len(clumps) == 2
    # every above-threshold cell is labeled
    assert ((np.asarray(labels) >= 0) == (rho > 0.05)).all()
    # peak positions at the blob centres
    pks = sorted(c.peak_cell for c in clumps)
    assert pks[0][0] == int(0.3 * 48) and pks[0][1] == 24
    assert pks[1][0] == int(0.75 * 48)
    # masses ~ 2π σ² amp ratio
    m = sorted(c.mass for c in clumps)
    assert 0.4 < m[0] / m[1] < 0.8


def test_clump_merging_by_relevance():
    """Overlapping blobs (peak/saddle ≈ 1.7-1.9) merge when the relevance
    threshold is above that, survive when below."""
    rho = _two_blobs(sep=0.16, amp2=0.9, sigma=0.05)
    _l1, c1 = find_clumps(rho, threshold=0.05, relevance=1.2, merge=True)
    _l2, c2 = find_clumps(rho, threshold=0.05, relevance=3.0, merge=True)
    assert len(c1) == 2
    assert len(c2) == 1
    # merged mass equals the sum
    assert np.isclose(c2[0].mass, sum(c.mass for c in c1), rtol=1e-12)


def test_clump_table(tmp_path):
    rho = _two_blobs()
    _, clumps = find_clumps(rho, threshold=0.05, merge=False)
    p = str(tmp_path / "clumps.txt")
    write_clump_table(clumps, p)
    rows = [l for l in open(p) if not l.startswith("#")]
    assert len(rows) == len(clumps)


def test_watershed_3d_single_peak():
    n = 16
    x = (np.arange(n) + 0.5) / n
    X, Y, Z = np.meshgrid(x, x, x, indexing="ij")
    rho = np.exp(-((X - 0.5) ** 2 + (Y - 0.5) ** 2 + (Z - 0.5) ** 2)
                 / 0.02)
    labels, clumps = find_clumps(rho, threshold=0.1)
    assert len(clumps) == 1
    assert clumps[0].peak_cell == (8, 8, 8)


def test_tracers_follow_uniform_advection():
    """Uniform flow: ensemble tracer drift ≈ gas velocity."""
    from ramses_tpu.grid.uniform import UniformGrid, step_with_flux
    from ramses_tpu.grid import boundary as bmod
    from ramses_tpu.hydro.core import HydroStatic

    cfg = HydroStatic(ndim=2, gamma=1.4, riemann="hllc")
    n = 32
    dx = 1.0 / n
    grid = UniformGrid(cfg=cfg, shape=(n, n), dx=dx,
                       bc=bmod.BoundarySpec.periodic(2))
    rho0, vx = 1.0, 0.5
    u = jnp.stack([jnp.full((n, n), rho0),
                   jnp.full((n, n), rho0 * vx),
                   jnp.zeros((n, n)),
                   jnp.full((n, n), 1.0 / 0.4 + 0.5 * rho0 * vx ** 2)])
    ntr = 4000
    key = jax.random.PRNGKey(0)
    key, k1, k2 = jax.random.split(key, 3)
    x = jax.random.uniform(k1, (ntr, 2))
    x0 = np.array(x)
    dt = 0.4 * dx / (vx + np.sqrt(1.4 / rho0))
    nsteps = 40
    for i in range(nsteps):
        rho_before = u[0]
        u, mf = step_with_flux(grid, u, dt)
        key, sub = jax.random.split(key)
        x = mc_tracer_step(x, sub, rho_before, mf, (n, n), dx)
    # mean displacement along x (mod box): expected vx * t
    disp = np.asarray(x) - x0
    disp = (disp + 0.5) % 1.0 - 0.5
    expect = vx * dt * nsteps
    assert abs(disp[:, 0].mean() - expect) < 0.15 * expect
    assert abs(disp[:, 1].mean()) < 0.02
    # distribution stays uniform: chi^2 over a coarse binning
    h, _ = np.histogram(np.asarray(x)[:, 0], bins=8, range=(0, 1))
    assert h.min() > ntr / 8 * 0.8


def test_tracer_no_flux_no_motion():
    from ramses_tpu.pm.tracers import mc_tracer_step
    x = jnp.asarray([[0.51, 0.52], [0.11, 0.93]])
    key = jax.random.PRNGKey(1)
    rho = jnp.ones((8, 8))
    mf = jnp.zeros((2, 8, 8))
    x2 = mc_tracer_step(x, key, rho, mf, (8, 8), 1.0 / 8)
    assert np.allclose(np.asarray(x2), np.asarray(x))


@pytest.mark.slow
def test_tracer_namelist_dump_restart(tmp_path):
    """&RUN_PARAMS tracer=.true.: Poisson-seeded jittered tracers
    advect, serialize as massless FAM_GAS_TRACER particle rows, and a
    restart continues the SAME trajectories (not a fresh seeding)."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import load_params

    p = load_params("namelists/tracer_sedov.nml", ndim=2)
    p.run.nstepmax = 3
    sim = AmrSim(p, dtype=jnp.float64)
    assert sim.tracer_x is not None and len(sim.tracer_x) > 0
    # jittered: no two tracers coincide with a cell centre lattice
    frac = np.mod(sim.tracer_x / sim.dx(sim.lmin), 1.0)
    assert not np.allclose(frac, 0.5, atol=1e-12)
    sim.evolve(1e9, nstepmax=3)
    out = sim.dump(1, str(tmp_path))
    back = AmrSim.from_snapshot(p, out, dtype=jnp.float64)
    assert back.tracer_x is not None
    a = np.sort(np.asarray(sim.tracer_x), axis=0)
    b = np.sort(np.asarray(back.tracer_x), axis=0)
    np.testing.assert_allclose(a, b, rtol=1e-12)
    # tracers are massless in the files: gas mass audit unchanged
    assert back.p is None or float(jnp.sum(back.p.m)) >= 0.0
    back.evolve(1e9, nstepmax=back.nstep + 1)
    assert np.isfinite(back.tracer_x).all()


def test_tracer_fractional_sampling():
    """tracer_per_cell=0.1 thins the population ~10x (Poisson mean),
    not one-per-cell."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import load_params

    p = load_params("namelists/tracer_sedov.nml", ndim=2)
    p.run.tracer_per_cell = 0.1
    sim = AmrSim(p, dtype=jnp.float64)
    nleaf = sim.ncell_leaf()
    ntr = 0 if sim.tracer_x is None else len(sim.tracer_x)
    assert ntr < 0.3 * nleaf            # far below one per cell


def test_tracer_empty_population_not_resurrected(tmp_path):
    """A restart of a tracer run whose population is EMPTY must stay
    empty — re-seeding would fabricate trajectories."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import load_params

    p = load_params("namelists/tracer_sedov.nml", ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.tracer_x = np.zeros((0, 2))        # everyone escaped
    sim.step_coarse(sim.coarse_dt())
    out = sim.dump(1, str(tmp_path))
    back = AmrSim.from_snapshot(p, out, dtype=jnp.float64)
    assert back.tracer_x is not None and len(back.tracer_x) == 0
    back.step_coarse(back.coarse_dt())     # and it still steps


def test_tracer_ids_stable_across_dumps(tmp_path):
    """Tracer ids are assigned ONCE at seeding (base TRACER_ID0, clear
    of the star/DM id space) and ride identically through successive
    dumps and a restart — cross-snapshot trajectory tracking by id must
    survive the live particle population changing."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import load_params
    from ramses_tpu.pm.particles import TRACER_ID0

    p = load_params("namelists/tracer_sedov.nml", ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    assert sim.tracer_id is not None and len(sim.tracer_id) == len(sim.tracer_x)
    ids0 = np.array(sim.tracer_id)
    assert ids0.min() >= TRACER_ID0
    assert len(np.unique(ids0)) == len(ids0)
    x0 = {i: x.copy() for i, x in zip(ids0, np.asarray(sim.tracer_x))}
    sim.dump(1, str(tmp_path))
    sim.evolve(1e9, nstepmax=2)
    out2 = sim.dump(2, str(tmp_path))
    back = AmrSim.from_snapshot(p, out2, dtype=jnp.float64)
    assert back.tracer_id is not None
    ids1 = np.array(back.tracer_id)
    # the SAME id set, not a fresh numbering from max(live idp)+1
    assert np.array_equal(np.sort(ids1), np.sort(ids0))
    # and each id still names the same trajectory (position advected,
    # but the id->row association is preserved through dump/restore)
    x1 = {i: x for i, x in zip(ids1, np.asarray(back.tracer_x))}
    xs = np.asarray(sim.tracer_x)
    for i, xb in zip(np.array(sim.tracer_id), xs):
        assert np.allclose(x1[i], xb)


def test_saddle_threshold_halo_grouping():
    """merge_clumps('saddleden') semantics (pm/clump_merger.f90:592):
    clumps joined by a saddle denser than saddle_threshold group into
    one halo; clumps below stay their own halo."""
    import numpy as np

    from ramses_tpu.pm.clumps import find_clumps

    n = 32
    rho = np.full((n, n), 0.1)
    x = np.arange(n)
    xx, yy = np.meshgrid(x, x, indexing="ij")

    def blob(cx, cy, amp, w):
        return amp * np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2)
                              / (2 * w ** 2)))
    # pair A: two peaks joined by a HIGH ridge (saddle ~ 5) — one halo
    rho += blob(8, 8, 10.0, 2.0) + blob(8, 14, 9.0, 2.0)
    # pair B: distant peak with only low surroundings — its own halo
    rho += blob(24, 24, 8.0, 2.0)
    labels, clumps = find_clumps(rho, threshold=1.0, relevance=1.2,
                                 saddle_threshold=3.0)
    assert len(clumps) == 3
    by_idx = {c.index: c for c in clumps}
    # the A-pair shares a parent; B is its own parent
    pa = [c.parent for c in clumps
          if abs(c.peak_cell[0] - 8) <= 2]
    assert len(set(pa)) == 1
    cb = [c for c in clumps if c.peak_cell[0] > 16][0]
    assert cb.parent == cb.index
    assert cb.parent not in pa or pa[0] != cb.parent
    # label field carries the halo segmentation: A-pair is one label
    la = np.unique(labels[(xx < 16) & (labels >= 0)])
    assert len(la) == 1
    # richer properties populated
    for c in clumps:
        assert c.rho_av >= c.rho_min > 0
        assert c.peak_rho >= c.rho_av
