"""RT on the AMR hierarchy (``rt/amr.py`` — the per-level subcycled
``rt_step`` of ``amr/amr_step.f90:594-672``, gray 1-group)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.amr.hierarchy import AmrSim

UNITS = {"units_density": 1.66e-24, "units_time": 3.15e13,
         "units_length": 3.08e18}


def _rt_groups(lmin, lmax, heating=False, refine=None, tend=0.01):
    g = {
        "run_params": {"hydro": True, "rt": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax,
                       "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1e-4]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "rt_params": {"rt_ndot": 1e48, "rt_c_fraction": 1e-4,
                      "rt_src_pos": [0.5, 0.5, 0.5], "rt_otsa": True,
                      "rt_heating": heating},
        "units_params": dict(UNITS),
        "output_params": {"tend": tend},
    }
    if refine:
        g["refine_params"] = refine
    return g


def test_rt_amr_matches_uniform_on_complete_level():
    """A levelmin==levelmax AMR run's ionized volume tracks the
    uniform RtCoupled path on the same grid."""
    from ramses_tpu.driver import Simulation

    tend = 0.004
    g = _rt_groups(4, 4, tend=tend)
    asim = AmrSim(params_from_dict({k: dict(v) for k, v in g.items()},
                                   ndim=3), dtype=jnp.float64)
    asim.evolve(tend, nstepmax=3)
    v_amr = asim.rt_amr.ionized_volume(asim)

    usim = Simulation(params_from_dict(
        {k: dict(v) for k, v in g.items()}, ndim=3), dtype=jnp.float64)
    usim.evolve()
    # compare through the RT sim's own measure (code volume)
    x_uni = np.asarray(usim.rt.sim.x)
    v_uni = float(x_uni.sum()) * usim.dx ** 3
    assert v_amr > 0.05 and v_uni > 0.05
    assert abs(v_amr - v_uni) < 0.35 * max(v_amr, v_uni), (v_amr, v_uni)


def test_rt_amr_refined_front_and_heating():
    """With a geometrically refined centre, the fine level ionizes
    around the source, photoheating raises the gas energy, and regrid
    migration keeps the radiation state consistent."""
    refine = {"r_refine": [0.15] * 8, "x_refine": [0.5] * 8,
              "y_refine": [0.5] * 8, "z_refine": [0.5] * 8}
    g = _rt_groups(4, 5, heating=True, refine=refine, tend=0.001)
    # denser gas + weaker source: the I-front stays INSIDE the refined
    # region so its radial profile is measurable on the fine level
    g["init_params"]["d_region"] = [10.0]
    g["rt_params"]["rt_ndot"] = 1e44
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    assert sim.tree.noct(5) > 0
    e0 = sim.totals()[4]
    v0 = sim.rt_amr.ionized_volume(sim)
    sim.evolve(0.001, nstepmax=2)
    v1 = sim.rt_amr.ionized_volume(sim)
    assert v1 > 1.5 * v0                      # front swept outward
    assert sim.totals()[4] > e0               # photoheated
    lmax = max(sim.levels())
    x = np.asarray(sim.rt_amr.xion[lmax])[:sim.maps[lmax].noct * 8]
    assert x.max() > 0.99                     # source cells ionized
    # the front is RADIALLY ordered on the refined level — this is the
    # row-order canary: oct/cell-major scrambles flatten the profile
    xc = sim.tree.cell_centers(lmax, sim.boxlen)
    rr = np.sqrt(((xc - 0.5) ** 2).sum(axis=1))
    near = x[:len(xc)][rr < 0.04].mean()
    far = x[:len(xc)][(rr > 0.11) & (rr < 0.145)].mean()
    assert near > 0.8 and far < 0.1, (near, far)
    # all levels hold sane radiation state after regrids
    for l in sim.levels():
        rad = np.asarray(sim.rt_amr.rad[l])
        assert np.isfinite(rad).all() and (rad[:, 0] >= 0).all()


def test_rt_amr_rejects_multigroup():
    g = _rt_groups(4, 4)
    g["rt_params"]["rt_ngroups"] = 3
    with pytest.raises(NotImplementedError):
        AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
