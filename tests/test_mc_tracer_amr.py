"""MC flux tracers on the AMR hierarchy (``pm/move_tracer.f90`` parity).

Three oracles:
  * the captured per-cell face fluxes reproduce the conservative mass
    update EXACTLY on every leaf cell (including coarse cells whose
    face slots carry fine-level flux corrections);
  * uniform advection across a statically refined patch drifts the
    tracer ensemble at the gas velocity;
  * a Sedov blast's tracer distribution follows the gas mass
    distribution within sampling noise.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import Params, load_params
from ramses_tpu.pm import amr_physics as ap


def _uniform_flow_params(vx=0.5):
    p = Params(ndim=2)
    p.run.tracer = True
    p.run.tracer_per_cell = 2.0
    p.amr.levelmin, p.amr.levelmax = 4, 5
    p.amr.boxlen = 1.0
    p.init.nregion = 1
    p.init.region_type = ["square"]
    p.init.x_center, p.init.y_center = [0.5], [0.5]
    p.init.length_x, p.init.length_y = [10.0], [10.0]
    p.init.exp_region = [10.0]
    p.init.d_region, p.init.p_region = [1.0], [1.0]
    p.init.u_region, p.init.v_region = [vx], [0.0]
    # static refined ball in the box centre (geometry criterion only)
    i = 4 - 1
    p.refine.r_refine[i] = 0.2
    p.refine.x_refine[i], p.refine.y_refine[i] = 0.5, 0.5
    return p


@pytest.mark.smoke
def test_mc_capture_matches_mass_update(monkeypatch):
    """Σ_d (φ_lo - φ_hi) == Δρ on every leaf cell of every level."""
    p = _uniform_flow_params()
    sim = AmrSim(p)
    assert sim._fused_spec().want_flux
    captured = {}

    real = ap.mc_tracer_amr

    def grab(s):
        captured.update({l: np.asarray(v)
                         for l, v in s._tracer_phi.items()})
        real(s)

    monkeypatch.setattr(ap, "mc_tracer_amr", grab)
    # second step exercises a developed state too
    for _ in range(2):
        u0 = {l: np.asarray(sim.u[l]) for l in sim.levels()}
        captured.clear()
        sim.step_coarse(sim.coarse_dt())
        for l in sim.levels():
            m = sim.maps[l]
            ncell = m.noct * 2 ** sim.cfg.ndim
            leaf = ~sim.tree.refined_mask(l)
            drho = (np.asarray(sim.u[l]) - u0[l])[:ncell, 0]
            phi = captured[l][:ncell]
            net = (phi[:, :, 0] - phi[:, :, 1]).sum(axis=1)
            np.testing.assert_allclose(net[leaf], drho[leaf],
                                       rtol=2e-4, atol=2e-6)


def test_mc_tracer_amr_uniform_advection():
    """Ensemble drift == v·t across the refinement boundary."""
    p = _uniform_flow_params(vx=0.5)
    sim = AmrSim(p)
    assert sim.tracer_x is not None and len(sim.tracer_x) > 200
    # the refined patch exists and covers < the whole box
    assert sim.tree.has(5) and sim.tree.noct(5) < sim.tree.noct(4)
    x0 = np.asarray(sim.tracer_x).copy()
    n0 = len(x0)
    sim.evolve(1e9, nstepmax=10)
    assert len(sim.tracer_x) == n0          # periodic: nothing escapes
    L = sim.boxlen
    disp = np.mod(sim.tracer_x - x0 + 0.5 * L, L) - 0.5 * L
    drift = disp.mean(axis=0)
    assert abs(drift[0] - 0.5 * sim.t) < 0.025
    assert abs(drift[1]) < 0.025
    # the gas itself stayed uniform (sanity of the oracle)
    for l in sim.levels():
        rho = np.asarray(sim.u[l])[:sim.maps[l].noct * 4, 0]
        assert np.allclose(rho, 1.0, atol=1e-3)


@pytest.mark.slow
def test_mc_tracer_sedov_follows_gas_mass():
    """Tracer radial distribution tracks the gas mass distribution on
    the refined blast (replaces the velocity-tracer stand-in)."""
    p = load_params("namelists/tracer_sedov.nml", ndim=2)
    p.run.tracer_per_cell = 2.0
    sim = AmrSim(p)
    sim.evolve(1e9, nstepmax=14)
    assert sim.tracer_x is not None and len(sim.tracer_x) > 500
    # gas: mass-weighted radius CDF over leaf cells of all levels
    r_gas, w_gas = [], []
    for l in sim.levels():
        cen, u = sim.leaf_sample(l)
        vol = sim.dx(l) ** 2
        r_gas.append(np.hypot(cen[:, 0] - 0.5, cen[:, 1] - 0.5))
        w_gas.append(u[:, 0] * vol)
    r_gas = np.concatenate(r_gas)
    w_gas = np.concatenate(w_gas)
    r_tr = np.hypot(sim.tracer_x[:, 0] - 0.5, sim.tracer_x[:, 1] - 0.5)
    # compare mass-weighted radius quantiles
    order = np.argsort(r_gas)
    cdf = np.cumsum(w_gas[order]) / w_gas.sum()
    for q in (0.25, 0.5, 0.75):
        gas_q = r_gas[order][np.searchsorted(cdf, q)]
        tr_q = np.quantile(r_tr, q)
        assert abs(tr_q - gas_q) < 0.035, (q, tr_q, gas_q)
