"""Stellar objects from sinks + their supernova feedback.

Reference: ``pm/stellar_particle.f90`` (make_stellar_from_sinks:1-84,
create_stellar:89-186, sample_powerlaw:234-264),
``pm/sink_sn_feedback.f90`` (make_sn_stellar:1-296), configured by
&STELLAR_PARAMS (``pm/read_sink_feedback_params.f90:15-21``).

Mechanics reproduced: every ``stellar_msink_th`` of mass a sink
accretes spawns one stellar object whose mass is drawn from a
power-law IMF on [imf_low, imf_high] and whose lifetime follows
``lt_t0·exp(lt_a·(ln(lt_m0/m))^lt_b)``; when an object outlives its
lifetime it explodes, injecting ``sn_e_ref`` of thermal energy into
its sink's surrounding cells with the reference's saturation
temperature cap (``Tsat``), then disappears.  Stellar objects are few
(one per ~100 Msun of sink growth): host-side numpy bookkeeping, like
the sinks they attach to.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class StellarSpec:
    """&STELLAR_PARAMS subset."""
    enabled: bool = False
    stellar_msink_th: float = 0.0    # sink-mass quantum per object [code]
    imf_index: float = -2.35         # Salpeter by default
    imf_low: float = 8.0             # massive-star window [Msun-like]
    imf_high: float = 120.0
    lt_t0: float = 0.0               # lifetime fit [code time]
    lt_m0: float = 148.16            # fit mass scale
    lt_a: float = 0.238
    lt_b: float = 2.0
    sn_e_ref: float = 0.0            # SN energy [code]
    sn_direct: bool = False          # explode at birth (testing mode)
    Tsat: float = 1e50               # post-injection temperature cap
    # sink RT (HII) feedback: the Vacca+96 ionizing-flux fit
    # S(M) = stf_K·(M/stf_m0)^a / (1+(M/stf_m0)^b)^c while the object
    # is younger than hii_t (pm/sink_feedback_parameters.f90:43-53);
    # hii_t <= 0 disables photon emission
    hii_t_myr: float = 0.0           # emitting lifetime [Myr]
    stf_k: float = 9.634642584812752e48   # photons/s
    stf_m0: float = 27.28098824280431     # Msun
    stf_a: float = 6.840015602892084
    stf_b: float = 4.353614230584390
    stf_c: float = 1.142166657042991
    fb_group: int = 0                # photon group receiving the flux

    @classmethod
    def from_params(cls, p) -> "StellarSpec":
        raw = p.raw.get("stellar_params", {}) if p.raw else {}

        def g(k, dflt):
            v = raw.get(k, dflt)
            return v[0] if isinstance(v, list) else v

        return cls(enabled=bool(raw),
                   stellar_msink_th=float(g("stellar_msink_th", 0.0)),
                   imf_index=float(g("imf_index", -2.35)),
                   imf_low=float(g("imf_low", 8.0)),
                   imf_high=float(g("imf_high", 120.0)),
                   lt_t0=float(g("lt_t0", 0.0)),
                   lt_m0=float(g("lt_m0", 148.16)),
                   lt_a=float(g("lt_a", 0.238)),
                   lt_b=float(g("lt_b", 2.0)),
                   sn_e_ref=float(g("sn_e_ref", 0.0)),
                   sn_direct=bool(g("sn_direct", False)),
                   Tsat=float(g("tsat", 1e50)),
                   hii_t_myr=float(g("hii_t", 0.0)),
                   stf_k=float(g("stf_k", cls.stf_k)),
                   stf_m0=float(g("stf_m0", cls.stf_m0)),
                   stf_a=float(g("stf_a", cls.stf_a)),
                   stf_b=float(g("stf_b", cls.stf_b)),
                   stf_c=float(g("stf_c", cls.stf_c)),
                   fb_group=int(g("feedback_photon_group", 1)) - 1)


def sample_powerlaw(rng: np.random.Generator, a: float, b: float,
                    alpha: float, n: int) -> np.ndarray:
    """n draws from p(x) ∝ x^alpha on [a, b] by inverse CDF
    (``sample_powerlaw``, stellar_particle.f90:234-264)."""
    u = rng.uniform(size=n)
    if abs(alpha + 1.0) < 1e-12:
        return a * (b / a) ** u
    p1 = alpha + 1.0
    return (a ** p1 + u * (b ** p1 - a ** p1)) ** (1.0 / p1)


def lifetime(m: np.ndarray, spec: StellarSpec) -> np.ndarray:
    """``lt_t0·exp(lt_a·(ln(lt_m0/m))^lt_b)`` (stellar_particle.f90:137)."""
    x = np.log(np.maximum(spec.lt_m0 / np.maximum(m, 1e-30), 1.0 + 1e-12))
    return spec.lt_t0 * np.exp(spec.lt_a * x ** spec.lt_b)


@dataclass
class StellarSet:
    """Host SoA of live stellar objects."""
    m: np.ndarray                    # IMF-sampled mass
    tform: np.ndarray
    tlife: np.ndarray
    x: np.ndarray                    # [n, ndim] (the sink position at birth)
    sink_idp: np.ndarray
    # persistent object ids (id_stellar): stable across SN removals so
    # consumers can track objects between outputs
    idp: np.ndarray = None
    next_id: int = 1
    # per-sink accreted-mass accumulator toward the next quantum
    # (``dmfsink``) — fed by the sink creation/accretion passes so
    # merger mass transfers are NOT double-counted as new accretion
    dmf: dict = field(default_factory=dict)

    @classmethod
    def empty(cls, ndim: int) -> "StellarSet":
        return cls(m=np.zeros(0), tform=np.zeros(0), tlife=np.zeros(0),
                   x=np.zeros((0, ndim)),
                   sink_idp=np.zeros(0, np.int64),
                   idp=np.zeros(0, np.int64))

    @property
    def n(self) -> int:
        return len(self.m)

    def add_accreted(self, sink_idp: int, dm: float):
        """Called by the sink passes for genuinely NEW mass (creation
        and gas accretion; merger transfers are excluded)."""
        self.dmf[int(sink_idp)] = self.dmf.get(int(sink_idp), 0.0) + dm


def make_stellar_from_sinks(sinks, stellar: StellarSet,
                            spec: StellarSpec,
                            rng: np.random.Generator, t: float):
    """Spawn one object per ``stellar_msink_th`` of NEW sink mass
    (make_stellar_from_sinks: the dmfsink accumulator loop)."""
    if spec.stellar_msink_th <= 0 or sinks.n == 0:
        return stellar
    live = {int(i) for i in sinks.idp}
    # drop accumulators of merged-away sinks (their already-credited
    # remainder dies with them, as in the reference's sink deletion)
    for sid in [k for k in stellar.dmf if k not in live]:
        del stellar.dmf[sid]
    for k in range(sinks.n):
        sid = int(sinks.idp[k])
        acc = stellar.dmf.get(sid, 0.0)
        nnew = int(acc / spec.stellar_msink_th)
        stellar.dmf[sid] = acc - nnew * spec.stellar_msink_th
        if nnew == 0:
            continue
        mnew = sample_powerlaw(rng, spec.imf_low, spec.imf_high,
                               spec.imf_index, nnew)
        tl = lifetime(mnew, spec)
        if spec.sn_direct:
            tl = np.zeros(nnew)
        stellar.m = np.concatenate([stellar.m, mnew])
        stellar.tform = np.concatenate([stellar.tform,
                                        np.full(nnew, t)])
        stellar.tlife = np.concatenate([stellar.tlife, tl])
        stellar.x = np.concatenate(
            [stellar.x, np.repeat(sinks.x[k:k + 1], nnew, axis=0)])
        stellar.sink_idp = np.concatenate(
            [stellar.sink_idp, np.full(nnew, sid, np.int64)])
        stellar.idp = np.concatenate(
            [stellar.idp,
             stellar.next_id + np.arange(nnew, dtype=np.int64)])
        stellar.next_id += nnew
    return stellar


def sn_from_stellar(sim, stellar: StellarSet, spec: StellarSpec):
    """Explode objects past their lifetime: inject ``sn_e_ref`` thermal
    energy into the containing cell at the finest covering level, with
    the ``Tsat`` cap of make_sn_stellar (sink_sn_feedback.f90:253-257);
    the object is then removed."""
    import jax.numpy as jnp

    from ramses_tpu.pm.amr_pm import assign_levels
    from ramses_tpu.pm.amr_physics import ngp_rows

    if stellar.n == 0 or spec.sn_e_ref <= 0:
        return stellar
    due = (sim.t - stellar.tform) >= stellar.tlife
    if not due.any():
        return stellar
    x = stellar.x[due]
    nd = sim.cfg.ndim
    gamma = float(sim.cfg.gamma)
    lv = assign_levels(sim.tree, x, sim.boxlen)
    for l in sim.levels():
        sel = lv == l
        if not sel.any():
            continue
        rows = ngp_rows(sim.tree, x[sel], l, sim.boxlen, sim.bc_kinds)
        ok = rows >= 0
        if not ok.any():
            continue
        r = rows[ok]
        vol = sim.dx(l) ** nd
        u = np.array(sim.u[l], dtype=np.float64)
        # energy density, capped so the cell stays below Tsat in T2
        # units (scale_T2 from the run's Units)
        ed = np.full(len(r), spec.sn_e_ref / vol)
        if sim.units is not None and spec.Tsat < 1e49:
            dgas = np.maximum(u[r, 0], 1e-300)
            ed_lim = (spec.Tsat / sim.units.scale_T2 * dgas
                      / (gamma - 1.0))
            ed = np.minimum(ed, ed_lim)
        np.add.at(u[:, 1 + nd], r, ed)
        sim.u[l] = jnp.asarray(u, sim.u[l].dtype)
    keep = ~due
    return StellarSet(m=stellar.m[keep], tform=stellar.tform[keep],
                      tlife=stellar.tlife[keep], x=stellar.x[keep],
                      sink_idp=stellar.sink_idp[keep],
                      idp=stellar.idp[keep], next_id=stellar.next_id,
                      dmf=stellar.dmf)
