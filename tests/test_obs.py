"""Fleet observability plane (ramses_tpu/obs): streaming results API,
Prometheus metrics, trace correlation, on-demand profiling.

Covers the PR 19 acceptance pins:

  * submit stamps a trace_id that survives requeue, stale reclaim and
    every failure_log entry;
  * /metrics renders valid Prometheus text on a live queue and the
    reconstructed counters are monotone;
  * the telemetry tail delivers every record exactly once across
    incremental writes and detects rotation;
  * a profile request is consumed exactly once at a chunk boundary and
    the trace dir becomes a manifest-validated artifact;
  * arming the whole plane against a drained queue performs ZERO
    device fetches;
  * one trace_id joins submit -> claim -> telemetry -> failure_log ->
    checkpoint manifest across a forced requeue (end-to-end).
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from ramses_tpu.ensemble import queue as jq
from ramses_tpu.obs import metrics as om
from ramses_tpu.obs.profile import (PROFILE_FLAG, ProfileRequestWatcher,
                                    request_profile)
from ramses_tpu.obs.server import MAX_TAIL_BYTES, ObsServer, tail_jsonl
from ramses_tpu.obs.trace import ENV_VAR, new_trace_id, worker_id
from ramses_tpu.resilience.checkpoint import (read_manifest_meta,
                                              validate_checkpoint,
                                              write_manifest)

pytestmark = pytest.mark.smoke

HEX32 = set("0123456789abcdef")


def _is_trace_id(s):
    return isinstance(s, str) and len(s) == 32 and set(s) <= HEX32


def _get(url, method="GET", data=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.getcode(), dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class _CapTel:
    """Telemetry stand-in capturing record_event calls."""

    def __init__(self):
        self.events = []

    def record_event(self, kind, **fields):
        self.events.append((kind, fields))


# ---------------------------------------------------------------------
# trace correlation (no jax)
# ---------------------------------------------------------------------
def test_submit_stamps_trace_id(tmp_path, monkeypatch):
    monkeypatch.delenv(ENV_VAR, raising=False)
    q = str(tmp_path / "q")
    jid = jq.submit(q, "&RUN_PARAMS\n/")
    rec = jq.job_status(q, jid).record
    assert _is_trace_id(rec["trace_id"])
    # two submits never share an id
    jid2 = jq.submit(q, "&RUN_PARAMS\n/")
    assert jq.job_status(q, jid2).record["trace_id"] != rec["trace_id"]
    # a parent pipeline pre-correlates children through the env var
    monkeypatch.setenv(ENV_VAR, "cafe" * 8)
    assert new_trace_id() == "cafe" * 8
    jid3 = jq.submit(q, "&RUN_PARAMS\n/")
    assert jq.job_status(q, jid3).record["trace_id"] == "cafe" * 8
    assert ":" in worker_id()


def test_trace_id_survives_requeue_and_reclaim(tmp_path):
    q = str(tmp_path / "q")
    jid = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-trace")
    tid = jq.job_status(q, jid).record["trace_id"]
    tel = _CapTel()

    job = jq.claim(q, worker="w1")
    jq.requeue(job, error="boom", telemetry=tel)
    job = jq.claim(q, worker="w2")
    jq._age_heartbeat(job.path, 3600.0)
    assert jq.reclaim_stale(q, stale_s=300.0, max_attempts=3,
                            log=None, telemetry=tel) == 1
    job = jq.claim(q, worker="w3")
    jq.fail(job, error="gave up", telemetry=tel)

    rec = jq.job_status(q, jid).record
    assert rec["trace_id"] == tid
    stages = [e["stage"] for e in rec["failure_log"]]
    assert stages == ["requeue", "stale", "fail"]
    assert all(e["trace_id"] == tid for e in rec["failure_log"])
    # the queue lifecycle events carry the id too
    kinds = [k for k, _ in tel.events]
    assert kinds == ["queue_requeue", "queue_reclaim", "queue_fail"]
    assert all(f["trace_id"] == tid for _, f in tel.events)


# ---------------------------------------------------------------------
# metrics (no jax)
# ---------------------------------------------------------------------
def _synthetic_queue(tmp_path):
    q = str(tmp_path / "q")
    jid_done = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-done")
    job = jq.claim(q, worker="w1")
    jq.complete(job, result={
        "queue_wait_s": 1.5, "scenarios_per_device_s": 4.0,
        "compile_cache_hits": 3, "compile_cache_misses": 1,
        "cell_updates": 4096, "partial": True,
        "failed_members": [1], "nmember": 2})
    jid_run = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-run")
    running = jq.claim(q, worker="w2")
    jid_fail = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-fail")
    job = jq.claim(q, worker="w3")
    jq.requeue(job, error="flaky")
    job = jq.claim(q, worker="w3")
    jq.fail(job, error="dead")
    jq.submit(q, "&RUN_PARAMS\n/", job_id="job-waiting")
    # a worker sink whose mtime is the liveness signal
    wdir = os.path.join(q, om.WORKERS_DIR)
    os.makedirs(wdir, exist_ok=True)
    with open(os.path.join(wdir, "w2.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "serve_start", "worker": "w2"}) + "\n")
        f.write(json.dumps({"kind": "gang_schedule", "jobs": 2,
                            "busy_frac": 0.75}) + "\n")
    return q, (jid_done, jid_run, jid_fail), running


def test_metrics_roundtrip_and_monotonic(tmp_path):
    q, _ids, running = _synthetic_queue(tmp_path)
    text = om.render_queue_metrics(q)
    assert "# HELP ramses_queue_jobs" in text
    assert "# TYPE ramses_queue_jobs gauge" in text
    m = om.parse(text)

    def val(name, **labels):
        return m[(name, tuple(sorted(labels.items())))]

    assert val("ramses_queue_jobs", state="queued") == 1
    assert val("ramses_queue_jobs", state="running") == 1
    assert val("ramses_queue_jobs", state="done") == 1
    assert val("ramses_queue_jobs", state="failed") == 1
    assert val("ramses_job_attempts_total") == 4   # 1 + 1 + 2
    assert val("ramses_failure_events_total", stage="requeue") == 1
    assert val("ramses_failure_events_total", stage="fail") == 1
    assert val("ramses_quarantined_members_total") == 1
    assert val("ramses_jobs_partial_total") == 1
    assert val("ramses_compile_cache_hits_total") == 3
    assert val("ramses_compile_cache_misses_total") == 1
    assert val("ramses_cell_updates_total") == 4096
    assert val("ramses_queue_wait_seconds_sum") == 1.5
    assert val("ramses_queue_wait_seconds_count") == 1
    assert val("ramses_scenarios_per_device_seconds") == 4.0
    assert val("ramses_job_heartbeat_age_seconds", job="job-run") >= 0
    assert val("ramses_worker_heartbeat_age_seconds", worker="w2") >= 0
    assert val("ramses_gang_busy_frac", worker="w2") == 0.75

    # counters reconstructed from durable records are monotone: more
    # failures can only raise them
    jq.requeue(running, error="flaky too")
    m2 = om.parse(om.render_queue_metrics(q))
    for key, v in m.items():
        name = key[0]
        if name.endswith("_total") or name.endswith("_sum") \
                or name.endswith("_count"):
            assert m2.get(key, 0.0) >= v, key
    assert m2[("ramses_failure_events_total",
               (("stage", "requeue"),))] == 2


def test_metrics_label_escaping():
    fam = om.Family("x_total", "counter", "h")
    fam.add(1, job='we"ird\\name')
    text = om.render([fam])
    parsed = om.parse(text)
    assert parsed[("x_total", (("job", 'we"ird\\name'),))] == 1.0


# ---------------------------------------------------------------------
# HTTP server (no jax)
# ---------------------------------------------------------------------
def test_obs_endpoints(tmp_path):
    q, (jid_done, jid_run, _), _run = _synthetic_queue(tmp_path)
    srv = ObsServer(q, port=0).start()
    try:
        code, _h, body = _get(srv.url + "/healthz")
        health = json.loads(body)
        assert code == 200 and health["ok"] and health["mode"] == "queue"
        assert health["queue"]["done"] == 1

        code, h, body = _get(srv.url + "/metrics")
        assert code == 200
        assert h["Content-Type"].startswith("text/plain; version=0.0.4")
        assert ("ramses_queue_jobs",
                (("state", "done"),)) in om.parse(body.decode())

        code, _h, body = _get(srv.url + "/jobs")
        jobs = {j["id"]: j for j in json.loads(body)["jobs"]}
        assert code == 200 and len(jobs) == 4
        assert jobs[jid_done]["state"] == "done"
        assert jobs[jid_done]["quarantined"] == 1
        assert _is_trace_id(jobs[jid_run]["trace_id"])

        code, _h, body = _get(srv.url + f"/jobs/{jid_done}")
        rec = json.loads(body)
        assert code == 200 and rec["state"] == "done"
        assert rec["result"]["nmember"] == 2

        assert _get(srv.url + "/jobs/nope")[0] == 404
        assert _get(srv.url + "/jobs/bad%20id")[0] == 400
        assert _get(srv.url + "/nothing")[0] == 404
    finally:
        srv.close()


def test_artifacts_listing_and_range(tmp_path):
    q, (jid_done, _, _), _run = _synthetic_queue(tmp_path)
    rdir = jq.results_dir(q, jid_done)
    ckpt = os.path.join(rdir, "ckpt_000004")
    os.makedirs(ckpt)
    with open(os.path.join(ckpt, "state.bin"), "wb") as f:
        f.write(b"0123456789")
    write_manifest(ckpt, meta={"kind": "ensemble", "trace_id": "t" * 32})
    with open(os.path.join(rdir, "run.nml"), "w") as f:
        f.write("&RUN_PARAMS\n/\n")
    os.makedirs(os.path.join(rdir, "staging"))   # manifest-less: hidden

    srv = ObsServer(q, port=0).start()
    try:
        code, _h, body = _get(srv.url + f"/jobs/{jid_done}/artifacts")
        art = json.loads(body)
        assert code == 200
        assert [d["name"] for d in art["checkpoints"]] == ["ckpt_000004"]
        d = art["checkpoints"][0]
        assert d["valid"] and d["meta"]["trace_id"] == "t" * 32
        assert {f["path"] for f in d["files"]} == {
            "ckpt_000004/state.bin", "ckpt_000004/manifest.json"}
        assert {f["path"] for f in art["files"]} == {"run.nml"}

        url = srv.url + f"/jobs/{jid_done}/artifacts/ckpt_000004/state.bin"
        code, _h, body = _get(url)
        assert (code, body) == (200, b"0123456789")
        req = urllib.request.Request(url)
        req.add_header("Range", "bytes=2-5")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.getcode() == 206 and r.read() == b"2345"
            assert r.headers["Content-Range"] == "bytes 2-5/10"
        req = urllib.request.Request(url)
        req.add_header("Range", "bytes=-3")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.getcode() == 206 and r.read() == b"789"
        req = urllib.request.Request(url)
        req.add_header("Range", "bytes=10-")
        assert _get_req(req)[0] == 416
        assert _get(srv.url + f"/jobs/{jid_done}/artifacts/none")[0] == 404
        # traversal out of the results dir is refused at resolution
        assert srv.artifact_file(jid_done, "../../queued") is None
        assert srv.artifact_file(
            jid_done, "../" + jid_done + "/run.nml") is not None
    finally:
        srv.close()


def _get_req(req):
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.getcode(), r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_telemetry_tail_exactly_once(tmp_path):
    q = str(tmp_path / "q")
    jid = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-tail")
    rdir = jq.results_dir(q, jid)
    os.makedirs(rdir, exist_ok=True)
    path = os.path.join(rdir, "telemetry.jsonl")

    srv = ObsServer(q, port=0).start()
    try:
        # no file yet: 204 with a resumable zero offset
        code, h, body = _get(srv.url + f"/jobs/{jid}/telemetry")
        assert code == 204 and h["X-Telemetry-Offset"] == "0"

        lines = [json.dumps({"kind": "step", "nstep": i}) + "\n"
                 for i in range(5)]
        with open(path, "w") as f:
            f.write("".join(lines[:2]))
        code, h, body = _get(srv.url + f"/jobs/{jid}/telemetry?offset=0")
        assert code == 200 and h["X-Telemetry-Records"] == "2"
        off = int(h["X-Telemetry-Offset"])
        assert body.decode() == "".join(lines[:2]) and off > 0

        # a torn (unterminated) line is withheld until complete
        with open(path, "a") as f:
            f.write(lines[2] + '{"kind": "ste')
        code, h, body = _get(srv.url
                             + f"/jobs/{jid}/telemetry?offset={off}")
        assert body.decode() == lines[2]
        assert "X-Telemetry-Rotated" not in h
        off = int(h["X-Telemetry-Offset"])
        with open(path, "a") as f:
            f.write('p"}\n' + lines[3])
        code, h, body = _get(srv.url
                             + f"/jobs/{jid}/telemetry?offset={off}")
        assert body.decode() == '{"kind": "step"}\n' + lines[3]
        off = int(h["X-Telemetry-Offset"])

        # rotation (a fresh attempt truncated the file): offset beyond
        # EOF restarts from 0 and says so
        with open(path, "w") as f:
            f.write(lines[4])
        code, h, body = _get(srv.url
                             + f"/jobs/{jid}/telemetry?offset={off}")
        assert h.get("X-Telemetry-Rotated") == "1"
        assert body.decode() == lines[4]

        assert _get(srv.url + f"/jobs/{jid}/telemetry?offset=x")[0] == 400
    finally:
        srv.close()


def test_tail_jsonl_respects_max_bytes(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        for i in range(100):
            f.write(json.dumps({"i": i, "pad": "x" * 64}) + "\n")
    assert MAX_TAIL_BYTES >= 1 << 20
    seen, off = [], 0
    while True:
        data, off, rot = tail_jsonl(path, off, max_bytes=256)
        assert not rot
        if not data:
            break
        seen.extend(json.loads(ln)["i"]
                    for ln in data.decode().splitlines())
    assert seen == list(range(100))   # exactly once, in order


# ---------------------------------------------------------------------
# on-demand profiling (fake capture hook; no jax profiler)
# ---------------------------------------------------------------------
class _FakeProfile:
    opened = []

    def __init__(self, outdir):
        self.outdir = outdir

    def __enter__(self):
        os.makedirs(self.outdir, exist_ok=True)
        with open(os.path.join(self.outdir, "trace.pb"), "wb") as f:
            f.write(b"fake-trace")
        _FakeProfile.opened.append(self.outdir)
        return self

    def __exit__(self, *exc):
        return False


def test_profile_watcher_chunk_boundary(tmp_path, monkeypatch):
    monkeypatch.setattr(ProfileRequestWatcher, "_profile_cm",
                        staticmethod(_FakeProfile))
    _FakeProfile.opened = []
    rdir = str(tmp_path / "results")
    tel = _CapTel()
    w = ProfileRequestWatcher(rdir)
    w.poll(tel)                       # no request pending: no-op
    assert not w.active and tel.events == []

    flag = request_profile(rdir, chunks=2)
    assert os.path.basename(flag) == PROFILE_FLAG
    w.poll(tel)                       # chunk boundary: capture opens
    assert w.active and not os.path.exists(flag)   # consumed once
    assert tel.events[-1][0] == "profile_start"
    assert tel.events[-1][1]["chunks"] == 2
    w.poll(tel)                       # armed chunk 1 of 2
    assert w.active
    w.poll(tel)                       # chunk 2: capture closes
    assert not w.active
    assert tel.events[-1][0] == "profile_captured"
    tdir = tel.events[-1][1]["trace_dir"]
    assert _FakeProfile.opened == [tdir]
    # the trace dir is a manifest-validated artifact
    ok, why = validate_checkpoint(tdir, verify_hash=True)
    assert ok, why
    assert read_manifest_meta(tdir)["kind"] == "profile"
    # one request = one capture: nothing re-arms
    w.poll(tel)
    assert not w.active and len(_FakeProfile.opened) == 1


def test_profile_stop_closes_midflight_capture(tmp_path, monkeypatch):
    monkeypatch.setattr(ProfileRequestWatcher, "_profile_cm",
                        staticmethod(_FakeProfile))
    rdir = str(tmp_path / "results")
    w = ProfileRequestWatcher(rdir)
    request_profile(rdir, chunks=100)
    w.poll()
    assert w.active
    w.stop()                          # job ended mid-capture
    assert not w.active
    assert validate_checkpoint(w.trace_dir, verify_hash=False)[0]


def test_profile_post_arms_flag(tmp_path):
    q, (jid_done, _, _), _run = _synthetic_queue(tmp_path)
    srv = ObsServer(q, port=0).start()
    try:
        code, _h, body = _get(srv.url + f"/jobs/{jid_done}/profile",
                              method="POST",
                              data=json.dumps({"chunks": 3}).encode())
        assert code == 202 and json.loads(body)["armed"]
        flag = os.path.join(jq.results_dir(q, jid_done), PROFILE_FLAG)
        with open(flag) as f:
            assert json.load(f)["chunks"] == 3
        assert _get(srv.url + f"/jobs/{jid_done}/profile?chunks=x",
                    method="POST")[0] == 400
    finally:
        srv.close()


# ---------------------------------------------------------------------
# worker sink + heartbeat sidecar
# ---------------------------------------------------------------------
def test_serve_idle_worker_sink(tmp_path):
    from ramses_tpu.ensemble.service import serve
    q = str(tmp_path / "q")
    counts = serve(q, worker="idle:w", idle_exit=True,
                   log=lambda *a: None)
    assert counts == {"done": 0, "failed": 0, "requeued": 0}
    path = os.path.join(q, om.WORKERS_DIR, "idle_w.jsonl")
    recs = [json.loads(ln) for ln in open(path)]
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "run_header"
    assert "serve_start" in kinds and "serve_exit" in kinds
    idle = next(r for r in recs if r["kind"] == "serve_idle")
    assert idle["exiting"] and idle["queued"] == 0
    # every record is stamped with the worker identity (bind())
    assert all(r.get("worker") == "idle:w" for r in recs)


def test_bench_heartbeat_from_env_trace(tmp_path, monkeypatch):
    from ramses_tpu.telemetry.heartbeat import Heartbeat
    hb_path = str(tmp_path / "hb.jsonl")
    monkeypatch.setenv("BENCH_HEARTBEAT_PATH", hb_path)
    monkeypatch.setenv(ENV_VAR, "beef" * 8)
    hb = Heartbeat.from_env()
    hb.mark("lower", name="sedov3d")
    rec = json.loads(open(hb_path).read().splitlines()[-1])
    assert rec["trace_id"] == "beef" * 8
    assert ":" in rec["worker_id"]
    assert rec["phase"] == "lower" and rec["name"] == "sedov3d"


# ---------------------------------------------------------------------
# report tooling
# ---------------------------------------------------------------------
def test_telemetry_report_service_offload_sections(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import telemetry_report
    recs = [
        {"kind": "run_header", "schema_version": 3, "time_unix": 100.0,
         "trace_id": "ab" * 16, "job": "job-x", "worker": "w1",
         "run_info": {"driver": "ensemble", "ndev": 8, "nmember": 4}},
        {"kind": "gang_schedule", "jobs": 2, "busy_devices": 6,
         "ndev": 8, "busy_frac": 0.75},
        {"kind": "serve_idle", "queued": 1, "running": 2, "done": 3,
         "failed": 0},
        {"kind": "job_summary", "queue_wait_s": 2.5,
         "scenarios_per_device_s": 1.25, "busy_frac": 0.75,
         "nmember": 4, "compile_cache_hits": 7},
        {"kind": "run_footer", "wall_s": 9.0, "offload_stalls": 2,
         "offload_prefetches": 11, "offload_overlap_frac": 0.8,
         "offload_bytes_parked": 1024},
    ]
    md = telemetry_report.render(recs)
    assert "| trace_id | " + "ab" * 16 in md
    assert "## Service" in md
    assert "| queue_wait_s | 2.5 |" in md
    assert "| scenarios_per_device_s | 1.25 |" in md
    assert "busy_frac=0.75" in md
    assert "idle beats | 1" in md and "queued=1" in md
    assert "## Offload" in md
    assert "| offload_stalls | 2 |" in md


def test_trace_report_timeline(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace_report
    q = str(tmp_path / "q")
    jid = jq.submit(q, "&RUN_PARAMS\n/", job_id="job-span")
    tid = jq.job_status(q, jid).record["trace_id"]
    job = jq.claim(q, worker="w1")
    jq.requeue(job, error="first try")
    job = jq.claim(q, worker="w2")
    rdir = jq.results_dir(q, jid)
    os.makedirs(rdir, exist_ok=True)
    t0 = job.record["claimed_unix"]
    with open(os.path.join(rdir, "telemetry.jsonl"), "w") as f:
        for rec in [
                {"kind": "run_header", "time_unix": t0, "trace_id": tid},
                {"kind": "ensemble_chunk", "nstep_max": 2, "wall_s": 1.0},
                {"kind": "ensemble_chunk", "nstep_max": 4, "wall_s": 2.5},
                {"kind": "ensemble_done"}]:
            f.write(json.dumps(rec) + "\n")
    ckpt = os.path.join(rdir, "ckpt_000004")
    os.makedirs(ckpt)
    write_manifest(ckpt, meta={"kind": "ensemble", "trace_id": tid})
    jq.complete(job, result={"ok": True})

    md = trace_report.render(
        trace_report._find_record(q, jid),
        trace_report._load_jsonl(os.path.join(rdir, "telemetry.jsonl")),
        trace_report._manifest_traces(rdir))
    assert f"`{tid}`" in md
    assert "queue wait" in md and "## Timeline" in md
    assert "a1 chunk -> nstep 2 (incl. compile)" in md
    assert "a1 chunk -> nstep 4" in md
    assert "continuity: one id across 3 source(s)" in md
    assert "requeue (attempt 1)" in md
    # a foreign manifest id flips the audit to a mismatch
    write_manifest(ckpt, meta={"kind": "ensemble", "trace_id": "f" * 32})
    md = trace_report.render(
        trace_report._find_record(q, jid),
        trace_report._load_jsonl(os.path.join(rdir, "telemetry.jsonl")),
        trace_report._manifest_traces(rdir))
    assert "TRACE MISMATCH" in md


# ---------------------------------------------------------------------
# end-to-end: one trace id across a forced requeue (jax, 2D hydro)
# ---------------------------------------------------------------------
SERVICE_NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "nstepmax=4", "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=4", "boxlen=1.0", "/",
    "&INIT_PARAMS", "nregion=2",
    "region_type(1)='square'", "region_type(2)='point'",
    "x_center=0.5,0.5", "y_center=0.5,0.5",
    "length_x=10.0,1.0", "length_y=10.0,1.0",
    "exp_region=10.0,10.0", "d_region=1.0,0.0", "p_region=1e-5,0.1", "/",
    "&HYDRO_PARAMS", "gamma=1.4", "riemann='hllc'", "/",
    "&OUTPUT_PARAMS", "tend=1e9", "/",
    "&ENSEMBLE_PARAMS", "nmember=2", "perturb_amp=0.01",
    "chunk_steps=2", "/",
])


def test_end_to_end_trace_joins_all_artifacts(tmp_path):
    from ramses_tpu.ensemble.service import serve
    q = str(tmp_path / "q")
    jid = jq.submit(q, SERVICE_NML, ndim=2, dtype="float64")
    tid = jq.job_status(q, jid).record["trace_id"]
    assert _is_trace_id(tid)

    # force one failed attempt before the real run: claim + requeue
    job = jq.claim(q, worker="flaky")
    jq.requeue(job, error="injected: worker evicted")

    counts = serve(q, worker="steady", idle_exit=True, max_attempts=3,
                   log=lambda *a: None)
    assert counts["done"] == 1

    job = jq.job_status(q, jid)
    assert job.state == "done"
    rec = job.record
    assert rec["trace_id"] == tid
    assert [e["stage"] for e in rec["failure_log"]] == ["requeue"]
    assert rec["failure_log"][0]["trace_id"] == tid

    # every telemetry record carries the bound id
    res = rec["result"]
    recs = [json.loads(ln) for ln in open(res["telemetry"])]
    assert recs and all(r.get("trace_id") == tid for r in recs)
    assert all(r.get("job") == jid for r in recs)
    kinds = [r["kind"] for r in recs]
    assert "run_header" in kinds and "job_summary" in kinds
    summary = next(r for r in recs if r["kind"] == "job_summary")
    assert summary["queue_wait_s"] >= 0
    assert summary["scenarios_per_device_s"] > 0

    # the checkpoint manifest meta carries it too
    meta = read_manifest_meta(res["snapshot"])
    assert meta["trace_id"] == tid and meta["job"] == jid

    # serve produced the worker sink with lifecycle events
    wpath = os.path.join(q, om.WORKERS_DIR, "steady.jsonl")
    wkinds = [json.loads(ln)["kind"] for ln in open(wpath)]
    assert "serve_start" in wkinds and "serve_exit" in wkinds

    # ---- zero-added-device-fetch pin: arm the whole plane against
    # this live queue dir and count device transfers
    import jax
    fetches = {"n": 0}
    real = jax.device_get

    def counting(x):
        fetches["n"] += 1
        return real(x)

    srv = ObsServer(q, port=0).start()
    try:
        jax.device_get = counting
        assert _get(srv.url + "/healthz")[0] == 200
        assert _get(srv.url + "/metrics")[0] == 200
        assert _get(srv.url + "/jobs")[0] == 200
        assert _get(srv.url + f"/jobs/{jid}")[0] == 200
        assert _get(srv.url + f"/jobs/{jid}/telemetry")[0] == 200
        assert _get(srv.url + f"/jobs/{jid}/artifacts")[0] == 200
    finally:
        jax.device_get = real
        srv.close()
    assert fetches["n"] == 0

    # the scrape sees the forced requeue and the completed job
    m = om.parse(om.render_queue_metrics(q))
    assert m[("ramses_failure_events_total",
              (("stage", "requeue"),))] == 1
    assert m[("ramses_queue_jobs", (("state", "done"),))] == 1


def test_results_mode_serves_single_run(tmp_path):
    """Pointed at a plain output dir the server exposes pseudo-job
    ``run`` (covers ``&OUTPUT_PARAMS obs_port`` on a solo run)."""
    out = str(tmp_path / "out")
    os.makedirs(out)
    with open(os.path.join(out, "run.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "run_header"}) + "\n")
    srv = ObsServer(out, port=0).start()
    try:
        code, _h, body = _get(srv.url + "/healthz")
        assert code == 200 and json.loads(body)["mode"] == "results"
        code, _h, body = _get(srv.url + "/jobs")
        assert [j["id"] for j in json.loads(body)["jobs"]] == ["run"]
        code, h, body = _get(srv.url + "/jobs/run/telemetry")
        assert code == 200 and h["X-Telemetry-Records"] == "1"
        code, _h, body = _get(srv.url + "/metrics")
        assert b"ramses_obs_results_mode 1" in body
    finally:
        srv.close()
