"""Phase-marker heartbeats for subprocess tools (bench, multichip).

A heartbeat is an append-only JSONL sidecar the CHILD process writes
one line to at every phase boundary; when the PARENT's hard timeout
fires, the sidecar's last line says exactly where the child hung —
turning BENCH_r05's four indistinguishable "sub-bench timed out"
errors into ``phase_at_timeout: "backend init"`` diagnoses.

Deliberately stdlib-only and side-effect free at import: the bench
parent never imports jax, and ``ramses_tpu/__init__`` may pull jax in
(compile-cache setup), so jax-free parents read the format with their
own three-line loader (see ``bench.py``) while children and tools use
this module.  Writes are single ``write()`` calls of one line, flushed
— a reader never sees a torn record.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional


class Heartbeat:
    """Append phase markers to ``path``; no-op when ``path`` is falsy.

    ``context`` keyword fields (``trace_id``, ``worker_id``, ...) are
    merged into every marker, so a sidecar is joinable with the rest
    of a trace's records (worker telemetry, failure logs) by one id.
    """

    def __init__(self, path: Optional[str], **context: Any):
        self.path = path or ""
        self.context = {k: v for k, v in context.items() if v}
        self._t0 = time.monotonic()

    @classmethod
    def from_env(cls, var: str = "BENCH_HEARTBEAT_PATH",
                 trace_var: str = "RAMSES_TRACE_ID") -> "Heartbeat":
        """Sidecar path from the parent's env; when the parent also
        exported a trace id (bench does since the obs plane landed),
        every marker carries it plus this child's host:pid."""
        ctx: Dict[str, Any] = {}
        trace_id = os.environ.get(trace_var, "").strip()
        if trace_id:
            ctx["trace_id"] = trace_id
            ctx["worker_id"] = f"{os.uname().nodename}:{os.getpid()}"
        return cls(os.environ.get(var, ""), **ctx)

    def mark(self, phase: str, **fields: Any):
        if not self.path:
            return
        rec = {"phase": str(phase),
               "t_s": round(time.monotonic() - self._t0, 3)}
        rec.update(self.context)
        rec.update(fields)
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
        except OSError:
            pass                    # a full disk must not kill the bench


def read_phases(path: str) -> List[Dict[str, Any]]:
    """All phase markers in the sidecar (unparsable lines skipped)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def last_phase(path: str) -> Optional[Dict[str, Any]]:
    """The most recent phase marker, or None."""
    phases = read_phases(path)
    return phases[-1] if phases else None
