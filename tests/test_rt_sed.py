"""Stellar SED tables + homogeneous UV background (rt/rt_spectra.f90,
rt_UV_hom) — VERDICT r3 item 7."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.rt.sed import (SedTables, blackbody_library, read_sed_dir, write_sed_dir)



pytestmark = pytest.mark.smoke

def _lib():
    # young stars hot (1e5 K), old stars cool (1.2e4 K)
    t_of_age = lambda a: 1e5 / (1.0 + 80.0 * a)
    return blackbody_library(t_of_age,
                             ages_gyr=np.array([0.0, 0.01, 0.1, 1.0, 10.0]),
                             zs=np.array([0.001, 0.02]))


def test_sed_dir_roundtrip(tmp_path):
    lib = _lib()
    d = str(tmp_path / "seds")
    write_sed_dir(d, lib)
    back = read_sed_dir(d)
    np.testing.assert_allclose(back.lam_A, lib.lam_A)
    np.testing.assert_allclose(back.ages_gyr, lib.ages_gyr, rtol=1e-6)
    np.testing.assert_allclose(back.zs, lib.zs, rtol=1e-6)
    np.testing.assert_allclose(back.seds, lib.seds)


def test_cross_sections_change_with_age():
    """The chemistry's group cross-sections must depend on source age
    (the whole point of SED tables vs a fixed blackbody)."""
    tab = SedTables(_lib(), (13.6, 1e3))
    young = tab.population_groups([0.0], [0.02], [1.0])[0]
    old = tab.population_groups([1.0], [0.02], [1.0])[0]
    # cooler old SED: ionizing photons pile up just above threshold,
    # where sigma_HI is largest
    assert old.sigmaN[0] > 1.2 * young.sigmaN[0]
    assert old.e_photon < young.e_photon
    # and the ionizing luminosity collapses with age
    r_young = tab.star_rates([0.0], [0.02], [1.0])[0, 0]
    r_old = tab.star_rates([1.0], [0.02], [1.0])[0, 0]
    assert r_old < 0.1 * r_young


def test_population_weighting():
    tab = SedTables(_lib(), (13.6, 24.59, 1e3))
    g_y = tab.population_groups([0.0], [0.02], [1.0])
    g_o = tab.population_groups([1.0], [0.02], [1.0])
    g_mix = tab.population_groups([0.0, 1.0], [0.02, 0.02], [1.0, 1.0])
    assert abs(sum(g.frac for g in g_mix) - 1.0) < 1e-12
    for g in range(2):
        lo = min(g_y[g].sigmaN[0], g_o[g].sigmaN[0])
        hi = max(g_y[g].sigmaN[0], g_o[g].sigmaN[0])
        assert lo <= g_mix[g].sigmaN[0] <= hi


def test_stellar_injection_amr(tmp_path):
    """A star particle with SED tables becomes a photon source and the
    population refresh rewires the chemistry's group properties."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import Params
    from ramses_tpu.pm.particles import FAM_STAR, ParticleSet

    d = str(tmp_path / "seds")
    write_sed_dir(d, _lib())
    p = Params(ndim=2)
    p.run.rt = True
    p.run.pic = False
    p.amr.levelmin, p.amr.levelmax = 4, 4
    p.init.nregion = 1
    p.init.region_type = ["square"]
    p.init.x_center, p.init.y_center = [0.5], [0.5]
    p.init.length_x, p.init.length_y = [10.0], [10.0]
    p.init.exp_region = [10.0]
    p.init.d_region, p.init.p_region = [1.0], [1e-4]
    p.init.u_region, p.init.v_region = [0.0], [0.0]
    p.rt.rt_ngroups = 3
    p.rt.rt_y_he = 0.25
    p.rt.sed_dir = d
    p.rt.sedprops_update = 1
    import dataclasses
    ps = ParticleSet.make(
        jnp.asarray([[0.5, 0.5]]), jnp.zeros((1, 2)),
        jnp.asarray([1e-3]), family=np.array([FAM_STAR]))
    ps = dataclasses.replace(ps, tp=jnp.asarray([-0.01]),
                             zp=jnp.asarray([0.02]))
    sim = AmrSim(p, particles=ps)
    assert sim.rt_amr is not None and sim.rt_amr.sed is not None
    n0 = {l: np.asarray(sim.rt_amr.rad[l][:, 0]).sum()
          for l in sim.levels()}
    # drive the RT advance directly with a dt under one reduced-light
    # crossing time (code units have scale 1 here, so any hydro-scale
    # dt would imply tens of thousands of RT substeps)
    sim.rt_amr.advance(sim, 1e-10)
    # photons were injected somewhere
    grew = any(np.asarray(sim.rt_amr.rad[l][:, 0]).sum() > n0[l] * 1.001
               for l in sim.levels())
    assert grew
    # group props refreshed to the (single-star) population values
    tab = sim.rt_amr.sed
    want = tab.population_groups(
        [max(sim.t - (-0.01), 0.0) * sim.rt_amr.un.scale_t / 3.15576e16],
        [0.02], [np.asarray(ps.m)[0] * sim.rt_amr.un.scale_d
                 * sim.rt_amr.un.scale_l ** 2 / 1.989e33])
    got = sim.rt_amr.spec.groups3
    assert got[0].sigmaN[0] == pytest.approx(want[0].sigmaN[0], rel=0.3)


def test_uv_background_shifts_equilibrium():
    """rt_UV_hom: the homogeneous UV photoionization raises the
    equilibrium ionized fraction of optically thin gas."""
    from ramses_tpu.hydro.cooling import uv_rates
    from ramses_tpu.rt import chem

    g, h = uv_rates(1.0, 1.0)
    uv = ((g["HI"], g["HeI"], g["HeII"]),
          (h["HI"], h["HeI"], h["HeII"]))
    nH = jnp.full((8,), 1e-4)
    T = jnp.full((8,), 1e4)
    N = jnp.full((8,), 1e-12)          # no local radiation
    x = jnp.full((8,), 1e-3)
    spec = chem.GroupSpec()
    for _ in range(200):
        N1, x_uv, T1 = chem.chem_step(N, x, T, nH, 3e11, 3e8, spec,
                                      uv=uv)
        x = x_uv
    x0 = jnp.full((8,), 1e-3)
    for _ in range(200):
        _, x0, _ = chem.chem_step(N, x0, T, nH, 3e11, 3e8, spec)
    assert float(x[0]) > 10 * float(x0[0])
    # analytic check: x/(1-x)^... Gamma = alpha_B ne x at equilibrium
    gam = g["HI"]
    ne = nH[0] * x[0]
    bal = gam * (1 - x[0]) / (float(chem.alpha_B(T[0])) * ne * x[0])
    assert 0.5 < float(bal) < 2.0
