"""Flat↔dense conversion for COMPLETE levels as a bit-permutation
reshape/transpose — no gather.

A complete level's flat row order is (sorted-Morton oct index) × (cell
offset): the sorted Morton keys of a full oct grid are simply
0..noct-1, so the flat cell index is a fixed *bit permutation* of the
dense C-order ravel index::

    flat bits (MSB→LSB):  [z_{l-1} y_{l-1} x_{l-1}] … [z_1 y_1 x_1] [x_0 y_0 z_0]
    dense bits (MSB→LSB): [x_{l-1} … x_0] [y_{l-1} … y_0] [z_{l-1} … z_0]

(x_k = bit k of the cell's x coordinate; the oct Morton triplets carry
coordinate bits 1..l-1 with z most significant per triplet —
``amr/keys.py`` ``encode`` — and the within-oct offset carries bit 0
with x slowest — ``amr/tree.py`` ``cell_offsets``.)

A gather by this permutation moves one ~nvar-float row per index: on
TPU that lowers to millions of latency-bound small copies and was the
dominant cost of the steady-state AMR step (BENCH_CAPTURED_r04).  A
reshape to ``(2,)*ndim*lvl`` axes + transpose expresses the same data
movement with static regular strides that XLA vectorizes.

Only valid for cubic complete levels (2^lvl cells per dim); callers
fall back to the index-permutation gather otherwise (non-cubic roots).

Slab (shard-local) variant: fixing the top ``mbits`` flat index bits
selects one contiguous flat row chunk of ``ncell / 2^mbits`` rows — a
device's shard under the equal row-split ``P("oct")`` sharding.  The
remaining bits are a bit permutation of a DENSE SUB-BOX: the fixed top
bits are the most significant coordinate bits (z-major interleave), so
chunk ``D`` is the axis-aligned box whose per-axis origin is the
device-grid coordinate × the local extent.  Each shard can therefore
run the same reshape→transpose→reshape on only the rows it owns — no
cross-device data motion at all (:mod:`ramses_tpu.parallel.dense_slab`
builds the halo exchange separately).  ``mbits`` must not reach into
the within-oct bits (``mbits <= ndim*(lvl-1)``) so every chunk cut
lands on an oct boundary.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp


@lru_cache(maxsize=None)
def _bit_seq(lvl: int, ndim: int) -> tuple:
    """The flat index's bit slots MSB→LSB as (axis, coord_bit) pairs:
    oct Morton triplets (z most significant) then the within-oct
    offset (x slowest)."""
    seq = [(d, i) for i in range(lvl - 1, 0, -1)
           for d in range(ndim - 1, -1, -1)]
    seq += [(d, 0) for d in range(ndim)]
    return tuple(seq)


@lru_cache(maxsize=None)
def _slab_axes(lvl: int, ndim: int, mbits: int = 0) -> tuple:
    """Transpose permutation taking the REMAINING flat bit axes (after
    fixing the top ``mbits`` device bits) to dense coordinate-major
    order over the local sub-box.  ``mbits=0`` is the full-box case:
    axis p of the reshaped flat array holds the p-th most significant
    flat index bit."""
    seq = _bit_seq(lvl, ndim)
    pos = {bit: p - mbits for p, bit in enumerate(seq) if p >= mbits}
    return tuple(pos[(d, i)] for d in range(ndim)
                 for i in range(lvl - 1, -1, -1) if (d, i) in pos)


@lru_cache(maxsize=None)
def _inv_slab_axes(lvl: int, ndim: int, mbits: int = 0) -> tuple:
    fwd = _slab_axes(lvl, ndim, mbits)
    inv = [0] * len(fwd)
    for i, a in enumerate(fwd):
        inv[a] = i
    return tuple(inv)


def _bit_axes(lvl: int, ndim: int) -> tuple:
    return _slab_axes(lvl, ndim, 0)


def _inv_bit_axes(lvl: int, ndim: int) -> tuple:
    return _inv_slab_axes(lvl, ndim, 0)


@lru_cache(maxsize=None)
def grid_bits(lvl: int, ndim: int, mbits: int) -> tuple:
    """Per-axis device-bit counts of an ``mbits``-bit chunk split: the
    top ``mbits`` flat bits in MSB→LSB order, tallied by axis.  The
    device grid is ``(2^b for b in grid_bits)`` and the local box is
    ``(2^(lvl-b))`` — z is cut first (it carries the most significant
    flat bits), then y, then x."""
    if mbits > ndim * (lvl - 1):
        raise ValueError(
            f"mbits={mbits} would cut inside octs at lvl={lvl}")
    md = [0] * ndim
    for d, _ in _bit_seq(lvl, ndim)[:mbits]:
        md[d] += 1
    return tuple(md)


@lru_cache(maxsize=None)
def slab_shape(lvl: int, ndim: int, mbits: int) -> tuple:
    """Local dense sub-box shape owned by one of ``2^mbits`` chunks."""
    return tuple(1 << (lvl - b) for b in grid_bits(lvl, ndim, mbits))


@lru_cache(maxsize=None)
def chunk_coords(lvl: int, ndim: int, mbits: int) -> tuple:
    """Device-grid coordinates of every chunk: ``coords[D][d]`` is
    chunk D's position along axis d (D = the top ``mbits`` flat bits
    verbatim; its axis-d bits are the coordinate's high bits in
    order)."""
    seq = _bit_seq(lvl, ndim)[:mbits]
    out = []
    for D in range(1 << mbits):
        g = [0] * ndim
        for j, (d, _) in enumerate(seq):
            g[d] = (g[d] << 1) | ((D >> (mbits - 1 - j)) & 1)
        out.append(tuple(g))
    return tuple(out)


def flat_to_dense_slab(rows, lvl: int, ndim: int, mbits: int):
    """One chunk's flat-order rows ``[ncell/2^mbits, *trailing]`` →
    its dense local sub-box ``slab_shape + trailing`` (pure
    reshape/transpose, shard-local)."""
    loc = slab_shape(lvl, ndim, mbits)
    trailing = rows.shape[1:]
    nb = ndim * lvl - mbits
    x = rows.reshape((2,) * nb + trailing)
    ax = _slab_axes(lvl, ndim, mbits) + tuple(range(nb, nb + len(trailing)))
    return jnp.transpose(x, ax).reshape(loc + trailing)


def dense_to_flat_slab(dense, lvl: int, ndim: int, mbits: int):
    """Dense local sub-box → one chunk's flat-order rows (inverse of
    :func:`flat_to_dense_slab`)."""
    ncell = 1 << (ndim * lvl - mbits)
    trailing = dense.shape[ndim:]
    nb = ndim * lvl - mbits
    x = dense.reshape((2,) * nb + trailing)
    ax = _inv_slab_axes(lvl, ndim, mbits) + tuple(
        range(nb, nb + len(trailing)))
    return jnp.transpose(x, ax).reshape((ncell,) + trailing)


def flat_index_np(coords, lvl: int, ndim: int):
    """Host-side (numpy) flat row index of dense cell coordinates —
    the scalar form of the bit permutation above, for map builders that
    need Morton-interleaved scatter targets (``mhd/amr.py`` builds its
    slab-path EMF override indices with this instead of a C-order
    ``ravel_multi_index``).  ``coords``: int array ``[..., ndim]``
    (values in ``[0, 2^lvl)``); returns int64 flat indices of shape
    ``coords.shape[:-1]``."""
    import numpy as np
    coords = np.asarray(coords)
    seq = _bit_seq(lvl, ndim)
    nb = len(seq)
    flat = np.zeros(coords.shape[:-1], dtype=np.int64)
    for p, (d, i) in enumerate(seq):
        flat |= ((coords[..., d].astype(np.int64) >> i) & 1) << (nb - 1 - p)
    return flat


def flat_to_dense(rows, lvl: int, ndim: int):
    """[ncell(+pad), *trailing] flat-order rows → dense
    ``(2^lvl,)*ndim + trailing`` array (pure reshape/transpose)."""
    n = 1 << lvl
    ncell = n ** ndim
    return flat_to_dense_slab(rows[:ncell], lvl, ndim, 0)


def dense_to_flat(dense, lvl: int, ndim: int):
    """Dense ``(2^lvl,)*ndim + trailing`` array → [ncell, *trailing]
    flat-order rows (inverse of :func:`flat_to_dense`)."""
    return dense_to_flat_slab(dense, lvl, ndim, 0)
