"""Per-host concurrent sharded checkpoints (``io/pario.py`` — the
pario/IOGROUPSIZE role, VERDICT-r04 Missing #1): every writer emits
only the shard rows it holds, concurrently, into its own validated
shard dir; process 0 seals the set under the two-phase global commit;
and the shard sets restore onto ANY device count bitwise.  Elastic
fault paths (torn shards, die-mid-commit, degraded-mesh restore) live
in test_elastic_checkpoint.py."""

import glob
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import params_from_string
from ramses_tpu.io.pario import dump_pario, restore_pario
from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=6", "boxlen=1.0", "/",
    "&INIT_PARAMS", "nregion=2",
    "region_type(1)='square'", "region_type(2)='square'",
    "x_center=0.25,0.75", "length_x=0.5,0.5",
    "exp_region=10.0,10.0", "d_region=1.0,0.125",
    "p_region=1.0,0.1", "/",
    "&HYDRO_PARAMS", "riemann='hllc'", "/",
    "&REFINE_PARAMS", "err_grad_d=0.05", "err_grad_p=0.05", "/",
    "&OUTPUT_PARAMS", "tend=0.01", "/",
])


@pytest.mark.slow
def test_pario_roundtrip_any_device_count(tmp_path):
    import jax
    devices = jax.devices()
    assert len(devices) >= 8
    sim = ShardedAmrSim(params_from_string(NML, ndim=2),
                        devices=devices[:8], dtype=jnp.float32)
    sim.evolve(0.004, nstepmax=3)
    ref = {l: np.asarray(sim.u[l]) for l in sim.levels()}

    out = dump_pario(sim, 1, str(tmp_path), split_hosts=4,
                     io_group_size=2)
    shards = sorted(glob.glob(os.path.join(out, "shard_*")))
    assert len(shards) == 4                     # one dir per "host"
    assert all(os.path.isfile(os.path.join(s, "manifest.json"))
               for s in shards)
    assert os.path.exists(os.path.join(out, "manifest.json"))

    # restore onto the SAME 8-device mesh: bitwise
    r8 = restore_pario(ShardedAmrSim, params_from_string(NML, ndim=2),
                       out, dtype=jnp.float32, devices=devices[:8])
    assert r8.t == sim.t and r8.nstep == sim.nstep
    for l in sim.levels():
        m = sim.maps[l]
        nc = m.noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r8.u[l])[:nc], ref[l][:nc]), l

    # restore onto ONE device (plain AmrSim): same state, and the two
    # sims keep evolving identically (mesh-of-1 == mesh-of-N)
    r1 = restore_pario(AmrSim, params_from_string(NML, ndim=2), out,
                       dtype=jnp.float32)
    for l in sim.levels():
        m = sim.maps[l]
        nc = m.noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r1.u[l])[:nc], ref[l][:nc]), l
    r8.evolve(0.006, nstepmax=r8.nstep + 2)
    r1.evolve(0.006, nstepmax=r1.nstep + 2)
    assert r8.nstep == r1.nstep
    for l in r1.levels():
        nc = r1.maps[l].noct * 2 ** r1.cfg.ndim
        a = np.asarray(r8.u[l])[:nc]
        b = np.asarray(r1.u[l])[:nc]
        assert np.allclose(a, b, rtol=2e-6, atol=1e-7), l


def test_pario_dtnew_roundtrip(tmp_path):
    """The pending next-step dt rides the manifest: a restore takes the
    same next step a continuous run would (dt hysteresis preserved)."""
    sim = AmrSim(params_from_string(NML, ndim=2), dtype=jnp.float64)
    sim.evolve(0.004, nstepmax=3)
    assert sim._dt_cache is not None
    out = dump_pario(sim, 3, str(tmp_path))
    r = restore_pario(AmrSim, params_from_string(NML, ndim=2), out,
                      dtype=jnp.float64)
    assert r._dt_cache == pytest.approx(sim._dt_cache, rel=0, abs=0)
    assert r.dt_old == sim.dt_old
    # next coarse step bitwise-identical to the continuous run
    sim.step_coarse(sim.coarse_dt())
    r.step_coarse(r.coarse_dt())
    assert r.t == sim.t
    for l in sim.levels():
        nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r.u[l])[:nc],
                              np.asarray(sim.u[l])[:nc]), l


PM_NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.",
    "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=4", "boxlen=1.0", "/",
    "&POISSON_PARAMS", "solver='cg'", "/",
    "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
    "d_region=1.0", "p_region=1.0", "/",
    "&HYDRO_PARAMS", "riemann='hllc'", "/",
    "&OUTPUT_PARAMS", "tend=0.01", "/",
])


def _pm_sim(dtype=None):
    import jax

    from ramses_tpu.pm.particles import ParticleSet

    rng = np.random.default_rng(3)
    ps = ParticleSet.make(rng.uniform(0, 1, (16, 2)),
                          rng.normal(0, 0.1, (16, 2)),
                          np.full(16, 1.0 / 16), nmax=24)
    return AmrSim(params_from_string(PM_NML, ndim=2),
                  dtype=dtype or jnp.float32,
                  particles=jax.device_put(ps))


def test_pario_pm_roundtrip(tmp_path):
    """Particles/sinks/tracers ride the single-process manifest and
    restore bitwise — full padded lanes, ids, families, flags, sink
    census, tracer positions (ROADMAP "warn today, persist next")."""
    import warnings as wmod

    from ramses_tpu.pm.sinks import SinkSet

    sim = _pm_sim(dtype=jnp.float64)
    sim.evolve(0.004, nstepmax=2)
    sim.sinks = SinkSet(x=np.asarray([[0.5, 0.5]]),
                        v=np.asarray([[0.1, 0.0]]),
                        m=np.asarray([2.5]), tform=np.asarray([0.001]),
                        idp=np.asarray([7]), next_id=8)
    sim.tracer_x = np.asarray([[0.25, 0.25], [0.75, 0.75]])
    sim.tracer_id = np.asarray([11, 12])
    with wmod.catch_warnings():
        wmod.simplefilter("error")       # persisted → no gas-only warn
        out = dump_pario(sim, 1, str(tmp_path))
        r = restore_pario(AmrSim, params_from_string(PM_NML, ndim=2),
                          out, dtype=jnp.float64)
    assert r.p is not None and r.pic
    for f in ("x", "v", "m", "active", "idp", "family", "tp", "zp",
              "flags"):
        assert np.array_equal(np.asarray(getattr(r.p, f)),
                              np.asarray(getattr(sim.p, f))), f
    assert np.array_equal(r.sinks.x, sim.sinks.x)
    assert np.array_equal(r.sinks.idp, sim.sinks.idp)
    assert r.sinks.next_id == sim.sinks.next_id
    assert np.array_equal(r.tracer_x, sim.tracer_x)
    assert np.array_equal(r.tracer_id, sim.tracer_id)
    # and the restored run keeps stepping identically (PM restart);
    # drop the hand-attached sinks first — stepping sink physics needs
    # &SINK_PARAMS units, and the identity claim here is about the
    # particle/gas state
    sim.sinks = r.sinks = None
    sim.step_coarse(sim.coarse_dt())
    r.step_coarse(r.coarse_dt())
    assert r.t == sim.t
    assert np.array_equal(np.asarray(r.p.x), np.asarray(sim.p.x))
    assert np.array_equal(np.asarray(r.p.v), np.asarray(sim.p.v))


def test_pario_two_phase_multiprocess(tmp_path, monkeypatch):
    """The gas-only multi-process era is over: simulate a 2-process
    dump by running both writer passes sequentially (barriers no-op).
    The non-zero process stages its shard and returns the UNCOMMITTED
    ``.tmp`` path; process 0's pass stages its shard + tree, validates
    the full set, and seals the global manifest — and the committed
    checkpoint restores particles on one device, warning-free."""
    import warnings as wmod

    import jax

    import ramses_tpu.io.pario as pario

    monkeypatch.setattr(pario, "_barrier", lambda tag: None)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    sim = _pm_sim(dtype=jnp.float64)
    sim.evolve(0.004, nstepmax=2)

    # pass 1: the OTHER host stages shard_00001; no commit happens
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    out1 = dump_pario(sim, 1, str(tmp_path))
    assert out1.endswith(".tmp")
    assert os.path.isfile(os.path.join(out1, "shard_00001",
                                       "manifest.json"))
    assert not os.path.exists(os.path.join(out1, "manifest.json"))

    # pass 2: process 0 stages its shard and seals the set — its
    # stale-stage sweep must keep the sibling's same-nstep shard
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    out = dump_pario(sim, 1, str(tmp_path))
    assert out.endswith("pario_00001") and os.path.isdir(out)
    from ramses_tpu.resilience import validate_checkpoint
    ok, reason = validate_checkpoint(out, verify_hash=True)
    assert ok, reason

    with wmod.catch_warnings():
        wmod.simplefilter("error")     # persisted → no gas-only warn
        r = restore_pario(AmrSim, params_from_string(PM_NML, ndim=2),
                          out, dtype=jnp.float64)
    assert r.p is not None
    for f in ("x", "v", "m", "active", "idp"):
        assert np.array_equal(np.asarray(getattr(r.p, f)),
                              np.asarray(getattr(sim.p, f))), f
    for l in sim.levels():
        nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r.u[l])[:nc],
                              np.asarray(sim.u[l])[:nc]), l


def test_pario_layout_roundtrip(tmp_path):
    """A dump taken under a Hilbert-rebalanced layout restores to tree
    order: host files carry rows in the dump sim's layout; the manifest
    oct_row permutation brings them back."""
    nml = NML.replace("levelmax=6",
                      "levelmax=5\nload_balance=.true.")
    sim = AmrSim(params_from_string(nml, ndim=2), dtype=jnp.float64)
    sim.evolve(0.004, nstepmax=3)
    sim.request_rebalance()
    sim.regrid()
    assert sim.layouts, "no layout adopted; test needs a partial level"
    out = dump_pario(sim, 4, str(tmp_path), split_hosts=3)
    r = restore_pario(AmrSim,
                      params_from_string(NML.replace("levelmax=6",
                                                     "levelmax=5"),
                                         ndim=2),
                      out, dtype=jnp.float64)
    assert not r.layouts
    for l in sim.levels():
        nc = sim.tree.noct(l) * 2 ** sim.cfg.ndim
        a = sim.tree_order_cells(np.asarray(sim.u[l]), l)[:nc]
        b = np.asarray(r.u[l])[:nc]
        assert np.array_equal(a, b), l


def test_pario_cross_host_waves(tmp_path, monkeypatch):
    """On a multi-process run io_group_size staggers HOSTS into waves
    (wave = process_index % group) with a barrier between them — this
    process's host files land strictly inside its own wave window."""
    import jax

    import ramses_tpu.io.pario as pario

    events = []
    monkeypatch.setattr(pario, "_barrier",
                        lambda tag: events.append(("barrier", tag)))
    orig = np.savez

    def recording_savez(path, *a, **k):
        events.append(("write", os.path.basename(str(path))))
        return orig(path, *a, **k)

    monkeypatch.setattr(np, "savez", recording_savez)
    # pretend to be process 1 of 4 (dump_pario reads both lazily)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    sim = AmrSim(params_from_string(NML, ndim=2), dtype=jnp.float32)
    out = dump_pario(sim, 7, str(tmp_path), split_hosts=2,
                     io_group_size=2)
    b0 = events.index(("barrier", "pario_00007_wave_0"))
    b1 = events.index(("barrier", "pario_00007_wave_1"))
    writes = [i for i, (kind, name) in enumerate(events)
              if kind == "write" and name == "data.npz"]
    assert len(writes) == 2           # split_hosts=2 shards this host
    # process 1 is in wave 1: every write sits between the two barriers
    assert all(b0 < i < b1 for i in writes)
    # a non-zero process never seals the global manifest, and with the
    # commit barrier stubbed out the stage stays uncommitted — the
    # returned path is the .tmp staging dir, which no scanner selects
    assert out.endswith(".tmp")
    assert not os.path.exists(os.path.join(out, "manifest.json"))
    from ramses_tpu.resilience import latest_valid_checkpoint
    assert latest_valid_checkpoint(str(tmp_path), log=None) is None
    # the wave schedule covers every residue class once
    assert [pario._host_wave(p, 2) for p in range(4)] == [0, 1, 0, 1]


def test_pario_io_group_throttle(tmp_path, monkeypatch):
    """io_group_size=1 serializes the writers (the IOGROUPSIZE token
    ring); the files still land and restore."""
    import threading

    import ramses_tpu.io.pario as pario
    peak = {"live": 0, "max": 0}
    lock = threading.Lock()
    orig = np.savez

    def counting_savez(*a, **k):
        with lock:
            peak["live"] += 1
            peak["max"] = max(peak["max"], peak["live"])
        try:
            return orig(*a, **k)
        finally:
            with lock:
                peak["live"] -= 1

    import jax
    sim = ShardedAmrSim(params_from_string(NML, ndim=2),
                        devices=jax.devices()[:8], dtype=jnp.float32)
    monkeypatch.setattr(np, "savez", counting_savez)
    out = dump_pario(sim, 2, str(tmp_path), split_hosts=4,
                     io_group_size=1)
    monkeypatch.setattr(np, "savez", orig)
    # tree payload writes outside the ring; shard writers hold the token
    assert peak["max"] <= 2
    r = restore_pario(ShardedAmrSim, params_from_string(NML, ndim=2),
                      out, dtype=jnp.float32, devices=jax.devices()[:8])
    for l in sim.levels():
        nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r.u[l])[:nc],
                              np.asarray(sim.u[l])[:nc])
