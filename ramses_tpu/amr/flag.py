"""Refinement flag computation — ``flag_fine`` (``amr/flag_utils.f90:57-718``).

Per level: device gradient criteria (``hydro_refine``) + host geometric
criteria, ``nexpand``-fold dilation (``smooth_fine``, ``:555``), then a
top-down nesting sweep that is the constructive form of the reference's
2:1 ``ensure_ref_rules`` (``:213``): a cell at level l is flagged whenever
any flagged cell x at level l+1 has a father-neighbourhood cell
``(x+e)>>1`` equal to it — this guarantees every surviving oct's 3^ndim
father-cell stencil exists.
"""

from __future__ import annotations

import itertools
from typing import Dict

import numpy as np

from ramses_tpu.amr import keys as kmod
from ramses_tpu.amr.tree import Octree, map_coords
from ramses_tpu.config import Params


def _neighbor_offsets(ndim: int) -> np.ndarray:
    return np.array(list(itertools.product((-1, 0, 1), repeat=ndim)),
                    dtype=np.int64)


def dilate(flag_coords: np.ndarray, lvl: int, bc_kinds, ndim: int,
           dims=None) -> np.ndarray:
    """One smoothing pass: the 3^ndim dilation of the flagged cell set."""
    if len(flag_coords) == 0:
        return flag_coords
    offs = _neighbor_offsets(ndim)
    ex = (flag_coords[:, None, :] + offs[None, :, :]).reshape(-1, ndim)
    ex, _ = map_coords(ex, lvl, bc_kinds, ndim, dims=dims)
    ks = np.unique(kmod.encode(ex, ndim))
    return kmod.decode(ks, ndim)


def geometry_flags(centers: np.ndarray, lvl: int, p: Params) -> np.ndarray:
    """Geometric refinement region of this level
    (``amr/flag_utils.f90:494-553``): generalized-ellipsoid ball around
    (x_refine, y_refine, z_refine) with radius r_refine, semi-axis ratios
    a/b_refine and p-norm exp_refine.  r_refine < 0 → disabled."""
    r = p.refine
    i = lvl - 1                                        # 1-based level lists
    if i >= len(r.r_refine) or r.r_refine[i] <= 0.0:
        return np.zeros(len(centers), dtype=bool)
    cen = [r.x_refine[i], r.y_refine[i], r.z_refine[i]][:p.ndim]
    ax = [1.0, r.a_refine[i], r.b_refine[i]][:p.ndim]
    en = float(r.exp_refine[i])
    rr = np.zeros(len(centers))
    for d in range(p.ndim):
        t = np.abs(centers[:, d] - cen[d]) / ax[d]
        rr += t ** min(en, 10.0) if en < 10.0 else 0.0
    if en < 10.0:
        rr = rr ** (1.0 / en)
    else:
        rr = np.maximum.reduce(
            [np.abs(centers[:, d] - cen[d]) / ax[d] for d in range(p.ndim)])
    return rr < float(r.r_refine[i])


def compute_new_tree(tree: Octree, crit_flags: Dict[int, np.ndarray],
                     bc_kinds, params: Params) -> Octree:
    """New octree from per-level per-cell criteria flags.

    ``crit_flags[l]``: bool [ncell_flat(l)] on the CURRENT tree.  Returns a
    tree whose level-(l+1) oct set is exactly the flagged cell set of level
    l after smoothing + nesting.
    """
    ndim = tree.ndim
    lmin, lmax = tree.levelmin, tree.levelmax
    nexpand = params.amr.nexpand

    # flagged cell coordinate sets per level, smoothed
    fcoords: Dict[int, np.ndarray] = {}
    for l in range(lmin, lmax + 1):
        if not tree.has(l):
            fcoords[l] = np.zeros((0, ndim), dtype=np.int64)
            continue
        cc = tree.cell_coords(l)
        f = crit_flags.get(l)
        coords = cc[f] if f is not None and f.any() else \
            np.zeros((0, ndim), dtype=np.int64)
        ne = nexpand[l - 1] if l - 1 < len(nexpand) else 1
        for _ in range(max(int(ne), 0)):
            coords = dilate(coords, l, bc_kinds, ndim,
                            dims=tree.cell_dims(l))
        fcoords[l] = coords

    # top-down nesting: project fine flags into father-neighbourhood flags
    offs = _neighbor_offsets(ndim)
    for l in range(lmax, lmin, -1):
        x = fcoords[l]
        if len(x) == 0:
            continue
        ex = (x[:, None, :] + offs[None, :, :]).reshape(-1, ndim)
        ex, _ = map_coords(ex, l, bc_kinds, ndim, dims=tree.cell_dims(l))
        up = ex >> 1
        ks = np.unique(kmod.encode(up, ndim))
        prev = kmod.encode(fcoords[l - 1], ndim) if len(fcoords[l - 1]) \
            else np.zeros(0, dtype=np.int64)
        allk = np.unique(np.concatenate([prev, ks]))
        fcoords[l - 1] = kmod.decode(allk, ndim)

    # flags only refine existing cells: intersect with current cell sets
    new = Octree(ndim, lmin, lmax, root=tree.root)
    new.set_level(lmin, tree.levels[lmin].og)          # base stays complete
    for l in range(lmin, lmax):
        coords = fcoords[l]
        if len(coords) == 0:
            break
        # a flagged cell must exist on the (new) level l to spawn an oct
        parent = new.lookup(l, coords >> 1)
        coords = coords[parent >= 0]
        if len(coords) == 0:
            break
        new.set_level(l + 1, coords)                   # cell coords = oct
    return new
