"""Barotropic equations of state (``hydro/eos.f90``).

``barotropic_eos_temperature``: T2 = T/mu [K] as a function of density
[H/cc], selected by ``barotropic_eos_form`` (&COOLING_PARAMS,
``amr/amr_parameters.f90:219-230``).  Used as the polytrope temperature
floor in the cooling pass and as the full EOS when ``barotropic_eos`` is
set (cooling then disabled, ``hydro/cooling_fine.f90:397``).
"""

from __future__ import annotations

import jax.numpy as jnp


def barotropic_eos_temperature(nH, form: str, T2_eos: float,
                               polytrope_rho_cu: float,
                               polytrope_index: float):
    """T2(nH); ``polytrope_rho_cu`` is the break density in code units
    already divided by scale_nH upstream (``cooling_fine.f90:139``)."""
    x = nH / polytrope_rho_cu
    if form == "isothermal":
        return jnp.full_like(nH, T2_eos)
    if form == "polytrope":
        return T2_eos * x ** (polytrope_index - 1.0)
    if form == "double_polytrope":
        return T2_eos * (1.0 + x ** (polytrope_index - 1.0))
    if form == "custom":
        # Double-where: the untaken power-law branch would be evaluated at
        # x < 1 too, where x -> 0 makes its derivative unbounded for
        # polytrope_index < 1 and poisons reverse-mode cotangents; feed it
        # the break density instead (forward value there is masked anyway).
        lo = x < 1.0
        hi = T2_eos * jnp.where(lo, 1.0, x) ** (polytrope_index - 1.0)
        return jnp.where(lo, T2_eos, hi)
    raise ValueError(f"unknown barotropic eos form {form!r}")
