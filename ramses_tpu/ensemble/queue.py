"""File-backed submit/claim/complete job queue for the run service.

The queue is a directory with one JSON record per job, and a job's
lifecycle IS its location: ``queued/`` -> ``running/`` -> ``done/`` or
``failed/``.  Every transition is a single ``os.rename`` on the same
filesystem, so claiming is atomic — two workers racing for one job see
exactly one rename succeed and one ``FileNotFoundError`` (the AMT
task-queue scheduling shape, arXiv:2412.15518, reduced to POSIX).

Liveness is the running record's mtime: a worker touches its claimed
record (``heartbeat``) between fused windows, and any caller may
``reclaim_stale`` records whose mtime is older than the staleness
timeout — bumping the attempt count and renaming the job back into
``queued/`` (or into ``failed/`` once ``max_attempts`` is exhausted).
Results (telemetry JSONL + checkpoints) land under ``results/<job>/``.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

STATES = ("queued", "running", "done", "failed")


@dataclass
class Job:
    """A claimed (or inspected) job: its id, current record path and
    parsed record dict."""
    id: str
    path: str
    record: Dict[str, Any]

    @property
    def state(self) -> str:
        return os.path.basename(os.path.dirname(self.path))


def _dirs(queue_dir: str) -> Dict[str, str]:
    return {s: os.path.join(queue_dir, s) for s in STATES}


def init_queue(queue_dir: str) -> str:
    for d in _dirs(queue_dir).values():
        os.makedirs(d, exist_ok=True)
    os.makedirs(os.path.join(queue_dir, "results"), exist_ok=True)
    return queue_dir


def results_dir(queue_dir: str, job_id: str) -> str:
    return os.path.join(queue_dir, "results", job_id)


def _write_record(path: str, record: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def submit(queue_dir: str, namelist: str,
           sweeps: Optional[Dict[str, List[Any]]] = None,
           solver: str = "", ndim: int = 3, dtype: str = "float32",
           job_id: str = "", meta: Optional[Dict[str, Any]] = None,
           kind: str = "run") -> str:
    """Enqueue a job: ``namelist`` is the full namelist *text* (the
    record is self-contained — workers need no shared checkout), plus
    optional explicit per-member ``sweeps``.  ``kind`` dispatches the
    worker-side handler first-class — ``"run"`` (forward ensemble,
    default) or ``"calibrate"`` (gradient-descent calibration,
    ramses_tpu/diff) — instead of being sniffed from the payload.
    Returns the job id."""
    init_queue(queue_dir)
    if kind not in ("run", "calibrate"):
        raise ValueError(f"unknown job kind {kind!r}")
    if not job_id:
        job_id = f"job-{time.time_ns():020d}-{os.getpid()}"
    path = os.path.join(queue_dir, "queued", job_id + ".json")
    if os.path.exists(path):
        raise FileExistsError(f"job id '{job_id}' already queued")
    from ramses_tpu.obs.trace import new_trace_id
    record = {
        "id": job_id, "kind": kind, "namelist": namelist,
        "sweeps": dict(sweeps or {}), "solver": solver,
        "ndim": int(ndim), "dtype": dtype,
        "submitted_unix": time.time(), "attempts": 0,
        # end-to-end correlation id (ramses_tpu/obs/trace): stamped
        # here once, then propagated into every telemetry record,
        # failure_log entry and checkpoint manifest this job produces
        "trace_id": new_trace_id(),
        "meta": dict(meta or {})}
    # submit-time cost stamp (members x cells x steps + shard clamps):
    # the currency plan_gang bin-packs on.  Strictly best-effort — an
    # unparseable namelist submits unstamped and schedules as a small
    # FIFO job (the failure then surfaces on the worker, with a log).
    try:
        from ramses_tpu.ensemble.meshplan import stamp_cost
        cost = stamp_cost(namelist, ndim=int(ndim), sweeps=sweeps,
                          solver=solver, kind=kind)
        if cost is not None:
            record["cost"] = cost
    except Exception:
        pass
    _write_record(path, record)
    return job_id


def job_kind(record: Dict[str, Any]) -> str:
    """The job's dispatch kind; records written before the field existed
    default to ``"run"``."""
    return str(record.get("kind") or "run")


def claim(queue_dir: str, worker: str = "",
          job_id: str = "") -> Optional[Job]:
    """Atomically claim the oldest queued job (rename into
    ``running/``), bump its attempt count and stamp the claim time.
    Returns None when the queue is empty; racing workers each get a
    distinct job or None.  ``job_id`` claims that specific job instead
    of the FIFO head — the gang scheduler plans from a
    :func:`peek_queued` snapshot and then claims each planned job by
    id, dropping any it loses to a racing worker."""
    dirs = _dirs(queue_dir)
    worker = worker or f"{os.uname().nodename}:{os.getpid()}"
    if job_id:
        names = [job_id + ".json"]
    else:
        try:
            names = sorted(n for n in os.listdir(dirs["queued"])
                           if n.endswith(".json"))
        except FileNotFoundError:
            return None
    for name in names:
        src = os.path.join(dirs["queued"], name)
        dst = os.path.join(dirs["running"], name)
        try:
            os.rename(src, dst)        # the atomic claim
        except OSError:
            continue                   # another worker won this one
        with open(dst) as f:
            record = json.load(f)
        record["attempts"] = int(record.get("attempts", 0)) + 1
        record["worker"] = worker
        record["claimed_unix"] = time.time()
        _write_record(dst, record)
        return Job(id=record["id"], path=dst, record=record)
    return None


def peek_queued(queue_dir: str) -> List[Dict[str, Any]]:
    """Snapshot the queued records in FIFO (file-name = submit) order
    without claiming anything — the gang scheduler's planning input.
    Records that vanish or fail to parse mid-listing are skipped (a
    racing worker claimed them, or a submit is mid-flight)."""
    dirs = _dirs(queue_dir)
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(n for n in os.listdir(dirs["queued"])
                       if n.endswith(".json"))
    except FileNotFoundError:
        return out
    for name in names:
        try:
            with open(os.path.join(dirs["queued"], name)) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


def _is_exclusive(record: Dict[str, Any]) -> bool:
    """Mesh-wide jobs drain the gang and run alone: a cost stamp with
    ``exclusive`` (per-member cells above the pack budget), or a
    non-``run`` kind (calibrate drives its own optimizer loop and
    shares no chunk cadence to gang on)."""
    cost = record.get("cost") or {}
    return bool(cost.get("exclusive")) or job_kind(record) != "run"


def plan_gang(records: List[Dict[str, Any]], ndev: int,
              order: str = "cost", now: Optional[float] = None,
              starve_s: float = 600.0
              ) -> List[Tuple[Dict[str, Any], int]]:
    """Pure gang-scheduling decision: which queued jobs to claim next
    and how many devices each gets.  No filesystem, no jax — the unit-
    testable core of the cost-aware serve loop.

    ``records`` is a FIFO-ordered :func:`peek_queued` snapshot;
    ``ndev`` the local device count.  Returns ``[(record, nshard),
    ...]`` whose nshards sum to at most ``ndev``.

    ``order="cost"`` (the default claim order):

    * an *exclusive* job (cost stamp says mesh-wide, or a calibrate)
      that has waited longer than ``starve_s`` preempts everything —
      the starvation bound: bin-packed small jobs can only overtake a
      big job for so long;
    * otherwise small jobs are greedily bin-packed cost-ascending
      (cheapest first — they drain soonest, keeping queue latency
      low), each granted its ``min_shards`` first and leftover devices
      spread round-robin up to ``min(max_shards, members)``;
    * with no packable small jobs, the oldest exclusive job takes the
      whole mesh.

    ``order="fifo"`` is the fallback knob: strictly the head job, all
    devices — the pre-scheduler behavior."""
    if not records:
        return []
    ndev = max(1, int(ndev))
    if order == "fifo":
        return [(records[0], ndev)]
    if order != "cost":
        raise ValueError(f"unknown claim order {order!r}")
    now = time.time() if now is None else float(now)
    exclusive = [r for r in records if _is_exclusive(r)]
    small = [r for r in records if not _is_exclusive(r)]
    starving = [r for r in exclusive
                if now - float(r.get("submitted_unix", now))
                >= float(starve_s)]
    if starving:
        return [(starving[0], ndev)]
    if not small:
        return [(exclusive[0], ndev)] if exclusive else []
    small = sorted(small, key=lambda r: int(
        (r.get("cost") or {}).get("cost") or 0))
    gang: List[List[Any]] = []
    avail = ndev

    def _clamps(rec):
        c = rec.get("cost") or {}
        lo = max(1, int(c.get("min_shards") or 1))
        hi = int(c.get("max_shards") or 0) or ndev
        # packed replicas cannot exceed the member count — extra
        # devices would idle, so leave them for the next job
        hi = min(hi, max(1, int(c.get("members") or 1)))
        return lo, max(lo, hi)

    for rec in small:
        lo, _hi = _clamps(rec)
        if lo > avail:
            continue                   # next gang, once devices free
        gang.append([rec, lo])
        avail -= lo
        if avail <= 0:
            break
    if not gang:
        return [(exclusive[0], ndev)] if exclusive else []
    grew = True
    while avail > 0 and grew:
        grew = False
        for entry in gang:
            if avail <= 0:
                break
            _lo, hi = _clamps(entry[0])
            if entry[1] < hi:
                entry[1] += 1
                avail -= 1
                grew = True
    return [(rec, int(n)) for rec, n in gang]


def heartbeat(job: Job) -> None:
    """Refresh the running record's mtime — the worker liveness signal
    the staleness reclaim keys on."""
    os.utime(job.path)


def _log_failure(record: Dict[str, Any], error: str,
                 stage: str) -> None:
    """Append one attempt's failure to the record's ``failure_log``.
    The log rides the record file through every requeue/reclaim, so a
    job that bounced across three workers arrives in ``failed/`` with
    the full history instead of only the last error."""
    record.setdefault("failure_log", []).append({
        "error": str(error), "stage": stage,
        "kind": job_kind(record),
        "attempt": int(record.get("attempts", 0)),
        "worker": record.get("worker", ""),
        "trace_id": record.get("trace_id", ""),
        "time_unix": time.time()})
    record["error"] = str(error)


def _emit(telemetry, kind: str, **fields) -> None:
    if telemetry is not None:
        try:
            telemetry.record_event(kind, **fields)
        except Exception:
            pass


def complete(job: Job, result: Optional[Dict[str, Any]] = None) -> str:
    """running -> done, folding ``result`` (artifact paths, final t/
    nstep) into the record."""
    return _finish(job, "done", result=result)


def fail(job: Job, error: str = "",
         result: Optional[Dict[str, Any]] = None,
         telemetry=None, stage: str = "fail") -> str:
    """running -> failed with the error appended to the accumulated
    ``failure_log`` (and recorded as the headline ``error``).
    ``stage`` labels the log entry — the serve loop passes ``"hang"``
    for deadline-killed jobs so the classification survives in the
    record."""
    if error:
        _log_failure(job.record, error, stage)
    _emit(telemetry, "queue_fail", job=job.id,
          trace_id=job.record.get("trace_id", ""),
          attempts=int(job.record.get("attempts", 0)), error=error,
          stage=stage)
    return _finish(job, "failed", result=result, error=error)


def requeue(job: Job, error: str = "", telemetry=None,
            stage: str = "requeue") -> str:
    """running -> queued (a failed attempt with attempts remaining);
    the attempt count stays — :func:`claim` bumps it on the next
    worker.  The attempt's error is appended to ``failure_log``, which
    survives the requeue because it lives in the record file.
    ``stage`` labels the entry (``"hang"`` for kill-and-requeue)."""
    if error:
        _log_failure(job.record, error, stage)
    _emit(telemetry, "queue_requeue", job=job.id,
          trace_id=job.record.get("trace_id", ""),
          attempts=int(job.record.get("attempts", 0)), error=error,
          stage=stage)
    _write_record(job.path, job.record)
    dst = os.path.join(os.path.dirname(os.path.dirname(job.path)),
                       "queued", os.path.basename(job.path))
    os.rename(job.path, dst)
    job.path = dst
    return dst


def _finish(job: Job, state: str, result=None, error: str = "") -> str:
    job.record["finished_unix"] = time.time()
    if result:
        job.record["result"] = result
    if error:
        job.record["error"] = error
    _write_record(job.path, job.record)
    dst = os.path.join(os.path.dirname(os.path.dirname(job.path)),
                       state, os.path.basename(job.path))
    os.rename(job.path, dst)
    job.path = dst
    return dst


def reclaim_stale(queue_dir: str, stale_s: float = 300.0,
                  max_attempts: int = 3, log=print,
                  telemetry=None) -> int:
    """Requeue running jobs whose heartbeat mtime is older than
    ``stale_s`` (a dead/preempted worker); jobs already at
    ``max_attempts`` go to ``failed/`` instead.  Returns the number of
    records moved.  Safe to call concurrently — the rename either
    succeeds for exactly one caller or raises and is skipped."""
    dirs = _dirs(queue_dir)
    now = time.time()
    moved = 0
    try:
        names = sorted(n for n in os.listdir(dirs["running"])
                       if n.endswith(".json"))
    except FileNotFoundError:
        return 0
    for name in names:
        path = os.path.join(dirs["running"], name)
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue                   # finished/reclaimed under us
        if age < stale_s:
            continue
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        attempts = int(record.get("attempts", 0))
        state = "queued" if attempts < max_attempts else "failed"
        _log_failure(record, f"stale worker (no heartbeat for "
                     f"{age:.0f}s, attempt {attempts})", "stale")
        if state == "queued":
            # the stale note is bookkeeping, not the job's verdict
            record.pop("error", None)
        record["reclaimed_unix"] = now
        dst = os.path.join(dirs[state], name)
        try:
            _write_record(path, record)
            os.rename(path, dst)
        except OSError:
            continue
        moved += 1
        _emit(telemetry, "queue_reclaim", job=record.get("id", name),
              trace_id=record.get("trace_id", ""),
              attempts=attempts, to=state, heartbeat_age_s=round(age, 1))
        if log is not None:
            log(f"queue: reclaimed {record.get('id', name)} -> {state} "
                f"(heartbeat {age:.0f}s old, attempt {attempts})")
    return moved


def job_status(queue_dir: str, job_id: str) -> Optional[Job]:
    """Find a job in any state dir (None when unknown)."""
    for state, d in _dirs(queue_dir).items():
        path = os.path.join(d, job_id + ".json")
        if os.path.isfile(path):
            with open(path) as f:
                return Job(id=job_id, path=path, record=json.load(f))
    return None


def queue_counts(queue_dir: str) -> Dict[str, int]:
    out = {}
    for state, d in _dirs(queue_dir).items():
        try:
            out[state] = len([n for n in os.listdir(d)
                              if n.endswith(".json")])
        except FileNotFoundError:
            out[state] = 0
    return out
