"""Explicit per-shard comm schedule for the sharded-AMR level sweep.

The ``build_comm`` analogue (``amr/virtual_boundaries.f90:1286``): after
every regrid the host walks each partial level's stencil/interp/corr
maps and materialises, per device, exactly which rows must move — the
reference's per-(cpu,level) emission/reception lists become per-ring-
offset ``lax.ppermute`` schedules:

* P2 (halo): each shard's 6^d stencil references rows of the SAME level
  owned by other shards, and its ghost-interpolation requests reference
  rows of the COARSER level — both become packed row buffers sent along
  the Hilbert ring (``make_virtual_fine_dp``, ``:373-533``).  The
  permutes ride the backend-dispatched exchange engine
  (:mod:`ramses_tpu.parallel.dma_halo`): async remote-copy DMA on TPU,
  ``lax.ppermute`` elsewhere, per the ``&AMR_PARAMS halo_backend``
  knob resolved into :class:`SweepCommSpec`.
* P3 (reverse): coarse flux-correction contributions are packed per
  owner, permuted back, and folded into the owner's block in a FIXED
  order — own entries first, then ring offsets ascending — the
  deterministic owner-fold of ``make_virtual_reverse_dp`` (``:693``).

Hilbert-ordered row sharding keeps the peer set small: almost all
traffic rides offsets ±1, so the schedule is a handful of
neighbour permutes instead of partitioner-inferred all-gathers.  The
sweep itself is the UNCHANGED :func:`ramses_tpu.amr.kernels.level_sweep`
run shard-locally on ``[own ++ halo]`` rows — identical physics, pinned
communication.

Static metadata (ring offsets) rides in :class:`SweepCommSpec` (part of
the jit key via ``FusedSpec``); the variable-size index buffers are
``[ndev, ...]`` device arrays sharded on their leading axis so every
shard reads its own rows under ``shard_map``.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ramses_tpu.parallel import dma_halo

AXIS = "oct"


class SweepCommSpec(NamedTuple):
    """Hashable static part of one level's sweep schedule."""
    mesh: Mesh
    fine_offsets: Tuple[int, ...]     # ring offsets carrying u_l halo rows
    coarse_offsets: Tuple[int, ...]   # ring offsets carrying u_{l-1} rows
    corr_offsets: Tuple[int, ...]     # ring offsets carrying corr folds
    itype: int
    backend: str = "ppermute"         # resolved halo backend (dma_halo)


def _shard_map(fn, mesh, in_specs, out_specs, check_rep=True):
    return dma_halo.shard_map_compat(fn, mesh, in_specs, out_specs,
                                     check_rep=check_rep)


def _halo_schedule(need: Dict[int, Dict[int, np.ndarray]], ndev: int):
    """need[s][p] = sorted global rows shard s needs from owner p.
    Returns (offsets, send_idx {k: [ndev, B_k]} sender-LOCAL rows,
    ext_pos {s: {global_row: ext_index}} via per-shard dicts)."""
    offs = sorted({(s - p) % ndev
                   for s in need for p in need[s] if len(need[s][p])})
    send_idx = {}
    bks = {}
    for k in offs:
        bk = max(len(need[(p + k) % ndev].get(p, ()))
                 for p in range(ndev))
        bks[k] = bk
        arr = np.zeros((ndev, bk), dtype=np.int32)
        for p in range(ndev):
            rows = need[(p + k) % ndev].get(p, np.zeros(0, np.int64))
            arr[p, :len(rows)] = rows           # sender-local remap later
        send_idx[k] = arr
    return offs, send_idx, bks


def _build_need(rows_by_shard, owner_of, ndev):
    """rows_by_shard[s] = global row refs of shard s (any order).
    Returns need[s][p] = np.sort(unique rows of s owned by p != s)."""
    need = {s: {} for s in range(ndev)}
    for s in range(ndev):
        rows = np.unique(rows_by_shard[s])
        own = owner_of(rows)
        for p in np.unique(own):
            if p == s:
                continue
            need[s][int(p)] = rows[own == p]
    return need


def build_sweep_comm(m, mc, ndev: int, mesh: Mesh, itype: int,
                     halo_backend: str = "auto"):
    """Schedule for one partial level l (maps ``m``) over coarse level
    l-1 (maps ``mc``).  Returns (SweepCommSpec, dict of numpy arrays
    [ndev, ...]) or None when ndev == 1.  ``halo_backend``: the
    ``&AMR_PARAMS`` knob, resolved here so the sweep's permutes
    dispatch to the DMA engine on TPU."""
    if ndev == 1:
        return None
    nd = m.ndim
    ttd = 1 << nd
    ns = m.stencil_src.shape[1]
    noct_pad, ncell_pad, ni_pad = m.noct_pad, m.ncell_pad, m.ni_pad
    assert noct_pad % ndev == 0, "oct rows must divide the mesh"
    octs_loc = noct_pad // ndev
    cells_loc = ncell_pad // ndev
    ncell_c = mc.ncell_pad
    assert ncell_c % ndev == 0
    coarse_loc = ncell_c // ndev
    trash = ncell_pad + ni_pad

    sten = m.stencil_src.reshape(ndev, octs_loc, ns).astype(np.int64)

    # ---- fine halo: same-level cell refs crossing shard boundaries
    fine_refs = [sten[s][(sten[s] < ncell_pad)] for s in range(ndev)]
    fneed = _build_need(fine_refs, lambda r: r // cells_loc, ndev)
    foffs, fsend, fbk = _halo_schedule(fneed, ndev)
    # sender-local remap of the send rows
    for k in foffs:
        fsend[k] = (fsend[k]
                    - (np.arange(ndev, dtype=np.int32)[:, None]
                       * cells_loc)).astype(np.int32)
        fsend[k] = np.maximum(fsend[k], 0)
    fbase = {}
    off_acc = cells_loc
    for k in foffs:
        fbase[k] = off_acc
        off_acc += fbk[k]
    halo_total = off_acc - cells_loc

    # ---- interp rows each shard must compute locally
    ineed = []
    for s in range(ndev):
        r = sten[s]
        sel = (r >= ncell_pad) & (r < trash)
        ineed.append(np.unique(r[sel] - ncell_pad))
    ipad_loc = max(8, max((len(x) for x in ineed), default=0))

    # ---- coarse halo: rows referenced by the local interp requests
    coarse_refs = []
    for s in range(ndev):
        rows = np.concatenate([
            m.interp_cell[ineed[s]].astype(np.int64),
            m.interp_nb[ineed[s]].reshape(-1).astype(np.int64)]) \
            if len(ineed[s]) else np.zeros(0, np.int64)
        coarse_refs.append(rows)
    cneed = _build_need(coarse_refs, lambda r: r // coarse_loc, ndev)
    coffs, csend, cbk = _halo_schedule(cneed, ndev)
    for k in coffs:
        csend[k] = (csend[k]
                    - (np.arange(ndev, dtype=np.int32)[:, None]
                       * coarse_loc)).astype(np.int32)
        csend[k] = np.maximum(csend[k], 0)
    cbase = {}
    off_acc = coarse_loc
    for k in coffs:
        cbase[k] = off_acc
        off_acc += cbk[k]

    # per-shard remap helpers ------------------------------------------
    def fine_ext_index(s, rows):
        """global fine-level row -> shard-s extended-array index."""
        out = np.empty(len(rows), dtype=np.int32)
        own = rows // cells_loc
        sel = own == s
        out[sel] = rows[sel] - s * cells_loc
        for p in np.unique(own[~sel]):
            k = (s - p) % ndev
            hrows = fneed[s][int(p)]
            pos = np.searchsorted(hrows, rows[own == p])
            out[own == p] = fbase[k] + pos
        return out

    def coarse_ext_index(s, rows):
        out = np.empty(len(rows), dtype=np.int32)
        own = rows // coarse_loc
        sel = own == s
        out[sel] = rows[sel] - s * coarse_loc
        for p in np.unique(own[~sel]):
            k = (s - p) % ndev
            hrows = cneed[s][int(p)]
            pos = np.searchsorted(hrows, rows[own == p])
            out[own == p] = cbase[k] + pos
        return out

    # ---- local stencil (into [own ++ halo ++ interp_loc ++ trash])
    interp_base = cells_loc + halo_total
    trash_loc = interp_base + ipad_loc
    lsten = np.full((ndev, octs_loc, ns), trash_loc, dtype=np.int32)
    licell = np.zeros((ndev, ipad_loc), dtype=np.int32)
    linb = np.zeros((ndev, ipad_loc, nd, 2), dtype=np.int32)
    lisgn = np.ones((ndev, ipad_loc, nd), dtype=np.int8)
    for s in range(ndev):
        r = sten[s].reshape(-1)
        cell = r < ncell_pad
        isel = (r >= ncell_pad) & (r < trash)
        out = np.full(len(r), trash_loc, dtype=np.int32)
        if cell.any():
            out[cell] = fine_ext_index(s, r[cell])
        if isel.any():
            ipos = np.searchsorted(ineed[s], r[isel] - ncell_pad)
            out[isel] = interp_base + ipos
        lsten[s] = out.reshape(octs_loc, ns)
        ii = ineed[s]
        if len(ii):
            licell[s, :len(ii)] = coarse_ext_index(s, m.interp_cell[ii]
                                                   .astype(np.int64))
            linb[s, :len(ii)] = coarse_ext_index(
                s, m.interp_nb[ii].reshape(-1).astype(np.int64)
            ).reshape(len(ii), nd, 2)
            lisgn[s, :len(ii)] = m.interp_sgn[ii]

    # ---- reverse (corr) schedule -------------------------------------
    corr = m.corr_idx.reshape(ndev, octs_loc * nd * 2).astype(np.int64)
    w = 1.0 / ttd
    sgn = np.tile(np.array([-1.0, 1.0]), octs_loc * nd)
    own_src, own_tgt, own_w = [], [], []
    rem = {}                               # k -> (src, w, rcv_tgt) lists
    for s in range(ndev):
        c = corr[s]
        valid = c >= 0
        coef = sgn * w * valid
        owner = np.where(valid, c // coarse_loc, s)
        sel_own = valid & (owner == s)
        own_src.append(np.nonzero(sel_own)[0].astype(np.int32))
        own_tgt.append((c[sel_own] - s * coarse_loc).astype(np.int32))
        own_w.append(coef[sel_own])
        for p in np.unique(owner[valid & (owner != s)]):
            k = int((int(p) - s) % ndev)
            src = np.nonzero(valid & (owner == p))[0].astype(np.int32)
            rem.setdefault(k, {})[s] = (
                src, coef[src],
                (c[src] - int(p) * coarse_loc).astype(np.int32))
    o_pad = max(8, max((len(x) for x in own_src), default=0))
    own_src_a = np.zeros((ndev, o_pad), dtype=np.int32)
    own_tgt_a = np.zeros((ndev, o_pad), dtype=np.int32)
    own_w_a = np.zeros((ndev, o_pad))
    for s in range(ndev):
        n = len(own_src[s])
        own_src_a[s, :n] = own_src[s]
        own_tgt_a[s, :n] = own_tgt[s]
        own_w_a[s, :n] = own_w[s]
    koffs = sorted(rem)
    corr_send, corr_w, corr_tgt = {}, {}, {}
    for k in koffs:
        pk = max(8, max(len(v[0]) for v in rem[k].values()))
        src_a = np.zeros((ndev, pk), dtype=np.int32)
        w_a = np.zeros((ndev, pk))
        tgt_a = np.zeros((ndev, pk), dtype=np.int32)
        for s, (src, cw, tgt) in rem[k].items():
            src_a[s, :len(src)] = src
            w_a[s, :len(src)] = cw
            # receiver (s+k)%ndev applies these targets in the SAME
            # packed order the sender used
            tgt_a[(s + k) % ndev, :len(tgt)] = tgt
        corr_send[k] = src_a
        corr_w[k] = w_a
        corr_tgt[k] = tgt_a

    spec = SweepCommSpec(mesh=mesh, fine_offsets=tuple(foffs),
                         coarse_offsets=tuple(coffs),
                         corr_offsets=tuple(koffs), itype=itype,
                         backend=dma_halo.resolve_backend(halo_backend))
    arrays = dict(
        lsten=lsten, licell=licell, linb=linb, lisgn=lisgn,
        own_src=own_src_a, own_tgt=own_tgt_a, own_w=own_w_a,
    )
    for k in foffs:
        arrays[f"fsend_{k}"] = fsend[k]
    for k in coffs:
        arrays[f"csend_{k}"] = csend[k]
    for k in koffs:
        arrays[f"corr_send_{k}"] = corr_send[k]
        arrays[f"corr_w_{k}"] = corr_w[k]
        arrays[f"corr_tgt_{k}"] = corr_tgt[k]
    return spec, arrays


def _perm(ndev: int, k: int):
    return [(p, (p + k) % ndev) for p in range(ndev)]


def sweep_correct_explicit(u_l, u_lm1, unew_lm1, d: dict, dt, dx: float,
                           cfg, spec: SweepCommSpec):
    """One partial-level sweep + coarse correction fold with the
    explicit schedule; drop-in for the global-view

        interp = K.interp_cells(...); du, corr = K.level_sweep(...)
        unew_lm1 = K.scatter_corrections(unew_lm1, corr, corr_idx, ...)

    Returns (du_flat rows of level l, updated unew_{l-1})."""
    from ramses_tpu.amr import kernels as K

    mesh = spec.mesh
    ndev = mesh.shape[AXIS]
    cm = d["comm"]

    def body(u_loc, uc_loc, unew_loc, dt_r, vsgn_loc, ok_loc, *sched):
        it = iter(sched)
        lsten = next(it)[0]
        licell, linb, lisgn = next(it)[0], next(it)[0], next(it)[0]
        own_src, own_tgt, own_w = (next(it)[0], next(it)[0],
                                   next(it)[0])
        fsend = {k: next(it)[0] for k in spec.fine_offsets}
        csend = {k: next(it)[0] for k in spec.coarse_offsets}
        corr_send = {k: next(it)[0] for k in spec.corr_offsets}
        corr_w = {k: next(it)[0] for k in spec.corr_offsets}
        corr_tgt = {k: next(it)[0] for k in spec.corr_offsets}

        # P2: fine + coarse halos — pack own rows, move them along the
        # ring in ONE fused backend exchange (every offset's buffer is
        # a separate slab of the same DMA kernel on TPU)
        halo = dma_halo.exchange_slabs(
            [u_loc[fsend[k]] for k in spec.fine_offsets]
            + [uc_loc[csend[k]] for k in spec.coarse_offsets],
            [_perm(ndev, k) for k in spec.fine_offsets]
            + [_perm(ndev, k) for k in spec.coarse_offsets],
            AXIS, backend=spec.backend)
        nf = len(spec.fine_offsets)
        u_ext = jnp.concatenate([u_loc] + halo[:nf], axis=0)
        uc_ext = jnp.concatenate([uc_loc] + halo[nf:], axis=0)

        interp = K.interp_cells(uc_ext, licell, linb,
                                lisgn.astype(u_loc.dtype), cfg,
                                itype=spec.itype)
        du, corr = K.level_sweep(u_ext, interp, lsten,
                                 vsgn_loc if has_vsgn else None, ok_loc,
                                 None, dt_r, dx, cfg)

        # P3: deterministic owner-fold — own first, then offsets
        # ascending (sorted segment order is fixed by the schedule)
        cflat = corr.reshape(-1, corr.shape[-1])
        unew_loc = unew_loc.at[own_tgt].add(
            (cflat[own_src] * own_w[:, None]).astype(unew_loc.dtype))
        if spec.corr_offsets:
            gots = dma_halo.exchange_slabs(
                [cflat[corr_send[k]] * corr_w[k][:, None]
                 for k in spec.corr_offsets],
                [_perm(ndev, k) for k in spec.corr_offsets],
                AXIS, backend=spec.backend)
            for k, got in zip(spec.corr_offsets, gots):
                unew_loc = unew_loc.at[corr_tgt[k]].add(
                    got.astype(unew_loc.dtype))
        return du, unew_loc

    sched_names = (["lsten", "licell", "linb", "lisgn", "own_src",
                    "own_tgt", "own_w"]
                   + [f"fsend_{k}" for k in spec.fine_offsets]
                   + [f"csend_{k}" for k in spec.coarse_offsets]
                   + [f"corr_send_{k}" for k in spec.corr_offsets]
                   + [f"corr_w_{k}" for k in spec.corr_offsets]
                   + [f"corr_tgt_{k}" for k in spec.corr_offsets])
    sched = [cm[n] for n in sched_names]
    has_vsgn = d["vsgn"] is not None
    vsgn = (d["vsgn"] if has_vsgn
            else jnp.zeros_like(d["ok_ref"], dtype=jnp.uint8))
    fn = _shard_map(
        body, mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(), P(AXIS), P(AXIS))
        + (P(AXIS),) * len(sched),
        out_specs=(P(AXIS), P(AXIS)),
        check_rep=(spec.backend != "dma"))
    return fn(u_l, u_lm1, unew_lm1, jnp.asarray(dt), vsgn, d["ok_ref"],
              *sched)


def fold_corrections_explicit(corr, unew_lm1, d: dict,
                              spec: SweepCommSpec):
    """Deterministic owner-fold of precomputed partial-level corrections
    — the P3 leg of :func:`sweep_correct_explicit` alone.

    For solvers whose partial-level sweep cannot run inside the
    shard_map (the MHD CT sweep carries staggered faces and child-EMF
    overrides the hydro schedule knows nothing about), the sweep stays
    global-view but the coarse fold still must not be a GSPMD scatter-
    add: the partitioner turns ``unew.at[idx].add`` over shard-crossing
    indices into an all-gathered scatter whose fold order is
    unspecified.  This reuses the same reverse schedule — own entries
    first, then ring offsets ascending — so the fold is bitwise
    reproducible and identical across halo backends.

    ``corr`` is the level-l ``[noct_pad, ndim, 2, nvar]`` correction
    block (row-sharded like u_l); the schedule's weights already carry
    ``±1/2^ndim`` and the validity mask, making this a drop-in for
    ``K.scatter_corrections(unew_lm1, corr, corr_idx, cfg)``."""
    mesh = spec.mesh
    ndev = mesh.shape[AXIS]
    cm = d["comm"]

    def body(c_loc, unew_loc, *sched):
        it = iter(sched)
        own_src, own_tgt, own_w = (next(it)[0], next(it)[0],
                                   next(it)[0])
        corr_send = {k: next(it)[0] for k in spec.corr_offsets}
        corr_w = {k: next(it)[0] for k in spec.corr_offsets}
        corr_tgt = {k: next(it)[0] for k in spec.corr_offsets}
        cflat = c_loc.reshape(-1, c_loc.shape[-1])
        unew_loc = unew_loc.at[own_tgt].add(
            (cflat[own_src] * own_w[:, None]).astype(unew_loc.dtype))
        if spec.corr_offsets:
            gots = dma_halo.exchange_slabs(
                [cflat[corr_send[k]] * corr_w[k][:, None]
                 for k in spec.corr_offsets],
                [_perm(ndev, k) for k in spec.corr_offsets],
                AXIS, backend=spec.backend)
            for k, got in zip(spec.corr_offsets, gots):
                unew_loc = unew_loc.at[corr_tgt[k]].add(
                    got.astype(unew_loc.dtype))
        return unew_loc

    sched_names = (["own_src", "own_tgt", "own_w"]
                   + [f"corr_send_{k}" for k in spec.corr_offsets]
                   + [f"corr_w_{k}" for k in spec.corr_offsets]
                   + [f"corr_tgt_{k}" for k in spec.corr_offsets])
    sched = [cm[n] for n in sched_names]
    fn = _shard_map(
        body, mesh,
        in_specs=(P(AXIS), P(AXIS)) + (P(AXIS),) * len(sched),
        out_specs=P(AXIS),
        check_rep=(spec.backend != "dma"))
    return fn(corr, unew_lm1, *sched)
