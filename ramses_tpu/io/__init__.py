"""Snapshot / restart I/O in the reference's on-disk format (SURVEY.md §3.4,
§5.4): Fortran sequential-unformatted record files under ``output_NNNNN/``."""
