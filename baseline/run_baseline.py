#!/usr/bin/env python
"""Build and run the CPU baseline proxies; record results in BASELINE.json.

The driver's north star compares TPU cell-updates/sec against a "64-rank
MPI CPU baseline" of the reference (BASELINE.md).  The reference is
Fortran 90 and this image ships no Fortran compiler (verified: no
gfortran/flang/ifx anywhere on the filesystem), so the baseline cannot be
produced by running the reference itself.  This script produces the
nearest honest substitute: C++ re-creations of the reference's two hot
kernels (muscl3d.cc — the hydro/umuscl.f90 MUSCL-Hancock+HLLC update;
mg3d.cc — the poisson/multigrid_fine_fine.f90 red-black V-cycle),
compiled -O3 -march=native and measured on this host's CPU, extrapolated
to 64 ranks assuming *perfect* linear scaling.  Both choices (kernel-only
cost without AMR/MPI overhead; perfect scaling) make the baseline FASTER
than a real 64-rank reference run would be, i.e. they are conservative
for the TPU framework's vs_baseline ratio.

Usage: python baseline/run_baseline.py   (writes ../BASELINE.json in place)
"""

import json
import os
import platform
import subprocess

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

PROXIES = {
    "muscl3d": ("muscl3d.cc", ["128", "5"]),
    "mg3d": ("mg3d.cc", ["128", "10"]),
}


def build_and_run(name, src, args):
    exe = os.path.join(HERE, name)
    subprocess.run(
        ["g++", "-O3", "-march=native", "-funroll-loops", "-o", exe,
         os.path.join(HERE, src)], check=True)
    out = subprocess.run([exe] + args, check=True, capture_output=True,
                         text=True).stdout.strip()
    return json.loads(out.splitlines()[-1])


def cpu_model():
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return platform.processor()


def main():
    hydro = build_and_run("muscl3d", *PROXIES["muscl3d"])
    mg = build_and_run("mg3d", *PROXIES["mg3d"])
    nranks = 64

    published = {
        "method": (
            "measured C++ proxy kernels (baseline/muscl3d.cc, baseline/"
            "mg3d.cc) recreating the reference's hot loops; the reference "
            "itself cannot be compiled in this image (no Fortran "
            "compiler). Kernel-only cost + perfect 64-rank scaling both "
            "overestimate the baseline, so vs_baseline is conservative."),
        "host_cpu": cpu_model(),
        "hydro": {
            "proxy": hydro,
            "mus_per_cell_update_1core": hydro["mus_per_cell_update"],
            "cell_updates_per_sec_1core": hydro["cell_updates_per_sec"],
            "cell_updates_per_sec_64rank":
                hydro["cell_updates_per_sec"] * nranks,
        },
        "multigrid": {
            "proxy": mg,
            "vcycles_per_sec_128_1core": mg["vcycles_per_sec"],
            "vcycles_per_sec_128_64rank": mg["vcycles_per_sec"] * nranks,
        },
        "nranks_extrapolated": nranks,
    }

    path = os.path.join(REPO, "BASELINE.json")
    with open(path) as f:
        doc = json.load(f)
    doc["published"] = published
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(published["hydro"], indent=2))
    print(json.dumps(published["multigrid"], indent=2))


if __name__ == "__main__":
    main()
