"""Persistent compile cache setup (&RUN_PARAMS compile_cache_dir).

``platform.setup_compile_cache`` points JAX's persistent compilation
cache at an operator-named directory BEFORE the first trace — unlike
the package-import default it is honored on CPU-forced runs too, since
the operator asked for it by name.  These tests pin the plumbing only
(config update, env fallback, stats surface, fail-soft on a bad path);
actual cache hits are a backend concern exercised on TPU.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ramses_tpu import platform
from ramses_tpu.config import params_from_string

pytestmark = pytest.mark.smoke

MINI = """
&RUN_PARAMS
hydro=.true.
{extra}
/
&AMR_PARAMS
levelmin=3
levelmax=3
/
"""


def _params(extra=""):
    return params_from_string(MINI.format(extra=extra), ndim=2)


@pytest.fixture
def restore_jax_cache_config():
    import jax

    keys = ("jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
            "jax_persistent_cache_enable_xla_caches")
    old = {k: getattr(jax.config, k) for k in keys}
    olddir = platform._CACHE_STATS["dir"]
    yield
    for k, v in old.items():
        jax.config.update(k, v)
    platform._CACHE_STATS["dir"] = olddir


def test_explicit_dir_configures_jax(tmp_path, restore_jax_cache_config):
    import jax

    d = str(tmp_path / "xla_cache")
    p = _params(f"compile_cache_dir='{d}'")
    got = platform.setup_compile_cache(p)
    assert got == d
    assert os.path.isdir(d)
    assert jax.config.jax_compilation_cache_dir == d
    assert platform.compile_cache_stats()["dir"] == d


def test_unset_leaves_cache_alone(monkeypatch):
    monkeypatch.delenv("RAMSES_COMPILE_CACHE", raising=False)
    assert platform.setup_compile_cache(_params()) == ""


def test_env_fallback(tmp_path, monkeypatch, restore_jax_cache_config):
    d = str(tmp_path / "env_cache")
    monkeypatch.setenv("RAMSES_COMPILE_CACHE", d)
    assert platform.setup_compile_cache(_params()) == d
    # the namelist field wins over the env when both are set
    d2 = str(tmp_path / "nml_cache")
    p = _params(f"compile_cache_dir='{d2}'")
    assert platform.setup_compile_cache(p) == d2


def test_bad_path_warns_and_runs_uncached(tmp_path,
                                          restore_jax_cache_config):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("x")
    p = _params(f"compile_cache_dir='{blocker}/sub'")
    with pytest.warns(UserWarning, match="not usable"):
        assert platform.setup_compile_cache(p) == ""
