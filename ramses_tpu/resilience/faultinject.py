"""Deterministic fault injection for resilience testing.

Spec syntax (``&RUN_PARAMS fault_inject='...'`` or env
``RAMSES_FAULT_INJECT``), comma-separable:

  ``nan@K``            poison one cell of the state with NaN just
                       before the coarse step that starts at nstep K
  ``nan@K:member=J``   same, but targeted at ensemble member J — the
                       batched engine clamps its fused windows so the
                       fault lands exactly at member J's step K
  ``sigterm@K``        deliver SIGTERM to this process at the guard
                       check when nstep >= K
  ``hang@K``           block the host thread inside the deadline-
                       guarded window that starts at nstep K — the
                       watchdog (resilience/watchdog.py) must detect
                       and classify it within ``step_deadline_s``
  ``hang@K:member=J``  same, triggered by ensemble member J reaching
                       its step K (the batched engine clamps windows
                       so the hang lands exactly there)
  ``truncate:NAME``    after the next checkpoint finalize, truncate
                       the file whose basename contains NAME (breaks
                       its manifest hash — validation must catch it)
  ``torn@K:shard=J``   during the first elastic pario dump at
                       nstep >= K, corrupt shard J's payload bytes
                       AFTER its shard manifest is staged (size
                       preserved, so the cheap size-only commit scan
                       passes and the checkpoint commits) — the
                       restore-side full-hash validation must catch
                       it, quarantine the shard, and fall back
  ``die@K:host=J``     during the first elastic pario dump at
                       nstep >= K, process J exits hard AFTER staging
                       its shards but BEFORE the global commit
                       barrier — the surviving hosts' watchdogged
                       barrier must kill-and-fall-through, and the
                       torn ``pario_NNNNN.tmp`` staging dir must
                       never scan as a valid checkpoint
  ``zombie@K``         fleet-layer: the claimed worker's host thread
                       sleeps ``RAMSES_ZOMBIE_SLEEP_S`` (default 5s)
                       at the chunk that starts at nstep K — long
                       enough for a short ``stale_timeout`` to
                       reclaim the job — then RESUMES and keeps
                       writing; the queue's fencing token must refuse
                       its late heartbeat/complete()
  ``enospc@K``         fleet-layer: the next checkpoint staging write
                       at nstep >= K raises ``OSError(ENOSPC)`` —
                       diskguard must shed the checkpoint and keep
                       the worker alive
  ``skew:<s>``         fleet-layer: bias every heartbeat wall-time
                       stamp by ``s`` seconds (positive or negative)
                       — the observer-clock reclaim logic must not
                       false-trip on it

Arming is strict: a fault fires only if the run is seen at
``nstep < K`` first, so a resumed run that restarts at nstep >= K does
not re-fire the same fault — exactly-once per logical run.  ``torn``
and ``die`` arm through the same per-step observations as ``nan``
(the window clamp / guard checks the drivers already make), then fire
inside the dump path.
"""

from __future__ import annotations

import os
import signal
from typing import Optional

ENV_VAR = "RAMSES_FAULT_INJECT"

# exit code of a die@K fault — distinct from HANG_EXIT_CODE (87) so a
# supervising shell can tell an injected mid-commit death from a
# watchdog kill
DIE_EXIT_CODE = 3

# each step-indexed kind's accepted ':key=' suffix (torn targets a
# shard index, die a host/process index, nan/hang an ensemble member)
_OPT_KEY = {"nan": "member", "hang": "member",
            "torn": "shard", "die": "host"}

# every step-indexed kind participates in strict arming and the fused
# window clamp — a torn/die fault must not be skipped over by a fused
# multi-step dispatch any more than a nan may be
STEP_KINDS = ("nan", "sigterm", "hang", "torn", "die",
              "zombie", "enospc")

# step-indexed kinds that take no ':key=' target option
_UNTARGETED_AT = ("sigterm", "zombie", "enospc")


def _parse(spec: str):
    """(faults, targets): ``faults`` keeps the historic 2-tuple shape
    (kind, arg); targeting options (``member=J``/``shard=J``/
    ``host=J``) ride in the parallel ``targets`` dict keyed by fault
    index."""
    faults = []
    targets = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        kind, sep, rest = part.partition("@")
        if sep and kind in _OPT_KEY:
            body, _, opt = rest.partition(":")
            if opt:
                want = _OPT_KEY[kind]
                if not opt.startswith(want + "="):
                    raise ValueError(
                        f"unknown fault_inject option {opt!r} "
                        f"in {part!r} (expected {want}=J)")
                targets[len(faults)] = int(opt[len(want) + 1:])
            faults.append((kind, int(body)))
        elif sep and kind in _UNTARGETED_AT:
            faults.append((kind, int(rest)))
        elif part.startswith("truncate:"):
            faults.append(("truncate", part[len("truncate:"):]))
        elif part.startswith("skew:"):
            faults.append(("skew", float(part[len("skew:"):])))
        else:
            raise ValueError(f"unknown fault_inject spec {part!r}")
    return faults, targets


class FaultInjector:
    """Holds the parsed fault list and per-fault armed/fired state."""

    def __init__(self, spec: str):
        self.faults, targets = _parse(spec)
        # split the target dict by what the index means: ensemble
        # member (nan/hang), shard (torn), host/process (die)
        self.member_of = {i: t for i, t in targets.items()
                          if self.faults[i][0] in ("nan", "hang")}
        self.shard_of = {i: t for i, t in targets.items()
                         if self.faults[i][0] == "torn"}
        self.host_of = {i: t for i, t in targets.items()
                        if self.faults[i][0] == "die"}
        self._armed = {}          # idx -> bool (saw nstep < K)
        self._fired = set()

    @classmethod
    def from_params(cls, params) -> Optional["FaultInjector"]:
        spec = str(getattr(getattr(params, "run", None),
                           "fault_inject", "") or "")
        env = os.environ.get(ENV_VAR, "")
        joined = ",".join(s for s in (spec, env) if s)
        if not joined:
            return None
        inj = cls(joined)
        return inj if inj.faults else None

    def _should_fire(self, idx: int, kind: str, nstep: int) -> bool:
        k = self.faults[idx][1]
        if idx in self._fired:
            return False
        if idx not in self._armed:
            # Strict arming: only a run first observed BEFORE the
            # trigger step can fire — a resume at nstep >= K won't.
            self._armed[idx] = nstep < k
        if not self._armed[idx]:
            return False
        if nstep >= k:
            self._fired.add(idx)
            return True
        return False

    def maybe_nan(self, sim) -> bool:
        """Poison one cell of ``sim``'s state with NaN when armed."""
        nstep = int(getattr(sim, "nstep",
                            getattr(getattr(sim, "state", None),
                                    "nstep", 0)))
        for i, (kind, _arg) in enumerate(self.faults):
            if kind != "nan" or i in self.member_of \
                    or not self._should_fire(i, kind, nstep):
                continue               # member-targeted: batched engine
            import numpy as np
            u = getattr(sim, "u", None)
            if u is None and getattr(sim, "state", None) is not None:
                u = sim.state.u
            if isinstance(u, dict):
                lv = min(u)
                arr = u[lv]
                u[lv] = arr.at[(0,) * (arr.ndim - 1) + (0,)].set(
                    np.nan)
            else:
                poisoned = u.at[(0,) * u.ndim].set(np.nan)
                if getattr(sim, "state", None) is not None and \
                        getattr(sim.state, "u", None) is u:
                    sim.state.u = poisoned
                else:
                    sim.u = poisoned
            print(f" fault-inject: NaN poisoned at nstep={nstep}")
            return True
        return False

    def observe(self, nstep: int) -> None:
        """Strict-arming observation for the dump-path faults
        (torn/die/enospc): they fire inside the dump/staging path, far
        from any per-step guard, so the window clamp — which every
        driver calls with the current nstep — records 'seen at
        nstep < K' for them.  nan/sigterm/hang/zombie arming stays
        inside their own guard checks (member-targeted faults must arm
        against the MEMBER's step)."""
        for i, (kind, k) in enumerate(self.faults):
            if kind in ("torn", "die", "enospc") \
                    and i not in self._armed:
                self._armed[i] = int(nstep) < int(k)

    def clamp_window(self, nstep: int, n: int) -> int:
        """Largest window size <= ``n`` that does not fuse past the
        next pending step-indexed fault target.  The uniform drivers
        run many coarse steps per device dispatch; without this clamp
        a ``nan@K``/``sigterm@K`` could only land on chunk boundaries
        — and a ``torn@K``/``die@K`` could miss the dump that was
        supposed to carry it.
        """
        nstep = int(nstep)
        self.observe(nstep)
        for i, (kind, k) in enumerate(self.faults):
            if kind not in STEP_KINDS \
                    or i in self._fired or self._hang_done(i):
                continue
            if self._armed.get(i) is False:
                continue               # resumed past K: will never fire
            if nstep < int(k):
                n = min(n, int(k) - nstep)
        return max(1, int(n))

    def clamp_window_batch(self, n: int, nstep_global: int,
                           member_nstep) -> int:
        """:meth:`clamp_window` for the batched engine: member-targeted
        faults clamp against *that member's* step count
        (``member_nstep(j)``; members of a group can lag each other
        after a retry), untargeted faults against the engine-global
        ``nstep_global`` — so ``nan@K:member=J`` lands exactly at
        member J's step K inside a fused window."""
        self.observe(int(nstep_global))
        for i, (kind, k) in enumerate(self.faults):
            if kind not in STEP_KINDS \
                    or i in self._fired or self._hang_done(i):
                continue
            if self._armed.get(i) is False:
                continue
            j = self.member_of.get(i)
            ns = int(nstep_global if j is None else member_nstep(j))
            if ns < int(k):
                n = min(n, int(k) - ns)
        return max(1, int(n))

    def maybe_nan_batch(self, group) -> list:
        """Poison one cell of each armed member-targeted fault whose
        member lives in this sub-batch (duck-typed: ``group`` has
        ``members``/``state``/``nstep``).  Returns the member indices
        poisoned.  Must run *after* the engine retained its pre-window
        state, so rollback restores the clean arrays."""
        import numpy as np
        poisoned = []
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "nan":
                continue
            j = self.member_of.get(i)
            if j is None or j not in group.members:
                continue
            pos = group.members.index(j)
            if not self._should_fire(i, kind, int(group.nstep[pos])):
                continue
            u = group.state[0]
            idx = (pos,) + (0,) * (u.ndim - 1)
            group.state = (u.at[idx].set(np.nan),) + group.state[1:]
            print(f" fault-inject: NaN poisoned member {j} at "
                  f"nstep={int(group.nstep[pos])}")
            poisoned.append(j)
        return poisoned

    def _hang_key(self, idx: int):
        kind, k = self.faults[idx]
        return (kind, int(k), self.member_of.get(idx))

    def _hang_done(self, idx: int) -> bool:
        """Hang faults fire once per PROCESS, not once per injector:
        the hang-policy resume (supervisor) or re-claim (serve loop)
        rebuilds the sim — and with it a fresh injector — inside the
        same process, usually from a checkpoint *before* K; without
        process-wide state the resumed run would re-arm and hang
        forever inside the bounded retry budget."""
        if self.faults[idx][0] != "hang":
            return False
        return self._hang_key(idx) in _hang_fired

    def _hang_now(self, nstep: int, member=None):
        """Block the host thread: sleep until the watchdog's SIGALRM
        soft interrupt raises HangDetected out of the sleep, capped
        (RAMSES_HANG_INJECT_CAP_S, default 60s) so a misconfigured run
        without a watchdog still terminates."""
        import time
        cap = float(os.environ.get("RAMSES_HANG_INJECT_CAP_S", "60"))
        tag = f" member {member}" if member is not None else ""
        print(f" fault-inject: hanging{tag} at nstep={int(nstep)} "
              f"(cap {cap:g}s)", flush=True)
        end = time.monotonic() + cap
        while True:
            left = end - time.monotonic()
            if left <= 0.0:
                print(" fault-inject: hang cap expired with no "
                      "watchdog; continuing", flush=True)
                return
            time.sleep(min(0.5, left))

    def maybe_hang(self, nstep: int) -> bool:
        """Injected hang for the solo drivers (untargeted ``hang@K``):
        call INSIDE the watchdog-guarded window so the deadline is
        what ends it."""
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "hang" or i in self.member_of \
                    or self._hang_done(i) \
                    or not self._should_fire(i, kind, int(nstep)):
                continue
            _hang_fired.add(self._hang_key(i))
            self._hang_now(nstep)
            return True
        return False

    def maybe_hang_batch(self, group, nstep_global: int) -> bool:
        """Injected hang for the batched engine: member-targeted
        faults trigger off that member's own step count, untargeted
        ones off the engine-global ``nstep_global``."""
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "hang" or self._hang_done(i):
                continue
            j = self.member_of.get(i)
            if j is None:
                ns = int(nstep_global)
            elif j in group.members:
                ns = int(group.nstep[group.members.index(j)])
            else:
                continue
            if not self._should_fire(i, kind, ns):
                continue
            _hang_fired.add(self._hang_key(i))
            self._hang_now(ns, member=j)
            return True
        return False

    def maybe_signal(self, nstep: int) -> bool:
        """SIGTERM this process when armed (OpsGuard handles it)."""
        for i, (kind, _arg) in enumerate(self.faults):
            if kind != "sigterm" or not self._should_fire(i, kind,
                                                          int(nstep)):
                continue
            print(f" fault-inject: SIGTERM at nstep={int(nstep)}")
            os.kill(os.getpid(), signal.SIGTERM)
            return True
        return False

    def maybe_zombie(self, nstep: int) -> bool:
        """``zombie@K``: stall the host thread long enough for a
        short ``stale_timeout`` to reclaim the job, then RETURN — the
        worker resumes and keeps writing, and the queue's fencing
        token (not this injector) is what must stop it.  Sleep length
        is ``RAMSES_ZOMBIE_SLEEP_S`` (default 5s).  Once per process
        (like hang): the re-claimed attempt rebuilds the injector in
        the same process and must not re-stall."""
        import time
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "zombie":
                continue
            key = (kind, int(self.faults[i][1]))
            if key in _zombie_fired \
                    or not self._should_fire(i, kind, int(nstep)):
                continue
            _zombie_fired.add(key)
            sleep_s = float(os.environ.get(
                "RAMSES_ZOMBIE_SLEEP_S", "5"))
            print(f" fault-inject: zombie stall {sleep_s:g}s at "
                  f"nstep={int(nstep)}", flush=True)
            time.sleep(sleep_s)
            print(" fault-inject: zombie woke — resuming writes",
                  flush=True)
            return True
        return False

    def maybe_enospc(self, nstep: int) -> None:
        """``enospc@K``: raise ``OSError(ENOSPC)`` out of the next
        checkpoint staging write at nstep >= K — diskguard must
        absorb it.  Once per process, so the job's later (and final)
        checkpoints land."""
        import errno
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "enospc":
                continue
            key = (kind, int(self.faults[i][1]))
            if key in _enospc_fired \
                    or not self._should_fire(i, kind, int(nstep)):
                continue
            _enospc_fired.add(key)
            print(f" fault-inject: ENOSPC at nstep={int(nstep)}",
                  flush=True)
            raise OSError(errno.ENOSPC, "fault-inject: no space "
                          "left on device")

    def maybe_torn(self, shard_dir: str, shard: int,
                   nstep: int) -> bool:
        """``torn@K:shard=J``: called by ``dump_pario`` after shard
        ``shard``'s manifest is staged and validated, just before the
        shard dir is committed.  Flips bytes in the middle of the
        shard's largest payload file WITHOUT changing its size — the
        commit-time size-only scan passes, so the torn shard ships
        inside a globally-committed checkpoint and only full-hash
        validation (restore / scrubber) can convict it."""
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "torn" or self.shard_of.get(i, 0) != int(shard):
                continue
            if not self._should_fire(i, kind, int(nstep)):
                continue
            target, tsize = None, -1
            for fn in os.listdir(shard_dir):
                p = os.path.join(shard_dir, fn)
                if fn == "manifest.json" or not os.path.isfile(p):
                    continue
                if os.path.getsize(p) > tsize:
                    target, tsize = p, os.path.getsize(p)
            if target is None:
                return False
            with open(target, "r+b") as f:
                f.seek(tsize // 2)
                chunk = f.read(64)
                f.seek(tsize // 2)
                f.write(bytes(b ^ 0xFF for b in chunk))
                f.flush()
                os.fsync(f.fileno())
            print(f" fault-inject: tore shard {int(shard)} payload "
                  f"{os.path.basename(target)} at nstep={int(nstep)}",
                  flush=True)
            return True
        return False

    def maybe_die(self, nstep: int, host: int) -> bool:
        """``die@K:host=J``: called by ``dump_pario`` on process
        ``host`` after its shards are staged but BEFORE the global
        commit barrier — the injected mid-commit host death.  Exits
        the process hard (``os._exit``: no atexit, no flushing, the
        closest sane stand-in for a SIGKILL'd host)."""
        for i, (kind, _k) in enumerate(self.faults):
            if kind != "die" or self.host_of.get(i, 0) != int(host):
                continue
            if not self._should_fire(i, kind, int(nstep)):
                continue
            print(f" fault-inject: host {int(host)} dying mid-commit "
                  f"at nstep={int(nstep)}", flush=True)
            _die(DIE_EXIT_CODE)
            return True                    # only under a patched _die
        return False


def _die(code: int):
    """Hard process exit for ``die@K`` (module-level so tests can
    patch it into a raise instead of killing the test runner)."""
    os._exit(code)


# ---- process-wide fired state ---------------------------------------

# hang faults already delivered in this process (see _hang_done)
_hang_fired = set()

# fleet-layer faults already delivered in this process: resumed /
# re-claimed attempts rebuild the injector but must not re-fire
_zombie_fired = set()
_enospc_fired = set()


def heartbeat_skew() -> float:
    """Summed ``skew:<s>`` bias (seconds) from the env spec — applied
    by the queue's heartbeat writer to its wall-time stamp.  Env-only
    on purpose: the skew is a property of the (simulated) worker
    host, not of any one job's namelist."""
    spec = os.environ.get(ENV_VAR, "")
    if "skew:" not in spec:
        return 0.0
    try:
        faults, _targets = _parse(spec)
    except ValueError:
        return 0.0
    return float(sum(arg for kind, arg in faults if kind == "skew"))


def reset_fired():
    """Forget process-wide fired state (test isolation)."""
    _hang_fired.clear()
    _truncate_fired.clear()
    _zombie_fired.clear()
    _enospc_fired.clear()


# ---- post-dump truncation (module-level: dump may run on the
#      AsyncDumper thread with no sim in reach) -----------------------

_truncate_fired = set()


def post_dump(outdir: str):
    """Called by dump_all after finalize; truncates a matching file
    once per process when a ``truncate:NAME`` fault is configured."""
    spec = os.environ.get(ENV_VAR, "")
    if "truncate:" not in spec:
        return
    faults, _members = _parse(spec)
    for kind, name in faults:
        if kind != "truncate" or name in _truncate_fired:
            continue
        for root, _dirs, files in os.walk(outdir):
            for fn in files:
                if name in fn and fn != "manifest.json":
                    p = os.path.join(root, fn)
                    sz = os.path.getsize(p)
                    with open(p, "r+b") as f:
                        f.truncate(max(0, sz // 2))
                    _truncate_fired.add(name)
                    print(f" fault-inject: truncated {p}")
                    return
