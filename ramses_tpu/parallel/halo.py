"""Explicit halo-exchange backend: shard_map slab pipeline.

The global-view path (:mod:`ramses_tpu.parallel.sharded`) leaves halo
communication to XLA's SPMD partitioner.  This module is the EXPLICIT
formulation of the reference's two-sided message schedule
(``amr/virtual_boundaries.f90:373-533`` ``make_virtual_fine``): the
state lives as per-device blocks under ``jax.shard_map``, each step
sends the ``NGHOST``-deep boundary slabs to the ring neighbours
through the backend-dispatched exchange engine
(:mod:`ramses_tpu.parallel.dma_halo` — Pallas async remote-copy DMA on
TPU, ``lax.ppermute`` elsewhere), pads the remaining axes locally, and
runs the unchanged MUSCL kernels on the interior.  The CFL reduction
is a ``lax.pmin`` over the mesh axis (P7).

On the DMA backend the step is region-split for comm/compute overlap:
the boundary slabs start their async remote copy, the interior band
(which reads no cross-device ghosts) is computed while the transfer is
in flight, and two ``NGHOST``-thin strips are finished from the
received ghosts — the hand-scheduled overlap the reference gets from
posting MPI_Isend/Irecv before the interior sweep.  The MUSCL update
is pure per-cell arithmetic, so the split is bitwise-invisible.

Why keep both: the GSPMD path is the idiomatic TPU formulation and
lets the compiler fuse; this path pins the communication schedule —
deterministic slab order, no partitioner heuristics.  All backends
must agree bitwise on periodic boxes (asserted in ``tests/test_halo.py``
and ``tests/test_dma_halo.py``).

Scope: fully periodic boxes, 1-D decomposition over the leading
spatial axis — the Hilbert-order row decomposition every other sharded
path uses (P1).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.timestep import compute_dt
from ramses_tpu.parallel import dma_halo

AXIS = "hx"          # mesh axis name of the slab decomposition


def make_halo_mesh(devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (AXIS,))


def _check(grid: UniformGrid, mesh: Mesh):
    n = mesh.shape[AXIS]
    if any(f[0].kind != 0 or f[1].kind != 0 for f in grid.bc.faces):
        raise NotImplementedError(
            "halo backend: fully periodic boxes only (physical "
            "boundary slabs stay on the GSPMD path)")
    if grid.shape[0] % n:
        raise ValueError(
            f"leading axis {grid.shape[0]} not divisible by the "
            f"{n}-device mesh")
    if grid.shape[0] // n < muscl.NGHOST:
        raise ValueError("shard thinner than the stencil halo")


def _ring(n: int):
    fwd = [(i, (i + 1) % n) for i in range(n)]    # data moves +x
    bwd = [(i, (i - 1) % n) for i in range(n)]    # data moves -x
    return fwd, bwd


def _exchange(u_loc, ng: int, n: int, backend: str):
    """Ring exchange of the leading-spatial-axis boundary slabs.

    ``u_loc``: [nvar, nx_loc, ...].  Returns the block extended to
    ``nx_loc + 2*ng`` — each device's low ghost slab is its left
    neighbour's high interior slab and vice versa (periodic ring, so
    device 0's left neighbour is device n-1: the wrap IS the physical
    periodic boundary)."""
    fwd, bwd = _ring(n)
    lo_ghost, hi_ghost = dma_halo.exchange_pair(
        u_loc[:, -ng:], u_loc[:, :ng], AXIS, fwd, bwd, backend=backend)
    return jnp.concatenate([lo_ghost, u_loc, hi_ghost], axis=1)


def _pad_rest(u_ext, ndim: int, ng: int):
    """Periodic-wrap padding of the non-decomposed spatial axes."""
    pads = [(0, 0), (0, 0)] + [(ng, ng)] * (ndim - 1)
    return jnp.pad(u_ext, pads, mode="wrap")


def _muscl_block(up, dt, grid: UniformGrid):
    """The padded-block MUSCL pipeline: ``up`` carries ``NGHOST``
    ghosts on every spatial axis; returns the unpadded interior."""
    cfg = grid.cfg
    flux, tmp = muscl.unsplit(up, None, dt, (grid.dx,) * cfg.ndim, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    if cfg.pressure_fix or cfg.nener:
        un = muscl.dual_energy_fix(up, un, tmp, dt,
                                   (grid.dx,) * cfg.ndim, cfg)
    return bmod.unpad(un, cfg.ndim, ng=muscl.NGHOST)


def _local_step(u_loc, dt, grid: UniformGrid, n: int, backend: str,
                split: bool):
    cfg = grid.cfg
    ng = muscl.NGHOST
    if not split:
        up = _pad_rest(_exchange(u_loc, ng, n, backend), cfg.ndim, ng)
        return _muscl_block(up, dt, grid)
    # DMA overlap split: pad the uncut axes first, start the ring
    # exchange of the (rest-padded) boundary slabs, compute the
    # interior band while the copies are in flight, then finish the
    # two NGHOST-thin strips from the received ghosts.  Exchanging
    # rest-padded slabs reproduces the corner values of the sequenced
    # pad-after-exchange order bitwise (the wrap is a per-axis local
    # copy, identical on either side of the exchange).
    upr = _pad_rest(u_loc, cfg.ndim, ng)
    fwd, bwd = _ring(n)
    lo_g, hi_g = dma_halo.exchange_pair(
        upr[:, -ng:], upr[:, :ng], AXIS, fwd, bwd, backend=backend)
    un_int = _muscl_block(upr, dt, grid)          # cells [ng, nx-ng)
    lo_blk = jnp.concatenate([lo_g, upr[:, :2 * ng]], axis=1)
    hi_blk = jnp.concatenate([upr[:, -2 * ng:], hi_g], axis=1)
    un_lo = _muscl_block(lo_blk, dt, grid)        # cells [0, ng)
    un_hi = _muscl_block(hi_blk, dt, grid)        # cells [nx-ng, nx)
    return jnp.concatenate([un_lo, un_int, un_hi], axis=1)


@lru_cache(maxsize=None)
def _build_run(grid: UniformGrid, mesh: Mesh, nsteps: int,
               backend: str):
    cfg = grid.cfg
    n = mesh.shape[AXIS]
    split = backend == "dma" and grid.shape[0] // n > 2 * muscl.NGHOST
    if split:
        nloc = grid.shape[0] // n
        dma_halo.TRAFFIC["overlap_frac"] = (
            (nloc - 2 * muscl.NGHOST) / nloc)

    def shard_body(u_loc, t, tend):
        def body(carry, _):
            u_loc, t, ndone = carry
            dt_loc = compute_dt(u_loc, None, grid.dx, cfg)
            dt = jax.lax.pmin(dt_loc, AXIS)
            dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
            active = t < tend
            un = _local_step(u_loc, jnp.where(active, dt, 0.0)
                             .astype(u_loc.dtype), grid, n, backend,
                             split)
            u_loc = jnp.where(active, un, u_loc)
            t = jnp.where(active, t + dt, t)
            ndone = ndone + jnp.where(active, 1, 0)
            return (u_loc, t, ndone), None

        # seed the step counter FROM t: older shard_map tracks a fresh
        # constant's replication as unknown, and the scan carry check
        # then rejects the (known-replicated) output counter
        ndone0 = (t - t).astype(jnp.int32)
        (u_loc, t, ndone), _ = jax.lax.scan(
            body, (u_loc, t, ndone0), None, length=nsteps)
        return u_loc, t, ndone

    return jax.jit(dma_halo.shard_map_compat(
        shard_body, mesh, (P(None, AXIS), P(), P()),
        (P(None, AXIS), P(), P()),
        check_rep=(backend != "dma")))


def run_steps_halo(grid: UniformGrid, mesh: Mesh, u, t, tend,
                   nsteps: int, halo_backend: str = "auto"):
    """``run_steps`` with the explicit slab pipeline: the whole window
    is ONE shard_map program; every step does one ring exchange (two
    slabs) + one pmin.  ``halo_backend``: ``auto``/``dma``/``ppermute``
    (:func:`ramses_tpu.parallel.dma_halo.resolve_backend`).  Returns
    (u, t, n_done) like the global-view version."""
    _check(grid, mesh)
    backend = dma_halo.resolve_backend(halo_backend)
    u = jax.device_put(u, NamedSharding(mesh, P(None, AXIS)))
    return _build_run(grid, mesh, nsteps, backend)(u, jnp.asarray(t),
                                                   jnp.asarray(tend))
