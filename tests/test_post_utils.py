"""Post-processing toolbox (``utils/f90`` equivalents,
``ramses_tpu.utils.post``)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.utils import post


@pytest.fixture(scope="module")
def snap_dir(tmp_path_factory):
    """One AMR snapshot with refinement + particles."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.pm.particles import ParticleSet

    rng = np.random.default_rng(3)
    x = np.concatenate([
        np.mod(rng.normal([0.5, 0.5, 0.5], 0.05, (200, 3)), 1.0),
        rng.uniform(0, 1, (56, 3))])
    p = ParticleSet.make(jnp.asarray(x),
                         jnp.asarray(rng.normal(0, 0.1, (256, 3))),
                         jnp.asarray(np.full(256, 1.0 / 256)))
    g = {
        "run_params": {"hydro": True, "poisson": True, "pic": True},
        "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, 10.0],
                        "p_region": [0.1, 5.0]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "refine_params": {"err_grad_d": 0.2},
        "output_params": {"tend": 0.02},
    }
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64,
                 particles=p)
    sim.evolve(0.01, nstepmax=3)
    base = tmp_path_factory.mktemp("snaps")
    return sim.dump(1, str(base)), sim


def test_amr2cube_mass_consistency(snap_dir):
    outdir, sim = snap_dir
    cube = post.amr2cube(outdir, var="density")
    n = cube.shape[0]
    assert n == 1 << 5                         # levelmax cube
    m_cube = cube.sum() / n ** 3               # boxlen=1
    m_sim = sim.totals()[0]
    assert np.isclose(m_cube, m_sim, rtol=1e-10)
    # the blob is denser than the background
    assert cube[n // 2, n // 2, n // 2] > cube[1, 1, 1]


def test_amr2cell_table(snap_dir, tmp_path):
    outdir, sim = snap_dir
    path = tmp_path / "cells.txt"
    nleaf = post.amr2cell(outdir, str(path))
    assert nleaf == sim.ncell_leaf()
    rows = np.loadtxt(path)
    assert rows.shape[0] == nleaf
    # x y z within the box; density positive
    assert (rows[:, :3] >= 0).all() and (rows[:, :3] <= 1).all()
    assert (rows[:, 5] > 0).all()


def test_part2cube_and_list(snap_dir, tmp_path):
    outdir, _sim = snap_dir
    cube = post.part2cube(outdir, n=16)
    assert np.isclose(cube.sum() / 16 ** 3, 1.0, rtol=1e-10)  # M=1
    n = post.part2list(outdir, str(tmp_path / "p.txt"))
    assert n == 256
    rows = np.loadtxt(tmp_path / "p.txt")
    assert rows.shape == (256, 8)


def test_histo_phase_diagram(snap_dir):
    outdir, sim = snap_dir
    H, xe, ye = post.histo(outdir, "density", "temperature", nbins=32)
    assert H.shape == (32, 32)
    assert np.isclose(H.sum(), sim.totals()[0], rtol=1e-10)


def test_profiles(snap_dir, tmp_path):
    outdir, _sim = snap_dir
    r, msh, prof = post.amr2prof(outdir, [0.5, 0.5, 0.5], nbins=16)
    assert len(r) == 16
    # central density above the outer bins (the blob)
    assert prof["density"][0] > prof["density"][-1]
    r2, msh2, prof2 = post.part2prof(outdir, [0.5, 0.5, 0.5], nbins=16)
    # particle mass concentrated centrally
    assert msh2[:4].sum() > msh2[-4:].sum()


def test_header_and_cli(snap_dir, tmp_path, capsys):
    outdir, sim = snap_dir
    h = post.header(outdir)
    assert h["ndim"] == 3 and h["npart"] == 256
    assert h["nlevelmax"] == 5
    # CLI smoke: every subcommand through main()
    assert post.main(["amr2cube", outdir, str(tmp_path / "c.npy")]) == 0
    assert post.main(["histo", outdir, str(tmp_path / "h.npz")]) == 0
    assert post.main(["amr2prof", outdir, str(tmp_path / "pr.txt")]) == 0
    assert post.main(["part2prof", outdir,
                      str(tmp_path / "pp.txt")]) == 0
    assert post.main(["header", outdir]) == 0


def test_async_dumper_roundtrip(snap_dir, tmp_path):
    """Background-thread snapshot writing (the pario offload,
    SURVEY.md §2.10): async dump == sync dump, errors surface on
    wait()."""
    from ramses_tpu.io.async_writer import AsyncDumper
    import filecmp
    import os

    _outdir, sim = snap_dir
    d_sync = sim.dump(3, str(tmp_path / "sync"))
    dumper = AsyncDumper()
    d_async = sim.dump(3, str(tmp_path / "async"), dumper=dumper)
    dumper.wait()
    files = sorted(os.listdir(d_sync))
    assert sorted(os.listdir(d_async)) == files
    for f in files:
        if f.endswith(".txt"):          # headers carry no timestamps
            continue
        assert filecmp.cmp(os.path.join(d_sync, f),
                           os.path.join(d_async, f), shallow=False), f

    # a bad path errors on wait, not in the compute thread
    from ramses_tpu.io import snapshot as snapmod
    snap = snapmod.snapshot_from_amr(sim, 4)
    blocker = tmp_path / "blockfile"
    blocker.write_text("x")
    dumper.submit(snap, 4, str(blocker / "sub"))   # dir under a FILE
    with pytest.raises(RuntimeError):
        dumper.wait()
    dumper.close()


def test_cut_cylprof_center_sod(snap_dir, tmp_path):
    """The second batch of analysis programs: slice, cylindrical
    profiles, shrinking-sphere centre, 1D sod extraction."""
    from ramses_tpu.utils.post import (amr2cut, amr2cylprof, main,
                                       part2cylprof, partcenter, sod)

    out, sim = snap_dir
    # slice through the blob: dense centre, finite values
    m = amr2cut(out, var="density", axis=2, coord=0.5)
    assert m.ndim == 2 and np.isfinite(m).all() and m.max() > m.mean()
    c = m.shape[0] // 2
    assert m[c, c] > np.median(m)
    # cylindrical gas profile: density falls outward from the blob
    R, mring, prof = amr2cylprof(out, [0.5, 0.5, 0.5], axis=2, nbins=8,
                                 rmax=0.4, zmax=0.1)
    assert prof["density"][0] > prof["density"][-1]
    # particle rotation-curve bins exist and are finite
    Rp, mp, pprof = part2cylprof(out, [0.5, 0.5, 0.5], axis=2, nbins=8)
    assert np.isfinite(pprof["vphi"]).all()
    # the particle cloud is centred near the box centre
    cm = partcenter(out)
    assert np.all(np.abs(cm - 0.5) < 0.1)
    # sod line: monotone x, full row count, positive density
    x, rho, v, press = sod(out, axis=0)
    assert np.all(np.diff(x) > 0) and (rho > 0).all()
    # CLI smoke for the new subcommands
    assert main(["amr2cut", out, str(tmp_path / "cut.npy")]) == 0
    assert main(["amr2cylprof", out, str(tmp_path / "cyl.txt")]) == 0
    assert main(["partcenter", out]) == 0
    assert main(["sod", out, str(tmp_path / "sod.txt")]) == 0


def test_birth_and_sfr(tmp_path):
    """part2birth/part2sfr read the star records of an SF snapshot."""
    import jax

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.pm.particles import FAM_STAR, ParticleSet
    from ramses_tpu.utils.post import main, part2birth, part2sfr

    rng = np.random.default_rng(4)
    n = 32
    ps = ParticleSet.make(rng.uniform(0.1, 0.9, (n, 2)),
                          np.zeros((n, 2)), np.full(n, 1.0 / n))
    import dataclasses
    ps = dataclasses.replace(
        ps, family=jnp.full((n,), FAM_STAR, jnp.int8),
        tp=jnp.asarray(rng.uniform(0.01, 0.5, n)))
    g = {
        "run_params": {"hydro": True, "poisson": True, "pic": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "d_region": [1.0], "p_region": [1.0]},
        "hydro_params": {"gamma": 1.4},
        "output_params": {"tend": 1.0},
    }
    sim = AmrSim(params_from_dict(g, ndim=2), dtype=jnp.float64,
                 particles=jax.device_put(ps))
    out = sim.dump(1, str(tmp_path))
    nstars = part2birth(out, str(tmp_path / "birth.txt"))
    assert nstars == n
    t, sfr = part2sfr(out, nbins=8)
    # total formed mass is recovered: sum sfr*dt == 1
    dt = t[1] - t[0]
    assert np.isclose((sfr * dt).sum(), 1.0, rtol=1e-6)
    assert main(["part2sfr", out, str(tmp_path / "sfr.txt")]) == 0


def test_part2map_vrot_starlist(snap_dir, tmp_path):
    """part2map surface density integrates to the total particle mass;
    vrot recovers a solid-body rotation curve; getstarlist filters
    stars (part2map.f90 / vrot.f90 / getstarlist.f90 roles)."""
    outdir, sim = snap_dir
    n = 64
    mp = post.part2map(outdir, n=n)
    m_tot = float(np.asarray(sim.p.m).sum())
    assert np.isclose(mp.sum() / n ** 2, m_tot, rtol=1e-10)
    # dm-only map: this run has no stars, so dm == all
    mp_dm = post.part2map(outdir, n=n, family="dm")
    np.testing.assert_allclose(mp_dm, mp)
    # CLI round-trips
    f = str(tmp_path / "m.npy")
    assert post.main(["part2map", outdir, f, "--n", "32"]) in (0, None)
    assert np.load(f).shape == (32, 32)
    # vrot on a synthetic solid-body rotator
    r, vr = post.vrot(outdir, [0.5, 0.5, 0.5])
    assert np.isfinite(vr).all()
    fv = str(tmp_path / "v.txt")
    assert post.main(["vrot", outdir, fv]) in (0, None)
    assert np.loadtxt(fv).shape[1] == 2
    fs = str(tmp_path / "s.txt")
    assert post.main(["getstarlist", outdir, fs]) in (0, None)
    # no stars in this run -> empty table body
    rows = [l for l in open(fs) if not l.startswith("#")]
    assert len(rows) == 0


def test_map2img_roundtrip(tmp_path):
    """map2img (map2bmp.c / utils/py/map2img.py role): a .map frame
    renders to PPM/PGM with correct dimensions and value mapping."""
    import numpy as np

    from ramses_tpu.io.movie import write_frame
    from ramses_tpu.utils.maps import main as maps_main, map2img, read_map

    m = np.outer(np.linspace(1.0, 10.0, 24),
                 np.ones(16)).astype(np.float64)
    p = str(tmp_path / "dens.map")
    write_frame(p, m, t=0.5, bounds=(1.0, 1.0, 1.0))
    back, meta = read_map(p)
    assert back.shape == (24, 16)
    np.testing.assert_allclose(back, m, rtol=1e-6)
    assert meta["t"] == 0.5

    img = str(tmp_path / "dens.ppm")
    w, h = map2img(p, img, log=True)
    hdr = open(img, "rb").read(20).split(b"\n")
    assert hdr[0] == b"P6" and hdr[1] == b"24 16"
    # darkest at the low end, brightest at the high end
    data = np.frombuffer(open(img, "rb").read().split(b"255\n", 1)[1],
                         np.uint8).reshape(16, 24, 3)
    assert data[:, 0].sum() < data[:, -1].sum()
    # grayscale + CLI path
    pgm = str(tmp_path / "dens.pgm")
    assert maps_main(["map2img", p, pgm, "--min", "1", "--max",
                      "10"]) == 0
    g = open(pgm, "rb").read()
    assert g.startswith(b"P5\n24 16\n255\n")
