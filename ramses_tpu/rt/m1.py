"""M1 photon transport: closure, face fluxes, conservative update.

Reference: ``rt/rt_flux_module.f90`` (``cmp_eddington:208-248`` for the
closure; GLF/HLL interface fluxes) and ``rt/rt_godunov_fine.f90``.  State
per group: photon number density N [1/cm^3] and flux F [1/cm^2/s],
advanced at the reduced speed of light ``c_red``
(``rt_c``, ``rt/rt_parameters.f90:12``).

Everything operates on dense arrays [*sp] / [ndim, *sp]; the GLF flux
makes the scheme a plain roll-stencil that XLA fuses into one kernel —
1/2/3D via the same code.
"""

from __future__ import annotations


import jax.numpy as jnp

SMALL_NP = 1e-30


def eddington(N, F, c_red, ndim: int):
    """Pressure tensor P[i][j] (units of N) from the M1 closure
    (``cmp_eddington``): chi = (3+4f²)/(5+2√(4-3f²)),
    D = (1-chi)/2 I + (3chi-1)/2 n⊗n."""
    Ns = jnp.maximum(N, SMALL_NP)
    f2 = sum(F[d] ** 2 for d in range(ndim)) / (c_red * Ns) ** 2
    f2 = jnp.clip(f2, 0.0, 1.0)
    chi = (3.0 + 4.0 * f2) / (5.0 + 2.0 * jnp.sqrt(
        jnp.maximum(4.0 - 3.0 * f2, 0.0)))
    iterm = 0.5 * (1.0 - chi)
    oterm = 0.5 * (3.0 * chi - 1.0)
    fmag2 = sum(F[d] ** 2 for d in range(ndim))
    inv = 1.0 / jnp.maximum(fmag2, SMALL_NP)
    P = [[None] * ndim for _ in range(ndim)]
    for i in range(ndim):
        for j in range(ndim):
            nn = F[i] * F[j] * inv
            P[i][j] = N * (oterm * nn + (iterm if i == j else 0.0))
    return P


def _phys_flux(N, F, c_red, ndim, d):
    """[1+ndim] physical flux components along direction d."""
    P = eddington(N, F, c_red, ndim)
    out = [F[d]]
    for j in range(ndim):
        out.append(c_red ** 2 * P[d][j])
    return out


def _pad(a, ndim, ng=1, periodic=True):
    for d in range(ndim):
        ax = a.ndim - ndim + d
        n = a.shape[ax]

        def take(s0, s1):
            idx = [slice(None)] * a.ndim
            idx[ax] = slice(s0, s1)
            return a[tuple(idx)]

        if periodic:
            lo, hi = take(n - ng, n), take(0, ng)
        else:  # outflow
            reps = [1] * a.ndim
            reps[ax] = ng
            lo = jnp.tile(take(0, 1), reps)
            hi = jnp.tile(take(n - 1, n), reps)
        a = jnp.concatenate([lo, a, hi], axis=ax)
    return a


def _unpad(a, ndim, ng=1):
    idx = [slice(None)] * a.ndim
    for d in range(ndim):
        ax = a.ndim - ndim + d
        idx[ax] = slice(ng, a.shape[ax] - ng)
    return a[tuple(idx)]


def transport_step(N, F, dt, dx: float, c_red: float, ndim: int,
                   periodic: bool = True):
    """One first-order GLF transport step (the reference's default HLL
    with eigenvalues ±c collapses to exactly this when the tabulated
    lambda bounds are at their extremes)."""
    Np = _pad(N, ndim, 1, periodic)
    Fp = _pad(F, ndim, 1, periodic)
    Fl = [Fp[d] for d in range(ndim)]
    U = [Np] + Fl

    dN = jnp.zeros_like(Np)
    dF = [jnp.zeros_like(Np) for _ in range(ndim)]
    for d in range(ndim):
        ax = Np.ndim - ndim + d
        flux = _phys_flux(Np, Fl, c_red, ndim, d)
        # GLF at the low face of each cell
        face = []
        for k in range(1 + ndim):
            fl = jnp.roll(flux[k], 1, axis=ax)
            ul = jnp.roll(U[k], 1, axis=ax)
            face.append(0.5 * (fl + flux[k])
                        - 0.5 * c_red * (U[k] - ul))
        dN = dN + (dt / dx) * (face[0] - jnp.roll(face[0], -1, axis=ax))
        for j in range(ndim):
            dF[j] = dF[j] + (dt / dx) * (
                face[1 + j] - jnp.roll(face[1 + j], -1, axis=ax))

    N_new = jnp.maximum(_unpad(Np + dN, ndim), SMALL_NP)
    F_new = jnp.stack([_unpad(Fl[j] + dF[j], ndim) for j in range(ndim)])
    # flux limiter |F| <= c N (M1 physical bound)
    fmag = jnp.sqrt(sum(F_new[j] ** 2 for j in range(ndim)))
    cap = c_red * N_new
    scale = jnp.where(fmag > cap, cap / jnp.maximum(fmag, SMALL_NP), 1.0)
    return N_new, F_new * scale


def rt_courant_dt(dx: float, c_red: float, courant: float = 0.8) -> float:
    """dt = C*dx/(3c) (``rt/rt_godunov_utils.f90:18``)."""
    return courant * dx / 3.0 / c_red
