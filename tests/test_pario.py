"""Per-host concurrent sharded checkpoints (``io/pario.py`` — the
pario/IOGROUPSIZE role, VERDICT-r04 Missing #1): every writer emits
only the shard rows it holds, concurrently, and the file sets restore
onto ANY device count bitwise."""

import glob
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import params_from_string
from ramses_tpu.io.pario import dump_pario, restore_pario
from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

NML = "\n".join([
    "&RUN_PARAMS", "hydro=.true.", "/",
    "&AMR_PARAMS", "levelmin=4", "levelmax=6", "boxlen=1.0", "/",
    "&INIT_PARAMS", "nregion=2",
    "region_type(1)='square'", "region_type(2)='square'",
    "x_center=0.25,0.75", "length_x=0.5,0.5",
    "exp_region=10.0,10.0", "d_region=1.0,0.125",
    "p_region=1.0,0.1", "/",
    "&HYDRO_PARAMS", "riemann='hllc'", "/",
    "&REFINE_PARAMS", "err_grad_d=0.05", "err_grad_p=0.05", "/",
    "&OUTPUT_PARAMS", "tend=0.01", "/",
])


def test_pario_roundtrip_any_device_count(tmp_path):
    import jax
    devices = jax.devices()
    assert len(devices) >= 8
    sim = ShardedAmrSim(params_from_string(NML, ndim=2),
                        devices=devices[:8], dtype=jnp.float32)
    sim.evolve(0.004, nstepmax=3)
    ref = {l: np.asarray(sim.u[l]) for l in sim.levels()}

    out = dump_pario(sim, 1, str(tmp_path), split_hosts=4,
                     io_group_size=2)
    hosts = sorted(glob.glob(os.path.join(out, "host_*.npz")))
    assert len(hosts) == 4                      # one file per "host"
    assert os.path.exists(os.path.join(out, "manifest.npz"))

    # restore onto the SAME 8-device mesh: bitwise
    r8 = restore_pario(ShardedAmrSim, params_from_string(NML, ndim=2),
                       out, dtype=jnp.float32, devices=devices[:8])
    assert r8.t == sim.t and r8.nstep == sim.nstep
    for l in sim.levels():
        m = sim.maps[l]
        nc = m.noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r8.u[l])[:nc], ref[l][:nc]), l

    # restore onto ONE device (plain AmrSim): same state, and the two
    # sims keep evolving identically (mesh-of-1 == mesh-of-N)
    r1 = restore_pario(AmrSim, params_from_string(NML, ndim=2), out,
                       dtype=jnp.float32)
    for l in sim.levels():
        m = sim.maps[l]
        nc = m.noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r1.u[l])[:nc], ref[l][:nc]), l
    r8.evolve(0.006, nstepmax=r8.nstep + 2)
    r1.evolve(0.006, nstepmax=r1.nstep + 2)
    assert r8.nstep == r1.nstep
    for l in r1.levels():
        nc = r1.maps[l].noct * 2 ** r1.cfg.ndim
        a = np.asarray(r8.u[l])[:nc]
        b = np.asarray(r1.u[l])[:nc]
        assert np.allclose(a, b, rtol=2e-6, atol=1e-7), l


def test_pario_io_group_throttle(tmp_path, monkeypatch):
    """io_group_size=1 serializes the writers (the IOGROUPSIZE token
    ring); the files still land and restore."""
    import threading

    import ramses_tpu.io.pario as pario
    peak = {"live": 0, "max": 0}
    lock = threading.Lock()
    orig = np.savez

    def counting_savez(*a, **k):
        with lock:
            peak["live"] += 1
            peak["max"] = max(peak["max"], peak["live"])
        try:
            return orig(*a, **k)
        finally:
            with lock:
                peak["live"] -= 1

    import jax
    sim = ShardedAmrSim(params_from_string(NML, ndim=2),
                        devices=jax.devices()[:8], dtype=jnp.float32)
    monkeypatch.setattr(np, "savez", counting_savez)
    out = dump_pario(sim, 2, str(tmp_path), split_hosts=4,
                     io_group_size=1)
    monkeypatch.setattr(np, "savez", orig)
    # manifest writes outside the ring; host writers hold the token
    assert peak["max"] <= 2
    r = restore_pario(ShardedAmrSim, params_from_string(NML, ndim=2),
                      out, dtype=jnp.float32, devices=jax.devices()[:8])
    for l in sim.levels():
        nc = sim.maps[l].noct * 2 ** sim.cfg.ndim
        assert np.array_equal(np.asarray(r.u[l])[:nc],
                              np.asarray(sim.u[l])[:nc])
