"""Multi-domain checkpoint/restart: dump with N per-domain file sets,
restore onto 1 device and onto the 8-device virtual mesh.

Reference behaviour: ``amr/output_amr.f90:256-400`` (one backup file
per cpu) + ``init_amr``'s multi-file read on restart with any new cpu
count — the 'restart on a different ncpu' workflow.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import load_params
from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

NML = "namelists/sedov3d.nml"


def _params(lmin=4, lmax=5):
    p = load_params(NML, ndim=3)
    p.amr.levelmin, p.amr.levelmax = lmin, lmax
    p.refine.err_grad_d = 0.1
    p.refine.err_grad_p = 0.1
    return p


@pytest.fixture(scope="module")
def source_sim():
    sim = AmrSim(_params(), dtype=jnp.float64)
    sim.evolve(1e9, nstepmax=3)
    return sim


@pytest.mark.slow
def test_dump8_restore1(tmp_path, source_sim):
    sim = source_sim
    out = sim.dump(1, str(tmp_path), ncpu=8)
    tot0 = sim.totals()
    back = AmrSim.from_snapshot(_params(), out, dtype=jnp.float64)
    assert back.nstep == sim.nstep
    assert back.t == pytest.approx(sim.t, rel=1e-12)
    for l in sim.levels():
        assert back.tree.noct(l) == sim.tree.noct(l)
    np.testing.assert_allclose(back.totals(), tot0, rtol=1e-13)
    # state matches cell for cell (same sorted-key order after rebuild)
    for l in sim.levels():
        n = sim.maps[l].noct * 8
        np.testing.assert_allclose(np.asarray(back.u[l])[:n],
                                   np.asarray(sim.u[l])[:n], rtol=1e-12)


def test_dump8_restore_sharded(tmp_path, source_sim):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual mesh")
    sim = source_sim
    out = sim.dump(2, str(tmp_path), ncpu=8)
    back = ShardedAmrSim.from_snapshot(_params(), out, dtype=jnp.float64)
    assert isinstance(back, ShardedAmrSim)
    np.testing.assert_allclose(back.totals(), sim.totals(), rtol=1e-13)
    # the restored sharded sim still steps
    back.step_coarse(back.coarse_dt())
    assert np.isfinite(np.asarray(back.totals())).all()


@pytest.mark.slow
def test_particle_multidomain_restore(tmp_path):
    """Particle files merge across domains on restore (scalar header
    entries must not be concatenated)."""
    from ramses_tpu.io.restart import restore_tree_state
    from ramses_tpu.pm.particles import ParticleSet
    from ramses_tpu.hydro.core import HydroStatic

    rng = np.random.default_rng(3)
    npart = 257                       # deliberately not divisible by 4
    parts = ParticleSet.make(
        jnp.asarray(rng.random((npart, 3))),
        jnp.asarray(rng.standard_normal((npart, 3)) * 0.01),
        jnp.asarray(np.full(npart, 1.0 / npart)))
    p = _params()
    p.run.pic = True
    p.run.poisson = True
    sim = AmrSim(p, dtype=jnp.float64, particles=parts)
    sim.evolve(1e9, nstepmax=1)
    out = sim.dump(4, str(tmp_path), ncpu=4)
    _, _, _, pd = restore_tree_state(out, HydroStatic.from_params(p), 4)
    assert pd is not None
    assert len(pd["mass"]) == npart
    assert pd["mass"].sum() == pytest.approx(1.0, rel=1e-12)
    assert len(np.unique(pd["identity"])) == npart


@pytest.mark.slow
def test_sharded_dump_restore1(tmp_path):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual mesh")
    sim = ShardedAmrSim(_params(), dtype=jnp.float64)
    sim.evolve(1e9, nstepmax=2)
    out = sim.dump(3, str(tmp_path))          # ncpu defaults to ndev
    import glob
    import os
    assert len(glob.glob(os.path.join(out, "hydro_00003.out*"))) == 8
    back = AmrSim.from_snapshot(_params(), out, dtype=jnp.float64)
    np.testing.assert_allclose(back.totals(), sim.totals(), rtol=1e-13)
