"""Static HLO gather-traffic inventory (telemetry/hlo.py) and the
blocked-sweep traffic regression gate.

The AMR per-cell gap is gather-bound, so the gathered RESULT element
count of the *lowered* fused coarse step is the number this PR-chain
optimizes.  It is backend-independent (counted from StableHLO before
XLA optimizes anything), deterministic for a fixed tree, and countable
on the CPU test backend — which makes it pinnable: the blocked Morton
tile path must gather at least 2x fewer elements than the per-oct
stencil path on the same tree.

Measured on this suite's Sedov tree (lmin=5, lmax=7, 3D):

* init tree (tile occupancy ~0.31, the worst case for blocking):
  5,580,160 -> 2,789,760 elements = 2.0x
* evolved to t=0.02 (occupancy ~0.6): 160.0M -> 44.2M = 3.6x
"""

import json

import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import params_from_string
from ramses_tpu.telemetry import hlo

from tests.test_oct_blocking import SEDOV3D

_SYNTH = """
  %9 = "stablehlo.gather"(%2, %8) <{dimension_numbers = #stablehlo.gather<offset_dims = [0]>}> : (tensor<100x5xf32>, tensor<7x1xi32>) -> tensor<5x7xf32>
  %12 = stablehlo.add %9, %9 : tensor<5x7xf32>
  %20 = "stablehlo.dynamic_gather"(%2, %8, %13) : (tensor<100x5xf32>, tensor<3x1xi32>, tensor<2xi32>) -> tensor<3x5xf64>
"""

# the generic/quoted syntax folded over multiple lines — what the old
# single-line regex silently dropped
_SYNTH_MULTILINE = """
  %9 = "stablehlo.gather"(%2, %8) <{
      dimension_numbers = #stablehlo.gather<offset_dims = [0]>,
      indices_are_sorted = false
    }> : (tensor<100x5xf32>, tensor<7x1xi32>)
      -> tensor<5x7xf32>
"""


def test_gather_inventory_parses_stablehlo():
    inv = hlo.gather_inventory(_SYNTH)
    assert [n for n, _ in inv] == [35, 15]       # largest first
    assert hlo.count_gather_elems(_SYNTH) == 50
    assert hlo.count_gather_elems("no gathers here") == 0
    # the #stablehlo.gather<...> ATTRIBUTE must not count as an op
    assert hlo.raw_gather_count(_SYNTH) == 2


def test_gather_inventory_multiline_generic_syntax():
    inv = hlo.gather_inventory(_SYNTH_MULTILINE)
    assert [n for n, _ in inv] == [35]
    assert hlo.raw_gather_count(_SYNTH_MULTILINE) == 1


def test_gather_inventory_warns_on_undercount():
    """A gather whose result type the parser cannot recover must warn,
    not silently shrink the inventory."""
    broken = '  %9 = "stablehlo.gather"(%2, %8) : who knows\n'
    with pytest.warns(RuntimeWarning, match="UNDERCOUNT"):
        inv = hlo.gather_inventory(broken)
    assert inv == []


def test_run_header_records_gather_inventory(tmp_path):
    """Telemetry satellite: the JSONL run header carries the static
    gather inventory of the fused step, and regrid sub-phase timers
    flow into the per-step phase wallclock."""
    nml = SEDOV3D.replace("&RUN_PARAMS", "&RUN_PARAMS\nnstepmax=2") \
        .replace("/\n&INIT_PARAMS",
                 f"/\n&OUTPUT_PARAMS\ntelemetry='{tmp_path}/run.jsonl'\n"
                 "telemetry_interval=1\n/\n&INIT_PARAMS")
    p = params_from_string(
        nml.format(lmin=4, lmax=5, blk=".true.", riemann="llf"), ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(1e9, nstepmax=2)
    sim.telemetry.close(sim, print_timers=False)
    with open(tmp_path / "run.jsonl") as f:
        recs = [json.loads(line) for line in f]
    hdr = recs[0]
    assert hdr["kind"] == "run_header"
    n = hdr["run_info"]["hlo_gather_elems"]
    assert isinstance(n, int) and n > 0, hdr["run_info"]
    assert hdr["run_info"]["hlo_gather_ops"] > 0
    # the static-analysis audit of the same lowering rides along
    counts = hdr["run_info"]["analysis_findings"]
    assert set(counts) == {"error", "warn", "info"}, hdr["run_info"]
    steps = [r for r in recs if r["kind"] == "step"]
    assert any("regrid: flag" in r.get("phases_s", {}) for r in steps)


@pytest.mark.slow
def test_blocked_sweep_halves_gather_traffic():
    """Regression gate: on the lmin=5/lmax=7 Sedov init tree the
    blocked fused step must gather >= 2x fewer elements than the
    per-oct stencil path, and stay under an absolute ceiling."""
    from ramses_tpu.analysis.hlo_rules import check_gather_ratio
    texts, invs = {}, {}
    for blk in (".false.", ".true."):
        p = params_from_string(
            SEDOV3D.format(lmin=5, lmax=7, blk=blk, riemann="llf"),
            ndim=3)
        sim = AmrSim(p, dtype=jnp.float32)
        texts[blk] = hlo.lower_fused_step(sim)
        invs[blk] = hlo.gather_inventory(texts[blk])
        if blk == ".true.":
            assert sim.blocks, "no blocked levels"
    # the 6^d-duplicated stencil batch is the largest gather class of
    # the per-oct program; blocking must remove that class entirely,
    # not just shrink the total
    off_max = invs[".false."][0][0]
    on_sizes = {n for n, _ in invs[".true."]}
    assert invs[".true."][0][0] < off_max
    assert off_max not in on_sizes
    # the headline >= 2x gate, through the SAME primitive the
    # gather-blowup lint rule uses (they must not drift)
    ok, off, on = check_gather_ratio(texts[".false."], texts[".true."],
                                     min_ratio=2.0)
    assert ok, (off, on)
    assert on <= 3_000_000, (off, on)       # measured 2,789,760
    assert off >= 5_000_000, (off, on)      # comparison stays meaningful


@pytest.mark.slow
def test_blocked_sweep_halves_gather_traffic_mhd():
    """The universal-blocking gate for the CT fused step: the MHD tile
    sweep (cells + staggered faces in one compact Morton-tile batch)
    must gather >= 2x fewer elements than the 6^d stencil path."""
    from ramses_tpu.analysis.hlo_rules import check_gather_ratio
    from ramses_tpu.config import load_params
    from ramses_tpu.mhd.amr import MhdAmrSim
    texts = {}
    for blk in (False, True):
        p = load_params("namelists/tube_mhd.nml", ndim=3)
        p.amr.levelmin, p.amr.levelmax = 4, 6
        p.amr.oct_blocking = blk
        p.refine.err_grad_d = 0.02
        p.refine.err_grad_p = 0.05
        sim = MhdAmrSim(p, dtype=jnp.float32)
        if blk:
            assert sim.blocks, "no blocked MHD levels"
        texts[blk] = hlo.lower_fused_step(sim)
    # measured 26.6M -> 10.5M (2.55x) on this tree; 2D stays ~1.3x
    # (thin-stripe refinement gives poor tile occupancy there)
    ok, off, on = check_gather_ratio(texts[False], texts[True], 2.0)
    assert ok, (off, on)


@pytest.mark.slow
def test_blocked_sweep_halves_gather_traffic_layouts():
    """Same gate with forced load-balance layouts adopted: the
    layout-composed tile tables must keep the >= 2x gather win."""
    from ramses_tpu.analysis.hlo_rules import check_gather_ratio
    from ramses_tpu.config import params_from_string as _pfs
    texts = {}
    for blk in (".false.", ".true."):
        p = _pfs(SEDOV3D.format(lmin=5, lmax=7, blk=blk,
                                riemann="llf"), ndim=3)
        p.amr.load_balance = True
        sim = AmrSim(p, dtype=jnp.float32)
        sim.request_rebalance()
        sim.regrid()
        assert sim.layouts, "forced rebalance adopted no layout"
        if blk == ".true.":
            assert sim.blocks, "no blocked levels under layouts"
        texts[blk] = hlo.lower_fused_step(sim)
    ok, off, on = check_gather_ratio(texts[".false."], texts[".true."],
                                     2.0)
    assert ok, (off, on)
