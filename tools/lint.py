"""Static-analysis gate: lint the canonical lowered programs.

Builds the canonical step-chain programs (one per driver family — see
``ramses_tpu/analysis/programs.py``) on a CPU host-device mesh, runs
every registered rule over their StableHLO plus the source-level AST
rules over the package tree, and reports findings against the
committed baseline of accepted fingerprints
(``ramses_tpu/analysis/baseline.json``).

Exit policy (``--check``): fails only on *new* findings of severity
``warn`` or higher — accepted (baselined) findings and ``info``-level
notes never gate.  ``--update-baseline`` rewrites the baseline from
the current ``warn+`` findings (info findings are reported but never
baselined, so the file stays a short list of consciously accepted
hazards).

Usage::

    python tools/lint.py                  # report, exit 0
    python tools/lint.py --check          # CI gate
    python tools/lint.py --check --json lint.json
    python tools/lint.py --update-baseline
    python tools/lint.py --programs hydro_amr,mhd_amr --rules gather-blowup
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_list(txt):
    return [s for s in (txt or "").split(",") if s] or None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on unbaselined warn+ findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current warn+ "
                         "findings")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the machine-readable report here")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: the committed "
                         "ramses_tpu/analysis/baseline.json)")
    ap.add_argument("--programs", default=None,
                    help="comma list of canonical programs (default: "
                         "all)")
    ap.add_argument("--rules", default=None,
                    help="comma list of rule ids (default: all)")
    ap.add_argument("--source-root", default=None,
                    help="package tree for source rules (default: the "
                         "installed ramses_tpu)")
    ap.add_argument("--devices", type=int, default=8,
                    help="CPU host-device mesh size (>=2 enables the "
                         "sharded program)")
    args = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ramses_tpu.platform import force_cpu_mesh
    force_cpu_mesh(args.devices)

    from ramses_tpu.analysis import engine
    from ramses_tpu.analysis.programs import build_programs
    from ramses_tpu.analysis.rules import Severity, save_baseline

    programs = build_programs(_parse_list(args.programs))
    findings = engine.run(programs, source_root=args.source_root,
                          rule_ids=_parse_list(args.rules))

    if args.update_baseline:
        accepted = [f for f in findings if f.severity >= Severity.WARN]
        path = save_baseline(accepted, args.baseline)
        print(f"lint: baseline of {len(accepted)} finding(s) -> {path}")
        return 0

    rep = engine.report(findings, baseline_path=args.baseline)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rep, f, indent=1)
            f.write("\n")

    nprog = len(programs)
    print(f"lint: {nprog} canonical program(s), "
          f"{sum(rep['counts'].values())} finding(s) "
          f"({rep['counts']['error']} error / {rep['counts']['warn']} "
          f"warn / {rep['counts']['info']} info), "
          f"{len(rep['accepted'])} baselined")
    for f in rep["new"]:
        print(f"  [{f['severity']:5}] {f['rule']} @ {f['program']}: "
              f"{f['message']}")
    if rep["stale_baseline"]:
        print(f"lint: note — {len(rep['stale_baseline'])} baseline "
              "entr(ies) no longer fire "
              f"({', '.join(rep['stale_baseline'][:4])}"
              f"{'...' if len(rep['stale_baseline']) > 4 else ''}); "
              "run --update-baseline to prune")
    if args.check and not rep["ok"]:
        bad = sum(1 for f in rep["new"]
                  if f["severity"] in ("warn", "error"))
        print(f"lint: FAIL — {bad} unbaselined warn+ finding(s); fix "
              "them or accept consciously with --update-baseline",
              file=sys.stderr)
        return 1
    print("lint: OK" if rep["ok"] else
          "lint: findings above are unbaselined (no --check, exit 0)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
