"""Run service: drain the job queue under supervised execution.

``serve(queue_dir)`` is the worker loop: reclaim stale records, claim a
job, run it through the batched :class:`~ramses_tpu.ensemble.batch.
EnsembleEngine` under ``resilience/supervisor.supervise`` (auto-resume
from the newest manifest-valid ensemble checkpoint in the job's results
dir), and publish telemetry JSONL + checkpoints as the result artifact.
A single-member job is just an ensemble of one — every job gets the
same artifact shape.  The engine covers the uniform fused step chains
(hydro incl. cooling, MHD, RHD); AMR/gravity namelists must run solo
via ``python -m ramses_tpu run.nml``.
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Any, Dict, Optional

from ramses_tpu.ensemble import queue as jq
from ramses_tpu.resilience.watchdog import HangDetected


def run_job(queue_dir: str, job: "jq.Job", max_attempts: int = 2,
            verbose: bool = False, log=print) -> Dict[str, Any]:
    """Execute one claimed job; returns the result dict recorded on
    ``done``.  Raises on failure (caller moves the record)."""
    import jax.numpy as jnp

    from ramses_tpu.config import params_from_string
    from ramses_tpu.ensemble.batch import EnsembleEngine, EnsembleSpec
    from ramses_tpu.resilience import supervisor as rsup

    rec = job.record
    rdir = jq.results_dir(queue_dir, job.id)
    os.makedirs(rdir, exist_ok=True)
    nml_path = os.path.join(rdir, "run.nml")
    with open(nml_path, "w") as f:
        f.write(rec["namelist"])
    params = params_from_string(rec["namelist"],
                                ndim=int(rec.get("ndim", 3)))
    # persistent compile cache before the first trace: a fleet worker
    # re-claiming a known namelist cold-starts in O(load), not
    # O(compile) (&RUN_PARAMS compile_cache_dir / RAMSES_COMPILE_CACHE)
    from ramses_tpu.platform import setup_compile_cache
    setup_compile_cache(params)
    params.output.output_dir = rdir
    if not params.output.telemetry:
        params.output.telemetry = os.path.join(rdir, "telemetry.jsonl")
    # a re-claimed job (stale worker) must continue from the dead
    # worker's last checkpoint, so supervise() attempt 1 resolves the
    # newest manifest-valid dir instead of starting fresh
    params.run.auto_resume = True
    # checkpoints can rot between beats (torn shard, truncated file on
    # a dying node): quarantine them NOW so the auto-resume scan below
    # never loops over a dir that validates at scan time but fails at
    # restore time
    from ramses_tpu.resilience import scrub_checkpoints
    scrub_checkpoints(rdir, log=log)
    dtype = getattr(jnp, rec.get("dtype") or "float32")
    if jq.job_kind(rec) == "calibrate" or params.calibration.calibrate:
        # calibrate-kind job: gradient-descent calibration through the
        # differentiable rollout (ramses_tpu/diff) — same artifact shape
        # (results dir + telemetry JSONL + resumable output_NNNNN
        # checkpoints), heartbeating the claim once per optimizer
        # iteration instead of per fused window
        from ramses_tpu.diff.calibrate import run_calibration_job

        result = run_calibration_job(
            params, dtype=dtype, base_dir=rdir, log=log,
            on_iter=lambda it, loss: jq.heartbeat(job))
        result["results_dir"] = rdir
        result["telemetry"] = params.output.telemetry
        return result
    spec = EnsembleSpec.from_params(params, sweeps=rec.get("sweeps"),
                                    solver=rec.get("solver", ""))

    def build(restart):
        if restart:
            return EnsembleEngine.from_checkpoint(spec, restart,
                                                  dtype=dtype)
        return EnsembleEngine(spec, dtype=dtype)

    def drive(eng):
        from ramses_tpu.resilience.checkpoint import rotate_checkpoints

        def beat(e):
            # worker liveness + resumability advance together: every
            # fused window refreshes the claim mtime and lands a
            # manifest-valid checkpoint (keep the newest two)
            jq.heartbeat(job)
            e.save(rdir)
            rotate_checkpoints(rdir, keep=2)
        eng.run(verbose=verbose, on_chunk=beat)

    # hang_retries=0: a deadline-expired chunk escapes immediately so
    # the serve loop can kill-and-requeue with stage="hang" instead of
    # retrying inside a worker the queue already believes is live
    eng = rsup.supervise(build, drive, params, base_dir=rdir,
                         max_attempts=max_attempts, log=log,
                         hang_retries=0)
    snap = eng.save(rdir)
    eng.telemetry.record_event("ensemble_done", nmember=eng.nmember,
                               ngroup=len(eng.groups), t_min=eng.t,
                               nstep_max=eng.nstep, snapshot=snap,
                               quarantined=eng.quarantined_count)
    eng.telemetry.close(eng, print_timers=False)
    if not eng.run_complete():
        raise RuntimeError(
            f"job {job.id}: incomplete after {max_attempts} attempts "
            f"(t_min={eng.t:.6g} nstep_max={eng.nstep})")
    result = {"results_dir": rdir, "snapshot": snap,
              "telemetry": params.output.telemetry,
              "nmember": eng.nmember, "ngroup": len(eng.groups),
              "t_min": eng.t, "nstep_max": eng.nstep,
              "cell_updates": eng.cell_updates}
    if eng.quarantined:
        # partial completion: quarantined members are a property of the
        # job's *result*, not a worker failure — the job lands in
        # done/ with the census attached and never burns another queue
        # attempt on behalf of its healthy members
        result["partial"] = True
        result["failed_members"] = [
            {"member": int(k), **info}
            for k, info in sorted(eng.quarantined.items())]
        log(f"serve: {job.id} partial completion — "
            f"{eng.quarantined_count}/{eng.nmember} members "
            f"quarantined")
    return result


def _counts_line(queue_dir: str) -> str:
    c = jq.queue_counts(queue_dir)
    return (f"queued={c['queued']} running={c['running']} "
            f"done={c['done']} failed={c['failed']}")


def serve(queue_dir: str, worker: str = "", max_jobs: int = 0,
          idle_exit: bool = False, poll_s: float = 1.0,
          stale_s: Optional[float] = None, max_attempts: int = 2,
          verbose: bool = False, log=print, beat_s: float = 30.0,
          telemetry=None) -> Dict[str, int]:
    """Worker loop: claim and run jobs until the queue is drained
    (``idle_exit``) or ``max_jobs`` jobs have been processed
    (0 = unbounded).  Returns done/failed counts for this worker.

    While idle-polling, a ``queue_counts()`` heartbeat line is printed
    every ``beat_s`` seconds so a stuck fleet is visible from any
    worker's log; ``telemetry`` (optional) receives the queue
    lifecycle events (requeue/fail/reclaim)."""
    jq.init_queue(queue_dir)
    counts = {"done": 0, "failed": 0, "requeued": 0}
    last_beat = 0.0
    while True:
        # default staleness from the first job's namelist is unknowable
        # before claiming — use the CLI/default value for the sweep
        jq.reclaim_stale(queue_dir, stale_s=stale_s or 300.0,
                         max_attempts=max_attempts, log=log,
                         telemetry=telemetry)
        job = jq.claim(queue_dir, worker=worker)
        if job is None:
            if idle_exit:
                if log is not None:
                    log(f"serve: idle, exiting — "
                        f"{_counts_line(queue_dir)}")
                return counts
            now = time.monotonic()
            if log is not None and now - last_beat >= beat_s:
                log(f"serve: idle — {_counts_line(queue_dir)}")
                last_beat = now
            time.sleep(poll_s)
            continue
        log(f"serve: claimed {job.id} "
            f"(attempt {job.record['attempts']}/{max_attempts})")
        try:
            result = run_job(queue_dir, job, max_attempts=max_attempts,
                             verbose=verbose, log=log)
        except HangDetected as e:
            # serve-loop liveness: a deadline-expired chunk comes back
            # HERE (run_job runs with hang_retries=0) — the wedged job
            # is killed-and-requeued with stage="hang" immediately
            # instead of zombifying this worker until stale-reclaim
            log(f"serve: {job.id} hang: {e!r}")
            err = "".join(traceback.format_exception_only(type(e), e))
            if int(job.record.get("attempts", 0)) < max_attempts:
                counts["requeued"] += 1
                jq.requeue(job, error=err.strip(), telemetry=telemetry,
                           stage="hang")
            else:
                counts["failed"] += 1
                jq.fail(job, error=err.strip(), telemetry=telemetry,
                        stage="hang")
        except Exception as e:   # noqa: BLE001 — worker boundary
            log(f"serve: {job.id} failed: {e!r}")
            err = "".join(traceback.format_exception_only(type(e), e))
            if int(job.record.get("attempts", 0)) < max_attempts:
                # hand it back for another worker/attempt; a requeue is
                # not a processed job (max_jobs counts final outcomes)
                counts["requeued"] += 1
                jq.requeue(job, error=err.strip(), telemetry=telemetry)
            else:
                counts["failed"] += 1
                jq.fail(job, error=err.strip(), telemetry=telemetry)
        else:
            counts["done"] += 1
            jq.complete(job, result=result)
            log(f"serve: {job.id} done -> "
                f"{result.get('snapshot') or result.get('checkpoint')}")
        if max_jobs and counts["done"] + counts["failed"] >= max_jobs:
            return counts


def submit_namelist(queue_dir: str, namelist_path: str,
                    sweeps: Optional[Dict[str, Any]] = None,
                    solver: str = "", ndim: int = 3,
                    dtype: str = "float32", kind: str = "run") -> str:
    """CLI submit helper: inline the namelist file into the job record
    so workers need no shared checkout."""
    with open(namelist_path) as f:
        text = f.read()
    return jq.submit(queue_dir, text, sweeps=sweeps, solver=solver,
                     ndim=ndim, dtype=dtype, kind=kind,
                     meta={"namelist_path": os.path.abspath(
                         namelist_path)})


def parse_sweep_args(items) -> Dict[str, list]:
    """``--sweep key=v1,v2,...`` CLI rows into a sweeps dict (values
    parsed as JSON scalars when possible, else kept as strings)."""
    sweeps: Dict[str, list] = {}
    for item in items or ():
        key, _, vals = item.partition("=")
        if not vals:
            raise ValueError(f"--sweep '{item}': expected key=v1,v2,...")
        parsed = []
        for v in vals.split(","):
            try:
                parsed.append(json.loads(v))
            except json.JSONDecodeError:
                parsed.append(v)
        sweeps[key.strip()] = parsed
    return sweeps
