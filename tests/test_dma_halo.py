"""Parity suite for the Pallas async remote-copy (DMA) halo engine.

The DMA backend (:mod:`ramses_tpu.parallel.dma_halo`) is pure data
movement with ppermute ring semantics, so every consumer — the uniform
halo stepper, the slab-sharded dense sweep (including its comm/compute
overlap split), the flags/RT appliers, and the slab MHD CT advance —
must agree BITWISE with the ppermute backend and with the mesh-of-1
global-view path.  CI drives the real kernel through the Pallas
interpreter (:data:`dma_halo.FORCE_INTERPRET`); on a physical TPU the
same tests exercise the compiled ``make_async_remote_copy`` path.
"""

import warnings
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ramses_tpu.amr import bitperm
from ramses_tpu.amr import kernels as K
from ramses_tpu.grid.boundary import BoundarySpec
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.parallel import dense_slab as DS
from ramses_tpu.parallel import dma_halo
from ramses_tpu.parallel.mesh import OCT_AXIS, oct_mesh

needs8 = pytest.mark.skipif(jax.device_count() < 8,
                            reason="needs the 8-device mesh")


@pytest.fixture
def dma(monkeypatch):
    """Run the DMA kernels through the Pallas interpreter on the CPU
    test backend (the real kernel, serialized devices)."""
    monkeypatch.setattr(dma_halo, "FORCE_INTERPRET", True)


def _kinds(bc):
    return tuple((f[0].kind, f[1].kind) for f in bc.faces)


def _sedov_like(ncell, nvar, ndim, seed=0):
    rng = np.random.default_rng(seed)
    u = np.ones((ncell, nvar), np.float32)
    u[:, 0] = 1.0 + 0.1 * rng.random(ncell)
    u[:, 1:1 + ndim] = 0.05 * rng.standard_normal(
        (ncell, ndim)).astype(np.float32)
    u[:, nvar - 1] = 1.0 + 0.1 * rng.random(ncell)
    return jnp.asarray(u)


def _oct_mask(ncell, ndim, lvl, frac=0.3, seed=1):
    rng = np.random.default_rng(seed)
    noct = ncell // (1 << ndim)
    ok_flat = np.repeat(rng.random(noct) < frac, 1 << ndim)
    ok_dense = np.asarray(
        bitperm.flat_to_dense(jnp.asarray(ok_flat), lvl, ndim)
    ).reshape(-1)
    return jnp.asarray(ok_flat), jnp.asarray(ok_dense)


# ----------------------------------------------------------------------
# backend selection
# ----------------------------------------------------------------------
def test_resolve_backend_auto_cpu():
    """auto on the CPU test backend keeps the portable path — the
    tier-1 suite never changes behaviour."""
    assert not dma_halo.available()
    assert dma_halo.resolve_backend("auto") == "ppermute"
    assert dma_halo.resolve_backend(None) == "ppermute"
    assert dma_halo.resolve_backend("ppermute") == "ppermute"


def test_resolve_backend_dma_fallback(monkeypatch):
    """An explicit dma request without a TPU warns once and falls
    back (a namelist written for TPU still runs on a laptop)."""
    monkeypatch.setattr(dma_halo, "FORCE_INTERPRET", False)
    monkeypatch.setattr(dma_halo, "_warned", set())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert dma_halo.resolve_backend("dma") == "ppermute"
    assert any("falling back" in str(x.message) for x in w)


def test_resolve_backend_dma_interpret(dma):
    assert dma_halo.resolve_backend("dma") == "dma"


# ----------------------------------------------------------------------
# the exchange primitive: dma vs ppermute, bitwise
# ----------------------------------------------------------------------
@needs8
def test_exchange_slabs_bitwise(dma):
    """Fused multi-slab exchange under an arbitrary set of ring perms
    equals per-slab ppermute exactly."""
    mesh = oct_mesh(jax.devices())
    n = 8
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.standard_normal((n * 4, 3)))
    b = jnp.asarray(rng.standard_normal((n * 2, 5)).astype(np.float32))

    from jax.sharding import PartitionSpec as P
    results = {}
    for backend in ("ppermute", "dma"):
        def body(a_loc, b_loc):
            ga, gb = dma_halo.exchange_slabs(
                [a_loc, b_loc], [fwd, bwd], OCT_AXIS, backend=backend)
            return ga, gb

        f = dma_halo.shard_map_compat(
            body, mesh,
            in_specs=(P(OCT_AXIS), P(OCT_AXIS)),
            out_specs=(P(OCT_AXIS), P(OCT_AXIS)),
            check_rep=(backend != "dma"))
        results[backend] = jax.jit(f)(a, b)
    for x, y in zip(results["ppermute"], results["dma"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------------------------
# slab hydro sweep under DMA (overlap split included)
# ----------------------------------------------------------------------
# tier-1 keeps the full masked x ret_flux grid on the cheap 2D shape
# plus the strictest 3D overlap-active combo; the remaining 3D combos
# (split-inactive (3,3) and the weaker (3,4) masks) re-run in the
# nightly full suite — 8-device interpret compiles dominate their
# wall time, not the assertions
_slow = pytest.mark.slow
@needs8
@pytest.mark.parametrize("ndim,lvl,masked,ret_flux", [
    (2, 4, False, False),
    (2, 4, False, True),
    (2, 4, True, False),
    (2, 4, True, True),
    # loc (8,8,8): comm/compute overlap split ACTIVE
    pytest.param(3, 4, True, True, marks=_slow),
    pytest.param(3, 4, False, False, marks=_slow),
    pytest.param(3, 4, False, True, marks=_slow),
    pytest.param(3, 4, True, False, marks=_slow),
    # loc (4,4,4): split inactive (loc == 2*NGHOST)
    pytest.param(3, 3, False, False, marks=_slow),
    pytest.param(3, 3, False, True, marks=_slow),
    pytest.param(3, 3, True, False, marks=_slow),
    pytest.param(3, 3, True, True, marks=_slow),
])
def test_dense_sweep_slab_dma_bitwise(dma, ndim, lvl, masked, ret_flux):
    cfg = HydroStatic(ndim=ndim, gamma=1.4, riemann="hllc")
    bc = BoundarySpec.periodic(ndim)
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    u = _sedov_like(ncell, cfg.nvar, ndim)
    ok_flat = ok_dense = None
    if masked:
        ok_flat, ok_dense = _oct_mask(ncell, ndim, lvl)
    dt = jnp.float32(1e-3)
    dx = 1.0 / n
    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              _kinds(bc), halo_backend="dma")
    assert spec is not None and spec.backend == "dma"
    ref = K.dense_sweep(u, None, None, ok_dense, dt, dx, shape, bc,
                        cfg, ret_flux=ret_flux)
    got = jax.jit(partial(DS.dense_sweep_slab, spec=spec, cfg=cfg,
                          dx=dx, ret_flux=ret_flux))(u, ok_flat, dt)
    if ret_flux:
        np.testing.assert_array_equal(np.asarray(ref[0]),
                                      np.asarray(got[0]))
        np.testing.assert_array_equal(np.asarray(ref[1]),
                                      np.asarray(got[1]))
    else:
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@needs8
def test_overlap_split_engages(dma):
    """The split is declared (telemetry) exactly when the cut box is
    deep enough for a ghost-free interior."""
    from ramses_tpu.hydro.muscl import NGHOST
    mesh = oct_mesh(jax.devices())
    bc = _kinds(BoundarySpec.periodic(3))
    thin = DS.build_slab_spec(mesh, 3, 3, (8,) * 3, 512, bc,
                              halo_backend="dma")
    deep = DS.build_slab_spec(mesh, 4, 3, (16,) * 3, 4096, bc,
                              halo_backend="dma")
    assert DS._split_axis(thin, NGHOST) is None
    assert DS._split_axis(deep, NGHOST) is not None
    # ppermute never splits (no async copy to overlap with)
    deep_pp = DS.build_slab_spec(mesh, 4, 3, (16,) * 3, 4096, bc,
                                 halo_backend="ppermute")
    assert DS._split_axis(deep_pp, NGHOST) is None


# ----------------------------------------------------------------------
# refine flags + RT transport under DMA
# ----------------------------------------------------------------------
@needs8
def test_refine_flags_slab_dma_bitwise(dma):
    ndim, lvl = 2, 4
    cfg = HydroStatic(ndim=ndim, gamma=1.4)
    bc = BoundarySpec.periodic(ndim)
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    u = _sedov_like(ncell, cfg.nvar, ndim, seed=2)
    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              _kinds(bc), halo_backend="dma")
    eg = (0.05, 0.05, -1.0)
    fls = (1e-10, 1e-10, 1e-10)
    ref = K.dense_refine_flags(u, None, None, eg, fls, shape, bc, cfg,
                               dx=1.0 / n)
    fn = partial(K._flags_fn(cfg), err_grad=eg, floors=fls, spatial0=0,
                 cfg=cfg)
    got = jax.jit(partial(DS.dense_flags_slab, spec=spec, flags_fn=fn,
                          twotondim=2 ** ndim))(u)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@needs8
def test_rt_transport_slab_dma_bitwise(dma):
    from ramses_tpu.rt import m1

    ndim, lvl = 2, 4
    n = 1 << lvl
    shape = (n,) * ndim
    ncell = n ** ndim
    rng = np.random.default_rng(4)
    rad = jnp.asarray(rng.random((ncell, 1 + ndim)).astype(np.float64))
    dt, dx, c_red = 1e-3, 1.0 / n, 1.0

    def global_step(rows):
        dense = K.rows_to_dense(rows, None, shape)
        N, F = dense[..., 0], jnp.stack(
            [dense[..., 1 + c] for c in range(ndim)])
        N, F = m1.transport_step(N, F, dt, dx, c_red, ndim,
                                 periodic=True)
        cols = [N[..., None]] + [F[c][..., None] for c in range(ndim)]
        return K.dense_to_rows(jnp.concatenate(cols, axis=-1), None,
                               shape)

    def local_fn(ext):
        N, F = ext[..., 0], jnp.stack(
            [ext[..., 1 + c] for c in range(ndim)])
        N, F = m1.transport_step(N, F, dt, dx, c_red, ndim,
                                 periodic=True)
        cols = [N[..., None]] + [F[c][..., None] for c in range(ndim)]
        out = jnp.concatenate(cols, axis=-1)
        return out[tuple(slice(1, -1) for _ in range(ndim))]

    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              ((0, 0),) * ndim, halo_backend="dma")
    ref = jax.jit(global_step)(rad)
    got = jax.jit(partial(DS.dense_apply_slab, spec=spec,
                          local_fn=local_fn, ng=1))(rad)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


# ----------------------------------------------------------------------
# uniform-grid halo stepper: dma vs ppermute vs global, split active
# ----------------------------------------------------------------------
@needs8
@pytest.mark.parametrize("ndim,lvl", [
    (2, 6), pytest.param(3, 5, marks=pytest.mark.slow)])
def test_run_steps_halo_dma_bitwise(dma, ndim, lvl):
    from ramses_tpu.config import params_from_string
    from ramses_tpu.driver import Simulation
    from ramses_tpu.grid.uniform import run_steps
    from ramses_tpu.parallel.halo import make_halo_mesh, run_steps_halo

    txt = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", f"levelmin={lvl}", f"levelmax={lvl}",
        "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=2",
        "region_type(1)='square'", "region_type(2)='square'",
        "x_center=0.5,0.5", "y_center=0.5,0.5", "z_center=0.5,0.5",
        "length_x=10.0,0.12", "length_y=10.0,0.12",
        "length_z=10.0,0.12", "exp_region=10.0,2.0",
        "d_region=1.0,4.0", "p_region=1e-2,1.0", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "courant_factor=0.8", "/",
    ])
    sim = Simulation(params_from_string(txt, ndim=ndim),
                     dtype=jnp.float64)
    u0 = sim.state.u
    t0 = jnp.asarray(0.0, jnp.float64)
    tend = jnp.asarray(1e9, jnp.float64)
    u_ref, t_ref, n_ref = run_steps(sim.grid, u0, t0, tend, 4)
    mesh = make_halo_mesh()
    for backend in ("ppermute", "dma"):
        u_h, t_h, n_h = run_steps_halo(sim.grid, mesh, u0, t0, tend, 4,
                                       halo_backend=backend)
        assert int(n_h) == int(n_ref) == 4
        assert float(t_h) == float(t_ref)
        np.testing.assert_array_equal(np.asarray(u_h), np.asarray(u_ref))
    # the dma run at this size declares comm/compute overlap
    assert dma_halo.TRAFFIC["overlap_frac"] > 0.0


# ----------------------------------------------------------------------
# slab MHD CT: dma vs ppermute vs global (mask + EMF override), and
# the single-block Pallas CT kernel
# ----------------------------------------------------------------------
def _ct_state(ndim, lvl, seed=11):
    """Consistent CT state: random low faces, hi = periodic neighbour's
    lo, cell B = face mean, positive density/pressure."""
    from ramses_tpu.mhd import core as mcore
    from ramses_tpu.mhd.core import IBX, IP, NCOMP, MhdStatic

    cfg = MhdStatic(ndim=ndim, gamma=1.4)
    n = 1 << lvl
    shape = (n,) * ndim
    rng = np.random.default_rng(seed)
    blo = rng.standard_normal((NCOMP,) + shape) * 0.1 + 1.0
    bld = np.zeros(shape + (NCOMP, 2))
    for c in range(NCOMP):
        bld[..., c, 0] = blo[c]
        bld[..., c, 1] = (np.roll(blo[c], -1, axis=c) if c < ndim
                          else blo[c])
    q = np.zeros((cfg.nvar,) + shape)
    q[0] = 1.0 + 0.1 * rng.random(shape)
    q[1:1 + NCOMP] = 0.05 * rng.standard_normal((NCOMP,) + shape)
    q[IBX:IBX + NCOMP] = 0.5 * (bld[..., :, 0] + bld[..., :, 1]
                                ).transpose((ndim,) + tuple(range(ndim)))
    q[IP] = 1.0 + 0.1 * rng.random(shape)
    ud = jnp.asarray(mcore.prim_to_cons(jnp.asarray(q), cfg))
    return cfg, shape, ud, jnp.asarray(bld)


def _ct_global(cfg, shape, ud, bld, dt, dx, ok_dense=None, override=None):
    """Reference: the global-view CT branch (mu.step + _dense_hi) in
    the same (du_rows, b_rows) layout as mhd_ct_slab."""
    from ramses_tpu.mhd import uniform as mu
    from ramses_tpu.mhd.amr import _dense_hi
    from ramses_tpu.mhd.core import NCOMP

    ndim = cfg.ndim
    grid = mu.MhdGrid(cfg=cfg, shape=shape, dx=dx,
                      bc_kinds=((0, 0),) * ndim)
    bfd = jnp.stack([bld[..., c, 0] for c in range(NCOMP)])

    def fn(ud, bld):
        un_d, bfn_d = mu.step(grid, ud, bfd, dt, ok=ok_dense,
                              emf_override=override)
        du = K.dense_to_rows(jnp.moveaxis(un_d - ud, 0, -1), None, shape)
        comps = []
        for c in range(NCOMP):
            lo = bfn_d[c]
            hi = _dense_hi(lo, c, True) if c < ndim else lo
            comps.append(jnp.stack([lo, hi], axis=-1))
        b = K.dense_to_rows(jnp.stack(comps, axis=-2), None, shape)
        return du, b

    return jax.jit(fn)(ud, bld)


# slow: each combo costs a full 8-device interpret compile of the CT
# slab program (~20 s on CPU); the nightly full suite and the
# dedicated DMA-parity CI step run them
@needs8
@pytest.mark.slow
@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("with_ovr", [False, True])
def test_mhd_ct_slab_dma_bitwise(dma, masked, with_ovr):
    ndim, lvl = 3, 3
    cfg, shape, ud, bld = _ct_state(ndim, lvl)
    n = 1 << lvl
    ncell = n ** ndim
    dt = jnp.asarray(2e-4, ud.dtype)
    dx = 1.0 / n
    u_rows = K.dense_to_rows(jnp.moveaxis(ud, 0, -1), None, shape)
    bf_rows = K.dense_to_rows(bld, None, shape)
    pairs = [(d1, d2) for d1 in range(ndim)
             for d2 in range(d1 + 1, ndim)]

    ok_flat = ok_dense = None
    if masked:
        ok_flat, okd = _oct_mask(ncell, ndim, lvl)
        ok_dense = okd.reshape(shape)
    override = ovr_flat = None
    if with_ovr:
        rng = np.random.default_rng(13)
        msk = rng.random((len(pairs),) + shape) < 0.2
        val = rng.standard_normal((len(pairs),) + shape) * 0.01
        override = {p: (jnp.asarray(msk[pi]), jnp.asarray(val[pi]))
                    for pi, p in enumerate(pairs)}
        om = jnp.stack([bitperm.dense_to_flat(
            jnp.asarray(msk[pi]).astype(u_rows.dtype), lvl, ndim)
            for pi in range(len(pairs))], axis=-1)
        ov = jnp.stack([bitperm.dense_to_flat(
            jnp.asarray(val[pi]).astype(u_rows.dtype), lvl, ndim)
            for pi in range(len(pairs))], axis=-1)
        ovr_flat = (om, ov)

    du_ref, b_ref = _ct_global(cfg, shape, ud, bld, dt, dx,
                               ok_dense, override)
    mesh = oct_mesh(jax.devices())
    for backend in ("ppermute", "dma"):
        spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                                  ((0, 0),) * ndim,
                                  halo_backend=backend)
        assert DS.mhd_slab_ok(spec)
        du, b = jax.jit(partial(DS.mhd_ct_slab, dx=dx, spec=spec,
                                cfg=cfg))(u_rows, bf_rows, dt,
                                          ok_flat=ok_flat,
                                          ovr_flat=ovr_flat)
        np.testing.assert_array_equal(np.asarray(du_ref),
                                      np.asarray(du))
        np.testing.assert_array_equal(np.asarray(b_ref), np.asarray(b))


@needs8
@pytest.mark.slow
def test_pallas_ct_kernel_bitwise(dma, monkeypatch):
    """The single-block Pallas CT kernel (interpret mode) equals the
    XLA step_padded spelling inside the same slab decomposition."""
    from ramses_tpu.mhd import pallas_ct

    ndim, lvl = 3, 3
    cfg, shape, ud, bld = _ct_state(ndim, lvl)
    n = 1 << lvl
    ncell = n ** ndim
    dt = jnp.asarray(2e-4, ud.dtype)
    dx = 1.0 / n
    u_rows = K.dense_to_rows(jnp.moveaxis(ud, 0, -1), None, shape)
    bf_rows = K.dense_to_rows(bld, None, shape)
    ok_flat, _ = _oct_mask(ncell, ndim, lvl)
    du_ref, b_ref = _ct_global(cfg, shape, ud, bld, dt, dx)

    mesh = oct_mesh(jax.devices())
    spec = DS.build_slab_spec(mesh, lvl, ndim, shape, ncell,
                              ((0, 0),) * ndim, halo_backend="dma")
    assert not pallas_ct.slab_available(cfg, spec.loc, u_rows.dtype)
    monkeypatch.setattr(pallas_ct, "FORCE_INTERPRET", True)
    assert pallas_ct.slab_available(cfg, spec.loc, u_rows.dtype)
    du, b = jax.jit(partial(DS.mhd_ct_slab, dx=dx, spec=spec,
                            cfg=cfg))(u_rows, bf_rows, dt)
    np.testing.assert_array_equal(np.asarray(du_ref), np.asarray(du))
    np.testing.assert_array_equal(np.asarray(b_ref), np.asarray(b))


def test_flat_index_np_matches_dense_to_flat():
    for ndim, lvl in [(1, 4), (2, 3), (3, 3)]:
        n = 1 << lvl
        rng = np.random.default_rng(5)
        coords = rng.integers(0, n, size=(64, ndim))
        X = jnp.asarray(rng.standard_normal((n,) * ndim))
        rows = np.asarray(bitperm.dense_to_flat(X, lvl, ndim))
        fi = bitperm.flat_index_np(coords, lvl, ndim)
        np.testing.assert_array_equal(
            rows[fi],
            np.asarray(X)[tuple(coords[:, d] for d in range(ndim))])


# ----------------------------------------------------------------------
# full sims: mesh-of-1 vs mesh-of-8 under the DMA backend
# ----------------------------------------------------------------------
@needs8
@pytest.mark.slow
def test_mhd_sim_shard_invariance_complete(dma):
    """Complete-level 3D MHD: MhdAmrSim vs ShardedMhdAmrSim on the
    DMA backend, bitwise (cells AND staggered faces)."""
    from ramses_tpu.config import load_params
    from ramses_tpu.mhd.amr import MhdAmrSim
    from ramses_tpu.parallel.amr_sharded import ShardedMhdAmrSim

    def mk(cls, **kw):
        p = load_params("namelists/tube_mhd.nml", ndim=3)
        p.amr.levelmin = p.amr.levelmax = 3
        p.boundary.nboundary = 0
        p.amr.halo_backend = "dma"
        return cls(p, dtype=jnp.float64, **kw)

    s1 = mk(MhdAmrSim)
    s8 = mk(ShardedMhdAmrSim, devices=jax.devices())
    assert s8._fused_spec().slab and s8._fused_spec().slab[0] is not None
    for _ in range(2):
        dt = min(s1.coarse_dt(), s8.coarse_dt())
        s1.step_coarse(dt)
        s8.step_coarse(dt)
    for l in s1.levels():
        np.testing.assert_array_equal(np.asarray(s1.u[l]),
                                      np.asarray(s8.u[l]))
        np.testing.assert_array_equal(np.asarray(s1.bfs[l]),
                                      np.asarray(s8.bfs[l]))


@needs8
@pytest.mark.slow
def test_mhd_sim_refined_dma_vs_ppermute(dma):
    """Refined 2D MHD (partial fine level, EMF override live): the two
    sharded backends are bitwise-identical — they run the same program
    modulo the exchange primitive.  The mesh-of-1 comparison is
    ulp-tight only: the partial level's correction scatter is GSPMD-
    partitioned, whose summation order is not the serial one."""
    from ramses_tpu.config import load_params
    from ramses_tpu.mhd.amr import MhdAmrSim
    from ramses_tpu.parallel.amr_sharded import ShardedMhdAmrSim

    def mk(cls, backend="dma", **kw):
        p = load_params("namelists/tube_mhd.nml", ndim=2)
        p.amr.levelmin, p.amr.levelmax = 4, 5
        p.boundary.nboundary = 0
        p.refine.err_grad_d = 0.02
        p.refine.err_grad_p = 0.05
        p.amr.halo_backend = backend
        return cls(p, dtype=jnp.float64, **kw)

    s1 = mk(MhdAmrSim)
    s8d = mk(ShardedMhdAmrSim, "dma", devices=jax.devices())
    s8p = mk(ShardedMhdAmrSim, "ppermute", devices=jax.devices())
    for _ in range(3):
        dt = min(s1.coarse_dt(), s8d.coarse_dt(), s8p.coarse_dt())
        s1.step_coarse(dt)
        s8d.step_coarse(dt)
        s8p.step_coarse(dt)
    assert s1.tree.noct(5) > 0
    for l in s1.levels():
        np.testing.assert_array_equal(np.asarray(s8d.u[l]),
                                      np.asarray(s8p.u[l]))
        np.testing.assert_array_equal(np.asarray(s8d.bfs[l]),
                                      np.asarray(s8p.bfs[l]))
        np.testing.assert_allclose(np.asarray(s1.u[l]),
                                   np.asarray(s8d.u[l]),
                                   rtol=1e-12, atol=1e-14)
        np.testing.assert_allclose(np.asarray(s1.bfs[l]),
                                   np.asarray(s8d.bfs[l]),
                                   rtol=1e-12, atol=1e-14)


@needs8
@pytest.mark.slow
def test_mhd_sim_refined_explicit_fold_bitwise(dma):
    """Refined 2D MHD with ``explicit_comm=True``: the partial level's
    coarse correction fold routes through the deterministic owner-fold
    (``amr_comm.fold_corrections_explicit``) instead of the GSPMD
    scatter-add, so — unlike the default path pinned above at
    ulp-tightness only — the sharded run is bitwise REPEATABLE and
    bitwise identical across halo backends, while staying ulp-tight
    against the mesh-of-1 serial fold."""
    from ramses_tpu.config import load_params
    from ramses_tpu.mhd.amr import MhdAmrSim
    from ramses_tpu.parallel.amr_sharded import ShardedMhdAmrSim

    def mk(cls, backend="dma", **kw):
        p = load_params("namelists/tube_mhd.nml", ndim=2)
        p.amr.levelmin, p.amr.levelmax = 4, 5
        p.boundary.nboundary = 0
        p.refine.err_grad_d = 0.02
        p.refine.err_grad_p = 0.05
        p.amr.halo_backend = backend
        return cls(p, dtype=jnp.float64, **kw)

    s1 = mk(MhdAmrSim)
    s8d = mk(ShardedMhdAmrSim, "dma", devices=jax.devices(),
             explicit_comm=True)
    s8p = mk(ShardedMhdAmrSim, "ppermute", devices=jax.devices(),
             explicit_comm=True)
    s8r = mk(ShardedMhdAmrSim, "dma", devices=jax.devices(),
             explicit_comm=True)                  # repeatability twin
    for _ in range(3):
        dt = min(s1.coarse_dt(), s8d.coarse_dt(), s8p.coarse_dt(),
                 s8r.coarse_dt())
        s1.step_coarse(dt)
        s8d.step_coarse(dt)
        s8p.step_coarse(dt)
        s8r.step_coarse(dt)
    assert s1.tree.noct(5) > 0
    # the explicit fold is actually live on the partial level
    spec = s8d._fused_spec()
    assert spec.comm and any(c is not None for c in spec.comm)
    for l in s1.levels():
        np.testing.assert_array_equal(np.asarray(s8d.u[l]),
                                      np.asarray(s8r.u[l]))
        np.testing.assert_array_equal(np.asarray(s8d.u[l]),
                                      np.asarray(s8p.u[l]))
        np.testing.assert_array_equal(np.asarray(s8d.bfs[l]),
                                      np.asarray(s8p.bfs[l]))
        np.testing.assert_allclose(np.asarray(s1.u[l]),
                                   np.asarray(s8d.u[l]),
                                   rtol=1e-12, atol=1e-14)
        np.testing.assert_allclose(np.asarray(s1.bfs[l]),
                                   np.asarray(s8d.bfs[l]),
                                   rtol=1e-12, atol=1e-14)


@needs8
def test_hydro_sim_shard_invariance_dma(dma):
    """The hydro precedent (tests/test_dense_slab.py) on the DMA
    backend: complete-level sedov, two coarse steps, bitwise."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0",
        "halo_backend='dma'", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "/",
        "&OUTPUT_PARAMS", "tend=0.01", "/",
    ])
    s1 = AmrSim(params_from_string(nml, ndim=3), dtype=jnp.float32)
    s8 = ShardedAmrSim(params_from_string(nml, ndim=3),
                       devices=jax.devices(), dtype=jnp.float32)
    spec8 = s8._fused_spec()
    assert spec8.slab and spec8.slab[0] is not None
    assert spec8.slab[0].backend == "dma"
    for _ in range(2):
        dt = min(s1.coarse_dt(), s8.coarse_dt())
        s1.step_coarse(dt)
        s8.step_coarse(dt)
    for l in s1.levels():
        np.testing.assert_array_equal(np.asarray(s1.u[l]),
                                      np.asarray(s8.u[l]))


@needs8
def test_dma_multi_step_donation_no_warnings(dma):
    """The donation pin of tests/test_dense_slab.py on the DMA
    backend: steady-state jits must keep donating cleanly."""
    import warnings as w

    from ramses_tpu.config import params_from_string
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0",
        "halo_backend='dma'", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "/",
        "&OUTPUT_PARAMS", "tend=0.01", "/",
    ])
    sim = ShardedAmrSim(params_from_string(nml, ndim=3),
                        devices=jax.devices(), dtype=jnp.float32)
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        for _ in range(3):
            sim.step_coarse(sim.coarse_dt())
    bad = [x for x in rec if "donat" in str(x.message).lower()]
    assert not bad, [str(x.message) for x in bad]
