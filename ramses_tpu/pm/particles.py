"""Particle storage and particle-mesh operations (uniform grid).

Reference equivalents:
  storage        ``pm/pm_commons.f90:46-96`` (SoA xp/vp/mp/tp/zp/idp/typep)
  deposition     ``pm/rho_fine.f90`` (``cic_amr:343``, ``tsc_amr:1148``)
  force gather   ``pm/move_fine.f90:255-510`` (inverse-CIC interpolation)
  kick           ``pm/synchro_fine.f90:513-538`` (v += f * 0.5*dt)
  drift          ``pm/move_fine.f90:540-550``  (x += v * dt)
  timestep       ``pm/newdt_fine.f90:186-233`` (Courant on particle v)

Particles live in fixed-size arrays (``npartmax``, the reference's hard
memory ceiling, ``amr/amr_parameters.f90:84``) with an ``active`` mask —
static shapes for XLA, masked lanes instead of linked-list surgery.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dreplace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# default lane budget for runs that keep creating particles (the
# reference's npartmax static ceiling, amr/amr_parameters.f90:84, when
# the namelist leaves it unset)
DEFAULT_HEADROOM = 100000


def lane_headroom(params, grows: bool):
    """Particle lane budget: ``npartmax`` when set, else the default
    headroom for particle-creating runs (SF/sinks), else None (exact
    fit).  The single source of truth for every construction/restore
    site."""
    if params.amr.npartmax:
        return int(params.amr.npartmax)
    return DEFAULT_HEADROOM if grows else None


# particle families (pm/pm_commons.f90:72-96)
FAM_GAS_TRACER = 0
# base of the gas-tracer id space: assigned once at seeding, stable
# across dumps, clear of the incremental star/DM id space
TRACER_ID0 = 1 << 30
FAM_DM = 1
FAM_STAR = 2
FAM_CLOUD = 3
FAM_DEBRIS = 4
FAM_UNDEF = 127


@jax.tree_util.register_dataclass
@dataclass
class ParticleSet:
    """SoA particle arrays; inactive lanes have mass 0 and active=False."""
    x: jax.Array          # [n, ndim] positions, user units [0, boxlen)
    v: jax.Array          # [n, ndim] velocities
    m: jax.Array          # [n] masses
    active: jax.Array     # [n] bool
    idp: jax.Array        # [n] int64 ids
    family: jax.Array     # [n] int8 family codes
    tp: jax.Array         # [n] birth time (stars)
    zp: jax.Array         # [n] metallicity (stars)
    flags: jax.Array      # [n] int8 event bookkeeping (e.g. SN done)

    @property
    def n(self) -> int:
        return self.x.shape[0]

    @property
    def ndim(self) -> int:
        return self.x.shape[1]

    @classmethod
    def make(cls, x, v, m, idp=None, family=None, nmax: Optional[int] = None,
             dtype=None) -> "ParticleSet":
        # default width follows the active x64 setting: requesting f64
        # with x64 off would silently truncate AND emit a UserWarning
        # per array (polluting every driver artifact)
        if dtype is None:
            dtype = (jnp.float64 if jax.config.jax_enable_x64
                     else jnp.float32)
        idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        x = jnp.asarray(x, dtype)
        v = jnp.asarray(v, dtype)
        m = jnp.asarray(m, dtype)
        n = x.shape[0]
        nmax = nmax or n
        pad = nmax - n
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0)))
            v = jnp.pad(v, ((0, pad), (0, 0)))
            m = jnp.pad(m, ((0, pad),))
        active = jnp.arange(nmax) < n
        idp = (jnp.pad(jnp.asarray(idp, idt), (0, pad))
               if idp is not None else jnp.arange(1, nmax + 1, dtype=idt))
        family = (jnp.pad(jnp.asarray(family, jnp.int8), (0, pad))
                  if family is not None
                  else jnp.full((nmax,), FAM_DM, jnp.int8))
        zero = jnp.zeros((nmax,), dtype)
        return cls(x=x, v=v, m=m, active=active, idp=idp, family=family,
                   tp=zero, zp=zero,
                   flags=jnp.zeros((nmax,), jnp.int8))


def _cic_corners(x, shape: Tuple[int, ...], dx: float):
    """CIC cell indices + weights: returns (idx [2^d][ndim,n], w [2^d][n])."""
    ndim = x.shape[1]
    s = x / dx - 0.5                      # position in cell-center coords
    i0 = jnp.floor(s)
    frac = s - i0                          # weight of the +1 corner
    i0 = i0.astype(jnp.int32)
    corners = []
    for bits in range(2 ** ndim):
        idx, w = [], None
        for d in range(ndim):
            b = (bits >> d) & 1
            idx.append((i0[:, d] + b) % shape[d])
            wd = frac[:, d] if b else (1.0 - frac[:, d])
            w = wd if w is None else w * wd
        corners.append((tuple(idx), w))
    return corners


def deposit_cic(p: ParticleSet, shape: Tuple[int, ...], dx: float,
                weights=None):
    """CIC mass deposition → density grid [*shape] (``cic_amr``,
    ``pm/rho_fine.f90:343``).  ``weights`` overrides masses (e.g. for
    momentum deposition)."""
    w0 = (p.m if weights is None else weights) * p.active
    vol = float(np.prod([dx] * p.ndim))
    rho = jnp.zeros(shape, p.x.dtype)
    for idx, w in _cic_corners(p.x, shape, dx):
        rho = rho.at[idx].add(w0 * w)
    return rho / vol


def deposit_ngp(p: ParticleSet, shape: Tuple[int, ...], dx: float):
    """Nearest-grid-point deposition (``interp_mode`` NGP path)."""
    w0 = p.m * p.active
    i = jnp.floor(p.x / dx).astype(jnp.int32)
    idx = tuple(i[:, d] % shape[d] for d in range(p.ndim))
    vol = float(np.prod([dx] * p.ndim))
    return jnp.zeros(shape, p.x.dtype).at[idx].add(w0) / vol


def _tsc_w(t):
    """TSC kernel weights for offsets (-1, 0, +1); t = frac offset."""
    return (0.5 * (0.5 - t) ** 2, 0.75 - t * t, 0.5 * (0.5 + t) ** 2)


def deposit_tsc(p: ParticleSet, shape: Tuple[int, ...], dx: float):
    """Triangular-shaped-cloud deposition (``tsc_amr``,
    ``pm/rho_fine.f90:1148``)."""
    w0 = p.m * p.active
    s = p.x / dx - 0.5
    ic = jnp.round(s).astype(jnp.int32)          # nearest cell center
    t = s - ic                                    # in [-0.5, 0.5]
    vol = float(np.prod([dx] * p.ndim))
    rho = jnp.zeros(shape, p.x.dtype)
    import itertools
    wd = [_tsc_w(t[:, d]) for d in range(p.ndim)]
    for offs in itertools.product((-1, 0, 1), repeat=p.ndim):
        idx, w = [], w0
        for d, o in enumerate(offs):
            idx.append((ic[:, d] + o) % shape[d])
            w = w * wd[d][o + 1]
        rho = rho.at[tuple(idx)].add(w)
    return rho / vol


def gather_cic(field, x, dx: float):
    """Inverse CIC: interpolate a [ncomp, *shape] field at positions x.

    Returns [n, ncomp] (``move_fine`` force interpolation,
    ``pm/move_fine.f90:255-510``)."""
    shape = field.shape[1:]
    out = jnp.zeros((x.shape[0], field.shape[0]), field.dtype)
    for idx, w in _cic_corners(x, shape, dx):
        vals = field[(slice(None),) + idx]           # [ncomp, n]
        out = out + (vals * w).T
    return out


def gather_ngp(field, x, dx: float):
    """NGP field sampling, the pair of :func:`deposit_ngp`."""
    shape = field.shape[1:]
    ndim = x.shape[1]
    i = jnp.floor(x / dx).astype(jnp.int32)
    idx = tuple(i[:, d] % shape[d] for d in range(ndim))
    return field[(slice(None),) + idx].T


def gather_tsc(field, x, dx: float):
    """TSC field sampling, the pair of :func:`deposit_tsc`."""
    import itertools
    shape = field.shape[1:]
    ndim = x.shape[1]
    s = x / dx - 0.5
    ic = jnp.round(s).astype(jnp.int32)
    t = s - ic
    wd = [_tsc_w(t[:, d]) for d in range(ndim)]
    out = jnp.zeros((x.shape[0], field.shape[0]), field.dtype)
    for offs in itertools.product((-1, 0, 1), repeat=ndim):
        idx, w = [], None
        for d, o in enumerate(offs):
            idx.append((ic[:, d] + o) % shape[d])
            w = wd[d][o + 1] if w is None else w * wd[d][o + 1]
        vals = field[(slice(None),) + tuple(idx)]
        out = out + (vals * w).T
    return out


def kick(p: ParticleSet, f_at_p, dteff) -> ParticleSet:
    """v += f * dteff (``synchro_fine``; dteff is usually 0.5*dt)."""
    v = p.v + f_at_p * dteff * p.active[:, None]
    return dreplace(p, v=v)


def drift(p: ParticleSet, dt, boxlen: float,
          periodic: bool = True) -> ParticleSet:
    """x += v*dt with periodic wrap (``move_fine:540-550``).

    ``periodic=False``: open box — positions do not wrap; particles
    that leave [0, boxlen) are DEACTIVATED (the reference removes
    escapers from non-periodic domains in ``kill_tree_fine``)."""
    x = p.x + p.v * dt * p.active[:, None]
    if periodic:
        return dreplace(p, x=x % boxlen)
    inside = jnp.all((x >= 0.0) & (x < boxlen), axis=1)
    act = p.active & inside
    # park escaped rows at the origin so stale coords can't alias maps
    x = jnp.where(act[:, None], x, 0.0)
    return dreplace(p, x=x, active=act)


def particle_dt(p: ParticleSet, dx: float, courant_factor: float):
    """Courant-type dt on particle velocities (``newdt2``,
    ``pm/newdt_fine.f90:186-233``): dt = cf*dx/max_component(|v|)."""
    v2 = jnp.max(p.v * p.v, axis=1)               # max component^2
    v2 = jnp.where(p.active, v2, 0.0)
    vmax = jnp.sqrt(jnp.max(v2))
    big = jnp.asarray(1e30, p.v.dtype)
    return jnp.where(vmax > 0.0, courant_factor * dx / jnp.maximum(vmax, 1e-30),
                     big)


def freefall_dt(rho_max, courant_factor: float, fourpi: float):
    """Free-fall constraint (``pm/newdt_fine.f90:51-60``):
    dt <= cf * sqrt(3*pi^2 / (8 * fourpi * rho_max))."""
    threepi2 = 3.0 * jnp.pi ** 2
    tff = jnp.sqrt(threepi2 / 8.0 / fourpi / jnp.maximum(rho_max, 1e-30))
    return courant_factor * tff
