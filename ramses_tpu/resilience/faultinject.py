"""Deterministic fault injection for resilience testing.

Spec syntax (``&RUN_PARAMS fault_inject='...'`` or env
``RAMSES_FAULT_INJECT``), comma-separable:

  ``nan@K``            poison one cell of the state with NaN just
                       before the coarse step that starts at nstep K
  ``sigterm@K``        deliver SIGTERM to this process at the guard
                       check when nstep >= K
  ``truncate:NAME``    after the next checkpoint finalize, truncate
                       the file whose basename contains NAME (breaks
                       its manifest hash — validation must catch it)

Arming is strict: a fault fires only if the run is seen at
``nstep < K`` first, so a resumed run that restarts at nstep >= K does
not re-fire the same fault — exactly-once per logical run.
"""

from __future__ import annotations

import os
import signal
from typing import Optional

ENV_VAR = "RAMSES_FAULT_INJECT"


def _parse(spec: str):
    faults = []
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("nan@"):
            faults.append(("nan", int(part[4:])))
        elif part.startswith("sigterm@"):
            faults.append(("sigterm", int(part[8:])))
        elif part.startswith("truncate:"):
            faults.append(("truncate", part[len("truncate:"):]))
        else:
            raise ValueError(f"unknown fault_inject spec {part!r}")
    return faults


class FaultInjector:
    """Holds the parsed fault list and per-fault armed/fired state."""

    def __init__(self, spec: str):
        self.faults = _parse(spec)
        self._armed = {}          # idx -> bool (saw nstep < K)
        self._fired = set()

    @classmethod
    def from_params(cls, params) -> Optional["FaultInjector"]:
        spec = str(getattr(getattr(params, "run", None),
                           "fault_inject", "") or "")
        env = os.environ.get(ENV_VAR, "")
        joined = ",".join(s for s in (spec, env) if s)
        if not joined:
            return None
        inj = cls(joined)
        return inj if inj.faults else None

    def _should_fire(self, idx: int, kind: str, nstep: int) -> bool:
        k = self.faults[idx][1]
        if idx in self._fired:
            return False
        if idx not in self._armed:
            # Strict arming: only a run first observed BEFORE the
            # trigger step can fire — a resume at nstep >= K won't.
            self._armed[idx] = nstep < k
        if not self._armed[idx]:
            return False
        if nstep >= k:
            self._fired.add(idx)
            return True
        return False

    def maybe_nan(self, sim) -> bool:
        """Poison one cell of ``sim``'s state with NaN when armed."""
        nstep = int(getattr(sim, "nstep",
                            getattr(getattr(sim, "state", None),
                                    "nstep", 0)))
        for i, (kind, _arg) in enumerate(self.faults):
            if kind != "nan" or not self._should_fire(i, kind, nstep):
                continue
            import numpy as np
            u = getattr(sim, "u", None)
            if u is None and getattr(sim, "state", None) is not None:
                u = sim.state.u
            if isinstance(u, dict):
                lv = min(u)
                arr = u[lv]
                u[lv] = arr.at[(0,) * (arr.ndim - 1) + (0,)].set(
                    np.nan)
            else:
                poisoned = u.at[(0,) * u.ndim].set(np.nan)
                if getattr(sim, "state", None) is not None and \
                        getattr(sim.state, "u", None) is u:
                    sim.state.u = poisoned
                else:
                    sim.u = poisoned
            print(f" fault-inject: NaN poisoned at nstep={nstep}")
            return True
        return False

    def clamp_window(self, nstep: int, n: int) -> int:
        """Largest window size <= ``n`` that does not fuse past the
        next pending step-indexed fault target.  The uniform drivers
        run many coarse steps per device dispatch; without this clamp
        a ``nan@K``/``sigterm@K`` could only land on chunk boundaries.
        """
        nstep = int(nstep)
        for i, (kind, k) in enumerate(self.faults):
            if kind not in ("nan", "sigterm") or i in self._fired:
                continue
            if self._armed.get(i) is False:
                continue               # resumed past K: will never fire
            if nstep < int(k):
                n = min(n, int(k) - nstep)
        return max(1, int(n))

    def maybe_signal(self, nstep: int) -> bool:
        """SIGTERM this process when armed (OpsGuard handles it)."""
        for i, (kind, _arg) in enumerate(self.faults):
            if kind != "sigterm" or not self._should_fire(i, kind,
                                                          int(nstep)):
                continue
            print(f" fault-inject: SIGTERM at nstep={int(nstep)}")
            os.kill(os.getpid(), signal.SIGTERM)
            return True
        return False


# ---- post-dump truncation (module-level: dump may run on the
#      AsyncDumper thread with no sim in reach) -----------------------

_truncate_fired = set()


def post_dump(outdir: str):
    """Called by dump_all after finalize; truncates a matching file
    once per process when a ``truncate:NAME`` fault is configured."""
    spec = os.environ.get(ENV_VAR, "")
    if "truncate:" not in spec:
        return
    for kind, name in _parse(spec):
        if kind != "truncate" or name in _truncate_fired:
            continue
        for root, _dirs, files in os.walk(outdir):
            for fn in files:
                if name in fn and fn != "manifest.json":
                    p = os.path.join(root, fn)
                    sz = os.path.getsize(p)
                    with open(p, "r+b") as f:
                        f.truncate(max(0, sz // 2))
                    _truncate_fired.add(name)
                    print(f" fault-inject: truncated {p}")
                    return
