"""Multi-device decomposition invariance.

The reference's own distributed test strategy (SURVEY.md §4.3): the same
aggregates must come out regardless of the decomposition.  Here: a sharded
run over the 8-device CPU mesh must match the single-device run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.config import params_from_string
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import run_steps
from ramses_tpu.parallel.mesh import factorize, make_mesh
from ramses_tpu.parallel.sharded import ShardedSim

from tests.test_hydro_3d import SEDOV


def test_factorize():
    assert factorize(8, 3) == (2, 2, 2)
    assert factorize(4, 3) == (2, 2, 1)
    assert factorize(8, 1) == (8,)
    assert factorize(6, 2) == (3, 2)
    assert factorize(1, 3) == (1, 1, 1)


@pytest.mark.parametrize("ndim", [2, 3])
def test_sharded_matches_single_device(ndim):
    assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
    p = params_from_string(SEDOV.format(lmin=4, tout=1.0, nstep=100),
                           ndim=ndim)
    # single device
    sim = Simulation(p, dtype=jnp.float64)
    u1, t1, n1 = run_steps(sim.grid, sim.state.u,
                           jnp.asarray(0.0, jnp.float64),
                           jnp.asarray(1e9, jnp.float64), 5)
    # 8-device sharded
    ssim = ShardedSim(p, dtype=jnp.float64)
    ssim.run(5)
    assert int(n1) == ssim.nstep
    np.testing.assert_allclose(np.asarray(u1), np.asarray(ssim.u),
                               rtol=1e-12, atol=1e-13)
    assert ssim.t == pytest.approx(float(t1), rel=1e-12)


def test_mesh_shape():
    mesh = make_mesh(3)
    assert mesh.devices.size == len(jax.devices())
