"""Static HLO gather-traffic inventory.

The AMR per-cell gap is gather-bound: every partial-level sweep starts
from index gathers out of the flat cell batches, and the gathered
RESULT element count of the lowered program is a backend-independent
proxy for that HBM traffic — countable on the CPU test backend, stable
across XLA versions (it is read from the *lowered* StableHLO, before
the partitioner or fusion touch it).  The blocked Morton-tile path
exists to shrink exactly this number, so the regression test pins it
(tests/test_hlo_inventory.py via the ``gather-blowup`` rule of
:mod:`ramses_tpu.analysis`) and the telemetry run header records it
(``hlo_gather_elems``) for offline trend tracking.

This module is the one low-level implementation: the ``analysis``
rule engine and the legacy telemetry hooks both count through
:func:`gather_inventory`, so the nightly gate and the lint CLI can
never drift apart.
"""

from __future__ import annotations

import re
import warnings
from typing import List, Tuple

# One gather op, pretty OR quoted generic syntax, possibly spanning
# lines (MLIR wraps long attribute dictionaries): anchor on the op
# name, then take the FIRST `-> tensor<...>` result type that follows
# within the op's own text window.  Gathers carry no region, so the
# window never swallows a neighbouring op's arrow: it is cut at the
# next `stablehlo.` op-name occurrence.
# negative lookbehind: `#stablehlo.gather<...>` is the op's
# dimension-numbers ATTRIBUTE, not an op occurrence
_GATHER_OP_RE = re.compile(r"(?<!#)stablehlo\.(?:dynamic_)?gather\b")
_ARROW_RE = re.compile(
    r"->\s*(?:\()?\s*tensor<([0-9x]+)x?([a-z][a-z0-9]*)>", re.DOTALL)


def _result_elems(dims_txt: str) -> int:
    n = 1
    for d in dims_txt.split("x"):
        if d:
            n *= int(d)
    return n


def raw_gather_count(text: str) -> int:
    """Number of ``stablehlo.gather``/``dynamic_gather`` op-name
    occurrences in ``text`` — the cross-check denominator for the
    inventory (a parse that silently drops ops is how a traffic gate
    rots)."""
    return len(_GATHER_OP_RE.findall(text))


def gather_inventory(text: str) -> List[Tuple[int, str]]:
    """All gather ops in lowered StableHLO/HLO ``text`` as
    ``(result_elems, op_text)`` pairs, largest first.

    Handles the pretty syntax (``%9 = stablehlo.gather ... ->
    tensor<...>``), the quoted generic syntax
    (``"stablehlo.gather"(...) <{...}> : (...) -> tensor<...>``), and
    ops whose attribute dictionary wraps across lines.  When the
    number of parsed ops disagrees with the raw op-name count a
    ``RuntimeWarning`` is emitted — the inventory is a CI gate, so a
    silent undercount is itself a bug.
    """
    starts = [m.start() for m in _GATHER_OP_RE.finditer(text)]
    out: List[Tuple[int, str]] = []
    for i, s in enumerate(starts):
        # op text window: from this op name to the next gather op (or
        # a bounded lookahead) — enough to cover a wrapped attr dict
        end = starts[i + 1] if i + 1 < len(starts) else min(
            len(text), s + 4000)
        window = text[s:end]
        # generic syntax puts the function type after `: ( ... ) ->`;
        # pretty syntax is `... -> tensor<...>` directly.  Either way
        # the first arrow-to-tensor in the window is the result type.
        m = _ARROW_RE.search(window)
        if not m:
            continue
        op_txt = " ".join(window[:m.end()].split())
        out.append((_result_elems(m.group(1)), op_txt[:200]))
    if len(out) != len(starts):
        warnings.warn(
            f"gather inventory parsed {len(out)} of {len(starts)} "
            "stablehlo.gather ops — the traffic count is an "
            "UNDERCOUNT; fix telemetry/hlo.py's parser",
            RuntimeWarning, stacklevel=2)
    out.sort(key=lambda t: -t[0])
    return out


def count_gather_elems(text: str) -> int:
    """Total gathered RESULT elements across every gather op in lowered
    ``text``."""
    return sum(n for n, _ in gather_inventory(text))


def lower_fused_step(sim, dt: float = 1e-6) -> str:
    """Lowered (pre-optimization) StableHLO text of one fused AMR coarse
    step for ``sim``'s current tree — the program whose gather traffic
    the inventory counts.  Dispatches on the solver family: MHD sims
    (``sim.bfs``) lower the CT fused step."""
    import jax.numpy as jnp

    dt_arr = jnp.asarray(float(sim.dt_old or dt), sim.dtype)
    spec = sim._fused_spec()
    if hasattr(sim, "bfs"):
        from ramses_tpu.mhd import amr as M

        return M._mhd_fused_coarse_step.lower(
            sim.u, sim.bfs, sim.dev, dt_arr, spec,
            sim.fg if sim.gravity else None).as_text()
    from ramses_tpu.amr import hierarchy as H

    return H._fused_coarse_step.lower(
        sim.u, sim.dev, sim.fg if sim.gravity else {}, dt_arr, spec,
        sim._cool_bundle()).as_text()


def fused_step_gather_elems(sim) -> int:
    """``count_gather_elems`` of the sim's fused coarse step."""
    return count_gather_elems(lower_fused_step(sim))
