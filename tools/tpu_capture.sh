#!/bin/bash
# One-command TPU measurement session — run this the moment the axon
# tunnel is healthy (probe first!).  Produces the artifacts round 5
# could not capture (the tunnel was down for the whole build session):
#
#   1. probe        — 90 s timeout; abort early if the tunnel hangs
#   2. profile      — per-kernel device times at the bench shapes
#                     (tools/profile_amr.py, ##PROF## JSON line) +
#                     optional jax.profiler trace
#   3. bench        — the full budgeted protocol; one JSON line +
#                     BENCH_PARTIAL.json incrementals, tunnel_rtt_s
#                     recorded inside every sub
#
# Usage:  bash tools/tpu_capture.sh [outfile-prefix]
set -u
cd "$(dirname "$0")/.."
PFX="${1:-TPU_CAPTURE}"

echo "== probe =="
if ! timeout 90 python -c "import jax; print(jax.devices())"; then
    echo "tunnel down — aborting (do NOT trust any numbers captured now)"
    exit 1
fi

echo "== per-kernel profile (bench shapes) =="
timeout 2400 python tools/profile_amr.py 2>&1 | tee "${PFX}_profile.log"
grep -o '##PROF##.*' "${PFX}_profile.log" | tail -1 \
    | sed 's/##PROF##//' > "${PFX}_profile.json" || true

echo "== bench (budgeted) =="
BENCH_TOTAL_BUDGET=900 timeout 1000 python bench.py \
    | tail -1 > "${PFX}_bench.json"
cp -f BENCH_PARTIAL.json "${PFX}_partial.json" 2>/dev/null || true

echo "== done =="
ls -la "${PFX}"_*.json
echo "Check tunnel_rtt_s in every sub before believing the numbers;"
echo "then update docs/perf-trace-r05.md section 5 with the results."
