"""Fixture tests for the static-analysis engine (ramses_tpu/analysis).

Each rule gets a known-bad micro-program that must fire and a clean
program that must stay silent — the rule-level contract the repo-wide
``tools/lint.py --check`` gate is built on.  Micro-programs are real
jax lowerings where cheap (constants, donation, f64) and synthetic
StableHLO where a real reproduction needs a multi-device mesh
(partitioned scatter).
"""

import json
import textwrap

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from ramses_tpu.analysis import engine  # noqa: E402
from ramses_tpu.analysis import hlo_rules, source_rules  # noqa: E402
from ramses_tpu.analysis.programs import (BUILDERS,  # noqa: E402
                                          GATHER_BUDGETS, Program)
from ramses_tpu.analysis.rules import (Finding, Severity,  # noqa: E402
                                       load_baseline, save_baseline,
                                       severity_counts, split_baselined)


def _prog(text, name="micro", **meta):
    return Program(name=name, family="test", text=text, meta=meta)


def _findings(rule_check, prog, rule=None):
    out = rule_check(prog)
    if rule is not None:
        assert all(f.rule == rule for f in out)
    return out


# ---------------------------------------------------------------------
# gather-blowup
# ---------------------------------------------------------------------
_GATHER_TXT = """
  %9 = "stablehlo.gather"(%2, %8) : (tensor<100x5xf32>, tensor<7x1xi32>) -> tensor<5x7xf32>
"""


def test_gather_blowup_budget_fires_and_clears():
    bad = _prog(_GATHER_TXT, gather_budget_elems=10)
    hits = _findings(hlo_rules._check_gather_blowup, bad,
                     "gather-blowup")
    assert [f.key for f in hits] == ["budget"]
    assert hits[0].severity == Severity.ERROR
    assert hits[0].detail["elems"] == 35

    clean = _prog(_GATHER_TXT, gather_budget_elems=100)
    assert _findings(hlo_rules._check_gather_blowup, clean) == []


def test_gather_blowup_ratio_gate():
    # "reference" gathers 35 elements, "optimized" gathers the same —
    # no 2x win, the rule must fire
    bad = _prog(_GATHER_TXT, gather_ref_text=_GATHER_TXT)
    hits = _findings(hlo_rules._check_gather_blowup, bad)
    assert [f.key for f in hits] == ["ratio"]
    ok, ref, cur = hlo_rules.check_gather_ratio(
        _GATHER_TXT, "no gathers", min_ratio=2.0)
    assert ok and ref == 35 and cur == 0


# ---------------------------------------------------------------------
# large-constant-capture  (real lowering: closed-over numpy table)
# ---------------------------------------------------------------------
def test_large_constant_capture_fires_on_closed_over_table():
    table = np.arange(65536, dtype=np.float32)      # 256 KiB
    idx = jnp.zeros(4, jnp.int32)
    text = jax.jit(lambda i: jnp.take(jnp.asarray(table), i)).lower(
        idx).as_text()
    hits = _findings(hlo_rules._check_large_constant, _prog(text),
                     "large-constant-capture")
    assert len(hits) == 1 and hits[0].severity == Severity.ERROR
    assert "65536" in hits[0].key

    # same program with the table passed as an argument is clean
    text = jax.jit(lambda i, t: jnp.take(t, i)).lower(
        idx, jnp.asarray(table)).as_text()
    assert _findings(hlo_rules._check_large_constant, _prog(text)) == []


# ---------------------------------------------------------------------
# nondeterministic-scatter  (synthetic: needs a partitioned module)
# ---------------------------------------------------------------------
_SCATTER_TMPL = """
module @jit_f attributes {{mhlo.num_partitions = {np} : i32}} {{
  func.func public @main(%arg0: tensor<64x4xf32>) -> tensor<64x4xf32> {{
    %1 = "stablehlo.scatter"(%arg0, %idx, %upd) <{{
        indices_are_sorted = false, unique_indices = {uniq}
      }}> ({{
    ^bb0(%a: tensor<f32>, %b: tensor<f32>):
      %s = stablehlo.{comb} %a, %b : tensor<f32>
      stablehlo.return %s : tensor<f32>
    }}) : (tensor<64x4xf32>, tensor<9x1xi32>, tensor<9x4xf32>) -> tensor<64x4xf32>
    return %1 : tensor<64x4xf32>
  }}
}}
"""


def test_nondet_scatter_fires_only_partitioned_nonunique_add():
    bad = _SCATTER_TMPL.format(np=8, uniq="false", comb="add")
    hits = _findings(hlo_rules._check_nondet_scatter, _prog(bad),
                     "nondeterministic-scatter")
    assert len(hits) == 1 and hits[0].severity == Severity.WARN
    assert "tensor<64x4xf32>" in hits[0].key

    for clean in (
            _SCATTER_TMPL.format(np=1, uniq="false", comb="add"),
            _SCATTER_TMPL.format(np=8, uniq="true", comb="add"),
            # overwrite combiner reorders safely
            _SCATTER_TMPL.format(np=8, uniq="false", comb="maximum")):
        assert _findings(hlo_rules._check_nondet_scatter,
                         _prog(clean)) == []


# ---------------------------------------------------------------------
# donation-miss  (real lowerings)
# ---------------------------------------------------------------------
def test_donation_miss_fires_when_expected_donation_dropped():
    x = jnp.ones((4, 4), jnp.float32)
    undonated = jax.jit(lambda x: x + 1).lower(x).as_text()
    hits = _findings(hlo_rules._check_donation,
                     _prog(undonated, expect_donation=True),
                     "donation-miss")
    assert [f.key for f in hits] == ["no-aliasing"]
    assert hits[0].severity == Severity.ERROR

    donated = jax.jit(lambda x: x + 1,
                      donate_argnums=0).lower(x).as_text()
    assert _findings(hlo_rules._check_donation,
                     _prog(donated, expect_donation=True)) == []


def test_donation_detects_buffer_donor_past_nested_braces():
    """Sharded lowerings emit ``jax.buffer_donor`` plus a sharding
    string with NESTED braces before/after it — the attr parse must
    not truncate there (the bug that made every sharded program look
    donation-less)."""
    sig = ('func.func public @main(%arg0: tensor<256x4xf32> '
           '{jax.buffer_donor = true, '
           'mhlo.sharding = "{devices=[8,1]<=[8]}"}, '
           '%arg1: tensor<256x4xf32> '
           '{mhlo.sharding = "{devices=[8,1]<=[8]}", '
           'tf.aliasing_output = 0 : i32}) -> tensor<256x4xf32> {')
    args = hlo_rules.main_args(sig)
    assert len(args) == 2
    assert all(hlo_rules._is_donated(a) for _, _, a in args)
    assert _findings(hlo_rules._check_donation,
                     _prog(sig, expect_donation=True)) == []


def test_donation_warns_on_large_undonated_input():
    sig = ('func.func public @main(%arg0: tensor<4194304xf32>) '
           '-> tensor<4194304xf32> {')
    hits = _findings(hlo_rules._check_donation,
                     _prog(sig, expect_donation=False))
    assert len(hits) == 1 and hits[0].severity == Severity.WARN
    assert hits[0].detail["bytes"] == 16 << 20


# ---------------------------------------------------------------------
# f64-leak  (real lowering under the suite's x64 host config)
# ---------------------------------------------------------------------
def test_f64_leak_fires_on_uncast_double():
    text = jax.jit(
        lambda x: x * np.float64(2.0) + np.float64(1.0)).lower(
        jnp.ones(4, jnp.float32)).as_text()
    hits = _findings(hlo_rules._check_f64_leak,
                     _prog(text, dtype_bits=32), "f64-leak")
    assert len(hits) == 1 and hits[0].severity == Severity.WARN
    # an f64-configured program is allowed to be full of f64
    assert _findings(hlo_rules._check_f64_leak,
                     _prog(text, dtype_bits=64)) == []

    clean = jax.jit(lambda x: x * 2.0 + 1.0).lower(
        jnp.ones(4, jnp.float32)).as_text()
    assert _findings(hlo_rules._check_f64_leak,
                     _prog(clean, dtype_bits=32)) == []


# ---------------------------------------------------------------------
# host-sync + static-arg-hazard  (AST rules over a tmp tree)
# ---------------------------------------------------------------------
def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def test_host_sync_rule_on_fixture_tree(tmp_path):
    root = _write_tree(tmp_path, {
        "kernels/sweep.py": """
            import jax
            import numpy as np

            def hot(self):
                jax.device_get(self.u)
                x = self.u[0].block_until_ready()
                return float(self.u), np.asarray(sim.bfs)

            def cold(arr):
                return np.asarray(arr)     # not a state root: silent
        """,
        # allowlisted locations: same calls, no findings
        "driver.py": "import jax\n\ndef s(self):\n"
                     "    return jax.device_get(self.u)\n",
        "telemetry/rec.py": "import jax\n\ndef s(self):\n"
                            "    return jax.device_get(self.u)\n",
    })
    hits = source_rules._check_host_sync(root)
    assert {f.program for f in hits} == {"kernels/sweep.py"}
    by_key = {f.key: f for f in hits}
    # explicit syncs gate at WARN, implicit transfers are INFO
    assert by_key["hot:device_get"].severity == Severity.WARN
    assert by_key["hot:block_until_ready"].severity == Severity.WARN
    assert by_key["hot:float(self.u)"].severity == Severity.INFO
    assert by_key["hot:np.asarray(sim.bfs)"].severity == Severity.INFO
    assert "cold:np.asarray" not in {f.key for f in hits}


def test_host_sync_reports_syntax_error(tmp_path):
    root = _write_tree(tmp_path, {"kernels/broken.py": "def f(:\n"})
    hits = source_rules._check_host_sync(root)
    assert [f.key for f in hits] == ["syntax-error"]
    assert hits[0].severity == Severity.ERROR


def test_static_arg_hazard_on_fixture_tree(tmp_path):
    root = _write_tree(tmp_path, {
        "mod.py": """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("opts",))
            def bad(x, opts={"a": 1}):
                return x

            @partial(jax.jit, static_argnums=(1,))
            def bad2(x, ids=[1, 2]):
                return x

            @partial(jax.jit, static_argnames=("opts",))
            def good(x, opts=("a",)):
                return x

            def plain(x, opts={}):
                return x
        """,
    })
    hits = source_rules._check_static_args(root)
    assert {f.key for f in hits} == {"bad:opts", "bad2:ids"}
    assert all(f.severity == Severity.ERROR for f in hits)


# ---------------------------------------------------------------------
# registry / baseline / engine plumbing
# ---------------------------------------------------------------------
def test_registry_has_the_documented_rules():
    from ramses_tpu.analysis.rules import all_rules
    ids = {r.id for r in all_rules()}
    assert {"gather-blowup", "large-constant-capture",
            "nondeterministic-scatter", "donation-miss", "f64-leak",
            "host-sync", "static-arg-hazard"} <= ids
    assert all(r.doc for r in all_rules())


def test_budget_names_match_builders():
    assert set(GATHER_BUDGETS) <= set(BUILDERS)


def test_fingerprints_stable_and_baseline_roundtrip(tmp_path):
    f1 = Finding(rule="r", severity=Severity.WARN, program="p",
                 message="msg A", key="k")
    f2 = Finding(rule="r", severity=Severity.ERROR, program="p",
                 message="msg B (moved lines, new message)", key="k")
    f3 = Finding(rule="r", severity=Severity.WARN, program="p",
                 message="msg", key="other")
    # identity = (rule, program, key): message/severity churn keeps
    # the fingerprint, a different key changes it
    assert f1.fingerprint == f2.fingerprint != f3.fingerprint

    path = str(tmp_path / "baseline.json")
    save_baseline([f1, f2], path)
    with open(path) as fh:
        assert len(json.load(fh)["findings"]) == 1   # deduped
    base = load_baseline(path)
    new, accepted = split_baselined([f2, f3], base)
    assert [f.key for f in accepted] == ["k"]
    assert [f.key for f in new] == ["other"]
    assert severity_counts([f1, f2, f3]) == {
        "error": 1, "warn": 2, "info": 0}


def test_report_gates_on_unbaselined_warn(tmp_path):
    warn = Finding(rule="r", severity=Severity.WARN, program="p",
                   message="m", key="k")
    info = Finding(rule="r", severity=Severity.INFO, program="p",
                   message="m", key="i")
    empty = str(tmp_path / "none.json")
    rep = engine.report([warn, info], baseline_path=empty)
    assert not rep["ok"] and rep["new_counts"]["warn"] == 1
    # info alone never gates
    rep = engine.report([info], baseline_path=empty)
    assert rep["ok"]
    # baselining the warn restores ok, and a vanished entry is stale
    path = str(tmp_path / "base.json")
    save_baseline([warn], path)
    rep = engine.report([info], baseline_path=path)
    assert rep["ok"] and rep["stale_baseline"] == [warn.fingerprint]


def test_canonical_program_enumerator_uniform():
    """One cheap end-to-end canonical build: the uniform program
    lowers x64-free even under the suite's x64 host config, and the
    full HLO rule set leaves it clean."""
    from ramses_tpu.analysis.programs import build_programs
    progs = build_programs(["hydro_uniform"])
    assert [p.name for p in progs] == ["hydro_uniform"]
    prog = progs[0]
    assert prog.meta["dtype_bits"] == 32
    assert "f64" not in prog.text
    assert engine.audit_program(prog) == []
