"""Slab-local MHD constrained-transport kernel (Pallas).

The slab-sharded CT advance (:func:`ramses_tpu.parallel.dense_slab.
mhd_ct_slab`) hands each device a halo-complete local box.  The XLA
spelling of the CT pipeline (:func:`ramses_tpu.mhd.uniform.step_padded`)
materializes every stage — primitives, slopes, Hancock predictor, six
Riemann faces, four EMF edge averages — as an HBM-resident grid array;
at slab sizes that is pure bandwidth waste.  This module runs the SAME
pipeline as ONE single-block Pallas kernel: the padded state and faces
are read into VMEM once, every intermediate lives in VMEM, and HBM sees
exactly one write of the padded outputs.

No re-derivation: the kernel body CALLS ``mu.step_padded`` on the VMEM
refs, so the arithmetic is definitionally identical to the XLA fallback
(the bitwise contract the slab parity tests pin).  Availability is a
single-block question — the whole padded box plus ~60 live
intermediates must fit the VMEM budget — so the gate is a size check,
not a tiling search; oversized slabs silently keep the XLA path.

Test hook: :data:`FORCE_INTERPRET` (env ``RAMSES_PALLAS_CT_INTERPRET``
or monkeypatch) runs the kernel through the Pallas interpreter on any
backend, which is how CI exercises this path on CPU.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
except Exception:                                  # pragma: no cover
    pl = pltpu = _CompilerParams = None

from ramses_tpu.mhd import uniform as mu
from ramses_tpu.mhd.core import MhdStatic, NCOMP

DISABLED = bool(os.environ.get("RAMSES_NO_PALLAS"))

# run the kernel through the Pallas interpreter on any backend (CI hook)
FORCE_INTERPRET = bool(os.environ.get("RAMSES_PALLAS_CT_INTERPRET"))

_VMEM_BUDGET = 100 * 1024 * 1024
_LIVE_ARRAYS = 60          # ≈ peak live grid-sized intermediates of ct_core


def interpret_mode() -> bool:
    return FORCE_INTERPRET or jax.default_backend() != "tpu"


def slab_available(cfg: MhdStatic, loc, dtype) -> bool:
    """True when the single-block kernel may run for a local box of
    shape ``loc``: pallas importable, a compiled TPU backend (or the
    explicit :data:`FORCE_INTERPRET` test hook — NOT just any CPU run:
    the interpreter is a correctness vehicle, not a fast path), and the
    padded box inside the VMEM budget.  Compiled runs additionally
    require float32 (the f64 VPU story is interpret-only)."""
    if DISABLED or pl is None:
        return False
    dt = jnp.dtype(dtype)
    if not FORCE_INTERPRET:
        if jax.default_backend() != "tpu":
            return False
        if not interpret_mode() and dt != jnp.dtype(jnp.float32):
            return False
    ext = 1
    for s in loc:
        ext *= s + 2 * (mu.NGHOST + 1)
    return ext * dt.itemsize * _LIVE_ARRAYS <= _VMEM_BUDGET


def ct_step_slab(up, bfp_ext, dt, dx: Sequence[float], cfg: MhdStatic,
                 okp=None, ovr: Optional[dict] = None,
                 interpret: bool = False):
    """``mu.step_padded`` as a single-block VMEM kernel.

    ``up`` [nvar, \\*sp+2·ng] padded cells (raw B slots), ``bfp_ext``
    [NCOMP, \\*sp+2·(ng+1)] padded low faces, ``okp`` optional padded
    refined mask (bool or arithmetic), ``ovr`` optional dict
    (d1,d2) → (padded bool mask, padded values).  Returns the padded
    ``(un, bfn_stacked)`` exactly like ``step_padded`` (``bfn`` stacked
    on axis 0 — iterable per component)."""
    nd = cfg.ndim
    pairs = [(d1, d2) for d1 in range(nd) for d2 in range(d1 + 1, nd)]
    dtype = up.dtype
    has_ok = okp is not None
    has_ovr = ovr is not None

    inputs = [jnp.asarray(dt, dtype).reshape(1), up, bfp_ext]
    if has_ok:
        inputs.append(okp.astype(dtype))
    if has_ovr:
        inputs.append(jnp.stack([ovr[p][0].astype(dtype) for p in pairs]))
        inputs.append(jnp.stack([ovr[p][1] for p in pairs]))

    def kern(*refs):
        it = iter(refs)
        dt_ref, up_ref, bf_ref = next(it), next(it), next(it)
        okp_k = (next(it)[...] > 0.5) if has_ok else None
        ovr_k = None
        if has_ovr:
            om, ov = next(it)[...], next(it)[...]
            ovr_k = {p: (om[i] > 0.5, ov[i])
                     for i, p in enumerate(pairs)}
        un_ref, bfn_ref = next(it), next(it)
        un, bfn = mu.step_padded(cfg, tuple(dx), up_ref[...],
                                 bf_ref[...], dt_ref[0],
                                 okp=okp_k, ovr=ovr_k)
        un_ref[...] = un
        bfn_ref[...] = jnp.stack(bfn)

    def _full(shape):
        rank = len(shape)
        return pl.BlockSpec(shape, lambda: (0,) * rank)

    kwargs = {}
    if not interpret and _CompilerParams is not None:
        kwargs["compiler_params"] = _CompilerParams(
            vmem_limit_bytes=_VMEM_BUDGET + 28 * 1024 * 1024)
    un, bfn = pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [_full(a.shape) for a in inputs[1:]],
        out_specs=(_full(up.shape),
                   _full((NCOMP,) + up.shape[1:])),
        out_shape=(jax.ShapeDtypeStruct(up.shape, dtype),
                   jax.ShapeDtypeStruct((NCOMP,) + up.shape[1:], dtype)),
        interpret=interpret,
        **kwargs)(*inputs)
    return un, bfn
