"""Numerical step-guard: finiteness checks + rollback bookkeeping.

The drivers already fetch scalar (t, dt) summaries per coarse step /
chunk; :class:`StepGuard` checks those for finiteness (a NaN from the
fused step poisons t within one iteration because the scan's active
flag ``t < tend`` compares False for NaN, so stepping freezes and the
NaN propagates to the returned time).  On a trip the driver restores
its retained pre-step state and retries with halved dt — the
reference's redo-step — escalating the Riemann solver to diffusive
LLF on the second retry.  This module holds only the policy and the
telemetry plumbing; the state capture/restore lives with each driver
because capture semantics differ (donated fused buffers need device
copies, the uniform path keeps plain refs).
"""

from __future__ import annotations

import math
from typing import Optional


class StepRetryExhausted(RuntimeError):
    """Raised after ``max_step_retries`` rollback attempts all failed;
    the driver emergency-dumps the last clean state before raising."""


class StepGuard:
    """Retry policy + telemetry for in-run numerical fault recovery.

    Stateless between steps apart from counters; ``ok()`` is the hot
    check and touches only already-host scalars — arming the guard
    adds no host<->device fetches.
    """

    def __init__(self, max_retries: int = 2, telemetry=None):
        self.max_retries = int(max_retries)
        self.telemetry = telemetry
        self.rollbacks = 0      # retry attempts taken (all steps)
        self.recovered = 0      # steps saved by the ladder
        self.aborts = 0

    @classmethod
    def from_params(cls, params, telemetry=None) -> Optional["StepGuard"]:
        """A guard when ``&RUN_PARAMS max_step_retries > 0``, else
        None (zero-overhead off switch: drivers skip capture)."""
        n = int(getattr(getattr(params, "run", None),
                        "max_step_retries", 0) or 0)
        if n <= 0:
            return None
        return cls(max_retries=n, telemetry=telemetry)

    @staticmethod
    def ok(*vals) -> bool:
        """All host scalars finite (None entries skipped).  Non-finite
        OR the guard's caller passing an already-NaN dt both trip."""
        for v in vals:
            if v is None:
                continue
            if not math.isfinite(float(v)):
                return False
        return True

    # ---- telemetry / screen ------------------------------------------

    def _emit(self, kind: str, **fields):
        tel = self.telemetry
        if tel is not None:
            try:
                tel.record_event(kind, **fields)
            except Exception:
                pass

    def record_trip(self, sim, reason: str = "nonfinite"):
        self._emit("fault", reason=reason,
                   nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)))
        print(f" step guard: non-finite state at nstep="
              f"{int(getattr(sim, 'nstep', 0))} ({reason}); "
              "rolling back")

    def record_rollback(self, sim, attempt: int, dt: float,
                        escalated: bool):
        self.rollbacks += 1
        self._emit("rollback", attempt=int(attempt), dt=float(dt),
                   escalated=bool(escalated),
                   nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)))
        extra = ", riemann->llf" if escalated else ""
        print(f" step guard: retry {attempt}/{self.max_retries} "
              f"with dt={dt:.6e}{extra}")

    def record_recovered(self, sim, attempt: int):
        self.recovered += 1
        self._emit("rollback_recovered", attempt=int(attempt),
                   nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)))
        print(f" step guard: step recovered on retry {attempt}")

    def record_abort(self, sim, outdir: Optional[str]):
        self.aborts += 1
        self._emit("rollback_abort", nstep=int(getattr(sim, "nstep", 0)),
                   t=float(getattr(sim, "t", 0.0)),
                   emergency_dump=outdir or "")
        print(" step guard: retry ladder exhausted"
              + (f"; emergency dump -> {outdir}" if outdir else ""))


class BatchGuard:
    """Member-granular :class:`StepGuard` for the batched ensemble
    engine (ensemble/batch.EnsembleEngine).

    The engine already fetches per-member ``(ndone[B], t[B])`` once per
    fused window; arming the guard only *widens* that single fetch with
    the on-device conserved/finiteness summary
    (``grid.uniform.batch_summary``), so the zero-device-fetch-when-off
    contract of :class:`StepGuard` carries over: ``screen()`` touches
    only already-host arrays.  Policy: a tripped member is restored
    from the retained pre-window state by masked select and re-advanced
    at halved dt (LLF escalation via an escalation sub-batch regroup
    from the second retry); after ``max_member_retries`` failures the
    member is quarantined — last clean state emergency-dumped, census
    recorded in the ensemble checkpoint manifest — and the batch
    continues without it.
    """

    def __init__(self, max_retries: int = 2, telemetry=None):
        self.max_retries = int(max_retries)
        self.telemetry = telemetry
        self.trips = 0          # member-windows that screened bad
        self.rollbacks = 0      # member retry attempts taken
        self.recovered = 0      # members saved by the ladder
        self.quarantined = 0    # members evicted

    @classmethod
    def from_params(cls, params, telemetry=None
                    ) -> Optional["BatchGuard"]:
        """A guard when ``&ENSEMBLE_PARAMS max_member_retries > 0`` or
        ``member_quarantine=.true.`` (quarantine-only mode: a trip
        evicts directly, no retries), else None — the engine then
        retains no state and adds no fetches."""
        e = getattr(params, "ensemble", None)
        n = int(getattr(e, "max_member_retries", 0) or 0)
        q = bool(getattr(e, "member_quarantine", False))
        if n <= 0 and not q:
            return None
        return cls(max_retries=max(0, n), telemetry=telemetry)

    @staticmethod
    def screen(t_host, summ=None, active=None):
        """bool[B] of tripped members, from *host* arrays only.

        A member trips when its time is non-finite (the in-scan NaN
        freeze) or its summary shows a non-finite state (finite-flag
        column 0, conserved totals columns 1+ — catches a NaN landing
        on the window's last step, where ``t`` is still finite).
        ``active`` (bool[B]) restricts screening to members that were
        actually advanced this window."""
        import numpy as np
        t_host = np.asarray(t_host, np.float64)
        bad = ~np.isfinite(t_host)
        if summ is not None:
            s = np.asarray(summ, np.float64)
            bad |= ~np.all(np.isfinite(s), axis=-1)
            bad |= s[..., 0] < 0.5
        if active is not None:
            bad &= np.asarray(active, bool)
        return bad

    # ---- telemetry (member-level fault/quarantine events) ------------

    def _emit(self, kind: str, **fields):
        tel = self.telemetry
        if tel is not None:
            try:
                tel.record_event(kind, **fields)
            except Exception:
                pass

    def record_trip(self, members, nsteps, ts,
                    reason: str = "nonfinite"):
        for m, n, t in zip(members, nsteps, ts):
            self.trips += 1
            self._emit("fault", member=int(m), reason=reason,
                       nstep=int(n), t=float(t))
        print(f" batch guard: non-finite members {list(members)}; "
              "rolling back")

    def record_rollback(self, members, attempt: int, dt_scale: float,
                        escalated: bool):
        for m in members:
            self.rollbacks += 1
            self._emit("member_rollback", member=int(m),
                       attempt=int(attempt), dt_scale=float(dt_scale),
                       escalated=bool(escalated))
        extra = ", riemann->llf regroup" if escalated else ""
        print(f" batch guard: retry {attempt}/{self.max_retries} for "
              f"members {list(members)} at dt_scale={dt_scale}{extra}")

    def record_recovered(self, members, attempt: int):
        for m in members:
            self.recovered += 1
            self._emit("member_recovered", member=int(m),
                       attempt=int(attempt))
        print(f" batch guard: members {list(members)} recovered on "
              f"retry {attempt}")

    def record_quarantine(self, member: int, info):
        self.quarantined += 1
        self._emit("quarantine", member=int(member), **dict(info))
        print(f" batch guard: member {int(member)} quarantined "
              f"({info.get('reason', '?')} at nstep={info.get('nstep')}"
              f", t={info.get('t')})"
              + (f"; dump -> {info['dump']}" if info.get("dump")
                 else ""))
