"""Asynchronous (background-thread) snapshot writing — the ``pario``
capability (SURVEY.md §2.10, reference ``pario/`` dormant tree).

The reference dedicates MPI ranks to I/O so compute ranks hand off
their dump and keep stepping.  The single-process equivalent: the
host-side snapshot assembly happens synchronously (it reads live
device state), then the byte-level file writing — the slow, purely
host-bound part — runs on a daemon worker thread while the simulation
continues.  One worker serializes writes (the reference throttles
concurrent writers the same way, &OUTPUT_PARAMS IOGROUPSIZE).

Usage::

    dumper = AsyncDumper()
    dumper.submit(snap, iout, base_dir)       # returns immediately
    ...
    dumper.wait()                             # barrier (end of run)

Failures are captured and re-raised on :meth:`wait` (or logged on the
next submit) instead of killing the compute thread.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional


class AsyncDumper:
    """One background writer thread draining a dump queue."""

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="ramses-io-writer")
            self._thread.start()
            # interpreter exit must not kill a half-written snapshot:
            # drain the queue before teardown even when the caller
            # forgot wait() (the reference's pario ranks block in
            # MPI_FINALIZE the same way)
            import atexit
            atexit.register(self._drain_at_exit)

    def _drain_at_exit(self):
        try:
            self._q.join()
        except Exception:
            pass

    def _run(self):
        from ramses_tpu.io import snapshot as snapmod
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            snap, iout, base_dir, kwargs = item
            try:
                snapmod.dump_all(snap, iout, base_dir, **kwargs)
            except BaseException as e:       # noqa: BLE001 — report later
                with self._lock:
                    self._errors.append(e)
            finally:
                self._q.task_done()

    def submit(self, snap, iout: int, base_dir: str, **kwargs):
        """Queue one snapshot for background writing.  ``snap`` must be
        fully host-resident (``snapshot_from_*`` already device_gets
        everything), so the live simulation state can keep mutating."""
        self._raise_pending()
        self._ensure_thread()
        self._q.put((snap, iout, base_dir, kwargs))

    @property
    def pending(self) -> int:
        return self._q.unfinished_tasks

    def _raise_pending(self):
        with self._lock:
            if self._errors:
                e = self._errors[0]
                self._errors.clear()
                raise RuntimeError("async snapshot write failed") from e

    def wait(self):
        """Block until every queued dump is on disk; re-raise the first
        captured writer error."""
        self._q.join()
        self._raise_pending()

    def drain(self) -> List[BaseException]:
        """Block until every queued dump is on disk and RETURN (not
        raise) the captured writer errors — the stop-path variant for
        OpsGuard's SIGTERM/walltime handling, where an I/O failure must
        be reported in the run footer but must not pre-empt the clean
        shutdown itself."""
        try:
            self._q.join()
        except Exception:
            pass
        with self._lock:
            errs = list(self._errors)
            self._errors.clear()
        return errs

    def close(self):
        self.wait()
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=30)
