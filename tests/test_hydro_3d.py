"""2D/3D smoke + physics tests: Sedov blast symmetry and conservation."""

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.config import params_from_string
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import totals

SEDOV = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmin}
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
z_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
length_z=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.4
/
&OUTPUT_PARAMS
noutput=1
tout={tout}
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
slope_type=1
riemann='hllc'
/
"""



pytestmark = pytest.mark.smoke

def run_sedov(ndim, lmin=5, tout=0.05, nstep=1000):
    p = params_from_string(SEDOV.format(lmin=lmin, tout=tout, nstep=nstep),
                           ndim=ndim)
    sim = Simulation(p, dtype=jnp.float64)
    tot0 = totals(sim.state.u, sim.cfg, sim.grid.dx)
    sim.evolve()
    return sim, tot0


@pytest.mark.parametrize("ndim", [2, 3])
def test_sedov_conservation(ndim):
    sim, tot0 = run_sedov(ndim)
    tot1 = totals(sim.state.u, sim.cfg, sim.grid.dx)
    assert float(tot1["mass"]) == pytest.approx(float(tot0["mass"]),
                                                rel=1e-12)
    assert float(tot1["energy"]) == pytest.approx(float(tot0["energy"]),
                                                  rel=1e-12)
    assert sim.state.nstep > 3


@pytest.mark.parametrize("ndim", [2, 3])
def test_sedov_symmetry(ndim):
    """The blast from a centred point source must stay mirror-symmetric
    about the box centre in every axis (even grid → symmetric stencils)."""
    sim, _ = run_sedov(ndim, lmin=4, tout=0.02)
    rho = np.asarray(sim.state.u[0])
    for ax in range(ndim):
        np.testing.assert_allclose(rho, np.flip(rho, axis=ax), rtol=1e-10)
    # density must have been pushed outward into a shell
    assert rho.max() > 1.2


def test_sedov_shock_radius_3d():
    """Shock radius follows the Sedov-Taylor similarity solution
    r_s = xi0*(E t^2 / rho)^(1/5) with xi0 ~= 1.15 for gamma=1.4."""
    sim, _ = run_sedov(3, lmin=5, tout=0.06)
    rho = np.asarray(sim.state.u[0])
    n = rho.shape[0]
    x = (np.arange(n) + 0.5) / n - 0.5
    X, Y, Z = np.meshgrid(x, x, x, indexing="ij")
    r = np.sqrt(X**2 + Y**2 + Z**2)
    # shock radius = radius of peak density
    r_shock = r.flat[np.argmax(rho)]
    E = 0.4 / (1.4 - 1.0)  # injected thermal energy (point P/(gamma-1))
    # Sedov-Taylor prefactor xi0 = alpha^(-1/5), alpha ~= 0.851 for
    # gamma=1.4 => xi0 ~= 1.033 (1.15 is the gamma=5/3 value).
    r_theory = 1.033 * (E * sim.state.t**2) ** 0.2
    assert abs(r_shock - r_theory) / r_theory < 0.15


def test_positivity_slope_runs():
    """slope_type=3 (positivity-preserving unsplit limiter) evolves a 3D
    blast without NaNs or negative density."""
    p = params_from_string(SEDOV.format(lmin=4, tout=0.01, nstep=50),
                           ndim=3)
    p.hydro.slope_type = 3
    sim = Simulation(p, dtype=jnp.float64)
    sim.evolve()
    rho = np.asarray(sim.state.u[0])
    assert np.isfinite(rho).all() and (rho > 0).all()
    assert sim.state.nstep > 3
