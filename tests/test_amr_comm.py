"""Explicit sharded-AMR comm schedule vs the GSPMD global-view path.

The reference pins its steady-state message schedule in ``build_comm``
metadata (``amr/virtual_boundaries.f90:1286``); the explicit backend
(parallel/amr_comm.py) does the same with per-shard ppermute schedules.
Both formulations must produce the same physics on the 8-device mesh.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax

from ramses_tpu.config import params_from_string
from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

NDEV = 8


def _params():
    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=5", "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=2",
        "region_type(1)='square'", "region_type(2)='square'",
        "x_center=0.25,0.75", "length_x=0.5,0.5",
        "exp_region=10.0,10.0", "d_region=1.0,0.125",
        "p_region=1.0,0.1", "/",
        "&HYDRO_PARAMS", "riemann='hllc'", "/",
        "&REFINE_PARAMS", "err_grad_d=0.05", "err_grad_p=0.05", "/",
        "&OUTPUT_PARAMS", "tend=0.01", "/",
    ])
    return params_from_string(nml, ndim=2)


def _devices():
    ds = jax.devices()
    if len(ds) < NDEV:
        pytest.skip(f"needs {NDEV} virtual devices")
    return ds[:NDEV]


def _run(explicit, nsteps=3):
    sim = ShardedAmrSim(_params(), devices=_devices(),
                        dtype=jnp.float64, explicit_comm=explicit)
    for _ in range(nsteps):
        sim.step_coarse(sim.coarse_dt())
    return sim


def test_explicit_comm_builds_schedules():
    sim = _run(True, nsteps=0)
    # the refined levels exist and at least one carries a schedule
    partial = [l for l in sim.levels()
               if not sim.maps[l].complete and l > sim.lmin]
    assert partial, "config must produce partial levels"
    assert any("comm" in sim.dev[l] for l in partial)
    for l in partial:
        if "comm" not in sim.dev[l]:
            continue
        spec = sim._comm_specs[l]
        # Hilbert-contiguous shards: halo traffic rides few ring offsets
        assert len(spec.fine_offsets) <= sim.ndev - 1


@pytest.mark.smoke
@pytest.mark.slow
def test_explicit_comm_matches_gspmd():
    """Same tree, same dt sequence: the explicit ppermute schedule and
    the compiler-inserted collectives integrate the same physics."""
    a = _run(False)
    b = _run(True)
    assert list(a.levels()) == list(b.levels())
    assert np.isclose(a.t, b.t, rtol=0, atol=0)
    for l in a.levels():
        ua = np.asarray(a.u[l])[:a.maps[l].noct * 4]
        ub = np.asarray(b.u[l])[:b.maps[l].noct * 4]
        scale = np.abs(ua).max()
        # f64: identical physics, summation order may differ only in
        # the corr fold (few terms) — tolerance at roundoff scale
        np.testing.assert_allclose(ua, ub, rtol=0, atol=5e-14 * scale)


def test_explicit_comm_deterministic():
    """The explicit schedule is bitwise repeatable run-to-run (the
    deterministic owner-fold contract)."""
    b1 = _run(True)
    b2 = _run(True)
    for l in b1.levels():
        assert (np.asarray(b1.u[l]).tobytes()
                == np.asarray(b2.u[l]).tobytes())


@pytest.mark.smoke
@pytest.mark.slow          # ~12s; CI smoke + nightly tiers still run it
def test_explicit_comm_collective_footprint():
    """Pin the comm footprint of the sharded-AMR coarse step: the
    explicit ppermute schedule must not regress into all-gathers, and
    must not be beaten by the GSPMD partitioner's own choice (VERDICT
    r3: a regression from neighbour ppermute to all-gather would
    otherwise be invisible until real multi-chip time)."""
    import jax.numpy as jnp

    from ramses_tpu.amr import hierarchy as H
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    def counts(explicit):
        p = _params()
        sim = ShardedAmrSim(p, devices=_devices(), dtype=jnp.float64,
                            explicit_comm=explicit)
        assert len(sim.levels()) >= 2       # a partial level exists
        spec = sim._fused_spec()
        if explicit:
            assert any(c is not None for c in spec.comm)
        dt = jnp.asarray(1e-4, sim.dtype)
        txt = H._fused_coarse_step.lower(
            sim.u, sim.dev, {}, dt, spec, None).compile().as_text()
        return {op: txt.count(f" {op}(")
                for op in ("all-gather", "collective-permute",
                           "all-reduce", "all-to-all")}

    gspmd = counts(False)
    expl = counts(True)
    # the sharded program really communicates
    assert sum(gspmd.values()) > 0
    # the explicit schedule rides point-to-point permutes, and never
    # MORE gathers than the partitioner's own lowering
    assert expl["collective-permute"] > 0
    assert expl["all-gather"] <= gspmd["all-gather"]
    # the CFL reduction stays a reduction on both paths
    assert expl["all-reduce"] > 0 and gspmd["all-reduce"] > 0
