"""Monte-Carlo gas tracers.

Reference: ``pm/move_tracer.f90`` / ``pm/tracer_utils.f90`` (Cadiou+
flux-probability scheme, SURVEY.md §2.7): a tracer in cell i jumps across
face f with probability (outgoing mass through f)/(cell gas mass), so the
tracer distribution follows the gas mass distribution exactly in
expectation.  Fully vectorized: one categorical draw per tracer per step.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("shape",))
def mc_tracer_step(x, key, rho_before, mass_fluxdt, shape: Tuple[int, ...],
                   dx: float):
    """Move tracers for one hydro step.

    ``x`` [ntr, ndim] positions (user units), ``rho_before`` the gas
    density BEFORE the step, ``mass_fluxdt`` [ndim, *sp] the mass
    flux·dt/dx at each cell's LOW face (positive = flowing in +d).
    Returns new positions.
    """
    ndim = len(shape)
    cell = jnp.clip((x / dx).astype(jnp.int32), 0,
                    jnp.asarray(shape, jnp.int32) - 1)
    idx = tuple(cell[:, d] for d in range(ndim))
    mcell = rho_before[idx]                       # mass/volume; flux is /dx

    # outgoing probabilities per face: low face if flux<0, high if >0
    probs = []
    for d in range(ndim):
        f_lo = mass_fluxdt[d][idx]
        hi = tuple((cell[:, dd] + (1 if dd == d else 0)) % shape[dd]
                   for dd in range(ndim))
        f_hi = mass_fluxdt[d][hi]
        probs.append(jnp.maximum(-f_lo, 0.0))     # leave through low face
        probs.append(jnp.maximum(f_hi, 0.0))      # leave through high face
    p = jnp.stack(probs, axis=1) / jnp.maximum(mcell, 1e-300)[:, None]
    p = jnp.clip(p, 0.0, 1.0)
    stay = jnp.maximum(1.0 - p.sum(axis=1), 0.0)
    full = jnp.concatenate([stay[:, None], p], axis=1)
    full = full / full.sum(axis=1, keepdims=True)

    choice = jax.random.categorical(key, jnp.log(full + 1e-300), axis=1)
    # choice 0 = stay; 1+2d = -d move; 2+2d = +d move
    newcell = cell
    for d in range(ndim):
        move = jnp.where(choice == 1 + 2 * d, -1,
                         jnp.where(choice == 2 + 2 * d, 1, 0))
        newcell = newcell.at[:, d].add(move)
    newcell = jnp.mod(newcell, jnp.asarray(shape, newcell.dtype))
    # keep the intra-cell offset so tracers don't pile on centres
    frac = x / dx - cell
    return (newcell + frac) * dx
