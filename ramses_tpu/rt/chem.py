"""Non-equilibrium hydrogen photochemistry + photoheating.

The ``rt/rt_cooling_module.f90`` capability, reduced to the gray
single-group hydrogen system (multi-group/He structure slots in along the
same axes): per cell and substep, implicitly coupled updates of

  photon density:  dN/dt = -c σ n_HI N                (absorption)
  ionized fraction: dx/dt = (Γ + β(T) n_e) (1-x) - α(T) n_e x
  temperature:      photoheating e_γ per ionization, recombination +
                    collisional-ionization cooling

with on-the-spot approximation (case-B recombination, ``rt_otsa``).
Rates are the standard published fits (Cen 1992; Hui & Gnedin 1997).
All quantities cgs; the update is one fused elementwise program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ramses_tpu.units import kB

EV = 1.602177e-12
E_ION_HI = 13.60 * EV


@dataclass(frozen=True)
class GroupSpec:
    """Gray photon group (the reference's per-group SED-averaged
    cross-sections/energies, ``rt/rt_spectra.f90``)."""
    sigma: float = 3.0e-18       # cm^2, HI-ionization-weighted
    e_photon: float = 18.85 * EV  # mean photon energy (1e5 K blackbody)


def alpha_B(T):
    """Case-B recombination [cm^3/s] (Hui & Gnedin 1997 fit)."""
    lam = 2.0 * 157807.0 / jnp.maximum(T, 1.0)
    return 2.753e-14 * lam ** 1.5 / (1.0 + (lam / 2.74) ** 0.407) ** 2.242


def alpha_A(T):
    lam = 2.0 * 157807.0 / jnp.maximum(T, 1.0)
    return 1.269e-13 * lam ** 1.503 / (1.0 + (lam / 0.522) ** 0.47) ** 1.923


def beta_ci(T):
    """Collisional ionization [cm^3/s] (Cen 1992)."""
    T = jnp.maximum(T, 1.0)
    return (5.85e-11 * jnp.sqrt(T) * jnp.exp(-157809.1 / T)
            / (1.0 + jnp.sqrt(T / 1e5)))


def cool_rec_B(T):
    """Case-B recombination cooling [erg cm^3/s]."""
    lam = 2.0 * 157807.0 / jnp.maximum(T, 1.0)
    return (3.435e-30 * T * lam ** 1.97
            / (1.0 + (lam / 2.25) ** 0.376) ** 3.72)


def chem_step(N, xHII, T, nH, dt, c_red, group: GroupSpec,
              otsa: bool = True, niter: int = 5, heating: bool = True):
    """One implicitly-coupled chemistry substep.  Returns (N', x', T').

    Sequential implicit sweep (the reference's cell-wise iteration,
    ``rt_cooling_module`` order absorption → ionization → thermal),
    fixed-point iterated ``niter`` times for the x↔ne coupling.
    """
    x = jnp.clip(xHII, 1e-10, 1.0 - 1e-10)
    alpha = alpha_B(T) if otsa else alpha_A(T)

    for _ in range(niter):
        nHI = nH * (1.0 - x)
        # implicit absorption at fixed nHI
        N_new = N / (1.0 + dt * c_red * group.sigma * nHI)
        gamma = c_red * group.sigma * N_new         # photoionizations/s/atom
        ne = nH * x
        cre = gamma + beta_ci(T) * ne
        dst = alpha * ne
        # implicit linearized x update
        x = jnp.clip((x + dt * cre) / (1.0 + dt * (cre + dst)),
                     1e-10, 1.0 - 1e-10)

    nHI = nH * (1.0 - x)
    N_out = N / (1.0 + dt * c_red * group.sigma * nHI)
    # photons actually absorbed per volume
    absorbed = jnp.maximum(N - N_out, 0.0)

    if heating:
        ne = nH * x
        heat = absorbed / dt * (group.e_photon - E_ION_HI)
        cool = cool_rec_B(T) * ne * nH * x
        ntot = nH * (1.0 + x)                        # H + electrons
        dT = dt * (heat - cool) / (1.5 * kB * jnp.maximum(ntot, 1e-30))
        T = jnp.maximum(T + dT, 1.0)
    return N_out, x, T
