"""Grafic IC manipulation CLI — the reference's IC-surgery programs.

Covers ``utils/f90/degrade_grafic.f90`` (halve the resolution),
``extract_grafic.f90`` (cut a sub-cube), ``center_grafic.f90``
(periodic-shift a chosen point to the box centre) and
``split_grafic.f90``'s role of re-windowing, over every IC field
present in a level directory (``ic_velc*``, ``ic_deltab``,
``ic_velb*``).  All are tiny host numpy passes through
:mod:`ramses_tpu.io.grafic`.

Usage::

    python -m ramses_tpu.utils.grafic_tools degrade  IN_DIR OUT_DIR
    python -m ramses_tpu.utils.grafic_tools extract  IN_DIR OUT_DIR \
        --origin 0 0 0 --shape 64 64 64
    python -m ramses_tpu.utils.grafic_tools center   IN_DIR OUT_DIR \
        --point 0.25 0.5 0.75
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

import numpy as np

from ramses_tpu.io import grafic as gr


def _each_field(indir: str):
    for name in gr.FIELDS_DM + gr.FIELDS_BARYON:
        p = os.path.join(indir, name)
        if os.path.exists(p):
            yield name, *gr.read_grafic(p)


def degrade(indir: str, outdir: str) -> int:
    """Halve the resolution by 2^3 block averaging
    (``degrade_grafic.f90``)."""
    os.makedirs(outdir, exist_ok=True)
    nf = 0
    for name, hdr, arr in _each_field(indir):
        if any(s % 2 for s in arr.shape):
            raise ValueError(f"{name}: odd dimensions {arr.shape} "
                             "cannot degrade by 2")
        small = arr.reshape(arr.shape[0] // 2, 2, arr.shape[1] // 2, 2,
                            arr.shape[2] // 2, 2).mean(axis=(1, 3, 5))
        h2 = dataclasses.replace(hdr, np1=small.shape[0],
                                 np2=small.shape[1], np3=small.shape[2],
                                 dx=2.0 * hdr.dx)
        gr.write_grafic(os.path.join(outdir, name), h2,
                        small.astype(np.float32))
        nf += 1
    return nf


def extract(indir: str, outdir: str, origin, shape) -> int:
    """Cut a sub-cube starting at cell ``origin`` with ``shape`` cells
    (``extract_grafic.f90``); the offsets land in the header's x*o so
    a zoom run knows where the patch sits."""
    os.makedirs(outdir, exist_ok=True)
    o = np.asarray(origin, dtype=int)
    s = np.asarray(shape, dtype=int)
    nf = 0
    for name, hdr, arr in _each_field(indir):
        if ((o < 0).any() or (o + s > arr.shape).any()):
            raise ValueError(f"{name}: window {o}+{s} outside "
                             f"{arr.shape}")
        sub = arr[o[0]:o[0] + s[0], o[1]:o[1] + s[1], o[2]:o[2] + s[2]]
        h2 = dataclasses.replace(
            hdr, np1=int(s[0]), np2=int(s[1]), np3=int(s[2]),
            x1o=hdr.x1o + float(o[0]) * hdr.dx,
            x2o=hdr.x2o + float(o[1]) * hdr.dx,
            x3o=hdr.x3o + float(o[2]) * hdr.dx)
        gr.write_grafic(os.path.join(outdir, name), h2, sub)
        nf += 1
    return nf


def center(indir: str, outdir: str, point) -> int:
    """Periodic roll so box-fraction ``point`` lands at the centre
    (``center_grafic.f90``)."""
    os.makedirs(outdir, exist_ok=True)
    nf = 0
    for name, hdr, arr in _each_field(indir):
        shift = [int(round((0.5 - p) * n)) % n
                 for p, n in zip(point, arr.shape)]
        gr.write_grafic(os.path.join(outdir, name), hdr,
                        np.roll(arr, shift, axis=(0, 1, 2)))
        nf += 1
    return nf


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ramses_tpu.utils.grafic_tools")
    sub = ap.add_subparsers(dest="tool", required=True)
    for name in ("degrade", "extract", "center"):
        p = sub.add_parser(name)
        p.add_argument("indir")
        p.add_argument("outdir")
        if name == "extract":
            p.add_argument("--origin", type=int, nargs=3,
                           default=[0, 0, 0])
            p.add_argument("--shape", type=int, nargs=3, required=True)
        if name == "center":
            p.add_argument("--point", type=float, nargs=3,
                           default=[0.5, 0.5, 0.5])
    args = ap.parse_args(argv)
    if args.tool == "degrade":
        n = degrade(args.indir, args.outdir)
    elif args.tool == "extract":
        n = extract(args.indir, args.outdir, args.origin, args.shape)
    else:
        n = center(args.indir, args.outdir, args.point)
    print(f"{args.tool}: {n} fields -> {args.outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
