"""``savegadget`` particle outputs (``io/gadget.py`` — the reference's
flag that mirrors each particle output as a Gadget SnapFormat=1 file
for external tooling): the dump helper writes active lanes only with
the format's fixed 3-D layout, and the namelist trigger lands the file
inside the snapshot directory."""

import glob
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_string
from ramses_tpu.io.gadget import dump_gadget_particles, read_gadget
from ramses_tpu.pm.particles import ParticleSet


def test_dump_gadget_particles_roundtrip(tmp_path):
    """Active lanes only; ndim<3 pads zero columns; header carries the
    count in the type-1 slot and the mean active mass."""
    rng = np.random.default_rng(5)
    # 24 lanes, 16 active (make pads inactive tail lanes)
    ps = ParticleSet.make(rng.uniform(0, 1, (16, 2)),
                          rng.normal(0, 0.2, (16, 2)),
                          np.full(16, 2.0), nmax=24)
    path = str(tmp_path / "gadget_test.dat")
    dump_gadget_particles(path, ps, boxlen=3.0, time=0.125)
    hdr, pos, vel, ids = read_gadget(path)
    assert hdr.npart == (0, 16, 0, 0, 0, 0)
    assert hdr.mass[1] == pytest.approx(2.0)
    assert hdr.boxsize == pytest.approx(3.0)
    assert hdr.time == pytest.approx(0.125)
    assert pos.shape == (16, 3) and vel.shape == (16, 3)
    np.testing.assert_allclose(pos[:, :2], np.asarray(ps.x)[:16],
                               rtol=1e-6)
    np.testing.assert_allclose(vel[:, :2], np.asarray(ps.v)[:16],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(pos[:, 2], 0.0)   # padded column
    np.testing.assert_array_equal(ids, np.asarray(ps.idp)[:16])


def test_savegadget_namelist_trigger(tmp_path):
    """&OUTPUT_PARAMS savegadget=.true. on a PM run: every snapshot
    directory also carries a ``gadget_NNNNN.dat`` readable by the
    SnapFormat=1 reader."""
    from ramses_tpu.driver import Simulation

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.",
        "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0", "/",
        "&OUTPUT_PARAMS", "noutput=1", "tout=0.01",
        "savegadget=.true.",
        f"output_dir='{tmp_path}'", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
    ])
    p = params_from_string(nml)
    assert p.output.savegadget is True
    rng = np.random.default_rng(7)
    parts = ParticleSet.make(rng.uniform(0, 1, (32, 3)),
                             np.zeros((32, 3)), np.full(32, 0.01))
    sim = Simulation(p, dtype=jnp.float64, particles=parts)
    out = sim.dump(1, str(tmp_path))
    files = glob.glob(os.path.join(out, "gadget_*.dat"))
    assert files, f"no gadget file in {out}: {os.listdir(out)}"
    hdr, pos, _, ids = read_gadget(files[0])
    assert hdr.npart[1] == 32
    assert hdr.boxsize == pytest.approx(1.0)
    assert pos.shape == (32, 3)
    assert len(np.unique(ids)) == 32
    # off by default: a plain dump ships no gadget file
    p2 = params_from_string(nml.replace("savegadget=.true.", ""))
    sim2 = Simulation(p2, dtype=jnp.float64, particles=parts)
    out2 = sim2.dump(2, str(tmp_path))
    assert not glob.glob(os.path.join(out2, "gadget_*.dat"))
